"""Phase-A LSH-mask layout experiment (diagnostic, not product code).

The LSH cells' phase A runs ~1.5x the exact scan (r05: 31 vs 20-24 ms
per 256-window at 20M) and the suspect is not the popcount itself but
the LAYOUT of the mask: scores come out of the MXU as (T, B) with B on
lanes, while the per-row bucket ids live lane-aligned as (T//bs, bs) —
broadcasting a bucket against all B lanes forces a per-element
cross-lane relayout.  Variant B computes scores transposed, (B, T), so
the bucket vector broadcasts along SUBLANES (one cheap flatten per
tile) and the block max reduces over lanes.

Usage: python docs/bench_diag/lsh_mask_probe.py [--items-m 20]
Prints one JSON line per variant (exec_ms via the m-deep queue method).
"""

from __future__ import annotations

import argparse
import json
import sys
from functools import partial

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402

from oryx_tpu.bench.kernel_probe import time_exec  # noqa: E402

T = 4096
BS = 128
MB = 1


@partial(jax.jit, static_argnames=("mb",))
def variant_a(Y, Qc, pen, bkt, tgt, mb: int):
    """Current product formulation: (T, B) scores, 3D-broadcast mask."""
    N, W = Y.shape
    B = Qc.shape[0]

    def kern(q_ref, y_ref, p_ref, b_ref, t_ref, o_ref):
        s = jax.lax.dot_general(y_ref[...], q_ref[...],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s3 = s.reshape(T // BS, BS, B) + p_ref[...][:, :, None]
        ok = jax.lax.population_count(
            jnp.bitwise_xor(b_ref[...][:, :, None],
                            t_ref[...][0][None, None, :])) <= mb
        s3 = jnp.where(ok, s3, -jnp.inf)
        o_ref[...] = s3.max(1)

    return pl.pallas_call(
        kern, grid=(N // T,),
        in_specs=[pl.BlockSpec((B, W), lambda i: (0, 0)),
                  pl.BlockSpec((T, W), lambda i: (i, 0)),
                  pl.BlockSpec((T // BS, BS), lambda i: (i, 0)),
                  pl.BlockSpec((T // BS, BS), lambda i: (i, 0)),
                  pl.BlockSpec((1, B), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((T // BS, B), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N // BS, B), jnp.float32),
    )(Qc, Y, pen, bkt, tgt)


@partial(jax.jit, static_argnames=("mb",))
def variant_b(Y, Qc, pen, bkt, tgt, mb: int):
    """Transposed: (B, T) scores; bucket/penalty flatten to (1, T) once
    per tile and broadcast along sublanes; block max over lanes; small
    (B, T//BS) -> (T//BS, B) transpose before the store."""
    N, W = Y.shape
    B = Qc.shape[0]

    def kern(q_ref, y_ref, p_ref, b_ref, t_ref, o_ref):
        s = jax.lax.dot_general(q_ref[...], y_ref[...],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        bb = b_ref[...].reshape(1, T)
        pp = p_ref[...].reshape(1, T)
        tq = t_ref[...].reshape(B, 1)
        ok = jax.lax.population_count(jnp.bitwise_xor(bb, tq)) <= mb
        s = jnp.where(ok, s + pp, -jnp.inf)
        m = s.reshape(B, T // BS, BS).max(-1)
        o_ref[...] = m.T

    return pl.pallas_call(
        kern, grid=(N // T,),
        in_specs=[pl.BlockSpec((B, W), lambda i: (0, 0)),
                  pl.BlockSpec((T, W), lambda i: (i, 0)),
                  pl.BlockSpec((T // BS, BS), lambda i: (i, 0)),
                  pl.BlockSpec((T // BS, BS), lambda i: (i, 0)),
                  pl.BlockSpec((1, B), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((T // BS, B), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N // BS, B), jnp.float32),
    )(Qc, Y, pen, bkt, tgt)


@jax.jit
def variant_exact(Y, Qc, pen):
    """No mask: the floor both variants chase."""
    N, W = Y.shape
    B = Qc.shape[0]

    def kern(q_ref, y_ref, p_ref, o_ref):
        s = jax.lax.dot_general(y_ref[...], q_ref[...],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s3 = s.reshape(T // BS, BS, B) + p_ref[...][:, :, None]
        o_ref[...] = s3.max(1)

    return pl.pallas_call(
        kern, grid=(N // T,),
        in_specs=[pl.BlockSpec((B, W), lambda i: (0, 0)),
                  pl.BlockSpec((T, W), lambda i: (i, 0)),
                  pl.BlockSpec((T // BS, BS), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((T // BS, B), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N // BS, B), jnp.float32),
    )(Qc, Y, pen)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--items-m", type=float, default=20.0)
    ap.add_argument("--features", type=int, default=50)
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()
    N = int(args.items_m * 1e6) // T * T
    W, B = 128, args.batch

    key = jax.random.PRNGKey(0)
    kY, kQ, kb, kt = jax.random.split(key, 4)
    # dense random lanes (the real snapshot zeroes lanes >= features,
    # which changes score values but not the kernels' work or layout;
    # zeroing in-place here would transiently double the 5.1 GB array)
    Y = jax.random.normal(kY, (N, W), jnp.bfloat16)
    Qc = jax.random.normal(kQ, (B, W), jnp.bfloat16)
    pen = jnp.zeros((N // BS, BS), jnp.float32)
    bkt = jax.random.randint(kb, (N // BS, BS), 0, 128, jnp.int32)
    tgt = jax.random.randint(kt, (1, B), 0, 128, jnp.int32)
    jax.block_until_ready((Y, Qc, pen, bkt, tgt))

    # correctness: variants must agree bit-for-bit
    a = jax.device_get(variant_a(Y, Qc, pen, bkt, tgt, MB))
    b = jax.device_get(variant_b(Y, Qc, pen, bkt, tgt, MB))
    assert np.array_equal(a, b, equal_nan=True), "variant mismatch"

    for name, fn in (
            ("exact_floor", lambda: variant_exact(Y, Qc, pen)),
            ("mask_3d_current", lambda: variant_a(Y, Qc, pen, bkt, tgt,
                                                  MB)),
            ("mask_2d_transposed", lambda: variant_b(Y, Qc, pen, bkt,
                                                     tgt, MB))):
        # shallow queue: each queued program holds a 160 MB (N//BS, B)
        # f32 output next to the 5.1 GB item matrix
        t = time_exec(fn, jax.device_get, m=4, min_delta_ms=20.0)
        t["variant"] = name
        print(json.dumps(t), flush=True)


if __name__ == "__main__":
    main()
