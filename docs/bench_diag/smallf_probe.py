"""Round-5 diagnostic: where does the F=50 serving scan lose 10x?

BENCH_GRID_r04: 50f cells run 7.5-40 GB/s effective while 250f/5M hits
872; 50f/20M per-tile cost is ~10.3 us vs 5.1 us at 250f/20M — MORE
time for 5x less data.  This probe isolates, on the real chip:

  1. raw HBM read of the (N, 50) bf16 array (its tiled layout pads the
     50-lane minor dim to 128 — is the padding the ceiling?)
  2. phase A (pallas fused dot+blockmax) alone, at T=4096 (current),
     8192, and with a multi-subtile kernel
  3. phase B alone
  4. the same at F=250 for reference

Usage: python docs/bench_diag/smallf_probe.py [--items 20] [--f 50,250]
"""

import argparse
import json
import sys
import time
from functools import partial

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.bench.kernel_probe import time_exec


def phase_a(Y, Q, penalty, T, bs):
    from jax.experimental import pallas as pl
    N, F = Y.shape
    B = Q.shape[0]

    def kern(q_ref, y_ref, p_ref, o_ref):
        s = jax.lax.dot_general(y_ref[...], q_ref[...],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s3 = s.reshape(T // bs, bs, B) + p_ref[...][:, :, None]
        o_ref[...] = s3.max(1)

    return pl.pallas_call(
        kern, grid=(N // T,),
        in_specs=[pl.BlockSpec((B, F), lambda i: (0, 0)),
                  pl.BlockSpec((T, F), lambda i: (i, 0)),
                  pl.BlockSpec((T // bs, bs), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((T // bs, B), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N // bs, B), jnp.float32),
    )(Q, Y, penalty)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--items", type=float, default=20)
    ap.add_argument("--f", default="50,250")
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()

    B = args.batch
    bs = 128
    N = int(args.items * 1e6) // 8192 * 8192
    key = jax.random.PRNGKey(0)
    out = {"N": N, "B": B, "results": {}}

    for F in [int(x) for x in args.f.split(",")]:
        Y = jax.device_put(jax.random.normal(key, (N, F), jnp.bfloat16))
        Q = jax.device_put(jax.random.normal(key, (B, F), jnp.bfloat16))
        penalty = jax.device_put(jnp.zeros((N // bs, bs), jnp.float32))
        jax.device_get(jnp.sum(Y[:8]))  # materialize
        res = {}
        gb = N * F * 2 / 1e9

        # 1. raw read: sum-reduce the whole array
        red = jax.jit(lambda y: jnp.sum(y.astype(jnp.float32), axis=0))
        t = time_exec(lambda: red(Y), jax.device_get)
        res["raw_read"] = {**t, "gbps": round(gb / (t["exec_ms"] / 1e3), 1)}

        # 2. phase A at several tile sizes
        for T in (4096, 8192):
            try:
                fn = jax.jit(partial(phase_a, T=T, bs=bs))
                t = time_exec(lambda: fn(Y, Q, penalty), jax.device_get)
                res[f"phase_a_T{T}"] = {
                    **t, "gbps": round(gb / (t["exec_ms"] / 1e3), 1)}
            except Exception as e:  # noqa: BLE001
                res[f"phase_a_T{T}"] = {"error": str(e)[:200]}

        # 3. the full two-phase kernel (phase A+B) as served
        from oryx_tpu.app.als import serving_model as sm
        full = partial(sm._batch_top_n_twophase_pallas, k=16, bs=bs,
                       ksel=32, max_bits=0)
        pen1 = penalty
        t = time_exec(
            lambda: full(Y, Q.astype(jnp.bfloat16), pen1,
                         jnp.ones((N,), bool), None, None),
            jax.device_get)
        res["full_twophase"] = {**t,
                               "gbps": round(gb / (t["exec_ms"] / 1e3), 1)}

        out["results"][f"F{F}"] = res
        print(json.dumps({f"F{F}": res}), flush=True)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
