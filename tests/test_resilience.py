"""Resilience policy + fault-registry unit tests (oryx_tpu/resilience/):
retry/backoff/deadline semantics, circuit-breaker state machine with an
injected clock, supervisor restart accounting with an injected sleep,
and the fault registry's arm/fire/times/config contract."""

import threading
import time

import pytest

from oryx_tpu.common.config import from_dict
from oryx_tpu.resilience import faults
from oryx_tpu.resilience.policy import (Backoff, CircuitBreaker,
                                        CircuitOpenError, Deadline,
                                        DeadlineExceeded, Retry,
                                        Supervisor, resilience_snapshot,
                                        run_with_resubscribe)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# -- backoff -----------------------------------------------------------------

def test_backoff_schedule_is_exponential_and_capped():
    b = Backoff(initial=0.1, maximum=0.5, multiplier=2.0, jitter=0.0)
    assert [b.delay(a) for a in (1, 2, 3, 4, 5)] == \
        [0.1, 0.2, 0.4, 0.5, 0.5]


def test_backoff_jitter_only_shrinks():
    b = Backoff(initial=0.1, maximum=1.0, multiplier=2.0, jitter=0.5)
    for attempt in range(1, 6):
        base = Backoff(initial=0.1, maximum=1.0, multiplier=2.0,
                       jitter=0.0).delay(attempt)
        for _ in range(20):
            d = b.delay(attempt)
            assert base * 0.5 <= d <= base


# -- retry -------------------------------------------------------------------

def _fail_n_times(n, exc=ConnectionError):
    state = {"calls": 0}

    def fn():
        state["calls"] += 1
        if state["calls"] <= n:
            raise exc(f"failure {state['calls']}")
        return "ok"

    return fn, state


def test_retry_succeeds_after_transient_failures():
    r = Retry("t-retry-1", max_attempts=4,
              backoff=Backoff(0.001, 0.002, jitter=0.0))
    fn, state = _fail_n_times(2)
    assert r.call(fn) == "ok"
    assert state["calls"] == 3
    s = r.stats()
    assert s["retries"] == 2 and s["give_ups"] == 0


def test_retry_gives_up_after_max_attempts():
    r = Retry("t-retry-2", max_attempts=3,
              backoff=Backoff(0.001, 0.002, jitter=0.0))
    fn, state = _fail_n_times(99)
    with pytest.raises(ConnectionError):
        r.call(fn)
    assert state["calls"] == 3
    assert r.stats()["give_ups"] == 1


def test_retry_does_not_retry_nonretryable():
    r = Retry("t-retry-3", retryable=(ConnectionError,), max_attempts=5,
              backoff=Backoff(0.001, 0.002, jitter=0.0))
    fn, state = _fail_n_times(99, exc=ValueError)
    with pytest.raises(ValueError):
        r.call(fn)
    assert state["calls"] == 1  # surfaced immediately


def test_retry_predicate_form():
    r = Retry("t-retry-4",
              retryable=lambda e: "soft" in str(e), max_attempts=3,
              backoff=Backoff(0.001, 0.002, jitter=0.0))
    fn, state = _fail_n_times(1, exc=lambda m: RuntimeError(f"soft {m}"))
    assert r.call(fn) == "ok"
    assert state["calls"] == 2


def test_retry_respects_deadline():
    # backoff pause (10 ms) exceeds the remaining budget: the retry
    # gives up and re-raises the CAUSE, not a DeadlineExceeded
    r = Retry("t-retry-5", max_attempts=10,
              backoff=Backoff(0.010, 0.010, jitter=0.0))
    fn, state = _fail_n_times(99)
    with pytest.raises(ConnectionError):
        r.call(fn, deadline=Deadline.after(0.001))
    assert state["calls"] == 1


def test_retry_retries_injected_faults_by_default():
    r = Retry("t-retry-6", max_attempts=3,
              backoff=Backoff(0.001, 0.002, jitter=0.0))
    fn, state = _fail_n_times(1, exc=faults.InjectedFault)
    assert r.call(fn) == "ok"


# -- deadline ----------------------------------------------------------------

def test_deadline_expiry_and_check():
    d = Deadline.after(60.0)
    assert not d.expired and d.remaining() > 0
    d.check("anything")  # no raise
    expired = Deadline.after(0.0)
    assert expired.expired and expired.remaining() == 0.0
    with pytest.raises(DeadlineExceeded):
        expired.check("work")


def test_deadline_tightest():
    a, b = Deadline.after(10.0), Deadline.after(1.0)
    assert Deadline.tightest(a, b) is b
    assert Deadline.tightest(a, None) is a
    assert Deadline.tightest(None, None) is None


# -- circuit breaker ---------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _boom():
    raise ConnectionError("down")


def test_breaker_opens_sheds_probes_and_closes():
    clock = _Clock()
    cb = CircuitBreaker("t-breaker-1", failure_threshold=2,
                        reset_timeout_sec=5.0, clock=clock)
    for _ in range(2):
        with pytest.raises(ConnectionError):
            cb.call(_boom)
    assert cb.state == CircuitBreaker.OPEN
    # open: calls shed without touching the dependency
    with pytest.raises(CircuitOpenError):
        cb.call(lambda: "never runs")
    # before the reset timeout the circuit stays open
    clock.t = 4.9
    with pytest.raises(CircuitOpenError):
        cb.call(lambda: "still shed")
    # after the timeout one probe is admitted; success closes
    clock.t = 5.1
    assert cb.call(lambda: "probe") == "probe"
    assert cb.state == CircuitBreaker.CLOSED
    s = cb.stats()
    assert s["opens"] == 1 and s["rejected"] == 2


def test_breaker_failed_probe_reopens():
    clock = _Clock()
    cb = CircuitBreaker("t-breaker-2", failure_threshold=1,
                        reset_timeout_sec=1.0, clock=clock)
    with pytest.raises(ConnectionError):
        cb.call(_boom)
    assert cb.state == CircuitBreaker.OPEN
    clock.t = 1.5
    with pytest.raises(ConnectionError):
        cb.call(_boom)  # half-open probe fails
    assert cb.state == CircuitBreaker.OPEN
    # and the reopen restarted the reset clock
    clock.t = 2.0
    with pytest.raises(CircuitOpenError):
        cb.call(lambda: "shed")
    assert cb.stats()["opens"] == 2


def test_breaker_half_open_bounds_concurrent_probes():
    clock = _Clock()
    cb = CircuitBreaker("t-breaker-3", failure_threshold=1,
                        reset_timeout_sec=1.0, half_open_probes=1,
                        clock=clock)
    with pytest.raises(ConnectionError):
        cb.call(_boom)
    clock.t = 2.0
    # first probe admitted and held in flight; the second is shed
    assert cb._admit() is True
    assert cb._admit() is False
    cb.record_success()
    assert cb.state == CircuitBreaker.CLOSED


def test_snapshot_carries_named_instances():
    r = Retry("t-snap-retry", max_attempts=2,
              backoff=Backoff(0.001, 0.002, jitter=0.0))
    cb = CircuitBreaker("t-snap-breaker")
    snap = resilience_snapshot()
    assert snap["t-snap-retry"]["kind"] == "retry"
    assert snap["t-snap-breaker"]["state"] == "closed"
    del r, cb


# -- supervisor --------------------------------------------------------------

class _FakeLayer:
    """await_ returns immediately while `alive` is False (a crashed
    worker thread); otherwise blocks until close()."""

    def __init__(self, alive: bool):
        self._alive = alive
        self._stop = threading.Event()
        self.closed = False

    def start(self):
        pass

    def await_(self):
        if self._alive:
            self._stop.wait()

    def close(self):
        self.closed = True
        self._stop.set()


def test_supervisor_restarts_dead_layer_then_runs():
    created = []
    sup_holder = {}

    def factory():
        # first two layers die instantly; the third stays up, and the
        # test stops the supervisor as if an operator shut it down
        layer = _FakeLayer(alive=len(created) >= 2)
        created.append(layer)
        return layer

    sleeps = []
    sup = Supervisor(factory, "t-layer", max_restarts=5,
                     backoff=Backoff(0.01, 0.04, jitter=0.0),
                     sleep=sleeps.append)
    sup_holder["sup"] = sup

    runner = threading.Thread(target=sup.run)
    runner.start()
    # third layer blocks in await_; stop it like the shutdown hook does
    deadline = Deadline.after(10.0)
    while len(created) < 3 and not deadline.expired:
        time.sleep(0.001)
    assert len(created) == 3
    sup.stop()
    sup.layer.close()
    runner.join(10.0)
    assert not runner.is_alive()
    assert sup.restarts == 2
    assert sleeps == [0.01, 0.02]  # exponential restart backoff
    assert all(layer.closed for layer in created)


def test_supervisor_gives_up_after_max_restarts():
    def factory():
        return _FakeLayer(alive=False)

    sup = Supervisor(factory, "t-layer-2", max_restarts=2,
                     backoff=Backoff(0.0, 0.0, jitter=0.0),
                     sleep=lambda s: None)
    with pytest.raises(RuntimeError, match="exceeded 2 restarts"):
        sup.run()
    assert sup.restarts == 2


# -- run_with_resubscribe ----------------------------------------------------
# Direct unit coverage (ISSUE 11 satellite): the speed/serving/router
# consumers and the mirror all run inside this loop — its backoff and
# stop semantics ARE their failover latency.


def test_resubscribe_restarts_failed_subscription_until_clean_end():
    stop = threading.Event()
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("broker gone")
        stop.set()  # clean end: the subscription saw stop and returned

    run_with_resubscribe(fn, stop, "t-sub",
                         backoff=Backoff(0.001, 0.002, jitter=0.0))
    assert len(calls) == 3


def test_resubscribe_backoff_resets_after_healthy_run():
    # two quick failures walk the backoff up; then a LONG healthy run
    # fails — the next resubscribe must wait the INITIAL backoff again,
    # not the lifetime-accumulated schedule (a mirror that ran for days
    # must not add a maxed-out sleep to its failover)
    clock = _Clock()
    stop = threading.Event()
    sleeps = []

    class _Stop:
        def is_set(self):
            return stop.is_set()

        def wait(self, t):
            sleeps.append(round(t, 4))
            return stop.wait(0)

    calls = []

    def fn():
        calls.append(1)
        if len(calls) == 3:
            clock.t += 1000.0  # ran healthily for a long time
        if len(calls) < 5:
            raise ConnectionError("down")
        stop.set()

    run_with_resubscribe(fn, _Stop(), "t-sub-reset",
                         backoff=Backoff(0.01, 10.0, jitter=0.0),
                         healthy_reset_sec=300.0, clock=clock)
    # attempts 1, 2 escalate; attempt after the healthy run restarts
    # the schedule at the initial delay
    assert sleeps == [0.01, 0.02, 0.01, 0.02]


def test_resubscribe_stop_during_backoff_sleep_returns_promptly():
    # the inter-attempt sleep must be interruptible: a shutdown (or a
    # supervised mirror failover) during a long backoff must not wait
    # it out
    stop = threading.Event()
    t_probe = {}

    def fn():
        if "t0" not in t_probe:
            t_probe["t0"] = time.monotonic()
            # stop lands while the loop sleeps the (huge) backoff
            threading.Timer(0.05, stop.set).start()
            raise ConnectionError("first failure")
        raise AssertionError("must not resubscribe after stop")

    run_with_resubscribe(fn, stop, "t-sub-stop",
                         backoff=Backoff(60.0, 60.0, jitter=0.0))
    assert time.monotonic() - t_probe["t0"] < 10.0


# -- fault registry ----------------------------------------------------------

def test_fault_fire_is_noop_when_unarmed():
    assert faults.fire("nothing-armed") is None
    assert faults.fired("nothing-armed") == 0


def test_fault_times_bound_and_counter():
    faults.inject("t-point", mode="error", times=2)
    for _ in range(2):
        with pytest.raises(faults.InjectedFault):
            faults.fire("t-point")
    assert faults.fire("t-point") is None  # disarmed after 2
    assert faults.fired("t-point") == 2


def test_fault_crash_is_base_exception():
    faults.inject("t-crash", mode="crash")
    with pytest.raises(faults.InjectedCrash):
        try:
            faults.fire("t-crash")
        except Exception:  # the layers' survival handlers
            pytest.fail("InjectedCrash must not be absorbable "
                        "by `except Exception`")


def test_fault_error_factory_matches_transport():
    faults.inject("t-conn", mode="error")
    with pytest.raises(ConnectionError):
        faults.fire("t-conn", error=lambda: ConnectionError("dropped"))


def test_fault_drop_and_duplicate_return_mode():
    faults.inject("t-dup", mode="duplicate", times=1)
    assert faults.fire("t-dup") == "duplicate"
    assert faults.fire("t-dup") is None
    faults.inject("t-drop", mode="drop", times=1)
    assert faults.fire("t-drop") == "drop"


def test_faults_configure_from_config():
    cfg = from_dict({
        "oryx.resilience.faults.some-point.mode": "error",
        "oryx.resilience.faults.some-point.times": 3,
        "oryx.resilience.faults.other-point.mode": "drop",
        "oryx.resilience.faults.other-point.times": -1,
    })
    faults.configure_from_config(cfg)
    with pytest.raises(faults.InjectedFault):
        faults.fire("some-point")
    assert faults.fire("other-point") == "drop"
    assert faults.fire("other-point") == "drop"  # -1 = unlimited


def test_default_config_arms_nothing():
    faults.configure_from_config(from_dict({}))
    assert faults.fire("inproc-send") is None


def test_retry_accepts_bare_exception_class():
    # an exception class is callable: it must be treated as isinstance,
    # never invoked as a predicate (which would retry EVERY error)
    r = Retry("t-retry-7", retryable=OSError, max_attempts=3,
              backoff=Backoff(0.001, 0.002, jitter=0.0))
    fn, state = _fail_n_times(1, exc=OSError)
    assert r.call(fn) == "ok"
    fn2, state2 = _fail_n_times(9, exc=ValueError)
    with pytest.raises(ValueError):
        r.call(fn2)
    assert state2["calls"] == 1


def test_supervisor_survives_factory_and_start_failures():
    # a rebuild against a still-down dependency raises from factory();
    # that must consume restart budget, not kill the process
    attempts = []

    def factory():
        attempts.append(1)
        if len(attempts) < 3:
            raise ConnectionError("broker still down")
        return _FakeLayer(alive=False)

    sup = Supervisor(factory, "t-layer-3", max_restarts=3,
                     backoff=Backoff(0.0, 0.0, jitter=0.0),
                     sleep=lambda s: None)
    with pytest.raises(RuntimeError, match="exceeded 3 restarts"):
        sup.run()
    assert len(attempts) == 4  # initial + 3 restarts


def test_supervisor_healthy_uptime_resets_restart_budget():
    clock = _Clock()

    class _TimedLayer(_FakeLayer):
        def __init__(self):
            super().__init__(alive=False)

        def await_(self):
            clock.t += 1000.0  # "ran healthily for a long time"

    sup = Supervisor(_TimedLayer, "t-layer-4", max_restarts=2,
                     backoff=Backoff(0.0, 0.0, jitter=0.0),
                     sleep=lambda s: None, healthy_reset_sec=300.0,
                     clock=clock)
    # every run exceeds the healthy window, so the budget keeps
    # resetting; stop it externally after a handful of cycles
    cycles = []
    real_sleep = sup._sleep

    def counting_sleep(s):
        cycles.append(1)
        if len(cycles) >= 6:
            sup.stop()
        real_sleep(s)

    sup._sleep = counting_sleep
    sup.run()  # would raise after 2 restarts without the reset
    assert sup.restarts <= 1


def test_breaker_releases_probe_slot_on_base_exception():
    # a crash (BaseException) during the half-open probe must record a
    # failure and free the probe slot — a leaked slot would shed every
    # later call forever even after the dependency recovers
    clock = _Clock()
    cb = CircuitBreaker("t-breaker-4", failure_threshold=1,
                        reset_timeout_sec=1.0, clock=clock)
    with pytest.raises(ConnectionError):
        cb.call(_boom)
    clock.t = 2.0

    def crash():
        raise faults.InjectedCrash("kill during probe")

    with pytest.raises(faults.InjectedCrash):
        cb.call(crash)
    assert cb.state == CircuitBreaker.OPEN  # re-opened, not wedged
    clock.t = 4.0
    assert cb.call(lambda: "probe") == "probe"
    assert cb.state == CircuitBreaker.CLOSED


def test_config_faults_arm_once_per_process():
    cfg = from_dict({
        "oryx.resilience.faults.once-point.mode": "error",
        "oryx.resilience.faults.once-point.times": 1,
    })
    faults.configure_from_config(cfg)
    with pytest.raises(faults.InjectedFault):
        faults.fire("once-point")
    # a supervised-restart rebuild calls configure again: it must NOT
    # re-arm the consumed one-shot fault
    faults.configure_from_config(cfg)
    assert faults.fire("once-point") is None
    # clear() re-opens the once-slot for the next staged run
    faults.clear()
    faults.configure_from_config(cfg)
    with pytest.raises(faults.InjectedFault):
        faults.fire("once-point")
