"""oryx-lint tier-1 wiring (ISSUE 14): the five static analysis
passes run clean over ``oryx_tpu/``, the suppression ledger is fully
justified and never stale, the seeded-defect fixtures prove each pass
actually fires, the ``--json`` report shape is golden-pinned for CI
consumers, and the whole-package run fits the wall-clock budget.

Plus the regression tests for the two real defects the suite
surfaced on its first run (guarded-by, both in the lost-update /
check-then-act class):

- ``kafka/inproc._Partition.close()`` closed the persisted-log fd
  without the partition lock, racing ``append()``'s is-open check /
  re-open / ``os.write`` — EBADF at best, a write into a recycled fd
  at worst;
- ``obs/events.WideEventLog.emit()`` bumped the ``dropped`` evidence
  counter outside the lock on the failure path, losing concurrent
  updates exactly when every drop must be countable.
"""

from __future__ import annotations

import json
import pathlib
import threading
import time

import pytest

from oryx_tpu.analysis import (PASS_NAMES, SourceModel,
                               apply_suppressions, load_suppressions,
                               run_passes)
from oryx_tpu.analysis import drift as drift_pass
from oryx_tpu.analysis import lock_order
from oryx_tpu.analysis.__main__ import main as analysis_main

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "oryx_tpu"
LEDGER = PKG / "analysis" / "suppressions.toml"
FIXTURES = REPO / "tests" / "fixtures" / "analysis"


# -- the real package -------------------------------------------------------

@pytest.fixture(scope="module")
def package_report():
    """One timed full run over oryx_tpu/, shared by every check."""
    t0 = time.monotonic()
    model = SourceModel(PKG, conf_path=PKG / "common" / "reference.conf",
                        doc_path=REPO / "docs" / "RESILIENCE.md")
    findings = run_passes(model)
    suppressions = load_suppressions(LEDGER)
    apply_suppressions(findings, suppressions)
    elapsed = time.monotonic() - t0
    return model, findings, suppressions, elapsed


@pytest.mark.parametrize("pass_name", PASS_NAMES)
def test_package_runs_clean(package_report, pass_name):
    _, findings, _, _ = package_report
    open_findings = [
        f"{f.file}:{f.line} [{f.rule}] {f.symbol}: {f.message}"
        for f in findings
        if f.pass_name == pass_name and not f.suppressed]
    assert not open_findings, (
        f"{pass_name} findings outside the suppression ledger "
        f"(fix the code, annotate, or add a justified ledger "
        f"entry):\n  " + "\n  ".join(open_findings))


def test_ledger_entries_justified_and_live(package_report):
    _, _, suppressions, _ = package_report
    assert suppressions, "ledger parsed empty — suppressions.toml gone?"
    for s in suppressions:
        assert s.justification and len(s.justification.strip()) >= 15, \
            f"suppression {s.pass_name}/{s.symbol}: justification " \
            f"must be a real sentence, got {s.justification!r}"
        assert s.hits > 0, (
            f"stale suppression (matches no live finding): "
            f"pass={s.pass_name} file={s.file} symbol={s.symbol} — "
            f"the finding it excused is gone; delete the entry")


def test_wall_clock_budget(package_report):
    model, _, _, elapsed = package_report
    assert len(model.modules) > 100, "package walk collapsed"
    assert elapsed < 10.0, (
        f"full-package analysis took {elapsed:.1f}s — past the 10s "
        f"tier-1 budget; profile the passes before adding more")


# -- walk sanity pins (a lint is only as good as its walk) ------------------

def test_walk_sees_known_config_reads(package_report):
    model, _, _, _ = package_report
    reads = drift_pass._KeyReads()
    for mod in model.modules:
        drift_pass._collect_key_reads(mod, reads)
    # a plain literal, an f-string-prefix key, and a default-parameter
    # prefix key — the three idioms the resolver must keep seeing
    assert "oryx.cluster.heartbeat-ttl-ms" in reads.getter_reads
    assert "oryx.cluster.async.max-connections" in reads.getter_reads
    assert "oryx.resilience.retry.initial-backoff-ms" \
        in reads.getter_reads
    assert "oryx.cluster.region.mirror.poll-interval-ms" \
        in reads.getter_reads


def test_walk_sees_known_fault_points(package_report):
    model, _, _, _ = package_report
    points: dict = {}
    for mod in model.modules:
        drift_pass._collect_fire_points(mod, points)
    assert "wire-read" in points          # literal fire()
    assert "store-write" in points        # aliased import (_fault)
    assert "route-measure-lsh" in points  # # chaos-point: annotation


def test_walk_sees_known_lock_edges(package_report):
    model, _, _, _ = package_report
    edges = lock_order.build_graph(model)
    names = {(a.display(), b.display()) for a, b in edges}
    # the router's documented route-then-bucket nesting must stay
    # visible, or the cycle detector has gone blind
    assert ("serving_model.ALSServingModel._route_lock",
            "serving_model.ALSServingModel._bucket_lock") in names
    assert len(edges) >= 3


# -- seeded-defect fixtures -------------------------------------------------

@pytest.fixture(scope="module")
def fixture_findings():
    model = SourceModel(FIXTURES,
                        conf_path=FIXTURES / "reference.conf",
                        doc_path=FIXTURES / "RESILIENCE.md")
    return run_passes(model)


def _have(findings, pass_name, rule, symbol):
    return any(f.pass_name == pass_name and f.rule == rule
               and f.symbol == symbol for f in findings)


def test_fixture_guarded_by_fires(fixture_findings):
    assert _have(fixture_findings, "guarded-by", "unguarded-mutation",
                 "TopologyCache._entries")
    assert _have(fixture_findings, "guarded-by", "unguarded-mutation",
                 "TopologyCache._epoch")
    # negatives: the _locked convention and the none opt-out hold
    assert not any(f.symbol == "TopologyCache.loop_stats"
                   for f in fixture_findings)
    assert not any("_purge_locked" in f.message
                   for f in fixture_findings)


def test_fixture_async_blocking_fires(fixture_findings):
    mine = [f for f in fixture_findings
            if f.pass_name == "async-blocking"]
    symbols = {f.symbol for f in mine}
    assert {"time.sleep", "open", ".acquire", ".scatter"} <= symbols
    # transitive: the sleep inside the sync helper reached from the
    # coroutine is seen too (two time.sleep findings, distinct lines)
    sleeps = [f for f in mine if f.symbol == "time.sleep"]
    assert len({f.line for f in sleeps}) == 2
    # negative: the run_in_executor-wrapped helper is not re-flagged
    assert all(f.line < 40 for f in mine), \
        "the bridged/wrapped negative case was flagged"


def test_fixture_lock_order_fires(fixture_findings):
    cycles = {f.symbol for f in fixture_findings
              if f.pass_name == "lock-order"}
    assert ("lock_cycle.Registry._a -> lock_cycle.Registry._b -> "
            "lock_cycle.Registry._a") in cycles
    assert ("lock_cycle.SelfDeadlock._lock -> "
            "lock_cycle.SelfDeadlock._lock") in cycles
    # the module-level cycle is only visible through the mutually
    # recursive _rec_a/_rec_b pair — a closure truncated mid-recursion
    # (the pre-fixpoint memo bug) loses the M -> L edge and the cycle
    assert ("lock_cycle.LOCK_L -> lock_cycle.LOCK_M -> "
            "lock_cycle.LOCK_L") in cycles
    assert not any("Ordered" in c for c in cycles), \
        "consistent ordering misreported as a cycle"


def test_fixture_drift_fires(fixture_findings):
    assert _have(fixture_findings, "drift", "unknown-config-key",
                 "oryx.fixture.unknown-key")
    assert _have(fixture_findings, "drift", "dead-config-key",
                 "oryx.fixture.dead-key")
    assert _have(fixture_findings, "drift", "undocumented-fault-point",
                 "fixture-undocumented")
    assert _have(fixture_findings, "drift", "unregistered-fault-point",
                 "fixture-stale")
    # negatives: compat annotation, f-string key, prefix subtree,
    # annotation-declared point
    quiet = {"oryx.fixture.compat-key", "oryx.fixture.tuning.depth",
             "oryx.fixture.subtree.inner", "fixture-annotated",
             "fixture-documented"}
    assert not quiet & {f.symbol for f in fixture_findings}


def test_fixture_diagnose_catalog_fires(fixture_findings):
    assert _have(fixture_findings, "diagnose-catalog",
                 "uncatalogued-metric", "fixture_renamed_away_counter")
    assert _have(fixture_findings, "diagnose-catalog",
                 "uncatalogued-flight-field", "fixture_ghost_field")
    # negatives: catalogued reads and the documented bundle field
    quiet = {"fixture_catalogued_counter", "fixture_catalogued_gauge",
             "trigger_id"}
    assert not quiet & {f.symbol for f in fixture_findings
                        if f.pass_name == "diagnose-catalog"}


def test_fixture_sim_clock_fires(fixture_findings):
    mine = [f for f in fixture_findings if f.pass_name == "sim-clock"]
    assert _have(fixture_findings, "sim-clock", "direct-time",
                 "time.monotonic")
    # aliased import (`import time as _t`) still resolves
    assert _have(fixture_findings, "sim-clock", "direct-time",
                 "time.sleep")
    assert _have(fixture_findings, "sim-clock", "event-wait",
                 "self._stop.wait")
    # negatives: the seam itself (clockmod.*, self._clock.wait) and
    # the `# wall-clock:` annotation stay quiet
    assert all(f.line < 30 for f in mine), \
        "a clock-seam/annotated negative case was flagged"


# -- CLI contract -----------------------------------------------------------

def _cli(capsys, *args):
    rc = analysis_main(list(args))
    return rc, capsys.readouterr().out


def test_cli_golden_json(capsys):
    rc, out = _cli(capsys, "--root", str(FIXTURES),
                   "--conf", str(FIXTURES / "reference.conf"),
                   "--doc", str(FIXTURES / "RESILIENCE.md"),
                   "--json", "--no-suppressions")
    assert rc == 1  # findings -> non-zero, so it can gate CI
    got = json.loads(out)
    golden = json.loads(
        (FIXTURES / "golden.json").read_text(encoding="utf-8"))
    assert got == golden, (
        "the --json report shape/content drifted from "
        "tests/fixtures/analysis/golden.json — if intentional, "
        "regenerate the golden file (docs/ANALYSIS.md runbook)")


def test_cli_clean_package_exits_zero(capsys):
    rc, _ = _cli(capsys, "--root", str(PKG))
    assert rc == 0


def test_cli_single_pass_selection(capsys):
    rc, out = _cli(capsys, "--root", str(FIXTURES),
                   "--conf", str(FIXTURES / "reference.conf"),
                   "--doc", str(FIXTURES / "RESILIENCE.md"),
                   "--json", "--no-suppressions",
                   "--pass", "lock-order")
    assert rc == 1
    got = json.loads(out)
    assert got["passes"] == ["lock-order"]
    assert {f["pass"] for f in got["findings"]} == {"lock-order"}


# -- regression: the defects the suite surfaced -----------------------------

@pytest.mark.chaos
def test_partition_close_is_atomic_with_append(tmp_path):
    """close() racing append() on a persisted partition must never
    leak an EBADF/recycled-fd write: both now hold the partition
    lock, so every acked append lands in the log file."""
    from oryx_tpu.kafka.inproc import _Partition

    part = _Partition(lambda: None, str(tmp_path / "p0.jsonl"))
    n, errors = 400, []

    def writer():
        try:
            for i in range(n):
                part.append("k", f"m{i}")
        except Exception as e:  # noqa: BLE001 — the regression signal
            errors.append(e)

    t = threading.Thread(target=writer)
    t.start()
    for _ in range(200):
        part.close()  # append() re-opens; close() must not tear it
    t.join(30.0)
    part.close()
    assert not errors, f"append raced close(): {errors[0]!r}"
    data = (tmp_path / "p0.jsonl").read_bytes()
    assert data.count(b"\n") == n, "acked appends lost in the race"


@pytest.mark.chaos
def test_wide_event_dropped_counter_is_exact(tmp_path):
    """Every failure-path drop must be counted: the ``dropped += 1``
    now happens under the log's lock, so concurrent droppers cannot
    lose updates (the counter is the only evidence the drop ever
    happened)."""
    from oryx_tpu.obs.events import WideEventLog
    from oryx_tpu.resilience import faults

    log = WideEventLog(str(tmp_path), "test", registry=None)
    faults.inject("obs-event-disk-full", mode="error", times=None)
    try:
        threads = [threading.Thread(
            target=lambda: [log.emit("GET /x", 200, 1.0, None)
                            for _ in range(200)])
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
    finally:
        faults.clear("obs-event-disk-full")
        log.close()
    assert log.emitted == 0
    assert log.dropped == 8 * 200, \
        f"lost drop-counter updates: {log.dropped} != 1600"
