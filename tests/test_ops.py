"""Tier-1 math kernel tests (reference analogs: VectorMathTest,
LinearSystemSolverTest, ALSUtilsTest)."""

import math

import numpy as np
import pytest

from oryx_tpu.ops import als_fold_in, solver, vectors


# -- vectors ----------------------------------------------------------------

def test_dot_norm_cosine():
    x = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    y = np.array([4.0, -5.0, 6.0], dtype=np.float32)
    assert float(vectors.dot(x, y)) == pytest.approx(12.0)
    assert float(vectors.norm(x)) == pytest.approx(math.sqrt(14.0))
    expected = 12.0 / (math.sqrt(14.0) * math.sqrt(77.0))
    assert float(vectors.cosine_similarity(x, y)) == pytest.approx(expected, rel=1e-6)


def test_transpose_times_self():
    v = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], dtype=np.float32)
    expected = v.T @ v
    np.testing.assert_allclose(np.asarray(vectors.transpose_times_self(v)),
                               expected, rtol=1e-6)


def test_random_vector_f_deterministic():
    a = vectors.random_vector_f(8)
    b = vectors.random_vector_f(8)
    np.testing.assert_array_equal(a, b)  # test seed active
    assert a.dtype == np.float32


# -- solver -----------------------------------------------------------------

def test_solver_solves_spd_system():
    rng = np.random.default_rng(42)
    m = rng.standard_normal((50, 8))
    a = m.T @ m + 0.1 * np.eye(8)
    s = solver.get_solver(a)
    b = rng.standard_normal(8)
    x = s.solve(b)
    np.testing.assert_allclose(a @ x, b, atol=1e-3)


def test_solver_batch_matches_loop():
    rng = np.random.default_rng(0)
    m = rng.standard_normal((40, 6))
    a = m.T @ m + 0.5 * np.eye(6)
    s = solver.get_solver(a)
    bs = rng.standard_normal((10, 6)).astype(np.float32)
    batch = s.solve(bs)
    for i in range(10):
        np.testing.assert_allclose(batch[i], s.solve(bs[i]), rtol=1e-5, atol=1e-5)


def test_solver_rejects_singular():
    a = np.ones((4, 4))  # rank 1
    with pytest.raises(solver.SingularMatrixSolverException) as ei:
        solver.get_solver(a)
    assert ei.value.apparent_rank == 1


def test_solver_rejects_indefinite():
    # symmetric, nonsingular, but not positive definite: Cholesky would
    # silently produce NaN without the guard
    a = np.array([[1.0, 0.0], [0.0, -1.0]])
    with pytest.raises(solver.SingularMatrixSolverException):
        solver.get_solver(a)


def test_packed_round_trip():
    # packed lower-triangular column-major for [[4,1,0],[1,5,2],[0,2,6]]
    packed = np.array([4.0, 1.0, 0.0, 5.0, 2.0, 6.0])
    full = solver.unpack_packed(packed)
    expected = np.array([[4.0, 1.0, 0.0], [1.0, 5.0, 2.0], [0.0, 2.0, 6.0]])
    np.testing.assert_array_equal(full, expected)
    s = solver.get_solver(packed)
    b = np.array([1.0, 2.0, 3.0])
    np.testing.assert_allclose(expected @ s.solve(b), b, atol=1e-4)


# -- fold-in ----------------------------------------------------------------

def _target_qui_scalar(implicit, value, current):
    """Straight transcription of the documented ALSUtils.computeTargetQui
    contract, used as an independent oracle."""
    if not implicit:
        return value
    if value > 0.0 and current < 1.0:
        return current + (value / (1.0 + value)) * (1.0 - max(0.0, current))
    if value < 0.0 and current > 0.0:
        return current + (value / (value - 1.0)) * (-min(1.0, current))
    return float("nan")


@pytest.mark.parametrize("implicit", [True, False])
@pytest.mark.parametrize("value,current", [
    (1.0, 0.3), (2.5, -0.2), (0.5, 1.5), (-1.0, 0.7), (-0.5, -0.1), (0.0, 0.5),
])
def test_compute_target_qui_matches_oracle(implicit, value, current):
    got = float(als_fold_in.compute_target_qui(implicit, value, current))
    want = _target_qui_scalar(implicit, value, current)
    if math.isnan(want):
        assert math.isnan(got)
    else:
        assert got == pytest.approx(want, rel=1e-5)


def _setup_solver(k=5, seed=7):
    rng = np.random.default_rng(seed)
    y = rng.standard_normal((30, k)).astype(np.float32)
    yty = y.T @ y
    return solver.get_solver(yty), yty, rng


def test_single_fold_in_moves_qui_toward_target():
    s, yty, rng = _setup_solver()
    xu = rng.standard_normal(5).astype(np.float32) * 0.1
    yi = rng.standard_normal(5).astype(np.float32)
    qui = float(xu @ yi)
    new_xu = als_fold_in.compute_updated_xu(s, 1.0, xu, yi, implicit=True)
    assert new_xu is not None
    target = _target_qui_scalar(True, 1.0, qui)
    # after the update, Xu . Yi should be closer to the target...
    new_qui = float(new_xu @ yi)
    assert abs(new_qui - target) < abs(qui - target)


def test_fold_in_no_item_vector_returns_none():
    s, _, _ = _setup_solver()
    assert als_fold_in.compute_updated_xu(s, 1.0, np.zeros(5, np.float32),
                                          None, True) is None


def test_fold_in_no_change_when_target_nan():
    s, _, rng = _setup_solver()
    # implicit, positive value but current >= 1 -> NaN target -> no update
    yi = rng.standard_normal(5).astype(np.float32)
    xu = 2.0 * yi / float(yi @ yi)  # dot = 2.0 >= 1
    assert als_fold_in.compute_updated_xu(s, 1.0, xu, yi, True) is None


def test_fold_in_new_user_uses_half_baseline():
    s, _, rng = _setup_solver()
    yi = rng.standard_normal(5).astype(np.float32)
    new_xu = als_fold_in.compute_updated_xu(s, 3.0, None, yi, implicit=True)
    assert new_xu is not None
    # target from current=0.5, Qui=0: dXu solves toward the full target
    target = _target_qui_scalar(True, 3.0, 0.5)
    assert not math.isnan(target)


def test_fold_in_explicit_sets_value_as_target():
    s, _, rng = _setup_solver()
    xu = rng.standard_normal(5).astype(np.float32) * 0.1
    yi = rng.standard_normal(5).astype(np.float32)
    new_xu = als_fold_in.compute_updated_xu(s, 4.0, xu, yi, implicit=False)
    qui = float(xu @ yi)
    new_qui = float(new_xu @ yi)
    assert abs(new_qui - 4.0) < abs(qui - 4.0)


def test_fold_in_batch_matches_singles():
    s, _, rng = _setup_solver(k=6, seed=11)
    n = 20
    values = rng.standard_normal(n).astype(np.float32) * 2
    xu = rng.standard_normal((n, 6)).astype(np.float32) * 0.2
    yi = rng.standard_normal((n, 6)).astype(np.float32)
    # some events have no existing Xu
    xu[3] = np.nan
    xu[7] = np.nan
    new_xu, valid = als_fold_in.fold_in_batch(s, values, xu, yi, implicit=True)
    for i in range(n):
        single = als_fold_in.compute_updated_xu(
            s, float(values[i]),
            None if np.isnan(xu[i]).any() else xu[i], yi[i], True)
        if single is None:
            assert not valid[i]
        else:
            assert valid[i]
            np.testing.assert_allclose(new_xu[i], single, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("implicit", [True, False])
@pytest.mark.parametrize("start_with_xu", [True, False])
def test_fold_in_sequential_matches_per_event_loop(implicit, start_with_xu):
    """The one-dispatch lax.scan context fold-in must reproduce the
    per-event compute_updated_xu loop exactly (including skipped items
    and the running-vector dependency between events)."""
    s, _, rng = _setup_solver(k=6, seed=23)
    item_vecs = {f"i{j}": rng.standard_normal(6).astype(np.float32) * 0.5
                 for j in range(8)}
    item_values = [("i0", 1.0), ("missing", 2.0), ("i1", -0.5),
                   ("i2", 3.0), ("i3", 0.0), ("i4", 1.5)]
    xu0 = (rng.standard_normal(6).astype(np.float32) * 0.1
           if start_with_xu else None)

    expected = xu0
    for iid, value in item_values:
        yi = item_vecs.get(iid)
        if yi is None:
            continue
        new = als_fold_in.compute_updated_xu(s, value, expected, yi, implicit)
        if new is not None:
            expected = new

    got = als_fold_in.fold_in_sequential(
        s, item_values, item_vecs.get, xu0, implicit, 6)
    if expected is None:
        assert got is None
    else:
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_fold_in_sequential_all_missing_returns_initial():
    s, _, rng = _setup_solver(k=6, seed=24)
    assert als_fold_in.fold_in_sequential(
        s, [("nope", 1.0)], lambda _: None, None, True, 6) is None
    xu = rng.standard_normal(6).astype(np.float32)
    got = als_fold_in.fold_in_sequential(
        s, [("nope", 1.0)], lambda _: None, xu, True, 6)
    np.testing.assert_allclose(got, xu)


def test_fold_in_batch_pads_to_pow2_buckets():
    """Live micro-batches arrive in arbitrary sizes; every size within a
    pow2 bucket must hit the same compiled kernel (VERDICT r2: the speed
    layer recompiled per distinct batch size)."""
    rng = np.random.default_rng(3)
    k = 6
    y = rng.standard_normal((4 * k, k)).astype(np.float32)
    s = solver.get_solver(y.T @ y)
    if not hasattr(als_fold_in._fold_in_kernel, "_cache_size"):
        pytest.skip("jit cache-size introspection not available")
    before = als_fold_in._fold_in_kernel._cache_size()
    results = {}
    for n in (3, 5, 7, 8):
        values = (rng.exponential(1.0, n) + 0.1).astype(np.float32)
        xu = (rng.standard_normal((n, k)) * 0.2).astype(np.float32)
        yi = rng.standard_normal((n, k)).astype(np.float32)
        new_xu, valid = als_fold_in.fold_in_batch(s, values, xu, yi,
                                                  implicit=True)
        assert new_xu.shape == (n, k)
        assert valid.shape == (n,)
        results[n] = (new_xu, valid)
    # all four sizes pad to the 8-bucket: at most one new compile
    # (zero when an earlier test already warmed this bucket)
    assert als_fold_in._fold_in_kernel._cache_size() <= before + 1
    # padded rows must not leak into results: size-3 batch result equals
    # the same 3 events folded at the exact bucket size
    n, k3 = 3, k
    values = (np.arange(1, n + 1) / 2).astype(np.float32)
    xu = (rng.standard_normal((n, k3)) * 0.2).astype(np.float32)
    yi = rng.standard_normal((n, k3)).astype(np.float32)
    a, va = als_fold_in.fold_in_batch(s, values, xu, yi, implicit=True)
    pad_v = np.pad(values, (0, 5))
    pad_xu = np.pad(xu, ((0, 5), (0, 0)), constant_values=np.nan)
    pad_yi = np.pad(yi, ((0, 5), (0, 0)), constant_values=np.nan)
    b, vb = als_fold_in.fold_in_batch(s, pad_v, pad_xu, pad_yi,
                                      implicit=True)
    np.testing.assert_allclose(a, b[:n], rtol=1e-6)
    np.testing.assert_array_equal(va, vb[:n])
