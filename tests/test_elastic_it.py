"""Elastic-topology chaos IT (ISSUE 6 acceptance): REAL OS processes
over a durable ``file://`` broker — `python -m oryx_tpu serving
--shard i/N` replicas and the `router`, exactly the production
topology — proving, with one router process and no restarts anywhere:

1. killing one member of a 2-replica group yields ZERO partial answers
   and zero 5xx on ``/recommend`` after the TTL window, byte-identical
   ids to the pre-kill answers (a dead replica costs latency, not
   coverage);
2. a live 2→3 reshard under continuous load completes with no
   downtime and exact answers before, during, and after the atomic
   cutover — and the retired fleet's stale heartbeats are counted,
   never merged;
3. ``reshard-warm-stall``: a new-topology replica stalled mid-replay
   (conf-armed fault, so it fires in THAT process only) never becomes
   ready, so cutover never happens and the old topology keeps serving
   exact answers;
4. ``replica-group-flap``: a group member whose heartbeats straggle
   just past the TTL oscillates in and out of routing with zero
   partial answers and zero topology churn.

Scenarios share one module-scoped cluster and run in file order (the
topology evolves 2 → 3 across them).  Marker: chaos (tier-1).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.bench.gateway import (_await, _free_port, _get_json,
                                    _spawn, _write_conf)
from oryx_tpu.cluster.sharding import shard_of
from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.kafka.api import KEY_MODEL_REF
from oryx_tpu.kafka.inproc import resolve_broker

pytestmark = [pytest.mark.chaos, pytest.mark.slow]
# slow: this module is the retained real-process smoke for scenarios
# whose tier-1 coverage moved to the deterministic simulation
# (tests/test_sim_sweep.py) — hundreds of seeded interleavings per
# run instead of one wall-clock interleaving per CI run.

_USERS = [f"u{j}" for j in range(6)]
_ITEMS = [f"i{j}" for j in range(60)]
_FEATURES = 3
# fast membership so TTL transitions fit the tier-1 budget
_FAST = {
    "oryx.cluster.heartbeat-interval-ms": 150,
    "oryx.cluster.heartbeat-ttl-ms": 900,
    "oryx.cluster.hedge-after-ms": 60,
    "oryx.cluster.max-attempts-per-shard": 3,
    # ready only at FULL replay: a warming replica must never answer
    # for users it has not absorbed yet (exactness during cutover)
    "oryx.serving.min-model-load-fraction": 1.0,
}


def _publish_model(broker_dir: str, work_dir: str) -> None:
    """SHARDED publish (ISSUE 10): a manifest-carrying MODEL-REF whose
    murmur2 slices live in the shared store, and NO per-row UP flood —
    so every replica in this IT (including the 2→3 reshard's warming
    fleet) loads from slices + the topic tail, never a full-stream
    replay.  The ring (24) is divisible by both topologies this IT
    walks (2 and 3)."""
    from oryx_tpu.app.als import slices as model_slices

    broker = resolve_broker(f"file://{broker_dir}")
    rng = np.random.default_rng(11)
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", _FEATURES)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", _USERS)
    pmml_io.add_extension_content(doc, "YIDs", _ITEMS)
    model_dir = os.path.join(work_dir, "model-gen1")
    os.makedirs(model_dir, exist_ok=True)
    pmml_path = os.path.join(model_dir, "model.pmml.xml")
    pmml_io.write(doc, pmml_path)
    Y = np.round(rng.standard_normal((len(_ITEMS), _FEATURES)), 3
                 ).astype(np.float32)
    X = np.round(rng.standard_normal((len(_USERS), _FEATURES)), 3
                 ).astype(np.float32)
    # monolithic artifacts alongside the slices — the production
    # layout, so a fail-closed load would degrade instead of hanging
    # (the IT still asserts the warm path took slices, zero fallbacks)
    from oryx_tpu.app.als.update import save_features
    save_features(os.path.join(model_dir, "Y"), _ITEMS, Y)
    save_features(os.path.join(model_dir, "X"), _USERS, X)
    slim = model_slices.publish_sliced(model_dir, _ITEMS, Y, _USERS, X,
                                       None, 24)
    broker.send("GwUp", KEY_MODEL_REF,
                model_slices.model_ref_message(pmml_path, model_dir,
                                               slim))
    broker.close()


def _get(port, path, timeout=15):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), json.loads(r.read() or b"null")


def _post_json(port, path, payload, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode("utf-8"), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read() or b"null")


class _Cluster:
    """Process bookkeeping for the module's evolving fleet."""

    def __init__(self, work_dir, broker_dir):
        self.work_dir = work_dir
        self.broker_dir = broker_dir
        self.procs: dict[str, tuple] = {}  # name -> (Popen, port)
        self.router_port: int | None = None

    def spawn_replica(self, name: str, shard: int, of: int,
                      extra: dict | None = None) -> int:
        port = _free_port()
        conf = os.path.join(self.work_dir, f"{name}.conf")
        overlay = {"oryx.cluster.enabled": True,
                   "oryx.cluster.shard": f"{shard}/{of}",
                   "oryx.cluster.replica-id": name, **_FAST,
                   **(extra or {})}
        _write_conf(conf, self.broker_dir, port, overlay)
        proc = _spawn(["serving", "--shard", f"{shard}/{of}"], conf,
                      None, os.path.join(self.work_dir, f"{name}.log"))
        self.procs[name] = (proc, port)
        return port

    def spawn_router(self) -> int:
        port = _free_port()
        conf = os.path.join(self.work_dir, "router.conf")
        _write_conf(conf, self.broker_dir, port, dict(_FAST))
        proc = _spawn(["router"], conf, None,
                      os.path.join(self.work_dir, "router.log"))
        self.procs["router"] = (proc, port)
        self.router_port = port
        return port

    def kill(self, name: str) -> None:
        proc, _ = self.procs.pop(name)
        proc.kill()  # SIGKILL: a crash, not a graceful drain
        proc.wait(timeout=15)

    def await_ready(self, names, timeout=240.0) -> None:
        ports = [self.procs[n][1] for n in names]
        _await(lambda: all(_get_json(p, "/shard/meta").get("ready")
                           for p in ports),
               f"replicas ready: {names}", timeout=timeout)

    def close(self) -> None:
        for name in list(self.procs):
            try:
                self.kill(name)
            except Exception:  # noqa: BLE001 — teardown best effort
                pass


class _LoadProbe(threading.Thread):
    """Continuous /recommend load with per-response verdicts: any
    non-200, any X-Oryx-Partial, any id-set drift from the expected
    exact answers is recorded."""

    def __init__(self, port, expected: dict[str, list[str]]):
        super().__init__(daemon=True)
        self.port = port
        self.expected = expected
        self.stop_event = threading.Event()
        self.count = 0
        self.failures: list[str] = []
        self.partials = 0

    def run(self):
        users = sorted(self.expected)
        i = 0
        while not self.stop_event.is_set():
            uid = users[i % len(users)]
            i += 1
            try:
                status, headers, rows = _get(
                    self.port, f"/recommend/{uid}?howMany=8")
                if status != 200:
                    self.failures.append(f"{uid}: HTTP {status}")
                elif headers.get("X-Oryx-Partial"):
                    self.partials += 1
                elif [d["id"] for d in rows] != self.expected[uid]:
                    self.failures.append(f"{uid}: ids drifted")
            except Exception as e:  # noqa: BLE001 — any failure counts
                self.failures.append(f"{uid}: {type(e).__name__}: {e}")
            self.count += 1
            time.sleep(0.02)

    def halt(self) -> "_LoadProbe":
        self.stop_event.set()
        self.join(10.0)
        return self


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    # the synthetic catalog must populate every shard of every
    # topology this IT walks through (2, 3, and the re-declared 2)
    for n in (2, 3):
        owners = {shard_of(i, n) for i in _ITEMS}
        assert owners == set(range(n)), f"catalog misses shards at {n}"
    work = tmp_path_factory.mktemp("elastic-it")
    broker_dir = str(work / "broker")
    os.makedirs(broker_dir)
    _publish_model(broker_dir, str(work))
    c = _Cluster(str(work), broker_dir)
    try:
        # shard 0 is a 2-way replica GROUP; shard 1 single-member
        c.spawn_replica("a1", 0, 2)
        c.spawn_replica("a2", 0, 2)
        c.spawn_replica("b", 1, 2)
        c.spawn_router()
        c.await_ready(["a1", "a2", "b"])
        _await(lambda: _get_json(c.router_port, "/metrics")
               ["cluster"]["covered_shards"] == [0, 1],
               "router coverage", timeout=60.0)
        # exact expected answers per user, captured while whole
        expected = {}
        for uid in _USERS:
            status, headers, rows = _get(c.router_port,
                                         f"/recommend/{uid}?howMany=8")
            assert status == 200 and not headers.get("X-Oryx-Partial")
            expected[uid] = [d["id"] for d in rows]
        c.expected = expected
        yield c
    finally:
        c.close()


def test_01_kill_group_member_zero_partials_zero_5xx(cluster):
    c = cluster
    c.kill("a2")  # one member of shard 0's 2-way group
    time.sleep(1.5 * _FAST["oryx.cluster.heartbeat-ttl-ms"] / 1000.0)
    # after the TTL window the dead member has aged out: the sibling
    # covers its shard — full coverage, zero partials, zero 5xx
    status, _, _ = _get(c.router_port, "/ready")
    assert status in (200, 204)
    for round_ in range(3):
        for uid in _USERS:
            status, headers, rows = _get(
                c.router_port, f"/recommend/{uid}?howMany=8")
            assert status == 200, (round_, uid)
            assert headers.get("X-Oryx-Partial") is None, (round_, uid)
            assert [d["id"] for d in rows] == c.expected[uid], uid
    # the failover left countable evidence on the router
    m = _get_json(c.router_port, "/metrics")
    assert m["cluster"]["membership"]["shards"] == 2


def test_02_live_reshard_2_to_3_under_continuous_load(cluster):
    # retained as the real-process smoke for this scenario; the
    # tier-1 coverage moved to the deterministic sim, which sweeps
    # hundreds of cutover interleavings per run at ~0.05 s each
    # (tests/test_sim_sweep.py, scenario "reshard-cutover")
    c = cluster
    # runbook step 1: declare the target
    status, st = _post_json(c.router_port, "/admin/topology", {"of": 3})
    assert status == 200 and st["reshard_target"] == 3
    probe = _LoadProbe(c.router_port, c.expected)
    probe.start()
    try:
        # step 2: start the M-way fleet (it warms from the same topic
        # through the murmur2 ring while the old fleet keeps serving)
        for s in range(3):
            c.spawn_replica(f"n{s}", s, 3)
        # step 3: watch /admin/topology until the atomic cutover
        _await(lambda: _get_json(c.router_port, "/admin/topology")
               ["merged_of"] == 3, "cutover to 3", timeout=240.0)
        time.sleep(1.0)  # keep load flowing across the cutover wake
    finally:
        probe.halt()
    assert probe.count > 50
    assert probe.failures == []
    assert probe.partials == 0
    # the old fleet still runs: its heartbeats are now stale — counted,
    # never merged
    _await(lambda: _get_json(c.router_port, "/metrics")["counters"]
           .get("stale_topology_heartbeats", 0) > 0,
           "stale heartbeats counted", timeout=30.0)
    snap = _get_json(c.router_port, "/metrics")["cluster"]["membership"]
    assert snap["shards"] == 3
    assert all(r["of"] == 3 for r in snap["replicas"].values())
    assert snap["topology_cutovers"] == 1
    # the warming fleet loaded from SLICES, not a full-stream replay:
    # every new replica shows slice bytes read, a stamped load clock,
    # and zero fallbacks to the monolithic artifacts (ISSUE 10
    # acceptance — reshard warmup is slices + topic tail)
    for s in range(3):
        g = _get_json(c.procs[f"n{s}"][1], "/metrics")["freshness"]
        assert g.get("slice_load_fallbacks") == 0, (s, g)
        assert g.get("model_slice_bytes", 0) > 0, (s, g)
        assert g.get("model_load_s", 0) > 0, (s, g)
    # step 4: retire the old fleet — answers stay exact and complete
    c.kill("a1")
    c.kill("b")
    time.sleep(1.5 * _FAST["oryx.cluster.heartbeat-ttl-ms"] / 1000.0)
    for uid in _USERS:
        status, headers, rows = _get(c.router_port,
                                     f"/recommend/{uid}?howMany=8")
        assert status == 200 and headers.get("X-Oryx-Partial") is None
        assert [d["id"] for d in rows] == c.expected[uid], uid


def test_03_reshard_warm_stall_never_cuts_over(cluster):
    c = cluster
    # scale back down: 2 was retired at the 2→3 cutover; re-declaring
    # un-retires it (the runbook's scale-down path)
    _post_json(c.router_port, "/admin/topology", {"of": 2})
    # shard 0's new replica stalls mid-replay — conf-armed, so the
    # fault fires in THAT process only; it never reaches ready
    c.spawn_replica("stall0", 0, 2, extra={
        "oryx.resilience.faults.reshard-warm-stall.mode": "delay",
        "oryx.resilience.faults.reshard-warm-stall.times": -1,
        "oryx.resilience.faults.reshard-warm-stall.delay-ms": 60000,
    })
    c.spawn_replica("ok1", 1, 2)
    c.await_ready(["ok1"])
    # give the would-be cutover every chance, under live checks: the
    # target topology never reaches full coverage, so the OLD topology
    # keeps serving exact, complete answers
    t_end = time.monotonic() + 4.0
    while time.monotonic() < t_end:
        status = _get_json(c.router_port, "/admin/topology")
        assert status["merged_of"] == 3
        t2 = status["topologies"].get("2")
        if t2 is not None:
            assert not t2["full_coverage"]
            assert t2["ready_shards"] <= 1
        uid = _USERS[0]
        s, headers, rows = _get(c.router_port,
                                f"/recommend/{uid}?howMany=8")
        assert s == 200 and headers.get("X-Oryx-Partial") is None
        assert [d["id"] for d in rows] == c.expected[uid]
        time.sleep(0.2)
    assert _get_json(c.router_port, "/metrics")["cluster"][
        "membership"]["topology_cutovers"] == 1  # still just 2→3
    # abandon the stalled reshard: cancel the target, stop its fleet
    _post_json(c.router_port, "/admin/topology", {"of": 3})
    c.kill("stall0")
    c.kill("ok1")


def test_04_replica_group_flap_causes_no_routing_churn(cluster):
    c = cluster
    cutovers_before = _get_json(c.router_port, "/metrics")["cluster"][
        "membership"]["topology_cutovers"]
    # a sibling for shard 0 whose heartbeats straggle past the TTL:
    # each publish sleeps 1.5 s against a 0.9 s TTL, so it keeps
    # aging out of routing and returning — the flap
    c.spawn_replica("flappy", 0, 3, extra={
        "oryx.resilience.faults.replica-group-flap.mode": "delay",
        "oryx.resilience.faults.replica-group-flap.times": -1,
        "oryx.resilience.faults.replica-group-flap.delay-ms": 1500,
    })
    _await(lambda: "flappy" in _get_json(
        c.router_port, "/metrics")["cluster"]["membership"]["replicas"],
        "flapping member announced", timeout=240.0)
    live_states = set()
    failures, partials = [], 0
    t_end = time.monotonic() + 5.0
    i = 0
    while time.monotonic() < t_end:
        uid = _USERS[i % len(_USERS)]
        i += 1
        try:
            status, headers, rows = _get(c.router_port,
                                         f"/recommend/{uid}?howMany=8")
            if status != 200:
                failures.append(status)
            elif headers.get("X-Oryx-Partial"):
                partials += 1
            elif [d["id"] for d in rows] != c.expected[uid]:
                failures.append(f"{uid} drifted")
        except Exception as e:  # noqa: BLE001 — any failure counts
            failures.append(str(e))
        snap = _get_json(c.router_port, "/metrics")["cluster"][
            "membership"]
        flap = snap["replicas"].get("flappy")
        if flap is not None:
            live_states.add(flap["live"])
        assert snap["shards"] == 3  # no topology churn, ever
        time.sleep(0.05)
    # the member really oscillated around the TTL...
    assert live_states == {True, False}, live_states
    # ...and routing never wavered: group siblings absorbed every flap
    assert failures == []
    assert partials == 0
    assert _get_json(c.router_port, "/metrics")["cluster"][
        "membership"]["topology_cutovers"] == cutovers_before
    c.kill("flappy")
