"""ML loop tests (reference analogs: HyperParamsTest, SimpleMLUpdateIT,
ThresholdIT via MockMLUpdate)."""

import os
from xml.etree.ElementTree import Element

import pytest

from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_MODEL, KeyMessage
from oryx_tpu.kafka.inproc import InProcTopicProducer, get_broker
from oryx_tpu.ml import params as hp
from oryx_tpu.ml.mlupdate import MODEL_FILE_NAME, MLUpdate


# -- params -----------------------------------------------------------------

def test_fixed_and_unordered():
    assert hp.fixed(7).get_trial_values(3) == [7]
    assert hp.unordered(["a", "b", "c"]).get_trial_values(2) == ["a", "b"]


def test_continuous_range_trials():
    r = hp.range_values(0.0, 1.0)
    assert r.get_trial_values(1) == [0.5]
    assert r.get_trial_values(2) == [0.0, 1.0]
    vals = r.get_trial_values(5)
    assert vals == pytest.approx([0.0, 0.25, 0.5, 0.75, 1.0])


def test_discrete_range_trials():
    r = hp.range_values(1, 10)
    assert r.get_trial_values(1) == [5]
    assert r.get_trial_values(2) == [1, 10]
    assert r.get_trial_values(4) == [1, 4, 7, 10]
    # more trials than distinct values -> all values
    assert hp.range_values(1, 3).get_trial_values(10) == [1, 2, 3]


def test_around_trials():
    assert hp.around(10, 2).get_trial_values(3) == [8, 10, 12]
    assert hp.around(1.0, 0.5).get_trial_values(3) == pytest.approx([0.5, 1.0, 1.5])
    assert hp.around(1.0, 0.5).get_trial_values(1) == [1.0]


def test_choose_values_per_hyperparam():
    assert hp.choose_values_per_hyperparam(0, 5) == 0
    assert hp.choose_values_per_hyperparam(1, 5) == 5
    assert hp.choose_values_per_hyperparam(2, 5) == 3  # 3^2 = 9 >= 5
    assert hp.choose_values_per_hyperparam(3, 8) == 2  # 2^3 = 8


def test_choose_combos_grid_and_subset():
    ranges = [hp.unordered([1, 2, 3]), hp.unordered(["x", "y"])]
    combos = hp.choose_hyper_parameter_combos(ranges, 100, 3)
    assert len(combos) == 6
    assert sorted(map(tuple, combos)) == sorted(
        [(a, b) for b in ["x", "y"] for a in [1, 2, 3]])
    subset = hp.choose_hyper_parameter_combos(ranges, 2, 3)
    assert len(subset) == 2
    # no params -> single empty combo
    assert hp.choose_hyper_parameter_combos([], 3, 0) == [[]]


def test_from_config():
    cfg = from_dict({
        "a.fixed-int": 5, "a.fixed-double": 1.5, "a.range-int": [2, 8],
        "a.range-double": [0.1, 0.9], "a.unordered": ["gini", "entropy"],
    })
    assert hp.from_config(cfg, "a.fixed-int").get_trial_values(2) == [5]
    assert hp.from_config(cfg, "a.fixed-double").get_trial_values(1) == [1.5]
    assert hp.from_config(cfg, "a.range-int").get_trial_values(2) == [2, 8]
    assert hp.from_config(cfg, "a.range-double").get_trial_values(2) == [0.1, 0.9]
    assert hp.from_config(cfg, "a.unordered").get_trial_values(9) == ["gini", "entropy"]


def test_from_config_unordered_keeps_native_types():
    cfg = from_dict({"a.ints": [5, 10, 20], "a.mixed": [1.5, 2.5, 3.5]})
    assert hp.from_config(cfg, "a.ints").get_trial_values(3) == [5, 10, 20]
    assert hp.from_config(cfg, "a.mixed").get_trial_values(3) == [1.5, 2.5, 3.5]


# -- pmml -------------------------------------------------------------------

def test_pmml_skeleton_and_extensions(tmp_path):
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", 10)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", ["u1", "u 2", "u3"])
    path = str(tmp_path / "model.pmml.xml")
    pmml_io.write(doc, path)
    loaded = pmml_io.read(path)
    assert pmml_io.get_extension_value(loaded, "features") == "10"
    assert pmml_io.get_extension_value(loaded, "implicit") == "true"
    assert pmml_io.get_extension_content(loaded, "XIDs") == ["u1", "u 2", "u3"]
    assert pmml_io.get_extension_value(loaded, "nope") is None
    # round-trip through string form (the MODEL message payload)
    re_read = pmml_io.from_string(pmml_io.to_string(loaded))
    assert pmml_io.get_extension_value(re_read, "features") == "10"


# -- MLUpdate ---------------------------------------------------------------

class MockMLUpdate(MLUpdate):
    """Records train/test sizes, emits a dummy PMML whose eval is set by
    the test (reference: MockMLUpdate.java:35)."""

    evals: list[float] = []
    train_counts: list[int] = []
    test_counts: list[int] = []
    _call = 0

    def get_hyper_parameter_values(self):
        return []

    def build_model(self, train_data, hyper_parameters, candidate_path):
        MockMLUpdate.train_counts.append(len(train_data))
        doc = pmml_io.build_skeleton_pmml()
        pmml_io.add_extension(doc, "mock", "yes")
        return doc

    def evaluate(self, model, candidate_path, test_data, train_data):
        MockMLUpdate.test_counts.append(len(test_data))
        i = MockMLUpdate._call
        MockMLUpdate._call += 1
        return MockMLUpdate.evals[i % len(MockMLUpdate.evals)]


def _run_update(cfg_overlay, data, tmp_path, topic_name):
    cfg = from_dict(cfg_overlay)
    update = MockMLUpdate(cfg)
    producer = InProcTopicProducer("memory://ml-test", topic_name)
    model_dir = str(tmp_path / "model")
    update.run_update(0, data, [], model_dir, producer)
    broker = get_broker("ml-test")
    msgs = list(broker.consume(topic_name, from_beginning=True, max_idle_sec=0.1))
    return model_dir, msgs


def _reset_mock(evals):
    MockMLUpdate.evals = evals
    MockMLUpdate.train_counts = []
    MockMLUpdate.test_counts = []
    MockMLUpdate._call = 0


def test_mlupdate_publishes_model(tmp_path):
    _reset_mock([0.5])
    data = [KeyMessage(None, f"line{i}") for i in range(100)]
    model_dir, msgs = _run_update({}, data, tmp_path, "t1")
    assert len(msgs) == 1
    assert msgs[0].key == KEY_MODEL
    doc = pmml_io.from_string(msgs[0].message)
    assert pmml_io.get_extension_value(doc, "mock") == "yes"
    # model dir holds one timestamped dir with the model file; temp cleaned
    entries = os.listdir(model_dir)
    assert len(entries) == 1 and entries[0].isdigit()
    assert MODEL_FILE_NAME in os.listdir(os.path.join(model_dir, entries[0]))
    # ~10% went to test by default
    assert MockMLUpdate.train_counts[0] + MockMLUpdate.test_counts[0] == 100
    assert 1 <= MockMLUpdate.test_counts[0] <= 30


def test_mlupdate_threshold_rejects_model(tmp_path):
    _reset_mock([0.1])
    data = [KeyMessage(None, f"line{i}") for i in range(50)]
    model_dir, msgs = _run_update({"oryx.ml.eval.threshold": 0.9}, data,
                                  tmp_path, "t2")
    assert msgs == []  # model discarded
    assert os.listdir(model_dir) == []


def test_mlupdate_candidates_pick_best(tmp_path):
    _reset_mock([0.1, 0.9, 0.3])
    data = [KeyMessage(None, f"line{i}") for i in range(60)]
    _, msgs = _run_update({"oryx.ml.eval.candidates": 3,
                           "oryx.ml.eval.parallelism": 1}, data, tmp_path, "t3")
    assert len(msgs) == 1 and msgs[0].key == KEY_MODEL
    assert MockMLUpdate._call == 3


def test_mlupdate_eval_disabled_keeps_model(tmp_path):
    _reset_mock([float("nan")])
    data = [KeyMessage(None, "x")] * 10
    _, msgs = _run_update({"oryx.ml.eval.test-fraction": 0.0}, data,
                          tmp_path, "t4")
    assert len(msgs) == 1  # model kept though never evaluated
    assert MockMLUpdate.test_counts == []


def test_mlupdate_model_ref_when_too_large(tmp_path):
    _reset_mock([0.5])
    data = [KeyMessage(None, "x")] * 10
    _, msgs = _run_update({"oryx.update-topic.message.max-size": 10}, data,
                          tmp_path, "t5")
    assert len(msgs) == 1
    assert msgs[0].key == "MODEL-REF"
    assert os.path.exists(msgs[0].message)


def test_mlupdate_profile_dir_writes_trace(tmp_path):
    """oryx.ml.profile-dir wraps candidate building in a JAX profiler
    trace (SURVEY §5.1 observability: the Spark-UI equivalent)."""
    import os
    _reset_mock([0.5])
    cfg = from_dict({"oryx.ml.profile-dir": str(tmp_path / "traces")})
    update = MockMLUpdate(cfg)
    data = [KeyMessage(None, f"line{i}") for i in range(20)]
    update.run_update(1234, data, [], str(tmp_path / "model"), None)
    # one timestamped trace dir with profiler output inside
    roots = os.listdir(tmp_path / "traces")
    assert roots == ["1234"]
    found = []
    for dirpath, _, files in os.walk(tmp_path / "traces"):
        found.extend(files)
    assert found, "profiler wrote no trace files"
