"""Cluster unit + property tests: sharding, membership, and the exact
top-N merge (ISSUE 4 satellite: random catalogs / shardings / ties /
retired rows, merged scatter-gather top-N byte-identical — ids and
order — to the single-node exact scan, including the rescorer path).

The property tests drive N sharded ALSServingModelManagers and one
full (0/1) manager through the IDENTICAL simulated update-topic
stream — the same totally-ordered replay real replicas consume — then
compare ``merge(shards)`` against the single node AND against an
independent brute-force numpy oracle.  Factor values are multiples of
1/4 at 4 features, so every dot product is an exact multiple of 1/16
in float32: scores are bit-identical no matter which kernel/shape
computed them, and the byte-identical claim is deterministic, not
rounding-lucky.  Ties are real (duplicate vectors), and retired rows
recycle store rows differently in every process — exactly the row
order divergence the canonical (score, ordinal, id) order exists to
neutralize.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from oryx_tpu.app.als.rescorer import Rescorer, RescorerProvider
from oryx_tpu.app.als.serving_manager import ALSServingModelManager
from oryx_tpu.app.als.serving_model import ALSServingModel
from oryx_tpu.cluster.membership import (Heartbeat, KEY_HEARTBEAT,
                                         MembershipRegistry,
                                         without_heartbeats)
from oryx_tpu.cluster.merge import (canon_sort, exact_local_top_n,
                                    merge_top_n)
from oryx_tpu.cluster.sharding import (is_local_item, parse_shard_spec,
                                       shard_of)
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_UP, KeyMessage

FEATURES = 4


# -- sharding ----------------------------------------------------------------

def test_parse_shard_spec():
    assert parse_shard_spec("0/1") == (0, 1)
    assert parse_shard_spec("3/4") == (3, 4)
    for bad in ("4/4", "-1/2", "x/2", "1", "1/0", "2/1"):
        with pytest.raises(ValueError):
            parse_shard_spec(bad)


def test_shard_of_is_stable_and_covers_all_shards():
    ids = [f"i{j}" for j in range(500)]
    n = 4
    first = {i: shard_of(i, n) for i in ids}
    assert all(0 <= s < n for s in first.values())
    assert {shard_of(i, n) for i in ids} == set(range(n))  # no empty shard
    assert all(shard_of(i, n) == first[i] for i in ids)    # stable
    assert all(shard_of(i, 1) == 0 for i in ids[:10])
    # partition of the catalog: each id local to exactly one shard
    for i in ids[:50]:
        assert sum(is_local_item(i, s, n) for s in range(n)) == 1


# -- membership --------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _hb(replica, shard, of=2, gen=1, ready=True, url=None):
    return Heartbeat(replica=replica, shard=shard, of=of,
                     url=url or f"http://h:{shard}", generation=gen,
                     ready=ready)


def test_registry_liveness_ttl_and_ready_gating():
    clock = _Clock()
    reg = MembershipRegistry(ttl_sec=1.0, clock=clock)
    reg.note(_hb("a", 0))
    reg.note(_hb("b", 1))
    reg.note(_hb("c", 1, ready=False))  # still loading: never routed
    assert [h.replica for h in reg.candidates(0)] == ["a"]
    assert [h.replica for h in reg.candidates(1)] == ["b"]
    assert reg.covered_shards() == [0, 1]
    clock.t = 2.0  # both age out
    assert reg.candidates(0) == []
    assert reg.covered_shards() == []
    reg.note(_hb("a", 0))  # rejoin: routed again, no reset needed
    assert [h.replica for h in reg.candidates(0)] == ["a"]


def test_registry_prefers_newest_generation_within_shard():
    reg = MembershipRegistry(ttl_sec=10.0, clock=_Clock())
    reg.note(_hb("old", 0, gen=1))
    reg.note(_hb("new", 0, gen=2))
    # the replica serving the older model is ranked strictly behind
    for _ in range(4):
        assert reg.candidates(0)[0].replica == "new"
        assert reg.candidates(0)[-1].replica == "old"


def test_registry_merges_one_topology_only():
    """A 0/1 full replica must never be merged with 2-way shards: the
    catalogs overlap and the merge would duplicate items."""
    reg = MembershipRegistry(ttl_sec=10.0, clock=_Clock())
    reg.note(_hb("full", 0, of=1))
    reg.note(_hb("s0", 0, of=2))
    reg.note(_hb("s1", 1, of=2))
    assert reg.shard_count == 2
    assert [h.replica for h in reg.candidates(0)] == ["s0"]
    assert reg.covered_shards() == [0, 1]
    assert all(h.of == 2 for h in reg.any_candidates())


def test_any_candidates_generation_first_with_rotation():
    """Rotation must spread load WITHIN the newest generation only — a
    stale-generation replica is never ranked ahead of an up-to-date
    one (it would serve stale user-store answers while fresh replicas
    are live)."""
    reg = MembershipRegistry(ttl_sec=10.0, clock=_Clock())
    reg.note(_hb("a", 0, gen=2))
    reg.note(_hb("b", 1, gen=2))
    reg.note(_hb("stale", 1, gen=1))
    seen_first = set()
    for _ in range(6):
        c = reg.any_candidates()
        assert [h.replica for h in c][-1] == "stale"
        seen_first.add(c[0].replica)
    assert seen_first == {"a", "b"}  # rotation still spreads load


def test_snapshot_reports_current_topology_after_reshard_down():
    """/metrics must agree with routing: after a reshard down, the
    live topology (largest of among live replicas), not the largest
    ever seen."""
    clock = _Clock()
    reg = MembershipRegistry(ttl_sec=1.0, clock=clock)
    for s in range(4):
        reg.note(_hb(f"r{s}", s, of=4))
    assert reg.snapshot()["shards"] == 4
    # 4-way fleet stops; the bootstrap hatch re-opens only once it has
    # been silent past the re-bootstrap grace (dead, not blinking)
    clock.t = 1.0 * MembershipRegistry.REBOOTSTRAP_GRACE_TTLS + 1.1
    reg.note(_hb("n0", 0, of=2))
    reg.note(_hb("n1", 1, of=2))
    assert reg.shard_count == 2
    assert reg.snapshot()["shards"] == 2


def test_collect_rows_marks_skewed_404_shard_partial():
    """A shard answering 404 while others return rows (replay skew: one
    replica absorbed a new user before its peer) must surface as a
    partial answer, not as a silently incomplete 200; a consensus 404
    stays a real 404."""
    from oryx_tpu.cluster.router import _collect_rows
    from oryx_tpu.cluster.scatter import ShardResponse

    ok = ShardResponse(0, 200, {"rows": [["a", 1.0, 0]]}, "u0")
    nf = ShardResponse(1, 404, None, "u1")
    rows, miss, odd = _collect_rows({0: ok, 1: nf})
    assert rows == [[("a", 1.0, 0)]] and miss == 0 and odd == [1]
    rows, miss, odd = _collect_rows(
        {0: ShardResponse(0, 404, None, "u0"), 1: nf})
    assert rows == [] and miss == 404 and odd == []


def test_heartbeat_json_roundtrip_and_malformed_ignored():
    hb = _hb("r1", 1, gen=7)
    back = Heartbeat.from_json(hb.to_json())
    assert back == hb
    assert Heartbeat.from_json("{not json") is None
    assert Heartbeat.from_json('{"replica": "x"}') is None
    reg = MembershipRegistry(ttl_sec=1.0, clock=_Clock())
    reg.note_message("garbage")  # must not raise
    assert reg.snapshot()["replicas"] == {}


def test_without_heartbeats_filters_only_hb_keys():
    stream = [KeyMessage(KEY_HEARTBEAT, "{}"), KeyMessage("UP", "u"),
              KeyMessage("MODEL", "m"), KeyMessage(KEY_HEARTBEAT, "{}")]
    assert [km.key for km in without_heartbeats(stream)] == ["UP", "MODEL"]


def test_manager_ignores_heartbeat_key():
    mgr = _manager("0/1")
    mgr.consume_key_message(KEY_HEARTBEAT, '{"whatever": 1}')  # no raise
    with pytest.raises(ValueError):
        mgr.consume_key_message("BOGUS", "x")


def test_routing_plan_is_one_consistent_snapshot():
    """The scatter fan-out must see ONE topology: routing_plan returns
    (of, per-shard candidates) from a single locked read — per-shard
    candidates() calls each re-derive the topology, and a cutover
    landing between two of them could merge shards of two different
    rings in one request (overlapping catalogs, no partial marker)."""
    reg = MembershipRegistry(ttl_sec=10.0, clock=_Clock())
    reg.note(_hb("a", 0, of=2))
    reg.note(_hb("b", 1, of=2))
    of, plan = reg.routing_plan()
    assert of == 2
    assert [hb.replica for hb in plan[0]] == ["a"]
    assert [hb.replica for hb in plan[1]] == ["b"]
    # the plan cuts over atomically: the moment a declared 3-way
    # target is fully ready, ONE plan is entirely 3-way (and the next
    # ones too) — never a 2/3 hybrid
    reg.begin_reshard(3)
    for s in range(3):
        reg.note(_hb(f"n{s}", s, of=3))
    of2, plan2 = reg.routing_plan()
    assert of2 == 3 and len(plan2) == 3
    assert all(hb.of == 3 for sl in plan2 for hb in sl)
    # rotation spreads load within the newest generation, same
    # contract as candidates()
    reg.note(_hb("n0b", 0, of=3))
    first = {reg.routing_plan()[1][0][0].replica for _ in range(6)}
    assert first == {"n0", "n0b"}


# -- the merge property tests ------------------------------------------------

def _manager(shard_spec: str, rescorer_provider=None) -> ALSServingModelManager:
    cfg = from_dict({
        "oryx.serving.model-manager-class": "unused",
        "oryx.cluster.enabled": True,
        "oryx.cluster.shard": shard_spec,
        "oryx.input-topic.broker": None,
        "oryx.update-topic.broker": None,
    })
    mgr = ALSServingModelManager(cfg)
    mgr.model = ALSServingModel(FEATURES, implicit=True, sample_rate=1.0,
                                rescorer_provider=rescorer_provider)
    return mgr


def _grid_vec(rng) -> list[float]:
    """Vectors on a coarse grid: all dot products exact in f32."""
    return [float(x) / 4.0 for x in rng.integers(-8, 9, FEATURES)]


def _feed(managers, key, message):
    for m in managers:
        m.consume_key_message(key, message)


def _random_replay(rng, managers, n_items=60, n_users=8,
                   distinct_vectors=14, retire_fraction=0.4):
    """One simulated update-topic replay, identically consumed by every
    manager: Y vectors drawn from a small pool (real exact ties), a
    retire wave (random subset removed — frees store rows), then a
    second wave whose new/re-added items RECYCLE freed rows in
    process-specific order."""
    pool = [_grid_vec(rng) for _ in range(distinct_vectors)]
    item_ids = [f"i{j}" for j in range(n_items)]
    for iid in item_ids:
        vec = pool[int(rng.integers(0, len(pool)))]
        _feed(managers, KEY_UP, json.dumps(["Y", iid, vec]))
    for u in range(n_users):
        known = [item_ids[k] for k in
                 rng.choice(n_items, size=5, replace=False)]
        _feed(managers, KEY_UP,
              json.dumps(["X", f"u{u}", _grid_vec(rng), known]))
    # retire wave: same ids everywhere; each process frees only the
    # rows it holds, so free-list order diverges between processes
    retired = [i for i in item_ids if rng.random() < retire_fraction]
    for m in managers:
        for iid in retired:
            m.model.Y.remove(iid)
    # second wave: new items + re-added retired items reuse freed rows
    second = [f"j{j}" for j in range(n_items // 2)] + retired[::2]
    for iid in second:
        vec = pool[int(rng.integers(0, len(pool)))]
        _feed(managers, KEY_UP, json.dumps(["Y", iid, vec]))
    return item_ids + [f"j{j}" for j in range(n_items // 2)], retired


def _oracle_top_n(model, ordinals, how_many, user_vector, exclude=(),
                  rescore=None, lowest=False):
    """Independent brute-force reference: numpy dots over the host
    arrays, sorted by the canonical (score, ordinal, id) order."""
    host, active, row_ids = model.Y.host_arrays()
    q = np.asarray(user_vector, np.float32)
    rows = []
    for r, iid in enumerate(row_ids):
        if iid is None or not active[r] or iid in exclude:
            continue
        s = float(np.dot(host[r].astype(np.float32), q))
        if rescore is not None:
            s = rescore(iid, s)
            if s is None:
                continue
        rows.append((iid, s, ordinals.get(iid, 1 << 62)))
    return canon_sort(rows, lowest)[:how_many]


@pytest.mark.parametrize("shards", [1, 2, 3, 5])
def test_merged_top_n_is_byte_identical_to_single_node(shards):
    rng = np.random.default_rng(100 + shards)
    shard_mgrs = [_manager(f"{s}/{shards}") for s in range(shards)]
    full = _manager("0/1")
    managers = shard_mgrs + [full]
    _random_replay(rng, managers)
    ordinals = full.item_ordinals
    assert all(m.item_ordinals == ordinals for m in shard_mgrs)
    # the shards partition the surviving catalog
    all_local = sorted(i for m in shard_mgrs
                       for i in m.model.all_item_ids())
    assert all_local == sorted(full.model.all_item_ids())

    for u in range(8):
        uid = f"u{u}"
        xu = full.model.get_user_vector(uid)
        exclude = full.model.get_known_items(uid)
        for how_many in (1, 3, 10, 25):
            per_shard = [
                exact_local_top_n(m.model, lambda i, m=m:
                                  m.item_ordinals.get(i, 1 << 62),
                                  how_many, user_vector=xu,
                                  exclude=exclude)
                for m in shard_mgrs]
            merged = merge_top_n(per_shard, how_many)
            single = exact_local_top_n(
                full.model, lambda i: ordinals.get(i, 1 << 62),
                how_many, user_vector=xu, exclude=exclude)
            # byte-identical: ids, order, scores, ordinals
            assert merged == single[:how_many], (uid, how_many)
            oracle = _oracle_top_n(full.model, ordinals, how_many, xu,
                                   exclude)
            assert merged == oracle, (uid, how_many)


def test_boundary_tie_group_straddling_k_is_widened_exactly():
    """A tie group crossing the local k boundary (where device top-k
    picks by row order) must be resolved by the widening loop, not by
    whichever rows the kernel happened to keep."""
    rng = np.random.default_rng(7)
    shard_mgrs = [_manager(f"{s}/2") for s in range(2)]
    full = _manager("0/1")
    managers = shard_mgrs + [full]
    # 1 clear winner + 30 items EXACTLY tied + 10 clear losers
    win = [2.0] * FEATURES
    tie = [1.0] * FEATURES
    lose = [0.25] * FEATURES
    _feed(managers, KEY_UP, json.dumps(["Y", "top", win]))
    for j in range(30):
        _feed(managers, KEY_UP, json.dumps(["Y", f"t{j:02d}", tie]))
    for j in range(10):
        _feed(managers, KEY_UP, json.dumps(["Y", f"z{j}", lose]))
    _feed(managers, KEY_UP,
          json.dumps(["X", "u0", [1.0] * FEATURES, []]))
    del rng
    ordinals = full.item_ordinals
    xu = full.model.get_user_vector("u0")
    for how_many in (2, 5, 17, 30, 31, 41):
        per_shard = [exact_local_top_n(
            m.model, lambda i, m=m: m.item_ordinals.get(i, 1 << 62),
            how_many, user_vector=xu) for m in shard_mgrs]
        merged = merge_top_n(per_shard, how_many)
        oracle = _oracle_top_n(full.model, ordinals, how_many, xu)
        assert merged == oracle, how_many
    # ordinal order inside the tie group: first-appearance order
    ids = [i for i, _, _ in merge_top_n(per_shard, 11)]
    assert ids == ["top"] + [f"t{j:02d}" for j in range(10)]


class _StubStore:
    def __init__(self, capacity):
        self._capacity = capacity

    def row_ids(self):
        return [None] * self._capacity


class _StubModel:
    """Minimal model for exact_local_top_n's widening loop: ``rows``
    lists (id, score) in DEVICE ROW order; top_n is stable within a
    score tie, exactly the device kernel's row-index tie-break."""

    def __init__(self, rows, capacity):
        self.rows = rows
        self.Y = _StubStore(capacity)

    def item_count(self):
        return len(self.rows)

    def top_n(self, how_many, user_vector=None, cosine_to=None,
              exclude=(), rescorer=None, allowed=None, lowest=False,
              use_lsh=True):
        cand = [(i, s) for i, s in self.rows if i not in exclude]
        cand.sort(key=lambda t: t[1] if lowest else -t[1])
        return cand[:how_many]


def test_remote_heavy_exclude_does_not_stop_widening():
    """On a sharded replica the exclude set is the user's GLOBAL known
    items — most occupy no local row.  Counting them toward window
    coverage used to stop the widening loop with live tied candidates
    still unfetched, so a boundary tie group resolved by device row
    order instead of the canonical ordinal."""
    # 50 exactly-tied items whose DEVICE row order is the reverse of
    # their ordinal order (recycled rows), padded store capacity 64
    rows = [(f"r{k:02d}", 1.0) for k in range(49, -1, -1)]
    model = _StubModel(rows, capacity=64)
    # 100 excluded ids, none of them local to this shard
    exclude = {f"remote{j}" for j in range(100)}
    got = exact_local_top_n(model, lambda i: int(i[1:]), 5,
                            user_vector=[1.0], exclude=exclude)
    # canonical: the 5 lowest ordinals of the tie group, NOT the 5
    # highest-row survivors the first narrow fetch happened to see
    assert got == [(f"r{k:02d}", 1.0, k) for k in range(5)]


class _TestRescorer(Rescorer):
    def rescore(self, item_id, score):
        # exact arithmetic (halving), order-scrambling (sign flip for
        # even-suffixed ids), plus filtering
        return -score / 2.0 if int(item_id[1:]) % 2 == 0 else score

    def is_filtered(self, item_id):
        return item_id.endswith("3")


class _TestProvider(RescorerProvider):
    def get_recommend_rescorer(self, user_id, args):
        return _TestRescorer()

    def get_recommend_to_anonymous_rescorer(self, item_ids, args):
        return _TestRescorer()

    def get_most_popular_items_rescorer(self, args):
        return None

    def get_most_active_users_rescorer(self, args):
        return None

    def get_most_similar_items_rescorer(self, args):
        return None


@pytest.mark.parametrize("shards", [2, 3])
def test_merged_top_n_rescorer_path_matches_single_node(shards):
    provider = _TestProvider()
    rng = np.random.default_rng(40 + shards)
    shard_mgrs = [_manager(f"{s}/{shards}", provider)
                  for s in range(shards)]
    full = _manager("0/1", provider)
    managers = shard_mgrs + [full]
    _random_replay(rng, managers, n_items=40, retire_fraction=0.3)
    ordinals = full.item_ordinals

    def rescore(iid, s):
        r = _TestRescorer()
        if r.is_filtered(iid):
            return None
        return r.rescore(iid, s)

    for u in range(4):
        uid = f"u{u}"
        xu = full.model.get_user_vector(uid)
        exclude = full.model.get_known_items(uid)
        for how_many in (3, 12):
            per_shard = [exact_local_top_n(
                m.model, lambda i, m=m: m.item_ordinals.get(i, 1 << 62),
                how_many, user_vector=xu, exclude=exclude,
                rescorer=provider.get_recommend_rescorer(uid, []))
                for m in shard_mgrs]
            merged = merge_top_n(per_shard, how_many)
            single = exact_local_top_n(
                full.model, lambda i: ordinals.get(i, 1 << 62),
                how_many, user_vector=xu, exclude=exclude,
                rescorer=provider.get_recommend_rescorer(uid, []))
            assert merged == single[:how_many], (uid, how_many)
            oracle = _oracle_top_n(full.model, ordinals, how_many, xu,
                                   exclude, rescore=rescore)
            assert merged == oracle, (uid, how_many)


def test_merge_offset_and_lowest():
    rows_a = [("a", 3.0, 0), ("b", 1.0, 1)]
    rows_b = [("c", 2.0, 2), ("d", 1.0, 0)]
    assert [r[0] for r in merge_top_n([rows_a, rows_b], 4)] == \
        ["a", "c", "d", "b"]  # tie at 1.0: ordinal 0 before 1
    assert [r[0] for r in merge_top_n([rows_a, rows_b], 2, offset=1)] == \
        ["c", "d"]
    assert [r[0] for r in merge_top_n([rows_a, rows_b], 2,
                                      lowest=True)] == ["d", "b"]


def test_sharded_manager_skips_remote_items_but_keeps_ordinals():
    mgr = _manager("0/2")
    n = 30
    for j in range(n):
        mgr.consume_key_message(
            KEY_UP, json.dumps(["Y", f"i{j}", [1.0] * FEATURES]))
    local = [f"i{j}" for j in range(n) if shard_of(f"i{j}", 2) == 0]
    assert sorted(mgr.model.all_item_ids()) == sorted(local)
    assert mgr.skipped_remote_items == n - len(local)
    # ordinals cover EVERY id, local or not, in stream order
    assert [i for i, _ in sorted(mgr.item_ordinals.items(),
                                 key=lambda kv: kv[1])] == \
        [f"i{j}" for j in range(n)]


def test_cli_serving_shard_spec_fails_fast():
    from oryx_tpu.deploy.main import main as cli_main
    with pytest.raises(ValueError):
        cli_main(["serving", "--shard", "9/2", "--conf", "/dev/null"])
