"""Seeded drift defects, chaos side: a fault point fired but not
documented in the fixture RESILIENCE.md (its inverse — documented but
never fired — is seeded in the doc itself as ``fixture-stale``).
NEVER imported — scanned as AST by tests/test_static_analysis.
"""

from oryx_tpu.resilience.faults import fire as _fault


def replay(batch):
    _fault("fixture-undocumented")  # SEEDED: no RESILIENCE.md row
    for record in batch:
        _fault("fixture-documented")
        yield record


def measure(point):
    # dynamically composed names declare themselves by annotation:
    _fault(point)  # chaos-point: fixture-annotated
