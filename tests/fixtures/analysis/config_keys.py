"""Seeded drift defects, config side: one key read but absent from
the fixture reference.conf, one declared key never read.  The
``# compat:`` annotated key and the prefix-literal subtree read are
negative cases.  NEVER imported — scanned as AST by
tests/test_static_analysis.
"""


def load(config):
    known = config.get_int("oryx.fixture.known-key")
    missing = config.get_string("oryx.fixture.unknown-key")  # SEEDED
    base = "oryx.fixture.tuning"
    depth = config.get_int(f"{base}.depth")
    helper(config, "oryx.fixture.subtree")
    return known, missing, depth


def helper(config, prefix):
    return config.get_optional_string(f"{prefix}.inner")
