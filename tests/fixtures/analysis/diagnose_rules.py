"""Seeded diagnose-catalog defects: a diagnosis rule reading a metric
with no fixture OBSERVABILITY.md row, and a flight bundle field the
catalog never documents (each next to a catalogued negative that must
stay quiet).  NEVER imported — scanned as AST by
tests/test_static_analysis.
"""

from oryx_tpu.obs.diagnose import Rule

BUNDLE_FIELDS = (
    "trigger_id",               # catalogued — no finding
    "fixture_ghost_field",      # SEEDED: no OBSERVABILITY.md row
)

RULES = (
    Rule("fixture-ok",
         reads=("fixture_catalogued_counter",
                "fixture_catalogued_gauge"),
         runbook="docs/OBSERVABILITY.md#nowhere",
         summary="catalogued reads — no finding",
         check=lambda surface: None),
    Rule("fixture-stale-read",
         reads=("fixture_renamed_away_counter",),  # SEEDED: uncatalogued
         runbook="docs/OBSERVABILITY.md#nowhere",
         summary="reads a metric the catalog no longer names",
         check=lambda surface: None),
)
