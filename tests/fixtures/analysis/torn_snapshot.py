"""Seeded guarded-by defects: the torn-snapshot / lost-update class.

``invalidate`` mutates lock-guarded state without the lock — exactly
the per-shard-reads-straddling-a-cutover bug class the pass exists
for.  ``_purge_locked`` and the ``# guarded-by: none`` attribute are
negative cases: the ``_locked`` convention and the opt-out must not
fire.  NEVER imported — scanned as AST by tests/test_static_analysis.
"""

import threading


class TopologyCache:

    def __init__(self):
        self._lock = threading.Lock()
        self._epoch = 0      # guarded-by: _lock
        self._entries = {}
        self.loop_stats = 0  # guarded-by: none — single-thread owner

    def absorb(self, key, value):
        with self._lock:
            self._entries[key] = value
            self._epoch += 1

    def invalidate(self, key):
        self._entries.pop(key, None)  # SEEDED: mutation without lock
        self._epoch += 1              # SEEDED: RMW without lock

    def _purge_locked(self):
        self._entries.clear()  # fine: caller holds the lock

    def reset(self):
        with self._lock:
            self._purge_locked()

    def tick(self):
        self.loop_stats += 1  # fine: declared unguarded

    def annotate_only(self):
        self._entries: dict  # fine: bare annotation, not a store
