"""Seeded lock-order defects.

``Registry`` nests its two locks in opposite orders across methods —
the classic two-thread deadlock.  ``SelfDeadlock`` re-acquires its
own non-reentrant lock through a method call made while holding it —
the obs/slo.py gauge-callback class.  ``Ordered`` is the negative
case: a consistent A-then-B order is not a cycle.  The module-level
``LOCK_L``/``LOCK_M`` trio seeds a cycle only reachable through a
*mutually recursive* call pair (``_rec_a``/``_rec_b``): the M -> L
edge exists only because ``_rec_b``'s transitive closure includes
``_rec_a``'s acquisition, so a closure truncated mid-recursion loses
the whole cycle.  NEVER imported — scanned as AST by
tests/test_static_analysis.
"""

import threading

LOCK_L = threading.Lock()
LOCK_M = threading.Lock()
LOCK_M2 = threading.Lock()


def _rec_a():
    with LOCK_L:
        pass
    _rec_b()


def _rec_b():
    _rec_a()


def rec_entry_first():
    # resolved before rec_entry_second: a truncated-memo closure would
    # cache closure(_rec_b) = {} while computing closure(_rec_a) here
    with LOCK_M2:
        _rec_a()


def rec_entry_second():
    with LOCK_M:  # SEEDED: M -> L only via _rec_b's recursive closure
        _rec_b()


def l_then_m():
    with LOCK_L:
        with LOCK_M:  # SEEDED: ... and L -> M closes the cycle
            pass


class Registry:

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.items = {}

    def ab(self):
        with self._a:
            with self._b:  # SEEDED: a -> b here ...
                return len(self.items)

    def ba(self):
        with self._b:
            with self._a:  # SEEDED: ... b -> a there
                return sorted(self.items)


class SelfDeadlock:

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def snapshot(self):
        with self._lock:
            return {"value": self._gauge()}

    def _gauge(self):
        with self._lock:  # SEEDED: called by snapshot() holding _lock
            return self.value


class Ordered:

    def __init__(self):
        self._outer = threading.Lock()
        self._inner = threading.Lock()
        self.n = 0

    def one(self):
        with self._outer:
            with self._inner:
                self.n += 1

    def two(self):
        with self._outer:
            with self._inner:
                self.n -= 1
