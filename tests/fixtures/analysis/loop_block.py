"""Seeded async-blocking defects: synchronous work on the event loop.

The wrapped ``run_in_executor`` dispatch and the non-blocking
``acquire(blocking=False)`` probe are negative cases.  NEVER
imported — scanned as AST by tests/test_static_analysis.
"""

import asyncio
import threading
import time


def _parse(raw):
    return raw.split()


class FrontEnd:

    def __init__(self, pool):
        self._pool = pool
        self._lock = threading.Lock()

    async def handle(self, raw):
        time.sleep(0.01)                # SEEDED: sleeps the loop
        parts = _parse(raw)
        body = open(parts[0]).read()    # SEEDED: file I/O on the loop
        self._lock.acquire()            # SEEDED: parks the loop
        try:
            return self._score(body)
        finally:
            self._lock.release()

    async def fan_out(self, query):
        return self._pool.scatter("GET", query)  # SEEDED: deny-list

    def _score(self, body):
        time.sleep(0.05)  # SEEDED: reached from async handle()
        return len(body)

    async def bridged(self, raw):
        # negative: wrapped work runs off-loop, probe is non-blocking
        if self._lock.acquire(blocking=False):
            self._lock.release()
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self._score, raw)
