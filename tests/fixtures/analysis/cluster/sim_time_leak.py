"""Seeded defects for the sim-clock pass (tests/test_static_analysis
.py::test_fixture_sim_clock_fires).

Positives: a direct monotonic read, an aliased sleep, and a raw event
wait in a sim-covered module.  Negatives below the marker: the clock
seam itself, an injected per-instance clock, and an annotated
wall-clock exception — none may be flagged.
"""

import threading
import time
import time as _t

from nowhere import clock as clockmod  # noqa: F401 (fixture only)


class StalenessGauge:
    def __init__(self, clock=None):
        self._clock = clock
        self._since = time.monotonic()          # direct-time
        self._stop = threading.Event()

    def backoff(self, delay: float) -> None:
        _t.sleep(delay)                          # direct-time (alias)

    def park(self, timeout: float) -> bool:
        return self._stop.wait(timeout)          # event-wait


# -- negatives: everything from here down must stay quiet -------------------

class SeamUser:
    def __init__(self, clock):
        self._clock = clock

    def ok_seam_module(self, ev, timeout):
        clockmod.wait(ev, timeout)               # the seam itself
        return clockmod.monotonic()

    def ok_seam_instance(self, ev, timeout):
        self._clock.wait(ev, timeout)            # injected clock

    def ok_annotated(self):
        t0 = time.time()  # wall-clock: profile file names need real timestamps
        return t0
