"""Framed-transport unit tests (ISSUE 12): wire framing, stream
multiplexing + CANCEL, deadline propagation, connection AUTH, the
scatter pool's hygiene bounds (idle TTL + per-URL cap), hedge-loser
cancellation on the legacy HTTP hop, and the replica-side result
cache's epoch discipline — all in-process and CPU-cheap."""

from __future__ import annotations

import json
import socket
import threading
import time

import pytest

from oryx_tpu.cluster import transport as tr
from oryx_tpu.cluster.membership import Heartbeat, MembershipRegistry
from oryx_tpu.cluster.result_cache import ShardResultCache
from oryx_tpu.cluster.scatter import ScatterGather, _Pool
from oryx_tpu.common.config import from_dict
from oryx_tpu.lambda_rt.http import HttpApp, Route
from oryx_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _config(**extra):
    overlay = {
        "oryx.cluster.transport.enabled": True,
        "oryx.cluster.heartbeat-ttl-ms": 60000,
        "oryx.cluster.hedge-after-ms": 80,
        "oryx.cluster.shard-timeout-ms": 5000,
    }
    overlay.update(extra)
    return from_dict(overlay)


# -- wire framing -------------------------------------------------------------

def test_frame_round_trip_over_socketpair():
    a, b = socket.socketpair()
    try:
        lock = threading.Lock()
        payload = tr._pack_msg({"m": "GET", "p": "/x", "h": {"A": "1"}},
                               b"body-bytes")
        tr.write_frame(a, tr.FRAME_REQ, 7, payload, lock)
        rfile = b.makefile("rb")
        ftype, stream, got = tr.read_frame(rfile)
        assert (ftype, stream) == (tr.FRAME_REQ, 7)
        header, body = tr._unpack_msg(got)
        assert header == {"m": "GET", "p": "/x", "h": {"A": "1"}}
        assert body == b"body-bytes"
        a.close()
        with pytest.raises(ConnectionError):
            tr.read_frame(rfile)
    finally:
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass


def test_oversized_frame_is_rejected_not_buffered():
    a, b = socket.socketpair()
    try:
        a.sendall(tr._HEAD.pack((1 << 30), tr.FRAME_REQ, 1))
        with pytest.raises(ConnectionError):
            tr.read_frame(b.makefile("rb"))
    finally:
        a.close()
        b.close()


def test_heartbeat_tport_round_trips_and_defaults_none():
    hb = Heartbeat(replica="r", shard=0, of=1, url="http://h:1",
                   generation=1, ready=True, tport=4711)
    got = Heartbeat.from_json(hb.to_json())
    assert got.tport == 4711
    # pre-r14 heartbeats carry no tport: parse to None, never KeyError
    legacy = json.dumps({"replica": "r", "shard": 0, "of": 1,
                         "url": "http://h:1", "generation": 1,
                         "ready": True})
    assert Heartbeat.from_json(legacy).tport is None
    assert "tport" not in Heartbeat(
        replica="r", shard=0, of=1, url="u", generation=0,
        ready=False).to_json()


# -- scatter pool hygiene (satellite regression tests) ------------------------

def _sock_pair_entry():
    a, b = socket.socketpair()
    return (a, a.makefile("rb")), b


def test_pool_bounds_per_url_stack():
    pool = _Pool(idle_ttl_sec=60.0, max_per_url=2)
    peers = []
    conns = []
    for _ in range(4):
        conn_rf, peer = _sock_pair_entry()
        peers.append(peer)
        conns.append(conn_rf)
        pool.release("http://r:1", conn_rf)
    # the cap held: only the newest 2 pooled, oldest 2 closed (their
    # peers read EOF; the survivors' peers still see an open socket)
    assert pool.pooled("http://r:1") == 2
    assert pool.cap_evictions == 2
    peers[0].settimeout(2.0)
    assert peers[0].recv(1) == b""  # oldest was shut down
    assert not conns[3][0]._closed
    pool.close()
    for p in peers:
        p.close()


def test_pool_ages_out_idle_sockets_and_drops_dead_urls():
    pool = _Pool(idle_ttl_sec=0.05, max_per_url=8)
    conn_rf, peer = _sock_pair_entry()
    pool.release("http://gone:9", conn_rf)
    time.sleep(0.08)
    # acquire discards the stale socket and falls through to fresh —
    # which we prove by the idle eviction counter and the closed fd
    with pytest.raises(OSError):
        pool.acquire("http://gone:9")  # fresh connect to nowhere
    assert pool.idle_evictions == 1
    peer.settimeout(2.0)
    assert peer.recv(1) == b""  # the idle socket was shut down
    # the sweep reclaims idle sockets of OTHER urls too (long-gone
    # replicas on ephemeral ports) and drops their map keys
    conn2, peer2 = _sock_pair_entry()
    pool.release("http://gone:10", conn2)
    time.sleep(0.08)
    pool._last_sweep = 0.0  # force the time-gated sweep to run now
    conn3, peer3 = _sock_pair_entry()
    pool.release("http://live:1", conn3)
    assert pool.pooled("http://gone:10") == 0
    assert "http://gone:10" not in pool._conns
    assert pool.pooled("http://live:1") == 1
    pool.close()
    for p in (peer, peer2, peer3):
        p.close()


# -- hedge-loser cancellation on the legacy HTTP hop --------------------------

class _StubReplica:
    """Minimal keep-alive HTTP replica with a controllable delay."""

    def __init__(self, delay_sec=0.0, body=b'{"rows": []}'):
        self.delay_sec = delay_sec
        self.body = body
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(16)
        self.port = self.sock.getsockname()[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self.aborted_reads = 0
        self._stop = False
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        rfile = conn.makefile("rb")
        try:
            while True:
                line = rfile.readline()
                if not line:
                    return
                while rfile.readline() not in (b"\r\n", b"\n", b""):
                    pass
                if self.delay_sec:
                    time.sleep(self.delay_sec)
                try:
                    conn.sendall(
                        b"HTTP/1.1 200 OK\r\nContent-Length: "
                        + str(len(self.body)).encode() + b"\r\n\r\n"
                        + self.body)
                except OSError:
                    self.aborted_reads += 1
                    return
        except OSError:
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass


def test_hedge_loser_socket_is_discarded_not_pooled():
    """The satellite's regression: when a hedge sibling wins, the
    loser's in-flight socket is torn down NOW (counted in
    hedge_abandoned) — it must never return to the keep-alive pool
    where its unread response bytes would desync the next request."""
    slow = _StubReplica(delay_sec=2.0)
    fast = _StubReplica(delay_sec=0.0)
    reg = MembershipRegistry(ttl_sec=60.0)
    reg.note(Heartbeat(replica="slow", shard=0, of=1, url=slow.url,
                       generation=1, ready=True))
    reg.note(Heartbeat(replica="fast", shard=0, of=1, url=fast.url,
                       generation=1, ready=True))
    sg = ScatterGather(reg, _config(
        **{"oryx.cluster.transport.enabled": False,
           "oryx.cluster.hedge-after-ms": 60}))
    try:
        # the registry rotates candidate order per query: within a few
        # queries the slow member leads at least once, forcing the
        # hedge whose fast sibling wins
        for _ in range(3):
            assert sg.query_shard(0, "GET", "/x").ok
        assert sg.hedges >= 1
        deadline = time.monotonic() + 5.0
        while sg.hedge_abandoned < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sg.hedge_abandoned >= 1
        # the loser's socket did NOT go back to the pool
        assert sg._pool.pooled(slow.url) == 0
        assert sg._pool.pooled(fast.url) == 1
    finally:
        sg.close()
        slow.close()
        fast.close()


def test_shard_timeout_fault_abandons_inflight_attempts():
    """``router-shard-timeout`` (mode=delay past the deadline) on a
    single-replica shard: the query gives up at the deadline AND the
    stalled attempt's socket is cancelled — counted, never pooled."""
    slow = _StubReplica(delay_sec=3.0)
    sibling = _StubReplica(delay_sec=3.0)
    reg = MembershipRegistry(ttl_sec=60.0)
    reg.note(Heartbeat(replica="a", shard=0, of=1, url=slow.url,
                       generation=1, ready=True))
    reg.note(Heartbeat(replica="b", shard=0, of=1, url=sibling.url,
                       generation=1, ready=True))
    sg = ScatterGather(reg, _config(
        **{"oryx.cluster.transport.enabled": False,
           "oryx.cluster.hedge-after-ms": 40}))
    from oryx_tpu.cluster.scatter import ShardUnavailable
    from oryx_tpu.resilience.policy import Deadline
    faults.inject("router-shard-timeout", mode="delay", times=1,
                  delay_sec=0.2)
    try:
        with pytest.raises(ShardUnavailable):
            sg.query_shard(0, "GET", "/x",
                           deadline=Deadline.after(0.6))
        assert faults.fired("router-shard-timeout") == 1
        deadline = time.monotonic() + 5.0
        while sg.hedge_abandoned < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        # both stalled attempts were abandoned at give-up: the pool
        # holds neither of their mid-response sockets
        assert sg.hedge_abandoned >= 2
        assert sg._pool.pooled(slow.url) == 0
        assert sg._pool.pooled(sibling.url) == 0
    finally:
        sg.close()
        slow.close()
        sibling.close()


# -- frame client <-> server loopback ----------------------------------------

def _echo_app(user=None, password=None):
    import time as _time

    def _echo(req):
        return {"path": req.path, "body": req.body.decode(),
                "deadline_ms": None if req.deadline is None
                else int(req.deadline.remaining() * 1000)}

    def _slow(req):
        _time.sleep(float(req.q1("sec", "0.5")))
        return {"slow": True}

    routes = [Route("POST", "/shard/echo", _echo),
              Route("GET", "/shard/slow", _slow),
              Route("GET", "/shard/meta", lambda req: {"meta": True})]
    return HttpApp(routes, context={}, user_name=user, password=password)


def _hb_for(server, url="http://127.0.0.1:1"):
    return Heartbeat(replica="r", shard=0, of=1,
                     url=f"http://127.0.0.1:{server.port}",
                     generation=1, ready=True, tport=server.port)


def test_framed_request_answers_through_the_app_dispatcher():
    app = _echo_app()
    server = tr.FrameServer(app, _config())
    server.start()
    client = tr.FrameTransport(_config())
    try:
        status, raw, _ = client.request(
            _hb_for(server), "POST", "/shard/echo", b"hello",
            {"X-Deadline-Ms": "2500"}, timeout=5.0)
        assert status == 200
        out = json.loads(raw)
        assert out["path"] == "/shard/echo"
        assert out["body"] == "hello"
        # deadline propagated: the handler saw a live remaining budget
        assert 0 < out["deadline_ms"] <= 2500
        assert client.open_connections() == 1
    finally:
        client.close()
        server.close()


def test_streams_multiplex_one_connection_and_do_not_holb():
    """Two interleaved streams on ONE connection: the slow one must
    not block the fast one (per-stream dispatch, completion-order
    responses)."""
    app = _echo_app()
    server = tr.FrameServer(app, _config())
    server.start()
    client = tr.FrameTransport(_config())
    try:
        hb = _hb_for(server)
        results = {}

        def call(name, path, method="GET", body=b""):
            t0 = time.monotonic()
            status, raw, _ = client.request(hb, method, path, body,
                                            {}, timeout=10.0)
            results[name] = (status, time.monotonic() - t0)

        slow_t = threading.Thread(
            target=call, args=("slow", "/shard/slow?sec=0.8"))
        slow_t.start()
        time.sleep(0.1)  # the slow stream is in flight on the conn
        call("fast", "/shard/echo", method="POST", body=b"x")
        slow_t.join(5.0)
        assert results["fast"][0] == 200
        assert results["slow"][0] == 200
        assert results["fast"][1] < 0.5  # never waited out the slow one
        assert client.open_connections() == 1  # ONE socket carried both
    finally:
        client.close()
        server.close()


def test_stream_timeout_sends_cancel_and_replica_drops_the_answer():
    app = _echo_app()
    server = tr.FrameServer(app, _config())
    server.start()
    client = tr.FrameTransport(_config())
    try:
        hb = _hb_for(server)
        with pytest.raises(TimeoutError):
            client.request(hb, "GET", "/shard/slow?sec=1.0", b"", {},
                           timeout=0.15)
        assert client.cancels_sent == 1
        # the replica saw the CANCEL and dropped the stream's answer
        deadline = time.monotonic() + 5.0
        while server.cancelled_streams < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert server.cancelled_streams >= 1
        # the connection survived the cancellation: next request flows
        status, _, _ = client.request(hb, "POST", "/shard/echo", b"y",
                                      {}, timeout=5.0)
        assert status == 200
        assert client.open_connections() == 1
    finally:
        client.close()
        server.close()


def test_replica_restart_retries_once_on_fresh_connection():
    app = _echo_app()
    server = tr.FrameServer(app, _config())
    server.start()
    port = server.port
    client = tr.FrameTransport(_config())
    try:
        hb = _hb_for(server)
        assert client.request(hb, "POST", "/shard/echo", b"1", {},
                              timeout=5.0)[0] == 200
        server.close()  # the replica restarts (supervised event)
        for _ in range(50):
            try:
                server = tr.FrameServer(_echo_app(), _config(),
                                        port=port)
                break
            except OSError:
                time.sleep(0.1)  # old conns draining off the port
        server.start()
        # the cached connection is dead: one internal retry, no error
        assert client.request(hb, "POST", "/shard/echo", b"2", {},
                              timeout=5.0)[0] == 200
    finally:
        client.close()
        server.close()


def test_auth_frame_gates_the_connection():
    app = _echo_app(user="oryx-admin", password="s3cret")
    server = tr.FrameServer(app, _config(
        **{"oryx.serving.api.user-name": "oryx-admin",
           "oryx.serving.api.password": "s3cret"}))
    server.start()
    good = tr.FrameTransport(_config(
        **{"oryx.serving.api.user-name": "oryx-admin",
           "oryx.serving.api.password": "s3cret"}))
    bad = tr.FrameTransport(_config(
        **{"oryx.serving.api.user-name": "oryx-admin",
           "oryx.serving.api.password": "wrong"}))
    try:
        hb = _hb_for(server)
        assert good.request(hb, "POST", "/shard/echo", b"ok", {},
                            timeout=5.0)[0] == 200
        with pytest.raises((ConnectionError, TimeoutError)):
            bad.request(hb, "POST", "/shard/echo", b"no", {},
                        timeout=2.0)
    finally:
        good.close()
        bad.close()
        server.close()


def test_frame_stall_chaos_stalls_one_stream_only():
    """``transport-frame-stall``: the armed stream's answer stalls;
    a second stream on the SAME connection is unaffected."""
    app = _echo_app()
    server = tr.FrameServer(app, _config())
    server.start()
    client = tr.FrameTransport(_config())
    faults.inject("transport-frame-stall", mode="delay", times=1,
                  delay_sec=1.0)
    try:
        hb = _hb_for(server)
        results = {}

        def call(name):
            t0 = time.monotonic()
            status, _, _ = client.request(hb, "POST", "/shard/echo",
                                          name.encode(), {},
                                          timeout=10.0)
            results[name] = (status, time.monotonic() - t0)

        stalled_t = threading.Thread(target=call, args=("stalled",))
        stalled_t.start()
        time.sleep(0.15)  # the armed stream consumed the fault
        call("bystander")
        stalled_t.join(5.0)
        assert faults.fired("transport-frame-stall") == 1
        assert results["bystander"][0] == 200
        assert results["bystander"][1] < 0.5  # unaffected by the stall
        assert results["stalled"][0] == 200
        assert results["stalled"][1] >= 0.9  # it really did stall
    finally:
        client.close()
        server.close()


# -- replica-side result cache ------------------------------------------------

def _cache_config(**extra):
    overlay = {"oryx.cluster.replica-cache.enabled": True,
               "oryx.cluster.replica-cache.quarantine-ms": 0}
    overlay.update(extra)
    return from_dict(overlay)


def test_shard_cache_serves_under_unchanged_epoch_only():
    cache = ShardResultCache(_cache_config())
    assert cache.lookup("POST", "/shard/query", b"q1") is None
    cache.store("POST", "/shard/query", b"q1", cache.epoch(), 200,
                {"x": "1"}, b"answer")
    assert cache.lookup("POST", "/shard/query", b"q1") == \
        (200, {"x": "1"}, b"answer")
    # ANY applied update record moves the epoch: the entry stops
    # serving instantly (exact by construction)
    cache.note_record()
    assert cache.lookup("POST", "/shard/query", b"q1") is None
    st = cache.stats()
    assert st["hits"] == 1 and st["misses"] == 2
    assert st["entries"] == 0  # the stale entry was reclaimed on touch


def test_shard_cache_refuses_stale_epoch_and_quarantined_stores():
    cache = ShardResultCache(_cache_config(
        **{"oryx.cluster.replica-cache.quarantine-ms": 100000}))
    e0 = cache.epoch()
    cache.note_record()
    # epoch moved during the request: refused
    cache.store("GET", "/shard/p", b"", e0, 200, {}, b"x")
    # within the quarantine after the bump: refused too
    cache.store("GET", "/shard/p", b"", cache.epoch(), 200, {}, b"x")
    assert cache.stats()["entries"] == 0
    assert cache.stats()["store_rejects"] == 2


def test_shard_cache_bounds_entries_and_bytes():
    cache = ShardResultCache(_cache_config(
        **{"oryx.cluster.replica-cache.max-entries": 2}))
    for i in range(4):
        cache.store("GET", f"/shard/p{i}", b"", cache.epoch(), 200,
                    {}, b"v")
    st = cache.stats()
    assert st["entries"] == 2 and st["evictions"] == 2
    assert cache.lookup("GET", "/shard/p3", b"") is not None
    assert cache.lookup("GET", "/shard/p0", b"") is None
    # non-200s are never stored
    cache.store("GET", "/shard/err", b"", cache.epoch(), 404, {}, b"e")
    assert cache.lookup("GET", "/shard/err", b"") is None


def test_shard_cache_tap_bumps_before_and_after_each_apply():
    """Pre-yield AND post-yield bumps: the post-apply fence retires
    anything a mid-apply request stored, no matter how long the apply
    ran (a sliced model load takes seconds — no fixed quarantine can
    cover it)."""
    cache = ShardResultCache(_cache_config())
    e0 = cache.epoch()
    tap = cache.tap(iter(["a", "b"]))
    assert next(tap) == "a"
    assert cache.epoch() == e0 + 1  # pre-apply fence
    # mid-apply store lands under the in-between epoch ...
    cache.store("GET", "/shard/mid", b"", cache.epoch(), 200, {}, b"x")
    assert cache.lookup("GET", "/shard/mid", b"") is not None
    assert next(tap) == "b"  # asking for the next record = apply done
    # ... and the post-apply bump retired it
    assert cache.epoch() == e0 + 3
    assert cache.lookup("GET", "/shard/mid", b"") is None
    assert list(tap) == []
    assert cache.epoch() == e0 + 4
