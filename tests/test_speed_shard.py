"""Crash-safe sharded speed layer (ISSUE 17 tentpole).

Covers, deterministically and at unit scale, what the sim sweeps prove
statistically (tests/test_sim_sweep.py, speed-shard-crash):

- the SpeedCheckpoint single-document fence: stage → publish → commit,
  atomic save, tolerant load, batch ids that survive restarts;
- recover_pending: the destination log is the arbiter — found staged
  sequences dedup, missing ones republish BYTE-EXACTLY from the staged
  intent, never re-derived against a model the consume thread already
  moved;
- the chaos point itself (``speed-crash-mid-batch``): a kill between
  the UP publishes and the checkpoint commit replays the batch but
  folds nothing twice — the update topic after crash + recovery is
  byte-identical to an uncrashed control run's;
- the close()/micro-batch race regression: close interrupts the poll
  wait promptly and joins the batch thread BEFORE tearing down the
  model manager;
- ring-sharded fold-in: two workers over the same input fold disjoint
  item slices that cover every event exactly once.
"""

import json
import threading
import time

import numpy as np
import pytest

from oryx_tpu.cluster.sharding import is_local_item
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_UP
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.batch import BatchLayer
from oryx_tpu.lambda_rt.speed import SpeedLayer
from oryx_tpu.lambda_rt.speed_checkpoint import (
    H_SPEED_BATCH, H_SPEED_SEQ, H_SPEED_SHARD, SpeedCheckpoint,
    recover_pending, stamp_headers)
from oryx_tpu.resilience import faults

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _base_config(tmp_path, broker_name, **extra):
    overlay = {
        "oryx.id": "it",
        "oryx.input-topic.broker": f"memory://{broker_name}",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "ItInput",
        "oryx.update-topic.broker": f"memory://{broker_name}",
        "oryx.update-topic.message.topic": "ItUpdate",
        "oryx.batch.update-class": "oryx_tpu.app.als.update.ALSUpdate",
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.als.speed.ALSSpeedModelManager",
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.als.iterations": 3,
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": 3,
        "oryx.ml.eval.test-fraction": 0.0,
        "oryx.resilience.retry.max-attempts": 2,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
    }
    overlay.update(extra)
    return from_dict(overlay)


def _produce_ratings(broker, topic, nu=20, ni=12, seed=5):
    rng = np.random.default_rng(seed)
    t = 1_700_000_000_000
    n = 0
    for u in range(nu):
        for i in range(ni):
            if rng.random() < 0.4:
                broker.send(topic, None,
                            f"u{u},i{i},{rng.exponential(1):.2f},{t}")
                t += 1000
                n += 1
    return n


def _replay_into(manager, broker, topic="ItUpdate"):
    manager.consume(broker.consume(topic, from_beginning=True,
                                   max_idle_sec=0.3))


def _up_records(broker, topic="ItUpdate"):
    end = broker.latest_offset(topic)
    return [km for km in broker.read_range(topic, 0, end)
            if km.key == KEY_UP]


# -- the single-document fence -----------------------------------------------

def test_checkpoint_roundtrips_one_atomic_document(tmp_path):
    ck = SpeedCheckpoint(str(tmp_path / "ck"))
    assert ck.input == {} and ck.pending is None and ck.next_batch == 0

    batch = ck.stage_batch([5], ["ua", "ub"], {"ts": "123"})
    assert batch == 0
    # staging is durable BEFORE any publish: a reload sees the intent
    ck2 = SpeedCheckpoint(str(tmp_path / "ck"))
    assert ck2.pending == {"batch": 0, "ends": [5],
                          "headers": {"ts": "123"},
                          "updates": ["ua", "ub"]}
    assert ck2.next_batch == 0  # the id is consumed only by the commit

    ck2.commit_batch([5], dest_ends=[9])
    ck3 = SpeedCheckpoint(str(tmp_path / "ck"))
    assert ck3.pending is None
    assert ck3.input == {0: 5}
    assert ck3.dest_scanned == {0: 9}
    assert ck3.next_batch == 1  # survives restart: ids never collide


def test_checkpoint_unreadable_document_restarts_clean(tmp_path):
    ck = SpeedCheckpoint(str(tmp_path / "ck"))
    ck.commit_batch([3])
    with open(ck.path, "wb") as f:
        f.write(b"{not json")
    ck2 = SpeedCheckpoint(str(tmp_path / "ck"))
    # tolerant load: restart from group offsets, no pending batch —
    # at-least-once, never a crash loop on a torn disk
    assert ck2.input == {} and ck2.pending is None


def test_commit_never_rewinds_dest_scan_mark(tmp_path):
    ck = SpeedCheckpoint(str(tmp_path / "ck"))
    ck.commit_batch([1], dest_ends=[10])
    ck.commit_batch([2], dest_ends=[7])   # stale read of the head
    assert ck.dest_scanned == {0: 10}
    ck.commit_batch([3], dest_ends=[None])  # unknown head: keep mark
    assert ck.dest_scanned == {0: 10}


# -- recovery: the destination log is the arbiter ----------------------------

class _Rec:
    def __init__(self, headers):
        self.headers = headers


def test_recover_republishes_only_missing_seqs_byte_exactly(tmp_path):
    ck = SpeedCheckpoint(str(tmp_path / "ck"))
    batch = ck.stage_batch([7], ["ua", "ub", "uc"], {"ts": "1"})
    # the crash landed mid-publish: seqs 0 and 2 made it durable.  A
    # foreign shard's record and a foreign batch must not count.
    dest = [_Rec(stamp_headers({}, "0/2", batch, 0)),
            _Rec(stamp_headers({}, "1/2", batch, 1)),
            _Rec(stamp_headers({}, "0/2", batch + 9, 1)),
            _Rec(stamp_headers({}, "0/2", batch, 2)),
            _Rec({}), _Rec(None)]
    sent = []
    republished, deduped = recover_pending(
        ck, "0/2", lambda starts, ends: dest, [len(dest)],
        lambda msg, h: sent.append((msg, h)))
    assert (republished, deduped) == (1, 2)
    # the missing seq re-sends the STAGED bytes under its original
    # identity — byte-exact, not re-derived
    assert sent == [("ub", stamp_headers({"ts": "1"}, "0/2", batch, 1))]
    assert ck.pending is None
    assert ck.input == {0: 7}
    assert ck.next_batch == batch + 1
    assert ck.dest_scanned == {0: len(dest)}


def test_recover_is_a_noop_without_a_staged_batch(tmp_path):
    ck = SpeedCheckpoint(str(tmp_path / "ck"))
    ck.commit_batch([4])
    called = []
    assert recover_pending(ck, "0/1", lambda s, e: called.append(1),
                           [0], lambda m, h: called.append(1)) == (0, 0)
    assert not called
    assert ck.input == {0: 4}


def test_recovery_scan_is_incremental_from_dest_scanned(tmp_path):
    ck = SpeedCheckpoint(str(tmp_path / "ck"))
    ck.commit_batch([1], dest_ends=[40])
    ck.stage_batch([2], ["u"], {})
    seen = []

    def read_dest(starts, ends):
        seen.append((starts, ends))
        return []

    recover_pending(ck, "0/1", read_dest, [55], lambda m, h: None)
    assert seen == [([40], [55])]


# -- the chaos point: crash between publish and commit -----------------------

def _copy_topic(src, dst, topic):
    for km in src.read_range(topic, 0, src.latest_offset(topic)):
        dst.send(topic, km.key, km.message, headers=km.headers)


def test_crash_mid_batch_replays_dedup_not_double_fold(tmp_path):
    """Kill the worker at ``speed-crash-mid-batch`` (UP publishes
    durable, checkpoint commit lost); restart.  Recovery must dedup
    every staged record against the destination log — zero new
    publishes — and the final update topic and folded factors must be
    byte-identical to an uncrashed control run over the same model
    and input."""
    cfg = _base_config(tmp_path, "spdcrash", **{
        "oryx.speed.shard": "0/1",
        "oryx.speed.checkpoint-dir": str(tmp_path / "speed-ckpt")})
    broker = get_broker("spdcrash")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()

    # control universe: same trained model (records copied byte-wise,
    # artifacts shared on disk), same input, no crash
    ctl_cfg = _base_config(tmp_path, "spdctl", **{
        "oryx.speed.shard": "0/1",
        "oryx.speed.checkpoint-dir": str(tmp_path / "ctl-ckpt")})
    ctl_broker = get_broker("spdctl")
    ctl_broker.create_topic("ItInput", partitions=1)
    ctl_broker.create_topic("ItUpdate", partitions=1)
    _copy_topic(broker, ctl_broker, "ItInput")
    _copy_topic(broker, ctl_broker, "ItUpdate")

    new_lines = ["u0,i1,3.0,1800000000000",
                 "newuser,i2,1.0,1800000000001",
                 "u3,i5,2.0,1800000000002"]
    for line in new_lines:
        broker.send("ItInput", None, line)
        ctl_broker.send("ItInput", None, line)

    ctl = SpeedLayer(ctl_cfg)
    _replay_into(ctl.model_manager, ctl_broker)
    ctl.run_one_micro_batch()

    speed1 = SpeedLayer(cfg)
    _replay_into(speed1.model_manager, broker)
    up_before = len(_up_records(broker))
    faults.inject("speed-crash-mid-batch", mode="crash", times=1)
    with pytest.raises(faults.InjectedCrash):
        speed1.run_one_micro_batch()

    # the dangerous intermediate state: every UP of the batch is
    # durable, the input fence is NOT advanced, the intent is staged
    staged = SpeedCheckpoint(str(tmp_path / "speed-ckpt" /
                                 "shard-0-of-1"))
    assert staged.pending is not None
    n_staged = len(staged.pending["updates"])
    assert n_staged > 0
    up_mid = _up_records(broker)
    assert len(up_mid) == up_before + n_staged

    # restart: a fresh incarnation resolves the stage before anything
    # else — all staged records found durable, NOTHING republished
    speed2 = SpeedLayer(cfg)
    speed2.run_one_micro_batch()
    assert speed2.checkpoint.pending is None
    assert speed2.dedup_skips == n_staged
    assert speed2.metrics.counters_snapshot()[
        "speed_shard_dedup_skips"] == n_staged
    up_after = _up_records(broker)
    assert len(up_after) == len(up_mid), \
        "recovery republished records that were already durable"
    # the fence committed: input offsets advanced past the batch
    assert speed2.checkpoint.input == \
        {0: broker.latest_offset("ItInput")}
    assert broker.get_offsets(speed2._group, "ItInput") == \
        [broker.latest_offset("ItInput")]

    # byte-exactness vs the uncrashed control: same UP payloads in the
    # same order, and byte-identical folded factors from full replay
    ctl_ups = _up_records(ctl_broker)
    assert [km.message for km in up_after] == \
        [km.message for km in ctl_ups]

    probe = SpeedLayer(_base_config(tmp_path, "spdcrash"))
    _replay_into(probe.model_manager, broker)
    ctl_probe = SpeedLayer(_base_config(tmp_path, "spdctl"))
    _replay_into(ctl_probe.model_manager, ctl_broker)
    got, ref = probe.model_manager.model, ctl_probe.model_manager.model
    assert sorted(got.X.all_ids()) == sorted(ref.X.all_ids())
    assert sorted(got.Y.all_ids()) == sorted(ref.Y.all_ids())
    for uid in ref.X.all_ids():
        assert np.array_equal(got.get_user_vector(uid),
                              ref.get_user_vector(uid))
    for iid in ref.Y.all_ids():
        assert np.array_equal(got.get_item_vector(iid),
                              ref.get_item_vector(iid))


def test_crash_before_first_commit_resumes_from_pinned_fence(tmp_path):
    """A worker killed before its FIRST micro-batch commit has no fence
    yet; a restart that re-tails the (moved) head would silently skip
    every record accepted in between.  ``_init_pos`` must pin the
    initial tail position durably, so the restart resumes from the pin
    and folds exactly the missed records."""
    cfg = _base_config(tmp_path, "spdpin", **{
        "oryx.speed.shard": "0/1",
        "oryx.speed.checkpoint-dir": str(tmp_path / "pin-ckpt")})
    broker = get_broker("spdpin")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()
    head0 = broker.latest_offset("ItInput")

    first = SpeedLayer(cfg)
    _replay_into(first.model_manager, broker)
    assert first._init_pos(broker) == [head0]
    # the tail position is durable BEFORE any micro-batch commits, and
    # mirrored into the group so the input-lag gauge counts from it
    assert first.checkpoint.input == {0: head0}
    assert broker.get_offsets(first._group, "ItInput") == [head0]

    # SIGKILL here: first never committed a batch.  Records keep
    # landing while the shard is down.
    up_before = len(_up_records(broker))
    new_lines = ["u0,i1,3.0,1800000000000",
                 "newuser,i2,1.0,1800000000001",
                 "u3,i5,2.0,1800000000002"]
    for line in new_lines:
        broker.send("ItInput", None, line)

    second = SpeedLayer(cfg)
    _replay_into(second.model_manager, broker)
    assert second._init_pos(broker) == [head0], \
        "restart re-tailed the moved head, skipping durable records"
    second.run_one_micro_batch()
    # exactly the missed records folded — none skipped, none doubled
    assert second.metrics.gauge_value("micro_batch_records") == \
        len(new_lines)
    assert second.checkpoint.input == {0: broker.latest_offset("ItInput")}
    assert len(_up_records(broker)) > up_before


def test_publish_failure_mid_batch_finishes_from_staged_bytes(tmp_path):
    """An exhausted publish failure leaves the batch staged; the next
    interval must finish it by republishing the STAGED bytes — never
    re-deriving under the same batch id (the model has moved)."""
    cfg = _base_config(tmp_path, "spdfail", **{
        "oryx.speed.checkpoint-dir": str(tmp_path / "speed-ckpt")})
    broker = get_broker("spdfail")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()

    speed = SpeedLayer(cfg)
    _replay_into(speed.model_manager, broker)
    broker.send("ItInput", None, "u1,i2,2.0,1800000000000")
    up_before = len(_up_records(broker))

    # the fault fires BEFORE the first send: intent staged, zero
    # records durable — the all-missing recovery case
    faults.inject("speed-publish", mode="error", times=1)
    with pytest.raises(faults.InjectedFault):
        speed.run_one_micro_batch()
    assert speed.checkpoint.pending is not None
    staged_updates = list(speed.checkpoint.pending["updates"])
    assert len(_up_records(broker)) == up_before

    # next micro-batch resolves the stage first: every staged record
    # republished byte-exactly, stamped with the original batch id
    speed.run_one_micro_batch()
    assert speed.checkpoint.pending is None
    tail = _up_records(broker)[up_before:]
    assert [km.message for km in tail] == staged_updates
    seqs = [(km.headers[H_SPEED_SHARD], int(km.headers[H_SPEED_BATCH]),
             int(km.headers[H_SPEED_SEQ])) for km in tail]
    assert seqs == [("0/1", 0, s) for s in range(len(staged_updates))]
    assert speed.dedup_skips == 0  # nothing was durable: all republish


# -- close()/micro-batch race (regression) -----------------------------------

class BlockingSpeedManager:
    """Stub manager whose build_updates blocks until released — makes
    the close()-during-micro-batch window as wide as the test needs."""

    last: "BlockingSpeedManager | None" = None

    def __init__(self, config):
        self.in_build = threading.Event()
        self.release = threading.Event()
        self.building = False
        self.closed = False
        self.closed_while_building = False
        BlockingSpeedManager.last = self

    def consume(self, updates):
        for _ in updates:
            pass

    def build_updates(self, new_data):
        self.building = True
        self.in_build.set()
        self.release.wait(15.0)
        self.building = False
        return ["stub-update"]

    def close(self):
        self.closed = True
        self.closed_while_building = self.building


def test_close_joins_inflight_micro_batch_before_teardown(tmp_path):
    cfg = _base_config(tmp_path, "closerace", **{
        "oryx.speed.model-manager-class":
            "tests.test_speed_shard.BlockingSpeedManager",
        "oryx.speed.streaming.generation-interval-sec": 1})
    broker = get_broker("closerace")
    broker.send("ItInput", None, "u0,i0,1.0,1800000000000")
    broker.set_offsets("OryxGroup-SpeedLayer-it", "ItInput", [0])
    speed = SpeedLayer(cfg)
    speed.start()
    try:
        mgr = BlockingSpeedManager.last
        assert mgr.in_build.wait(10.0), "micro-batch never started"
        closer = threading.Thread(target=speed.close)
        closer.start()
        time.sleep(0.25)
        # the regression: close() used to tear the manager down while
        # the batch thread was still inside build_updates
        assert not mgr.closed, \
            "close() tore down the manager mid-micro-batch"
        mgr.release.set()
        closer.join(15.0)
        assert not closer.is_alive()
        assert mgr.closed
        assert not mgr.closed_while_building
    finally:
        BlockingSpeedManager.last.release.set()


def test_close_interrupts_long_poll_wait_promptly(tmp_path):
    cfg = _base_config(tmp_path, "closewait", **{
        "oryx.speed.model-manager-class":
            "tests.test_speed_shard.BlockingSpeedManager",
        "oryx.speed.streaming.generation-interval-sec": 300})
    get_broker("closewait")
    speed = SpeedLayer(cfg)
    speed.start()
    time.sleep(0.3)  # let the batch thread enter its 300 s poll wait
    t0 = time.monotonic()
    speed.close()
    took = time.monotonic() - t0
    assert took < 5.0, (
        f"close() took {took:.1f}s against a 300 s poll interval — "
        f"the wait is not going through the interruptible clock seam")
    assert not speed._batch_thread.is_alive()
    assert BlockingSpeedManager.last.closed


# -- ring-sharded fold-in ----------------------------------------------------

def test_two_shards_fold_disjoint_item_slices_covering_all(tmp_path):
    cfg = _base_config(tmp_path, "shardsplit")
    broker = get_broker("shardsplit")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()

    new_lines = [f"u{u},i{i},1.5,{1_800_000_000_000 + u * 13 + i}"
                 for u in range(4) for i in range(6)]
    for line in new_lines:
        broker.send("ItInput", None, line)

    workers = []
    for s in range(2):
        wcfg = _base_config(tmp_path, "shardsplit", **{
            "oryx.speed.shard": f"{s}/2",
            "oryx.speed.checkpoint-dir":
                str(tmp_path / "shard-ckpt")})
        w = SpeedLayer(wcfg)
        assert w._group.endswith(f"-{s}x2")  # group per worker
        _replay_into(w.model_manager, broker)
        workers.append(w)

    up_before = len(_up_records(broker))
    for w in workers:
        w.run_one_micro_batch()
    ups = _up_records(broker)[up_before:]
    assert ups, "no shard folded anything"

    # every published delta is stamped by its worker, and every item
    # delta belongs to the stamping worker's ring slice
    by_shard: dict[str, set] = {"0/2": set(), "1/2": set()}
    for km in ups:
        tag = km.headers[H_SPEED_SHARD]
        kind, id_ = json.loads(km.message)[:2]
        if kind == "Y":
            by_shard[tag].add(id_)
            shard = int(tag.split("/")[0])
            assert is_local_item(id_, shard, 2), \
                f"shard {tag} published remote item {id_}"
    assert by_shard["0/2"] and by_shard["1/2"]
    assert not (by_shard["0/2"] & by_shard["1/2"])

    # the split is exhaustive: both workers read the FULL input (the
    # whole topic from 0 — history plus the new lines), and each event
    # is remote to exactly one of the two, so the two skip counts sum
    # to the total event count
    total_events = broker.latest_offset("ItInput")
    skipped = [w.model_manager.skipped_remote_events for w in workers]
    assert sum(skipped) == total_events
    assert all(0 < s < total_events for s in skipped)
