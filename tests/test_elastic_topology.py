"""Elastic-topology unit + property tests (ISSUE 6): the membership
registry's topology state machine (warming → atomic cutover →
retired), stale-heartbeat rejection, ring-filtered replay properties
for live N→M resharding, R-way replica-group exactness, and the
router's measured-queue-wait admission control.

The reshard property tests reuse the test_cluster_merge oracle
harness: old- and new-topology managers consume the IDENTICAL
simulated update-topic stream (exactly how a warming replica replays
through the murmur2 ring), and exactness claims are checked against
both the single full-catalog node and the independent numpy oracle.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.cluster.admission import AdmissionController
from oryx_tpu.cluster.membership import Heartbeat, MembershipRegistry
from oryx_tpu.cluster.merge import exact_local_top_n, merge_top_n
from oryx_tpu.common.config import from_dict
from oryx_tpu.lambda_rt.metrics import MetricsRegistry
from oryx_tpu.obs.prom import LATENCY_BUCKETS_MS, bucket_quantile
from tests.test_cluster_merge import (_manager, _oracle_top_n,
                                      _random_replay)

_NO_ORD = 1 << 62


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _hb(replica, shard, of=2, gen=1, ready=True, fraction=1.0):
    return Heartbeat(replica=replica, shard=shard, of=of,
                     url=f"http://h{replica}:80", generation=gen,
                     ready=ready, fraction=fraction)


def _merged_two(clock=None) -> MembershipRegistry:
    reg = MembershipRegistry(ttl_sec=10.0, clock=clock or _Clock())
    reg.note(_hb("a", 0))
    reg.note(_hb("b", 1))
    assert reg.shard_count == 2  # bootstrap commit on full coverage
    return reg


# -- topology state machine ---------------------------------------------------

def test_misconfigured_heartbeats_rejected_with_counter():
    reg = _merged_two()
    # structurally invalid shard coordinates
    assert reg.note(_hb("bad", 5, of=2)) is False
    assert reg.note(_hb("bad2", 0, of=0)) is False
    # an undeclared foreign topology while the merged fleet is alive:
    # the wrong-ring replica is dropped, counted, and never routed
    assert reg.note(_hb("rogue", 0, of=3)) is False
    assert reg.stale_topology_heartbeats == 3
    assert "rogue" not in reg.snapshot()["replicas"]
    assert reg.shard_count == 2


def test_rogue_full_replica_cannot_yank_topology():
    """A lone 0/1 replica is trivially 'fully covered' by itself; it
    must not pull the routed topology down to 1 (which would serve the
    whole catalog from one node and double-count against nothing)."""
    reg = _merged_two()
    assert reg.note(_hb("full", 0, of=1)) is False
    for _ in range(3):
        assert reg.shard_count == 2
    assert reg.stale_topology_heartbeats == 1


def test_declared_reshard_waits_for_full_coverage_then_cuts_over():
    reg = _merged_two()
    reg.begin_reshard(3)
    # new-topology replicas warm: accepted, tracked, never routed
    assert reg.note(_hb("n0", 0, of=3))
    assert reg.note(_hb("n1", 1, of=3))
    assert reg.note(_hb("n2", 2, of=3, ready=False, fraction=0.4))
    assert reg.shard_count == 2
    assert [h.of for h in reg.candidates(0)] == [2]
    status = reg.topology_status()
    assert status["merged_of"] == 2
    assert status["reshard_target"] == 3
    t3 = status["topologies"]["3"]
    assert t3["state"] == "warming" and not t3["full_coverage"]
    assert t3["ready_shards"] == 2 and t3["min_fraction"] == 0.4
    # the moment the last warming shard turns ready: atomic cutover
    assert reg.note(_hb("n2", 2, of=3, ready=True))
    assert reg.shard_count == 3
    assert all(h.of == 3 for h in reg.any_candidates())
    assert reg.covered_shards() == [0, 1, 2]
    assert reg.topology_cutovers == 1
    # the old fleet is retired: purged at cutover, and its continuing
    # heartbeats drop with the stale counter — never merged again
    assert all(r["of"] == 3
               for r in reg.snapshot()["replicas"].values())
    before = reg.stale_topology_heartbeats
    assert reg.note(_hb("a", 0, of=2)) is False
    assert reg.stale_topology_heartbeats == before + 1


def test_redeclaring_a_retired_topology_unretires_it():
    reg = _merged_two()
    reg.begin_reshard(3)
    for s in range(3):
        reg.note(_hb(f"n{s}", s, of=3))
    assert reg.shard_count == 3
    # scale back down: 2 was retired at cutover, re-declare it
    reg.begin_reshard(2)
    assert reg.note(_hb("m0", 0, of=2))
    assert reg.note(_hb("m1", 1, of=2))
    assert reg.shard_count == 2
    assert reg.topology_cutovers == 2
    assert all(h.of == 2 for h in reg.any_candidates())


def test_declaring_merged_topology_cancels_target():
    reg = _merged_two()
    reg.begin_reshard(3)
    assert reg.topology_status()["reshard_target"] == 3
    reg.begin_reshard(2)
    assert reg.topology_status()["reshard_target"] is None
    # and the would-be warming heartbeat now drops
    assert reg.note(_hb("n0", 0, of=3)) is False


def test_heartbeat_blip_does_not_let_rogue_topology_take_over():
    """A transient full-TTL gap in the merged fleet's heartbeats (a
    broker stall, a GC pause) must NOT open the bootstrap hatch: a
    lone 0/1 replica beating through the blip would otherwise commit
    its ring and permanently retire the real fleet."""
    clock = _Clock()
    reg = MembershipRegistry(ttl_sec=1.0, clock=clock)
    reg.note(_hb("a", 0))
    reg.note(_hb("b", 1))
    assert reg.shard_count == 2
    clock.t = 1.5  # fleet past TTL, but only blinking
    assert reg.note(_hb("rogue", 0, of=1)) is False  # inside grace
    assert reg.shard_count == 2
    # the fleet resumes: routed again, nothing retired, no cutover
    reg.note(_hb("a", 0))
    reg.note(_hb("b", 1))
    assert reg.shard_count == 2
    assert reg.snapshot()["replicas"]
    assert reg.topology_cutovers == 0
    # but a REAL total loss (past the grace) still re-bootstraps
    clock.t = 1.5 + 1.0 * MembershipRegistry.REBOOTSTRAP_GRACE_TTLS + 1.1
    assert reg.note(_hb("rogue2", 0, of=1)) is True
    assert reg.shard_count == 1


def test_total_fleet_loss_rebootstraps_without_declaration():
    """The recovery hatch: with the merged fleet entirely gone, a
    fresh fleet of any non-retired topology takes over once fully
    covered — the old stop-the-world reshard still works with zero
    admin calls."""
    clock = _Clock()
    reg = MembershipRegistry(ttl_sec=1.0, clock=clock)
    reg.note(_hb("a", 0))
    reg.note(_hb("b", 1))
    assert reg.shard_count == 2
    clock.t = 5.0  # old fleet gone
    assert reg.note(_hb("n0", 0, of=3))
    assert reg.shard_count == 2  # partial new fleet: no cutover yet
    for s in (1, 2):
        assert reg.note(_hb(f"n{s}", s, of=3))
    assert reg.shard_count == 3


def test_group_sizes_reports_merged_topology_groups():
    reg = _merged_two()
    reg.note(_hb("a2", 0))       # second member of shard 0's group
    reg.note(_hb("a3", 0, ready=False))  # warming member: not counted
    assert reg.group_sizes() == {0: 2, 1: 1}


# -- ring-filtered replay properties (live N→M resharding) -------------------

@pytest.mark.parametrize("pair", [(1, 2), (2, 3), (3, 2), (2, 5)])
def test_reshard_replay_partitions_catalog_exactly(pair):
    """New-topology replicas warm from the SAME totally-ordered update
    stream, filtered through the murmur2 ring: every surviving item
    must land on exactly one new shard and the union must be the full
    catalog — no loss, no duplication, for any N→M."""
    n, m = pair
    rng = np.random.default_rng(500 + 10 * n + m)
    old = [_manager(f"{s}/{n}") for s in range(n)]
    new = [_manager(f"{s}/{m}") for s in range(m)]
    full = _manager("0/1")
    _random_replay(rng, old + new + [full])
    surviving = sorted(full.model.all_item_ids())
    per_new = [mm.model.all_item_ids() for mm in new]
    assert sorted(i for ids in per_new for i in ids) == surviving
    # ordinals (the canonical tie-break) agree across topologies
    assert all(mm.item_ordinals == full.item_ordinals
               for mm in old + new)


def test_reshard_merge_exact_before_and_after_cutover():
    """The router's answers must be byte-identical across a 2→3
    reshard: merge(old shards) == merge(new shards) == single node ==
    oracle, for the same user queries."""
    rng = np.random.default_rng(77)
    old = [_manager(f"{s}/2") for s in range(2)]
    new = [_manager(f"{s}/3") for s in range(3)]
    full = _manager("0/1")
    _random_replay(rng, old + new + [full])
    ordinals = full.item_ordinals
    for u in range(6):
        xu = full.model.get_user_vector(f"u{u}")
        exclude = full.model.get_known_items(f"u{u}")
        for how_many in (3, 12):
            merged = {}
            for name, fleet in (("old", old), ("new", new)):
                per_shard = [exact_local_top_n(
                    mm.model,
                    lambda i, mm=mm: mm.item_ordinals.get(i, _NO_ORD),
                    how_many, user_vector=xu, exclude=exclude)
                    for mm in fleet]
                merged[name] = merge_top_n(per_shard, how_many)
            oracle = _oracle_top_n(full.model, ordinals, how_many, xu,
                                   exclude)
            assert merged["old"] == oracle, (u, how_many)
            assert merged["new"] == oracle, (u, how_many)


def test_any_two_of_three_group_members_answer_byte_identically():
    """An R=3 replica group per shard: every member replays the same
    stream, so ANY member's local top-k — and therefore any 2-of-3
    surviving subset — merges byte-identically to the single
    full-catalog node.  This is the exactness half of 'a dead replica
    costs latency, not coverage'."""
    rng = np.random.default_rng(91)
    shards = 2
    groups = [[_manager(f"{s}/{shards}") for _ in range(3)]
              for s in range(shards)]
    full = _manager("0/1")
    _random_replay(rng, [m for g in groups for m in g] + [full])
    ordinals = full.item_ordinals
    pick = np.random.default_rng(5)
    for u in range(6):
        xu = full.model.get_user_vector(f"u{u}")
        exclude = full.model.get_known_items(f"u{u}")
        for how_many in (4, 15):
            # each member of a group answers identically
            for g in groups:
                answers = [exact_local_top_n(
                    m.model,
                    lambda i, m=m: m.item_ordinals.get(i, _NO_ORD),
                    how_many, user_vector=xu, exclude=exclude)
                    for m in g]
                assert answers[0] == answers[1] == answers[2]
            # merge over a random surviving 2-of-3 per shard
            per_shard = []
            for g in groups:
                alive = pick.choice(3, size=2, replace=False)
                member = g[int(alive[0])]
                per_shard.append(exact_local_top_n(
                    member.model,
                    lambda i, m=member: m.item_ordinals.get(i, _NO_ORD),
                    how_many, user_vector=xu, exclude=exclude))
            merged = merge_top_n(per_shard, how_many)
            oracle = _oracle_top_n(full.model, ordinals, how_many, xu,
                                   exclude)
            assert merged == oracle, (u, how_many)


# -- bucket quantile (the autoscaler's p99 estimator) ------------------------

def test_bucket_quantile_edges_and_interpolation():
    assert bucket_quantile([], 0.99) is None
    assert bucket_quantile([0] * 14, 0.99) is None
    # all mass in one bucket: interpolate within its bounds
    counts = [0] * 14
    counts[2] = 10  # (2, 5] ms
    assert 2.0 < bucket_quantile(counts, 0.5) <= 5.0
    # overflow bucket reports the top bound (nothing to interpolate to)
    counts = [0] * 14
    counts[-1] = 5
    assert bucket_quantile(counts, 0.99) == LATENCY_BUCKETS_MS[-1]
    # uniform counts: the median lands mid-range
    q50 = bucket_quantile([7] * 14, 0.5)
    assert LATENCY_BUCKETS_MS[5] < q50 <= LATENCY_BUCKETS_MS[7]


# -- admission control --------------------------------------------------------

class _FakeScatter:
    def __init__(self, qw=None):
        self.qw = qw

    def cluster_queue_wait_ms(self):
        return self.qw


def _admission(scatter=None, metrics=None, **keys):
    overlay = {f"oryx.cluster.admission.{k}": v for k, v in keys.items()}
    return AdmissionController(from_dict(overlay),
                               scatter or _FakeScatter(), metrics)


def test_admission_disabled_by_default():
    a = _admission()
    assert not a.enabled
    assert a.try_acquire() == (True, 0)
    a.release()


def test_admission_max_inflight_gate():
    metrics = MetricsRegistry()
    a = _admission(metrics=metrics, **{"max-inflight": 2,
                                       "retry-after-sec": 3})
    assert a.enabled
    assert a.try_acquire() == (True, 0)
    assert a.try_acquire() == (True, 0)
    ok, retry_after = a.try_acquire()
    assert not ok and retry_after == 3
    assert a.rejected == 1
    assert metrics.counters_snapshot()["admission_rejects"] == 1
    a.release()
    assert a.try_acquire() == (True, 0)
    a.release()
    a.release()
    assert a.inflight == 0


def test_admission_measured_queue_wait_gate():
    scatter = _FakeScatter(qw=None)
    a = _admission(scatter=scatter, **{"queue-wait-high-ms": 100})
    # no signal yet (cluster idle / unreported): admit
    assert a.try_acquire()[0]
    a.release()
    scatter.qw = 250.0
    ok, _ = a.try_acquire()
    assert not ok and a.inflight == 0  # rejected slot released
    scatter.qw = 40.0
    assert a.try_acquire()[0]
    a.release()


def test_admission_rejects_render_503_with_retry_after():
    """End-to-end through the HTTP layer: an admission-marked route
    sheds as a FAST 503 carrying Retry-After; un-marked routes (the
    operator's view into the overloaded process) stay open."""
    from oryx_tpu.lambda_rt.http import HttpApp, Route, make_server

    a = _admission(**{"max-inflight": 1, "retry-after-sec": 7})
    a.try_acquire()  # pin the only slot: every gated request sheds
    app = HttpApp(
        [Route("GET", "/data", lambda req: {"ok": True},
               admission=True),
         Route("GET", "/health", lambda req: {"ok": True})],
        context={"admission": a})
    server = make_server(app, 0)
    port = server.server_address[1]
    import threading
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/data", timeout=10)
        assert e.value.code == 503
        assert e.value.headers.get("Retry-After") == "7"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=10) as r:
            assert json.loads(r.read()) == {"ok": True}
        # released slot: admitted again, and the handler runs
        a.release()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/data", timeout=10) as r:
            assert json.loads(r.read()) == {"ok": True}
        assert a.inflight == 0  # release() ran after the handler
    finally:
        server.shutdown()
