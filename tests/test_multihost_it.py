"""Two-process multi-host join IT: the DCN story of SURVEY §5.8 as a
tested capability instead of plausible code.

Spawns two subprocesses that each join a jax.distributed cluster over a
localhost coordinator (the config-driven initialize_multihost path),
build one global mesh over both processes' virtual CPU devices, and run
one distributed ALS step.  Both must report the same global checksum —
proof the collective crossed the process boundary.

Skips (not fails) when this JAX build cannot initialize a multi-process
CPU cluster or the join times out; any other failure is real.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

_N_DEV = 4  # per process; the global mesh spans 2 * _N_DEV devices
_TIMEOUT_SEC = 180


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_cluster_join_and_train():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = os.path.join(repo, "tests", "multihost_child.py")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          .replace("--xla_force_host_platform_device_count=8",
                                   "")
                          + f" --xla_force_host_platform_device_count"
                            f"={_N_DEV}").strip())
    procs = [subprocess.Popen(
        [sys.executable, child, coord, str(pid), str(_N_DEV), repo],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=repo) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=_TIMEOUT_SEC)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("multi-process cluster join timed out on this host")

    for rc, out, err in outs:
        if "DISTRIBUTED_UNSUPPORTED" in out:
            pytest.skip(f"jax.distributed unsupported here: {out.strip()}")
        assert rc == 0, f"child failed rc={rc}\nstdout:{out}\nstderr:{err}"
        assert "MULTIHOST_OK" in out, (out, err)

    import json
    payloads = [json.loads(out.split("MULTIHOST_OK", 1)[1].strip())
                for _, out, _ in outs]
    assert {p["process"] for p in payloads} == {0, 1}
    assert all(p["devices"] == 2 * _N_DEV for p in payloads)
    # same global checksum in both processes = the collective really
    # crossed the process boundary
    assert payloads[0]["checksum"] == payloads[1]["checksum"]


@pytest.mark.slow
def test_two_process_full_lambda_loop(tmp_path):
    """The FULL lambda loop across a 2-process jax.distributed cluster:
    both processes run the real ALSUpdate.run_update over the global
    mesh; process 0 publishes to a shared file:// broker and a
    ServingLayer answers a live /recommend from the process-spanning
    model (VERDICT r04 item 5; reference analog: batch training on the
    cluster, serving answering from the published model — SURVEY §2.14
    P1/P3)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = os.path.join(repo, "tests", "multihost_lambda_child.py")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          .replace("--xla_force_host_platform_device_count=8",
                                   "")
                          + f" --xla_force_host_platform_device_count"
                            f"={_N_DEV}").strip())
    procs = [subprocess.Popen(
        [sys.executable, child, coord, str(pid), str(_N_DEV), repo,
         str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=repo) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=_TIMEOUT_SEC)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("multi-process cluster join timed out on this host")

    for rc, out, err in outs:
        if "DISTRIBUTED_UNSUPPORTED" in out:
            pytest.skip(f"jax.distributed unsupported here: {out.strip()}")
        assert rc == 0, f"child failed rc={rc}\nstdout:{out}\nstderr:{err}"
        assert "LAMBDA_OK" in out, (out, err)

    import json
    payloads = [json.loads(out.split("LAMBDA_OK", 1)[1].strip())
                for _, out, _ in outs]
    by_pid = {p["process"]: p for p in payloads}
    assert set(by_pid) == {0, 1}
    assert all(p["devices"] == 2 * _N_DEV for p in payloads)
    # the serving layer really answered from the cluster-trained model
    assert len(by_pid[0]["recommend_ids"]) == 3
