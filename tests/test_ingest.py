"""Write-path admission + pipelined batch produce (ISSUE 17).

The durable-ack contract under test: a ``202`` from ``/ingest`` or
``/pref`` means every record of the body is durable in the input
topic; a ``503`` (shed, breaker, broker fault) means retry and NOTHING
was silently half-written.  Two mechanisms carry it:

- :class:`IngestGate` (serving/ingest.py): bounded in-flight sends +
  measured-lag shedding, fast 503 + ``Retry-After``, ``ingest_sheds``
  counter — wrapping ONLY the produce, never health/admin/reads;
- ``send_many`` pipelining (kafka/inproc.py, resilience/policy.py):
  a multi-line body is ONE broker call, classified per record through
  the ``inproc-send`` chaos point BEFORE any append, so a mid-batch
  fault retries the whole batch and never splits it.
"""

import threading
from types import SimpleNamespace

import pytest

from oryx_tpu.api.serving import OryxServingException
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.inproc import InProcTopicProducer, get_broker
from oryx_tpu.lambda_rt.metrics import MetricsRegistry
from oryx_tpu.resilience import faults
from oryx_tpu.resilience.policy import (CircuitOpenError,
                                        ResilientTopicProducer, Retry)
from oryx_tpu.serving.framework import send_input, send_input_many
from oryx_tpu.serving.ingest import IngestGate


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _gate(**extra) -> IngestGate:
    return IngestGate(from_dict(
        {f"oryx.serving.ingest.{k}": v for k, v in extra.items()}))


# -- the admission gate ------------------------------------------------------

def test_gate_disabled_by_default():
    g = _gate()
    assert not g.enabled  # both gates ship 0 = off


def test_inflight_cap_sheds_fast_503_with_retry_after():
    g = _gate(**{"max-inflight-sends": 1, "retry-after-sec": 7})
    metrics = MetricsRegistry()
    adm = g.admitted(metrics)          # slot taken, send "in flight"
    with pytest.raises(OryxServingException) as ei:
        g.admitted(metrics)
    assert ei.value.status == 503
    assert ei.value.headers == {"Retry-After": "7"}
    assert g.sheds == 1
    assert metrics.counters_snapshot()["ingest_sheds"] == 1
    with adm:                          # the admitted send completes
        pass
    with g.admitted(metrics):          # slot free again: admitted
        pass
    assert g.sheds == 1


def test_measured_lag_ewma_sheds_and_recovers(monkeypatch):
    from oryx_tpu.serving import ingest as ingest_mod
    t = [0.0]
    monkeypatch.setattr(ingest_mod.clockmod, "monotonic",
                        lambda: t[0])
    g = _gate(**{"send-lag-high-ms": 50})

    def send_taking(sec):
        with g.admitted():
            t[0] += sec

    for _ in range(4):
        send_taking(0.200)             # broker demonstrably slow
    assert g.send_lag_ms() > 50
    # lag high AND a send in flight = a convoy to join: shed
    hold = g.admitted()
    with pytest.raises(OryxServingException) as ei:
        g.admitted()
    assert ei.value.status == 503
    with hold:
        t[0] += 0.2
    # with nothing in flight there is no convoy; requests are admitted
    # as probes whose measurements drain the EWMA and reopen the gate
    assert g.inflight == 0
    for _ in range(20):
        send_taking(0.001)
    assert g.send_lag_ms() < 50
    with g.admitted():
        pass


def test_admission_releases_on_produce_failure():
    g = _gate(**{"max-inflight-sends": 2})
    with pytest.raises(RuntimeError):
        with g.admitted():
            raise RuntimeError("broker went away mid-send")
    assert g.inflight == 0             # a failed send must not leak a slot


# -- send_input_many: the batched write surface ------------------------------

class _CapturingProducer:
    def __init__(self):
        self.send_calls = []
        self.send_many_calls = []

    def send(self, key, message, headers=None):
        self.send_calls.append((key, message, headers))

    def send_many(self, entries):
        self.send_many_calls.append(list(entries))


def _req(producer, **ctx):
    return SimpleNamespace(context={"input_producer": producer, **ctx})


def test_multi_line_body_is_one_pipelined_produce():
    p = _CapturingProducer()
    send_input_many(_req(p), ["a,b,1", "c,d,2", "e,f,3"])
    assert not p.send_calls
    assert len(p.send_many_calls) == 1
    entries = p.send_many_calls[0]
    assert [m for _, m, _ in entries] == ["a,b,1", "c,d,2", "e,f,3"]
    # headers are preserved PER RECORD: distinct dicts, each stamped
    # with the ingest wall-clock ts the speed layer measures from
    for _, _, h in entries:
        assert h["ts"].isdigit()
    assert len({id(h) for _, _, h in entries}) == len(entries)


def test_single_line_uses_plain_send():
    p = _CapturingProducer()
    send_input(_req(p), "a,b,1")
    assert len(p.send_calls) == 1 and not p.send_many_calls


def test_no_producer_is_403():
    with pytest.raises(OryxServingException) as ei:
        send_input(_req(None), "a,b,1")
    assert ei.value.status == 403


def test_gate_shed_passes_through_before_any_append():
    p = _CapturingProducer()
    g = _gate(**{"max-inflight-sends": 1})
    hold = g.admitted()                # the one slot is taken
    with pytest.raises(OryxServingException) as ei:
        send_input_many(_req(p, ingest_gate=g), ["a,b,1", "c,d,2"])
    assert ei.value.status == 503
    assert ei.value.headers["Retry-After"]
    assert not p.send_calls and not p.send_many_calls, \
        "a shed request must not half-produce its body"
    with hold:
        pass


class _FailingProducer:
    def __init__(self, exc):
        self.exc = exc

    def send(self, key, message, headers=None):
        raise self.exc

    def send_many(self, entries):
        raise self.exc


def test_breaker_open_and_broker_fault_both_map_to_503():
    for exc, frag in ((CircuitOpenError("input-producer open"),
                       "input unavailable"),
                      (OSError("wire torn"), "input send failed")):
        with pytest.raises(OryxServingException) as ei:
            send_input_many(_req(_FailingProducer(exc)), ["x", "y"])
        assert ei.value.status == 503
        assert frag in str(ei.value)


# -- the pipelined append under injected broker faults -----------------------

def _resilient(broker_name, topic):
    cfg = from_dict({
        "oryx.resilience.retry.max-attempts": 3,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
    })
    return ResilientTopicProducer(
        InProcTopicProducer(f"memory://{broker_name}", topic),
        retry=Retry.from_config("test-ingest", cfg))


def _messages(broker, topic):
    end = broker.latest_offset(topic)
    return [(km.key, km.message, km.headers)
            for km in broker.read_range(topic, 0, end)]


ENTRIES = [("k1", "m1", {"ts": "1"}), ("k2", "m2", {"ts": "2"}),
           ("k3", "m3", {"ts": "3"})]


def test_send_many_transient_fault_retries_whole_batch_exactly_once():
    broker = get_broker("ingest-retry")
    broker.create_topic("In", partitions=1)
    prod = _resilient("ingest-retry", "In")
    # the fault classifies records BEFORE any append: attempt 1 dies
    # with zero records durable, the retry lands all three once
    faults.inject("inproc-send", mode="error", times=1)
    prod.send_many(list(ENTRIES))
    assert _messages(broker, "In") == list(ENTRIES)


def test_send_many_duplicate_delivery_is_at_least_once():
    broker = get_broker("ingest-dup")
    broker.create_topic("In", partitions=1)
    prod = _resilient("ingest-dup", "In")
    faults.inject("inproc-send", mode="duplicate", times=1)
    prod.send_many(list(ENTRIES))
    msgs = [m for _, m, _ in _messages(broker, "In")]
    assert sorted(msgs) == ["m1", "m1", "m2", "m3"]


def test_send_many_drop_loses_only_the_dropped_record():
    broker = get_broker("ingest-drop")
    broker.create_topic("In", partitions=1)
    prod = _resilient("ingest-drop", "In")
    faults.inject("inproc-send", mode="drop", times=1)
    prod.send_many(list(ENTRIES))
    msgs = [m for _, m, _ in _messages(broker, "In")]
    assert msgs == ["m2", "m3"]


def test_send_many_preserves_per_record_headers_and_order():
    broker = get_broker("ingest-hdrs")
    broker.create_topic("In", partitions=1)
    prod = _resilient("ingest-hdrs", "In")
    prod.send_many(list(ENTRIES))
    got = _messages(broker, "In")
    assert got == list(ENTRIES)
    assert [h["ts"] for _, _, h in got] == ["1", "2", "3"]


class _NoBatchProducer:
    """Inner producer without send_many: the resilient wrapper must
    fall back to a per-record loop under the same retry admission."""

    def __init__(self):
        self.sent = []
        self.fail_first = True

    def send(self, key, message, headers=None):
        if self.fail_first:
            self.fail_first = False
            raise OSError("transient")
        self.sent.append((key, message, headers))


def test_send_many_falls_back_to_per_record_loop():
    inner = _NoBatchProducer()
    cfg = from_dict({
        "oryx.resilience.retry.max-attempts": 3,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
    })
    prod = ResilientTopicProducer(inner,
                                  retry=Retry.from_config("t", cfg))
    prod.send_many(list(ENTRIES))
    assert inner.sent == list(ENTRIES)


def test_send_many_under_concurrency_interleaves_whole_records():
    """Pipelined appends from many threads must never tear: every
    record lands intact, each exactly once."""
    broker = get_broker("ingest-conc")
    broker.create_topic("In", partitions=1)
    prod = _resilient("ingest-conc", "In")
    n_threads, per = 8, 25

    def worker(t):
        prod.send_many([(f"k{t}-{i}", f"m{t}-{i}", {"t": str(t)})
                        for i in range(per)])

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    msgs = [m for _, m, _ in _messages(broker, "In")]
    assert len(msgs) == n_threads * per
    assert sorted(msgs) == sorted(f"m{t}-{i}" for t in range(n_threads)
                                  for i in range(per))
