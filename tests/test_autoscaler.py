"""Gauge-driven autoscaler unit tests (ISSUE 6): the pure decision
core — consecutive-poll streaks, cooldown, thinnest-group targeting,
owned-only scale-down with the live floor — plus the interval-p99
computation over merged bucket deltas and the policy config surface.
The launcher and HTTP are faked; the real-process path is exercised by
the elastic chaos IT and the gateway bench."""

from __future__ import annotations

from oryx_tpu.cluster.autoscaler import (Autoscaler, AutoscalePolicy,
                                         ReplicaLauncher, Signals)
from oryx_tpu.common.config import from_dict
from oryx_tpu.lambda_rt.metrics import MetricsRegistry
from oryx_tpu.obs.prom import LATENCY_BUCKETS_MS


class FakeLauncher(ReplicaLauncher):
    def __init__(self):
        self.spawned: list[tuple[int, int]] = []
        self.retired: list[tuple[int, int]] = []
        self._owned: dict[tuple[int, int], int] = {}

    def spawn(self, shard, of):
        self.spawned.append((shard, of))
        self._owned[(shard, of)] = self._owned.get((shard, of), 0) + 1
        return f"fake-{shard}of{of}-{len(self.spawned)}"

    def retire(self, shard, of):
        if self._owned.get((shard, of), 0) <= 0:
            return None
        self._owned[(shard, of)] -= 1
        self.retired.append((shard, of))
        return f"fake-{shard}of{of}"

    def owned(self, of):
        return {s: n for (s, o), n in self._owned.items()
                if o == of and n > 0}


def _policy(**kw):
    base = dict(p99_high_ms=500, p99_low_ms=50, queue_wait_high_ms=200,
                update_lag_high_records=0, scale_up_after=2,
                scale_down_after=3, cooldown_sec=10.0,
                min_replicas_per_shard=1, max_replicas_per_shard=3)
    base.update(kw)
    return AutoscalePolicy(**base)


def _scaler(policy=None, launcher=None, metrics=None):
    return Autoscaler(policy or _policy(), launcher or FakeLauncher(),
                      "http://r", metrics=metrics)


def _sig(p99=None, qw=None, lag=None, groups=None, of=2, ok=True):
    return Signals(ok=ok, merged_of=of,
                   group_sizes=groups or {0: 1, 1: 1},
                   p99_ms=p99, queue_wait_ms=qw,
                   update_lag_records=lag)


def test_scale_up_needs_consecutive_pressure_then_targets_thinnest():
    launcher = FakeLauncher()
    sc = _scaler(launcher=launcher)
    assert sc.step(_sig(p99=800, groups={0: 2, 1: 1}), now=0.0) is None
    action = sc.step(_sig(p99=800, groups={0: 2, 1: 1}), now=1.0)
    assert action == {"kind": "spawn", "shard": 1,
                      "member": "fake-1of2-1",
                      "reason": "p99 800ms > 500"}
    assert launcher.spawned == [(1, 2)]


def test_one_bad_poll_never_scales():
    sc = _scaler()
    assert sc.step(_sig(p99=800), now=0.0) is None
    assert sc.step(_sig(p99=30), now=1.0) is None  # calm resets streak
    assert sc.step(_sig(p99=800), now=2.0) is None
    assert sc.up_streak == 1


def test_cooldown_blocks_followup_actions():
    launcher = FakeLauncher()
    sc = _scaler(launcher=launcher)
    sc.step(_sig(qw=400), now=0.0)
    assert sc.step(_sig(qw=400), now=1.0) is not None
    # pressure persists, but the fleet must settle first
    for t in (2.0, 5.0, 10.9):
        assert sc.step(_sig(qw=400), now=t) is None
    # past the cooldown the streak re-accrues from zero
    assert sc.step(_sig(qw=400), now=12.0) is None
    assert sc.step(_sig(qw=400), now=13.0) is not None
    assert len(launcher.spawned) == 2


def test_max_replicas_per_shard_caps_scale_up():
    launcher = FakeLauncher()
    sc = _scaler(_policy(max_replicas_per_shard=2), launcher)
    sc.step(_sig(p99=900, groups={0: 2, 1: 2}), now=0.0)
    assert sc.step(_sig(p99=900, groups={0: 2, 1: 2}), now=1.0) is None
    assert launcher.spawned == []


def test_scale_down_retires_only_owned_and_respects_live_floor():
    launcher = FakeLauncher()
    sc = _scaler(launcher=launcher)
    # nothing owned: calm forever never touches the static fleet
    for t in range(5):
        assert sc.step(_sig(p99=10), now=float(t)) is None
    launcher.spawn(0, 2)
    launcher.spawn(1, 2)
    sc.up_streak = sc.down_streak = 0
    # shard 1's LIVE group is at the floor (1 member): not eligible
    # even though we own a member there; shard 0 has headroom
    groups = {0: 2, 1: 1}
    assert sc.step(_sig(p99=10, groups=groups), now=20.0) is None
    assert sc.step(_sig(p99=10, groups=groups), now=21.0) is None
    action = sc.step(_sig(p99=10, groups=groups), now=22.0)
    assert action["kind"] == "retire" and action["shard"] == 0
    assert launcher.retired == [(0, 2)]


def test_no_traffic_counts_as_calm():
    launcher = FakeLauncher()
    launcher.spawn(0, 2)
    sc = _scaler(launcher=launcher)
    groups = {0: 2, 1: 1}
    for t in range(2):
        assert sc.step(_sig(p99=None, groups=groups),
                       now=float(t)) is None
    assert sc.step(_sig(p99=None, groups=groups),
                   now=2.0)["kind"] == "retire"


def test_blind_polls_reset_streaks_and_never_act():
    sc = _scaler()
    sc.step(_sig(p99=900), now=0.0)
    assert sc.up_streak == 1
    assert sc.step(_sig(ok=False), now=1.0) is None
    assert sc.up_streak == 0


def test_update_lag_pressure_signal():
    policy = _policy(update_lag_high_records=1000)
    sc = _scaler(policy)
    sc.step(_sig(lag=5000.0), now=0.0)
    action = sc.step(_sig(lag=5000.0), now=1.0)
    assert action is not None and "update_lag" in action["reason"]


def test_gauges_published_each_step():
    metrics = MetricsRegistry()
    sc = _scaler(metrics=metrics)
    sc.step(_sig(p99=123.4, qw=5.6), now=0.0)
    g = metrics.gauges_snapshot()
    assert g["autoscale_p99_ms"] == 123.4
    assert g["autoscale_queue_wait_ms"] == 5.6
    assert g["autoscale_update_lag_records"] == -1.0  # unavailable
    assert g["autoscale_members"] == 0


def test_interval_p99_uses_bucket_deltas_not_history():
    sc = _scaler()

    def snap(counts):
        return {"routes": {
            "GET /recommend/{userID}": {"latency_ms":
                                        {"buckets": list(counts)}},
            # control surface must not vote
            "GET /metrics": {"latency_ms":
                             {"buckets": [1000] * 14}},
        }}

    fast = [0] * 14
    fast[1] = 100  # 100 requests in (1, 2] ms
    assert sc._interval_p99(snap(fast)) is None  # first poll: no delta
    # second poll: 10 NEW slow requests on top of the cumulative fast
    # history — the interval p99 must be slow although lifetime p99 is
    # still fast
    slow = list(fast)
    slow[10] = 10  # (1000, 2000] ms
    p99 = sc._interval_p99(snap(slow))
    assert p99 is not None and p99 > LATENCY_BUCKETS_MS[9]
    # third poll, nothing new: no traffic this interval
    assert sc._interval_p99(snap(slow)) is None


def test_policy_from_config_reads_autoscale_block():
    policy = AutoscalePolicy.from_config(from_dict({
        "oryx.cluster.autoscale.p99-high-ms": 300,
        "oryx.cluster.autoscale.scale-up-after": 4,
    }))
    assert policy.p99_high_ms == 300
    assert policy.scale_up_after == 4
    assert policy.min_replicas_per_shard == 1  # defaults resolve
    assert policy.max_replicas_per_shard == 4


def test_poll_signals_parses_router_metrics():
    payloads = {
        "http://r/metrics": {
            "cluster": {
                "membership": {
                    "shards": 2,
                    "replicas": {
                        "a": {"shard": 0, "of": 2, "ready": True,
                              "live": True, "url": "http://a"},
                        "a2": {"shard": 0, "of": 2, "ready": True,
                               "live": True, "url": "http://a2"},
                        "b": {"shard": 1, "of": 2, "ready": True,
                              "live": True, "url": "http://b"},
                        "dead": {"shard": 1, "of": 2, "ready": True,
                                 "live": False, "url": "http://d"},
                    }},
                "scatter": {"cluster_queue_wait_ms": 42.5}}},
        "http://r/metrics?format=prometheus-json": {"routes": {}},
    }
    sc = Autoscaler(_policy(), FakeLauncher(), "http://r",
                    fetch=lambda url, timeout=5.0: payloads[url])
    s = sc.poll_signals()
    assert s.ok and s.merged_of == 2
    assert s.group_sizes == {0: 2, 1: 1}
    assert s.queue_wait_ms == 42.5
    assert s.p99_ms is None  # first poll has no interval


def test_counter_reset_discards_interval_and_counts():
    """ISSUE 7 satellite: a replica/router restart resets cumulative
    bucket counters to 0 mid-poll.  Clamping per-bucket deltas at 0
    (the old behavior) produced a PARTIALLY-zeroed delta vector whose
    quantile was garbage — the whole interval must be discarded, the
    reset counted, and the next interval measured cleanly against the
    post-reset baseline."""
    metrics = MetricsRegistry()
    sc = _scaler(metrics=metrics)

    def snap(counts):
        return {"routes": {"GET /recommend/{userID}":
                           {"latency_ms": {"buckets": list(counts)}}}}

    healthy = [0] * 14
    healthy[1] = 500          # long fast history in (1, 2] ms
    healthy[10] = 40          # plus some old slow ones (1000, 2000]
    assert sc._interval_p99(snap(healthy)) is None   # first poll
    # the fake replica restarts: counters reset, then 10 fast requests
    # land before the next poll.  Under max(0, c-p) clamping the fast
    # bucket would delta to 0 while nothing else moved -> the old code
    # returned a garbage quantile of an all-zero-except-noise vector;
    # now the monotonicity violation discards the poll.
    restarted = [0] * 14
    restarted[1] = 10
    assert sc._interval_p99(snap(restarted)) is None
    assert sc.counter_resets == 1
    assert metrics.counters_snapshot()["autoscale_counter_resets"] == 1
    # next poll measures cleanly against the post-reset baseline
    after = list(restarted)
    after[1] += 100
    p99 = sc._interval_p99(snap(after))
    assert p99 is not None and p99 <= LATENCY_BUCKETS_MS[1]
    assert sc.counter_resets == 1


def test_slo_burn_pressure_signal_and_gauge():
    """The PR 6 autoscaler scales on raw thresholds; ISSUE 7 wires the
    SLO engine's error-budget burn in as an additional scale-up
    signal (oryx.cluster.autoscale.slo-burn-high)."""
    launcher = FakeLauncher()
    metrics = MetricsRegistry()
    sc = _scaler(_policy(slo_burn_high=10.0, p99_high_ms=0,
                         queue_wait_high_ms=0), launcher, metrics)
    s = _sig()
    s.slo_burn_rate = 25.0
    assert sc.step(s, now=0.0) is None
    action = sc.step(s, now=1.0)
    assert action is not None and "slo_burn 25.0 > 10.0" in action["reason"]
    assert metrics.gauges_snapshot()["autoscale_slo_burn_rate"] == 25.0
    # disabled (the default): the signal never votes
    sc2 = _scaler(_policy(slo_burn_high=0.0, p99_high_ms=0,
                          queue_wait_high_ms=0))
    s2 = _sig()
    s2.slo_burn_rate = 1e9
    assert sc2.policy.pressure(s2) == []


def test_poll_signals_reads_slo_gauge():
    payloads = {
        "http://r/metrics": {
            "cluster": {"membership": {"shards": 1, "replicas": {}},
                        "scatter": {}},
            "freshness": {"slo_burn_rate": 18.5}},
        "http://r/metrics?format=prometheus-json": {"routes": {}},
    }
    sc = Autoscaler(_policy(), FakeLauncher(), "http://r",
                    fetch=lambda url, timeout=5.0: payloads[url])
    assert sc.poll_signals().slo_burn_rate == 18.5
    # engine off -> gauge absent -> None, never 0.0 (absence of
    # evidence must not read as calm)
    del payloads["http://r/metrics"]["freshness"]
    assert sc.poll_signals().slo_burn_rate is None


def test_policy_from_config_reads_slo_burn_high():
    policy = AutoscalePolicy.from_config(from_dict({
        "oryx.cluster.autoscale.slo-burn-high": 14.4}))
    assert policy.slo_burn_high == 14.4
    assert AutoscalePolicy.from_config(
        from_dict({})).slo_burn_high == 0.0  # default: off


def test_poll_signals_survives_unreachable_router():
    def boom(url, timeout=5.0):
        raise OSError("connection refused")

    sc = Autoscaler(_policy(), FakeLauncher(), "http://r", fetch=boom)
    s = sc.poll_signals()
    assert not s.ok
    assert sc.step(s) is None
