"""Framed-transport integration tests (ISSUE 12 acceptance): a real
2-shard cluster served through TWO routers — one on the multiplexed
framed hop, one on the legacy HTTP/1.1 pool — proving

1. byte-identity: the framed router's scatter merges are
   BYTE-IDENTICAL to the HTTP hop's across the public surface;
2. the chaos suite holds on frames: kill → partial parity → rejoin,
   hedged failover within the TTL, live reshard cutover — each
   byte-identical to the HTTP router throughout;
3. hedges cost a frame, not a connection: through a forced-hedge
   storm the router keeps ONE transport connection per replica;
4. the replica-side result cache: a repeated identical shard query
   skips the device (hits count), an update-topic record evicts by
   moving the epoch, and answers stay byte-identical either way.

Marker: chaos (in the tier-1 budget).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.cluster.router import RouterLayer
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.serving import ServingLayer
from oryx_tpu.resilience import faults
from oryx_tpu.resilience.policy import Deadline

pytestmark = pytest.mark.chaos

BROKER = "transport-it"
UPDATE_TOPIC = "TUp"
FEATURES = 3


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _config(**extra):
    overlay = {
        "oryx.id": "transport-it",
        "oryx.input-topic.broker": f"memory://{BROKER}",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "TIn",
        "oryx.update-topic.broker": f"memory://{BROKER}",
        "oryx.update-topic.message.topic": UPDATE_TOPIC,
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": FEATURES,
        "oryx.cluster.heartbeat-interval-ms": 60,
        "oryx.cluster.heartbeat-ttl-ms": 400,
        "oryx.cluster.hedge-after-ms": 50,
        "oryx.cluster.shard-timeout-ms": 5000,
        "oryx.cluster.transport.enabled": True,
        "oryx.cluster.replica-cache.enabled": True,
        "oryx.cluster.replica-cache.quarantine-ms": 50,
        "oryx.resilience.retry.max-attempts": 2,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
    }
    overlay.update(extra)
    return from_dict(overlay)


def _publish_model(broker, n_users=6, n_items=14, seed=11):
    from oryx_tpu.common import pmml as pmml_io
    from oryx_tpu.kafka.api import KEY_MODEL, KEY_UP
    users = [f"tu{j}" for j in range(n_users)]
    items = [f"ti{j}" for j in range(n_items)]
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", FEATURES)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", users)
    pmml_io.add_extension_content(doc, "YIDs", items)
    broker.send(UPDATE_TOPIC, KEY_MODEL, pmml_io.to_string(doc))
    rng = np.random.default_rng(seed)
    for iid in items:
        broker.send(UPDATE_TOPIC, KEY_UP, json.dumps(
            ["Y", iid, [float(x) for x in rng.standard_normal(FEATURES)]]))
    for uid in users:
        broker.send(UPDATE_TOPIC, KEY_UP, json.dumps(
            ["X", uid, [float(x) for x in rng.standard_normal(FEATURES)],
             []]))
    return users, items


def _raw_get(port, path, headers=None, timeout=15):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), r.read()


def _raw_get_any(port, path, headers=None, timeout=15):
    try:
        return _raw_get(port, path, headers=headers, timeout=timeout)
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _await(predicate, what, timeout=30.0):
    deadline = Deadline.after(timeout)
    while not deadline.expired:
        try:
            if predicate():
                return
        except (urllib.error.URLError, OSError, KeyError):
            pass
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _start_replica(shard, of, replica_id=None, extra=None):
    overlay = {"oryx.cluster.enabled": True,
               "oryx.cluster.shard": f"{shard}/{of}"}
    if replica_id:
        overlay["oryx.cluster.replica-id"] = replica_id
    overlay.update(extra or {})
    layer = ServingLayer(_config(**overlay), port=0)
    layer.start()
    return layer


def _ready(router):
    try:
        return _raw_get(router.port, "/ready")[0] in (200, 204)
    except (urllib.error.HTTPError, urllib.error.URLError, OSError):
        return False


SURFACE = [
    "/recommend/{u}?howMany=8",
    "/recommend/{u}?howMany=5&offset=2&considerKnownItems=true",
    "/recommendToMany/{u}/{v}",
    "/similarity/{i}/{j}?howMany=5",
    "/similarityToItem/{i}/{j}/{k}",
    "/estimate/{u}/{i}/{j}",
    "/because/{u}/{i}?howMany=4",
    "/mostSurprising/{u}",
    "/knownItems/{u}",
    "/recommendToAnonymous/{i}=2.0/{j}",
    "/recommendWithContext/{u}/{i}=1.5",
    "/estimateForAnonymous/{i}/{j}=0.5",
    "/mostPopularItems",
    "/allItemIDs",
    "/allUserIDs",
]


def _fill(path, users, items):
    return (path.replace("{u}", users[0]).replace("{v}", users[1])
            .replace("{i}", items[0]).replace("{j}", items[1])
            .replace("{k}", items[2]))


@pytest.fixture(scope="module")
def cluster():
    """2 transport-enabled shards + a framed router + an HTTP router."""
    broker = get_broker(BROKER)
    users, items = _publish_model(broker)
    replicas = [_start_replica(s, 2) for s in range(2)]
    framed = RouterLayer(_config(), port=0)
    framed.start()
    plain = RouterLayer(_config(**{
        "oryx.cluster.transport.enabled": False}), port=0)
    plain.start()

    def fully_loaded(layer):
        meta = json.loads(_raw_get(layer.port, "/shard/meta")[2])
        return meta.get("users", 0) >= len(users)

    _await(lambda: _ready(framed), "framed router readiness")
    _await(lambda: _ready(plain), "plain router readiness")
    _await(lambda: all(fully_loaded(r) for r in replicas),
           "full replica replay")
    yield {"replicas": replicas, "framed": framed, "plain": plain,
           "broker": broker, "users": users, "items": items}
    for layer in replicas + [framed, plain]:
        try:
            layer.close()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass


def test_framed_router_actually_uses_frames(cluster):
    framed, plain = cluster["framed"], cluster["plain"]
    _raw_get(framed.port, f"/recommend/{cluster['users'][0]}?howMany=5")
    assert framed.scatter.transport is not None
    assert framed.scatter.transport.open_connections() >= 1
    # every live heartbeat advertises its frame listener
    assert all(hb.tport for hb, _ in
               framed.membership._replicas.values())
    assert plain.scatter.transport is None


def test_public_surface_byte_identical_framed_vs_http(cluster):
    framed, plain = cluster["framed"], cluster["plain"]
    users, items = cluster["users"], cluster["items"]
    for raw in SURFACE:
        path = _fill(raw, users, items)
        sf, hf, bf = _raw_get(framed.port, path)
        sp, hp, bp = _raw_get(plain.port, path)
        assert (sf, bf) == (sp, bp), path
        assert hf.get("X-Oryx-Partial") == hp.get("X-Oryx-Partial")
    # 404 parity
    for path in ("/recommend/nosuchuser",
                 f"/similarity/nosuchitem/{items[0]}"):
        assert _raw_get_any(framed.port, path)[0] == \
            _raw_get_any(plain.port, path)[0] == 404


def test_kill_partial_parity_then_rejoin_exact(cluster):
    """Chaos: kill one shard's replica — BOTH routers degrade to the
    same partial answer (header and bytes); rejoin → exact again,
    byte-identical, no router restarts anywhere."""
    framed, plain = cluster["framed"], cluster["plain"]
    users, items = cluster["users"], cluster["items"]
    path = f"/recommend/{users[0]}?howMany=8"
    _, _, full_framed = _raw_get(framed.port, path)
    victim = cluster["replicas"][1]
    victim.close()
    try:
        def partial_seen():
            # BOTH routers must have seen the death: the framed one
            # notices at the dead frame connection, the plain one may
            # ride a zombie keep-alive socket until the TTL ages the
            # victim out of membership
            out = []
            for router in (framed, plain):
                _, h, _ = _raw_get(router.port, path,
                                   headers={"X-Deadline-Ms": "8000"})
                out.append(h.get("X-Oryx-Partial") == "shards=1/2")
            return all(out)
        _await(partial_seen, "partial after replica kill")
        sf, hf, bf = _raw_get(framed.port, path,
                              headers={"X-Deadline-Ms": "8000"})
        sp, hp, bp = _raw_get(plain.port, path,
                              headers={"X-Deadline-Ms": "8000"})
        assert sf == sp == 200
        assert hf.get("X-Oryx-Partial") == hp.get("X-Oryx-Partial") \
            == "shards=1/2"
        assert bf == bp
    finally:
        cluster["replicas"][1] = _start_replica(1, 2)
    _await(lambda: _ready(framed), "framed rejoin readiness")
    _await(lambda: _ready(plain), "plain rejoin readiness")

    def exact_again():
        _, h, b = _raw_get(framed.port, path)
        return h.get("X-Oryx-Partial") is None and b == full_framed
    _await(exact_again, "exact after rejoin")
    assert _raw_get(plain.port, path)[2] == full_framed


def test_hedged_failover_and_frame_stall_hedge(cluster):
    """A shard-0 sibling joins, dies inside its TTL: the framed router
    fails over within one request.  Then the frame-stall chaos point
    stalls the primary's stream — the hedge fires as a FRAME and the
    router still holds at most one transport connection per replica."""
    framed = cluster["framed"]
    users = cluster["users"]
    path = f"/recommend/{users[2]}?howMany=6"
    sibling = _start_replica(0, 2, replica_id="shard0-sib")
    try:
        _await(lambda: len(framed.membership._replicas) >= 3,
               "sibling registered")
        _, _, expected = _raw_get(framed.port, path,
                                  headers={"X-Deadline-Ms": "8000"})
        sibling.close()  # dead but inside its TTL
        for _ in range(6):
            s, h, b = _raw_get(framed.port, path,
                               headers={"X-Deadline-Ms": "8000"})
            assert s == 200 and h.get("X-Oryx-Partial") is None
            assert b == expected
    finally:
        try:
            sibling.close()
        except Exception:  # noqa: BLE001
            pass
    # frame-stall: with a live sibling, the stalled stream loses to a
    # hedged frame on the sibling's connection
    sibling = _start_replica(0, 2, replica_id="shard0-sib2")
    try:
        # TWO live READY shard-0 candidates (the dead first sibling
        # ages out of candidates() at its TTL; membership._replicas
        # would still list its stale entry)
        _await(lambda: len(framed.membership.candidates(0)) >= 2,
               "two ready shard-0 candidates")
        # warm the new sibling's scoring path directly: its first
        # dispatch pays the XLA compile, and a multi-second compile
        # inside the hedge window would let the stalled primary "win"
        _raw_get(sibling.port,
                 f"/shard/recommend/{users[2]}?howMany=6", timeout=60)
        hedges0 = framed.scatter.hedges
        abandoned0 = framed.scatter.hedge_abandoned
        # times=2: ONE request's scatter carries one frame per shard,
        # and both consume a stall — shard 0 hedges to its (unstalled)
        # sibling while shard 1's single member just runs the delay
        # out inside the deadline
        faults.inject("transport-frame-stall", mode="delay",
                      times=2, delay_sec=2.0)
        s, h, _ = _raw_get(framed.port, path,
                           headers={"X-Deadline-Ms": "8000"})
        assert s == 200 and h.get("X-Oryx-Partial") is None
        assert faults.fired("transport-frame-stall") == 2
        assert framed.scatter.hedges > hedges0
        _await(lambda: framed.scatter.hedge_abandoned > abandoned0,
               "stalled stream abandoned")
        # the hedge cost a frame, not a connection: at most ONE
        # transport connection per live replica, even mid-storm
        snapshot = framed.scatter.transport.connection_snapshot()
        assert len(snapshot) <= 3  # <= one per live replica
        assert framed.scatter.transport.cancels_sent >= 1
    finally:
        faults.clear("transport-frame-stall")
        try:
            sibling.close()
        except Exception:  # noqa: BLE001
            pass
    _await(lambda: _ready(framed), "cluster settled")


def test_replica_cache_skips_recompute_and_epoch_evicts(cluster):
    """The replica-side result cache: identical shard queries under an
    unchanged epoch replay stored bytes (hits count, answers stay
    byte-identical); one update-topic record moves the epoch and the
    next query recomputes."""
    framed = cluster["framed"]
    users = cluster["users"]
    replica = cluster["replicas"][0]
    cache = replica._shard_cache
    assert cache is not None and cache.enabled
    # let the quarantine window after the replay's last record pass
    time.sleep(0.1)
    path = f"/recommend/{users[3]}?howMany=7"
    _, _, b1 = _raw_get(framed.port, path)
    hits0 = cache.stats()["hits"]
    _, _, b2 = _raw_get(framed.port, path)
    assert b2 == b1
    assert cache.stats()["hits"] > hits0  # the device was skipped
    # an applied update record moves the epoch: entries stop serving
    epoch0 = cache.epoch()
    cluster["broker"].send(UPDATE_TOPIC, "UP", json.dumps(
        ["X", users[3],
         [0.05 * (j + 1) for j in range(FEATURES)], []]))
    _await(lambda: cache.epoch() > epoch0, "epoch moved")
    hits1 = cache.stats()["hits"]

    def recomputed():
        _, _, b3 = _raw_get(framed.port, path)
        return b3 != b1
    _await(recomputed, "post-fold-in recompute")
    assert cache.stats()["hits"] == hits1  # no stale hit served


def test_live_reshard_cutover_byte_identical(cluster):
    """Live 2→1 reshard under the framed transport: declare the
    target, warm a 0/1 replica, cut over — both routers answer
    byte-identically before, during (old ring), and after."""
    framed, plain = cluster["framed"], cluster["plain"]
    users, items = cluster["users"], cluster["items"]
    path = f"/recommend/{users[4]}?howMany=8"
    _, _, before = _raw_get(framed.port, path)
    assert before == _raw_get(plain.port, path)[2]
    for router in (framed, plain):
        req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/admin/topology",
            data=json.dumps({"of": 1}).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=15) as r:
            assert r.status == 200
    wide = _start_replica(0, 1, replica_id="whole-catalog")
    try:
        # cutover fires at the ready gate (80% loaded) — wait for the
        # FULL replay too, or the user store may not hold tu4 yet
        _await(lambda: json.loads(
            _raw_get(wide.port, "/shard/meta")[2]).get("users", 0)
            >= len(users), "wide replica full replay")
        _await(lambda: framed.membership.shard_count == 1,
               "framed cutover")
        _await(lambda: plain.membership.shard_count == 1,
               "plain cutover")
        sf, _, bf = _raw_get(framed.port, path)
        sp, _, bp = _raw_get(plain.port, path)
        assert sf == sp == 200
        assert bf == bp
        # same ids as the 2-way ring served (the catalog is the same)
        assert [d["id"] for d in json.loads(bf)] == \
            [d["id"] for d in json.loads(before)]
    finally:
        # scale back up: un-retire 2, wait for cutover back so later
        # tests (and reruns) see the module fixture's topology
        for router in (framed, plain):
            req = urllib.request.Request(
                f"http://127.0.0.1:{router.port}/admin/topology",
                data=json.dumps({"of": 2}).encode(), method="POST")
            with urllib.request.urlopen(req, timeout=15) as r:
                assert r.status == 200
        _await(lambda: framed.membership.shard_count == 2,
               "framed scale-back")
        _await(lambda: plain.membership.shard_count == 2,
               "plain scale-back")
        wide.close()
    _await(lambda: _ready(framed) and _ready(plain), "settled")
    assert _raw_get(framed.port, path)[2] == before
