"""Load-bench harness tests (reference: LoadBenchmark runs as an
opt-in profile; here a scaled-down smoke run is part of the suite)."""

import numpy as np
import pytest

from oryx_tpu.bench.load import (StaticModelManager, build_load_test_model,
                                 run_recommend_load)
from oryx_tpu.bench.traffic import ALS_ENDPOINTS, EndpointMix, run_traffic
from oryx_tpu.common.config import from_dict
from oryx_tpu.lambda_rt.serving import ServingLayer


class LoadMockManager(StaticModelManager):
    model = None


@pytest.fixture(scope="module")
def load_server():
    LoadMockManager.model = build_load_test_model(
        users=200, items=500, features=8, known_items_per_user=3)
    cfg = from_dict({
        "oryx.serving.model-manager-class":
            "tests.test_bench_load.LoadMockManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.input-topic.broker": None,
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": None,
        "oryx.update-topic.broker": None,
        "oryx.update-topic.message.topic": None,
    })
    layer = ServingLayer(cfg, port=0)
    layer.start()
    yield layer
    layer.close()


def test_recommend_load(load_server):
    base = f"http://127.0.0.1:{load_server.port}"
    user_ids = [str(u) for u in range(200)]
    stats = run_recommend_load(base, user_ids, requests=50, workers=3)
    assert stats.errors == 0
    assert stats.requests == 50
    assert stats.qps > 0
    assert np.isfinite(stats.percentile_ms(50))
    summary = stats.summary()
    assert set(summary) == {"requests", "errors", "qps", "p50_ms",
                            "p95_ms", "p99_ms"}


def test_traffic_generator(load_server):
    base = f"http://127.0.0.1:{load_server.port}"
    mix = EndpointMix(ALS_ENDPOINTS, users=200, items=500)
    stats = run_traffic([base], mix, mean_qps=100.0, duration_sec=1.5,
                        workers=3)
    assert stats.requests + stats.errors > 0
    # estimates for random ids can 404/503-free: all mix endpoints exist
    assert stats.errors == 0


def test_bench_apps_small_scale():
    """The kmeans/RDF bench harness runs end to end at toy scale (the
    recorded artifacts use the same code at full scale on the chip)."""
    from oryx_tpu.bench.apps import bench_kmeans, bench_rdf

    km = bench_kmeans(n_points=2000, dims=4, k=3, iterations=2)
    # toy-scale Lloyd rounds to 0.000s at 3-decimal precision; total
    # includes init and is always measurable
    assert km["total_s"] > 0 and km["points"] == 2000
    rdf = bench_rdf(n_examples=1500, n_predictors=4, num_trees=2,
                    max_depth=3, min_accuracy=0.6)
    assert rdf["warm_total_s"] > 0
    assert 0.6 <= rdf["heldout_accuracy"] <= 1.0


def test_grid_bench_toy_scale(monkeypatch):
    """The full-grid serving bench harness runs end to end at toy scale
    (the recorded BENCH_GRID artifact uses this code at reference scale
    on the chip): both LSH modes, warm-up, calibration, low-concurrency
    latency."""
    from oryx_tpu.bench import grid

    monkeypatch.setattr(grid, "SAT_WORKERS", 4)
    monkeypatch.setattr(grid, "LOW_REQUESTS", 8)
    monkeypatch.setattr(grid, "MEASURE_SEC", 0.3)
    monkeypatch.setattr(grid, "N_USERS", 50)
    monkeypatch.setitem(grid.BASELINES, (4, 0, False), (10, 10))
    monkeypatch.setitem(grid.BASELINES, (4, 0, True), (10, 10))
    rng = np.random.default_rng(0)
    model, user_ids = grid.build_model(4, 600, rng)
    assert str(model.Y.device_arrays()[0].dtype) == "bfloat16"
    rows = grid.bench_config(4, 0, model, user_ids)
    assert len(rows) == 2
    for r in rows:
        assert r["qps"] > 0 and r["qps_errors"] == 0
        assert np.isfinite(r["p50_ms_at_2_workers"])
    assert rows[0]["lsh"] is False and rows[1]["lsh"] is True
    assert model.lsh is not None  # restored after the exact rows


def test_open_loop_driver(load_server):
    """Open-loop /recommend driver (TrafficUtil.java:63 analog):
    arrival-rate-driven, latency from scheduled arrival, saturation
    visible as achieved < offered."""
    from oryx_tpu.bench.load import run_recommend_open_loop

    base = f"http://127.0.0.1:{load_server.port}"
    user_ids = [str(u) for u in range(200)]
    out = run_recommend_open_loop(base, user_ids, rate_qps=60.0,
                                  duration_sec=1.5, workers=16)
    assert out["errors"] == 0
    assert out["achieved_qps"] > 0
    assert set(out) >= {"offered_qps", "achieved_qps", "p50_ms",
                        "p95_ms", "mean_sched_lateness_ms", "sustained"}
    # a modest rate against an idle in-proc model must sustain
    assert out["sustained"] is True
