"""Crash-recovery / chaos integration tests: the lambda runtime's
recovery semantics exercised under *injected* failures (marker: chaos).

Until this suite, offset-commit-after-batch, update-topic replay from
offset 0, and 503 gating existed as code paths that no test ever drove
through an actual failure.  Each scenario here is deterministic: faults
fire at named injection points (oryx_tpu/resilience/faults.py), crashes
are synchronous raises of InjectedCrash, and every wait is a bounded
condition, not a sleep-as-synchronization.

The three headline scenarios (ISSUE acceptance criteria):
1. batch layer killed between the generation save and the offset
   commit reprocesses without duplicating input;
2. a speed layer restarted mid-stream replays the update topic and
   converges to the same factors;
3. a serving layer under injected broker loss degrades writes to 503
   (circuit breaker) and recovers via the half-open probe without a
   restart.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_MODEL, KEY_MODEL_REF, KEY_UP, KeyMessage
from oryx_tpu.kafka.client import KafkaBroker
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.kafka.mini_broker import MiniKafkaBroker
from oryx_tpu.lambda_rt import data_store
from oryx_tpu.lambda_rt.batch import BatchLayer
from oryx_tpu.lambda_rt.speed import SpeedLayer
from oryx_tpu.lambda_rt.serving import ServingLayer
from oryx_tpu.resilience import faults
from oryx_tpu.resilience.policy import (Backoff, Deadline, Supervisor,
                                        resilience_snapshot)

pytestmark = pytest.mark.chaos

BATCH_GROUP = "OryxGroup-BatchLayer-it"
SPEED_GROUP = "OryxGroup-SpeedLayer-it"


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _base_config(tmp_path, broker_name, **extra):
    overlay = {
        "oryx.id": "it",
        "oryx.input-topic.broker": f"memory://{broker_name}",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "ItInput",
        "oryx.update-topic.broker": f"memory://{broker_name}",
        "oryx.update-topic.message.topic": "ItUpdate",
        "oryx.batch.update-class": "oryx_tpu.app.als.update.ALSUpdate",
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.als.speed.ALSSpeedModelManager",
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.als.iterations": 3,
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": 3,
        "oryx.ml.eval.test-fraction": 0.0,
        # fast-failing policies so chaos runs stay inside the tier-1
        # budget: single-digit-ms backoffs, 1 ms breaker reset
        "oryx.resilience.retry.max-attempts": 2,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
        "oryx.resilience.breaker.failure-threshold": 2,
        "oryx.resilience.breaker.reset-timeout-ms": 1,
    }
    overlay.update(extra)
    return from_dict(overlay)


def _produce_ratings(broker, topic, nu=20, ni=12, seed=5):
    rng = np.random.default_rng(seed)
    t = 1_700_000_000_000
    n = 0
    for u in range(nu):
        for i in range(ni):
            if rng.random() < 0.4:
                broker.send(topic, None,
                            f"u{u},i{i},{rng.exponential(1):.2f},{t}")
                t += 1000
                n += 1
    return n


def _drain(broker, topic):
    return list(broker.consume(topic, from_beginning=True,
                               max_idle_sec=0.2))


def _replay_into(manager, broker, topic="ItUpdate"):
    """Synchronously replay the update topic from offset 0 into a model
    manager — the layers' consume thread minus the thread, so tests
    need no polling at all."""
    manager.consume(broker.consume(topic, from_beginning=True,
                                   max_idle_sec=0.3))


# -- scenario 1: batch crash between generation save and offset commit -------

def test_batch_crash_between_save_and_commit_does_not_duplicate(tmp_path):
    cfg = _base_config(tmp_path, "chaos1")
    broker = get_broker("chaos1")
    n = _produce_ratings(broker, "ItInput")

    faults.inject("batch-crash-before-commit", mode="crash", times=1)
    with pytest.raises(faults.InjectedCrash):
        BatchLayer(cfg).run_one_generation()
    assert faults.fired("batch-crash-before-commit") == 1

    # the kill left the dangerous intermediate state: model published,
    # generation durable, offsets NOT committed — the exact window
    # where naive recovery reads the same records as new AND past
    assert sum(1 for m in _drain(broker, "ItUpdate")
               if m.key == KEY_MODEL) == 1
    assert len(data_store.read_all_data(str(tmp_path / "data"))) == n
    assert broker.get_offset(BATCH_GROUP, "ItInput") is None

    # "restart": a fresh layer recovers the interrupted commit from the
    # generation file's offsets header, then rebuilds from past data
    BatchLayer(cfg).run_one_generation()

    # no input duplication: still exactly n stored records, offsets
    # advanced to the saved generation's ends, and the retried
    # generation published its own model (at-least-once publish)
    assert len(data_store.read_all_data(str(tmp_path / "data"))) == n
    assert broker.get_offsets(BATCH_GROUP, "ItInput") == [n]
    assert sum(1 for m in _drain(broker, "ItUpdate")
               if m.key == KEY_MODEL) == 2


def test_batch_crash_before_save_reprocesses_same_input(tmp_path):
    cfg = _base_config(tmp_path, "chaos1b")
    broker = get_broker("chaos1b")
    n = _produce_ratings(broker, "ItInput")

    faults.inject("batch-crash-after-update", mode="crash", times=1)
    with pytest.raises(faults.InjectedCrash):
        BatchLayer(cfg).run_one_generation()
    # model published but nothing durable: neither data nor offsets
    assert data_store.read_all_data(str(tmp_path / "data")) == []
    assert broker.get_offset(BATCH_GROUP, "ItInput") is None

    BatchLayer(cfg).run_one_generation()
    # the retry saw exactly the same (new, past) split: one generation
    # file with the full input, no double counting
    assert len(data_store.read_all_data(str(tmp_path / "data"))) == n
    assert broker.get_offsets(BATCH_GROUP, "ItInput") == [n]


def test_batch_crash_after_commit_loses_nothing(tmp_path):
    cfg = _base_config(tmp_path, "chaos1c")
    broker = get_broker("chaos1c")
    n = _produce_ratings(broker, "ItInput")

    faults.inject("batch-crash-after-commit", mode="crash", times=1)
    with pytest.raises(faults.InjectedCrash):
        BatchLayer(cfg).run_one_generation()
    # the generation fully completed before the kill
    assert broker.get_offsets(BATCH_GROUP, "ItInput") == [n]

    # restart: nothing new, the rebuild runs purely from past data
    BatchLayer(cfg).run_one_generation()
    assert len(data_store.read_all_data(str(tmp_path / "data"))) == n


# -- scenario 2: speed layer restart replays the topic and converges ---------

def test_speed_restart_replays_update_topic_and_converges(tmp_path):
    cfg = _base_config(tmp_path, "chaos2")
    broker = get_broker("chaos2")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()

    # first "process": build state from replay, then fold in a
    # mid-stream micro-batch whose deltas land on the update topic
    speed1 = SpeedLayer(cfg)
    _replay_into(speed1.model_manager, broker)
    m1 = speed1.model_manager.model
    assert m1 is not None and m1.get_fraction_loaded() >= 0.8
    broker.send("ItInput", None, "u0,i1,3.0,1800000000000")
    broker.send("ItInput", None, "newuser,i2,1.0,1800000000001")
    speed1.run_one_micro_batch()
    ups = [m for m in _drain(broker, "ItUpdate") if m.key == KEY_UP
           and json.loads(m.message)[1] == "newuser"]
    assert ups, "micro-batch published no delta for the new user"

    # catch speed1 up with its own published deltas (its tailing
    # consume thread would have done this live), giving the reference
    # state a never-killed layer would hold
    _replay_into(speed1.model_manager, broker)
    ref = speed1.model_manager.model

    # kill + restart: a FRESH layer must converge to identical factors
    # from nothing but the update-topic replay
    speed2 = SpeedLayer(cfg)
    _replay_into(speed2.model_manager, broker)
    got = speed2.model_manager.model
    assert got is not None

    assert sorted(got.X.all_ids()) == sorted(ref.X.all_ids())
    assert sorted(got.Y.all_ids()) == sorted(ref.Y.all_ids())
    assert "newuser" in got.X.all_ids()
    for uid in ref.X.all_ids():
        np.testing.assert_allclose(got.get_user_vector(uid),
                                   ref.get_user_vector(uid), rtol=1e-6)
    for iid in ref.Y.all_ids():
        np.testing.assert_allclose(got.get_item_vector(iid),
                                   ref.get_item_vector(iid), rtol=1e-6)


def test_speed_publish_failure_does_not_advance_offsets(tmp_path):
    # satellite: an UP-publish failure must surface and must NOT commit
    # the micro-batch's offsets — the batch redelivers in full
    cfg = _base_config(tmp_path, "chaos2b")
    broker = get_broker("chaos2b")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()

    speed = SpeedLayer(cfg)
    _replay_into(speed.model_manager, broker)
    committed_before = broker.get_offsets(SPEED_GROUP, "ItInput")
    update_end_before = broker.latest_offset("ItUpdate")
    broker.send("ItInput", None, "u1,i2,2.0,1800000000000")

    faults.inject("speed-publish", mode="error", times=1)
    with pytest.raises(faults.InjectedFault):
        speed.run_one_micro_batch()
    assert broker.get_offsets(SPEED_GROUP, "ItInput") == committed_before

    # the retry (here: the next micro-batch) redelivers and commits
    speed.run_one_micro_batch()
    assert broker.latest_offset("ItUpdate") > update_end_before
    ends = broker.latest_offsets("ItInput")
    assert broker.get_offsets(SPEED_GROUP, "ItInput") == ends


# -- scenario 3: serving degrades writes to 503 and recovers -----------------

def _post(port, path, body):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=body.encode(), method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status
    except urllib.error.HTTPError as e:
        return e.code


def _get_json(port, path, headers=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _await_model(serving):
    deadline = Deadline.after(15.0)
    while not deadline.expired:
        model = serving.model_manager.get_model()
        if model is not None and model.get_fraction_loaded() >= 0.8:
            return model
        time.sleep(0.02)
    raise AssertionError("serving model never loaded")


def test_serving_degrades_to_503_and_recovers_without_restart(tmp_path):
    cfg = _base_config(
        tmp_path, "chaos3",
        **{"oryx.resilience.breaker.reset-timeout-ms": 1000})
    broker = get_broker("chaos3")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()

    serving = ServingLayer(cfg, port=0)
    serving.start()
    try:
        model = _await_model(serving)
        uid = model.all_user_ids()[0]
        # deterministic time: the test, not the wall clock, decides
        # when the breaker's reset timeout has elapsed
        clock = _FakeClock()
        serving.input_breaker._clock = clock
        # healthy: writes land, reads answer
        assert _post(serving.port, "/ingest", "u0,i0,1.0") == 200

        # broker loss: every send fails until cleared
        faults.inject("inproc-send", mode="error", times=None)
        # retries exhaust -> 503; enough failures open the breaker
        # (failure-threshold = 2 in this config)
        assert _post(serving.port, "/ingest", "u0,i1,1.0") == 503
        assert _post(serving.port, "/ingest", "u0,i2,1.0") == 503
        snap = _get_json(serving.port, "/metrics")
        assert snap["resilience"]["serving-input"]["state"] == "open"
        # open circuit sheds instantly — the broker is not even tried
        # (injected time stands still, so no probe is admitted)
        fired_before = faults.fired("inproc-send")
        assert _post(serving.port, "/ingest", "u0,i3,1.0") == 503
        assert faults.fired("inproc-send") == fired_before
        assert _get_json(serving.port, "/metrics")[
            "resilience"]["serving-input"]["rejected"] >= 1
        # reads degrade gracefully: the in-memory model still serves
        recs = _get_json(serving.port, f"/recommend/{uid}")
        assert recs and "id" in recs[0]

        # broker back + reset timeout elapsed: the half-open probe
        # closes the circuit — service recovers with NO restart
        faults.clear("inproc-send")
        clock.t += 2.0
        assert _post(serving.port, "/ingest", "u0,i4,1.0") == 200
        snap = _get_json(serving.port, "/metrics")
        assert snap["resilience"]["serving-input"]["state"] == "closed"
        assert snap["resilience"]["serving-input"]["opens"] >= 1
        retry_stats = snap["resilience"]["serving-input-send"]
        assert retry_stats["retries"] >= 1  # backoff retries really ran
    finally:
        serving.close()


def test_request_deadline_sheds_expired_work_as_503(tmp_path):
    cfg = _base_config(tmp_path, "chaos3b")
    broker = get_broker("chaos3b")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()

    serving = ServingLayer(cfg, port=0)
    serving.start()
    try:
        model = _await_model(serving)
        uid = model.all_user_ids()[0]
        # a zero budget is expired on arrival: refused before queueing
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get_json(serving.port, f"/recommend/{uid}",
                      headers={"X-Deadline-Ms": "0"})
        assert exc.value.code == 503
        assert serving.top_n_batcher.stats()["deadline_rejects"] >= 1
        # an ample budget answers normally
        recs = _get_json(serving.port, f"/recommend/{uid}",
                         headers={"X-Deadline-Ms": "10000"})
        assert recs and "id" in recs[0]
    finally:
        serving.close()


# -- model integrity: corrupt/truncated MODEL-REF artifacts ------------------

def test_corrupt_model_ref_degrades_to_503_and_recovers(tmp_path):
    """The ISSUE 2 integrity scenario: a corrupt MODEL-REF artifact
    (driven deterministically through the ``store-corrupt-model`` fault
    point) must take the consumer's clean error path — no dead consume
    thread, no resubscribe storm — leaving serving gated at 503, and
    the NEXT published generation must restore service with no
    restart."""
    cfg = _base_config(
        tmp_path, "chaos7",
        # force overflow-by-reference publishing: the model travels as
        # a MODEL-REF path into the shared store, the integrity surface
        # under test
        **{"oryx.update-topic.message.max-size": 100})
    broker = get_broker("chaos7")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()
    refs = [m for m in _drain(broker, "ItUpdate") if m.key == KEY_MODEL_REF]
    assert len(refs) == 1, "expected an overflowed MODEL-REF publish"

    faults.inject("store-corrupt-model", mode="error", times=1)
    serving = ServingLayer(cfg, port=0)
    serving.start()
    try:
        # replay hits the injected corruption: rejected and counted
        deadline = Deadline.after(15.0)
        while serving.model_manager.rejected_models < 1 \
                and not deadline.expired:
            time.sleep(0.02)
        assert serving.model_manager.rejected_models >= 1
        assert faults.fired("store-corrupt-model") == 1
        assert serving.model_manager.get_model() is None
        # reads gate at 503 — garbage was refused, not served
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get_json(serving.port, "/recommend/u0")
        assert exc.value.code == 503
        # the consumer survived its poison message (clean error path)
        assert serving._consume_thread.is_alive()
        # the refusal is operator-visible on /metrics
        snap = _get_json(serving.port, "/metrics")
        assert snap["model_integrity"]["rejected_models"] >= 1

        # recovery: the next generation republishes model + factors;
        # the fault is exhausted, so the ref loads and service returns
        # WITHOUT a serving restart
        BatchLayer(cfg).run_one_generation()
        model = _await_model(serving)
        uid = model.all_user_ids()[0]
        recs = _get_json(serving.port, f"/recommend/{uid}")
        assert recs and "id" in recs[0]
    finally:
        serving.close()


def test_truncated_model_artifact_is_rejected_not_fatal(tmp_path):
    """A REAL truncated artifact on disk (no injection): the speed
    consumer must reject it and keep the model it already has."""
    cfg = _base_config(tmp_path, "chaos8")
    broker = get_broker("chaos8")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()

    speed = SpeedLayer(cfg)
    _replay_into(speed.model_manager, broker)
    model_before = speed.model_manager.model
    assert model_before is not None
    users_before = sorted(model_before.X.all_ids())

    published = [d for d in os.listdir(tmp_path / "model") if d.isdigit()]
    src = tmp_path / "model" / published[0] / "model.pmml.xml"
    content = src.read_bytes()
    trunc = tmp_path / "model" / "truncated.pmml.xml"
    trunc.write_bytes(content[:len(content) // 2])
    broker.send("ItUpdate", KEY_MODEL_REF, str(trunc))

    _replay_into(speed.model_manager, broker)
    assert speed.model_manager.rejected_models >= 1
    model = speed.model_manager.model
    assert model is not None
    assert sorted(model.X.all_ids()) == users_before


def test_nonfinite_up_message_is_rejected(tmp_path):
    """A NaN-bearing UP payload (JSON NaN is representable) must be
    refused at the consumer trust boundary, never folded into factors."""
    cfg = _base_config(tmp_path, "chaos9")
    broker = get_broker("chaos9")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()

    speed = SpeedLayer(cfg)
    _replay_into(speed.model_manager, broker)
    manager = speed.model_manager
    uid = sorted(manager.model.X.all_ids())[0]
    before = manager.model.get_user_vector(uid).copy()

    manager.consume_key_message(KEY_UP, f'["X", "{uid}", [NaN, NaN, NaN]]')
    manager.consume_key_message(KEY_UP, '["X", "u0", "not-a-vector"]')
    manager.consume_key_message(KEY_UP, "{corrupt json")
    # a JSON *object* indexes by key (KeyError class), and a finite but
    # wrong-dimension vector would broadcast-corrupt the factor row
    manager.consume_key_message(KEY_UP, '{"a": 1}')
    manager.consume_key_message(KEY_UP, f'["X", "{uid}", [0.5]]')
    assert manager.rejected_updates == 5
    vec = manager.model.get_user_vector(uid)
    np.testing.assert_array_equal(vec, before)
    assert np.all(np.isfinite(vec))


# -- supervised restart of a crashed layer thread ----------------------------

@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_supervisor_restarts_crashed_batch_layer(tmp_path):
    cfg = _base_config(
        tmp_path, "chaos4",
        **{"oryx.batch.streaming.generation-interval-sec": 1})
    broker = get_broker("chaos4")
    n = _produce_ratings(broker, "ItInput")

    # generation 1 crashes mid-flight (nothing durable); the supervisor
    # must rebuild the layer, whose retried generation then commits
    faults.inject("batch-crash-after-update", mode="crash", times=1)
    sup = Supervisor(lambda: BatchLayer(cfg), "batch", max_restarts=3,
                     backoff=Backoff(0.01, 0.02, jitter=0.0))
    runner = threading.Thread(target=sup.run, daemon=True)
    runner.start()
    try:
        deadline = Deadline.after(60.0)
        while not deadline.expired:
            if broker.get_offsets(BATCH_GROUP, "ItInput") == [n]:
                break
            time.sleep(0.05)
        assert broker.get_offsets(BATCH_GROUP, "ItInput") == [n]
        assert sup.restarts >= 1
        assert faults.fired("batch-crash-after-update") == 1
    finally:
        sup.stop()
        if sup.layer is not None:
            sup.layer.close()
        runner.join(15.0)
    assert not runner.is_alive()


# -- config-staged chaos (oryx.resilience.faults.*) --------------------------

def test_config_staged_fault_arms_through_layer_construction(tmp_path):
    cfg = _base_config(
        tmp_path, "chaos5",
        **{"oryx.resilience.faults.inproc-read.mode": "error",
           "oryx.resilience.faults.inproc-read.times": 1})
    broker = get_broker("chaos5")
    _produce_ratings(broker, "ItInput")
    layer = BatchLayer(cfg)  # construction arms the config's faults
    with pytest.raises(faults.InjectedFault):
        layer.run_one_generation()
    # the fault disarmed after one activation: the retry generation
    # drains the same range (nothing was committed past it)
    layer.run_one_generation()
    assert broker.get_offsets(BATCH_GROUP, "ItInput") == \
        [broker.latest_offset("ItInput")]


# -- wire transport under connection loss / transient broker errors ----------

def test_wire_client_retries_through_connection_drop():
    mini = MiniKafkaBroker()
    try:
        kb = KafkaBroker(mini.bootstrap)
        kb.create_topic("wt1", 1)
        kb.send("wt1", "k", "v0")
        # connection dies before the next request is written
        faults.inject("wire-send", mode="error", times=1)
        kb.send("wt1", "k", "v1")
        assert faults.fired("wire-send") == 1
        assert kb.latest_offset("wt1") == 2
        got = [km.message for km in kb.read_range("wt1", 0, 2)]
        assert got == ["v0", "v1"]
        kb.close()
    finally:
        mini.close()


def test_wire_client_partial_read_redelivers_at_least_once():
    mini = MiniKafkaBroker()
    try:
        kb = KafkaBroker(mini.bootstrap)
        kb.create_topic("wt2", 1)
        # the connection dies mid-response AFTER the broker applied the
        # produce: the client cannot know, so the retry may append the
        # record again — duplication, never loss (at-least-once)
        faults.inject("wire-read", mode="drop", times=1)
        kb.send("wt2", "k", "v0")
        assert faults.fired("wire-read") == 1
        end = kb.latest_offset("wt2")
        assert end in (1, 2)
        values = {km.message for km in kb.read_range("wt2", 0, end)}
        assert values == {"v0"}  # present at least once, maybe twice
        kb.close()
    finally:
        mini.close()


def test_broker_transient_error_code_is_retried():
    mini = MiniKafkaBroker()
    try:
        kb = KafkaBroker(mini.bootstrap)
        kb.create_topic("wt3", 1)
        # broker answers REQUEST_TIMED_OUT once without appending; the
        # client's transient-code retry must succeed on attempt 2
        faults.inject("mini-broker-produce-error", mode="drop", times=1)
        kb.send("wt3", None, "v0")
        assert faults.fired("mini-broker-produce-error") == 1
        assert kb.latest_offset("wt3") == 1
        snap = resilience_snapshot()
        assert snap[f"kafka-client[{mini.bootstrap}]"]["retries"] >= 1
        kb.close()
    finally:
        mini.close()


def test_broker_dropping_connection_mid_request_is_survived():
    mini = MiniKafkaBroker()
    try:
        kb = KafkaBroker(mini.bootstrap)
        kb.create_topic("wt4", 1)
        # broker reads the request then dies without answering — the
        # ambiguous-outcome case (did the produce land?)
        faults.inject("mini-broker-drop", mode="drop", times=1)
        kb.send("wt4", None, "v0")
        assert faults.fired("mini-broker-drop") == 1
        end = kb.latest_offset("wt4")
        assert end >= 1
        assert {km.message for km in kb.read_range("wt4", 0, end)} \
            == {"v0"}
        kb.close()
    finally:
        mini.close()


# -- storage faults ----------------------------------------------------------

def test_store_rename_retries_transient_failure(tmp_path):
    faults.inject("store-rename", mode="error", times=1)
    path = data_store.save_generation(str(tmp_path / "d"), 1234,
                                      [KeyMessage("k", "m")])
    assert faults.fired("store-rename") == 1
    assert path is not None
    assert [km.message for km in
            data_store.read_all_data(str(tmp_path / "d"))] == ["m"]


def test_store_write_failure_surfaces_and_next_attempt_succeeds(tmp_path):
    faults.inject("store-write", mode="error", times=1)
    with pytest.raises(OSError):
        data_store.save_generation(str(tmp_path / "d"), 1234,
                                   [KeyMessage("k", "m")])
    # the layer's generation loop retries next interval; nothing stale
    # blocks the rewrite (idempotent save)
    data_store.save_generation(str(tmp_path / "d"), 1234,
                               [KeyMessage("k", "m")])
    assert [km.message for km in
            data_store.read_all_data(str(tmp_path / "d"))] == ["m"]


def test_generation_offsets_header_roundtrip(tmp_path):
    d = str(tmp_path / "d")
    assert data_store.last_saved_offsets(d) is None
    data_store.save_generation(d, 1000, [KeyMessage(None, "a")],
                               end_offsets={"T": [3]})
    data_store.save_generation(d, 2000, [KeyMessage(None, "b")],
                               end_offsets={"T": [7]})
    # newest generation wins; headers are invisible to data reads
    assert data_store.last_saved_offsets(d) == {"T": [7]}
    assert [km.message for km in data_store.read_all_data(d)] == \
        ["a", "b"]


# -- record headers through the resilient producer (ISSUE 11 satellite) ------
# The mirror's exactly-once-effective fence keys on the
# origin-region/origin-offset headers and the staleness gauges on `ts`:
# a RETRIED send that dropped or doubled them would silently break both.


def _headered_send_producer(broker_name):
    from oryx_tpu.kafka.inproc import InProcTopicProducer
    from oryx_tpu.resilience.policy import (Backoff,
                                            ResilientTopicProducer, Retry)
    return ResilientTopicProducer(
        InProcTopicProducer(f"memory://{broker_name}", "HdrT"),
        retry=Retry("t-hdr-send", max_attempts=3,
                    backoff=Backoff(0.001, 0.002, jitter=0.0)))


def test_headers_survive_injected_retry_exactly_once():
    broker = get_broker("hdr1")
    producer = _headered_send_producer("hdr1")
    headers = {"origin-region": "west", "origin-offset": "41",
               "ts": "1700000000000"}
    faults.inject("inproc-send", mode="error", times=1)
    producer.send(KEY_UP, '["X","u1",[1.0]]', headers=headers)
    assert faults.fired("inproc-send") == 1
    records = _drain(broker, "HdrT")
    # exactly one record landed (the failed attempt appended nothing)
    # and it carries EXACTLY the headers the caller attached
    assert len(records) == 1
    assert records[0].headers == headers


def test_headers_ride_every_copy_of_a_duplicated_delivery():
    # producer-retry duplication (the ambiguous-ack case): BOTH copies
    # must carry the full header set — a consumer deduping on
    # origin-offset sees the same identity twice and keeps one effect
    broker = get_broker("hdr2")
    producer = _headered_send_producer("hdr2")
    headers = {"origin-region": "west", "origin-offset": "7"}
    faults.inject("inproc-send", mode="duplicate", times=1)
    producer.send(KEY_UP, '["X","u2",[1.0]]', headers=headers)
    records = _drain(broker, "HdrT")
    assert len(records) == 2
    assert all(km.headers == headers for km in records)
    assert len({km.headers["origin-offset"] for km in records}) == 1


def test_headerless_send_still_works_through_retry():
    # the widened send signature must stay optional end to end: a
    # header-free payload retried through the same producer lands with
    # headers absent, not {}-polluted
    broker = get_broker("hdr3")
    producer = _headered_send_producer("hdr3")
    faults.inject("inproc-send", mode="error", times=1)
    producer.send(KEY_UP, '["X","u3",[1.0]]')
    records = _drain(broker, "HdrT")
    assert len(records) == 1
    assert records[0].headers is None


# -- delivery under injected duplication -------------------------------------

def test_duplicated_delivery_is_absorbed_by_batch_idempotence(tmp_path):
    # producer-retry duplication on the input topic: the batch layer
    # must still converge (ALS aggregates duplicate events; the store
    # keeps whatever the topic held — at-least-once, loss-free)
    cfg = _base_config(tmp_path, "chaos6")
    broker = get_broker("chaos6")
    faults.inject("inproc-send", mode="duplicate", times=2)
    n = _produce_ratings(broker, "ItInput", nu=10, ni=8)
    total = broker.latest_offset("ItInput")
    assert total == n + 2  # two records were delivered twice
    BatchLayer(cfg).run_one_generation()
    assert broker.get_offsets(BATCH_GROUP, "ItInput") == [total]
    assert sum(1 for m in _drain(broker, "ItUpdate")
               if m.key == KEY_MODEL) == 1
