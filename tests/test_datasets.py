"""MovieLens-format dataset adapter (bench/datasets.py): real files
consumed when present, synthetic fallback otherwise (VERDICT r3 weak
#5 — no adapter existed that could consume real MovieLens files)."""

import os

import numpy as np

from oryx_tpu.bench.datasets import load_movielens, movielens_or_synthetic


def test_loads_ml20m_style_csv(tmp_path):
    (tmp_path / "ratings.csv").write_text(
        "userId,movieId,rating,timestamp\n"
        "3,10,4.5,111\n7,10,2.0,112\n3,99,5.0,113\n")
    users, items, values, uids, iids = load_movielens(str(tmp_path))
    assert uids == ["3", "7"] and iids == ["10", "99"]
    assert users.tolist() == [0, 1, 0] and items.tolist() == [0, 0, 1]
    assert values.tolist() == [4.5, 2.0, 5.0]


def test_loads_ml1m_style_dat(tmp_path):
    p = tmp_path / "ratings.dat"
    p.write_text("1::20::3.5::900\n2::20::1.0::901\n")
    users, items, values, uids, iids = load_movielens(str(p))
    assert values.tolist() == [3.5, 1.0]
    assert iids == ["20"]


def test_env_guard_selects_real_data(tmp_path, monkeypatch):
    (tmp_path / "ratings.csv").write_text(
        "userId,movieId,rating,timestamp\n1,2,3.0,4\n")
    monkeypatch.setenv("ORYX_ML_DATA", str(tmp_path))
    users, items, values, uids, iids, source = \
        movielens_or_synthetic(None, n_ratings=1000)
    assert source == str(tmp_path)
    assert values.tolist() == [3.0]


def test_synthetic_fallback(monkeypatch):
    monkeypatch.delenv("ORYX_ML_DATA", raising=False)
    users, items, values, uids, iids, source = \
        movielens_or_synthetic(None, n_ratings=5000, seed=3)
    assert source.startswith("synthetic")
    assert len(users) == len(items) == len(values)
    assert np.isfinite(values).all()
