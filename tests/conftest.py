"""Test configuration: force JAX onto a virtual 8-device CPU platform so
sharding/mesh tests run anywhere, and make all randomness deterministic
(reference test strategy: OryxTest.java:38 + RandomManager.useTestSeed)."""

import os

# XLA_FLAGS must be in the env before the CPU backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The environment may have imported jax at interpreter startup (site
# customization registering a real accelerator plugin), in which case
# jax captured JAX_PLATFORMS before we could set it. config.update wins
# regardless of import order; tests must never touch real hardware.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from oryx_tpu.common.rand import RandomManager  # noqa: E402


@pytest.fixture(autouse=True)
def _test_seed():
    RandomManager.use_test_seed()
    yield
