"""Tier-1 unit tests for oryx_tpu.common (reference analogs:
ConfigUtilsTest, TextUtilsTest, RandomManagerTest, ExecUtilsTest,
AutoReadWriteLockTest, DoubleWeightedMeanTest, IOUtilsTest)."""

import threading
import time

import pytest

from oryx_tpu.common import hocon, io_utils, lang, text
from oryx_tpu.common.config import get_default, overlay_on
from oryx_tpu.common.rand import RandomManager
from oryx_tpu.common.stats import DoubleWeightedMean


# -- hocon / config ---------------------------------------------------------

def test_hocon_basic():
    d = hocon.loads("""
    a = 1
    b { c = "x", d = [1, 2, 3] }
    b.e = true
    f = null
    # comment
    g = 1.5 // other comment
    """)
    assert d == {"a": 1, "b": {"c": "x", "d": [1, 2, 3], "e": True},
                 "f": None, "g": 1.5}


def test_hocon_substitution():
    d = hocon.loads("base = { x = 1 }\nother = { config = ${base} }")
    assert d["other"]["config"] == {"x": 1}


def test_hocon_merge_nested():
    base = hocon.loads("a { b = 1\n c = 2 }")
    over = hocon.loads("a { c = 3 }")
    assert hocon.merge(base, over) == {"a": {"b": 1, "c": 3}}


def test_default_config_key_surface():
    cfg = get_default()
    # spot-check the full reference key surface
    assert cfg.get_string("oryx.input-topic.message.topic") == "OryxInput"
    assert cfg.get_string("oryx.update-topic.message.topic") == "OryxUpdate"
    assert cfg.get_int("oryx.update-topic.message.max-size") == 16777216
    assert cfg.get_int("oryx.batch.streaming.generation-interval-sec") == 21600
    assert cfg.get_int("oryx.speed.streaming.generation-interval-sec") == 10
    assert cfg.get_double("oryx.serving.min-model-load-fraction") == 0.8
    assert cfg.get_double("oryx.ml.eval.test-fraction") == 0.1
    assert cfg.get_optional_string("oryx.batch.update-class") is None
    assert cfg.get_int("oryx.als.hyperparams.features") == 10
    assert cfg.get_bool("oryx.als.implicit") is True
    assert cfg.get_string("oryx.kmeans.initialization-strategy") == "k-means||"
    assert cfg.get_string("oryx.rdf.hyperparams.impurity") == "entropy"
    # substitution carried streaming config through
    assert cfg.get("oryx.batch.streaming.config.jax.matrix-dtype") == "float32"


def test_overlay_and_serialize():
    cfg = overlay_on({"oryx.als.hyperparams.features": 42}, get_default())
    assert cfg.get_int("oryx.als.hyperparams.features") == 42
    rt = type(cfg).deserialize(cfg.serialize())
    assert rt.get_int("oryx.als.hyperparams.features") == 42


def test_pretty_print_redacts_password():
    cfg = overlay_on({"oryx.serving.api.password": "hunter2"}, get_default())
    assert "hunter2" not in cfg.pretty_print()
    assert "*****" in cfg.pretty_print()


def test_user_conf_substitutes_base_keys(tmp_path):
    # Typesafe Config resolves substitutions after merge: user files may
    # reference keys defined only in the packaged defaults
    p = tmp_path / "user.conf"
    p.write_text("oryx.speed.streaming.config = ${oryx.default-streaming-config}\n")
    from oryx_tpu.common.config import from_file
    cfg = from_file(str(p))
    assert cfg.get_bool("oryx.speed.streaming.config.jax.donate-buffers") is True


def test_config_mutation_isolated_from_defaults():
    from oryx_tpu.common.config import from_dict
    d2 = from_dict({"oryx.als.iterations": 99})
    d2.as_dict()["oryx"]["als"]["hyperparams"]["features"] = 777
    assert get_default().get("oryx.als.hyperparams.features") == 10


def test_properties_render_hocon_booleans():
    assert get_default().to_properties()["oryx.als.implicit"] == "true"


def test_typed_getters_raise():
    cfg = get_default()
    with pytest.raises(KeyError):
        cfg.get("oryx.nope")
    with pytest.raises(TypeError):
        cfg.get_int("oryx.input-topic.message.topic")


# -- text -------------------------------------------------------------------

def test_csv_roundtrip():
    row = ["a", "b with, comma", 'quote"inside', "1.5"]
    line = text.join_delimited(row)
    assert text.parse_delimited(line) == row


def test_parse_delimited_simple():
    assert text.parse_delimited("a,b,c") == ["a", "b", "c"]
    assert text.parse_delimited("a,,c") == ["a", "", "c"]


def test_join_json_and_parse():
    line = text.join_json(["X", "user1", [0.5, -1.25], ["item1"]])
    assert line == '["X","user1",[0.5,-1.25],["item1"]]'
    parsed = text.parse_json_array(line)
    assert parsed[1] == "user1"
    assert parsed[2] == [0.5, -1.25]


def test_parse_input_line_json_or_csv():
    assert text.parse_input_line('["u","i","5",""]') == ["u", "i", "5", ""]
    assert text.parse_input_line("u,i,5,123") == ["u", "i", "5", "123"]


def test_pmml_delimited():
    assert text.parse_pmml_delimited('a "b c"  d') == ["a", "b c", "d"]
    assert text.join_pmml_delimited_numbers([1, -2.5]) == "1 -2.5"


def test_pmml_delimited_round_trips_special_tokens():
    for row in (["a", ""], ['"'], ["a b", 'c"d'], ["x"]):
        assert text.parse_pmml_delimited(text.join_pmml_delimited(row)) == row


def test_parse_input_line_null_is_empty():
    assert text.parse_input_line('["u","i",null,"123"]') == ["u", "i", "", "123"]


# -- random -----------------------------------------------------------------

def test_random_deterministic_under_test_seed():
    RandomManager.use_test_seed()
    a = RandomManager.random().random(5)
    b = RandomManager.random().random(5)
    assert (a == b).all()


# -- lang -------------------------------------------------------------------

def test_collect_in_parallel_order():
    out = lang.collect_in_parallel(10, lambda i: i * i, parallelism=4)
    assert out == [i * i for i in range(10)]


def test_load_class_and_instance():
    cls = lang.load_class("oryx_tpu.common.stats.DoubleWeightedMean")
    assert cls is DoubleWeightedMean
    inst = lang.load_instance("oryx_tpu.common.stats.DoubleWeightedMean")
    assert isinstance(inst, DoubleWeightedMean)


def test_auto_rw_lock():
    lock = lang.AutoReadWriteLock()
    state = []

    with lock.read():
        state.append("r")
    with lock.write():
        state.append("w")

    # a writer blocks until readers release
    entered = threading.Event()

    def writer():
        with lock.write():
            entered.set()

    with lock.read():
        t = threading.Thread(target=writer)
        t.start()
        assert not entered.wait(0.05)
    assert entered.wait(1.0)
    t.join()


def test_reentrant_read_with_waiting_writer():
    # nested read acquisition must not deadlock while a writer waits
    lock = lang.AutoReadWriteLock()
    done = threading.Event()

    def nested_reader():
        with lock.read():
            time.sleep(0.05)  # let the writer start waiting
            with lock.read():
                done.set()

    t1 = threading.Thread(target=nested_reader)
    t1.start()
    time.sleep(0.01)

    def writer():
        with lock.write():
            pass

    t2 = threading.Thread(target=writer)
    t2.start()
    assert done.wait(2.0), "nested read deadlocked behind waiting writer"
    t1.join(2.0)
    t2.join(2.0)


def test_load_instance_propagates_ctor_errors():
    with pytest.raises(ZeroDivisionError):
        lang.load_instance("tests.test_common._ExplodingPlugin", object())


class _ExplodingPlugin:
    def __init__(self, config=None):
        1 / 0


def test_collect_in_parallel_zero_parallelism():
    assert lang.collect_in_parallel(5, lambda i: i, parallelism=0) == list(range(5))


def test_rate_limit_check():
    check = lang.RateLimitCheck(1000.0)
    assert check.test() is True
    assert check.test() is False


# -- stats ------------------------------------------------------------------

def test_weighted_mean():
    m = DoubleWeightedMean()
    m.increment(1.0, 1.0)
    m.increment(3.0, 3.0)
    assert abs(m.result - 2.5) < 1e-12
    assert m.count == 2


# -- io ---------------------------------------------------------------------

def test_strip_scheme():
    assert io_utils.strip_scheme("file:/tmp/x") == "/tmp/x"
    assert io_utils.strip_scheme("file:///tmp/x") == "/tmp/x"
    assert io_utils.strip_scheme("/tmp/x") == "/tmp/x"


def test_choose_free_port():
    p = io_utils.choose_free_port()
    assert 0 < p < 65536


def test_compile_cache_enable_from_config(tmp_path, monkeypatch):
    import jax

    from oryx_tpu.common import compile_cache
    from oryx_tpu.common.config import from_dict

    prev = jax.config.jax_compilation_cache_dir
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    # JAX memoizes the cache instance at first use; earlier tests that
    # started layers may have initialized it at the default path
    from jax._src import compilation_cache as _cc

    _cc.reset_cache()
    try:
        cc = str(tmp_path / "cc")
        cfg = from_dict({"oryx.compile-cache-dir": cc,
                         "oryx.compile-cache-min-compile-secs": 0.0})
        assert compile_cache.enable_from_config(cfg) == cc
        assert jax.config.jax_compilation_cache_dir == cc
        # first configuration wins process-wide
        cfg2 = from_dict({"oryx.compile-cache-dir": "/elsewhere"})
        assert compile_cache.enable_from_config(cfg2) == cc
        # a compiled executable lands on disk
        f = jax.jit(lambda x: x * 2 + 1)
        assert float(f(jax.numpy.float32(3))) == 7.0
        import pathlib
        assert list(pathlib.Path(cc).iterdir())
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        _cc.reset_cache()


def test_compile_cache_disabled_when_null(monkeypatch):
    from oryx_tpu.common import compile_cache
    from oryx_tpu.common.config import from_dict

    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    cfg = from_dict({"oryx.compile-cache-dir": None})
    assert compile_cache.enable_from_config(cfg) is None
