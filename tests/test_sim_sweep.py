"""Seed sweeps + the seed-regression corpus (ISSUE 16 acceptance).

Tier-1 explores >=200 interleavings of EACH chaos scenario per CI
run — the whole point of the simulation refactor.  For scale: the
real-process ITs these sweeps cover explore exactly ONE interleaving
per run at ~11 s (elastic 2→3 reshard, test_elastic_it.py) and
~16 s (region partition/heal, test_region_it.py) apiece, while a sim
seed costs ~52 ms (reshard-cutover) / ~130 ms (mirror-partition) —
two-plus orders of magnitude per interleaving, far beyond the >=5x
the acceptance asks.  The real ITs are retained as single ``-m
slow`` smokes; tier-1 wall-clock stays inside its 870 s budget
(pre-simulation baseline 360 s with both real ITs tier-1).

Every sweep asserts a hard wall-clock budget in-test, and replay
determinism is asserted two ways: a sampled re-run of sweep seeds
must reproduce byte-identical trace hashes, and the pinned corpus in
tests/fixtures/sim_seeds.toml (seeds that exposed real bugs during
bring-up) runs green twice with hash equality every CI run.
"""

from __future__ import annotations

import os
import time

import pytest
import tomli

from oryx_tpu.sim import SimFailure, run_scenario

_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                        "sim_seeds.toml")

# tier-1 sweep shape: >=200 interleavings per scenario, each with a
# hard wall-clock ceiling (~4x the measured cost, so a perf
# regression that would blow the tier-1 budget fails HERE, named,
# not as a mysterious global slowdown)
_SWEEP_SEEDS = 200
_BUDGETS_SEC = {"mirror-partition": 120.0, "reshard-cutover": 90.0,
                "speed-shard-crash": 60.0, "ingest-overload": 60.0,
                "slo-page-flight": 90.0}
# seeds re-run after each sweep to assert trace-hash reproducibility
_REPLAY_SAMPLE = (0, 67, 133, 199)


def _corpus() -> list[dict]:
    with open(_FIXTURE, "rb") as fh:
        return tomli.load(fh)["seed"]


def _corpus_ids() -> list[str]:
    return [f"{e['scenario']}-{e['seed']}" for e in _corpus()]


@pytest.mark.parametrize("entry", _corpus(), ids=_corpus_ids())
def test_seed_regression_corpus(entry):
    """Each pinned (scenario, seed) once exposed a real bug; replay
    it twice — invariants must hold and the two trace hashes must be
    byte-identical (same seed, same trace)."""
    first = run_scenario(entry["scenario"], entry["seed"])
    second = run_scenario(entry["scenario"], entry["seed"])
    assert first.trace_hash == second.trace_hash, (
        f"nondeterministic replay of pinned seed {entry['seed']} "
        f"({entry['scenario']}): {first.trace_hash[:16]} != "
        f"{second.trace_hash[:16]}")
    assert first.steps == second.steps


def _sweep(scenario: str, seeds) -> dict[int, str]:
    hashes: dict[int, str] = {}
    for seed in seeds:
        try:
            hashes[seed] = run_scenario(scenario, seed).trace_hash
        except SimFailure as e:
            # the message IS the bug report: invariant, seed, trace
            # hash, and the one-line repro command
            pytest.fail(str(e), pytrace=False)
    return hashes


@pytest.mark.parametrize("scenario", sorted(_BUDGETS_SEC))
def test_sweep_200_interleavings(scenario):
    """>=200 seeded interleavings, all invariants green, inside a
    hard wall-clock budget; then a sampled replay must reproduce the
    sweep's exact trace hashes."""
    t0 = time.perf_counter()
    hashes = _sweep(scenario, range(_SWEEP_SEEDS))
    took = time.perf_counter() - t0
    budget = _BUDGETS_SEC[scenario]
    assert took < budget, (
        f"{scenario} sweep of {_SWEEP_SEEDS} seeds took {took:.1f}s "
        f"(budget {budget:.0f}s) — the simulation got too slow for "
        f"tier-1")
    assert len(hashes) == _SWEEP_SEEDS
    for seed in _REPLAY_SAMPLE:
        assert run_scenario(scenario, seed).trace_hash \
            == hashes[seed], f"seed {seed} did not replay its trace"


@pytest.mark.slow
@pytest.mark.parametrize("scenario", sorted(_BUDGETS_SEC))
def test_wide_sweep_1000_interleavings(scenario):
    """The wide sweep: a thousand interleavings per scenario, beyond
    the tier-1 200 — the nightly net for tail-seed bugs."""
    _sweep(scenario, range(_SWEEP_SEEDS, _SWEEP_SEEDS + 1000))
