"""Unit tests for the observability layer (oryx_tpu/obs/,
docs/OBSERVABILITY.md): traceparent propagation, sampling, the bounded
trace ring, mergeable fixed-bucket histograms, Prometheus text
exposition (golden-parsed by an in-test parser), MetricsRegistry
error-class split / gauges / concurrency, freshness helpers, and
record-header transport through the in-proc broker."""

from __future__ import annotations

import json
import re
import threading

import numpy as np
import pytest

from oryx_tpu.kafka.api import KeyMessage
from oryx_tpu.kafka.inproc import InProcBroker
from oryx_tpu.lambda_rt.metrics import MetricsRegistry, _RESERVOIR
from oryx_tpu.obs import freshness
from oryx_tpu.obs.prom import (LATENCY_BUCKETS_MS, Histogram,
                               bucket_quantile, merge_histograms,
                               merge_snapshots, render_openmetrics,
                               render_prometheus)
from oryx_tpu.obs.trace import (NOOP_SPAN, Tracer, format_traceparent,
                                parse_traceparent)
from oryx_tpu.resilience import faults


# -- traceparent --------------------------------------------------------------

def test_traceparent_roundtrip():
    tp = format_traceparent("ab" * 16, "cd" * 8, sampled=True)
    assert parse_traceparent(tp) == ("ab" * 16, "cd" * 8, True)
    tp0 = format_traceparent("ab" * 16, "cd" * 8, sampled=False)
    assert parse_traceparent(tp0) == ("ab" * 16, "cd" * 8, False)


@pytest.mark.parametrize("bad", [
    None, "", "00-short-bad-01", "zz-" + "a" * 32 + "-" + "b" * 16 + "-01",
    "00-" + "g" * 32 + "-" + "b" * 16 + "-01",      # non-hex trace id
    "00-" + "0" * 32 + "-" + "b" * 16 + "-01",      # all-zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",      # all-zero span id
    "00-" + "a" * 32 + "-" + "b" * 16 + "-01-extra",
])
def test_traceparent_malformed_starts_fresh(bad):
    # W3C processing model: malformed context is ignored, never an error
    assert parse_traceparent(bad) is None


# -- tracer sampling + ring ---------------------------------------------------

def test_unsampled_request_is_the_shared_noop_span():
    t = Tracer("svc", sample_ratio=0.0)
    span = t.begin_request("svc.request")
    assert span is NOOP_SPAN          # no allocation on the hot path
    assert t.span("svc.child") is NOOP_SPAN
    # ending a noop request records nothing
    t.end_request(span, status=200, route="r")
    assert t.traces_snapshot() == {}


def test_sampled_request_records_span_tree():
    t = Tracer("svc", sample_ratio=1.0)
    req = t.begin_request("svc.request")
    assert req.sampled
    with t.span("svc.child") as child:
        child.set_attr("k", 1)
        with t.span("svc.grandchild"):
            pass
    t.end_request(req, status=200, route="GET /x")
    traces = t.traces_snapshot()
    assert list(traces) == [req.trace_id]
    spans = traces[req.trace_id]
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"svc.request", "svc.child", "svc.grandchild"}
    # tree reconstructable from parent ids
    assert by_name["svc.request"]["parent_id"] is None
    assert by_name["svc.child"]["parent_id"] == req.span_id
    assert by_name["svc.grandchild"]["parent_id"] == \
        by_name["svc.child"]["span_id"]
    assert by_name["svc.child"]["attrs"] == {"k": 1}
    assert by_name["svc.request"]["attrs"]["http.status"] == 200


def test_inbound_sampled_context_is_continued():
    t = Tracer("svc", sample_ratio=0.0)  # local sampling would say no
    tp = format_traceparent("ab" * 16, "cd" * 8, sampled=True)
    span = t.begin_request("svc.request", tp)
    assert span.sampled
    assert span.trace_id == "ab" * 16
    assert span.parent_id == "cd" * 8
    t.end_request(span, status=200)
    # explicitly UNsampled inbound context is honored even at ratio 1.0
    t2 = Tracer("svc", sample_ratio=1.0)
    tp0 = format_traceparent("ab" * 16, "cd" * 8, sampled=False)
    assert t2.begin_request("svc.request", tp0) is NOOP_SPAN


def test_trace_ring_evicts_oldest():
    t = Tracer("svc", sample_ratio=1.0, max_traces=4)
    ids = []
    for _ in range(10):
        span = t.begin_request("svc.request")
        ids.append(span.trace_id)
        t.end_request(span, status=200)
    traces = t.traces_snapshot()
    assert list(traces) == ids[-4:]


def test_status_500_and_0_mark_span_error():
    t = Tracer("svc", sample_ratio=1.0)
    for status, want in ((200, "ok"), (404, "ok"), (500, "error"),
                         (0, "error")):
        span = t.begin_request("svc.request")
        t.end_request(span, status=status)
        spans = t.traces_snapshot()[span.trace_id]
        assert spans[0]["status"] == want, status


def test_record_span_retroactive():
    t = Tracer("svc", sample_ratio=1.0)
    t.record_span("serving.queue_wait", ("f" * 32, "e" * 16),
                  10.0, 10.25, {"batch_size": 3})
    spans = t.traces_snapshot()["f" * 32]
    assert spans[0]["duration_ms"] == pytest.approx(250.0)
    assert spans[0]["parent_id"] == "e" * 16
    # no context (unsampled) = no record, no error
    t.record_span("serving.queue_wait", None, 1.0, 2.0)


def test_trace_drop_fault_degrades_to_counter():
    """Chaos point obs-trace-drop: a raising recorder must not surface
    to the caller — the span call succeeds, the failure is counted."""
    t = Tracer("svc", sample_ratio=1.0)
    faults.clear()
    try:
        faults.inject("obs-trace-drop", mode="error", times=1)
        span = t.begin_request("svc.request")
        t.end_request(span, status=200)  # must NOT raise
        assert t.record_failures == 1
        assert faults.fired("obs-trace-drop") == 1
        assert t.traces_snapshot() == {}
    finally:
        faults.clear()


def test_child_span_for_cross_thread_fanout():
    t = Tracer("svc", sample_ratio=1.0)
    req = t.begin_request("svc.request")
    out = []

    def pool_thread():
        # thread-local current() does not follow — explicit parent does
        assert t.current() is NOOP_SPAN
        child = t.child_span(req, "router.shard_call")
        child.end()
        out.append(child)

    th = threading.Thread(target=pool_thread)
    th.start()
    th.join()
    t.end_request(req, status=200)
    assert out[0].parent_id == req.span_id
    assert t.child_span(None, "x") is NOOP_SPAN
    assert t.child_span(NOOP_SPAN, "x") is NOOP_SPAN


# -- histograms + merge -------------------------------------------------------

def test_histogram_bucket_boundaries():
    h = Histogram()
    h.observe(0.5)     # < 1 ms -> first bucket
    h.observe(1.0)     # == bound -> still le=1 (bisect_left)
    h.observe(1.5)
    h.observe(20000.0)  # past the last bound -> +Inf bucket
    snap = h.snapshot()
    assert snap["buckets"][0] == 2
    assert snap["buckets"][1] == 1
    assert snap["buckets"][-1] == 1
    assert snap["sum_ms"] == pytest.approx(20003.0)


def test_merge_histograms_is_exact_sum():
    rng = np.random.default_rng(7)
    parts = []
    everything = Histogram()
    for _ in range(3):
        h = Histogram()
        for ms in rng.exponential(30.0, 500):
            h.observe(float(ms))
            everything.observe(float(ms))
        parts.append(h.snapshot())
    merged = merge_histograms(parts)
    assert merged["buckets"] == everything.snapshot()["buckets"]
    assert merged["sum_ms"] == pytest.approx(
        everything.snapshot()["sum_ms"])


def test_merge_snapshots_routes_and_counters():
    a = {"routes": {"GET /r": {"count": 3, "client_errors": 1,
                               "server_errors": 0,
                               "latency_ms": {"buckets": [3] + [0] * 13,
                                              "sum_ms": 1.5}}},
         "counters": {"partial_answers": 2}}
    b = {"routes": {"GET /r": {"count": 2, "client_errors": 0,
                               "server_errors": 2,
                               "latency_ms": {"buckets": [0] * 13 + [2],
                                              "sum_ms": 40000.0}}},
         "counters": {"partial_answers": 1, "other": 5},
         "gauges": {"update_lag_records": 9}}   # gauges never merge
    m = merge_snapshots([a, b])
    r = m["routes"]["GET /r"]
    assert r["count"] == 5
    assert r["client_errors"] == 1 and r["server_errors"] == 2
    assert r["latency_ms"]["buckets"][0] == 3
    assert r["latency_ms"]["buckets"][-1] == 2
    assert m["counters"] == {"other": 5, "partial_answers": 3}
    assert "gauges" not in m


# -- Prometheus text exposition ----------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(\{(?P<labels>.*)\})? (?P<value>\S+)$")


def _parse_prometheus(text: str) -> list[tuple[str, dict, float]]:
    """Tiny text-format (0.0.4) parser: [(name, labels, value)]."""
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = {}
        if m.group("labels"):
            for part in re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"',
                                   m.group("labels")):
                labels[part[0]] = part[1]
        out.append((m.group("name"), labels, float(m.group("value"))))
    return out


def test_render_prometheus_golden():
    reg = MetricsRegistry()
    reg.record("GET /recommend/{userID}", 200, 0.0105)
    reg.record("GET /recommend/{userID}", 200, 0.120)
    reg.record("GET /recommend/{userID}", 404, 0.0007)
    reg.record("GET /recommend/{userID}", 503, 30.0)
    reg.inc("partial_answers")
    reg.set_gauge("update_lag_records", 4)
    text = render_prometheus(reg.prometheus_snapshot(),
                             labels={"tier": "router"})
    samples = _parse_prometheus(text)
    by = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
    route = ("route", "GET /recommend/{userID}")
    tier = ("tier", "router")
    assert by[("oryx_requests_total", (route, tier))] == 4
    assert by[("oryx_request_errors_total",
               (("class", "client"), route, tier))] == 1
    assert by[("oryx_request_errors_total",
               (("class", "server"), route, tier))] == 1
    assert by[("oryx_partial_answers_total", (tier,))] == 1
    assert by[("oryx_update_lag_records", (tier,))] == 4
    # histogram: cumulative buckets, final bucket == count
    buckets = [(l["le"], v) for n, l, v in samples
               if n == "oryx_request_latency_ms_bucket"]
    values = [v for _, v in buckets]
    assert values == sorted(values)  # cumulative is monotone
    assert buckets[-1][0] == "+Inf"
    count = by[("oryx_request_latency_ms_count", (route, tier))]
    assert buckets[-1][1] == count == 4
    # bucket sums consistent with observations
    le_ms = {le: v for le, v in buckets}
    assert le_ms["1"] == 1         # the 0.7 ms 404
    assert le_ms["20"] == 2        # + the 10.5 ms hit
    assert le_ms["200"] == 3       # + the 120 ms hit
    assert le_ms["10000"] == 3     # the 30 s outlier is +Inf only
    assert by[("oryx_request_latency_ms_sum", (route, tier))] == \
        pytest.approx(0.7 + 10.5 + 120.0 + 30000.0, rel=1e-6)


def test_label_escaping():
    text = render_prometheus(
        {"routes": {}, "counters": {"c": 1}},
        labels={"tier": 'we"ird\\na\nme'})
    line = [ln for ln in text.splitlines()
            if ln.startswith("oryx_c_total")][0]
    assert '\\"' in line and "\\\\" in line and "\\n" in line
    assert "\n" not in line


# -- MetricsRegistry ----------------------------------------------------------

def test_error_class_split():
    reg = MetricsRegistry()
    for status in (200, 204, 301, 404, 451, 500, 503, 0):
        reg.record("GET /r", status, 0.001)
    snap = reg.snapshot()["GET /r"]
    assert snap["client_errors"] == 2            # 404, 451
    assert snap["server_errors"] == 3            # 500, 503, 0 (conn died)
    assert snap["errors"] == 5                   # back-compat total
    assert snap["count"] == 8


def test_gauges_snapshot_best_effort():
    reg = MetricsRegistry()
    reg.set_gauge("micro_batch_duration_ms", 12.5)
    reg.gauge_fn("update_lag_records", lambda: 7)

    def boom():
        raise RuntimeError("broker down")

    reg.gauge_fn("input_lag_records", boom)
    g = reg.gauges_snapshot()
    assert g["micro_batch_duration_ms"] == 12.5
    assert g["update_lag_records"] == 7
    assert g["input_lag_records"] is None        # raising fn = null


def test_reservoir_wraparound_percentiles():
    reg = MetricsRegistry()
    n = _RESERVOIR + 500
    # old slow values must be overwritten by the newest _RESERVOIR
    for i in range(n):
        ms = 1000.0 if i < 500 else 1.0
        reg.record("GET /r", 200, ms / 1000.0)
    snap = reg.snapshot()["GET /r"]
    assert snap["count"] == n
    assert snap["p99_ms"] == pytest.approx(1.0)  # the 1000s aged out


def test_registry_concurrent_record_inc_snapshot():
    reg = MetricsRegistry()
    threads_n, per_thread = 8, 2000
    stop = threading.Event()

    def writer(k):
        for i in range(per_thread):
            reg.record(f"GET /r{k % 2}", 200 if i % 10 else 500,
                       0.001 * (i % 7))
            reg.inc("partial_answers")
            reg.set_gauge("update_lag_records", i)

    def reader():
        while not stop.is_set():
            s = reg.snapshot()
            for r in s.values():
                # totals are internally consistent at every instant
                assert r["client_errors"] + r["server_errors"] \
                    <= r["count"]
            reg.prometheus_snapshot()
            reg.gauges_snapshot()

    writers = [threading.Thread(target=writer, args=(k,))
               for k in range(threads_n)]
    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    snap = reg.snapshot()
    total = sum(r["count"] for r in snap.values())
    assert total == threads_n * per_thread
    assert sum(r["server_errors"] for r in snap.values()) == \
        threads_n * (per_thread // 10)
    assert reg.counters_snapshot()["partial_answers"] == total
    prom = reg.prometheus_snapshot()
    for route, r in prom["routes"].items():
        assert sum(r["latency_ms"]["buckets"]) == r["count"]


# -- freshness helpers --------------------------------------------------------

def test_update_stream_tap_counts_and_model_age():
    tap = freshness.UpdateStreamTap()
    assert tap.model_age_sec() is None
    records = [KeyMessage("UP", "x"), KeyMessage("MODEL", "doc"),
               KeyMessage("UP", "y")]
    assert list(tap.wrap(iter(records))) == records
    assert tap.consumed == 3
    assert tap.model_age_sec() is not None
    # re-wrap resets the count (resubscribe replays from zero)
    assert list(tap.wrap(iter(records[:1]))) == records[:1]
    assert tap.consumed == 1


def test_oldest_ingest_ts():
    kms = [KeyMessage(None, "a", {"ts": "1000"}),
           KeyMessage(None, "b", {"ts": "500"}),
           KeyMessage(None, "c", None),
           KeyMessage(None, "d", {"ts": "junk"}),
           KeyMessage(None, "e", {"other": "1"})]
    assert freshness.oldest_ingest_ts_ms(kms) == 500
    assert freshness.oldest_ingest_ts_ms(kms[2:]) is None


# -- record headers through the in-proc broker --------------------------------

def test_inproc_broker_header_roundtrip():
    broker = InProcBroker()
    broker.send("T", "k", "m1", headers={"ts": "123",
                                         "traceparent": "00-x"})
    broker.send("T", "k", "m2")
    got = broker.read_ranges("T", [0], [2])
    assert got[0].headers == {"ts": "123", "traceparent": "00-x"}
    assert got[1].headers is None
    seen = []
    stop = threading.Event()
    for km in broker.consume("T", from_beginning=True, stop=stop):
        seen.append(km)
        if len(seen) == 2:
            stop.set()
    assert seen[0].headers == {"ts": "123", "traceparent": "00-x"}


def test_file_broker_headers_persist_and_old_logs_read_back(tmp_path):
    """Headers serialize as an optional third JSONL element; a log
    written by an older (two-element) process reads back unchanged."""
    old = tmp_path / "OldT.topic.jsonl"
    old.write_text(json.dumps(["k", "legacy"]) + "\n", encoding="utf-8")
    b = InProcBroker("obs-hdr-a", persist_dir=str(tmp_path))
    try:
        assert b.read_ranges("OldT", [0], [1])[0] == \
            KeyMessage("k", "legacy", None)
        b.send("OldT", "k", "new", headers={"ts": "9"})
        got = b.read_ranges("OldT", [0], [2])
        assert got[1].headers == {"ts": "9"}
    finally:
        b.close()
    # a fresh broker instance re-reads both record shapes from disk
    b2 = InProcBroker("obs-hdr-b", persist_dir=str(tmp_path))
    try:
        got = b2.read_ranges("OldT", [0], [2])
        assert got[0].headers is None
        assert got[1].headers == {"ts": "9"}
    finally:
        b2.close()


# -- exemplars (ISSUE 7 tentpole) --------------------------------------------

def test_histogram_exemplar_newest_wins_and_unsampled_costs_nothing():
    h = Histogram()
    h.observe(3.0)                      # unsampled: no exemplar dict
    assert h.exemplars is None
    h.observe(3.0, trace_id="aa" * 16)
    h.observe(3.5, trace_id="bb" * 16)  # same bucket: newest wins
    h.observe(30.0, trace_id="cc" * 16)
    snap = h.snapshot()
    i_3ms = 2       # (2, 5] ms bucket
    i_30ms = 5      # (20, 50] ms bucket
    assert snap["exemplars"][str(i_3ms)][0] == "bb" * 16
    assert snap["exemplars"][str(i_3ms)][1] == pytest.approx(3.5)
    assert snap["exemplars"][str(i_30ms)][0] == "cc" * 16
    # exemplar presence never perturbs the counts
    assert sum(snap["buckets"]) == 4


def test_merge_preserves_exemplars_newest_per_bucket():
    a, b = Histogram(), Histogram()
    a.observe(3.0, trace_id="aa" * 16)
    b.observe(3.0, trace_id="bb" * 16)
    b.observe(700.0, trace_id="dd" * 16)
    snap_a, snap_b = a.snapshot(), b.snapshot()
    # pin the wall-clock stamps (two in-test observes can land on the
    # same millisecond): b's exemplar is the newer one
    snap_a["exemplars"]["2"][2] = 1000.0
    snap_b["exemplars"]["2"][2] = 1000.5
    merged = merge_histograms([snap_a, snap_b])
    assert merged["exemplars"]["2"][0] == "bb" * 16
    assert merged["exemplars"]["9"][0] == "dd" * 16
    # order of inputs must not matter — newest TS wins, not last write
    assert merge_histograms([snap_b, snap_a])["exemplars"]["2"][0] \
        == "bb" * 16
    # and the counts merged exactly as before
    assert merged["buckets"][2] == 2
    # an exemplar-free merge has no exemplars key at all
    assert "exemplars" not in merge_histograms(
        [Histogram().snapshot(), Histogram().snapshot()])


def test_registry_record_threads_trace_id_into_exemplar():
    reg = MetricsRegistry()
    reg.record("GET /r", 200, 0.003, trace_id="ab" * 16)
    reg.record("GET /r", 200, 0.004)                # unsampled
    hist = reg.prometheus_snapshot()["routes"]["GET /r"]["latency_ms"]
    assert hist["exemplars"]["2"][0] == "ab" * 16
    # merge_snapshots keeps them (rides the router's cross-replica merge)
    merged = merge_snapshots([reg.prometheus_snapshot()])
    assert merged["routes"]["GET /r"]["latency_ms"]["exemplars"][
        "2"][0] == "ab" * 16


# -- OpenMetrics golden (in-test parser round-trips exemplars) ----------------

_OM_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(\{(?P<labels>.*?)\})? (?P<value>\S+)"
    r"(?: # \{(?P<exlabels>[^}]*)\} (?P<exvalue>\S+) (?P<exts>\S+))?$")


def _parse_openmetrics(text: str):
    """Tiny OpenMetrics parser: asserts the framing rules (one # EOF
    at the very end, counter TYPE lines without _total) and returns
    [(name, labels, value, exemplar|None)]."""
    lines = text.splitlines()
    assert lines[-1] == "# EOF", "exposition must end with # EOF"
    assert lines.count("# EOF") == 1
    out, types = [], {}
    for line in lines[:-1]:
        if line.startswith("# TYPE"):
            _, _, family, type_ = line.split()
            assert family not in types, f"duplicate TYPE for {family}"
            types[family] = type_
            if type_ == "counter":
                assert not family.endswith("_total"), \
                    "counter families are named without the _total suffix"
            continue
        if not line or line.startswith("#"):
            continue
        m = _OM_SAMPLE_RE.match(line)
        assert m, f"unparseable OpenMetrics line: {line!r}"
        labels = dict(re.findall(
            r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"', m.group("labels") or ""))
        exemplar = None
        if m.group("exlabels"):
            exlabels = dict(re.findall(
                r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"', m.group("exlabels")))
            exemplar = (exlabels, float(m.group("exvalue")),
                        float(m.group("exts")))
        out.append((m.group("name"), labels, float(m.group("value")),
                    exemplar))
    return out, types


def test_render_openmetrics_golden_roundtrips_exemplars():
    reg = MetricsRegistry()
    reg.record("GET /recommend/{userID}", 200, 0.0105,
               trace_id="ab" * 16)
    reg.record("GET /recommend/{userID}", 200, 0.120)
    reg.record("GET /recommend/{userID}", 503, 30.0,
               trace_id="cd" * 16)
    reg.inc("partial_answers")
    reg.set_gauge("update_lag_records", 4)
    text = render_openmetrics(reg.prometheus_snapshot(),
                              labels={"tier": "router"})
    samples, types = _parse_openmetrics(text)
    assert types["oryx_requests"] == "counter"
    assert types["oryx_partial_answers"] == "counter"
    assert types["oryx_update_lag_records"] == "gauge"
    assert types["oryx_request_latency_ms"] == "histogram"
    by = {(n, tuple(sorted(l.items()))): v
          for n, l, v, _ in samples}
    route = ("route", "GET /recommend/{userID}")
    tier = ("tier", "router")
    assert by[("oryx_requests_total", (route, tier))] == 3
    assert by[("oryx_partial_answers_total", (tier,))] == 1
    # buckets: cumulative, le canonical floats, +Inf last, count matches
    buckets = [(l["le"], v, ex) for n, l, v, ex in samples
               if n == "oryx_request_latency_ms_bucket"]
    values = [v for _, v, _ in buckets]
    assert values == sorted(values)
    assert buckets[-1][0] == "+Inf"
    assert buckets[-1][1] == 3
    assert all("." in le or le == "+Inf" for le, _, _ in buckets)
    # the two exemplars landed on their buckets and round-trip exactly
    exemplars = {le: ex for le, _, ex in buckets if ex is not None}
    le_10ms = repr(20.0)  # the 10.5 ms observation -> the (10, 20] bucket
    assert exemplars[le_10ms][0] == {"trace_id": "ab" * 16}
    assert exemplars[le_10ms][1] == pytest.approx(10.5)
    assert exemplars["+Inf"][0] == {"trace_id": "cd" * 16}
    assert exemplars["+Inf"][1] == pytest.approx(30000.0)
    # exemplar timestamps are recent unix seconds
    import time as _time
    assert abs(exemplars["+Inf"][2] - _time.time()) < 60.0


# -- bucket_quantile property tests (ISSUE 7 satellite) -----------------------

def _random_counts(rng):
    counts = [int(c) for c in rng.integers(0, 50,
                                           len(LATENCY_BUCKETS_MS) + 1)]
    if sum(counts) == 0:
        counts[rng.integers(0, len(counts))] = 1
    return counts


def test_bucket_quantile_monotone_in_q():
    rng = np.random.default_rng(42)
    for _ in range(50):
        counts = _random_counts(rng)
        qs = [0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99]
        vals = [bucket_quantile(counts, q) for q in qs]
        assert all(v is not None for v in vals)
        for lo, hi in zip(vals, vals[1:]):
            assert lo <= hi + 1e-9, (counts, vals)


def test_bucket_quantile_lands_in_target_bucket():
    rng = np.random.default_rng(43)
    for _ in range(50):
        counts = _random_counts(rng)
        total = sum(counts)
        for q in (0.1, 0.5, 0.9, 0.99):
            # the bucket the rank falls in, straight from the counts
            rank = q * total
            cum, target = 0, len(counts) - 1
            for i, c in enumerate(counts):
                cum += c
                if cum >= rank:
                    target = i
                    break
            v = bucket_quantile(counts, q)
            lo = 0.0 if target == 0 else LATENCY_BUCKETS_MS[target - 1]
            hi = LATENCY_BUCKETS_MS[min(target,
                                        len(LATENCY_BUCKETS_MS) - 1)]
            assert lo - 1e-9 <= v <= hi + 1e-9, \
                (counts, q, v, target)


def test_bucket_quantile_inf_bucket_reports_lower_bound():
    counts = [0] * len(LATENCY_BUCKETS_MS) + [7]
    # everything overflowed: nothing to interpolate toward, the +Inf
    # bucket reports its lower bound (the last finite bound)
    for q in (0.01, 0.5, 0.999):
        assert bucket_quantile(counts, q) == LATENCY_BUCKETS_MS[-1]


def test_bucket_quantile_all_zero_is_none():
    assert bucket_quantile([0] * (len(LATENCY_BUCKETS_MS) + 1),
                           0.99) is None
    assert bucket_quantile([], 0.99) is None


# -- review regressions -------------------------------------------------------

def test_unsampled_shard_hop_propagates_flags00_context():
    """The root's don't-sample decision must ride internal hops: the
    scatter transport sends a flags-00 traceparent for unsampled
    requests, and a downstream begin_request honors it instead of
    re-rolling its own sampling dice."""
    from oryx_tpu.obs.trace import unsampled_traceparent
    tp = unsampled_traceparent()
    parsed = parse_traceparent(tp)
    assert parsed is not None and parsed[2] is False
    downstream = Tracer("serving", sample_ratio=1.0)
    span = downstream.begin_request("serving.request", tp)
    assert span is NOOP_SPAN


def test_obs_server_gates_mutating_profile_route():
    """The side-door ObsServer honors read-only mode and DIGEST creds
    (oryx.serving.api.*) exactly like the main serving port — the
    mutating /admin/profile must not be an unauthenticated back door."""
    import urllib.error
    import urllib.request

    from oryx_tpu.common.config import from_dict
    from oryx_tpu.obs.server import ObsServer

    def probe(extra):
        cfg = from_dict({"oryx.obs.metrics-port": 0,
                         "oryx.obs.profile-dir": "/tmp/obs-gate", **extra})
        srv = ObsServer(cfg, MetricsRegistry(), None)
        srv.start()
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/admin/profile?ms=1",
                timeout=5)
        except urllib.error.HTTPError as e:
            return e.code
        finally:
            srv.close()
        return 200

    assert probe({"oryx.serving.api.read-only": True}) == 403
    assert probe({"oryx.serving.api.user-name": "u",
                  "oryx.serving.api.password": "p"}) == 401


def test_render_blocks_single_type_line_per_family():
    """The router's two-tier exposition must stay one valid 0.0.4
    payload: exactly one # TYPE line per metric name, with all of a
    family's samples contiguous behind it (strict parsers reject a
    second TYPE line for the same name)."""
    from oryx_tpu.obs.prom import render_prometheus_blocks
    reg_a, reg_b = MetricsRegistry(), MetricsRegistry()
    reg_a.record("GET /x", 200, 0.003)
    reg_a.inc("partial_answers")
    reg_b.record("GET /x", 200, 0.004)
    reg_b.record("GET /y", 500, 0.2)
    snap_b = reg_b.prometheus_snapshot()
    snap_b["gauges"] = {"scraped_replicas": 2}
    text = render_prometheus_blocks(
        [(reg_a.prometheus_snapshot(), {"tier": "router"}),
         (snap_b, {"tier": "replica"})])
    lines = text.splitlines()
    types = [ln for ln in lines if ln.startswith("# TYPE")]
    assert len(types) == len(set(types)), f"duplicate TYPE lines: {types}"
    # samples of each family form one contiguous group: every sample
    # line belongs to the family declared by the nearest TYPE above it
    current_family = None
    for ln in lines:
        if ln.startswith("# TYPE"):
            current_family = ln.split()[2]
            continue
        name = ln.split("{")[0].split(" ")[0]
        base = re.sub(r"_(bucket|sum|count)$", "", name) \
            if current_family == "oryx_request_latency_ms" else name
        assert base == current_family, (ln, current_family)
    # both tiers' samples made it into the shared families
    req_lines = [ln for ln in lines
                 if ln.startswith("oryx_requests_total")]
    assert any('tier="router"' in ln for ln in req_lines)
    assert any('tier="replica"' in ln for ln in req_lines)
