"""ISSUE 3 tier-1 coverage: int8 phase-A exactness at the shipped
f=50 shape (tie and retired-row edges included), the int8+fold mirror,
and the measured-cost kernel router (LSH auto-fallback under an
injected cost inflation).

All CPU-runnable: pallas kernels run in interpret mode; the router is
exercised with the injected-delay fault points it exposes for exactly
this purpose (kernel_router fires ``route-measure-lsh`` /
``route-measure-exact`` inside the timed region of each variant).
"""

from __future__ import annotations

import numpy as np
import pytest

from oryx_tpu.app.als.serving_model import ALSServingModel
from oryx_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clear_faults():
    faults.clear()
    yield
    faults.clear()


def _exact_sets_match(got_s, got_i, want_s, want_i):
    """Exact-top-N equality that is honest about ties: scores must be
    bit-identical position-by-position, ids must match wherever the
    score is untied, and each tied-score group must select the same id
    SET (lax.top_k breaks ties by index order, which differs between
    the flat scan's global order and phase B's gathered-block order —
    either way the returned items all genuinely share the kth score)."""
    np.testing.assert_array_equal(got_s, want_s)
    for b in range(got_s.shape[0]):
        gs, ws = got_s[b], want_s[b]
        start = 0
        while start < len(gs):
            end = start
            while end < len(gs) and gs[end] == gs[start]:
                end += 1
            assert set(got_i[b, start:end].tolist()) == \
                set(want_i[b, start:end].tolist()), (b, start, end)
            start = end


def _f50_fixture(seed: int, n: int = 4096, b: int = 8):
    """Lane-padded f=50 item matrix with deliberate tie and retired-row
    edges: a duplicated head row (guaranteed score tie inside the
    top-N) and retired rows salted through the head blocks."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    F, W = 50, 128
    Y = np.zeros((n, W), np.float32)
    Y[:, :F] = rng.standard_normal((n, F)).astype(np.float32)
    # tie edge: three identical copies of one strong row, spread across
    # different 128-row blocks so phase B's gather order differs from
    # the flat scan's index order
    strong = (3.0 * rng.standard_normal(F)).astype(np.float32)
    for idx in (7, 700, 2900):
        Y[idx, :F] = strong
    act = np.ones(n, bool)
    act[5::11] = False          # retired rows, including head blocks
    act[701] = False            # retired right next to a tie copy
    Q = np.zeros((b, W), np.float32)
    Q[:, :F] = rng.standard_normal((b, F)).astype(np.float32)
    Q[0, :F] = strong / np.linalg.norm(strong)  # aims at the tied rows
    return jnp.asarray(Y), jnp.asarray(Q), jnp.asarray(act), F, W


@pytest.mark.numerics
def test_int8_certificate_exact_at_f50_ties_and_retired():
    """int8 phase A + f32 rescore must return exactly the f32 exact
    top-N at the shipped f=50 shape — including score ties and retired
    rows — wherever the certificate passes, and retired rows must never
    appear."""
    import jax
    from oryx_tpu.app.als import serving_model as sm

    Y, Q, active, F, W = _f50_fixture(80)
    n, b = int(Y.shape[0]), int(Q.shape[0])
    bs, ksel, k = 128, 24, 8
    y8, sy_b, l1y_b = sm._quantize_items_kernel(Y, bs)
    pen_i = sm._penalty_kernel_i32(active, bs)
    old_tile = sm._PA_TILE
    sm._PA_TILE = 1024
    try:
        ts, ti, cert = jax.device_get(sm._batch_top_n_twophase_pallas_i8(
            Y, y8, sy_b, l1y_b, Q, pen_i, active, None, None,
            k=k, bs=bs, ksel=ksel, max_bits=0, interpret=True))
    finally:
        sm._PA_TILE = old_tile
    want_s, want_i = jax.device_get(
        sm._batch_top_n_kernel(Y, Q, active, k))
    ok = np.asarray(cert)
    assert ok.sum() >= b - 1, ok  # margin must not mass-fail certs
    _exact_sets_match(np.asarray(ts)[ok], np.asarray(ti)[ok],
                      want_s[ok], want_i[ok])
    retired = set(np.nonzero(~np.asarray(active))[0].tolist())
    assert not (set(np.asarray(ti)[ok].ravel().tolist()) & retired)
    # the tie row the query aims at must surface through the int8 path
    assert {7, 700, 2900} & set(np.asarray(ti)[0, :3].tolist())


@pytest.mark.numerics
def test_int8_fold_certificate_exact_at_f50():
    """The int8+fold phase A (the deepened mirror that streams ~items x
    features bytes) must agree with the f32 exact scan at f=50 exactly
    like the unfolded int8 kernel — the folded integer dot is
    bit-identical, so bounds, certificates and phase B are shared."""
    import jax
    from oryx_tpu.app.als import serving_model as sm

    Y, Q, active, F, W = _f50_fixture(81)
    bs, ksel, k = 128, 24, 8
    fold = sm._fold_factor(W, F)
    assert fold == 2  # 50 <= 64 = 128/2
    y8, sy_b, l1y_b = sm._quantize_items_kernel(Y, bs)
    y8f, pen_i_f = sm._fold_items_i8_kernel(y8, active, fold, bs)
    old_tile = sm._PA_TILE
    sm._PA_TILE = 1024
    try:
        ts, ti, cert = jax.device_get(
            sm._batch_top_n_twophase_pallas_i8_fold(
                Y, y8f, sy_b, l1y_b, Q, pen_i_f, active, None, None,
                None, k=k, bs=bs, ksel=ksel, max_bits=0, fold=fold,
                interpret=True))
        # and bit-identical to the UNFOLDED int8 build: same integer
        # maxima, same bounds, same phase B
        pen_i = sm._penalty_kernel_i32(active, bs)
        ts_u, ti_u, cert_u = jax.device_get(
            sm._batch_top_n_twophase_pallas_i8(
                Y, y8, sy_b, l1y_b, Q, pen_i, active, None, None,
                k=k, bs=bs, ksel=ksel, max_bits=0, interpret=True))
    finally:
        sm._PA_TILE = old_tile
    np.testing.assert_array_equal(ts, ts_u)
    np.testing.assert_array_equal(ti, ti_u)
    np.testing.assert_array_equal(cert, cert_u)
    want_s, want_i = jax.device_get(
        sm._batch_top_n_kernel(Y, Q, active, k))
    ok = np.asarray(cert)
    assert ok.sum() >= Q.shape[0] - 1, ok
    _exact_sets_match(np.asarray(ts)[ok], np.asarray(ti)[ok],
                      want_s[ok], want_i[ok])


def test_int8_fold_lsh_variant_matches_scan_build():
    """With the Hamming mask fused in, the int8+fold phase A must agree
    with the lax.scan build's top-k (the LSH candidate-set invariant
    must not diverge between builds)."""
    import jax
    import jax.numpy as jnp
    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(82)
    N, F, W, B, k, bs, ksel = 4096, 50, 128, 8, 8, 128, 16
    fold = sm._fold_factor(W, F)
    Y = np.zeros((N, W), np.float32)
    Y[:, :F] = rng.standard_normal((N, F)).astype(np.float32)
    Yj = jnp.asarray(Y)
    Q = np.zeros((B, W), np.float32)
    Q[:, :F] = rng.standard_normal((B, F)).astype(np.float32)
    Qj = jnp.asarray(Q)
    active = jnp.asarray(np.ones(N, bool))
    bkt = jnp.asarray(rng.integers(0, 8, N).astype(np.int32))
    hp = jnp.asarray(rng.standard_normal((3, W)).astype(np.float32))
    y8, sy_b, l1y_b = sm._quantize_items_kernel(Yj, bs)
    y8f, pen_i_f = sm._fold_items_i8_kernel(y8, active, fold, bs)
    bkt_f = sm._fold_buckets_kernel(bkt, fold, bs)
    old_tile = sm._PA_TILE
    sm._PA_TILE = 1024
    try:
        ts_f, ti_f, cert_f = jax.device_get(
            sm._batch_top_n_twophase_pallas_i8_fold(
                Yj, y8f, sy_b, l1y_b, Qj, pen_i_f, active, bkt_f, bkt,
                hp, k=k, bs=bs, ksel=ksel, max_bits=1, fold=fold,
                interpret=True))
    finally:
        sm._PA_TILE = old_tile
    ts_s, ti_s, cert_s = jax.device_get(
        sm._batch_top_n_twophase_kernel(
            Yj, Qj, active, bkt, hp, k, 2048, bs, ksel, 1))
    ok = np.asarray(cert_f) & np.asarray(cert_s)
    assert ok.sum() >= B - 2
    np.testing.assert_allclose(np.asarray(ts_f)[ok],
                               np.asarray(ts_s)[ok], rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(ti_f)[ok],
                                  np.asarray(ti_s)[ok])


def _small_lsh_model(n=2048, features=10, seed=90):
    rng = np.random.default_rng(seed)
    model = ALSServingModel(features=features, implicit=True,
                            sample_rate=0.3)
    assert model._lsh_active()
    model.Y.bulk_load([f"i{j}" for j in range(n)],
                      rng.standard_normal((n, features)).astype(
                          np.float32))
    model.X.bulk_load(["u0"],
                      rng.standard_normal((1, features)).astype(
                          np.float32))
    return model


def test_router_falls_back_to_exact_when_lsh_cost_inflated():
    """ISSUE 3 satellite: when a fault point inflates the measured LSH
    cost, the router must route LSH-configured queries to the exact
    scan — and the served results must BE the exact results."""
    model = _small_lsh_model()
    n_rows = len(model.Y.row_ids())
    faults.inject("route-measure-lsh", mode="delay", times=None,
                  delay_sec=0.05)
    route = model.refresh_route(force=True)
    assert faults.fired("route-measure-lsh") > 0
    assert route["measured"] and route["use_lsh"] is False
    assert model._route_use_lsh(n_rows) is False
    # LSH-configured batched queries now serve the exact scan
    rng = np.random.default_rng(91)
    q = rng.standard_normal((3, model.features)).astype(np.float32)
    got = model.top_n_batch(5, q, use_lsh=True)
    want = model.top_n_batch(5, q, use_lsh=False)
    assert got == want
    # /metrics exposes the decision and the measured costs
    m = model.metrics()
    assert m["kernel_route"]["use_lsh"] is False
    assert m["kernel_route"]["costs_lsh_ms"]
    assert m["kernel_route"]["costs_exact_ms"]


def test_router_honors_lsh_when_it_measures_faster():
    """Config semantics are preserved where LSH wins: inflate the EXACT
    side instead and the router keeps the Hamming mask."""
    model = _small_lsh_model(seed=92)
    n_rows = len(model.Y.row_ids())
    faults.inject("route-measure-exact", mode="delay", times=None,
                  delay_sec=0.05)
    route = model.refresh_route(force=True)
    assert faults.fired("route-measure-exact") > 0
    assert route["use_lsh"] is True
    assert model._route_use_lsh(n_rows) is True


def test_router_streaming_orders_kinds_and_survives_pallas_fallback():
    """On the CPU streaming path every pallas build fails to lower; the
    router must still measure the lax.scan build, install a route, and
    leave the dispatch chain's static order intact for unmeasured
    kinds.  A synthetic cost table must reorder the chain strictly by
    measured cost."""
    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(93)
    model = ALSServingModel(features=6, implicit=True)
    model.Y.bulk_load([f"i{j}" for j in range(4096)],
                      rng.standard_normal((4096, 6)).astype(np.float32))
    old = (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS, sm._BLOCK_KSEL,
           sm._PA_TILE)
    old_state = dict(sm._PALLAS_STATE)
    sm._PALLAS_STATE.clear()
    sm._FLAT_SCORES_LIMIT = 1
    sm._MAX_CHUNK_ROWS = 1024
    sm._BLOCK_KSEL = 4
    sm._PA_TILE = 1024
    try:
        route = model.refresh_route(force=True)
        assert route["path"] == "streaming"
        # scan measured; pallas builds recorded as unavailable on CPU
        assert route["costs_exact_ms"].get("scan") is not None
        assert route["costs_exact_ms"].get("pallas") is None
        n_rows = len(model.Y.row_ids())
        # synthetic measured costs reorder the chain cheapest-first
        model._route = {"measured": True, "lsh_configured": False,
                        "phase_a_costs_ms": {"pallas": 1.0,
                                             "fold": 5.0,
                                             "i8_fold": 3.0}}
        model._route_capacity = n_rows
        assert model._route_order(
            ["i8_fold", "fold", "i8", "pallas"], n_rows) == \
            ["pallas", "i8_fold", "fold", "i8"]
        # a stale route (capacity mismatch) leaves the static order
        assert model._route_order(["fold", "pallas"], n_rows + 1) == \
            ["fold", "pallas"]
    finally:
        sm._PALLAS_STATE.clear()
        sm._PALLAS_STATE.update(old_state)
        (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS, sm._BLOCK_KSEL,
         sm._PA_TILE) = old
        model._route = None


def test_route_cached_per_capacity_and_refreshed_on_growth():
    """A route is reused while the padded capacity matches and is NOT
    consulted after the store regrows (hot-swap semantics)."""
    model = _small_lsh_model(seed=94)
    r1 = model.refresh_route()
    assert r1 is not None
    assert model.refresh_route() is r1  # cached, no re-measure
    n_rows = len(model.Y.row_ids())
    assert model._route_current(n_rows) is r1
    assert model._route_current(n_rows * 2) is None
    r2 = model.refresh_route(force=True)
    assert r2 is not r1


def test_router_skips_empty_and_sharded_models():
    model = ALSServingModel(features=6, implicit=True)
    assert model.refresh_route() is None
    assert model._route_use_lsh(0) is True  # no route -> config honored


def test_refresh_route_failure_never_escapes(monkeypatch):
    """Route measurement is advisory: a failure inside measure_routes
    (device OOM building a mirror, transport error) must not escape
    refresh_route — an escaped exception on the MODEL consume path
    would trap the serving update consumer in replay-from-0 against
    the same deterministic failure."""
    from oryx_tpu.app.als import kernel_router

    model = _small_lsh_model(seed=95)

    def boom(*_a, **_k):
        raise RuntimeError("injected measurement failure")

    monkeypatch.setattr(kernel_router, "measure_routes", boom)
    assert model.refresh_route(force=True) is None  # swallowed
    # serving continues config-driven: no route installed
    assert model._route_use_lsh(len(model.Y.row_ids())) is True


def test_route_measurement_evicts_losing_mirrors():
    """Measurement materializes every build's mirror; after routing,
    only the chosen kind's device arrays may stay pinned (at 20M rows
    the losers are ~5 GB of HBM next to the store)."""
    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(96)
    model = ALSServingModel(features=6, implicit=True)
    model.Y.bulk_load([f"i{j}" for j in range(4096)],
                      rng.standard_normal((4096, 6)).astype(np.float32))
    old = (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS, sm._BLOCK_KSEL,
           sm._PA_TILE)
    old_state = dict(sm._PALLAS_STATE)
    sm._PALLAS_STATE.clear()
    sm._FLAT_SCORES_LIMIT = 1
    sm._MAX_CHUNK_ROWS = 1024
    sm._BLOCK_KSEL = 4
    sm._PA_TILE = 1024
    try:
        route = model.refresh_route(force=True)
        # CPU routes to the scan build, which needs NO mirror: every
        # measured-and-lost mirror must be gone
        assert route["chosen"] == "scan"
        for attr in ("_i8", "_i8_fold", "_fold", "_fold_bkt",
                     "_penalty", "_penalty_i"):
            assert getattr(model, attr) is None, attr
    finally:
        sm._PALLAS_STATE.clear()
        sm._PALLAS_STATE.update(old_state)
        (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS, sm._BLOCK_KSEL,
         sm._PA_TILE) = old
