"""Deterministic cluster simulation — unit tests for the substrate
(ISSUE 16 tentpole): virtual clock, seeded cooperative scheduler,
in-memory loopback transport, fault-schedule DSL, and the two-region
end-to-end assembly proving the whole topology runs in ONE process
with zero real sockets and zero real sleeps.

The acceptance e2e here runs a single seed of each scenario under a
``time.sleep``/``socket.socket`` tripwire; the interleaving sweeps
(hundreds of seeds, replay-equality hashes, wall-clock budgets) live
in tests/test_sim_sweep.py.
"""

from __future__ import annotations

import json
import socket
import subprocess
import sys
import time

import pytest

from oryx_tpu.sim import (Scheduler, SimClock, SimDeadlock, SimError,
                          SimEvent, Sleep, Step, WaitEvent,
                          run_scenario)
from oryx_tpu.sim.faults import (FaultAction, FaultSchedule, KINDS,
                                 random_schedule)
from oryx_tpu.sim.invariants import InvariantViolation
from oryx_tpu.sim.net import NetError, RemoteError, SimNet
from oryx_tpu.sim.scenarios import SimFailure, _run
from oryx_tpu.sim.sched import SimTaskFailed, gather

# -- virtual clock ------------------------------------------------------------


class TestSimClock:
    def test_monotonic_starts_at_zero_and_sleep_advances(self):
        c = SimClock()
        assert c.monotonic() == 0.0
        c.sleep(1.5)
        assert c.monotonic() == 1.5
        c.sleep(-3.0)  # negative sleep is a no-op, never a rewind
        assert c.monotonic() == 1.5

    def test_wall_clock_is_epoch_plus_monotonic(self):
        c = SimClock(start_wall=1000.0)
        assert c.time() == 1000.0
        c.sleep(2.0)
        assert c.time() == 1002.0

    def test_advance_to_rejects_rewind(self):
        c = SimClock()
        c.advance_to(5.0)
        with pytest.raises(SimError, match="rewind"):
            c.advance_to(4.0)

    def test_wait_set_event_returns_true_without_advancing(self):
        c, ev = SimClock(), SimEvent()
        ev.set()
        assert c.wait(ev, timeout=9.0) is True
        assert c.monotonic() == 0.0

    def test_wait_unset_event_burns_the_timeout(self):
        c, ev = SimClock(), SimEvent()
        assert c.wait(ev, timeout=3.0) is False
        assert c.monotonic() == 3.0

    def test_untimed_wait_is_rejected(self):
        # an untimed Event.wait inside reused production code would
        # hang virtual time forever — the sim-clock contract bans it
        with pytest.raises(SimError, match="untimed"):
            SimClock().wait(SimEvent(), timeout=None)


# -- scheduler ----------------------------------------------------------------


def _trace_of(seed: int) -> tuple[str, list[str]]:
    """A small multi-task world: sleeps, event waits, preemption
    points — every directive kind the scheduler knows."""
    s = Scheduler(seed, keep_trace=True)
    ev = SimEvent()
    log: list[str] = []

    def ticker(name, period, n):
        for i in range(n):
            yield Sleep(period)
            log.append(f"{name}{i}")

    def setter():
        yield Sleep(0.25)
        ev.set()

    def waiter():
        got = yield WaitEvent(ev, timeout=10.0)
        log.append(f"waiter:{got}")
        yield Step()
        log.append("waiter:stepped")

    s.spawn("t1", ticker("a", 0.1, 3))
    s.spawn("t2", ticker("b", 0.07, 3))
    s.spawn("setter", setter())
    s.spawn("waiter", waiter())
    s.run_until(2.0)
    return s.trace_hash(), log


class TestScheduler:
    def test_same_seed_same_trace_and_order(self):
        h1, log1 = _trace_of(42)
        h2, log2 = _trace_of(42)
        assert h1 == h2
        assert log1 == log2

    def test_different_seed_different_interleaving(self):
        hashes = {_trace_of(seed)[0] for seed in range(8)}
        # 8 seeds of a contended world: at least two distinct traces,
        # or the scheduler is not actually exploring interleavings
        assert len(hashes) > 1

    def test_waiter_woken_by_set_sees_true(self):
        _, log = _trace_of(0)
        assert "waiter:True" in log
        assert "waiter:stepped" in log

    def test_event_wait_timeout_sends_false(self):
        s = Scheduler(0)
        ev = SimEvent()
        out = []

        def waiter():
            out.append((yield WaitEvent(ev, timeout=0.5)))

        s.spawn("w", waiter())
        s.run_until(2.0)
        assert out == [False]
        assert s.clock.monotonic() >= 0.5

    def test_time_jumps_to_next_deadline_without_busy_stepping(self):
        s = Scheduler(0)

        def lone():
            yield Sleep(100.0)

        s.spawn("lone", lone())
        steps_before = s.step_no
        s.run_until(100.0)
        # one spawn-step + one wake: the century of virtual idle time
        # costs O(1) steps, not a poll loop
        assert s.step_no - steps_before <= 2

    def test_kill_runs_finally_blocks_and_frees_the_name(self):
        s = Scheduler(0)
        closed = []

        def victim():
            try:
                while True:
                    yield Sleep(0.1)
            finally:
                closed.append(True)

        s.spawn("v", victim())
        s.run_until(0.5)
        assert s.kill("v") is True
        assert closed == [True]
        assert s.kill("v") is False  # already dead
        s.spawn("v", victim())  # restart semantics: name reusable

    def test_spawn_rejects_live_duplicate_name(self):
        s = Scheduler(0)
        s.spawn("x", iter(()))
        s.spawn("dup", (Sleep(1.0) for _ in range(1)))
        with pytest.raises(SimError, match="already alive"):
            s.spawn("dup", iter(()))

    def test_stall_freezes_a_task_past_its_wake_time(self):
        s = Scheduler(0)
        woke = []

        def sleeper():
            yield Sleep(0.1)
            woke.append(s.clock.monotonic())

        s.spawn("z", sleeper())
        assert s.stall("z", 1.0) is True
        s.run_until(5.0)
        # due at 0.1 but frozen until 1.0 — the GC-pause model
        assert woke and woke[0] >= 1.0

    def test_deadlock_detected(self):
        s = Scheduler(0)

        def stuck():
            yield WaitEvent(SimEvent(), timeout=None)

        s.spawn("stuck", stuck())
        with pytest.raises(SimDeadlock):
            s.run_until(10.0)

    def test_task_exception_surfaces_with_name_and_time(self):
        s = Scheduler(0)

        def bad():
            yield Sleep(0.2)
            raise ValueError("boom")

        s.spawn("bad", bad())
        with pytest.raises(SimTaskFailed, match="'bad'.*boom"):
            s.run_until(1.0)

    def test_gather_returns_in_order_with_errors_in_place(self):
        s = Scheduler(3)

        def child(i):
            yield Sleep(0.01 * (3 - i))  # finish out of spawn order
            if i == 1:
                raise RuntimeError("child down")
            return i * 10

        out = []

        def parent():
            res = yield from gather(s, "fan", [child(i)
                                               for i in range(3)])
            out.append(res)

        s.spawn("parent", parent())
        s.run_until(1.0)
        (res,) = out
        assert res[0] == ("ok", 0)
        assert res[2] == ("ok", 20)
        kind, err = res[1]
        assert kind == "err" and isinstance(err, RuntimeError)


# -- loopback transport -------------------------------------------------------


def _rpc(net, sched, req, out, timeout=0.5, src="cli", dst="srv"):
    def task():
        try:
            out.append(("ok", (yield from net.call(src, dst, req,
                                                   timeout=timeout))))
        except (NetError, RemoteError) as e:
            out.append(("err", e))
    sched.spawn(f"rpc{len(out)}-{sched.step_no}", task())


class TestSimNet:
    def test_roundtrip_and_virtual_latency(self):
        s = Scheduler(1)
        net = SimNet(s)
        net.register("srv", lambda req: {"echo": req})
        out = []
        _rpc(net, s, "hi", out)
        s.run_until(1.0)
        assert out == [("ok", {"echo": "hi"})]
        assert s.clock.monotonic() > 0.0  # the hop cost virtual time

    def test_unregistered_destination_refuses(self):
        s = Scheduler(1)
        net = SimNet(s)
        out = []
        _rpc(net, s, "hi", out)
        s.run_until(1.0)
        kind, err = out[0]
        assert kind == "err" and "refused" in str(err)

    def test_cut_times_out_heal_restores(self):
        s = Scheduler(1)
        net = SimNet(s)
        net.register("srv", lambda req: "pong")
        net.cut("cli", "srv")
        assert not net.reachable("cli", "srv")
        out = []
        _rpc(net, s, "a", out)
        s.run_until(1.0)
        assert out[0][0] == "err"
        net.heal("cli", "srv")
        assert net.reachable("cli", "srv")
        _rpc(net, s, "b", out)
        s.run_until(2.0)
        assert out[1] == ("ok", "pong")

    def test_cut_matches_by_prefix_both_orientations(self):
        s = Scheduler(1)
        net = SimNet(s)
        net.cut("A.router", "A.rep")
        assert not net.reachable("A.rep2x0.1", "A.router")
        assert not net.reachable("A.router", "A.rep3x2.0")
        assert net.reachable("A.router", "B.rep2x0.1")

    def test_add_delay_slows_the_link(self):
        s = Scheduler(1)
        net = SimNet(s)
        net.register("srv", lambda req: "pong")
        net.add_delay("cli", "srv", 0.2)
        out = []
        _rpc(net, s, "a", out, timeout=1.0)
        s.run_until(2.0)
        assert out == [("ok", "pong")]
        assert s.clock.monotonic() >= 0.2

    def test_duplicate_runs_handler_twice_first_reply_wins(self):
        s = Scheduler(1)
        net = SimNet(s)
        calls = []
        net.register("srv", lambda req: calls.append(req) or "pong")
        net.duplicate("cli", "srv", times=1)
        out = []
        _rpc(net, s, "a", out)
        s.run_until(1.0)
        assert out == [("ok", "pong")]
        assert calls == ["a", "a"]  # at-least-once redelivery

    def test_handler_exception_is_remote_error(self):
        s = Scheduler(1)
        net = SimNet(s)

        def boom(req):
            raise RuntimeError("500")

        net.register("srv", boom)
        out = []
        _rpc(net, s, "a", out)
        s.run_until(1.0)
        kind, err = out[0]
        assert kind == "err" and isinstance(err, RemoteError)

    def test_generator_handler_interleaves_as_its_own_task(self):
        s = Scheduler(1)
        net = SimNet(s)

        def slow_handler(req):
            yield Sleep(0.1)
            return f"done:{req}"

        net.register("srv", slow_handler)
        out = []
        _rpc(net, s, "x", out, timeout=1.0)
        s.run_until(2.0)
        assert out == [("ok", "done:x")]


# -- fault-schedule DSL -------------------------------------------------------


class TestFaultDSL:
    def test_random_schedule_is_a_pure_function_of_the_rng(self):
        import random
        comps = ["A.rep", "A.router"]
        links = [("A.router", "A.rep")]
        s1 = random_schedule(random.Random(7), 6.0, 5, comps, links)
        s2 = random_schedule(random.Random(7), 6.0, 5, comps, links)
        assert [str(a) for a in s1.actions] \
            == [str(a) for a in s2.actions]

    def test_destructive_actions_are_paired_with_recovery(self):
        import random
        comps = ["A.rep"]
        links = [("A.router", "A.rep")]
        sched = random_schedule(random.Random(3), 6.0, 12, comps,
                                links, crashable=["A.rep"])
        kinds = [a.kind for a in sched.actions]
        assert kinds.count("restart") \
            == kinds.count("kill") + kinds.count("crash")
        assert kinds.count("heal") == kinds.count("cut")
        for a in sched.actions:
            if a.kind in ("stall", "delay", "duplicate"):
                assert a.arg is not None  # the seed-0/3/7 regression
            assert a.kind in KINDS + ("restart", "heal")

    def test_allow_filter_restricts_kinds(self):
        import random
        sched = random_schedule(
            random.Random(5), 6.0, 10, ["c"], [("a", "b")],
            allow=("stall", "delay"))
        assert {a.kind for a in sched.actions} <= {"stall", "delay"}

    def test_driver_applies_actions_at_their_instants(self):
        s = Scheduler(0)
        applied = []

        class _Cx:
            sched = s

            def apply_fault(self, act):
                applied.append((round(s.clock.monotonic(), 3),
                                act.kind, act.a))

        sched = FaultSchedule([FaultAction(0.5, "kill", "x"),
                               FaultAction(0.2, "cut", "a", "b")])
        s.spawn("driver", sched.driver(_Cx()))
        s.run_until(2.0)
        # sorted by instant, each applied at its virtual time
        assert applied == [(0.2, "cut", "a"), (0.5, "kill", "x")]


# -- end-to-end: the whole region pair, one process, no real I/O --------------


@pytest.fixture
def _no_real_io(monkeypatch):
    """Tripwire: any real socket or real sleep inside the sim path is
    an immediate failure — the zero-sockets/zero-sleeps acceptance
    criterion, enforced rather than asserted after the fact."""

    def _no_sleep(seconds):
        raise AssertionError(
            f"real time.sleep({seconds!r}) inside the sim path")

    class _NoSocket(socket.socket):
        def __init__(self, *a, **kw):
            raise AssertionError("real socket inside the sim path")

    monkeypatch.setattr(time, "sleep", _no_sleep)
    monkeypatch.setattr(socket, "socket", _NoSocket)


class TestEndToEnd:
    def test_two_region_pair_converges_no_sockets_no_sleeps(
            self, _no_real_io):
        """The tentpole acceptance: routers, 2×2 replica fleets per
        region, speed layers, both mirrors — assembled over the
        inproc broker, run to quiesce under the virtual clock, all
        invariants green."""
        res = run_scenario("mirror-partition", seed=1)
        assert res.scenario == "mirror-partition"
        assert len(res.trace_hash) == 64
        # both regions took writes and the checkers actually ran
        assert res.summary["responses_checked"] > 0
        assert res.summary["mirror_polls_checked"] > 0
        assert res.summary["entities"] > 0
        # virtual hours may pass; wall-clock is whatever the CPU took
        assert res.virtual_sec > 6.0

    def test_reshard_cutover_completes_no_sockets_no_sleeps(
            self, _no_real_io):
        res = run_scenario("reshard-cutover", seed=1)
        assert res.stats.get("cutover") == 1
        assert res.stats.get("probe_full", 0) >= 1
        assert res.summary["responses_checked"] > 0

    def test_failure_message_carries_the_repro_command(self):
        """A violated invariant must print seed + repro line — the
        sweep-to-bisect workflow's contract."""

        def body(cx):
            raise InvariantViolation("convergence", "synthetic")

        with pytest.raises(SimFailure) as ei:
            _run("mirror-partition", 77, False, body)
        msg = str(ei.value)
        assert "seed=77" in msg
        assert ("repro: python -m oryx_tpu.sim "
                "--scenario mirror-partition --seed 77 --trace") in msg

    def test_cli_repro_replays_byte_identical_across_processes(self):
        """python -m oryx_tpu.sim twice in FRESH interpreters: the
        trace hash must match across processes, not just within one —
        no process-unique value (pid, id(), tmpdir) may leak into the
        trace."""
        cmd = [sys.executable, "-m", "oryx_tpu.sim",
               "--scenario", "reshard-cutover", "--seed", "5"]
        outs = []
        for _ in range(2):
            p = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=120, check=True)
            outs.append(json.loads(p.stdout))
        assert outs[0]["trace_hash"] == outs[1]["trace_hash"]
        assert outs[0]["steps"] == outs[1]["steps"]
