"""ALS app tests (reference analogs: ALSUtilsTest, ALSUpdateIT,
ALSSpeedIT, ALSServingModelTest, ALSServingModelManagerIT,
LocalitySensitiveHashTest)."""

import math
import os

import numpy as np
import pytest

from oryx_tpu.app.als import common as als_common
from oryx_tpu.app.als import evaluation
from oryx_tpu.app.als.feature_vectors import FeatureVectorStore
from oryx_tpu.app.als.lsh import LocalitySensitiveHash, choose_hash_count
from oryx_tpu.app.als.serving_manager import ALSServingModelManager
from oryx_tpu.app.als.serving_model import ALSServingModel, SolverCache
from oryx_tpu.app.als.speed import ALSSpeedModelManager
from oryx_tpu.app.als.trainer import train_als, predict_pairs
from oryx_tpu.app.als.update import ALSUpdate, load_features, save_features
from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_MODEL, KEY_UP, KeyMessage
from oryx_tpu.kafka.inproc import InProcBroker, InProcTopicProducer, get_broker


# -- common: parse/aggregate/known ------------------------------------------

def test_aggregate_implicit_sums_and_deletes():
    events = [("u", "i", 1.0, 1), ("u", "i", 2.0, 2), ("u", "j", 1.0, 3),
              ("v", "i", float("nan"), 4)]
    r = als_common.aggregate(events, implicit=True)
    pairs = {(r.user_ids[u], r.item_ids[i]): v
             for u, i, v in zip(r.users, r.items, r.values)}
    assert pairs[("u", "i")] == 3.0
    assert pairs[("u", "j")] == 1.0
    assert ("v", "i") not in pairs  # delete wiped the pair


def test_aggregate_implicit_delete_after_add():
    events = [("u", "i", 1.0, 1), ("u", "i", float("nan"), 2)]
    r = als_common.aggregate(events, implicit=True)
    assert len(r.values) == 0


def test_aggregate_explicit_last_wins():
    events = [("u", "i", 3.0, 1), ("u", "i", 5.0, 2)]
    r = als_common.aggregate(events, implicit=False)
    assert list(r.values) == [5.0]


def test_decay():
    day_ms = 86_400_000
    assert als_common.decay_value(1.0, 0, 3 * day_ms, 0.9) == pytest.approx(0.9 ** 3)
    assert als_common.decay_value(1.0, 5, 5, 0.9) == 1.0  # not older than now


def test_known_items_delete():
    events = [("u", "a", 1.0, 1), ("u", "b", 1.0, 2), ("u", "a", float("nan"), 3)]
    known = als_common.build_known_items(events)
    assert known["u"] == {"b"}


def test_parse_events_orders_by_timestamp():
    msgs = [KeyMessage(None, "u,i,1,300"), KeyMessage(None, "u,j,1,100")]
    events = als_common.parse_events(msgs)
    assert [e[3] for e in events] == [100, 300]


# -- trainer ----------------------------------------------------------------

def _synthetic_explicit(nu=120, ni=60, k=3, density=0.35, seed=0):
    rng = np.random.default_rng(seed)
    Xt = rng.standard_normal((nu, k))
    Yt = rng.standard_normal((ni, k))
    R = Xt @ Yt.T
    mask = rng.random((nu, ni)) < density
    us, its = np.nonzero(mask)
    return als_common.ParsedRatings(
        [f"u{i}" for i in range(nu)], [f"i{j}" for j in range(ni)],
        us.astype(np.int32), its.astype(np.int32),
        R[us, its].astype(np.float32)), R, mask


def test_train_als_explicit_recovers_low_rank():
    ratings, R, mask = _synthetic_explicit()
    m = train_als(ratings, features=3, lam=0.01, alpha=1.0, implicit=False,
                  iterations=6, seed=1)
    pred = predict_pairs(m.X, m.Y, ratings.users, ratings.items)
    rmse = float(np.sqrt(np.mean((pred - ratings.values) ** 2)))
    assert rmse < 0.1
    # held-out generalization
    held = ~mask & (np.random.default_rng(9).random(mask.shape) < 0.05)
    u2, i2 = np.nonzero(held)
    p2 = predict_pairs(m.X, m.Y, u2.astype(np.int32), i2.astype(np.int32))
    assert float(np.sqrt(np.mean((p2 - R[u2, i2]) ** 2))) < 0.3


def test_train_als_implicit_ranks_positives_higher():
    ratings, R, _ = _synthetic_explicit(seed=3)
    pos = R > 1.0
    us, its = np.nonzero(pos)
    r = als_common.ParsedRatings(ratings.user_ids, ratings.item_ids,
                                 us.astype(np.int32), its.astype(np.int32),
                                 np.ones(len(us), np.float32))
    m = train_als(r, 3, 0.01, 1.0, True, 5, seed=2)
    s = m.X @ m.Y.T
    assert float(s[pos].mean()) > float(s[~pos].mean()) + 0.3


def test_evaluation_auc_perfect_and_random():
    # construct scores where positives always outrank: AUC ~ 1
    X = np.eye(4, dtype=np.float32)
    Y = np.vstack([np.eye(4), -np.eye(4)]).astype(np.float32)
    users = np.arange(4, dtype=np.int32)
    items = np.arange(4, dtype=np.int32)  # item i == best for user i
    auc = evaluation.area_under_curve(X, Y, users, items)
    assert auc > 0.9


# -- artifacts --------------------------------------------------------------

def test_save_load_features_round_trip(tmp_path):
    ids = ["a", "b", "c"]
    mat = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], dtype=np.float32)
    save_features(str(tmp_path / "X"), ids, mat)
    ids2, mat2 = load_features(str(tmp_path / "X"))
    assert ids2 == ids
    np.testing.assert_allclose(mat2, mat, rtol=1e-6)


# -- feature store ----------------------------------------------------------

def test_feature_store_basics():
    fs = FeatureVectorStore(2, initial_capacity=4)
    fs.set_vector("a", [1.0, 2.0])
    fs.set_vector("b", [3.0, 4.0])
    assert len(fs) == 2
    np.testing.assert_array_equal(fs.get_vector("a"), [1.0, 2.0])
    fs.remove("a")
    assert fs.get_vector("a") is None
    # grow beyond capacity
    for i in range(10):
        fs.set_vector(f"x{i}", [float(i), 0.0])
    assert len(fs) == 11
    vecs, active = fs.device_arrays()
    assert int(np.asarray(active).sum()) == 11


def test_feature_store_retain_recent():
    fs = FeatureVectorStore(2)
    fs.set_vector("old1", [1, 1])
    fs.set_vector("old2", [2, 2])
    fs.device_arrays()
    fs._recent.clear()  # simulate time passing: nothing recent
    fs.set_vector("recent", [3, 3])
    fs.retain_recent_and_ids(["old1"])
    assert "old1" in fs and "recent" in fs and "old2" not in fs


def test_feature_store_vtv():
    fs = FeatureVectorStore(2)
    fs.set_vector("a", [1.0, 2.0])
    fs.set_vector("b", [3.0, 4.0])
    expected = np.array([[1, 2], [3, 4]], dtype=np.float32)
    np.testing.assert_allclose(fs.vtv(), expected.T @ expected, rtol=1e-5)


def test_feature_store_incremental_device_sync():
    fs = FeatureVectorStore(2, initial_capacity=64)
    for i in range(20):
        fs.set_vector(f"v{i}", [float(i), 1.0])
    v1, _ = fs.device_arrays()
    fs.set_vector("v3", [99.0, 99.0])  # single dirty row -> scatter path
    v2, _ = fs.device_arrays()
    row = fs.row_of("v3")
    # device snapshot is lane-padded to 128 features; the true columns
    # carry the update and the padding stays exactly zero
    assert v2.shape[1] == fs.device_features == 128
    np.testing.assert_array_equal(np.asarray(v2)[row][:2], [99.0, 99.0])
    assert not np.asarray(v2)[row][2:].any()


# -- LSH --------------------------------------------------------------------

def test_choose_hash_count_full_sample():
    nh, _ = choose_hash_count(1.0, 8)
    assert nh <= 3  # near-trivial hashing at sample rate 1.0


def test_lsh_masks_fraction_of_items():
    lsh = LocalitySensitiveHash(0.3, 8, num_cores=8)
    assert lsh.num_hashes > 0
    rng = np.random.default_rng(5)
    items = rng.standard_normal((2000, 8)).astype(np.float32)
    import jax.numpy as jnp
    buckets = jnp.asarray(lsh.bucket_of(items))
    q = rng.standard_normal(8).astype(np.float32)
    mask = np.asarray(lsh.candidate_mask(q, buckets))
    frac = mask.mean()
    assert 0.02 < frac < 0.8  # prunes, but keeps a viable candidate set
    # query's own bucket always included: a vector equal to an item
    mask_self = np.asarray(lsh.candidate_mask(np.asarray(items[0]), buckets))
    assert mask_self[0]


# -- serving model ----------------------------------------------------------

def _make_serving_model(nu=20, ni=50, k=4, seed=0):
    rng = np.random.default_rng(seed)
    model = ALSServingModel(k, implicit=True)
    X = rng.standard_normal((nu, k)).astype(np.float32)
    Y = rng.standard_normal((ni, k)).astype(np.float32)
    for i in range(nu):
        model.set_user_vector(f"u{i}", X[i])
    for j in range(ni):
        model.set_item_vector(f"i{j}", Y[j])
    return model, X, Y


def test_top_n_matches_numpy():
    model, X, Y = _make_serving_model()
    got = model.top_n(5, user_vector=X[0])
    scores = Y @ X[0]
    want_idx = np.argsort(-scores)[:5]
    assert [g[0] for g in got] == [f"i{j}" for j in want_idx]
    np.testing.assert_allclose([g[1] for g in got], scores[want_idx], rtol=1e-5)


def test_top_n_excludes_known_items():
    model, X, Y = _make_serving_model()
    scores = Y @ X[0]
    best = f"i{int(np.argmax(scores))}"
    got = model.top_n(5, user_vector=X[0], exclude={best})
    assert best not in [g[0] for g in got]
    assert len(got) == 5


def test_top_n_cosine_and_lowest():
    model, X, Y = _make_serving_model()
    v = Y[7]
    got = model.top_n(3, cosine_to=v)
    # the item itself has cosine 1.0 -> top
    assert got[0][0] == "i7"
    assert got[0][1] == pytest.approx(1.0, abs=1e-5)
    low = model.top_n(3, user_vector=X[0], lowest=True)
    scores = Y @ X[0]
    assert low[0][0] == f"i{int(np.argmin(scores))}"


def test_top_n_with_rescorer():
    from oryx_tpu.app.als.rescorer import Rescorer

    class Halver(Rescorer):
        def rescore(self, item_id, score):
            return score * 0.5

        def is_filtered(self, item_id):
            return item_id == "i0"

    model, X, Y = _make_serving_model()
    got = model.top_n(5, user_vector=X[0], rescorer=Halver())
    assert "i0" not in [g[0] for g in got]
    scores = (Y @ X[0]) * 0.5
    order = [f"i{j}" for j in np.argsort(-scores) if j != 0][:5]
    assert [g[0] for g in got] == order


def test_fraction_loaded_and_retain():
    model, X, Y = _make_serving_model(nu=4, ni=4)
    assert model.get_fraction_loaded() == 1.0
    model.set_expected_ids(["u0", "new1", "new2"], ["i0"])
    # u0/i0 already loaded; new1,new2 expected -> 8/(8+2)
    assert model.get_fraction_loaded() == pytest.approx(8 / 10)
    model.add_known_items("u0", ["i1"])
    model.add_known_items("gone", ["i2"])
    # clear recency so only the new model's IDs are kept
    model.X._recent.clear()
    model.Y._recent.clear()
    model.retain_recent_and_known_items(["u0"], ["i1", "i3"])
    assert model.get_known_items("gone") == set()
    assert model.get_known_items("u0") == {"i1"}
    # items absent from the new model are pruned from surviving sets
    model.add_known_items("u0", ["i9"])
    model.Y._recent.clear()
    model.retain_recent_and_known_items(["u0"], ["i1"])
    assert model.get_known_items("u0") == {"i1"}


def test_item_popularity_counts_incremental():
    """The popularity counter tracks known-items writes AND model-swap
    pruning exactly (backs O(items) /mostPopularItems)."""
    model, X, Y = _make_serving_model(nu=4, ni=4)
    model.add_known_items("u0", ["i1", "i2"])
    model.add_known_items("u1", ["i1"])
    model.add_known_items("u1", ["i1"])          # duplicate: no double count
    assert model.get_item_popularity_counts() == {"i1": 2, "i2": 1}
    model.X._recent.clear()
    model.Y._recent.clear()
    # u1 dropped entirely; u0 keeps only i1
    model.retain_recent_and_known_items(["u0"], ["i1"])
    assert model.get_item_popularity_counts() == {"i1": 1}


def test_top_n_lowest_with_rescorer():
    from oryx_tpu.app.als.rescorer import Rescorer

    class Identity(Rescorer):
        def rescore(self, item_id, score):
            return score

    model, X, Y = _make_serving_model()
    got = model.top_n(3, user_vector=X[0], lowest=True, rescorer=Identity())
    scores = Y @ X[0]
    want = [f"i{j}" for j in np.argsort(scores)[:3]]
    assert [g[0] for g in got] == want


def test_solver_cache_returns_none_fast_when_singular():
    import time as _time
    cache = SolverCache(lambda: np.zeros((3, 3)))  # always singular
    t0 = _time.monotonic()
    assert cache.get(blocking=True) is None
    assert _time.monotonic() - t0 < 5.0  # no stall waiting on a timeout


def test_aggregate_log_strength_domain():
    # a pair whose sum is far negative must drop, not crash the build
    events = [("u", "i", -5.0, 1), ("u", "j", 2.0, 2)]
    r = als_common.aggregate(events, implicit=True, log_strength=True,
                             epsilon=1e-5)
    assert len(r.values) == 1  # only the positive pair survives
    assert r.values[0] == pytest.approx(math.log1p(2.0 / 1e-5))


def test_solver_cache_dirty_refresh():
    calls = []

    def supplier():
        calls.append(1)
        return np.eye(3) * (len(calls) + 1.0)

    cache = SolverCache(supplier)
    s1 = cache.get(blocking=True)
    assert s1 is not None and len(calls) == 1
    s2 = cache.get(blocking=True)
    assert len(calls) == 1  # not dirty: cached
    cache.set_dirty()
    cache.compute_now()
    assert len(calls) == 2


# -- ALSUpdate end-to-end (ALSUpdateIT level) --------------------------------

def _ratings_lines(seed=0, nu=60, ni=30, k=3):
    rng = np.random.default_rng(seed)
    Xt = rng.standard_normal((nu, k))
    Yt = rng.standard_normal((ni, k))
    R = Xt @ Yt.T
    lines = []
    t = 1_500_000_000_000
    for u in range(nu):
        for i in range(ni):
            if R[u, i] > 0.5:
                lines.append(KeyMessage(None, f"u{u},i{i},{R[u, i]:.3f},{t}"))
                t += 1000
    return lines


def test_als_update_end_to_end(tmp_path):
    cfg = from_dict({
        "oryx.als.iterations": 5,
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": 4,
        "oryx.ml.eval.test-fraction": 0.2,
    })
    update = ALSUpdate(cfg)
    data = _ratings_lines()
    broker_name = "als-e2e"
    producer = InProcTopicProducer(f"memory://{broker_name}", "Up")
    model_dir = str(tmp_path / "model")
    update.run_update(0, data, [], model_dir, producer)

    broker = get_broker(broker_name)
    msgs = list(broker.consume("Up", from_beginning=True, max_idle_sec=0.2))
    # first a MODEL, then Y rows, then X rows with known-items
    assert msgs[0].key == KEY_MODEL
    doc = pmml_io.from_string(msgs[0].message)
    assert pmml_io.get_extension_value(doc, "features") == "4"
    assert pmml_io.get_extension_value(doc, "implicit") == "true"
    x_ids = pmml_io.get_extension_content(doc, "XIDs")
    y_ids = pmml_io.get_extension_content(doc, "YIDs")
    assert len(x_ids) > 0 and len(y_ids) > 0
    ups = [m for m in msgs if m.key == KEY_UP]
    kinds = [als_common.text_utils.read_json(m.message)[0] for m in ups]
    assert kinds.count("Y") == len(y_ids)
    assert kinds.count("X") == len(x_ids)
    # Y updates come before X updates (reference ordering)
    assert kinds.index("X") > kinds.index("Y")
    # X updates carry known items
    first_x = als_common.text_utils.read_json(
        ups[kinds.index("X")].message)
    assert len(first_x) == 4 and isinstance(first_x[3], list)
    # artifacts exist under the published model dir
    gen_dirs = [d for d in os.listdir(model_dir) if d.isdigit()]
    assert len(gen_dirs) == 1
    assert os.path.exists(os.path.join(model_dir, gen_dirs[0], "X",
                                       "part-00000.gz"))


def test_als_time_based_split():
    cfg = from_dict({"oryx.ml.eval.test-fraction": 0.25})
    update = ALSUpdate(cfg)
    data = [KeyMessage(None, f"u,i,1,{1000 + i}") for i in range(100)]
    train, test = update.split_new_data_to_train_test(data)
    assert len(test) == pytest.approx(25, abs=2)
    max_train_ts = max(int(km.message.split(",")[3]) for km in train)
    min_test_ts = min(int(km.message.split(",")[3]) for km in test)
    assert max_train_ts < min_test_ts  # split purely on time


# -- speed layer (ALSSpeedIT level) -----------------------------------------

def _speed_manager_with_model(nu=12, ni=12, k=3, seed=4):
    rng = np.random.default_rng(seed)
    cfg = from_dict({})
    mgr = ALSSpeedModelManager(cfg)
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", k)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension(doc, "logStrength", False)
    x_ids = [f"u{i}" for i in range(nu)]
    y_ids = [f"i{j}" for j in range(ni)]
    pmml_io.add_extension_content(doc, "XIDs", x_ids)
    pmml_io.add_extension_content(doc, "YIDs", y_ids)
    mgr.consume_key_message(KEY_MODEL, pmml_io.to_string(doc))
    # small-norm vectors keep every current estimate below 1 so implicit
    # fold-in always has a non-NaN target
    X = (0.3 * rng.standard_normal((nu, k))).astype(np.float32)
    Y = (0.3 * rng.standard_normal((ni, k))).astype(np.float32)
    for i, id_ in enumerate(x_ids):
        mgr.consume_key_message(
            KEY_UP, als_common.text_utils.join_json(
                ["X", id_, [float(v) for v in X[i]]]))
    for j, id_ in enumerate(y_ids):
        mgr.consume_key_message(
            KEY_UP, als_common.text_utils.join_json(
                ["Y", id_, [float(v) for v in Y[j]]]))
    return mgr, X, Y


def test_speed_manager_builds_fold_in_updates():
    mgr, X, Y = _speed_manager_with_model()
    assert mgr.model.get_fraction_loaded() == 1.0
    new_data = [KeyMessage(None, "u0,i1,2.5,1000"),
                KeyMessage(None, "unew,i2,1.0,2000")]
    updates = list(mgr.build_updates(new_data))
    assert updates
    parsed = [als_common.text_utils.read_json(u) for u in updates]
    # updates reference both matrices and include the other-id as known
    kinds = {p[0] for p in parsed}
    assert kinds <= {"X", "Y"}
    x_up = [p for p in parsed if p[0] == "X" and p[1] == "u0"]
    assert x_up and x_up[0][3] == ["i1"]
    # new user gets a vector from nothing (fold-in from 'don't know')
    assert any(p[0] == "X" and p[1] == "unew" for p in parsed)
    # the update moves u0's estimate for i1 upward toward 1
    old_est = float(X[0] @ Y[1])
    new_xu = np.asarray(x_up[0][2], dtype=np.float32)
    new_est = float(new_xu @ Y[1])
    if old_est < 1.0:
        assert new_est > old_est


def test_speed_manager_skips_without_model():
    mgr = ALSSpeedModelManager(from_dict({}))
    assert list(mgr.build_updates([KeyMessage(None, "u,i,1,1")])) == []
    # UP before MODEL silently ignored
    mgr.consume_key_message(KEY_UP, '["X","u",[0.1,0.2]]')
    assert mgr.model is None


def test_speed_model_feature_change_resets():
    mgr, _, _ = _speed_manager_with_model(k=3)
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", 5)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension(doc, "logStrength", False)
    pmml_io.add_extension_content(doc, "XIDs", ["u0"])
    pmml_io.add_extension_content(doc, "YIDs", ["i0"])
    mgr.consume_key_message(KEY_MODEL, pmml_io.to_string(doc))
    assert mgr.model.features == 5
    assert len(mgr.model.X) == 0  # fresh model


# -- serving manager (ALSServingModelManagerIT level) ------------------------

def test_serving_manager_full_replay(tmp_path):
    # run a real batch update, then replay its topic into a serving manager
    cfg = from_dict({
        "oryx.als.iterations": 3,
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": 3,
        "oryx.ml.eval.test-fraction": 0.0,
    })
    data = _ratings_lines(seed=7, nu=25, ni=15)
    producer = InProcTopicProducer("memory://als-serve-replay", "Up2")
    ALSUpdate(cfg).run_update(0, data, [], str(tmp_path / "m"), producer)

    mgr = ALSServingModelManager(cfg)
    broker = get_broker("als-serve-replay")
    for km in broker.consume("Up2", from_beginning=True, max_idle_sec=0.2):
        mgr.consume_key_message(km.key, km.message)
    model = mgr.get_model()
    assert model is not None
    assert model.get_fraction_loaded() == 1.0
    assert model.user_count() > 0 and model.item_count() > 0
    # a user's recommendations exclude nothing by default and score sanely
    uid = model.all_user_ids()[0]
    recs = model.top_n(5, user_vector=model.get_user_vector(uid))
    assert len(recs) == 5
    assert all(isinstance(r[0], str) for r in recs)
    # known items were delivered with X updates
    counts = model.get_known_item_counts()
    assert counts and all(v > 0 for v in counts.values())


# -- batched serving scan + bulk load ----------------------------------------

def test_top_n_batch_matches_single():
    from oryx_tpu.app.als.serving_model import ALSServingModel
    rng = np.random.default_rng(9)
    model = ALSServingModel(features=5, implicit=True)
    ids = [f"I{j}" for j in range(40)]
    Y = rng.standard_normal((40, 5)).astype(np.float32)
    model.Y.bulk_load(ids, Y)
    Q = rng.standard_normal((6, 5)).astype(np.float32)
    batch = model.top_n_batch(4, Q)
    assert len(batch) == 6
    for b in range(6):
        single = model.top_n(4, user_vector=Q[b])
        assert [i for i, _ in batch[b]] == [i for i, _ in single]
        np.testing.assert_allclose([s for _, s in batch[b]],
                                   [s for _, s in single], rtol=1e-5)


def test_top_n_batch_respects_exclusions():
    from oryx_tpu.app.als.serving_model import ALSServingModel
    rng = np.random.default_rng(10)
    model = ALSServingModel(features=3, implicit=True)
    ids = [f"I{j}" for j in range(10)]
    model.Y.bulk_load(ids, rng.standard_normal((10, 3)).astype(np.float32))
    q = rng.standard_normal((1, 3)).astype(np.float32)
    full = model.top_n_batch(3, q)[0]
    excluded = model.top_n_batch(3, q, exclude=[{full[0][0]}])[0]
    assert full[0][0] not in [i for i, _ in excluded]
    assert len(excluded) == 3


def test_bulk_load_overwrites_and_grows():
    from oryx_tpu.app.als.feature_vectors import FeatureVectorStore
    store = FeatureVectorStore(4, initial_capacity=16)
    rng = np.random.default_rng(11)
    ids = [f"x{j}" for j in range(100)]
    M = rng.standard_normal((100, 4)).astype(np.float32)
    store.bulk_load(ids, M)
    assert len(store) == 100
    np.testing.assert_array_equal(store.get_vector("x7"), M[7])
    M2 = rng.standard_normal((100, 4)).astype(np.float32)
    store.bulk_load(ids, M2)
    assert len(store) == 100
    np.testing.assert_array_equal(store.get_vector("x7"), M2[7])
    vecs, active = store.device_arrays()
    assert int(np.asarray(active).sum()) == 100


def test_feature_store_bfloat16_storage():
    from oryx_tpu.app.als.feature_vectors import FeatureVectorStore
    store = FeatureVectorStore(4, dtype="bfloat16")
    v = np.array([1.5, -2.25, 0.125, 3.0], np.float32)  # bf16-exact values
    store.set_vector("a", v)
    got = store.get_vector("a")
    assert got.dtype == np.float32
    np.testing.assert_array_equal(got, v)
    vecs, active = store.device_arrays()
    assert str(vecs.dtype) == "bfloat16"
    # device matmul still accumulates f32 and round-trips the values
    model_scores = np.asarray(store.vtv())
    assert model_scores.dtype == np.float32


def test_bulk_load_exact_fit_capacity():
    from oryx_tpu.app.als.feature_vectors import (FeatureVectorStore,
                                                  _LARGE_ALIGN)
    store = FeatureVectorStore(2, initial_capacity=16)
    n = _LARGE_ALIGN + 5000
    ids = [str(i) for i in range(n)]
    store.bulk_load(ids, np.zeros((n, 2), np.float32))
    cap = len(store.row_ids())
    # large stores size to the next chunk multiple, not the next pow2
    assert cap % _LARGE_ALIGN == 0
    assert cap - n < _LARGE_ALIGN


def test_top_n_batch_chunked_matches_flat(monkeypatch):
    from oryx_tpu.app.als import serving_model as sm
    rng = np.random.default_rng(3)
    ni, k = 1500, 8
    model = ALSServingModel(k, implicit=True)
    Y = rng.standard_normal((ni, k)).astype(np.float32)
    model.Y.bulk_load([f"i{j}" for j in range(ni)], Y)
    Q = rng.standard_normal((5, k)).astype(np.float32)
    flat = model.top_n_batch(6, Q)
    monkeypatch.setattr(sm, "_FLAT_SCORES_LIMIT", 1)
    monkeypatch.setattr(sm, "_MAX_CHUNK_ROWS", 256)
    chunked = model.top_n_batch(6, Q)
    for f, c in zip(flat, chunked):
        assert [i for i, _ in f] == [i for i, _ in c]
        np.testing.assert_allclose([s for _, s in f], [s for _, s in c],
                                   rtol=1e-5)


def test_top_n_batch_lsh_matches_single():
    rng = np.random.default_rng(4)
    ni, k = 3000, 8
    model = ALSServingModel(k, implicit=True, sample_rate=0.3)
    assert model.lsh is not None and model.lsh.num_hashes > 0
    model.Y.bulk_load([f"i{j}" for j in range(ni)],
                      rng.standard_normal((ni, k)).astype(np.float32))
    Q = rng.standard_normal((4, k)).astype(np.float32)
    batched = model.top_n_batch(5, Q)
    exact = model.top_n_batch(5, Q, use_lsh=False)
    assert batched != exact  # the Hamming-ball mask actually pruned
    for b in range(4):
        single = model.top_n(5, user_vector=Q[b])
        assert [i for i, _ in batched[b]] == [i for i, _ in single]
        np.testing.assert_allclose([s for _, s in batched[b]],
                                   [s for _, s in single], rtol=1e-5)


def test_top_n_batch_chunked_lsh(monkeypatch):
    from oryx_tpu.app.als import serving_model as sm
    rng = np.random.default_rng(6)
    ni, k = 1800, 8
    model = ALSServingModel(k, implicit=True, sample_rate=0.3)
    model.Y.bulk_load([f"i{j}" for j in range(ni)],
                      rng.standard_normal((ni, k)).astype(np.float32))
    Q = rng.standard_normal((3, k)).astype(np.float32)
    flat = model.top_n_batch(5, Q)
    monkeypatch.setattr(sm, "_FLAT_SCORES_LIMIT", 1)
    monkeypatch.setattr(sm, "_MAX_CHUNK_ROWS", 256)
    chunked = model.top_n_batch(5, Q)
    for f, c in zip(flat, chunked):
        assert [i for i, _ in f] == [i for i, _ in c]
        np.testing.assert_allclose([s for _, s in f], [s for _, s in c],
                                   rtol=1e-5)


def test_top_n_batch_twophase_matches_flat(monkeypatch):
    """The streaming two-phase path (block maxima + approx block pick +
    exact rescore + certificate) agrees with the flat exact kernel."""
    from oryx_tpu.app.als import serving_model as sm
    rng = np.random.default_rng(12)
    ni, k = 4096, 8
    model = ALSServingModel(k, implicit=True)
    Y = rng.standard_normal((ni, k)).astype(np.float32)
    model.Y.bulk_load([f"i{j}" for j in range(ni)], Y)
    Q = rng.standard_normal((5, k)).astype(np.float32)
    flat = model.top_n_batch(6, Q)
    monkeypatch.setattr(sm, "_FLAT_SCORES_LIMIT", 1)
    monkeypatch.setattr(sm, "_MAX_CHUNK_ROWS", 1024)
    monkeypatch.setattr(sm, "_BLOCK_ROWS", 64)
    monkeypatch.setattr(sm, "_BLOCK_KSEL", 8)
    two = model.top_n_batch(6, Q)
    assert model.twophase_fallbacks == 0
    for f, c in zip(flat, two):
        assert [i for i, _ in f] == [i for i, _ in c]
        np.testing.assert_allclose([s for _, s in f], [s for _, s in c],
                                   rtol=1e-5)
    # LSH masks fuse into both phases
    model2 = ALSServingModel(k, implicit=True, sample_rate=0.3)
    model2.Y.bulk_load([f"i{j}" for j in range(ni)], Y)
    lsh_two = model2.top_n_batch(6, Q)
    monkeypatch.undo()
    lsh_flat = model2.top_n_batch(6, Q)
    for f, c in zip(lsh_flat, lsh_two):
        assert [i for i, _ in f] == [i for i, _ in c]


def test_top_n_batch_twophase_cert_fallback(monkeypatch):
    """A failed exactness certificate triggers the exact-scan recompute
    and still returns correct results."""
    from oryx_tpu.app.als import serving_model as sm
    rng = np.random.default_rng(13)
    ni, k = 2048, 8
    model = ALSServingModel(k, implicit=True)
    model.Y.bulk_load([f"i{j}" for j in range(ni)],
                      rng.standard_normal((ni, k)).astype(np.float32))
    Q = rng.standard_normal((3, k)).astype(np.float32)
    want = model.top_n_batch(5, Q)

    real = sm._batch_top_n_twophase_kernel

    def sabotaged(*args, **kw):
        ts, ti, cert = real(*args, **kw)
        return ts, ti, cert & False  # force every certificate to fail

    monkeypatch.setattr(sm, "_FLAT_SCORES_LIMIT", 1)
    monkeypatch.setattr(sm, "_MAX_CHUNK_ROWS", 512)
    monkeypatch.setattr(sm, "_BLOCK_ROWS", 64)
    monkeypatch.setattr(sm, "_BLOCK_KSEL", 8)
    monkeypatch.setattr(sm, "_batch_top_n_twophase_kernel", sabotaged)
    got = model.top_n_batch(5, Q)
    assert model.twophase_fallbacks >= 1
    for f, c in zip(want, got):
        assert [i for i, _ in f] == [i for i, _ in c]


def test_pallas_phase_a_interpret_agrees_with_scan_kernel():
    """The pallas-built two-phase program (interpret mode, so it runs on
    the CPU test platform) must produce the same top-k as the lax.scan
    build — same phase B, same certificate semantics."""
    import jax
    import jax.numpy as jnp

    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(11)
    n, f, b, k = 8192, 16, 8, 8
    Y = jnp.asarray(rng.standard_normal((n, f)).astype(np.float32))
    Q = jnp.asarray(rng.standard_normal((b, f)).astype(np.float32))
    act = np.ones(n, bool)
    act[::5] = False
    active = jnp.asarray(act)
    bs, ksel = 128, 8
    penalty = sm._penalty_kernel(active, bs)
    chunk = 2048
    old_tile = sm._PA_TILE
    sm._PA_TILE = 2048
    try:
        ts_p, ti_p, cert_p = jax.device_get(
            sm._batch_top_n_twophase_pallas(
                Y, Q, penalty, active, None, None, k, bs, ksel, 0,
                interpret=True))
    finally:
        sm._PA_TILE = old_tile
    ts_s, ti_s, cert_s = jax.device_get(
        sm._batch_top_n_twophase_kernel(
            Y, Q, active, None, None, k, chunk, bs, ksel, 0))
    np.testing.assert_allclose(ts_p, ts_s, rtol=1e-5)
    assert (ti_p == ti_s).all()
    assert (cert_p == cert_s).all()


def test_pallas_fallback_on_unsupported_backend():
    """On the CPU test platform the non-interpret pallas path cannot
    lower; the dispatcher must fall back to the scan kernel and still
    answer correctly (and permanently, without raising)."""
    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(3)
    model = ALSServingModel(features=6, implicit=True)
    model.Y.bulk_load([f"i{j}" for j in range(4096)],
                      rng.standard_normal((4096, 6)).astype(np.float32))
    q = rng.standard_normal((3, 6)).astype(np.float32)
    old_state = dict(sm._PALLAS_STATE)
    old_limits = (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS,
                  sm._BLOCK_KSEL, sm._PA_TILE)
    import jax  # noqa: F401 — device_get in the exercised path
    sm._PALLAS_STATE.clear()
    sm._FLAT_SCORES_LIMIT = 1
    sm._MAX_CHUNK_ROWS = 1024
    sm._BLOCK_KSEL = 4
    sm._PA_TILE = 1024
    try:
        got = model.top_n_batch(5, q)
        want = [model.top_n(5, user_vector=v) for v in q]
        for g, w in zip(got, want):
            assert [i for i, _ in g] == [i for i, _ in w]
        assert set(sm._PALLAS_STATE.values()) <= {"ok", "broken"}
        assert sm._PALLAS_STATE  # the dispatcher recorded a verdict
    finally:
        sm._PALLAS_STATE.clear()
        sm._PALLAS_STATE.update(old_state)
        (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS,
         sm._BLOCK_KSEL, sm._PA_TILE) = old_limits


def test_certificate_passes_when_all_unselected_blocks_masked():
    """m_rest of -inf (every unselected block masked away, e.g. a tight
    LSH ball) must leave the certificate passing, not poison it with
    -inf + inf = NaN."""
    import jax
    import jax.numpy as jnp

    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(2)
    n, f, b, k, bs, ksel = 1024, 4, 8, 8, 64, 8
    Y = jnp.asarray(rng.standard_normal((n, f)).astype(np.float32))
    Q = jnp.asarray(rng.standard_normal((b, f)).astype(np.float32))
    # only the first ksel*bs rows are active: every unselected block's
    # maximum is -inf
    act = np.zeros(n, bool)
    act[:ksel * bs] = True
    ts, ti, cert = jax.device_get(sm._batch_top_n_twophase_kernel(
        Y, Q, jnp.asarray(act), None, None, k, 256, bs, ksel, 0))
    assert cert.all(), cert


def test_window_ladder_shapes():
    """Drains map to static window shapes: full 256-windows plus one
    ladder window sized to the tail, so an idle server's lone request
    pays an 8-window, not the full 256 (VERDICT r04: the 50f/20M LSH
    cell's unloaded p50 lost to the baseline purely on window
    padding)."""
    from oryx_tpu.app.als.serving_model import _window_sizes
    assert _window_sizes(1) == [8]
    assert _window_sizes(8) == [8]
    assert _window_sizes(9) == [32]
    assert _window_sizes(33) == [256]
    assert _window_sizes(256) == [256]
    assert _window_sizes(257) == [256, 8]
    assert _window_sizes(300) == [256, 256]
    assert _window_sizes(512 + 20) == [256, 256, 32]


def test_streaming_small_drain_matches_oracle():
    """A 3-query drain through the streaming two-phase path (forced at
    toy scale) pads to the 8-window and still matches the flat-path
    oracle exactly."""
    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(31)
    model = ALSServingModel(features=6, implicit=True)
    model.Y.bulk_load([f"i{j}" for j in range(4096)],
                      rng.standard_normal((4096, 6)).astype(np.float32))
    q = rng.standard_normal((3, 6)).astype(np.float32)
    old_limits = (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS,
                  sm._BLOCK_KSEL, sm._PA_TILE)
    sm._FLAT_SCORES_LIMIT = 1
    sm._MAX_CHUNK_ROWS = 1024
    sm._BLOCK_KSEL = 4
    sm._PA_TILE = 1024
    try:
        got = model.top_n_batch(5, q)
        want = [model.top_n(5, user_vector=v) for v in q]
        for g, w in zip(got, want):
            assert [i for i, _ in g] == [i for i, _ in w]
    finally:
        (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS,
         sm._BLOCK_KSEL, sm._PA_TILE) = old_limits


class _BoostRescorer:
    """Monotone-ish rescorer: halves every score; filters ids ending 7."""

    def is_filtered(self, id_):
        return id_.endswith("7")

    def rescore(self, id_, score):
        return score * 0.5


class _OnlyRescorer:
    def __init__(self, keep):
        self.keep = set(keep)

    def is_filtered(self, id_):
        return id_ not in self.keep

    def rescore(self, id_, score):
        return score


def test_rescorer_window_matches_full_scan():
    """The device top-M window path must agree with the full host scan
    for rescorers that keep enough of the head (the common case)."""
    rng = np.random.default_rng(50)
    model = ALSServingModel(features=8, implicit=True)
    model.Y.bulk_load([f"i{j}" for j in range(3000)],
                      rng.standard_normal((3000, 8)).astype(np.float32))
    q = rng.standard_normal(8).astype(np.float32)
    got = model.top_n(10, user_vector=q, rescorer=_BoostRescorer())
    want = model._host_top_n(
        np.asarray((model.Y.device_arrays()[0].astype(np.float32)
                    @ np.pad(q, (0, model.Y.device_features - 8)))),
        np.asarray(model.Y.device_arrays()[1]), 10, set(),
        _BoostRescorer(), None, False)
    assert [i for i, _ in got] == [i for i, _ in want]
    for (_, a), (_, b) in zip(got, want):
        assert abs(a - b) < 1e-4


def test_rescorer_window_falls_back_when_filtered_out():
    """A rescorer that keeps only items far below the top-M window must
    still find them (fallback to the full pull — the window form never
    changes WHICH items are reachable)."""
    rng = np.random.default_rng(51)
    model = ALSServingModel(features=4, implicit=True)
    n = 3000
    mat = rng.standard_normal((n, 4)).astype(np.float32)
    q = rng.standard_normal(4).astype(np.float32)
    scores = mat @ q
    # keep exactly the three WORST-scoring ids: guaranteed outside any
    # top-512 window
    worst = np.argsort(scores)[:3]
    keep = {f"i{j}" for j in worst}
    model.Y.bulk_load([f"i{j}" for j in range(n)], mat)
    got = model.top_n(5, user_vector=q, rescorer=_OnlyRescorer(keep))
    assert {i for i, _ in got} == keep


def test_int8_twophase_matches_oracle_interpret():
    """The int8 phase-A selection (pallas interpret mode) must return
    the same top-k as the exact flat path: quantized block maxima are
    inflated into sound upper bounds, phase B rescores exactly, and the
    certificate flags any miss."""
    import jax.numpy as jnp
    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(60)
    # ksel covers 24 of 32 blocks and k is small: the margin-inflated
    # bounds of the 8 worst blocks sit far below the 4th-best score,
    # so certificates pass robustly at toy scale (production uses
    # 64 of ~156k blocks where the gap is far wider)
    N, F, B, bs, ksel, k = 4096, 16, 8, 128, 24, 4
    Y = jnp.asarray(rng.standard_normal((N, F)).astype(np.float32))
    Q = jnp.asarray(rng.standard_normal((B, F)).astype(np.float32))
    active = jnp.ones((N,), bool)
    y8, sy_b, l1y_b = sm._quantize_items_kernel(Y, bs)
    pen_i = sm._penalty_kernel_i32(active, bs)
    old_tile = sm._PA_TILE
    sm._PA_TILE = 1024
    try:
        ts, ti, cert = sm._batch_top_n_twophase_pallas_i8(
            Y, y8, sy_b, l1y_b, Q, pen_i, active, None, None,
            k=k, bs=bs, ksel=ksel, max_bits=0, interpret=True)
    finally:
        sm._PA_TILE = old_tile
    want_s, want_i = sm._batch_top_n_kernel(Y, Q, active, k)
    import numpy as _np
    ok_rows = _np.asarray(cert)
    # rows whose certificate passed must match the oracle exactly
    assert ok_rows.sum() >= B // 2, ok_rows
    _np.testing.assert_array_equal(_np.asarray(ti)[ok_rows],
                                   _np.asarray(want_i)[ok_rows])
    _np.testing.assert_allclose(_np.asarray(ts)[ok_rows],
                                _np.asarray(want_s)[ok_rows], rtol=1e-5)


def test_int8_quantizer_bounds_are_sound():
    """Every exact block max must lie at or below the quantized bound
    (the certificate's soundness rests on this inequality)."""
    import jax.numpy as jnp
    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(61)
    N, F, B, bs = 2048, 12, 16, 128
    # adversarial-ish: heavy-tailed rows so block scales vary a lot
    Y = (rng.standard_normal((N, F))
         * rng.lognormal(0, 1.5, (N, 1))).astype(np.float32)
    Q = rng.standard_normal((B, F)).astype(np.float32)
    Yj = jnp.asarray(Y)
    y8, sy_b, l1y_b = sm._quantize_items_kernel(Yj, bs)
    sq = np.maximum(np.max(np.abs(Q), axis=1), 1e-30) / 127.0
    q8 = np.clip(np.round(Q / sq[:, None]), -127, 127)
    s_int = np.asarray(y8, np.int32) @ q8.T                  # (N, B)
    m_int = s_int.reshape(-1, bs, B).max(1)                  # (N/bs, B)
    l1q = np.abs(Q).sum(1)
    sy = np.asarray(sy_b)
    bound = (m_int * sy[:, None] * sq[None, :]
             + 0.5 * sq[None, :] * np.asarray(l1y_b)[:, None]
             + 0.5 * sy[:, None] * l1q[None, :]
             + 0.25 * F * sy[:, None] * sq[None, :])
    exact = (Y @ Q.T).reshape(-1, bs, B).max(1)
    assert (bound >= exact - 1e-4).all(), \
        float((exact - bound).max())


def test_int8_selection_dispatch_path():
    """With int8-selection forced on, the streaming dispatch routes
    through the quantized kernel (falling back to the scan build on the
    CPU test platform) and still matches the flat oracle."""
    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(62)
    model = ALSServingModel(features=6, implicit=True,
                            int8_selection="auto")
    assert model._int8_enabled()  # features 6 < 128 -> padded -> on
    model.Y.bulk_load([f"i{j}" for j in range(4096)],
                      rng.standard_normal((4096, 6)).astype(np.float32))
    q = rng.standard_normal((3, 6)).astype(np.float32)
    old_limits = (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS,
                  sm._BLOCK_KSEL, sm._PA_TILE)
    old_state = dict(sm._PALLAS_STATE)
    sm._PALLAS_STATE.clear()
    sm._FLAT_SCORES_LIMIT = 1
    sm._MAX_CHUNK_ROWS = 1024
    sm._BLOCK_KSEL = 4
    sm._PA_TILE = 1024
    try:
        got = model.top_n_batch(5, q)
        want = [model.top_n(5, user_vector=v) for v in q]
        for g, w in zip(got, want):
            assert [i for i, _ in g] == [i for i, _ in w]
    finally:
        sm._PALLAS_STATE.clear()
        sm._PALLAS_STATE.update(old_state)
        (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS,
         sm._BLOCK_KSEL, sm._PA_TILE) = old_limits
    # default-constructed models get the f<=64 auto default (ON — the
    # int8+fold mirror is the roofline lever at small F; ISSUE 3)
    assert ALSServingModel(features=6, implicit=True)._int8_enabled()
    # ... but auto stays off in the 64 < f < 128 wash zone, and at
    # unpadded widths where there is no byte tax to reclaim
    assert not ALSServingModel(features=100, implicit=True)._int8_enabled()
    assert not ALSServingModel(features=128, implicit=True)._int8_enabled()


def test_int8_certificate_passes_on_zero_padded_rows():
    """Window padding rows (all-zero queries) must not fail the int8
    certificate: their exact scores are 0 everywhere, so their bound is
    forced to -inf instead of a small positive quantization margin
    (a false failure would recompute EVERY padded drain on the exact
    scan)."""
    import jax.numpy as jnp
    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(63)
    N, F, bs, ksel, k = 2048, 16, 128, 12, 4
    Y = jnp.asarray(rng.standard_normal((N, F)).astype(np.float32))
    Q = np.zeros((8, F), np.float32)
    Q[:3] = rng.standard_normal((3, F))  # 5 zero padding rows
    active = jnp.ones((N,), bool)
    y8, sy_b, l1y_b = sm._quantize_items_kernel(Y, bs)
    pen_i = sm._penalty_kernel_i32(active, bs)
    old_tile = sm._PA_TILE
    sm._PA_TILE = 1024
    try:
        ts, ti, cert = sm._batch_top_n_twophase_pallas_i8(
            Y, y8, sy_b, l1y_b, jnp.asarray(Q), pen_i, active, None,
            None, k=k, bs=bs, ksel=ksel, max_bits=0, interpret=True)
    finally:
        sm._PA_TILE = old_tile
    assert np.asarray(cert)[3:].all()  # padding rows always certify

def test_fold_mirror_layout_matches_numpy():
    """_fold_items_kernel's slot layout: logical row i*fold + j lives
    in lanes [j*w, j*w + w) of folded row i; penalty/bucket side inputs
    land in the (fold, N//bs, bs//fold) layout the kernel reads."""
    import jax
    import jax.numpy as jnp
    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(70)
    N, F, W, fold, bs = 1024, 20, 128, 4, 128
    w = W // fold
    Y = np.zeros((N, W), np.float32)
    Y[:, :F] = rng.standard_normal((N, F)).astype(np.float32)
    act = rng.random(N) > 0.2
    bkt = rng.integers(0, 16, N).astype(np.int32)
    yf, pen_f = jax.device_get(sm._fold_items_kernel(
        jnp.asarray(Y), jnp.asarray(act), fold, bs))
    bkt_f = jax.device_get(sm._fold_buckets_kernel(
        jnp.asarray(bkt), fold, bs))
    assert yf.shape == (N // fold, W)
    for i in range(0, N // fold, 37):
        for j in range(fold):
            np.testing.assert_array_equal(yf[i, j * w:j * w + w],
                                          Y[i * fold + j, :w])
    pen = np.where(act, 0.0, -np.inf).astype(np.float32)
    assert pen_f.shape == (fold, N // bs, bs // fold)
    assert bkt_f.shape == (fold, N // bs, bs // fold)
    for j in range(fold):
        np.testing.assert_array_equal(
            pen_f[j].reshape(-1), pen.reshape(-1, fold)[:, j])
        np.testing.assert_array_equal(
            bkt_f[j].reshape(-1), bkt.reshape(-1, fold)[:, j])


def test_fold_pallas_interpret_agrees_with_scan_kernel():
    """The folded phase-A program (pallas interpret mode) must produce
    the same top-k and certificates as the lax.scan build, with and
    without the LSH mask — phase B is shared, so this pins the folded
    block maxima to the canonical ones."""
    import jax
    import jax.numpy as jnp
    from oryx_tpu.app.als import serving_model as sm

    rng = np.random.default_rng(71)
    N, F, W, B, k, bs, ksel = 8192, 20, 128, 8, 8, 128, 8
    fold = sm._fold_factor(W, F)
    assert fold == 4
    Y = np.zeros((N, W), np.float32)
    Y[:, :F] = rng.standard_normal((N, F)).astype(np.float32)
    Yj = jnp.asarray(Y)
    Q = jnp.asarray(rng.standard_normal((B, W)).astype(np.float32)
                    * np.concatenate([np.ones(F), np.zeros(W - F)]
                                     ).astype(np.float32))
    act = np.ones(N, bool)
    act[::7] = False
    active = jnp.asarray(act)
    bkt = jnp.asarray(rng.integers(0, 8, N).astype(np.int32))
    hp = jnp.asarray(rng.standard_normal((3, W)).astype(np.float32))
    old_tile = sm._PA_TILE
    sm._PA_TILE = 2048
    try:
        for buckets, hyp, mb in ((None, None, 0), (bkt, hp, 1)):
            yf, pen_f = sm._fold_items_kernel(Yj, active, fold, bs)
            bkt_f = sm._fold_buckets_kernel(buckets, fold, bs) \
                if buckets is not None else None
            ts_f, ti_f, cert_f = jax.device_get(
                sm._batch_top_n_twophase_pallas_fold(
                    Yj, yf, Q, pen_f, active, bkt_f, buckets, hyp,
                    k, bs, ksel, mb, fold, interpret=True))
            ts_s, ti_s, cert_s = jax.device_get(
                sm._batch_top_n_twophase_kernel(
                    Yj, Q, active, buckets, hyp, k, 2048, bs, ksel, mb))
            np.testing.assert_allclose(ts_f, ts_s, rtol=1e-5)
            np.testing.assert_array_equal(ti_f, ti_s)
            np.testing.assert_array_equal(cert_f, cert_s)
    finally:
        sm._PA_TILE = old_tile


def test_int8_selection_bool_normalizes_to_explicit_opt_in():
    """ADVICE r05 #1: a programmatic int8_selection=True (bool, allowed
    by the `str | bool` signature) must get the same explicit-opt-in
    precedence as the string "true" — the dispatch chain orders kinds
    by comparing against canonical strings."""
    model = ALSServingModel(features=6, implicit=True,
                            int8_selection=True)
    assert model._int8_selection == "true"
    assert model._int8_enabled()
    # False normalizes to the canonical off string, not bool identity
    off = ALSServingModel(features=6, implicit=True,
                          int8_selection=False)
    assert off._int8_selection == "false"
    assert not off._int8_enabled()
