"""RDF common-tier tests: decisions, trees, predictions, PMML
round-trip, and the device-array forest kernel (reference tests:
DecisionTreeTest.java:26, NumericDecisionTest, CategoricalDecisionTest,
CategoricalPredictionTest, NumericPredictionTest, WeightedPredictionTest,
RDFPMMLUtilsTest.java:54)."""

import numpy as np
import pytest

from oryx_tpu.app.classreg import (CategoricalPrediction, Example,
                                   NumericPrediction, example_from_tokens,
                                   vote_on_feature)
from oryx_tpu.app.rdf import pmml as rdf_pmml
from oryx_tpu.app.rdf.forest_arrays import ForestArrays, examples_to_matrix
from oryx_tpu.app.rdf.tree import (CategoricalDecision, DecisionForest,
                                   DecisionNode, DecisionTree,
                                   NumericDecision, TerminalNode)
from oryx_tpu.app.schema import CategoricalValueEncodings, InputSchema
from oryx_tpu.common.config import from_dict
from oryx_tpu.common.pmml import to_string, from_string


def _classification_schema():
    return InputSchema(from_dict({
        "oryx.input-schema.feature-names": ["color", "size", "fruit"],
        "oryx.input-schema.categorical-features": ["color", "fruit"],
        "oryx.input-schema.target-feature": "fruit"}))


def _encodings():
    return CategoricalValueEncodings({0: ["red", "green"],
                                      2: ["apple", "lime", "cherry"]})


def _classification_tree():
    # (#1 size >= 2.0) ? ((#0 color in {red}) ? cherry-ish : lime) : apple
    right = DecisionNode(
        "r+", CategoricalDecision(0, [0], False),
        TerminalNode("r+-", CategoricalPrediction([0, 9, 1])),
        TerminalNode("r++", CategoricalPrediction([1, 1, 8])),
        count=10)
    root = DecisionNode(
        "r", NumericDecision(1, 2.0, True),
        TerminalNode("r-", CategoricalPrediction([8, 1, 1])),
        right, count=20)
    return DecisionTree(root)


def test_numeric_decision():
    d = NumericDecision(1, 2.0, True)
    assert d.is_positive(Example(None, [None, 2.0, None]))
    assert not d.is_positive(Example(None, [None, 1.9, None]))
    assert d.is_positive(Example(None, [None, None, None]))  # default


def test_categorical_decision():
    d = CategoricalDecision(0, [0, 2], False)
    assert d.is_positive(Example(None, [0, None, None]))
    assert not d.is_positive(Example(None, [1, None, None]))
    assert not d.is_positive(Example(None, [None, None, None]))


def test_tree_walk_and_find_by_id():
    tree = _classification_tree()
    leaf = tree.find_terminal(Example(None, [1, 5.0, None]))
    assert leaf.id == "r+-"
    assert tree.find_by_id("r++").id == "r++"
    assert tree.find_by_id("r").id == "r"
    with pytest.raises(ValueError):
        tree.find_by_id("r--")


def test_predictions_update():
    p = CategoricalPrediction([2, 1, 0])
    assert p.get_most_probable_category_encoding() == 0
    p.update(2, 5)
    assert p.get_most_probable_category_encoding() == 2
    assert p.count == 8
    n = NumericPrediction(1.0, 1)
    n.update(3.0, 1)
    assert n.prediction == pytest.approx(2.0)
    assert n.count == 2


def test_weighted_vote():
    votes = [CategoricalPrediction([1, 0]), CategoricalPrediction([0, 1]),
             CategoricalPrediction([1, 0])]
    combined = vote_on_feature(votes, [1.0, 1.0, 1.0])
    assert combined.get_most_probable_category_encoding() == 0
    nums = [NumericPrediction(1.0, 1), NumericPrediction(2.0, 1)]
    assert vote_on_feature(nums, [1.0, 3.0]).prediction == \
        pytest.approx(1.75)


def test_example_from_tokens():
    schema = _classification_schema()
    ex = example_from_tokens(["green", "1.5", "lime"], schema, _encodings())
    assert ex.features == [1, 1.5, None]
    assert ex.target == 1
    ex2 = example_from_tokens(["red", "3", ""], schema, _encodings())
    assert ex2.target is None


def test_pmml_round_trip_classification():
    schema = _classification_schema()
    encodings = _encodings()
    forest = DecisionForest([_classification_tree(),
                             _classification_tree()],
                            [1.0, 1.0], [0.4, 0.6, 0.0])
    pmml = rdf_pmml.forest_to_pmml(forest, schema, encodings,
                                   max_depth=8, max_split_candidates=10,
                                   impurity="entropy")
    rdf_pmml.validate_pmml_vs_schema(pmml, schema)
    round_tripped = from_string(to_string(pmml))
    forest2, encodings2 = rdf_pmml.read_forest(round_tripped)
    assert len(forest2.trees) == 2
    assert encodings2.get_value_encoding_map(2) == \
        encodings.get_value_encoding_map(2)
    assert list(forest2.feature_importances) == [0.4, 0.6, 0.0]
    for tokens in (["red", "5", ""], ["green", "1", ""], ["red", "0", ""]):
        ex = example_from_tokens(tokens, schema, encodings)
        a = forest.predict(ex)
        b = forest2.predict(ex)
        assert a.get_most_probable_category_encoding() == \
            b.get_most_probable_category_encoding()
        np.testing.assert_allclose(a.category_probabilities,
                                   b.category_probabilities, atol=1e-9)
    # structural checks on the written XML
    assert 'defaultChild="r++"' not in to_string(pmml)  # default is left
    assert "weightedMajorityVote" in to_string(pmml)


def test_pmml_round_trip_regression():
    schema = InputSchema(from_dict({
        "oryx.input-schema.feature-names": ["a", "b", "y"],
        "oryx.input-schema.numeric-features": ["a", "b", "y"],
        "oryx.input-schema.target-feature": "y"}))
    encodings = CategoricalValueEncodings({})
    root = DecisionNode(
        "r", NumericDecision(0, 1.0, False),
        TerminalNode("r-", NumericPrediction(-1.5, 4)),
        TerminalNode("r+", NumericPrediction(2.5, 6)), count=10)
    forest = DecisionForest([DecisionTree(root)], [1.0], [1.0, 0.0])
    pmml = rdf_pmml.forest_to_pmml(forest, schema, encodings)
    rdf_pmml.validate_pmml_vs_schema(pmml, schema)
    forest2, _ = rdf_pmml.read_forest(from_string(to_string(pmml)))
    ex = example_from_tokens(["2.0", "0", ""], schema, encodings)
    assert forest2.predict(ex).prediction == pytest.approx(2.5)
    assert forest2.trees[0].root.count == 10


def test_validate_rejects_mismatches():
    schema = _classification_schema()
    forest = DecisionForest([_classification_tree()])
    pmml = rdf_pmml.forest_to_pmml(forest, schema, _encodings())
    other = InputSchema(from_dict({
        "oryx.input-schema.feature-names": ["x", "y", "z"],
        "oryx.input-schema.numeric-features": ["x", "y", "z"],
        "oryx.input-schema.target-feature": "z"}))
    with pytest.raises(ValueError):
        rdf_pmml.validate_pmml_vs_schema(pmml, other)


def test_forest_arrays_matches_host_walk():
    schema = _classification_schema()
    encodings = _encodings()
    forest = DecisionForest([_classification_tree(),
                             _classification_tree()])
    arrays = ForestArrays(forest, schema.num_features, num_classes=3)
    rng = np.random.default_rng(0)
    examples = []
    for _ in range(50):
        color = None if rng.random() < 0.2 else int(rng.integers(0, 2))
        size = None if rng.random() < 0.2 else float(rng.uniform(0, 4))
        examples.append(Example(None, [color, size, None]))
    x = examples_to_matrix(examples, schema.num_features)
    probs = arrays.predict_proba(x)
    ids = arrays.route_ids(x)
    for i, ex in enumerate(examples):
        expected = forest.predict(ex)
        np.testing.assert_allclose(probs[i],
                                   expected.category_probabilities,
                                   atol=1e-6)
        assert ids[0][i] == forest.trees[0].find_terminal(ex).id


def test_forest_arrays_regression():
    root = DecisionNode(
        "r", NumericDecision(0, 0.0, False),
        TerminalNode("r-", NumericPrediction(-1.0, 1)),
        TerminalNode("r+", NumericPrediction(1.0, 1)))
    forest = DecisionForest([DecisionTree(root)])
    arrays = ForestArrays(forest, 1, num_classes=0)
    out = arrays.predict_value(np.array([[-3.0], [4.0]], dtype=np.float32))
    np.testing.assert_allclose(out, [-1.0, 1.0])
