"""A stateful in-process fake of the ``kafka-python`` client API.

Why this exists: the real-Kafka binding (`oryx_tpu/kafka/client.py`)
is written against kafka-python, but the hermetic test image has
neither that library nor a broker to point it at, and nothing may be
installed.  The unit tests in test_kafka_client.py inject per-test
stubs, which proves call sequences but not SEMANTICS.  This module is
the next-strongest evidence available in this environment: one
broker-state machine — topics, partitions, append logs, consumer-group
committed offsets, auto_offset_reset rules, poll batching, blocking
polls — shared by every producer/consumer/admin client the binding
creates, so the full broker contract suite (produce/replay, group
resume, fill-in-latest, multi-partition drains) runs through the REAL
client code against one consistent implementation of Kafka's visible
behavior.  (The reference proves its broker code against an actual
in-process Kafka, LocalKafkaBroker.java:35; a wire-protocol server
would be pointless here with no real client library to speak to it.)

Install with :func:`install` — it registers ``kafka``, ``kafka.admin``,
``kafka.structs`` and ``kafka.errors`` modules in ``sys.modules`` only
when the real library is absent.
"""

from __future__ import annotations

import sys
import threading
import time
import types
import zlib
from collections import namedtuple

TopicPartition = namedtuple("TopicPartition", ["topic", "partition"])
OffsetAndMetadata = namedtuple("OffsetAndMetadata", ["offset", "metadata"])
ConsumerRecord = namedtuple(
    "ConsumerRecord", ["topic", "partition", "offset", "key", "value"])
RecordMetadata = namedtuple(
    "RecordMetadata", ["topic", "partition", "offset"])

MAX_POLL_RECORDS = 500


class KafkaError(Exception):
    pass


class TopicAlreadyExistsError(KafkaError):
    pass


class UnknownTopicOrPartitionError(KafkaError):
    pass


class _Cluster:
    """All broker-visible state for one bootstrap address."""

    _registry: dict[str, "_Cluster"] = {}
    _registry_lock = threading.Lock()

    @classmethod
    def get(cls, bootstrap) -> "_Cluster":
        key = str(bootstrap)
        with cls._registry_lock:
            c = cls._registry.get(key)
            if c is None:
                c = cls._registry[key] = _Cluster()
            return c

    def __init__(self):
        self.cond = threading.Condition()
        # topic -> list of partition logs; log entry = (key, value) bytes
        self.topics: dict[str, list[list[tuple[bytes | None,
                                               bytes | None]]]] = {}
        # (group, topic, partition) -> committed offset
        self.offsets: dict[tuple[str, str, int], int] = {}
        self._round_robin: dict[str, int] = {}

    # -- broker operations ---------------------------------------------------

    def create_topic(self, name: str, partitions: int) -> None:
        with self.cond:
            if name in self.topics:
                raise TopicAlreadyExistsError(name)
            self.topics[name] = [[] for _ in range(partitions)]

    def delete_topic(self, name: str) -> None:
        with self.cond:
            if name not in self.topics:
                raise UnknownTopicOrPartitionError(name)
            del self.topics[name]
            for k in [k for k in self.offsets if k[1] == name]:
                del self.offsets[k]

    def append(self, topic: str, key: bytes | None,
               value: bytes | None) -> tuple[int, int]:
        """(partition, offset); auto-creates a 1-partition topic like a
        default broker (auto.create.topics.enable=true)."""
        with self.cond:
            logs = self.topics.get(topic)
            if logs is None:
                logs = self.topics[topic] = [[]]
            n = len(logs)
            if key is None:
                p = self._round_robin.get(topic, 0) % n
                self._round_robin[topic] = p + 1
            else:
                p = zlib.crc32(key) % n
            logs[p].append((key, value))
            self.cond.notify_all()
            return p, len(logs[p]) - 1

    def partitions(self, topic: str) -> set[int] | None:
        with self.cond:
            logs = self.topics.get(topic)
            return None if logs is None else set(range(len(logs)))

    def end_offset(self, topic: str, partition: int) -> int:
        with self.cond:
            logs = self.topics.get(topic)
            if logs is None or partition >= len(logs):
                return 0
            return len(logs[partition])


class _Future:
    def __init__(self, meta: RecordMetadata):
        self._meta = meta

    def get(self, timeout=None) -> RecordMetadata:
        return self._meta


class KafkaProducer:
    def __init__(self, bootstrap_servers=None, **_kw):
        self._cluster = _Cluster.get(bootstrap_servers)
        self._closed = False

    def send(self, topic, value=None, key=None) -> _Future:
        if self._closed:
            raise KafkaError("producer is closed")
        p, off = self._cluster.append(topic, key, value)
        return _Future(RecordMetadata(topic, p, off))

    def flush(self, timeout=None) -> None:
        pass  # appends are synchronous in the fake

    def close(self, timeout=None) -> None:
        self._closed = True


class KafkaConsumer:
    def __init__(self, bootstrap_servers=None, group_id=None,
                 enable_auto_commit=False, auto_offset_reset="latest",
                 **_kw):
        self._cluster = _Cluster.get(bootstrap_servers)
        self._group = group_id
        self._reset = auto_offset_reset
        self._assigned: list[TopicPartition] = []
        self._subscribed: list[str] = []
        self._positions: dict[TopicPartition, int] = {}
        self._closed = False

    # -- metadata ------------------------------------------------------------

    def partitions_for_topic(self, topic):
        return self._cluster.partitions(topic)

    def end_offsets(self, tps):
        return {tp: self._cluster.end_offset(tp.topic, tp.partition)
                for tp in tps}

    # -- assignment ----------------------------------------------------------

    def assign(self, tps) -> None:
        self._subscribed = []
        self._assigned = list(tps)
        self._positions = {tp: p for tp, p in self._positions.items()
                           if tp in self._assigned}

    def subscribe(self, topics) -> None:
        """Single-member group: this consumer gets every partition (a
        real group with one member resolves to the same assignment)."""
        self._subscribed = list(topics)
        self._refresh_subscription()

    def _refresh_subscription(self) -> None:
        if not self._subscribed:
            return
        assigned = []
        for t in self._subscribed:
            parts = self._cluster.partitions(t)
            for p in sorted(parts or ()):
                assigned.append(TopicPartition(t, p))
        self._assigned = assigned

    def unsubscribe(self) -> None:
        self._subscribed = []
        self._assigned = []
        self._positions = {}

    def seek(self, tp, offset) -> None:
        self._positions[tp] = offset

    def position(self, tp) -> int:
        if tp not in self._positions:
            self._positions[tp] = self._initial_position(tp)
        return self._positions[tp]

    def _initial_position(self, tp) -> int:
        if self._group is not None:
            committed = self._cluster.offsets.get(
                (self._group, tp.topic, tp.partition))
            if committed is not None:
                return committed
        if self._reset == "earliest":
            return 0
        return self._cluster.end_offset(tp.topic, tp.partition)

    # -- consumption ---------------------------------------------------------

    def poll(self, timeout_ms=0, max_records=None):
        if self._closed:
            raise KafkaError("consumer is closed")
        limit = max_records or MAX_POLL_RECORDS
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            # a subscription sees partitions/topics created after it
            # (real consumers refresh metadata periodically)
            self._refresh_subscription()
            out: dict[TopicPartition, list[ConsumerRecord]] = {}
            total = 0
            with self._cluster.cond:
                for tp in self._assigned:
                    pos = self.position(tp)
                    end = self._cluster.end_offset(tp.topic, tp.partition)
                    take = min(end - pos, limit - total)
                    if take <= 0:
                        continue
                    log = self._cluster.topics[tp.topic][tp.partition]
                    recs = [ConsumerRecord(tp.topic, tp.partition,
                                           pos + i, *log[pos + i])
                            for i in range(take)]
                    self._positions[tp] = pos + take
                    out[tp] = recs
                    total += take
                if out or time.monotonic() >= deadline:
                    return out
                # block until new data or the poll timeout, like a real
                # long poll
                self._cluster.cond.wait(
                    max(0.0, deadline - time.monotonic()))

    # -- offsets -------------------------------------------------------------

    def committed(self, tp):
        if self._group is None:
            return None
        return self._cluster.offsets.get(
            (self._group, tp.topic, tp.partition))

    def commit(self, offsets=None) -> None:
        if self._group is None:
            raise KafkaError("commit requires a group id")
        if offsets is None:
            offsets = {tp: OffsetAndMetadata(pos, None)
                       for tp, pos in self._positions.items()}
        with self._cluster.cond:
            for tp, om in offsets.items():
                off = om.offset if hasattr(om, "offset") else int(om)
                self._cluster.offsets[
                    (self._group, tp.topic, tp.partition)] = off

    def close(self, *a, **kw) -> None:
        self._closed = True
        self.unsubscribe()


class NewTopic:
    def __init__(self, name, num_partitions=1, replication_factor=1):
        self.name = name
        self.num_partitions = num_partitions
        self.replication_factor = replication_factor


class KafkaAdminClient:
    def __init__(self, bootstrap_servers=None, **_kw):
        self._cluster = _Cluster.get(bootstrap_servers)

    def list_topics(self):
        with self._cluster.cond:
            return list(self._cluster.topics)

    def create_topics(self, new_topics) -> None:
        for nt in new_topics:
            self._cluster.create_topic(nt.name, nt.num_partitions)

    def delete_topics(self, topics) -> None:
        for t in topics:
            self._cluster.delete_topic(t)

    def close(self) -> None:
        pass


def install() -> None:
    """Register the fake as ``kafka``/``kafka.admin``/``kafka.structs``/
    ``kafka.errors`` unless the real kafka-python is importable."""
    if "kafka" in sys.modules and not getattr(
            sys.modules["kafka"], "_ORYX_FAKE", False):
        return  # a real (or other) kafka module is already loaded
    try:
        import importlib.util
        if importlib.util.find_spec("kafka") is not None \
                and "kafka" not in sys.modules:
            return  # real library present on disk; let it win
    except (ImportError, ValueError):
        pass
    root = types.ModuleType("kafka")
    root._ORYX_FAKE = True
    root.KafkaConsumer = KafkaConsumer
    root.KafkaProducer = KafkaProducer
    root.TopicPartition = TopicPartition
    admin = types.ModuleType("kafka.admin")
    admin.KafkaAdminClient = KafkaAdminClient
    admin.NewTopic = NewTopic
    structs = types.ModuleType("kafka.structs")
    structs.OffsetAndMetadata = OffsetAndMetadata
    structs.TopicPartition = TopicPartition
    errors = types.ModuleType("kafka.errors")
    errors.KafkaError = KafkaError
    errors.TopicAlreadyExistsError = TopicAlreadyExistsError
    errors.UnknownTopicOrPartitionError = UnknownTopicOrPartitionError
    root.admin = admin
    root.structs = structs
    root.errors = errors
    sys.modules["kafka"] = root
    sys.modules["kafka.admin"] = admin
    sys.modules["kafka.structs"] = structs
    sys.modules["kafka.errors"] = errors
