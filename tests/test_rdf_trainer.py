"""Forest trainer tests: learn separable synthetic problems and check
the model form (reference analog: RDFUpdateIT and MLlib-backed
behavior asserted through accuracy rather than structure)."""

import numpy as np
import pytest

from oryx_tpu.app.classreg import example_from_tokens
from oryx_tpu.app.rdf.forest_arrays import ForestArrays
from oryx_tpu.app.rdf.trainer import train_forest
from oryx_tpu.app.schema import CategoricalValueEncodings, InputSchema
from oryx_tpu.common.config import from_dict


def _classification_schema():
    return InputSchema(from_dict({
        "oryx.input-schema.feature-names": ["a", "b", "color", "label"],
        "oryx.input-schema.categorical-features": ["color", "label"],
        "oryx.input-schema.target-feature": "label"}))


def test_classification_forest_learns():
    rng = np.random.default_rng(7)
    n = 600
    a = rng.uniform(-1, 1, n)
    b = rng.uniform(-1, 1, n)
    color = rng.integers(0, 3, n)
    # label: 1 if a >= 0.2, else 0 unless color == 2 -> 1
    y = np.where(a >= 0.2, 1, np.where(color == 2, 1, 0))
    x = np.stack([a, b, color.astype(float)], axis=1).astype(np.float32)
    schema = _classification_schema()
    forest = train_forest(x, y, schema, category_counts={2: 3},
                          num_trees=5, max_depth=4,
                          max_split_candidates=16, impurity="gini",
                          seed=123, num_classes=2)
    assert len(forest.trees) == 5
    arrays = ForestArrays(forest, schema.num_features, num_classes=2)
    full = np.full((n, 4), np.nan, dtype=np.float32)
    full[:, 0], full[:, 1], full[:, 2] = a, b, color
    pred = arrays.predict_proba(full).argmax(axis=1)
    accuracy = (pred == y).mean()
    assert accuracy > 0.95
    # importances: 'a' and 'color' should dominate over noise feature 'b'
    imp = forest.feature_importances
    assert imp[0] > imp[1]
    assert imp.sum() == pytest.approx(1.0)
    assert imp[3] == 0.0  # target has no importance
    # record counts: root count equals the full training-set size
    for tree in forest.trees:
        assert tree.root.count == n or tree.root.is_terminal


def test_regression_forest_learns():
    rng = np.random.default_rng(3)
    n = 500
    a = rng.uniform(0, 4, n)
    y = np.where(a < 2.0, 1.0, 5.0) + rng.normal(0, 0.05, n)
    x = a[:, None].astype(np.float32)
    schema = InputSchema(from_dict({
        "oryx.input-schema.feature-names": ["a", "y"],
        "oryx.input-schema.numeric-features": ["a", "y"],
        "oryx.input-schema.target-feature": "y"}))
    forest = train_forest(x, y, schema, category_counts={},
                          num_trees=3, max_depth=3,
                          max_split_candidates=32, impurity="variance",
                          seed=5)
    arrays = ForestArrays(forest, 2, num_classes=0)
    test = np.array([[0.5, np.nan], [3.5, np.nan]], dtype=np.float32)
    out = arrays.predict_value(test)
    assert abs(out[0] - 1.0) < 0.3
    assert abs(out[1] - 5.0) < 0.3


def test_trainer_determinism_and_validation():
    x = np.array([[0.0], [1.0], [2.0], [3.0]] * 10, dtype=np.float32)
    y = np.array([0, 0, 1, 1] * 10)
    schema = InputSchema(from_dict({
        "oryx.input-schema.feature-names": ["a", "label"],
        "oryx.input-schema.categorical-features": ["label"],
        "oryx.input-schema.target-feature": "label"}))
    f1 = train_forest(x, y, schema, {}, 2, 3, 8, "entropy", seed=9,
                      num_classes=2)
    f2 = train_forest(x, y, schema, {}, 2, 3, 8, "entropy", seed=9,
                      num_classes=2)
    for t1, t2 in zip(f1.trees, f2.trees):
        assert [n.id for n in t1.nodes()] == [n.id for n in t2.nodes()]
    with pytest.raises(ValueError):
        train_forest(x, y, schema, {}, 2, 3, 8, "variance", seed=9)
    with pytest.raises(ValueError):
        train_forest(x, y, schema, {0: 100}, 2, 3, 8, "gini", seed=9)


def test_advance_matches_numpy_oracle_at_wide_frontier():
    """The MXU-formulated advance must route bit-identically to a plain
    per-example walk, including child slot ids past 256 (a bf16-operand
    matmul pass would round those — the fetch matmul must run exact
    f32 passes)."""
    import jax.numpy as jnp
    from oryx_tpu.app.rdf.trainer import _advance_body

    rng = np.random.default_rng(44)
    T, B, P, M, S = 3, 5000, 6, 512, 16
    slot_of = rng.integers(-1, M, (T, B)).astype(np.int32)
    binned = rng.integers(0, S, (B, P)).astype(np.int32)
    split = rng.random((T, M)) < 0.8
    best_p = rng.integers(0, P, (T, M)).astype(np.int32)
    best_b = rng.integers(0, S - 1, (T, M)).astype(np.int32)
    is_cat = rng.random((T, M)) < 0.3
    rmask = rng.random((T, M, S)) < 0.5
    child = rng.integers(0, 2 * M, (T, M, 2)).astype(np.int32)

    got = np.asarray(_advance_body(
        jnp.asarray(slot_of), jnp.asarray(binned), jnp.asarray(split),
        jnp.asarray(best_p), jnp.asarray(best_b), jnp.asarray(is_cat),
        jnp.asarray(rmask), jnp.asarray(child)))

    want = np.full((T, B), -1, np.int32)
    for t in range(T):
        for b in range(B):
            s = slot_of[t, b]
            if s < 0 or not split[t, s]:
                continue
            p = best_p[t, s]
            v = binned[b, p]
            right = rmask[t, s, v] if is_cat[t, s] else v > best_b[t, s]
            want[t, b] = child[t, s, 1 if right else 0]
    np.testing.assert_array_equal(got, want)


def test_slot_counts_match_numpy():
    import jax.numpy as jnp
    from oryx_tpu.app.rdf.trainer import _slot_counts

    rng = np.random.default_rng(45)
    T, B, M = 4, 3000, 64
    slot_of = rng.integers(-1, M, (T, B)).astype(np.int32)
    got = np.asarray(_slot_counts(jnp.asarray(slot_of), M))
    for t in range(T):
        alive = slot_of[t][slot_of[t] >= 0]
        want = np.bincount(alive, minlength=M)
        np.testing.assert_array_equal(got[t], want)
