"""Sharded model distribution (ISSUE 10): slice partitioning property
tests on the test_cluster_merge oracle harness, slice-loaded vs
replay-loaded byte-identity across the serving surface, the
``store-slice-missing`` chaos point's fail-closed fallback, ring
compatibility, envelope back-compat, and the batch publisher's
end-to-end manifest publish.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from oryx_tpu.app.als import slices
from oryx_tpu.app.als.serving_manager import ALSServingModelManager
from oryx_tpu.app.als.speed import ALSSpeedModelManager
from oryx_tpu.cluster.merge import exact_local_top_n, merge_top_n
from oryx_tpu.cluster.sharding import is_local_item, shard_of
from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_MODEL, KEY_MODEL_REF, KEY_UP, KeyMessage
from oryx_tpu.resilience import faults

FEATURES = 4
RING = 24


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _grid_vec(rng) -> list[float]:
    """Vectors on a coarse grid (multiples of 1/4): every dot product
    is exact in float32, so byte-identity claims are deterministic —
    the same trick as test_cluster_merge."""
    return [float(x) / 4.0 for x in rng.integers(-8, 9, FEATURES)]


def _catalog(rng, n_items=120, n_users=10, distinct=14):
    pool = [_grid_vec(rng) for _ in range(distinct)]
    y_ids = [f"i{j}" for j in range(n_items)]
    x_ids = [f"u{j}" for j in range(n_users)]
    Y = np.asarray([pool[int(rng.integers(0, distinct))]
                    for _ in y_ids], dtype=np.float32)
    X = np.asarray([_grid_vec(rng) for _ in x_ids], dtype=np.float32)
    known = {u: sorted(y_ids[k] for k in
                       rng.choice(n_items, size=5, replace=False))
             for u in x_ids}
    return y_ids, Y, x_ids, X, known


def _publish(tmp_path, y_ids, Y, x_ids, X, known, ring=RING):
    model_dir = str(tmp_path / "model")
    os.makedirs(model_dir, exist_ok=True)
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", FEATURES)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", x_ids)
    pmml_io.add_extension_content(doc, "YIDs", y_ids)
    pmml_path = model_dir + "/model.pmml.xml"
    pmml_io.write(doc, pmml_path)
    slim = slices.publish_sliced(model_dir, y_ids, Y, x_ids, X, known,
                                 ring)
    return (model_dir, pmml_path, slim,
            slices.model_ref_message(pmml_path, model_dir, slim))


def _manager(spec: str) -> ALSServingModelManager:
    return ALSServingModelManager(from_dict({
        "oryx.serving.model-manager-class": "unused",
        "oryx.cluster.enabled": True,
        "oryx.cluster.shard": spec,
        "oryx.input-topic.broker": None,
        "oryx.update-topic.broker": None,
    }))


def _replay_manager(spec, y_ids, Y, x_ids, X, known):
    """The OLD distribution: inline MODEL + the full per-row UP stream
    rendered exactly as ALSUpdate.publish_additional_model_data
    renders it — the reference baseline every slice-loaded replica
    must be byte-identical to."""
    mgr = _manager(spec)
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", FEATURES)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", x_ids)
    pmml_io.add_extension_content(doc, "YIDs", y_ids)
    mgr.consume_key_message(KEY_MODEL, pmml_io.to_string(doc))
    for iid, row in zip(y_ids, Y):
        mgr.consume_key_message(KEY_UP, json.dumps(
            ["Y", iid, [float(v) for v in row]]))
    for uid, row in zip(x_ids, X):
        mgr.consume_key_message(KEY_UP, json.dumps(
            ["X", uid, [float(v) for v in row], known.get(uid, [])]))
    return mgr


# -- slice partitioning properties -------------------------------------------

@pytest.mark.parametrize("shards", [1, 2, 3, 4])
def test_slices_partition_the_catalog_exactly(tmp_path, shards):
    rng = np.random.default_rng(100 + shards)
    y_ids, Y, x_ids, X, known = _catalog(rng)
    _, _, slim, msg = _publish(tmp_path, y_ids, Y, x_ids, X, known)
    assert slim["ring"] == RING and "gramians" not in slim
    mgrs = [_manager(f"{s}/{shards}") for s in range(shards)]
    for m in mgrs:
        m.consume_key_message(KEY_MODEL_REF, msg)
        assert m.slice_load_fallbacks == 0
        assert m.slice_loads == RING // shards
    held = [set(m.model.Y.all_ids()) for m in mgrs]
    # pairwise disjoint, union == catalog, each shard exactly its
    # murmur2 cut
    for a in range(shards):
        for b in range(a + 1, shards):
            assert held[a].isdisjoint(held[b])
        assert held[a] == {i for i in y_ids
                           if is_local_item(i, a, shards)}
    assert set().union(*held) == set(y_ids)
    # the user store and known-items are FULL on every shard
    for m in mgrs:
        assert len(m.model.X) == len(x_ids)
        assert m.model.get_known_items(x_ids[0]) == set(known[x_ids[0]])
        assert m.model.get_fraction_loaded() == 1.0


@pytest.mark.parametrize("shards", [2, 3])
def test_per_slice_gramians_sum_to_full_yty(tmp_path, shards):
    """Sum over every shard's partial_yty == the full catalog YtY of
    the float32 rows consumers hold, within the docs/NUMERICS.md
    row-partition bound (f64 accumulation, reassociation only)."""
    rng = np.random.default_rng(7 + shards)
    y_ids, Y, x_ids, X, known = _catalog(rng, n_items=200)
    _, _, _, msg = _publish(tmp_path, y_ids, Y, x_ids, X, known)
    mgrs = [_manager(f"{s}/{shards}") for s in range(shards)]
    for m in mgrs:
        m.consume_key_message(KEY_MODEL_REF, msg)
    total = sum(m.partial_yty() for m in mgrs)
    want = Y.astype(np.float64).T @ Y.astype(np.float64)
    np.testing.assert_allclose(total, want, rtol=1e-9, atol=1e-9)
    # and it matches what a device scan of the loaded store reports
    scan = sum(np.asarray(m.model.Y.vtv(), dtype=np.float64)
               for m in mgrs)
    np.testing.assert_allclose(total, scan, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("shards", [2, 3])
def test_slice_loaded_replica_is_byte_identical_to_replay_loaded(
        tmp_path, shards):
    """The acceptance property: a slice-loaded shard answers
    byte-identically (ids, scores, ordinals — and therefore every
    rendered response) to a replica that replayed the full UP stream,
    and the merged cluster answer equals the full single-node one."""
    rng = np.random.default_rng(40 + shards)
    y_ids, Y, x_ids, X, known = _catalog(rng)
    _, _, _, msg = _publish(tmp_path, y_ids, Y, x_ids, X, known)

    sliced = [_manager(f"{s}/{shards}") for s in range(shards)]
    for m in sliced:
        m.consume_key_message(KEY_MODEL_REF, msg)
    replayed = [_replay_manager(f"{s}/{shards}", y_ids, Y, x_ids, X,
                                known) for s in range(shards)]
    full = _replay_manager("0/1", y_ids, Y, x_ids, X, known)

    for m_s, m_r in zip(sliced, replayed):
        assert sorted(m_s.model.Y.all_ids()) == \
            sorted(m_r.model.Y.all_ids())
        for iid in m_s.model.Y.all_ids():
            np.testing.assert_array_equal(
                m_s.model.get_item_vector(iid),
                m_r.model.get_item_vector(iid), err_msg=iid)
        # ordinals agree wherever both know the id (the replayed
        # manager knows every id; the sliced one its locals)
        for iid, o in m_s.item_ordinals.items():
            assert m_r.item_ordinals[iid] == o, iid
        assert m_s._ordinal_next == m_r._ordinal_next

    def ordinal_of(m):
        return lambda i, m=m: m.item_ordinals.get(i, 1 << 62)

    for u in range(4):
        uid = f"u{u}"
        xu = full.model.get_user_vector(uid)
        exclude = full.model.get_known_items(uid)
        for how_many in (3, 10, 25):
            per_sliced = [exact_local_top_n(
                m.model, ordinal_of(m), how_many, user_vector=xu,
                exclude=exclude) for m in sliced]
            per_replayed = [exact_local_top_n(
                m.model, ordinal_of(m), how_many, user_vector=xu,
                exclude=exclude) for m in replayed]
            assert per_sliced == per_replayed, (uid, how_many)
            merged = merge_top_n(per_sliced, how_many)
            single = exact_local_top_n(
                full.model, ordinal_of(full), how_many, user_vector=xu,
                exclude=exclude)
            assert merged == single[:how_many], (uid, how_many)


def test_post_publish_up_tail_keeps_ordinals_consistent(tmp_path):
    """New items arriving on the topic tail after a sliced publish get
    the SAME ordinal on every replica, whichever slices it loaded —
    the counter advances from the manifest's total item count."""
    rng = np.random.default_rng(3)
    y_ids, Y, x_ids, X, known = _catalog(rng, n_items=60)
    _, _, _, msg = _publish(tmp_path, y_ids, Y, x_ids, X, known)
    mgrs = [_manager(f"{s}/3") for s in range(3)] + [_manager("0/1")]
    for m in mgrs:
        m.consume_key_message(KEY_MODEL_REF, msg)
    for j in range(5):
        up = json.dumps(["Y", f"new{j}", _grid_vec(rng)])
        for m in mgrs:
            m.consume_key_message(KEY_UP, up)
    for m in mgrs:
        for j in range(5):
            assert m.item_ordinals[f"new{j}"] == len(y_ids) + j
    # and each lands on exactly one shard of the 3-way ring
    for j in range(5):
        holders = [m for m in mgrs[:3]
                   if f"new{j}" in m.model.Y.all_ids()]
        assert len(holders) == 1
        assert shard_of(f"new{j}", 3) == holders[0].shard_index


def test_up_update_to_existing_remote_item_keeps_counters_aligned(
        tmp_path):
    """Review-hardening regression: a fold-in Y record for an EXISTING
    item must advance every replica's ordinal counter identically even
    on replicas that never slice-loaded that item's ordinal (they
    cannot tell a remote manifest item from a new one) — otherwise the
    NEXT genuinely new id gets different ordinals per replica and the
    cluster merge's tie-break diverges by load mode."""
    rng = np.random.default_rng(14)
    y_ids, Y, x_ids, X, known = _catalog(rng, n_items=60)
    _, _, _, msg = _publish(tmp_path, y_ids, Y, x_ids, X, known)
    shard0, shard1 = _manager("0/2"), _manager("1/2")
    replayed = _replay_manager("0/1", y_ids, Y, x_ids, X, known)
    for m in (shard0, shard1):
        m.consume_key_message(KEY_MODEL_REF, msg)
    # an existing item owned by shard 0: shard 1 never loaded its
    # ordinal, the replayed manager knows it
    existing = next(i for i in y_ids if shard_of(i, 2) == 0)
    up = json.dumps(["Y", existing, _grid_vec(rng)])
    for m in (shard0, shard1, replayed):
        m.consume_key_message(KEY_UP, up)
    # its ordinal stays STABLE wherever it was known
    assert shard0.item_ordinals[existing] == \
        replayed.item_ordinals[existing] == y_ids.index(existing)
    # ...and the next NEW item's ordinal agrees on EVERY replica
    up_new = json.dumps(["Y", "brand-new", _grid_vec(rng)])
    for m in (shard0, shard1, replayed):
        m.consume_key_message(KEY_UP, up_new)
    assert shard0.item_ordinals["brand-new"] \
        == shard1.item_ordinals["brand-new"] \
        == replayed.item_ordinals["brand-new"]


# -- fail-closed fallback (chaos point store-slice-missing) -------------------

@pytest.mark.chaos
def test_corrupt_slice_fails_closed_to_full_artifact_load(tmp_path):
    """A checksum-failing slice (chaos: ``store-slice-missing``) falls
    back to the monolithic Y/X artifacts: the replica still reaches
    ready with the exact same state, and the fallback is counted."""
    rng = np.random.default_rng(9)
    y_ids, Y, x_ids, X, known = _catalog(rng)
    model_dir, _, _, msg = _publish(tmp_path, y_ids, Y, x_ids, X, known)
    # the monolithic artifacts the fallback reads (the real publisher
    # writes them before the slices; known-items are carried by the UP
    # stream in the pure-reference flow, so the fallback skips them)
    from oryx_tpu.app.als.update import save_features
    save_features(model_dir + "/Y", y_ids, Y)
    save_features(model_dir + "/X", x_ids, X)

    faults.inject("store-slice-missing", mode="error", times=1)
    mgr = _manager("0/2")
    mgr.consume_key_message(KEY_MODEL_REF, msg)
    assert faults.fired("store-slice-missing") == 1
    assert mgr.slice_load_fallbacks == 1
    assert mgr.model.get_fraction_loaded() == 1.0  # still READY
    # state equals a clean slice load's
    clean = _manager("0/2")
    clean.consume_key_message(KEY_MODEL_REF, msg)
    assert sorted(mgr.model.Y.all_ids()) == \
        sorted(clean.model.Y.all_ids())
    for iid in mgr.model.Y.all_ids():
        np.testing.assert_array_equal(mgr.model.get_item_vector(iid),
                                      clean.model.get_item_vector(iid))
        assert mgr.item_ordinals[iid] == clean.item_ordinals[iid]
    assert mgr._ordinal_next == clean._ordinal_next
    # no fresh manifest Gramian on the fallback path: /shard/yty scans
    assert mgr.partial_yty() is None


def test_truncated_slice_artifact_is_a_checksum_failure(tmp_path):
    rng = np.random.default_rng(10)
    y_ids, Y, x_ids, X, known = _catalog(rng, n_items=40)
    model_dir, _, slim, msg = _publish(tmp_path, y_ids, Y, x_ids, X,
                                       known)
    from oryx_tpu.app.als.update import save_features
    save_features(model_dir + "/Y", y_ids, Y)
    save_features(model_dir + "/X", x_ids, X)
    # truncate one slice the 0/2 shard owns (slice 0)
    victim = os.path.join(model_dir, slim["slices"][0]["path"])
    with open(victim, "r+b") as f:
        f.truncate(max(1, os.path.getsize(victim) // 2))
    mgr = _manager("0/2")
    mgr.consume_key_message(KEY_MODEL_REF, msg)
    assert mgr.slice_load_fallbacks == 1
    assert mgr.model.get_fraction_loaded() == 1.0
    assert sorted(mgr.model.Y.all_ids()) == \
        sorted(i for i in y_ids if is_local_item(i, 0, 2))


def test_incompatible_ring_falls_back(tmp_path):
    """A shard count that does not divide the ring cannot map whole
    slices to shards: the replica falls back to the monolithic
    artifacts (O(catalog) but correct) and still reaches ready."""
    rng = np.random.default_rng(11)
    y_ids, Y, x_ids, X, known = _catalog(rng, n_items=50)
    model_dir, _, _, msg = _publish(tmp_path, y_ids, Y, x_ids, X,
                                    known, ring=24)
    from oryx_tpu.app.als.update import save_features
    save_features(model_dir + "/Y", y_ids, Y)
    save_features(model_dir + "/X", x_ids, X)
    mgr = _manager("2/5")  # 5 does not divide 24
    mgr.consume_key_message(KEY_MODEL_REF, msg)
    assert mgr.slice_load_fallbacks == 1 and mgr.slice_loads == 0
    assert mgr.model.get_fraction_loaded() == 1.0
    assert sorted(mgr.model.Y.all_ids()) == \
        sorted(i for i in y_ids if is_local_item(i, 2, 5))


def test_owned_slices_contract():
    assert slices.owned_slices(24, 0, 1) == list(range(24))
    assert slices.owned_slices(24, 1, 2) == [j for j in range(24)
                                             if j % 2 == 1]
    assert slices.owned_slices(24, 2, 3) == [2, 5, 8, 11, 14, 17, 20, 23]
    assert slices.owned_slices(24, 0, 5) is None
    # the mapping really is murmur2-consistent: every id in slice j
    # belongs to shard j % N
    for iid in (f"x{i}" for i in range(200)):
        j = shard_of(iid, 24)
        assert shard_of(iid, 3) == j % 3
        assert shard_of(iid, 2) == j % 2


# -- envelope back-compat -----------------------------------------------------

def test_bare_path_model_ref_still_replays(tmp_path):
    """Pre-manifest MODEL-REF payloads (a bare path) keep the exact old
    behavior: PMML loads, no slice load, the UP stream fills the
    model."""
    rng = np.random.default_rng(12)
    y_ids, Y, x_ids, X, known = _catalog(rng, n_items=30)
    _, pmml_path, _, _ = _publish(tmp_path, y_ids, Y, x_ids, X, known)
    mgr = _manager("0/1")
    mgr.consume_key_message(KEY_MODEL_REF, pmml_path)  # bare path
    assert mgr.slice_loads == 0 and mgr.slice_load_fallbacks == 0
    assert len(mgr.model.Y) == 0  # awaiting the UP stream, as ever
    mgr.consume_key_message(KEY_UP, json.dumps(
        ["Y", y_ids[0], [float(v) for v in Y[0]]]))
    assert len(mgr.model.Y) == 1


def test_parse_model_ref_forms():
    assert slices.parse_model_ref("/a/b/model.pmml.xml") == \
        ("/a/b/model.pmml.xml", None, None)
    path, d, m = slices.parse_model_ref(
        json.dumps({"path": "/p/m.xml", "dir": "/p",
                    "manifest": {"ring": 4}}))
    assert (path, d, m) == ("/p/m.xml", "/p", {"ring": 4})
    # malformed envelope degrades to bare-path (warn, don't die)
    path, d, m = slices.parse_model_ref("{not json")
    assert path == "{not json" and d is None and m is None


def test_speed_manager_bulk_loads_every_slice(tmp_path):
    rng = np.random.default_rng(13)
    y_ids, Y, x_ids, X, known = _catalog(rng)
    _, _, _, msg = _publish(tmp_path, y_ids, Y, x_ids, X, known)
    mgr = ALSSpeedModelManager(from_dict({
        "oryx.speed.model-manager-class": "unused"}))
    mgr.consume_key_message(KEY_MODEL_REF, msg)
    assert mgr.slice_loads == RING and mgr.slice_load_fallbacks == 0
    assert len(mgr.model.Y) == len(y_ids)
    assert len(mgr.model.X) == len(x_ids)
    assert mgr.model.get_fraction_loaded() == 1.0
    np.testing.assert_array_equal(mgr.model.get_item_vector(y_ids[3]),
                                  Y[3])


# -- the batch publisher end-to-end -------------------------------------------

class _CollectingProducer:
    def __init__(self):
        self.sent: list[tuple[str, str]] = []

    def send(self, key, message, headers=None):
        self.sent.append((key, message))


def _als_update_config(tmp_path, max_size=600):
    return from_dict({
        "oryx.als.hyperparams.features": FEATURES,
        "oryx.als.implicit": True,
        "oryx.als.iterations": 2,
        "oryx.ml.eval.test-fraction": 0.0,
        "oryx.ml.eval.candidates": 1,
        "oryx.update-topic.message.max-size": max_size,
        "oryx.batch.storage.model-dir": str(tmp_path / "models"),
    })


def _interactions(rng, n=300, users=25, items=40):
    return [KeyMessage(None, f"u{rng.integers(users)},"
                             f"i{rng.integers(items)},1,{t}")
            for t, _ in enumerate(range(n))]


def test_als_update_publishes_manifest_envelope_and_skips_up(tmp_path):
    """run_update with a too-large model publishes the sharded form:
    one MODEL-REF envelope carrying the manifest, slices + X-known in
    the store, and NO per-row UP flood; a serving manager loads it to
    a fully servable model with known-items intact."""
    from oryx_tpu.app.als.update import ALSUpdate

    rng = np.random.default_rng(5)
    update = ALSUpdate(_als_update_config(tmp_path))
    assert update.publish_slices == RING  # reference.conf default
    producer = _CollectingProducer()
    update.run_update(0, _interactions(rng), [],
                      str(tmp_path / "models"), producer)
    keys = [k for k, _ in producer.sent]
    assert keys == [KEY_MODEL_REF], keys  # no UP stream at all
    _, msg = producer.sent[0]
    path, model_dir, manifest = slices.parse_model_ref(msg)
    assert manifest is not None and manifest["ring"] == RING
    assert os.path.exists(os.path.join(model_dir, slices.MANIFEST_FILE))

    mgr = _manager("0/1")
    mgr.consume_key_message(KEY_MODEL_REF, msg)
    assert mgr.slice_loads == RING and mgr.slice_load_fallbacks == 0
    assert mgr.model.get_fraction_loaded() == 1.0
    assert len(mgr.model.Y) == manifest["items"]
    # known-items rode the x artifact (the reference carried them on
    # the X UP stream): a user who interacted has a non-empty set
    assert any(mgr.model.get_known_items(u)
               for u in mgr.model.X.all_ids())


@pytest.mark.chaos
def test_slice_publish_failure_falls_back_to_bare_ref_plus_up(tmp_path):
    """A store failure while writing slices degrades the PUBLISH side:
    bare-path MODEL-REF + the full UP stream, exactly the pre-manifest
    contract — a broken slice write never costs the generation (the
    two stay consistent because publish_additional keys on the
    manifest's PRESENCE)."""
    from oryx_tpu.app.als.update import ALSUpdate

    rng = np.random.default_rng(6)
    data = _interactions(rng)
    update = ALSUpdate(_als_update_config(tmp_path))
    producer = _CollectingProducer()
    update.run_update(0, data, [], str(tmp_path / "models"), producer)
    path, model_dir, _ = slices.parse_model_ref(producer.sent[0][1])
    model = pmml_io.read(path)
    # simulate the NEXT generation's publish hitting a store failure
    # mid-slice-write: the manifest never lands, prepare returns the
    # bare path
    os.remove(os.path.join(model_dir, slices.MANIFEST_FILE))
    faults.inject("store-write", mode="error", times=1)
    payload = update.prepare_model_ref_payload(model, path, data, [])
    assert faults.fired("store-write") == 1
    assert payload == path  # bare-path degrade
    assert not os.path.exists(
        os.path.join(model_dir, slices.MANIFEST_FILE))
    # ...and publish_additional therefore streams the UP flood again
    producer2 = _CollectingProducer()
    update.publish_additional_model_data(model, data, [], model_dir,
                                         producer2)
    keys2 = [k for k, _ in producer2.sent]
    assert keys2 and set(keys2) == {KEY_UP}
    # a replica consuming the degraded publish converges as ever
    mgr = _manager("0/1")
    mgr.consume_key_message(KEY_MODEL_REF, payload)
    for k, m in producer2.sent:
        mgr.consume_key_message(k, m)
    assert mgr.model.get_fraction_loaded() == 1.0
    assert mgr.slice_loads == 0


def test_small_model_still_inlines(tmp_path):
    """Below max-size nothing changes: inline MODEL + UP stream (the
    manifest path exists only where load time matters)."""
    from oryx_tpu.app.als.update import ALSUpdate

    rng = np.random.default_rng(8)
    update = ALSUpdate(_als_update_config(tmp_path, max_size=16777216))
    producer = _CollectingProducer()
    update.run_update(0, _interactions(rng, n=120, users=8, items=10),
                      [], str(tmp_path / "models"), producer)
    keys = [k for k, _ in producer.sent]
    assert keys[0] == KEY_MODEL
    assert KEY_UP in keys[1:]
