"""Full-stack lambda integration: real Batch/Speed/Serving layers over the
in-process broker (reference analogs: AbstractLambdaIT/AbstractBatchIT/
AbstractSpeedIT/AbstractServingIT — everything in-process on one host,
small max message size exercising both MODEL and MODEL-REF paths)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_MODEL, KEY_MODEL_REF
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.batch import BatchLayer
from oryx_tpu.lambda_rt.serving import ServingLayer
from oryx_tpu.lambda_rt.speed import SpeedLayer


def _base_config(tmp_path, broker_name, **extra):
    overlay = {
        "oryx.id": "it",
        "oryx.input-topic.broker": f"memory://{broker_name}",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "ItInput",
        "oryx.update-topic.broker": f"memory://{broker_name}",
        "oryx.update-topic.message.topic": "ItUpdate",
        "oryx.batch.update-class": "oryx_tpu.app.als.update.ALSUpdate",
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.als.speed.ALSSpeedModelManager",
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.als.iterations": 3,
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": 3,
        "oryx.ml.eval.test-fraction": 0.0,
    }
    overlay.update(extra)
    return from_dict(overlay)


def _produce_ratings(broker, topic, nu=20, ni=12, seed=5):
    rng = np.random.default_rng(seed)
    t = 1_700_000_000_000
    n = 0
    for u in range(nu):
        for i in range(ni):
            if rng.random() < 0.4:
                broker.send(topic, None,
                            f"u{u},i{i},{rng.exponential(1):.2f},{t}")
                t += 1000
                n += 1
    return n


def test_batch_then_serving_loop(tmp_path):
    cfg = _base_config(tmp_path, "it1")
    broker = get_broker("it1")
    n = _produce_ratings(broker, "ItInput")

    batch = BatchLayer(cfg)
    batch.run_one_generation()

    # model + factor rows landed on the update topic
    msgs = list(broker.consume("ItUpdate", from_beginning=True,
                               max_idle_sec=0.2))
    assert msgs[0].key == KEY_MODEL
    assert len(msgs) > 1

    # data persisted for the next generation; offsets committed
    gen2_past = __import__(
        "oryx_tpu.lambda_rt.data_store",
        fromlist=["read_all_data"]).read_all_data(str(tmp_path / "data"))
    assert len(gen2_past) == n
    assert broker.get_offset("OryxGroup-BatchLayer-it", "ItInput") == n

    # a second generation with no new data still rebuilds from past data
    batch.run_one_generation()
    msgs2 = list(broker.consume("ItUpdate", from_beginning=True,
                                max_idle_sec=0.2))
    assert sum(1 for m in msgs2 if m.key == KEY_MODEL) == 2

    # serving layer replays the topic and answers queries
    serving = ServingLayer(cfg, port=0)
    serving.start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            model = serving.model_manager.get_model()
            if model is not None and model.get_fraction_loaded() >= 0.8:
                break
            time.sleep(0.05)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{serving.port}/ready", timeout=10) as r:
            assert r.status in (200, 204)
        uid = model.all_user_ids()[0]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{serving.port}/recommend/{uid}",
                timeout=10) as r:
            recs = json.loads(r.read())
        assert recs and "id" in recs[0]
    finally:
        serving.close()


def test_model_ref_path_when_message_too_large(tmp_path):
    # tiny max-size forces MODEL-REF (reference: AbstractLambdaIT.java:104)
    cfg = _base_config(tmp_path, "it2",
                       **{"oryx.update-topic.message.max-size": 1 << 7})
    broker = get_broker("it2")
    _produce_ratings(broker, "ItInput", nu=30, ni=20)
    BatchLayer(cfg).run_one_generation()
    msgs = list(broker.consume("ItUpdate", from_beginning=True,
                               max_idle_sec=0.2))
    assert msgs[0].key == KEY_MODEL_REF
    # serving can follow the reference to the file
    serving = ServingLayer(cfg, port=0)
    serving.start()
    try:
        deadline = time.time() + 10
        model = None
        while time.time() < deadline:
            model = serving.model_manager.get_model()
            if model is not None and model.get_fraction_loaded() >= 0.8:
                break
            time.sleep(0.05)
        assert model is not None and model.user_count() > 0
    finally:
        serving.close()


def test_speed_layer_micro_batch_loop(tmp_path):
    cfg = _base_config(tmp_path, "it3")
    broker = get_broker("it3")
    _produce_ratings(broker, "ItInput")
    BatchLayer(cfg).run_one_generation()

    speed = SpeedLayer(cfg)
    speed.start()
    try:
        # wait for the speed model to load via topic replay
        deadline = time.time() + 10
        while time.time() < deadline:
            m = speed.model_manager.model
            if m is not None and m.get_fraction_loaded() >= 0.8:
                break
            time.sleep(0.05)
        before = broker.latest_offset("ItUpdate")
        broker.send("ItInput", None, "u0,i1,3.0,1800000000000")
        broker.send("ItInput", None, "newuser,i2,1.0,1800000000001")
        speed.run_one_micro_batch()
        deadline = time.time() + 5
        ups = []
        while time.time() < deadline:
            after = broker.latest_offset("ItUpdate")
            if after > before:
                ups = [km.message
                       for km in broker.read_range("ItUpdate", before, after)
                       if km.key == "UP"]
                if ups:
                    break
            time.sleep(0.05)
        assert ups, "speed layer produced no UP deltas"
        parsed = [json.loads(u) for u in ups]
        assert any(p[0] == "X" and p[1] == "newuser" for p in parsed)
    finally:
        speed.close()


def test_data_store_ttl(tmp_path):
    from oryx_tpu.lambda_rt import data_store
    from oryx_tpu.kafka.api import KeyMessage

    old_ts = int(time.time() * 1000) - 10 * 3_600_000
    new_ts = int(time.time() * 1000)
    data_store.save_generation(str(tmp_path), old_ts, [KeyMessage(None, "a")])
    data_store.save_generation(str(tmp_path), new_ts, [KeyMessage(None, "b")])
    assert len(data_store.read_all_data(str(tmp_path))) == 2
    deleted = data_store.delete_old_data(str(tmp_path), 5)
    assert deleted == 1
    remaining = data_store.read_all_data(str(tmp_path))
    assert [km.message for km in remaining] == ["b"]
    # -1 means keep forever
    assert data_store.delete_old_data(str(tmp_path), -1) == 0
