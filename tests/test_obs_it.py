"""Observability integration tests (ISSUE 5 acceptance): a 2-shard
cluster with tracing at sample-ratio 1.0 proves

1. ONE trace id spans router -> both shard replicas -> the scoring
   batcher: `router.request`, `router.merge`, per-shard
   `router.shard_call`, each replica's `serving.request` parented
   under its shard_call, and the batcher's `serving.queue_wait` /
   `serving.device_execute` split — the whole tree reconstructable
   from the per-process `/admin/traces` rings joined by trace id;
2. the router's `/metrics?format=prometheus` merges both replicas'
   mergeable snapshots into bucket histograms whose total counts equal
   the sum of the replicas' own counts;
3. a sampled `/ingest` through the router is followed into the speed
   layer's fold-in (`traceparent` Kafka record header ->
   `speed.fold_in` span on the same trace), and the headless tier's
   side-door ObsServer serves its ring;
4. the chaos points: `obs-trace-drop` (a raising span recorder never
   fails the traced request) and `obs-profile-slow` (a stalled
   profiler pins only the requesting handler, and concurrent captures
   are refused 503, not queued);
5. `/admin/profile` 404s where `oryx.obs.profile-dir` is unset and
   captures a `jax.profiler` trace + device stats where it is set.

Marker: chaos (in the tier-1 budget).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.cluster.router import RouterLayer
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.batch import BatchLayer
from oryx_tpu.lambda_rt.serving import ServingLayer
from oryx_tpu.lambda_rt.speed import SpeedLayer
from oryx_tpu.resilience import faults
from oryx_tpu.resilience.policy import Deadline

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _config(tmp_path, broker_name, **extra):
    overlay = {
        "oryx.id": "obs-it",
        "oryx.input-topic.broker": f"memory://{broker_name}",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "OIn",
        "oryx.update-topic.broker": f"memory://{broker_name}",
        "oryx.update-topic.message.topic": "OUp",
        "oryx.batch.update-class": "oryx_tpu.app.als.update.ALSUpdate",
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.als.speed.ALSSpeedModelManager",
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.als.iterations": 2,
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": 3,
        "oryx.ml.eval.test-fraction": 0.0,
        # every request sampled: the IT asserts on recorded span trees
        "oryx.obs.tracing.enabled": True,
        "oryx.obs.tracing.sample-ratio": 1.0,
        # SLO engine armed on router + replicas (ISSUE 7): a latency
        # objective generous enough that organic traffic stays good,
        # and a fast-burn low enough that a handful of chaos-slowed
        # requests inside the module's 5m window trips the page state
        "oryx.obs.slo.enabled": True,
        "oryx.obs.slo.resolution-sec": 1,
        "oryx.obs.slo.fast-burn": 5.0,
        "oryx.obs.slo.objectives.availability.kind": "availability",
        "oryx.obs.slo.objectives.availability.target": 0.999,
        "oryx.obs.slo.objectives.latency.kind": "latency",
        "oryx.obs.slo.objectives.latency.target": 0.99,
        "oryx.obs.slo.objectives.latency.threshold-ms": 1000,
        # fast cluster timings so membership transitions stay inside
        # the tier-1 budget
        "oryx.cluster.heartbeat-interval-ms": 60,
        "oryx.cluster.heartbeat-ttl-ms": 400,
        "oryx.cluster.hedge-after-ms": 50,
        "oryx.cluster.shard-timeout-ms": 5000,
        "oryx.resilience.retry.max-attempts": 2,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
    }
    overlay.update(extra)
    return from_dict(overlay)


def _produce_ratings(broker, topic, nu=16, ni=12, seed=11):
    rng = np.random.default_rng(seed)
    t = 1_700_000_000_000
    for u in range(nu):
        for i in range(ni):
            if rng.random() < 0.5:
                broker.send(topic, None,
                            f"u{u},i{i},{rng.exponential(1):.2f},{t}")
                t += 1000


def _get(port, path, headers=None, timeout=15):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        body = r.read()
        ctype = r.headers.get("Content-Type", "")
        # text expositions (prometheus text/plain, openmetrics'
        # dedicated media type) come back as str; anything JSON parses
        payload = json.loads(body or b"null") if "json" in ctype \
            else body.decode("utf-8")
        return r.status, dict(r.headers), payload


def _post(port, path, data=b"", timeout=15):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=data, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), json.loads(r.read() or b"null")


def _await(predicate, what, timeout=25.0):
    deadline = Deadline.after(timeout)
    while not deadline.expired:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _router_ready(router):
    try:
        return _get(router.port, "/ready")[0] in (200, 204)
    except (urllib.error.HTTPError, urllib.error.URLError, OSError):
        return False


@pytest.fixture(scope="module")
def obs_cluster(tmp_path_factory):
    """2-shard traced cluster + router + speed layer, one batch model."""
    tmp_path = tmp_path_factory.mktemp("obs-it")
    broker = get_broker("obs-it")
    _produce_ratings(broker, "OIn")
    profile_dir = tmp_path / "profiles"

    def cfg_fn(extra=None):
        return _config(tmp_path, "obs-it", **(extra or {}))

    BatchLayer(cfg_fn()).run_one_generation()
    replicas = []
    for s in range(2):
        # profile-dir only on the replicas: the router's
        # /admin/profile must 404 (the endpoint is config-gated)
        layer = ServingLayer(cfg_fn({
            "oryx.cluster.enabled": True,
            "oryx.cluster.shard": f"{s}/2",
            "oryx.obs.profile-dir": str(profile_dir),
        }), port=0)
        layer.start()
        replicas.append(layer)
    # wide events on the ROUTER only: every in-proc layer shares one
    # pid, so per-layer files would collide (production processes get
    # distinct pids and may share a dir)
    events_dir = tmp_path / "events"
    router = RouterLayer(cfg_fn({
        "oryx.obs.events.dir": str(events_dir)}), port=0)
    router.start()
    speed = SpeedLayer(cfg_fn({"oryx.obs.metrics-port": 0}))
    speed.start()
    _await(lambda: _router_ready(router), "router readiness")
    _await(lambda: (m := speed.model_manager.model) is not None
           and m.get_fraction_loaded() >= 0.8, "speed model")
    # the first-ever jax.profiler.start_trace in a process pays a
    # ~10 s one-time profiler init; warm it here (the profiler is
    # process-global, so one warmup covers both in-proc replicas) so
    # the chaos tests measure steady-state capture cost
    _get(replicas[0].port, "/admin/profile?ms=1", timeout=90)
    yield {"cfg_fn": cfg_fn, "replicas": replicas, "router": router,
           "speed": speed, "broker": broker,
           "profile_dir": profile_dir, "events_dir": events_dir}
    for layer in replicas + [router, speed]:
        try:
            layer.close()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass


def _user_ids(router_port):
    _, _, ids = _get(router_port, "/allUserIDs")
    assert ids
    return sorted(ids)


def _all_traces(cluster):
    """Cluster-complete traces: the router's server-side ``?join=1``
    fan-in (ISSUE 7 — it scrapes both replicas via the scatter
    registry, replacing this helper's old by-hand join), plus the
    speed tier's side-door ring (not a scatter target)."""
    router, speed = cluster["router"], cluster["speed"]
    _, _, payload = _get(router.port, "/admin/traces?join=1&limit=128")
    assert payload["joined_replicas"] == len(cluster["replicas"])
    joined: dict[str, list[dict]] = dict(payload["traces"])
    _, _, sp = _get(speed.obs_server.port, "/admin/traces")
    for tid, spans in sp["traces"].items():
        joined.setdefault(tid, []).extend(spans)
    return joined


# -- 1. one trace id across router -> replicas -> batcher --------------------

def test_one_trace_spans_router_both_replicas_and_batcher(obs_cluster):
    router = obs_cluster["router"]
    uid = _user_ids(router.port)[0]
    status, headers, _ = _get(router.port,
                              f"/recommend/{uid}?howMany=8")
    assert status == 200
    trace_id = headers.get("X-Oryx-Trace")
    assert trace_id, "router did not echo X-Oryx-Trace on a sampled request"

    def recorded():
        spans = _all_traces(obs_cluster).get(trace_id, [])
        return {"serving.device_execute", "router.merge"} <= \
            {s["name"] for s in spans}

    # batcher spans are recorded retroactively by dispatcher threads —
    # give the rings a moment to settle
    _await(recorded, "span tree completion", timeout=5.0)

    spans = _all_traces(obs_cluster)[trace_id]
    by_name: dict[str, list[dict]] = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)

    # the request span and the exact-merge span on the router
    assert len(by_name["router.request"]) == 1
    root = by_name["router.request"][0]
    assert root["parent_id"] is None
    merge = by_name["router.merge"][0]
    assert merge["parent_id"] == root["span_id"]
    assert merge["attrs"]["shards_merged"] == 2

    # one shard_call per shard, both under the request span, and the
    # traceparent hop means each replica's serving.request parents
    # under ITS shard_call
    calls = by_name["router.shard_call"]
    assert {c["attrs"]["shard"] for c in calls} == {0, 1}
    for c in calls:
        assert c["parent_id"] == root["span_id"]
    call_ids = {c["span_id"] for c in calls}
    serv_reqs = by_name["serving.request"]
    assert len(serv_reqs) == 2, "both replicas must record their span"
    assert {s["parent_id"] for s in serv_reqs} <= call_ids
    assert {s["service"] for s in serv_reqs} == {"serving"}

    # the batcher split, parented under each replica's request span
    serv_ids = {s["span_id"] for s in serv_reqs}
    for name in ("serving.queue_wait", "serving.device_execute"):
        got = by_name[name]
        assert len(got) == 2, name
        assert {s["parent_id"] for s in got} <= serv_ids, name
    assert all(s["attrs"]["batch_size"] >= 1
               for s in by_name["serving.device_execute"])

    # the whole tree is reconstructable: every parent_id resolves
    # within the joined trace (or is the root)
    all_ids = {s["span_id"] for s in spans}
    for s in spans:
        assert s["parent_id"] is None or s["parent_id"] in all_ids, \
            s["name"]
    # and every span really is on the one trace
    assert {s["trace_id"] for s in spans} == {trace_id}


# -- 2. cluster-wide Prometheus merge -----------------------------------------

_SAMPLE_RE = __import__("re").compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(\{(?P<labels>.*)\})? (?P<value>\S+)$")


def _parse_prom(text):
    import re
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = dict(re.findall(
            r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"', m.group("labels") or ""))
        out.append((m.group("name"), labels, float(m.group("value"))))
    return out


def test_router_prometheus_merges_replica_histograms(obs_cluster):
    router, replicas = obs_cluster["router"], obs_cluster["replicas"]
    for uid in _user_ids(router.port)[:4]:
        _get(router.port, f"/recommend/{uid}?howMany=5")

    # each replica's own mergeable snapshot (what the router scrapes)
    replica_snaps = [
        _get(r.port, "/metrics?format=prometheus-json")[2]
        for r in replicas]
    route = "GET /shard/recommend/{userID}"
    want_count = sum(s["routes"][route]["count"] for s in replica_snaps)
    want_buckets = [
        sum(s["routes"][route]["latency_ms"]["buckets"][i]
            for s in replica_snaps)
        for i in range(len(
            replica_snaps[0]["routes"][route]["latency_ms"]["buckets"]))]
    assert want_count >= 4 and want_count == sum(want_buckets)

    status, headers, text = _get(router.port,
                                 "/metrics?format=prometheus")
    assert status == 200 and isinstance(text, str)
    samples = _parse_prom(text)
    # the merged replica block's totals equal the sum of the replicas'
    merged_total = [v for n, l, v in samples
                    if n == "oryx_requests_total"
                    and l.get("tier") == "replica"
                    and l.get("route") == route]
    assert merged_total == [want_count]
    # cumulative +Inf bucket == count == the replica sum
    inf = [v for n, l, v in samples
           if n == "oryx_request_latency_ms_bucket"
           and l.get("tier") == "replica" and l.get("route") == route
           and l.get("le") == "+Inf"]
    assert inf == [want_count]
    # per-bucket: de-cumulate the merged text and compare exactly
    merged_cum = [(l["le"], v) for n, l, v in samples
                  if n == "oryx_request_latency_ms_bucket"
                  and l.get("tier") == "replica"
                  and l.get("route") == route]
    merged_per = [v - (merged_cum[i - 1][1] if i else 0.0)
                  for i, (_, v) in enumerate(merged_cum)]
    assert merged_per == [float(b) for b in want_buckets]
    # coverage gauge: both replicas answered the scrape
    scraped = [v for n, l, v in samples
               if n == "oryx_scraped_replicas"
               and l.get("tier") == "replica"]
    assert scraped == [2.0]
    # the router's own block is present and separately labeled
    assert any(n == "oryx_requests_total" and l.get("tier") == "router"
               for n, l, v in samples)


# -- 3. /ingest followed into the speed layer's fold-in -----------------------

def test_ingest_trace_reaches_speed_fold_in(obs_cluster):
    router, speed = obs_cluster["router"], obs_cluster["speed"]
    broker = obs_cluster["broker"]
    before = broker.latest_offset("OIn")
    status, headers, _ = _post(router.port, "/pref/obsuser/i1",
                               data=b"4.0")
    assert status in (200, 204)
    trace_id = headers.get("X-Oryx-Trace")
    assert trace_id
    _await(lambda: broker.latest_offset("OIn") > before,
           "ingest reaching the input topic", timeout=5.0)
    speed.run_one_micro_batch()

    # the side-door ObsServer serves the headless tier's ring
    _, _, payload = _get(speed.obs_server.port, "/admin/traces")
    assert payload["service"] == "speed"
    spans = payload["traces"].get(trace_id)
    assert spans, "speed layer recorded no span on the ingest trace"
    fold = [s for s in spans if s["name"] == "speed.fold_in"]
    assert fold and fold[0]["attrs"]["batch_records"] >= 1

    # freshness: the same micro-batch fed the end-to-end gauge from
    # the ts record header stamped at ingest
    _, _, metrics = _get(speed.obs_server.port, "/metrics")
    fresh = metrics["freshness"]
    assert fresh["ingest_to_servable_ms"] is not None
    assert 0 <= fresh["ingest_to_servable_ms"] < 60_000
    assert fresh["micro_batch_records"] >= 1


# -- 4. chaos: observability is strictly best-effort --------------------------

def test_trace_drop_fault_never_fails_request(obs_cluster):
    router = obs_cluster["router"]
    uid = _user_ids(router.port)[0]
    fails_before = router.tracer.record_failures
    # every span recording in the process raises while injected; the
    # request must still answer 200 end to end (router AND replicas
    # share the in-proc faults registry, so all tiers degrade at once)
    faults.inject("obs-trace-drop", mode="error", times=50)
    try:
        status, headers, body = _get(router.port,
                                     f"/recommend/{uid}?howMany=5")
    finally:
        faults.clear()
    assert status == 200 and body
    assert router.tracer.record_failures > fails_before
    # the degraded recordings surface as a counter on the exposition
    _, _, text = _get(router.port, "/metrics?format=prometheus")
    assert any(n == "oryx_trace_record_failures_total" and v > 0
               for n, l, v in _parse_prom(text)
               if l.get("tier") == "router")


def test_profile_slow_fault_pins_only_the_capture(obs_cluster):
    replica = obs_cluster["replicas"][0]
    router = obs_cluster["router"]
    uid = _user_ids(router.port)[0]
    # mode="hold": the capture parks on a gate the test opens, so the
    # "serving answered while the capture stalled" ordering is decided
    # by the test, not by a sleep window racing scheduler load (the
    # 0.4 s delay flaked under full-suite load; 1.5 s merely hid it)
    faults.inject("obs-profile-slow", mode="hold", times=1)
    box = {}

    def capture():
        try:
            box["profile"] = _get(replica.port, "/admin/profile?ms=10")
        except urllib.error.HTTPError as e:  # pragma: no cover
            box["profile"] = (e.code, {}, None)

    th = threading.Thread(target=capture)
    th.start()
    try:
        # while the capture is held at the gate, serving traffic on
        # the same replica answers normally (the profiler pins only
        # the handler thread)
        status, _, _ = _get(replica.port,
                            f"/shard/recommend/{uid}?howMany=3")
        assert status == 200
        # the capture cannot have completed: its gate is still shut
        assert th.is_alive()
        assert faults.fired("obs-profile-slow") <= 1
    finally:
        faults.release("obs-profile-slow")
    th.join(20.0)
    assert not th.is_alive()
    assert box["profile"][0] == 200
    assert box["profile"][2]["captured_ms"] >= 10.0


# -- 5. exemplar -> joined trace -> tail anatomy (ISSUE 7 tentpole) -----------

_OM_EX_RE = __import__("re").compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(\{(?P<labels>.*?)\})? (?P<value>\S+)"
    r"(?: # \{trace_id=\"(?P<trace>[0-9a-f]{32})\"\} "
    r"(?P<exvalue>\S+) (?P<exts>\S+))?$")


def _parse_om(text):
    import re
    assert text.rstrip("\n").endswith("# EOF")
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _OM_EX_RE.match(line)
        assert m, f"unparseable OpenMetrics line: {line!r}"
        labels = dict(re.findall(
            r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"', m.group("labels") or ""))
        out.append((m.group("name"), labels, float(m.group("value")),
                    m.group("trace")))
    return out


def test_exemplar_resolves_to_joined_trace_and_tail_sums(obs_cluster):
    """The acceptance loop: a bucket exemplar scraped from the
    router's MERGED OpenMetrics exposition names a trace id; that id
    resolves via /admin/traces?join=1 to a full cluster-joined tree;
    and its anatomy breakdown (the same decomposition /admin/tail
    serves) sums to the request duration exactly."""
    from oryx_tpu.obs import anatomy
    router = obs_cluster["router"]
    fresh_ids = set()
    for uid in _user_ids(router.port)[:3]:
        status, headers, _ = _get(router.port,
                                  f"/recommend/{uid}?howMany=5")
        assert status == 200
        fresh_ids.add(headers["X-Oryx-Trace"])

    _, headers, text = _get(router.port, "/metrics?format=openmetrics")
    assert "openmetrics-text" in headers.get("Content-Type", "")
    samples = _parse_om(text)
    # the router's own data-plane buckets carry exemplars, and because
    # newest-per-bucket wins, the buckets our fresh requests landed in
    # name exactly those requests' trace ids
    router_ex = {tr for n, l, v, tr in samples
                 if tr and l.get("tier") == "router"
                 and l.get("route") == "GET /recommend/{userID}"}
    assert router_ex & fresh_ids, (router_ex, fresh_ids)
    # ...and so does the MERGED replica block: replica-side exemplars
    # survive the cross-replica merge (newest per bucket wins), naming
    # the SAME trace ids (the replicas continued the inbound context)
    replica_ex = {tr for n, l, v, tr in samples
                  if tr and l.get("tier") == "replica"
                  and l.get("route") == "GET /shard/recommend/{userID}"}
    assert replica_ex & fresh_ids, (replica_ex, fresh_ids)

    joined = _all_traces(obs_cluster)
    for trace_id in (router_ex | replica_ex) & fresh_ids:
        assert trace_id in joined, \
            "exemplar trace id must resolve on the joined ring"
    # a router-rooted exemplar trace decomposes over the JOINED tree
    # (replica spans included) and the stages sum to the root duration
    trace_id = next(iter(router_ex & fresh_ids))
    breakdown = anatomy.analyze_trace(joined[trace_id])
    assert breakdown is not None
    assert breakdown["route"] == "GET /recommend/{userID}"
    assert sum(breakdown["stages"].values()) == pytest.approx(
        breakdown["total_ms"], rel=0.01)
    # the replica-side stages are attributed (the join worked), not
    # lumped into scatter wait
    assert breakdown["stages"]["serving.device_execute"] > 0.0

    # /admin/tail serves the same identity for its top-k entries
    # (route-filtered: the joined ring also holds profile-capture and
    # direct-shard traces that are not this route's tail)
    _, _, report = _get(router.port, "/admin/tail?k=5&route=/recommend")
    assert report["analyzed"] >= 3
    assert report["joined_replicas"] == 2
    share = report["tail"]["stage_share"]
    assert sum(share.values()) == pytest.approx(1.0, abs=0.02)
    for entry in report["top"]:
        assert sum(entry["stages"].values()) == pytest.approx(
            entry["total_ms"], rel=0.01)

    # wide events (router-side): every sampled request left a durable
    # line whose trace id ties back to the same rings
    events_dir = obs_cluster["events_dir"]
    files = list(events_dir.glob("events-router-*.jsonl"))
    assert files, "router wide-event log missing"
    lines = [json.loads(ln) for ln in
             files[0].read_text().splitlines()]
    by_trace = {ev.get("trace_id"): ev for ev in lines}
    assert trace_id in by_trace
    ev = by_trace[trace_id]
    assert ev["route"] == "GET /recommend/{userID}"
    assert ev["status"] == 200 and ev["sampled"] is True
    assert ev["shards_called"] == 2


def test_slow_shard_moves_stage_share_slo_burn_and_autoscaler(
        obs_cluster):
    """Chaos acceptance: a slow shard (emulated device delay on the
    batcher seam) must (a) move /admin/tail's attributed stage share
    onto serving.device_execute, (b) push the fast-window
    slo_burn_rate gauge over the configured fast-burn into the page
    state, and (c) be SEEN by the autoscaler's pure step() as SLO
    pressure."""
    from oryx_tpu.cluster.autoscaler import Autoscaler, AutoscalePolicy
    router = obs_cluster["router"]
    uids = _user_ids(router.port)
    # baseline SLO snapshot (resolution-sec=1), then the incident
    _get(router.port, "/metrics")
    time.sleep(1.1)
    faults.inject("serving-scan-dispatch", mode="delay",
                  delay_sec=1.3, times=40)
    try:
        for uid in (uids * 3)[:6]:
            status, _, _ = _get(router.port,
                                f"/recommend/{uid}?howMany=5",
                                timeout=30)
            assert status == 200
    finally:
        faults.clear()
    time.sleep(1.1)

    # (a) the tail report attributes the incident to the device stage
    _, _, report = _get(router.port,
                        "/admin/tail?k=5&limit=256&route=/recommend")
    share = report["tail"]["stage_share"]
    assert share["serving.device_execute"] > 0.5, share
    assert report["top"][0]["total_ms"] > 1000.0
    assert report["top"][0]["stages"]["serving.device_execute"] > 1000.0

    # (b) the latency objective burns past fast-burn (5.0) -> page
    _, _, metrics = _get(router.port, "/metrics")
    burn = metrics["freshness"]["slo_burn_rate"]
    assert burn is not None and burn > 5.0, metrics["freshness"]
    assert metrics["freshness"]["slo_error_budget_remaining"] < 1.0
    _, _, slo_state = _get(router.port, "/admin/slo")
    lat = slo_state["objectives"]["latency"]
    assert lat["state"] == "page", lat
    assert lat["windows"]["5m"]["burn"] >= 5.0

    # (c) the autoscaler's poll sees the gauge and step() treats it as
    # scale-up pressure (two consecutive polls -> spawn)
    class _Launcher:
        def __init__(self):
            self.spawned = []

        def spawn(self, shard, of):
            self.spawned.append((shard, of))
            return f"it-{shard}of{of}"

        def retire(self, shard, of):
            return None

        def owned(self, of):
            return {}

    launcher = _Launcher()
    sc = Autoscaler(
        AutoscalePolicy(p99_high_ms=0, p99_low_ms=0,
                        queue_wait_high_ms=0,
                        update_lag_high_records=0, slo_burn_high=3.0,
                        scale_up_after=2, cooldown_sec=0.0),
        launcher, f"http://127.0.0.1:{router.port}")
    s1 = sc.poll_signals()
    assert s1.ok and s1.slo_burn_rate is not None \
        and s1.slo_burn_rate > 3.0
    assert sc.step(s1, now=0.0) is None       # streak discipline holds
    action = sc.step(sc.poll_signals(), now=1.0)
    assert action is not None and action["kind"] == "spawn"
    assert "slo_burn" in action["reason"]
    assert launcher.spawned and launcher.spawned[0][1] == 2


# -- 6. /admin/profile gating + capture ---------------------------------------

def test_admin_profile_capture_and_gating(obs_cluster):
    import os
    replica = obs_cluster["replicas"][0]
    router = obs_cluster["router"]
    # router has no profile-dir configured: the endpoint 404s
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(router.port, "/admin/profile?ms=10")
    assert e.value.code == 404
    # the replica captures: a real jax.profiler trace dir + devices
    status, _, payload = _get(replica.port, "/admin/profile?ms=30")
    assert status == 200
    assert payload["requested_ms"] == 30
    assert payload["captured_ms"] >= 30.0
    assert os.path.isdir(payload["trace_dir"])
    assert payload["trace_dir"].startswith(
        str(obs_cluster["profile_dir"]))
    assert isinstance(payload["devices"], list)

    # concurrent captures are refused 503, never queued
    faults.inject("obs-profile-slow", mode="delay", delay_sec=0.3,
                  times=1)
    th = threading.Thread(
        target=lambda: _get(replica.port, "/admin/profile?ms=10"))
    th.start()
    time.sleep(0.1)
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(replica.port, "/admin/profile?ms=10")
    assert e.value.code == 503
    th.join(10.0)


# -- 7. flight recorder + cluster auto-triage (ISSUE 20) ----------------------
#
# A SEPARATE flight-armed cluster on its own broker: the recorder's
# chaos-fault listener is process-global, so arming the shared
# obs_cluster fixture would make every other test's injected fault
# publish bundles.  This cluster opts in via oryx.obs.flight.dir.

def test_flight_endpoints_are_config_gated(obs_cluster):
    # the shared cluster never set oryx.obs.flight.dir: both the
    # status view and the manual trigger 404 on every tier
    for port in (obs_cluster["router"].port,
                 obs_cluster["replicas"][0].port):
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(port, "/admin/flight")
        assert e.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, "/admin/flight/dump")
        assert e.value.code == 404


@pytest.fixture(scope="module")
def flight_cluster(tmp_path_factory):
    """2-shard cluster + router with the flight recorder armed on
    every tier (no speed layer — the diagnosis path under test is
    router-joined serving)."""
    tmp_path = tmp_path_factory.mktemp("flight-it")
    broker = get_broker("obs-flight-it")
    _produce_ratings(broker, "OIn")
    flight_dir = tmp_path / "flight"

    def cfg_fn(extra=None):
        overlay = {
            "oryx.obs.flight.dir": str(flight_dir),
            # short enough that the page trigger lands outside the
            # window the injected-fault dump opened, long enough that
            # the fault storm's repeat triggers collapse into it
            "oryx.obs.flight.debounce-sec": 1.0,
            # ticks scrape gauge fns, and the SLO engine's burn gauges
            # evaluate on read — a mid-loop tick could page while the
            # chaos dump's debounce window is still open and swallow
            # the transition.  Park the tick clock past the test so
            # the page fires exactly when the test scrapes /admin/slo.
            "oryx.obs.flight.tick-sec": 300,
            "oryx.obs.flight.dump-on-exit": False,
        }
        overlay.update(extra or {})
        return _config(tmp_path, "obs-flight-it", **overlay)

    BatchLayer(cfg_fn()).run_one_generation()
    replicas = []
    for s in range(2):
        layer = ServingLayer(cfg_fn({
            "oryx.cluster.enabled": True,
            "oryx.cluster.shard": f"{s}/2",
        }), port=0)
        layer.start()
        replicas.append(layer)
    router = RouterLayer(cfg_fn(), port=0)
    router.start()
    _await(lambda: _router_ready(router), "flight router readiness")
    yield {"router": router, "replicas": replicas,
           "flight_dir": flight_dir}
    router.close()
    for r in replicas:
        r.close()


def _flight_bundles(flight_dir):
    """Every published bundle under the per-service subdirs; asserts
    no temp file ever leaks into the published namespace."""
    import os
    bundles = []
    for root, _dirs, files in os.walk(flight_dir):
        for f in files:
            assert not f.endswith(".tmp"), f
            if f.endswith(".json"):
                with open(os.path.join(root, f)) as fh:
                    bundles.append(json.load(fh))
    return bundles


def test_chaos_fault_pages_slo_and_dumps_one_correlated_cluster_bundle(
        flight_cluster):
    """ISSUE 20 acceptance: induced chaos fault on a live
    multi-process cluster -> SLO page -> exactly one cluster-wide
    flight dump (debounced), bundles correlated by trigger id, and the
    router-joined /admin/diagnose ranks the injected cause first."""
    router = flight_cluster["router"]
    replicas = flight_cluster["replicas"]
    flight_dir = flight_cluster["flight_dir"]
    uids = _user_ids(router.port)

    # healthy traffic seeds the availability window and books real
    # device time on the batcher's scoring dispatches
    for uid in uids[:3]:
        assert _get(router.port,
                    f"/recommend/{uid}?howMany=5")[0] == 200
    _get(router.port, "/admin/slo")
    time.sleep(1.1)

    # the induced fault: every scoring drain fails -> all-shard 5xx.
    # Each consumed fire is itself a flight trigger (chaos-fault) on
    # all three armed recorders; the storm's repeats collapse into the
    # debounce window.
    faults.inject("serving-scan-dispatch", mode="error", times=400)
    try:
        failures = 0
        for uid in (uids * 3)[:8]:
            try:
                status = _get(router.port,
                              f"/recommend/{uid}?howMany=5")[0]
            except urllib.error.HTTPError as e:
                status = e.code
            failures += 1 if status >= 500 else 0
        assert failures >= 5, failures
    finally:
        faults.clear()

    # sit out the debounce window the chaos-fault dump opened, then
    # drive an evaluation: the availability objective's 5m burn is far
    # past fast-burn, the transition fires on_page, and the router's
    # recorder dumps + fans the trigger id to both replicas
    time.sleep(1.1)

    def _paged():
        avail = _get(router.port, "/admin/slo")[2][
            "objectives"]["availability"]
        return avail["state"] == "page"
    _await(_paged, "availability page transition", timeout=10.0)

    _, _, fstat = _get(router.port, "/admin/flight")
    assert fstat["armed"] and fstat["service"] == "router"
    last = fstat["last_dump"]
    assert last is not None and last["reason"] == "slo-page", fstat
    tid = last["trigger_id"]
    # the fault storm's repeat triggers were debounced, not dumped
    assert fstat["debounced"] >= 1

    # exactly ONE cluster-wide dump: the originating router bundle
    # plus one fanned bundle per replica, all sharing the trigger id
    # (the chaos-fault bundles from the storm carry their own ids)
    def _correlated():
        return [b for b in _flight_bundles(flight_dir)
                if b["trigger_id"] == tid]
    _await(lambda: len(_correlated()) == 3,
           "cluster-correlated bundles", timeout=10.0)
    bundles = _correlated()
    assert sorted(b["service"] for b in bundles) \
        == ["router", "serving", "serving"]
    assert all(b["trigger_reason"] == "slo-page" for b in bundles)
    by_service = {b["service"]: b for b in bundles}
    # the router bundle embeds the rule engine's verdict at dump time:
    # the injected all-shard failure manifests as an error burst
    diag = by_service["router"]["diagnosis"]
    assert diag["causes"] \
        and diag["causes"][0]["cause"] == "error-burst", diag
    # black-box rings captured the failing requests
    rows = by_service["router"]["flight_events"]["rows"]
    route_i = by_service["router"]["flight_events"][
        "fields"].index("status")
    assert any(r[route_i] >= 500 for r in rows)
    # fanned-in replica bundles carry the serving-side forensics
    assert all(b["device_time"] is not None
               or b["service"] == "router" for b in bundles)

    # a repeat trigger right after the page dump is debounced — the
    # "exactly one" guarantee an operator relies on during a storm
    _, _, repeat = _post(router.port,
                         "/admin/flight/dump?reason=operator-repeat")
    assert repeat["dumped"] is False and repeat["debounced"] is True

    # ...but re-POSTing the SAME trigger id is deduped, not re-dumped
    _, _, dup = _post(
        router.port, f"/admin/flight/dump?trigger={tid}&reason=slo-page")
    assert dup["dumped"] is False and dup.get("duplicate") is True

    # router-joined auto-triage ranks the injected cause first, with a
    # runbook anchor an operator can follow
    _, _, triage = _get(router.port, "/admin/diagnose?join=1")
    assert triage["joined_replicas"] == 2
    assert triage["healthy"] is False
    top = triage["causes"][0]
    assert top["cause"] == "error-burst", triage["causes"]
    assert top["runbook"].startswith("docs/") and "#" in top["runbook"]
    assert top["evidence"]

    # device-time accounting rode along: the replicas booked the warm
    # requests' scoring dispatches, visible in the tail taxonomy
    _, _, tail = _get(replicas[0].port, "/admin/tail")
    assert tail["device_time"]["busy_s"] > 0.0
    assert any(r["route_class"] == "serve"
               for r in tail["device_time"]["by_route"])
