"""Observability integration tests (ISSUE 5 acceptance): a 2-shard
cluster with tracing at sample-ratio 1.0 proves

1. ONE trace id spans router -> both shard replicas -> the scoring
   batcher: `router.request`, `router.merge`, per-shard
   `router.shard_call`, each replica's `serving.request` parented
   under its shard_call, and the batcher's `serving.queue_wait` /
   `serving.device_execute` split — the whole tree reconstructable
   from the per-process `/admin/traces` rings joined by trace id;
2. the router's `/metrics?format=prometheus` merges both replicas'
   mergeable snapshots into bucket histograms whose total counts equal
   the sum of the replicas' own counts;
3. a sampled `/ingest` through the router is followed into the speed
   layer's fold-in (`traceparent` Kafka record header ->
   `speed.fold_in` span on the same trace), and the headless tier's
   side-door ObsServer serves its ring;
4. the chaos points: `obs-trace-drop` (a raising span recorder never
   fails the traced request) and `obs-profile-slow` (a stalled
   profiler pins only the requesting handler, and concurrent captures
   are refused 503, not queued);
5. `/admin/profile` 404s where `oryx.obs.profile-dir` is unset and
   captures a `jax.profiler` trace + device stats where it is set.

Marker: chaos (in the tier-1 budget).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.cluster.router import RouterLayer
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.batch import BatchLayer
from oryx_tpu.lambda_rt.serving import ServingLayer
from oryx_tpu.lambda_rt.speed import SpeedLayer
from oryx_tpu.resilience import faults
from oryx_tpu.resilience.policy import Deadline

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _config(tmp_path, broker_name, **extra):
    overlay = {
        "oryx.id": "obs-it",
        "oryx.input-topic.broker": f"memory://{broker_name}",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "OIn",
        "oryx.update-topic.broker": f"memory://{broker_name}",
        "oryx.update-topic.message.topic": "OUp",
        "oryx.batch.update-class": "oryx_tpu.app.als.update.ALSUpdate",
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.als.speed.ALSSpeedModelManager",
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.als.iterations": 2,
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": 3,
        "oryx.ml.eval.test-fraction": 0.0,
        # every request sampled: the IT asserts on recorded span trees
        "oryx.obs.tracing.enabled": True,
        "oryx.obs.tracing.sample-ratio": 1.0,
        # fast cluster timings so membership transitions stay inside
        # the tier-1 budget
        "oryx.cluster.heartbeat-interval-ms": 60,
        "oryx.cluster.heartbeat-ttl-ms": 400,
        "oryx.cluster.hedge-after-ms": 50,
        "oryx.cluster.shard-timeout-ms": 5000,
        "oryx.resilience.retry.max-attempts": 2,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
    }
    overlay.update(extra)
    return from_dict(overlay)


def _produce_ratings(broker, topic, nu=16, ni=12, seed=11):
    rng = np.random.default_rng(seed)
    t = 1_700_000_000_000
    for u in range(nu):
        for i in range(ni):
            if rng.random() < 0.5:
                broker.send(topic, None,
                            f"u{u},i{i},{rng.exponential(1):.2f},{t}")
                t += 1000


def _get(port, path, headers=None, timeout=15):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        body = r.read()
        ctype = r.headers.get("Content-Type", "")
        payload = body.decode("utf-8") if "text/plain" in ctype \
            else json.loads(body or b"null")
        return r.status, dict(r.headers), payload


def _post(port, path, data=b"", timeout=15):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=data, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), json.loads(r.read() or b"null")


def _await(predicate, what, timeout=25.0):
    deadline = Deadline.after(timeout)
    while not deadline.expired:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _router_ready(router):
    try:
        return _get(router.port, "/ready")[0] in (200, 204)
    except (urllib.error.HTTPError, urllib.error.URLError, OSError):
        return False


@pytest.fixture(scope="module")
def obs_cluster(tmp_path_factory):
    """2-shard traced cluster + router + speed layer, one batch model."""
    tmp_path = tmp_path_factory.mktemp("obs-it")
    broker = get_broker("obs-it")
    _produce_ratings(broker, "OIn")
    profile_dir = tmp_path / "profiles"

    def cfg_fn(extra=None):
        return _config(tmp_path, "obs-it", **(extra or {}))

    BatchLayer(cfg_fn()).run_one_generation()
    replicas = []
    for s in range(2):
        # profile-dir only on the replicas: the router's
        # /admin/profile must 404 (the endpoint is config-gated)
        layer = ServingLayer(cfg_fn({
            "oryx.cluster.enabled": True,
            "oryx.cluster.shard": f"{s}/2",
            "oryx.obs.profile-dir": str(profile_dir),
        }), port=0)
        layer.start()
        replicas.append(layer)
    router = RouterLayer(cfg_fn(), port=0)
    router.start()
    speed = SpeedLayer(cfg_fn({"oryx.obs.metrics-port": 0}))
    speed.start()
    _await(lambda: _router_ready(router), "router readiness")
    _await(lambda: (m := speed.model_manager.model) is not None
           and m.get_fraction_loaded() >= 0.8, "speed model")
    # the first-ever jax.profiler.start_trace in a process pays a
    # ~10 s one-time profiler init; warm it here (the profiler is
    # process-global, so one warmup covers both in-proc replicas) so
    # the chaos tests measure steady-state capture cost
    _get(replicas[0].port, "/admin/profile?ms=1", timeout=90)
    yield {"cfg_fn": cfg_fn, "replicas": replicas, "router": router,
           "speed": speed, "broker": broker,
           "profile_dir": profile_dir}
    for layer in replicas + [router, speed]:
        try:
            layer.close()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass


def _user_ids(router_port):
    _, _, ids = _get(router_port, "/allUserIDs")
    assert ids
    return sorted(ids)


def _all_traces(cluster):
    """Every tier's /admin/traces ring joined: trace id -> spans."""
    router, replicas = cluster["router"], cluster["replicas"]
    speed = cluster["speed"]
    joined: dict[str, list[dict]] = {}
    ports = [router.port] + [r.port for r in replicas] \
        + [speed.obs_server.port]
    for port in ports:
        _, _, payload = _get(port, "/admin/traces")
        for tid, spans in payload["traces"].items():
            joined.setdefault(tid, []).extend(spans)
    return joined


# -- 1. one trace id across router -> replicas -> batcher --------------------

def test_one_trace_spans_router_both_replicas_and_batcher(obs_cluster):
    router = obs_cluster["router"]
    uid = _user_ids(router.port)[0]
    status, headers, _ = _get(router.port,
                              f"/recommend/{uid}?howMany=8")
    assert status == 200
    trace_id = headers.get("X-Oryx-Trace")
    assert trace_id, "router did not echo X-Oryx-Trace on a sampled request"

    def recorded():
        spans = _all_traces(obs_cluster).get(trace_id, [])
        return {"serving.device_execute", "router.merge"} <= \
            {s["name"] for s in spans}

    # batcher spans are recorded retroactively by dispatcher threads —
    # give the rings a moment to settle
    _await(recorded, "span tree completion", timeout=5.0)

    spans = _all_traces(obs_cluster)[trace_id]
    by_name: dict[str, list[dict]] = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)

    # the request span and the exact-merge span on the router
    assert len(by_name["router.request"]) == 1
    root = by_name["router.request"][0]
    assert root["parent_id"] is None
    merge = by_name["router.merge"][0]
    assert merge["parent_id"] == root["span_id"]
    assert merge["attrs"]["shards_merged"] == 2

    # one shard_call per shard, both under the request span, and the
    # traceparent hop means each replica's serving.request parents
    # under ITS shard_call
    calls = by_name["router.shard_call"]
    assert {c["attrs"]["shard"] for c in calls} == {0, 1}
    for c in calls:
        assert c["parent_id"] == root["span_id"]
    call_ids = {c["span_id"] for c in calls}
    serv_reqs = by_name["serving.request"]
    assert len(serv_reqs) == 2, "both replicas must record their span"
    assert {s["parent_id"] for s in serv_reqs} <= call_ids
    assert {s["service"] for s in serv_reqs} == {"serving"}

    # the batcher split, parented under each replica's request span
    serv_ids = {s["span_id"] for s in serv_reqs}
    for name in ("serving.queue_wait", "serving.device_execute"):
        got = by_name[name]
        assert len(got) == 2, name
        assert {s["parent_id"] for s in got} <= serv_ids, name
    assert all(s["attrs"]["batch_size"] >= 1
               for s in by_name["serving.device_execute"])

    # the whole tree is reconstructable: every parent_id resolves
    # within the joined trace (or is the root)
    all_ids = {s["span_id"] for s in spans}
    for s in spans:
        assert s["parent_id"] is None or s["parent_id"] in all_ids, \
            s["name"]
    # and every span really is on the one trace
    assert {s["trace_id"] for s in spans} == {trace_id}


# -- 2. cluster-wide Prometheus merge -----------------------------------------

_SAMPLE_RE = __import__("re").compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(\{(?P<labels>.*)\})? (?P<value>\S+)$")


def _parse_prom(text):
    import re
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = dict(re.findall(
            r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"', m.group("labels") or ""))
        out.append((m.group("name"), labels, float(m.group("value"))))
    return out


def test_router_prometheus_merges_replica_histograms(obs_cluster):
    router, replicas = obs_cluster["router"], obs_cluster["replicas"]
    for uid in _user_ids(router.port)[:4]:
        _get(router.port, f"/recommend/{uid}?howMany=5")

    # each replica's own mergeable snapshot (what the router scrapes)
    replica_snaps = [
        _get(r.port, "/metrics?format=prometheus-json")[2]
        for r in replicas]
    route = "GET /shard/recommend/{userID}"
    want_count = sum(s["routes"][route]["count"] for s in replica_snaps)
    want_buckets = [
        sum(s["routes"][route]["latency_ms"]["buckets"][i]
            for s in replica_snaps)
        for i in range(len(
            replica_snaps[0]["routes"][route]["latency_ms"]["buckets"]))]
    assert want_count >= 4 and want_count == sum(want_buckets)

    status, headers, text = _get(router.port,
                                 "/metrics?format=prometheus")
    assert status == 200 and isinstance(text, str)
    samples = _parse_prom(text)
    # the merged replica block's totals equal the sum of the replicas'
    merged_total = [v for n, l, v in samples
                    if n == "oryx_requests_total"
                    and l.get("tier") == "replica"
                    and l.get("route") == route]
    assert merged_total == [want_count]
    # cumulative +Inf bucket == count == the replica sum
    inf = [v for n, l, v in samples
           if n == "oryx_request_latency_ms_bucket"
           and l.get("tier") == "replica" and l.get("route") == route
           and l.get("le") == "+Inf"]
    assert inf == [want_count]
    # per-bucket: de-cumulate the merged text and compare exactly
    merged_cum = [(l["le"], v) for n, l, v in samples
                  if n == "oryx_request_latency_ms_bucket"
                  and l.get("tier") == "replica"
                  and l.get("route") == route]
    merged_per = [v - (merged_cum[i - 1][1] if i else 0.0)
                  for i, (_, v) in enumerate(merged_cum)]
    assert merged_per == [float(b) for b in want_buckets]
    # coverage gauge: both replicas answered the scrape
    scraped = [v for n, l, v in samples
               if n == "oryx_scraped_replicas"
               and l.get("tier") == "replica"]
    assert scraped == [2.0]
    # the router's own block is present and separately labeled
    assert any(n == "oryx_requests_total" and l.get("tier") == "router"
               for n, l, v in samples)


# -- 3. /ingest followed into the speed layer's fold-in -----------------------

def test_ingest_trace_reaches_speed_fold_in(obs_cluster):
    router, speed = obs_cluster["router"], obs_cluster["speed"]
    broker = obs_cluster["broker"]
    before = broker.latest_offset("OIn")
    status, headers, _ = _post(router.port, "/pref/obsuser/i1",
                               data=b"4.0")
    assert status in (200, 204)
    trace_id = headers.get("X-Oryx-Trace")
    assert trace_id
    _await(lambda: broker.latest_offset("OIn") > before,
           "ingest reaching the input topic", timeout=5.0)
    speed.run_one_micro_batch()

    # the side-door ObsServer serves the headless tier's ring
    _, _, payload = _get(speed.obs_server.port, "/admin/traces")
    assert payload["service"] == "speed"
    spans = payload["traces"].get(trace_id)
    assert spans, "speed layer recorded no span on the ingest trace"
    fold = [s for s in spans if s["name"] == "speed.fold_in"]
    assert fold and fold[0]["attrs"]["batch_records"] >= 1

    # freshness: the same micro-batch fed the end-to-end gauge from
    # the ts record header stamped at ingest
    _, _, metrics = _get(speed.obs_server.port, "/metrics")
    fresh = metrics["freshness"]
    assert fresh["ingest_to_servable_ms"] is not None
    assert 0 <= fresh["ingest_to_servable_ms"] < 60_000
    assert fresh["micro_batch_records"] >= 1


# -- 4. chaos: observability is strictly best-effort --------------------------

def test_trace_drop_fault_never_fails_request(obs_cluster):
    router = obs_cluster["router"]
    uid = _user_ids(router.port)[0]
    fails_before = router.tracer.record_failures
    # every span recording in the process raises while injected; the
    # request must still answer 200 end to end (router AND replicas
    # share the in-proc faults registry, so all tiers degrade at once)
    faults.inject("obs-trace-drop", mode="error", times=50)
    try:
        status, headers, body = _get(router.port,
                                     f"/recommend/{uid}?howMany=5")
    finally:
        faults.clear()
    assert status == 200 and body
    assert router.tracer.record_failures > fails_before
    # the degraded recordings surface as a counter on the exposition
    _, _, text = _get(router.port, "/metrics?format=prometheus")
    assert any(n == "oryx_trace_record_failures_total" and v > 0
               for n, l, v in _parse_prom(text)
               if l.get("tier") == "router")


def test_profile_slow_fault_pins_only_the_capture(obs_cluster):
    replica = obs_cluster["replicas"][0]
    router = obs_cluster["router"]
    uid = _user_ids(router.port)[0]
    faults.inject("obs-profile-slow", mode="delay", delay_sec=0.4,
                  times=1)
    box = {}

    def capture():
        try:
            box["profile"] = _get(replica.port, "/admin/profile?ms=10")
        except urllib.error.HTTPError as e:  # pragma: no cover
            box["profile"] = (e.code, {}, None)

    th = threading.Thread(target=capture)
    t0 = time.monotonic()
    th.start()
    # while the capture stalls, serving traffic on the same replica
    # answers normally (the profiler pins only the handler thread)
    status, _, _ = _get(replica.port,
                        f"/shard/recommend/{uid}?howMany=3")
    served_ms = (time.monotonic() - t0) * 1000.0
    assert status == 200
    th.join(10.0)
    assert box["profile"][0] == 200
    assert box["profile"][2]["captured_ms"] >= 400.0
    assert served_ms < box["profile"][2]["captured_ms"]


# -- 5. /admin/profile gating + capture ---------------------------------------

def test_admin_profile_capture_and_gating(obs_cluster):
    import os
    replica = obs_cluster["replicas"][0]
    router = obs_cluster["router"]
    # router has no profile-dir configured: the endpoint 404s
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(router.port, "/admin/profile?ms=10")
    assert e.value.code == 404
    # the replica captures: a real jax.profiler trace dir + devices
    status, _, payload = _get(replica.port, "/admin/profile?ms=30")
    assert status == 200
    assert payload["requested_ms"] == 30
    assert payload["captured_ms"] >= 30.0
    assert os.path.isdir(payload["trace_dir"])
    assert payload["trace_dir"].startswith(
        str(obs_cluster["profile_dir"]))
    assert isinstance(payload["devices"], list)

    # concurrent captures are refused 503, never queued
    faults.inject("obs-profile-slow", mode="delay", delay_sec=0.3,
                  times=1)
    th = threading.Thread(
        target=lambda: _get(replica.port, "/admin/profile?ms=10"))
    th.start()
    time.sleep(0.1)
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(replica.port, "/admin/profile?ms=10")
    assert e.value.code == 503
    th.join(10.0)
