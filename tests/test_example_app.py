"""Word-count example app tests (reference: ExampleBatchLayerUpdateIT,
ExampleSpeedIT, ExampleServingIT): the custom-app API demonstration
running through the real layers."""

import json
import time
import urllib.parse
import urllib.request

import pytest

from oryx_tpu.common.config import from_dict
from oryx_tpu.example.batch import (ExampleBatchLayerUpdate,
                                    count_distinct_other_words)
from oryx_tpu.example.speed import ExampleSpeedModelManager
from oryx_tpu.kafka.api import KEY_MODEL, KeyMessage
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.batch import BatchLayer
from oryx_tpu.lambda_rt.serving import ServingLayer


def test_count_distinct_other_words():
    data = [KeyMessage(None, "a b c"), KeyMessage(None, "a b"),
            KeyMessage(None, "a a b")]
    counts = count_distinct_other_words(data)
    # a co-occurs with b and c; b with a and c; c with a and b
    assert counts == {"a": 2, "b": 2, "c": 2}


def test_speed_manager_accumulates():
    mgr = ExampleSpeedModelManager(from_dict({}))
    mgr.consume_key_message(KEY_MODEL, json.dumps({"a": 1}))
    ups = sorted(mgr.build_updates([KeyMessage(None, "a b")]))
    assert ups == ["a,2", "b,1"]


def test_example_full_loop(tmp_path):
    cfg = from_dict({
        "oryx.id": "ex",
        "oryx.input-topic.broker": "memory://ex-it",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "ExIn",
        "oryx.update-topic.broker": "memory://ex-it",
        "oryx.update-topic.message.topic": "ExUp",
        "oryx.batch.update-class":
            "oryx_tpu.example.batch.ExampleBatchLayerUpdate",
        "oryx.serving.model-manager-class":
            "oryx_tpu.example.serving.ExampleServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.example.serving",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
    })
    broker = get_broker("ex-it")
    serving = ServingLayer(cfg, port=0)
    serving.start()
    base = None
    try:
        base = f"http://127.0.0.1:{serving.port}"
        # /add writes the input topic (no model needed yet)
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/add/" + urllib.parse.quote("cats and dogs"), data=b"", method="POST"),
                    timeout=2)
                break
            except urllib.error.URLError:
                time.sleep(0.1)
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/add/" + urllib.parse.quote("cats are great"), data=b"", method="POST"),
            timeout=5)
        assert broker.latest_offset("ExIn") == 2

        BatchLayer(cfg).run_one_generation()
        deadline = time.time() + 10
        words = None
        while time.time() < deadline:
            words = json.loads(urllib.request.urlopen(
                f"{base}/distinct", timeout=5).read())
            if words:
                break
            time.sleep(0.2)
        assert words["cats"] == 4  # and, dogs, are, great
        one = json.loads(urllib.request.urlopen(
            f"{base}/distinct/dogs", timeout=5).read())
        assert one == 2
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{base}/distinct/zebra", timeout=5)
        assert e.value.code == 400
    finally:
        serving.close()
