"""Spec-derived Kafka wire-protocol conformance fixtures (VERDICT r5
Missing #1 / ISSUE 3 satellite).

The binding's client (kafka/wire.py) and the broker it is normally
tested against (kafka/mini_broker.py) are both self-authored, so a
mirrored protocol misunderstanding would pass every existing test.
Everything in this file is derived from the protocol specifications
with the mini-broker OUT of the loop:

- CRC32C check values from RFC 3720 §B.4 (the published iSCSI test
  vectors for the Castagnoli polynomial Kafka mandates for record
  batches).
- Zigzag varint vectors from the Protocol Buffers encoding spec, which
  the Kafka record format v2 adopts verbatim for record fields.
- A golden v2 RecordBatch, field-by-field from KIP-98 / the Kafka
  protocol guide's record-batch layout, with its CRC sealed by an
  independent bit-by-bit CRC32C implementation (validated against the
  RFC vectors first) — not by the codec under test.
- A golden request frame per the RequestHeader v1 layout.
- Property/fuzz round-trips of the v2 record-batch codec (null/empty
  keys and values, binary payloads, multi-batch concatenation,
  truncated tails, control batches, compressed-batch rejection).
"""

from __future__ import annotations

import struct

import numpy as np
import pytest

from oryx_tpu.kafka import wire


# -- independent CRC32C (NOT the implementation under test) ---------------

def _crc32c_bitwise(data: bytes) -> int:
    """Bit-by-bit CRC32C: reflected Castagnoli polynomial 0x82F63B78,
    init/xorout 0xFFFFFFFF — transcribed from the polynomial
    definition, sharing nothing with wire.crc32c's sliced table."""
    crc = 0xFFFFFFFF
    for b in data:
        crc ^= b
        for _ in range(8):
            crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
    return crc ^ 0xFFFFFFFF


# RFC 3720 §B.4 published test vectors for CRC32C
_RFC3720_VECTORS = [
    (b"", 0x00000000),
    (b"123456789", 0xE3069283),          # the classic check value
    (b"\x00" * 32, 0x8A9136AA),
    (b"\xff" * 32, 0x62A8AB43),
    (bytes(range(32)), 0x46DD794E),
]


def test_crc32c_matches_rfc3720_vectors():
    for data, want in _RFC3720_VECTORS:
        assert wire.crc32c(data) == want, data[:16]
        # the sealing implementation used for the golden batch below
        # must itself pass the published vectors
        assert _crc32c_bitwise(data) == want, data[:16]


def test_crc32c_agrees_with_independent_implementation_on_fuzz():
    rng = np.random.default_rng(7)
    for n in (1, 2, 3, 7, 64, 255, 1024, 4097):
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert wire.crc32c(data) == _crc32c_bitwise(data)


# -- zigzag varints (Protocol Buffers encoding spec) ----------------------

# (signed value, zigzag-encoded unsigned) from the protobuf spec table
_ZIGZAG_VECTORS = [(0, 0), (-1, 1), (1, 2), (-2, 3), (2, 4),
                   (2147483647, 4294967294), (-2147483648, 4294967295)]


def test_zigzag_matches_protobuf_spec_table():
    for signed, encoded in _ZIGZAG_VECTORS:
        assert wire._zigzag(signed) & 0xFFFFFFFFFFFFFFFF == encoded
        assert wire._unzigzag(encoded) == signed


def test_varint_wire_bytes_match_spec():
    # varint(300) per the protobuf spec worked example is AC 02 — for
    # the unsigned value; Kafka writes zigzag(signed), so signed 150
    # (zigzag -> 300) must serialize to AC 02
    buf = bytearray()
    wire.write_varint(buf, 150)
    assert bytes(buf) == b"\xac\x02"
    # single-byte boundary: zigzag(63) = 126 = 0x7E; zigzag(64) = 128
    # crosses into two bytes 0x80 0x01
    buf = bytearray()
    wire.write_varint(buf, 63)
    assert bytes(buf) == b"\x7e"
    buf = bytearray()
    wire.write_varint(buf, 64)
    assert bytes(buf) == b"\x80\x01"
    for v in (0, -1, 1, 63, 64, -65, 150, 10**12, -(10**12)):
        buf = bytearray()
        wire.write_varint(buf, v)
        got, off = wire.read_varint(bytes(buf), 0)
        assert (got, off) == (v, len(buf))


# -- golden v2 RecordBatch (KIP-98 layout, sealed independently) ----------

# baseOffset=0, one record key=b"key" value=b"value", timestamps 1000,
# producer id/epoch/baseSequence -1 (idempotence unused), uncompressed.
# Layout, field by field (big-endian; varints zigzag):
#   baseOffset           int64   0
#   batchLength          int32   64   (partitionLeaderEpoch..end)
#   partitionLeaderEpoch int32   -1
#   magic                int8    2
#   crc                  uint32  0x44C98E4F  = bitwise CRC32C of the
#                                55 tail bytes (attributes..records)
#   attributes           int16   0
#   lastOffsetDelta      int32   0
#   baseTimestamp        int64   1000
#   maxTimestamp         int64   1000
#   producerId           int64   -1
#   producerEpoch        int16   -1
#   baseSequence         int32   -1
#   recordsCount         int32   1
#   record: length=varint(14)=0x1C, attributes=0, tsDelta=varint(0),
#           offsetDelta=varint(0), keyLen=varint(3)=0x06, "key",
#           valueLen=varint(5)=0x0A, "value", headersCount=varint(0)
_GOLDEN_BATCH = bytes.fromhex(
    "000000000000000000000040ffffffff0244c98e4f0000000000000000000000"
    "0003e800000000000003e8ffffffffffffffffffffffffffff000000011c0000"
    "00066b65790a76616c756500")


def test_golden_batch_crc_is_sealed_by_independent_crc32c():
    tail = _GOLDEN_BATCH[21:]
    assert len(tail) == 55
    (crc,) = struct.unpack(">I", _GOLDEN_BATCH[17:21])
    assert crc == 0x44C98E4F
    assert _crc32c_bitwise(tail) == crc


def test_decoder_parses_spec_golden_batch():
    got = wire.decode_record_batches(_GOLDEN_BATCH)
    assert got == [(0, b"key", b"value")]


def test_encoder_reproduces_spec_golden_batch_byte_identical():
    enc = wire.encode_record_batch(0, [(b"key", b"value")],
                                   timestamp_ms=1000)
    assert enc == _GOLDEN_BATCH


# -- golden request frame (RequestHeader v1) ------------------------------

def test_request_header_frame_matches_spec_layout():
    """ApiVersions v0 request for client 'oryx-tpu', correlation 1:
    Size(18) | api_key(18) | api_version(0) | correlation_id(1) |
    client_id as int16-length-prefixed string — the RequestHeader v1
    layout from the protocol guide, assembled here by hand."""
    golden = bytes.fromhex("00000012" "0012" "0000" "00000001"
                           "0008" + b"oryx-tpu".hex())
    head = wire.Writer()
    head.i16(18).i16(0).i32(1)
    head.string("oryx-tpu")
    payload = head.getvalue()
    assert struct.pack("!i", len(payload)) + payload == golden


def test_reader_parses_spec_assembled_api_versions_response():
    """An ApiVersions v0 response body assembled by hand from the spec
    (error_code, then [api_key min max] array) must parse through the
    same Reader primitives the client uses."""
    body = struct.pack(">hih h h", 0, 2, 18, 0, 2) \
        + struct.pack(">hhh", 3, 0, 9)
    r = wire.Reader(body)
    assert r.i16() == 0
    rows = r.array(lambda rr: (rr.i16(), rr.i16(), rr.i16()))
    assert rows == [(18, 0, 2), (3, 0, 9)]
    assert r.remaining() == 0


# -- property / fuzz round-trips ------------------------------------------

def _random_records(rng, n):
    out = []
    for _ in range(n):
        key = None if rng.random() < 0.25 else \
            rng.integers(0, 256, int(rng.integers(0, 40)),
                         dtype=np.uint8).tobytes()
        value = None if rng.random() < 0.1 else \
            rng.integers(0, 256, int(rng.integers(0, 300)),
                         dtype=np.uint8).tobytes()
        out.append((key, value))
    return out


def test_record_batch_roundtrip_fuzz():
    rng = np.random.default_rng(11)
    for trial in range(40):
        base = int(rng.integers(0, 2**40))
        recs = _random_records(rng, int(rng.integers(1, 20)))
        ts = int(rng.integers(0, 2**41))
        enc = wire.encode_record_batch(base, recs, timestamp_ms=ts)
        # frame invariants straight from the spec
        (base_off, batch_len) = struct.unpack_from(">qi", enc, 0)
        assert base_off == base and batch_len == len(enc) - 12
        assert enc[16] == 2  # magic
        (crc,) = struct.unpack_from(">I", enc, 17)
        assert crc == _crc32c_bitwise(enc[21:])
        got = wire.decode_record_batches(enc)
        assert got == [(base + i, k, v)
                       for i, (k, v) in enumerate(recs)]


def test_multi_batch_concatenation_and_truncated_tail():
    rng = np.random.default_rng(13)
    batches, want, off = [], [], 5
    for _ in range(4):
        recs = _random_records(rng, int(rng.integers(1, 8)))
        batches.append(wire.encode_record_batch(off, recs))
        want += [(off + i, k, v) for i, (k, v) in enumerate(recs)]
        off += len(recs)
    blob = b"".join(batches)
    assert wire.decode_record_batches(blob) == want
    # a broker may cut the stream at max_bytes mid-batch: every prefix
    # must decode to a prefix of the full record list, never raise
    for cut in range(len(blob)):
        got = wire.decode_record_batches(blob[:cut])
        assert got == want[:len(got)]


def test_control_batch_skipped_and_compressed_rejected():
    data = bytearray(wire.encode_record_batch(0, [(b"k", b"v")]))
    # attributes live right after the crc (offset 21); bit 5 = control
    control = bytearray(data)
    control[22] |= 0x20
    struct.pack_into(">I", control, 17,
                     _crc32c_bitwise(bytes(control[21:])))
    follow = wire.encode_record_batch(1, [(b"k2", b"v2")])
    assert wire.decode_record_batches(bytes(control) + follow) == \
        [(1, b"k2", b"v2")]
    compressed = bytearray(data)
    compressed[22] |= 0x01  # gzip codec bits
    struct.pack_into(">I", compressed, 17,
                     _crc32c_bitwise(bytes(compressed[21:])))
    with pytest.raises(wire.KafkaProtocolError):
        wire.decode_record_batches(bytes(compressed))


# -- murmur2 keyed partitioning (DefaultPartitioner contract) ----------------

def test_murmur2_matches_kafka_utils_test_golden_vectors():
    """Golden values from the Kafka project's own test suite
    (clients/src/test/.../org/apache/kafka/common/utils/UtilsTest.java,
    testMurmur2) — Java returns signed int32, ours the masked unsigned
    form of the same bits."""
    from oryx_tpu.kafka.partitioner import murmur2

    def signed(v):
        return v - (1 << 32) if v >= (1 << 31) else v

    golden = {
        b"21": -973932308,
        b"foobar": -790332482,
        b"a-little-bit-long-string": -985981536,
        b"a-little-bit-longer-string": -1486304829,
        b"lkjh234lh9fiuh90y23oiuhsafujhadof229phr9h19h89h8": -58897971,
        bytes([ord("a"), ord("b"), ord("c")]): 479470107,
    }
    for data, want in golden.items():
        assert signed(murmur2(data)) == want, data


def test_murmur2_agrees_with_independent_reimplementation_on_fuzz():
    """An independently written murmur2 (struct-based word loop instead
    of int.from_bytes slicing) must agree on random inputs of every
    tail-length class — a spec-transcription error in either copy would
    show immediately."""
    from oryx_tpu.kafka.partitioner import murmur2

    def murmur2_independent(data: bytes) -> int:
        m, mask = 0x5BD1E995, 0xFFFFFFFF
        h = (0x9747B28C ^ len(data)) & mask
        n_words = len(data) // 4
        for (k,) in struct.iter_unpack("<I", data[:4 * n_words]):
            k = (k * m) & mask
            k ^= k >> 24
            k = (k * m) & mask
            h = ((h * m) & mask) ^ k
        tail = data[4 * n_words:]
        if len(tail) == 3:
            h ^= tail[2] << 16
        if len(tail) >= 2:
            h ^= tail[1] << 8
        if len(tail) >= 1:
            h ^= tail[0]
            h = (h * m) & mask
        h ^= h >> 13
        h = (h * m) & mask
        h ^= h >> 15
        return h

    rng = np.random.default_rng(11)
    for n in list(range(0, 9)) + [100, 1001]:
        for _ in range(20):
            data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            assert murmur2(data) == murmur2_independent(data), (n, data)


def test_keyed_partitioning_agrees_across_broker_backends():
    """The same key must land on the same partition no matter the
    backend: the in-proc broker's partition choice must equal the wire
    client's DefaultPartitioner arithmetic (in-proc used crc32 until
    the cluster made cross-backend key affinity load-bearing)."""
    from oryx_tpu.kafka.inproc import InProcBroker
    from oryx_tpu.kafka.partitioner import murmur2, partition_for_key

    broker = InProcBroker("conformance-partitioning")
    broker.create_topic("pt", partitions=4)
    t = broker._topic("pt")
    for key in ("alpha", "beta", "", "日本語", "u" * 100, "21", "foobar"):
        wire_choice = (murmur2(key.encode("utf-8")) & 0x7FFFFFFF) % 4
        assert t.partition_for(key) == wire_choice == \
            partition_for_key(key, 4), key
