"""Numerical fault tolerance (marker: numerics, in tier-1).

Three layers under test:
1. the f64 rescue ladder — solver-level (ops/solver.py) and
   trainer-level (app/als/trainer.py f32 -> f64 -> escalated lambda);
2. oracle parity — the TPU trainer must reach the in-tree float64
   NumPy ALS oracle's RMSE/AUC at equal hyperparams (the strongest
   available substitute for the MLlib side of the north-star gate);
3. the pre-publish validation gate — ml/mlupdate.py provably refuses
   to publish a model with non-finite factors or a non-finite eval.
"""

import os

import numpy as np
import pytest

from oryx_tpu.app.als.common import ParsedRatings
from oryx_tpu.app.als.evaluation import area_under_curve, rmse
from oryx_tpu.app.als.trainer import train_als
from oryx_tpu.bench.train import synthesize_movielens
from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KeyMessage
from oryx_tpu.kafka.inproc import InProcTopicProducer, get_broker
from oryx_tpu.ml.integrity import (ModelIntegrityError, check_finite_array,
                                   is_finite_array)
from oryx_tpu.ml.oracle import train_als_oracle
from oryx_tpu.ops.solver import SingularMatrixSolverException, get_solver
from oryx_tpu.resilience import faults

pytestmark = pytest.mark.numerics


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# -- integrity primitives ----------------------------------------------------

def test_is_finite_array():
    assert is_finite_array(np.zeros((3, 3), np.float32))
    assert is_finite_array(np.zeros((0, 4)))
    assert not is_finite_array(np.array([1.0, np.nan]))
    assert not is_finite_array(np.array([[np.inf]]))


def test_check_finite_array_raises_with_count():
    with pytest.raises(ModelIntegrityError, match="2 non-finite"):
        check_finite_array("X", np.array([1.0, np.nan, np.inf]))
    check_finite_array("ok", np.ones(4))  # no raise


# -- solver-level f64 rescue -------------------------------------------------

def test_solver_f64_rescue_solves_correctly():
    rng = np.random.default_rng(0)
    m = rng.standard_normal((12, 6))
    a = m.T @ m + 0.1 * np.eye(6)
    reference = get_solver(a)
    assert reference.precision == "float32"
    faults.inject("solver-f32-discard", mode="drop", times=1)
    rescued = get_solver(a)
    assert faults.fired("solver-f32-discard") == 1
    assert rescued.precision == "float64"
    b = rng.standard_normal((5, 6)).astype(np.float32)
    np.testing.assert_allclose(rescued.solve(b), reference.solve(b),
                               rtol=1e-4, atol=1e-5)
    # the device-facing factor stays finite and usable
    assert bool(np.all(np.isfinite(np.asarray(rescued.cholesky))))


def test_solver_marginally_conditioned_gramian_still_solves():
    """A Gramian just inside the singularity gate (condition ~5e4) must
    yield a working solver whichever precision path it takes."""
    q, _ = np.linalg.qr(np.random.default_rng(1).standard_normal((6, 6)))
    a = (q * np.array([1e4, 1e4, 1e4, 1e4, 1e4, 2e-1])) @ q.T
    a = (a + a.T) / 2.0
    s = get_solver(a)
    x = s.solve(np.ones(6, np.float32))
    resid = a @ x.astype(np.float64) - 1.0
    assert float(np.max(np.abs(resid))) < 1e-2


def test_solver_still_rejects_indefinite_and_nonfinite():
    with pytest.raises(SingularMatrixSolverException):
        get_solver(np.diag([1.0, -1.0, 1.0]))  # indefinite in f64 too
    with pytest.raises(SingularMatrixSolverException):
        get_solver(np.array([[np.nan, 0.0], [0.0, 1.0]]))


# -- trainer rescue ladder ---------------------------------------------------

def _ratings(n_u=60, n_i=40, nnz=800, seed=3, explicit=False):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, n_u, nnz).astype(np.int32)
    items = rng.integers(0, n_i, nnz).astype(np.int32)
    vals = (np.clip(rng.normal(3.0, 1.0, nnz), 0.5, 5.0) if explicit
            else rng.exponential(1.0, nnz)).astype(np.float32)
    return ParsedRatings([f"u{u}" for u in range(n_u)],
                         [f"i{i}" for i in range(n_i)],
                         users, items, vals)


def test_trainer_rescue_produces_finite_equivalent_factors():
    ratings = _ratings()
    clean = train_als(ratings, 4, 0.01, 1.0, True, 3, seed=11)
    assert clean.rescue is None
    faults.inject("trainer-f32-poison", mode="drop", times=1)
    rescued = train_als(ratings, 4, 0.01, 1.0, True, 3, seed=11)
    assert faults.fired("trainer-f32-poison") == 1
    assert rescued.rescue is not None
    assert rescued.rescue["precision"] == "float64"
    assert rescued.rescue["escalated_lambda"] is None
    assert np.all(np.isfinite(rescued.X)) and np.all(np.isfinite(rescued.Y))
    # the f64 retrain optimizes the same objective from the same init:
    # factors match the healthy f32 run to f32 round-off
    np.testing.assert_allclose(rescued.X, clean.X, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(rescued.Y, clean.Y, rtol=1e-3, atol=1e-4)


def test_trainer_rescue_explicit_mode():
    ratings = _ratings(explicit=True)
    faults.inject("trainer-f32-poison", mode="drop", times=1)
    rescued = train_als(ratings, 4, 0.05, 1.0, False, 3, seed=11)
    assert rescued.rescue is not None
    assert np.all(np.isfinite(rescued.X)) and np.all(np.isfinite(rescued.Y))


# -- oracle parity (the north-star quality gate's runnable half) -------------

def _synthetic_100k(implicit: bool):
    users, items, imp_vals, exp_vals, _ = synthesize_movielens(
        n_users=1500, n_items=800, n_ratings=100_000, seed=7)
    vals = (imp_vals if implicit else exp_vals).astype(np.float32)
    n_users = int(users.max()) + 1
    n_items = int(items.max()) + 1
    # time-less random holdout: 10% test
    rng = np.random.default_rng(13)
    test_mask = rng.random(len(users)) < 0.1
    return (users, items, vals, n_users, n_items, test_mask)


def _trainer_factors(users, items, vals, n_users, n_items, k, lam, alpha,
                     implicit, iterations, seed):
    ratings = ParsedRatings([str(u) for u in range(n_users)],
                            [str(i) for i in range(n_items)],
                            users.astype(np.int32), items.astype(np.int32),
                            vals)
    model = train_als(ratings, k, lam, alpha, implicit, iterations,
                      seed=seed)
    assert model.rescue is None, "oracle-parity run should not need rescue"
    return model.X, model.Y


def test_oracle_parity_explicit_rmse_100k():
    users, items, vals, n_users, n_items, test_mask = _synthetic_100k(False)
    k, lam, alpha, iters = 12, 0.05, 1.0, 5
    tr_u, tr_i, tr_v = users[~test_mask], items[~test_mask], vals[~test_mask]
    te_u, te_i, te_v = users[test_mask], items[test_mask], vals[test_mask]

    X, Y = _trainer_factors(tr_u, tr_i, tr_v, n_users, n_items, k, lam,
                            alpha, False, iters, seed=5)
    oracle = train_als_oracle(tr_u, tr_i, tr_v, n_users, n_items, k, lam,
                              alpha, False, iters, seed=5)

    got = rmse(X, Y, te_u, te_i, te_v)
    want = rmse(oracle.X.astype(np.float32), oracle.Y.astype(np.float32),
                te_u, te_i, te_v)
    # equal-or-better within 5% relative: the trainer may not trail the
    # trusted f64 implementation at equal hyperparameters
    assert got <= want * 1.05, (got, want)


def test_oracle_parity_implicit_auc_100k():
    users, items, vals, n_users, n_items, test_mask = _synthetic_100k(True)
    k, lam, alpha, iters = 12, 0.01, 1.0, 5
    tr_u, tr_i, tr_v = users[~test_mask], items[~test_mask], vals[~test_mask]
    te_u, te_i = users[test_mask], items[test_mask]

    X, Y = _trainer_factors(tr_u, tr_i, tr_v, n_users, n_items, k, lam,
                            alpha, True, iters, seed=5)
    oracle = train_als_oracle(tr_u, tr_i, tr_v, n_users, n_items, k, lam,
                              alpha, True, iters, seed=5)

    got = area_under_curve(X, Y, te_u.astype(np.int32),
                           te_i.astype(np.int32))
    want = area_under_curve(oracle.X.astype(np.float32),
                            oracle.Y.astype(np.float32),
                            te_u.astype(np.int32), te_i.astype(np.int32))
    assert want > 0.6, f"oracle itself failed to learn (AUC {want})"
    assert got >= want - 0.03, (got, want)


def test_oracle_recovers_planted_structure_vs_unregularized_noise():
    """Sanity on the oracle itself: it must beat random factors by a
    wide margin on the planted-structure data, or parity with it means
    nothing."""
    users, items, vals, n_users, n_items, test_mask = _synthetic_100k(True)
    tr_u, tr_i, tr_v = users[~test_mask], items[~test_mask], vals[~test_mask]
    te_u, te_i = users[test_mask].astype(np.int32), \
        items[test_mask].astype(np.int32)
    oracle = train_als_oracle(tr_u, tr_i, tr_v, n_users, n_items, 12,
                              0.01, 1.0, True, 5, seed=5)
    rng = np.random.default_rng(0)
    rand_auc = area_under_curve(
        rng.standard_normal((n_users, 12)).astype(np.float32),
        rng.standard_normal((n_items, 12)).astype(np.float32), te_u, te_i)
    oracle_auc = area_under_curve(oracle.X.astype(np.float32),
                                  oracle.Y.astype(np.float32), te_u, te_i)
    assert oracle_auc > rand_auc + 0.15, (oracle_auc, rand_auc)


# -- pre-publish validation gate --------------------------------------------

def _als_cfg(**extra):
    overlay = {
        "oryx.als.implicit": False,
        "oryx.als.iterations": 2,
        "oryx.als.hyperparams.features": 3,
        "oryx.als.hyperparams.lambda": 0.1,
        "oryx.ml.eval.test-fraction": 0.1,
    }
    overlay.update(extra)
    return from_dict(overlay)


def _als_messages(n=300, seed=4):
    rng = np.random.default_rng(seed)
    t = 1_700_000_000_000
    msgs = []
    for j in range(n):
        u, i = rng.integers(0, 40), rng.integers(0, 25)
        msgs.append(KeyMessage(None, f"u{u},i{i},{rng.uniform(1, 5):.2f},"
                                     f"{t + j * 1000}"))
    return msgs


def test_mlupdate_refuses_to_publish_nonfinite_factors(tmp_path):
    """A candidate whose factor artifact carries NaN must never become
    the published generation, even when it is the only candidate."""
    from oryx_tpu.app.als.update import ALSUpdate, save_features

    class PoisonedALSUpdate(ALSUpdate):
        def build_model(self, train_data, hyper_parameters, candidate_path):
            doc = super().build_model(train_data, hyper_parameters,
                                      candidate_path)
            # corrupt the already-written Y artifact in place
            ids = [f"i{i}" for i in range(3)]
            bad = np.full((3, 3), np.nan, dtype=np.float32)
            save_features(os.path.join(candidate_path, "Y"), ids, bad)
            return doc

    update = PoisonedALSUpdate(_als_cfg())
    producer = InProcTopicProducer("memory://numerics-gate", "NumT1")
    model_dir = str(tmp_path / "model")
    update.run_update(0, _als_messages(), [], model_dir, producer)
    broker = get_broker("numerics-gate")
    msgs = list(broker.consume("NumT1", from_beginning=True,
                               max_idle_sec=0.1))
    assert msgs == [], "published a NaN model"
    assert [d for d in os.listdir(model_dir) if d.isdigit()] == []


def test_mlupdate_refuses_nonfinite_factors_even_with_eval_disabled(tmp_path):
    from oryx_tpu.app.als.update import ALSUpdate, save_features

    class PoisonedALSUpdate(ALSUpdate):
        def build_model(self, train_data, hyper_parameters, candidate_path):
            doc = super().build_model(train_data, hyper_parameters,
                                      candidate_path)
            ids = [f"i{i}" for i in range(3)]
            save_features(os.path.join(candidate_path, "Y"), ids,
                          np.full((3, 3), np.inf, dtype=np.float32))
            return doc

    update = PoisonedALSUpdate(_als_cfg(**{"oryx.ml.eval.test-fraction": 0.0}))
    model_dir = str(tmp_path / "model")
    update.run_update(0, _als_messages(), [], model_dir, None)
    assert [d for d in os.listdir(model_dir) if d.isdigit()] == []


def test_mlupdate_rejects_nonfinite_eval(tmp_path):
    """+Inf (or -Inf) eval is a degenerate metric: such a candidate may
    never outrank a real one."""
    from tests.test_ml import MockMLUpdate, _reset_mock

    _reset_mock([float("inf"), 0.4])
    cfg = from_dict({"oryx.ml.eval.candidates": 2,
                     "oryx.ml.eval.parallelism": 1})
    update = MockMLUpdate(cfg)
    producer = InProcTopicProducer("memory://numerics-gate", "NumT2")
    data = [KeyMessage(None, f"line{i}") for i in range(60)]
    update.run_update(0, data, [], str(tmp_path / "model"), producer)
    broker = get_broker("numerics-gate")
    msgs = list(broker.consume("NumT2", from_beginning=True,
                               max_idle_sec=0.1))
    assert len(msgs) == 1  # the finite candidate won; +Inf did not


def test_sweep_records_rescue_and_gates_on_all_finite():
    """The sweep artifact carries per-candidate rescue records and the
    0-NaN gate, at test scale over the reference's grid (including the
    lambda=5e-4 half that used to diverge)."""
    from oryx_tpu.bench.sweep import run_sweep

    r = run_sweep(ratings=3000, iterations=2, n_users=150, n_items=80)
    assert r["published_is_argmax"]
    assert r["nan_candidates"] == 0 and r["all_candidates_trained"]
    assert len(r["candidates"]) == 4
    assert all("rescue" in c for c in r["candidates"])
    assert r["rescued_candidates"] == sum(
        1 for c in r["candidates"] if c["rescue"])


def test_sweep_poisoned_candidate_is_rescued_and_recorded():
    """One injected f32 divergence mid-sweep: the candidate retrains on
    the f64 rung, evaluates finite, and the artifact records exactly
    one rescue — 0 NaN candidates either way."""
    from oryx_tpu.bench.sweep import run_sweep

    faults.inject("trainer-f32-poison", mode="drop", times=1)
    r = run_sweep(ratings=3000, iterations=2, n_users=150, n_items=80)
    assert faults.fired("trainer-f32-poison") == 1
    assert r["nan_candidates"] == 0 and r["all_candidates_trained"]
    assert r["rescued_candidates"] == 1
    assert r["rescues"]["float64"] + r["rescues"]["escalated_lambda"] == 1
    assert r["published_is_argmax"]


def test_rescued_candidate_annotated_in_pmml(tmp_path):
    """End-to-end through ALSUpdate: a poisoned f32 factorization leads
    to a PUBLISHED, finite, rescue-annotated model — never a NaN one."""
    from oryx_tpu.app.als.update import ALSUpdate, load_features
    from oryx_tpu.ml.mlupdate import MODEL_FILE_NAME

    faults.inject("trainer-f32-poison", mode="drop", times=1)
    update = ALSUpdate(_als_cfg())
    model_dir = str(tmp_path / "model")
    update.run_update(0, _als_messages(), [], model_dir, None)
    published = [d for d in os.listdir(model_dir) if d.isdigit()]
    assert len(published) == 1
    doc = pmml_io.read(os.path.join(model_dir, published[0],
                                    MODEL_FILE_NAME))
    rescue = pmml_io.get_extension_value(doc, "rescue")
    assert rescue is not None and "float64" in rescue
    for side in ("X", "Y"):
        _, matrix = load_features(os.path.join(model_dir, published[0],
                                               side))
        assert matrix.size and np.all(np.isfinite(matrix))
