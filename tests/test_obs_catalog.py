"""Metric/span name lint (ISSUE 5 satellite): every counter, gauge,
and span name literal in the source must appear in the
docs/OBSERVABILITY.md catalog tables and follow the naming rules —
the same keep-the-namespace-from-rotting contract RESILIENCE.md
already enforces for fault-point names.

The walk is AST-based (not regex) so multi-line call sites and
keyword-argument forms are seen.  Names are collected from the
call-site surface of MetricsRegistry and Tracer:

- ``.inc("<counter>")``
- ``.set_gauge("<gauge>", ...)`` / ``.gauge_fn("<gauge>", ...)``
- ``.span("<span>")`` / ``.child_span(parent, "<span>")`` /
  ``.record_span("<span>", ...)``

Request spans are built dynamically as ``f"{service}.request"``
(lambda_rt/http.py), so the known service tiers' request spans are
asserted against the catalog explicitly.
"""

from __future__ import annotations

import ast
import pathlib
import re

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
SRC = REPO / "oryx_tpu"
DOC = REPO / "docs" / "OBSERVABILITY.md"

# snake_case on both sides of the single dot for spans; plain
# snake_case for counters/gauges
_SPAN_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$")
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# (method attribute, index of the positional name argument)
_SPAN_METHODS = {"span": 0, "child_span": 1, "record_span": 0}
_COUNTER_METHODS = {"inc": 0}
_GAUGE_METHODS = {"set_gauge": 0, "gauge_fn": 0}

# dynamic f"{service}.request" spans (lambda_rt/http.py): one per
# tier with an HTTP surface — router, serving, and the headless
# tiers' side-door ObsServer — not literals the AST walk can see
_DYNAMIC_REQUEST_SPANS = {"router.request", "serving.request",
                          "speed.request", "batch.request",
                          "mirror.request"}


def _literal_arg(call: ast.Call, index: int) -> str | None:
    if len(call.args) > index:
        arg = call.args[index]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
    return None


def _collect_names():
    """{kind: {name: [file:line, ...]}} for every literal call site."""
    found: dict[str, dict[str, list[str]]] = {
        "span": {}, "counter": {}, "gauge": {}}
    for path in sorted(SRC.rglob("*.py")):
        tree = ast.parse(path.read_text(encoding="utf-8"),
                         filename=str(path))
        rel = path.relative_to(REPO)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            for kind, methods in (("span", _SPAN_METHODS),
                                  ("counter", _COUNTER_METHODS),
                                  ("gauge", _GAUGE_METHODS)):
                if attr in methods:
                    name = _literal_arg(node, methods[attr])
                    if name is not None:
                        found[kind].setdefault(name, []).append(
                            f"{rel}:{node.lineno}")
    return found


def _catalog_names() -> set[str]:
    """Backticked names from the first cell of every catalog table row
    in docs/OBSERVABILITY.md (prose mentions elsewhere don't count as
    cataloguing)."""
    names = set()
    for line in DOC.read_text(encoding="utf-8").splitlines():
        if not line.startswith("|"):
            continue
        first_cell = line.split("|")[1].strip()
        m = re.fullmatch(r"`([^`]+)`", first_cell)
        if m:
            names.add(m.group(1))
    return names


@pytest.fixture(scope="module")
def source_names():
    return _collect_names()


@pytest.fixture(scope="module")
def catalog():
    assert DOC.is_file(), "docs/OBSERVABILITY.md is the catalog source"
    names = _catalog_names()
    assert names, "no catalog tables parsed from OBSERVABILITY.md"
    return names


def test_walk_sees_the_known_call_sites(source_names):
    # the lint is only as good as its walk: pin a known literal of
    # each kind so an AST/API drift fails loudly instead of silently
    # linting nothing
    assert "router.merge" in source_names["span"]
    assert "serving.queue_wait" in source_names["span"]
    assert "partial_answers" in source_names["counter"]
    assert "ingest_to_servable_ms" in source_names["gauge"]
    assert "update_lag_records" in source_names["gauge"]


def test_every_source_name_is_catalogued(source_names, catalog):
    missing = [
        f"{kind} {name!r} ({', '.join(sites)})"
        for kind, names in source_names.items()
        for name, sites in sorted(names.items())
        if name not in catalog]
    assert not missing, (
        "names used in source but absent from the docs/OBSERVABILITY.md"
        " catalog tables:\n  " + "\n  ".join(missing))


def test_dynamic_request_spans_are_catalogued(catalog):
    missing = _DYNAMIC_REQUEST_SPANS - catalog
    assert not missing, (
        f"dynamic request spans missing from the catalog: {missing}")


def _module_tuple(path: pathlib.Path, name: str) -> tuple[str, ...]:
    """A module-level ``NAME = ("...", ...)`` string-tuple literal,
    extracted via AST (no import needed)."""
    tree = ast.parse(path.read_text(encoding="utf-8"),
                     filename=str(path))
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            value = node.value
            assert isinstance(value, ast.Tuple), f"{name} not a tuple"
            out = []
            for el in value.elts:
                assert isinstance(el, ast.Constant) \
                    and isinstance(el.value, str), f"{name}: non-string"
                out.append(el.value)
            return tuple(out)
    raise AssertionError(f"{name} not found in {path}")


def test_anatomy_stage_names_are_catalogued(catalog):
    """The /admin/tail stage taxonomy (obs/anatomy.py STAGES) must be
    in the OBSERVABILITY.md stage table — same rot-prevention contract
    as the span names.  Stages are tier.operation like spans, except
    the designated residue bucket ``untraced``."""
    stages = _module_tuple(SRC / "obs" / "anatomy.py", "STAGES")
    assert len(stages) >= 5
    missing = set(stages) - catalog
    assert not missing, \
        f"anatomy stages missing from the catalog: {sorted(missing)}"
    for name in stages:
        assert name == "untraced" or _SPAN_RE.fullmatch(name), \
            f"stage {name!r} must be tier.operation snake_case"


def test_wide_event_fields_are_catalogued(catalog):
    """Every wide-event field (obs/events.py FIELDS) must be in the
    OBSERVABILITY.md schema table, snake_case."""
    fields = _module_tuple(SRC / "obs" / "events.py", "FIELDS")
    assert len(fields) >= 6
    missing = set(fields) - catalog
    assert not missing, \
        f"wide-event fields missing from the catalog: {sorted(missing)}"
    for name in fields:
        assert _NAME_RE.fullmatch(name), \
            f"wide-event field {name!r} must be snake_case"


def test_names_follow_the_naming_rules(source_names):
    bad = []
    for name, sites in sorted(source_names["span"].items()):
        if not _SPAN_RE.fullmatch(name):
            bad.append(f"span {name!r} must be tier.operation "
                       f"snake_case ({', '.join(sites)})")
    for kind in ("counter", "gauge"):
        for name, sites in sorted(source_names[kind].items()):
            if not _NAME_RE.fullmatch(name):
                bad.append(f"{kind} {name!r} must be snake_case "
                           f"({', '.join(sites)})")
    assert not bad, "\n".join(bad)
