"""Messaging layer tests (reference analogs: KafkaUtilsTest and the
LocalKafkaBroker-based produce/consume fixtures)."""

import threading
import time

import pytest

from oryx_tpu.kafka import InProcBroker, utils
from oryx_tpu.kafka.api import KEY_MODEL, KEY_UP, KeyMessage
from oryx_tpu.kafka.inproc import InProcTopicProducer, get_broker, resolve_broker


@pytest.fixture
def broker():
    b = InProcBroker("test-" + str(time.monotonic_ns()))
    yield b


def test_topic_admin(broker):
    assert not broker.topic_exists("t")
    broker.create_topic("t")
    assert broker.topic_exists("t")
    broker.delete_topic("t")
    assert not broker.topic_exists("t")


def test_produce_consume_from_beginning(broker):
    broker.send("t", KEY_MODEL, "<PMML/>")
    broker.send("t", KEY_UP, '["X","u1",[0.1]]')
    got = []
    stop = threading.Event()
    for km in broker.consume("t", from_beginning=True, stop=stop,
                             max_idle_sec=0.2):
        got.append(km)
        if len(got) == 2:
            stop.set()
    assert got == [KeyMessage(KEY_MODEL, "<PMML/>"),
                   KeyMessage(KEY_UP, '["X","u1",[0.1]]')]


def test_consume_latest_skips_history(broker):
    broker.send("t", None, "old")
    out = list(broker.consume("t", max_idle_sec=0.1))
    assert out == []


def test_group_offsets_resume(broker):
    for i in range(5):
        broker.send("t", None, f"m{i}")
    first = []
    for km in broker.consume("t", group="g", from_beginning=True, max_idle_sec=0.1):
        first.append(km.message)
        if len(first) == 3:
            break
    assert first == ["m0", "m1", "m2"]
    # a new consumer in the same group resumes from the last COMMITTED
    # message: m2 was in flight when the first consumer broke, so
    # at-least-once redelivers it (duplicates possible, loss impossible)
    rest = [km.message for km in broker.consume("t", group="g", max_idle_sec=0.1)]
    assert rest == ["m2", "m3", "m4"]


def test_fill_in_latest_offsets(broker):
    broker.send("t", None, "a")
    broker.send("t", None, "b")
    broker.fill_in_latest_offsets("g", ["t"])
    assert broker.get_offset("g", "t") == 2
    out = [km.message for km in broker.consume("t", group="g", max_idle_sec=0.1)]
    assert out == []  # starts from now


def test_blocking_consumer_sees_live_messages(broker):
    got = []
    done = threading.Event()

    def consumer():
        for km in broker.consume("t", from_beginning=True, max_idle_sec=2.0):
            got.append(km.message)
            if len(got) == 2:
                done.set()
                return

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    broker.send("t", None, "live1")
    broker.send("t", None, "live2")
    assert done.wait(3.0)
    t.join()
    assert got == ["live1", "live2"]


def test_offset_commits_after_processing(broker):
    # at-least-once: if the consumer FAILS while processing message N, the
    # group offset must still point at N so it is redelivered
    for i in range(3):
        broker.send("t", None, f"m{i}")
    it = broker.consume("t", group="g", from_beginning=True, max_idle_sec=0.1)
    next(it)  # m0 delivered, processing begins...
    with pytest.raises(RuntimeError):
        it.throw(RuntimeError("crash mid-processing"))
    # m0 was never committed -> a restarted consumer sees it again
    redelivered = [km.message
                   for km in broker.consume("t", group="g", from_beginning=True,
                                            max_idle_sec=0.1)]
    assert redelivered[0] == "m0"


def test_delete_topic_clears_persisted_offsets(tmp_path):
    b1 = InProcBroker("d1", persist_dir=str(tmp_path))
    b1.send("t", None, "x")
    b1.set_offset("g", "t", 1)
    b1.flush()
    b1.delete_topic("t")
    b2 = InProcBroker("d2", persist_dir=str(tmp_path))
    assert b2.get_offset("g", "t") is None


def test_persistence_round_trip(tmp_path):
    b1 = InProcBroker("p1", persist_dir=str(tmp_path))
    b1.send("t", "k", "v1")
    b1.send("t", None, "v2")
    b1.set_offset("g", "t", 1)
    b1.flush()
    # a fresh broker over the same dir sees the log and offsets
    b2 = InProcBroker("p2", persist_dir=str(tmp_path))
    msgs = [km for km in b2.consume("t", from_beginning=True, max_idle_sec=0.1)]
    assert [(m.key, m.message) for m in msgs] == [("k", "v1"), (None, "v2")]
    assert b2.get_offset("g", "t") == 1


def test_producer_and_uri_resolution():
    uri = "memory://uri-test"
    p = InProcTopicProducer(uri, "topicA")
    p.send("k", "m")
    assert p.get_update_broker() == uri
    assert p.get_topic() == "topicA"
    b = resolve_broker(uri)
    assert [km.message for km in b.consume("topicA", from_beginning=True,
                                           max_idle_sec=0.1)] == ["m"]


def test_resolve_rejects_external_broker():
    with pytest.raises(RuntimeError, match="Kafka"):
        resolve_broker("localhost:9092")


def test_utils_module():
    uri = "memory://utils-test"
    utils.maybe_create_topic(uri, "t1")
    assert utils.topic_exists(uri, "t1")
    utils.maybe_create_topic(uri, "t1")  # idempotent
    get_broker("utils-test").send("t1", None, "x")
    utils.fill_in_latest_offsets(uri, "g", ["t1"])
    assert utils.get_offsets(uri, "g", ["t1"]) == {"t1": 1}
    utils.set_offsets(uri, "g", {"t1": 0})
    assert utils.get_offsets(uri, "g", ["t1"]) == {"t1": 0}
    utils.delete_topic(uri, "t1")
    assert not utils.topic_exists(uri, "t1")
