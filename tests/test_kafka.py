"""Messaging layer tests (reference analogs: KafkaUtilsTest and the
LocalKafkaBroker-based produce/consume fixtures)."""

import threading
import time

import pytest

from oryx_tpu.kafka import InProcBroker, utils
from oryx_tpu.kafka.api import KEY_MODEL, KEY_UP, KeyMessage
from oryx_tpu.kafka.inproc import InProcTopicProducer, get_broker, resolve_broker


@pytest.fixture
def broker():
    b = InProcBroker("test-" + str(time.monotonic_ns()))
    yield b


def test_topic_admin(broker):
    assert not broker.topic_exists("t")
    broker.create_topic("t")
    assert broker.topic_exists("t")
    broker.delete_topic("t")
    assert not broker.topic_exists("t")


def test_consume_latest_skips_history(broker):
    broker.send("t", None, "old")
    out = list(broker.consume("t", max_idle_sec=0.1))
    assert out == []


# produce/replay, group-offset resume and fill-in-latest are covered by
# the binding-parametrized contract suite at the bottom of this file


def test_blocking_consumer_sees_live_messages(broker):
    got = []
    done = threading.Event()

    def consumer():
        for km in broker.consume("t", from_beginning=True, max_idle_sec=2.0):
            got.append(km.message)
            if len(got) == 2:
                done.set()
                return

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    broker.send("t", None, "live1")
    broker.send("t", None, "live2")
    assert done.wait(3.0)
    t.join()
    assert got == ["live1", "live2"]


def test_offset_commits_after_processing(broker):
    # at-least-once: if the consumer FAILS while processing message N, the
    # group offset must still point at N so it is redelivered
    for i in range(3):
        broker.send("t", None, f"m{i}")
    it = broker.consume("t", group="g", from_beginning=True, max_idle_sec=0.1)
    next(it)  # m0 delivered, processing begins...
    with pytest.raises(RuntimeError):
        it.throw(RuntimeError("crash mid-processing"))
    # m0 was never committed -> a restarted consumer sees it again
    redelivered = [km.message
                   for km in broker.consume("t", group="g", from_beginning=True,
                                            max_idle_sec=0.1)]
    assert redelivered[0] == "m0"


def test_delete_topic_clears_persisted_offsets(tmp_path):
    b1 = InProcBroker("d1", persist_dir=str(tmp_path))
    b1.send("t", None, "x")
    b1.set_offset("g", "t", 1)
    b1.flush()
    b1.delete_topic("t")
    b2 = InProcBroker("d2", persist_dir=str(tmp_path))
    assert b2.get_offset("g", "t") is None


def test_persistence_round_trip(tmp_path):
    b1 = InProcBroker("p1", persist_dir=str(tmp_path))
    b1.send("t", "k", "v1")
    b1.send("t", None, "v2")
    b1.set_offset("g", "t", 1)
    b1.flush()
    # a fresh broker over the same dir sees the log and offsets
    b2 = InProcBroker("p2", persist_dir=str(tmp_path))
    msgs = [km for km in b2.consume("t", from_beginning=True, max_idle_sec=0.1)]
    assert [(m.key, m.message) for m in msgs] == [("k", "v1"), (None, "v2")]
    assert b2.get_offset("g", "t") == 1


def test_send_after_close_reopens_the_durable_log(tmp_path):
    """A close()d durable broker handed back by the process-local
    registry must NOT ack appends into memory only: a record invisible
    to every other process is acked-but-lost.  The partition re-opens
    its log on the next append instead (found driving the router's
    cache-invalidation tap with a publisher that had sanity-read and
    closed the same file:// broker earlier in the process)."""
    b1 = InProcBroker("reopen", persist_dir=str(tmp_path))
    b1.send("t", "k", "v1")
    b1.close()
    assert b1.send("t", "k", "v2") == 1  # would previously ack to RAM
    # a fresh broker over the same dir sees BOTH records
    b2 = InProcBroker("reopen2", persist_dir=str(tmp_path))
    msgs = [km.message for km in
            b2.consume("t", from_beginning=True, max_idle_sec=0.1)]
    assert msgs == ["v1", "v2"]


def test_producer_and_uri_resolution():
    uri = "memory://uri-test"
    p = InProcTopicProducer(uri, "topicA")
    p.send("k", "m")
    assert p.get_update_broker() == uri
    assert p.get_topic() == "topicA"
    b = resolve_broker(uri)
    assert [km.message for km in b.consume("topicA", from_beginning=True,
                                           max_idle_sec=0.1)] == ["m"]


def test_resolve_external_broker_binds_wire_client():
    """A bare host:port resolves to the wire-protocol binding (lazy
    connection — errors surface on first use, not at resolve)."""
    from oryx_tpu.kafka.client import KafkaBroker
    b = resolve_broker("localhost:19092")
    assert isinstance(b, KafkaBroker)
    with pytest.raises((ConnectionError, OSError)):
        b.topic_exists("nope")


def test_utils_module():
    uri = "memory://utils-test"
    utils.maybe_create_topic(uri, "t1")
    assert utils.topic_exists(uri, "t1")
    utils.maybe_create_topic(uri, "t1")  # idempotent
    get_broker("utils-test").send("t1", None, "x")
    utils.fill_in_latest_offsets(uri, "g", ["t1"])
    assert utils.get_offsets(uri, "g", ["t1"]) == {"t1": [1]}
    utils.set_offsets(uri, "g", {"t1": [0]})
    assert utils.get_offsets(uri, "g", ["t1"]) == {"t1": [0]}
    utils.delete_topic(uri, "t1")
    assert not utils.topic_exists(uri, "t1")


# -- multi-partition topics (P7 message-partition parallelism) ---------------

def test_keyed_partitioning_is_stable(broker):
    """Same key -> same partition, across sends (Kafka's contract)."""
    broker.create_topic("p", partitions=4)
    assert broker.num_partitions("p") == 4
    t = broker._topic("p")
    for key in ("alpha", "beta", "gamma", "delta", "epsilon"):
        parts = {t.partition_for(key) for _ in range(10)}
        assert len(parts) == 1
    # keyless records round-robin over all partitions
    assert {t.partition_for(None) for _ in range(16)} == {0, 1, 2, 3}


def test_partition_order_preserved_and_concurrent_drain(broker):
    """4-partition ingest: per-partition record order survives the
    concurrent read_ranges drain (the batch layer's P7 path)."""
    broker.create_topic("p", partitions=4)
    per_key = {f"k{i}": [f"k{i}-m{j}" for j in range(25)] for i in range(8)}
    # interleave writers across keys
    for j in range(25):
        for key in per_key:
            broker.send("p", key, per_key[key][j])
    ends = broker.latest_offsets("p")
    assert sum(ends) == 200
    got = broker.read_ranges("p", [0, 0, 0, 0], ends)
    assert len(got) == 200
    seen: dict[str, list[str]] = {}
    for km in got:
        seen.setdefault(km.key, []).append(km.message)
    assert seen == per_key  # order within each key's partition intact


def test_per_partition_offsets_resume(broker):
    """Committed per-(group, topic, partition) offsets resume exactly
    (reference: per-partition ZK offsets, KafkaUtils.java:134-180)."""
    broker.create_topic("p", partitions=4)
    for i in range(40):
        broker.send("p", f"k{i % 8}", f"m{i}")
    ends = broker.latest_offsets("p")
    # consume everything once with a group
    stop = threading.Event()
    first = [km.message for km in broker.consume(
        "p", group="g", from_beginning=True, max_idle_sec=0.2, stop=stop)]
    assert sorted(first) == sorted(f"m{i}" for i in range(40))
    # a partition the keys never hashed to has nothing to commit
    # (murmur2 keyed placement need not cover every partition)
    assert [o or 0 for o in broker.get_offsets("g", "p")] == ends
    # new records land after the committed offsets; resume sees only them
    broker.send("p", "k0", "late0")
    broker.send("p", "k5", "late1")
    second = [km.message for km in broker.consume(
        "p", group="g", from_beginning=True, max_idle_sec=0.2)]
    assert sorted(second) == ["late0", "late1"]


def test_partitioned_persistence_round_trip(tmp_path):
    """Partition logs + meta survive a broker restart; per-partition
    offsets reload."""
    d = str(tmp_path / "broker")
    b1 = InProcBroker("p-persist-1-" + str(time.monotonic_ns()), persist_dir=d)
    b1.create_topic("p", partitions=3)
    for i in range(12):
        b1.send("p", f"k{i % 5}", f"m{i}")
    ends = b1.latest_offsets("p")
    b1.set_offsets("g", "p", ends)
    b1.close()

    b2 = InProcBroker("p-persist-2-" + str(time.monotonic_ns()), persist_dir=d)
    assert b2.num_partitions("p") == 3
    assert b2.latest_offsets("p") == ends
    assert b2.get_offsets("g", "p") == ends
    got = b2.read_ranges("p", [0, 0, 0], ends)
    assert sorted(km.message for km in got) == sorted(f"m{i}" for i in range(12))
    b2.close()


def test_create_topic_partition_mismatch_rejected(broker):
    broker.create_topic("p", partitions=2)
    broker.create_topic("p", partitions=2)  # idempotent
    with pytest.raises(ValueError, match="partition"):
        broker.create_topic("p", partitions=3)


def test_scalar_api_rejects_multipartition(broker):
    broker.create_topic("p", partitions=2)
    with pytest.raises(ValueError, match="partitions"):
        broker.latest_offset("p")
    with pytest.raises(ValueError, match="partitions"):
        broker.read_range("p", 0, 1)


def test_stale_single_partition_writer_lands_in_p0(tmp_path):
    """A process that lazily sees a topic as 1 partition writes to the
    flat file — which IS partition 0 of the real layout — so layout
    disagreement between processes degrades key affinity but never
    strands records.  A late-starting broker consults the on-disk meta
    and sees the full layout."""
    d = str(tmp_path / "broker")
    setup = InProcBroker("meta-setup-" + str(time.monotonic_ns()),
                         persist_dir=d)
    setup.create_topic("In", partitions=4)
    setup.close()

    # a second broker over the same dir that never called create_topic
    # resolves the partition count from the meta sidecar
    late = InProcBroker("meta-late-" + str(time.monotonic_ns()),
                        persist_dir=d)
    assert late.num_partitions("In") == 4
    for i in range(8):
        late.send("In", f"k{i}", f"m{i}")
    ends = late.latest_offsets("In")
    assert sum(ends) == 8
    got = late.read_ranges("In", [0] * 4, ends)
    assert sorted(km.message for km in got) == [f"m{i}" for i in range(8)]
    late.close()


# -- broker contract suite, parametrized over implementations ----------------
#
# The same offset/replay contract must hold for the in-proc broker and
# the real-Kafka binding (reference: KafkaUtils.java:63-181).  The wire
# leg runs the production protocol client (kafka/wire.py) against a
# real-socket broker: an external cluster when KAFKA_TEST_BOOTSTRAP
# names one, otherwise an in-process MiniKafkaBroker — the analog of
# the reference's LocalKafkaBroker.java:35, so this leg ALWAYS runs.

_MINI_BROKER = None


def _wire_test_broker():
    import os
    import socket
    from oryx_tpu.kafka.client import KafkaBroker

    bootstrap = os.environ.get("KAFKA_TEST_BOOTSTRAP")
    if bootstrap:
        first = bootstrap.split(",")[0]
        host, _, port = first.partition(":")
        try:
            socket.create_connection((host, int(port or 9092)), 1).close()
        except (OSError, ValueError):
            pytest.skip(f"no Kafka broker reachable at {bootstrap}")
        return KafkaBroker(first)
    global _MINI_BROKER
    if _MINI_BROKER is None:
        from oryx_tpu.kafka.mini_broker import MiniKafkaBroker
        _MINI_BROKER = MiniKafkaBroker()
    return KafkaBroker(_MINI_BROKER.bootstrap)


@pytest.fixture(params=["inproc", "wire"])
def any_broker(request):
    if request.param == "wire":
        yield _wire_test_broker(), 1.0
    else:
        yield (InProcBroker("contract-" + str(time.monotonic_ns())), 0.2)


@pytest.fixture
def contract_topic(any_broker):
    b, _ = any_broker
    topic = "ct-" + str(time.monotonic_ns())
    b.create_topic(topic, partitions=1)
    yield topic
    b.delete_topic(topic)


def test_contract_produce_consume_replay(any_broker, contract_topic):
    b, idle = any_broker
    t = contract_topic
    b.send(t, KEY_MODEL, "<PMML/>")
    b.send(t, KEY_UP, '["X","u1",[0.1]]')
    got = list(b.consume(t, from_beginning=True, max_idle_sec=idle))
    assert [(m.key, m.message) for m in got] == \
        [(KEY_MODEL, "<PMML/>"), (KEY_UP, '["X","u1",[0.1]]')]


def test_contract_group_offsets_commit_and_resume(any_broker, contract_topic):
    b, idle = any_broker
    t = contract_topic
    for i in range(5):
        b.send(t, None, f"m{i}")
    group = "g-" + t
    first = []
    for km in b.consume(t, group=group, from_beginning=True,
                        max_idle_sec=idle):
        first.append(km.message)
        if len(first) == 3:
            break
    assert first == ["m0", "m1", "m2"]
    # m2 was in-flight when the consumer broke: at-least-once redelivers
    rest = [km.message for km in b.consume(t, group=group,
                                           max_idle_sec=idle)]
    assert rest == ["m2", "m3", "m4"]


def test_contract_fill_in_latest(any_broker, contract_topic):
    b, idle = any_broker
    t = contract_topic
    b.send(t, None, "a")
    b.send(t, None, "b")
    group = "g-" + t
    b.fill_in_latest_offsets(group, [t])
    assert b.get_offsets(group, t) == b.latest_offsets(t)
    out = [km.message for km in b.consume(t, group=group,
                                          max_idle_sec=idle)]
    assert out == []  # starts from now


def test_contract_vector_offset_roundtrip(any_broker, contract_topic):
    b, _ = any_broker
    t = contract_topic
    for i in range(4):
        b.send(t, f"k{i}", f"m{i}")
    ends = b.latest_offsets(t)
    assert sum(ends) == 4
    group = "g-" + t
    b.set_offsets(group, t, ends)
    assert b.get_offsets(group, t) == ends
    got = b.read_ranges(t, [0] * len(ends), ends)
    assert sorted(km.message for km in got) == [f"m{i}" for i in range(4)]


# -- synthetic producers / tailers (ProduceData / ConsumeTopic) ---------------

def test_produce_data_and_consume_topic():
    from oryx_tpu.kafka.produce import (ConsumeTopic, ProduceData,
                                        csv_datum_generator)
    uri = "memory://produce-" + str(time.monotonic_ns())
    tail = ConsumeTopic(uri, "T").start()
    n = ProduceData(csv_datum_generator(3), uri, "T", how_many=25).start()
    assert n == 25
    assert tail.await_count(25)
    got = tail.close()
    assert len(got) == 25
    # CSV shape: id,bool,float
    fields = got[0].message.split(",")
    assert fields[0] == "0" and fields[1] in ("true", "false")
    float(fields[2])


def test_restart_restores_topic_named_like_partition_file(tmp_path):
    """A topic legitimately named '<x>.p<digits>' must survive a broker
    restart as its own flat topic, not be misread as a partition file of
    a topic '<x>' that does not exist (ADVICE r2, inproc restart scan)."""
    b1 = InProcBroker("pn1", persist_dir=str(tmp_path))
    b1.send("events.p2", "k", "v")
    # a sibling flat topic with the stripped name must not change the
    # classification of "events.p2" (it is NOT a partition of "events")
    b1.send("events", "k", "w")
    b1.flush()
    b2 = InProcBroker("pn2", persist_dir=str(tmp_path))
    assert b2.topic_exists("events.p2")
    assert b2.topic_exists("events")
    msgs = list(b2.consume("events.p2", from_beginning=True,
                           max_idle_sec=0.1))
    assert [(m.key, m.message) for m in msgs] == [("k", "v")]


def test_restart_still_recognizes_real_partition_files(tmp_path):
    """The partition-file heuristic keeps working when the base topic's
    flat (partition-0) file and meta sidecar are present."""
    b1 = InProcBroker("pr1", persist_dir=str(tmp_path))
    b1.create_topic("multi", partitions=3)
    for i in range(6):
        b1.send("multi", f"k{i}", f"v{i}")
    b1.flush()
    b2 = InProcBroker("pr2", persist_dir=str(tmp_path))
    assert b2.num_partitions("multi") == 3
    # 'multi.p1'/'multi.p2' must NOT appear as standalone topics
    assert not b2.topic_exists("multi.p1")
    assert not b2.topic_exists("multi.p2")
    got = sorted(m.message for m in b2.consume(
        "multi", from_beginning=True, max_idle_sec=0.1))
    assert got == [f"v{i}" for i in range(6)]
