"""Unit tests: flight recorder, auto-triage rules, device-time
accounting (ISSUE 20).

The two production chaos seams documented in docs/RESILIENCE.md land
here: ``flight-dump-disk-full`` (ENOSPC mid-bundle — the partial temp
file is discarded, the failure is counted, the process is unaffected)
and ``flight-trigger-storm`` (duplicate mode doubles a trigger — the
debounce window must collapse the pair to one bundle).
"""

from __future__ import annotations

import json
import os
import re

import pytest

from oryx_tpu.lambda_rt.metrics import MetricsRegistry
from oryx_tpu.obs.diagnose import (RULES, diagnose, diagnose_bundle,
                                   merge_surfaces,
                                   surface_from_bundle)
from oryx_tpu.obs.device_time import (DeviceTimeAccountant,
                                      install_process_accountant,
                                      process_accountant)
from oryx_tpu.obs.flight import (BUNDLE_FIELDS, RING_EVENT_FIELDS,
                                 FlightRecorder)
from oryx_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _recorder(tmp_path, registry=None, **kw):
    clock = kw.pop("clock", None) or FakeClock()
    kw.setdefault("debounce_sec", 30.0)
    kw.setdefault("dump_on_exit", False)
    rec = FlightRecorder("t", registry, dir=str(tmp_path / "flight"),
                         clock=clock, wall=clock, **kw)
    return rec, clock


def _bundles(tmp_path) -> list[dict]:
    d = tmp_path / "flight"
    if not d.is_dir():
        return []
    out = []
    for name in sorted(os.listdir(d)):
        assert not name.endswith(".tmp"), \
            f"unpublished temp file leaked: {name}"
        with open(d / name, encoding="utf-8") as fh:
            out.append(json.load(fh))
    return out


# -- rings + bundle ----------------------------------------------------------

def test_rings_are_bounded_and_bundle_carries_them(tmp_path):
    reg = MetricsRegistry()
    rec, clock = _recorder(tmp_path, reg, ring_events=8, ring_spans=4,
                           tick_sec=1.0)
    try:
        for i in range(20):
            clock.advance(0.3)
            rec.observe_request(
                "GET /r", 200, 1.5, trace_id=f"t{i}",
                spans=[{"name": "score", "duration_ms": 0.7}])
        out = rec.trigger("manual")
        assert out["dumped"] and out["trigger_id"]
        (bundle,) = _bundles(tmp_path)
        assert set(bundle) >= set(BUNDLE_FIELDS) - {"diagnosis"}
        ev = bundle["flight_events"]
        assert ev["fields"] == list(RING_EVENT_FIELDS)
        assert len(ev["rows"]) == 8          # bounded, newest kept
        assert ev["rows"][-1][4] == "t19"
        assert len(bundle["flight_spans"]["rows"]) == 4
        # the coarse ticks carried counter deltas from the registry
        assert bundle["flight_ticks"]
        assert bundle["counters"].get("flight_dumps", 0) == 0
    finally:
        rec.close()
    assert reg.counters_snapshot()["flight_dumps"] == 1


def test_tick_ring_records_counter_deltas_and_gauges(tmp_path):
    reg = MetricsRegistry()
    reg.set_gauge("cross_region_staleness_ms", 7.0)
    rec, clock = _recorder(tmp_path, reg, tick_sec=1.0)
    try:
        rec.observe_request("GET /r", 200, 1.0)   # first tick
        reg.inc("mirror_link_failures", 3)
        reg.set_gauge("cross_region_staleness_ms", 4200.0)
        clock.advance(1.5)
        rec.observe_request("GET /r", 200, 1.0)   # second tick
        tick = list(rec._ticks_ring)[-1]
        assert tick["counter_deltas"]["mirror_link_failures"] == 3
        assert tick["gauges"]["cross_region_staleness_ms"] == 4200.0
        # the bundle's gauge view IS the newest tick (never a live
        # gauges_snapshot — see the deadlock note in flight.py)
        rec.trigger("manual")
        (bundle,) = _bundles(tmp_path)
        assert bundle["gauges"] == tick["gauges"]
    finally:
        rec.close()


# -- triggers: debounce / dedupe / burst / fan-out ---------------------------

def test_debounce_collapses_local_triggers(tmp_path):
    reg = MetricsRegistry()
    rec, clock = _recorder(tmp_path, reg, debounce_sec=30.0)
    try:
        assert rec.trigger("slo-page")["dumped"]
        res = rec.trigger("slo-page")
        assert res == {"dumped": False, "debounced": True,
                       "debounced_total": 1}
        assert reg.counters_snapshot()["flight_trigger_debounced"] == 1
        assert len(_bundles(tmp_path)) == 1
        # outside the window a fresh local trigger dumps again
        clock.advance(31.0)
        assert rec.trigger("slo-page")["dumped"]
        assert len(_bundles(tmp_path)) == 2
    finally:
        rec.close()


def test_fanned_in_trigger_bypasses_window_but_dedupes_by_id(tmp_path):
    rec, _clock = _recorder(tmp_path, debounce_sec=30.0)
    try:
        assert rec.trigger("chaos-fault")["dumped"]
        # a cluster-correlated capture must not be lost to a local
        # dump moments earlier: the explicit id bypasses the window
        res = rec.trigger("slo-page", trigger_id="ft-123-1-1")
        assert res["dumped"] and res["trigger_id"] == "ft-123-1-1"
        # ... but a same-id replay (scatter retry) is deduped
        res = rec.trigger("slo-page", trigger_id="ft-123-1-1")
        assert res == {"dumped": False, "duplicate": True,
                       "trigger_id": "ft-123-1-1"}
        assert len(_bundles(tmp_path)) == 2
    finally:
        rec.close()


def test_error_burst_triggers_a_dump(tmp_path):
    rec, clock = _recorder(tmp_path, burst_errors=3,
                           burst_window_sec=10.0)
    try:
        for status in (500, 0, 503):
            clock.advance(0.5)
            rec.observe_request("GET /r", status, 2.0)
        (bundle,) = _bundles(tmp_path)
        assert bundle["trigger_reason"] == "error-burst"
        # statuses below the 5xx/0 line never count toward a burst
        clock.advance(60.0)
        for status in (200, 404, 429):
            rec.observe_request("GET /r", status, 2.0)
        assert len(_bundles(tmp_path)) == 1
    finally:
        rec.close()


def test_chaos_fault_fire_is_a_trigger_and_originator_fans_out(tmp_path):
    rec, _clock = _recorder(tmp_path)
    fanned = []
    rec.fan_out = lambda tid, reason: fanned.append((tid, reason))
    try:
        faults.inject("serving-scan-dispatch", mode="error", times=1)
        with pytest.raises(Exception):
            faults.fire("serving-scan-dispatch")
        (bundle,) = _bundles(tmp_path)
        assert bundle["trigger_reason"] == "chaos-fault"
        assert bundle["trigger_detail"]["point"] == \
            "serving-scan-dispatch"
        # the local (originating) trigger fanned the id cluster-wide
        assert fanned == [(bundle["trigger_id"], "chaos-fault")]
        # a fanned-IN trigger (explicit id) must never re-fan
        res = rec.trigger("chaos-fault", trigger_id="ft-9-9-9")
        assert res["dumped"] and "fanned_out" not in res
        assert len(fanned) == 1
    finally:
        rec.close()


def test_closed_recorder_ignores_fault_fires(tmp_path):
    rec, _clock = _recorder(tmp_path)
    rec.close()
    faults.inject("serving-scan-dispatch", mode="error", times=1)
    with pytest.raises(Exception):
        faults.fire("serving-scan-dispatch")
    assert _bundles(tmp_path) == []


# -- the two production chaos seams (docs/RESILIENCE.md rows) ----------------

def test_flight_dump_disk_full_discards_partial_and_counts(tmp_path):
    reg = MetricsRegistry()
    rec, clock = _recorder(tmp_path, reg)
    try:
        faults.inject("flight-dump-disk-full", mode="error", times=1)
        res = rec.trigger("slo-page")
        assert res["dumped"] is False and res["path"] is None
        # the partial temp file was discarded, never published
        assert _bundles(tmp_path) == []
        assert rec.dump_failures == 1
        assert reg.counters_snapshot()["flight_dump_failures"] == 1
        # the process is unaffected: the next trigger (outside the
        # debounce window) publishes normally
        clock.advance(31.0)
        assert rec.trigger("slo-page")["dumped"]
        assert len(_bundles(tmp_path)) == 1
    finally:
        rec.close()


def test_flight_trigger_storm_collapses_to_one_bundle(tmp_path):
    reg = MetricsRegistry()
    rec, _clock = _recorder(tmp_path, reg, debounce_sec=30.0)
    try:
        faults.inject("flight-trigger-storm", mode="duplicate",
                      times=1)
        res = rec.trigger("slo-page")
        assert res["dumped"]
        # duplicate mode doubled the trigger; the debounce window
        # collapsed the pair to ONE published bundle
        assert len(_bundles(tmp_path)) == 1
        assert reg.counters_snapshot()["flight_trigger_debounced"] == 1
    finally:
        rec.close()


# -- auto-triage rules -------------------------------------------------------

def test_diagnose_empty_surface_is_healthy():
    out = diagnose({})
    assert out["healthy"] and out["causes"] == []
    assert out["rules_evaluated"] == len(RULES)


def test_diagnose_mirror_stalled_from_staleness():
    out = diagnose({"gauges": {"cross_region_staleness_ms": 30000.0},
                    "counters": {"mirror_link_failures": 4}})
    top = out["causes"][0]
    assert top["cause"] == "mirror-stalled"
    assert top["evidence"]["mirror_link_failures"] == 4
    assert 0.0 < top["score"] <= 0.95


def test_diagnose_ranks_breaker_over_slow_burn_signals():
    surface = {
        "gauges": {"cross_region_staleness_ms": 3000.0},
        "resilience": {"speed-fold": {"name": "speed-fold",
                                      "state": "open"}},
        "routes": {"GET /recommend": {"count": 40,
                                      "server_errors": 2}},
    }
    causes = [c["cause"] for c in diagnose(surface)["causes"]]
    assert causes[0] == "breaker-open"
    assert set(causes) >= {"breaker-open", "mirror-stalled",
                           "error-burst"}


def test_diagnose_error_burst_needs_material_traffic():
    quiet = diagnose({"routes": {"GET /r": {"count": 3,
                                            "server_errors": 3}}})
    assert not any(c["cause"] == "error-burst"
                   for c in quiet["causes"])
    loud = diagnose({"routes": {"GET /r": {"count": 100,
                                           "server_errors": 30}}})
    assert loud["causes"][0]["cause"] == "error-burst"


def test_diagnose_bundle_reads_the_tick_gauges(tmp_path):
    bundle = {"counters": {"ingest_sheds": 6}, "gauges": None,
              "routes": {}, "resilience": None}
    out = diagnose_bundle(bundle)
    assert out["causes"][0]["cause"] == "ingest-overload"
    surface = surface_from_bundle(bundle)
    assert surface["counters"]["ingest_sheds"] == 6
    assert surface["gauges"] == {}


def test_merge_surfaces_sums_counters_keeps_worst_gauges():
    merged = merge_surfaces([
        {"counters": {"ingest_sheds": 2},
         "gauges": {"device_busy_fraction": 0.2},
         "routes": {"GET /r": {"count": 10, "server_errors": 1}},
         "resilience": {"b": {"name": "b", "state": "closed"}}},
        {"counters": {"ingest_sheds": 3},
         "gauges": {"device_busy_fraction": 0.9},
         "routes": {"GET /r": {"count": 5, "server_errors": 4}},
         "resilience": {"b": {"name": "b", "state": "open"}}},
    ])
    assert merged["counters"]["ingest_sheds"] == 5
    assert merged["gauges"]["device_busy_fraction"] == 0.9
    assert merged["routes"]["GET /r"]["count"] == 15
    assert merged["routes"]["GET /r"]["server_errors"] == 5
    # colliding breaker names keep the open one
    assert merged["resilience"]["b"]["state"] == "open"


def _heading_slug(line: str) -> str:
    text = line.lstrip("#").strip().lower()
    text = re.sub(r"[^a-z0-9 _-]", "", text)
    return text.replace(" ", "-")


def test_every_runbook_anchor_resolves_to_a_real_heading():
    """A runbook link that 404s at 3am is worse than none: every
    rule's ``docs/FILE.md#anchor`` must name a real doc heading
    (GitHub slug rules)."""
    root = os.path.join(os.path.dirname(__file__), os.pardir)
    slugs_by_doc: dict[str, set] = {}
    for rule in RULES:
        doc, _, anchor = rule.runbook.partition("#")
        assert doc and anchor, f"{rule.name}: malformed runbook " \
            f"{rule.runbook!r}"
        if doc not in slugs_by_doc:
            path = os.path.join(root, doc)
            with open(path, encoding="utf-8") as fh:
                slugs_by_doc[doc] = {
                    _heading_slug(ln) for ln in fh
                    if ln.startswith("#")}
        assert anchor in slugs_by_doc[doc], (
            f"rule {rule.name}: anchor #{anchor} not a heading of "
            f"{doc}")


# -- device-time accounting --------------------------------------------------

def test_device_time_accountant_counters_and_snapshot():
    reg = MetricsRegistry()
    clock = FakeClock()
    acct = DeviceTimeAccountant(reg, clock=clock)
    acct.note("serve", "ann", 3, 0.004)
    acct.note("serve", "ann", 3, 0.001)
    acct.note("measure", None, None, 0.002)
    counters = reg.counters_snapshot()
    assert counters["device_time_us"] == 7000
    assert counters["device_time_us_serve_ann"] == 5000
    snap = acct.snapshot()
    assert snap["busy_s"] == pytest.approx(0.007)
    # busiest-first, with time shares summing to ~1
    assert snap["by_route"][0]["route_class"] == "serve"
    assert sum(r["share"] for r in snap["by_route"]) \
        == pytest.approx(1.0)
    clock.advance(0.07)
    assert 0.0 < reg.gauge_value("device_busy_fraction") <= 1.0


def test_device_time_accountant_never_raises_on_junk():
    acct = DeviceTimeAccountant(None)
    acct.note("serve", object(), "gen?", float("nan"))
    acct.note("serve", "ok", 1, -5.0)
    assert acct.snapshot()["busy_s"] >= 0.0


def test_process_accountant_hook_roundtrip():
    prev = process_accountant()
    acct = DeviceTimeAccountant(None)
    try:
        install_process_accountant(acct)
        assert process_accountant() is acct
    finally:
        install_process_accountant(prev)
    assert process_accountant() is prev
