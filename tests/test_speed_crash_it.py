"""Speed-layer kill→restart chaos IT (ISSUE 17 acceptance): a REAL
``python -m oryx_tpu speed --shard 0/1`` process over a durable
``file://`` broker, killed by a conf-armed ``speed-crash-mid-batch``
crash — the exact window where every UP publish of the micro-batch is
durable but the checkpoint commit is lost — then restarted.

The restarted process must resolve the staged batch against the
destination log (every staged record found durable → dedup, zero
republishes), fold any remaining input exactly once, and leave an
update topic and folded factors BYTE-IDENTICAL to an uncrashed control
run over the same model, input, and batch boundaries: zero lost
records, zero double-folds.

Tier-1 coverage of this seam lives in the deterministic simulation
(tests/test_sim_sweep.py, scenario ``speed-shard-crash``: 200 seeded
interleavings per CI run) and the in-process unit proof
(tests/test_speed_shard.py).  This module is the retained real-process
smoke: one wall-clock interleaving through actual OS process death.
"""

from __future__ import annotations

import os
import shutil

import numpy as np
import pytest

from oryx_tpu.app.als.speed import ALSSpeedModelManager
from oryx_tpu.bench.gateway import _await, _free_port, _get_json, _spawn
from oryx_tpu.common.config import from_dict, keys_to_hocon
from oryx_tpu.kafka.api import KEY_UP
from oryx_tpu.kafka.inproc import resolve_broker
from oryx_tpu.lambda_rt.batch import BatchLayer
from oryx_tpu.lambda_rt.speed import SpeedLayer
from oryx_tpu.lambda_rt.speed_checkpoint import (H_SPEED_BATCH,
                                                 H_SPEED_SEQ,
                                                 H_SPEED_SHARD,
                                                 SpeedCheckpoint)

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

_GROUP = "OryxGroup-SpeedLayer-spit-0x1"
_NEW_LINES = ["u0,i1,3.0,1800000000000",
              "newuser,i2,1.0,1800000000001",
              "u3,i5,2.0,1800000000002",
              "u5,i7,1.5,1800000000003"]


def _overlay(broker_dir: str, tmp_path, **extra) -> dict:
    kv = {
        "oryx.id": "spit",
        "oryx.input-topic.broker": f"file://{broker_dir}",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "ItInput",
        "oryx.update-topic.broker": f"file://{broker_dir}",
        "oryx.update-topic.message.topic": "ItUpdate",
        "oryx.batch.update-class": "oryx_tpu.app.als.update.ALSUpdate",
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.als.speed.ALSSpeedModelManager",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.als.iterations": 3,
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": 3,
        "oryx.ml.eval.test-fraction": 0.0,
        "oryx.resilience.retry.max-attempts": 2,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
        "oryx.resilience.supervisor.enabled": False,
    }
    kv.update(extra)
    return kv


def _write_conf(path: str, kv: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(keys_to_hocon(sorted(kv.items())))


def _produce_history(broker) -> int:
    rng = np.random.default_rng(5)
    t = 1_700_000_000_000
    n = 0
    for u in range(20):
        for i in range(12):
            if rng.random() < 0.4:
                broker.send("ItInput", None,
                            f"u{u},i{i},{rng.exponential(1):.2f},{t}")
                t += 1000
                n += 1
    return n


def _up_records(broker):
    end = broker.latest_offset("ItUpdate")
    return [km for km in broker.read_range("ItUpdate", 0, end)
            if km.key == KEY_UP]


def _replay_manager(cfg, broker) -> ALSSpeedModelManager:
    mgr = ALSSpeedModelManager(cfg)
    mgr.consume(broker.consume("ItUpdate", from_beginning=True,
                               max_idle_sec=0.3))
    return mgr


def test_kill_restart_mid_micro_batch_zero_lost_zero_double(tmp_path):
    work = str(tmp_path)
    crash_dir = os.path.join(work, "broker-crash")
    ctl_dir = os.path.join(work, "broker-ctl")
    ckpt_dir = os.path.join(work, "speed-ckpt")
    os.makedirs(crash_dir)
    os.makedirs(ctl_dir)

    # one trained model, durable on the file broker: the real batch
    # layer's MODEL publish plus its input history
    batch_cfg = from_dict(_overlay(crash_dir, tmp_path))
    broker = resolve_broker(f"file://{crash_dir}")
    _produce_history(broker)
    BatchLayer(batch_cfg).run_one_generation()
    history_end = broker.latest_offset("ItInput")
    up_history = len(_up_records(broker))

    # control universe: the topic logs copied byte-wise (model
    # artifacts are shared on disk via the MODEL message), its own
    # checkpoint dir, no crash
    for fn in os.listdir(crash_dir):
        if fn.endswith(".topic.jsonl") or fn.endswith(".meta.json"):
            shutil.copy(os.path.join(crash_dir, fn),
                        os.path.join(ctl_dir, fn))
    ctl_broker = resolve_broker(f"file://{ctl_dir}")
    assert ctl_broker.latest_offset("ItInput") == history_end

    # both universes start their fold-in fence at the history head —
    # the worker tails new input, exactly like a deployed speed tier
    for b in (broker, ctl_broker):
        b.set_offsets(_GROUP, "ItInput", [history_end])
        b.flush()  # the child reads the preset group offsets from disk

    # -- the victim: a real speed worker, crash conf-armed ------------------
    obs_port = _free_port()
    conf1 = os.path.join(work, "speed-crash.conf")
    _write_conf(conf1, _overlay(crash_dir, tmp_path, **{
        "oryx.speed.checkpoint-dir": ckpt_dir,
        "oryx.speed.streaming.generation-interval-sec": 1,
        "oryx.obs.metrics-port": obs_port,
        # the kill, in THIS process only: after the batch's UP
        # publishes are durable, before the checkpoint commit
        "oryx.resilience.faults.speed-crash-mid-batch.mode": "crash",
        "oryx.resilience.faults.speed-crash-mid-batch.times": 1,
    }))
    log_path = os.path.join(work, "speed-it.log")
    proc = _spawn(["speed", "--shard", "0/1"], conf1, None, log_path)
    try:
        # fold-in needs the replayed model first: gate new input on the
        # child's own freshness gauges (records folded against a
        # half-replayed model would be silently skipped, not lost —
        # but then the control comparison would not be like-for-like)
        _await(lambda: (lambda g: g.get("update_lag_records") == 0
                        and g.get("model_generation_age_sec")
                        is not None)(
                            _get_json(obs_port, "/metrics")
                            .get("freshness", {})),
               "speed worker model replay", timeout=120.0)
        for line in _NEW_LINES:
            broker.send("ItInput", None, line)
        # the armed crash kills the batch thread mid-protocol and the
        # process drains out — OS process death at the exact seam
        proc.wait(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=15)

    # the dangerous intermediate state, read from the durable fence:
    # intent staged, UP records durable, input fence NOT advanced
    staged = SpeedCheckpoint(os.path.join(ckpt_dir, "shard-0-of-1"))
    assert staged.pending is not None, "crash fired outside the window"
    n_staged = len(staged.pending["updates"])
    assert n_staged > 0
    batch_a_end = staged.pending["ends"][0]
    assert history_end < batch_a_end <= history_end + len(_NEW_LINES)
    assert len(_up_records(broker)) == up_history + n_staged

    # -- control run: same model, same input, same batch boundaries ---------
    ctl_cfg = from_dict(_overlay(ctl_dir, tmp_path, **{
        "oryx.speed.shard": "0/1",
        "oryx.speed.checkpoint-dir": os.path.join(work, "ctl-ckpt")}))
    ctl = SpeedLayer(ctl_cfg)
    for line in _NEW_LINES[:batch_a_end - history_end]:
        ctl_broker.send("ItInput", None, line)
    ctl.model_manager.consume(ctl_broker.consume(
        "ItUpdate", from_beginning=True, max_idle_sec=0.3))
    ctl.run_one_micro_batch()
    remainder = _NEW_LINES[batch_a_end - history_end:]
    if remainder:
        for line in remainder:
            ctl_broker.send("ItInput", None, line)
        ctl.run_one_micro_batch()

    # -- the restart: fresh process, same checkpoint, no fault --------------
    obs_port2 = _free_port()
    conf2 = os.path.join(work, "speed-restart.conf")
    _write_conf(conf2, _overlay(crash_dir, tmp_path, **{
        "oryx.speed.checkpoint-dir": ckpt_dir,
        "oryx.speed.streaming.generation-interval-sec": 2,
        "oryx.obs.metrics-port": obs_port2,
    }))
    proc2 = _spawn(["speed", "--shard", "0/1"], conf2, None, log_path)
    try:
        # recovery resolves the stage before anything else: every
        # staged record found durable in the destination log — all
        # dedup, zero republishes — then the remaining input folds
        def _recovered() -> bool:
            m = _get_json(obs_port2, "/metrics")
            return (m["counters"].get("speed_shard_dedup_skips")
                    == n_staged
                    and m.get("freshness", {})
                    .get("input_lag_records") == 0)
        _await(_recovered, "crash recovery + drain", timeout=180.0)
    finally:
        proc2.terminate()
        try:
            proc2.wait(timeout=15)
        except Exception:  # noqa: BLE001 — teardown best effort
            proc2.kill()
            proc2.wait(timeout=15)

    # zero double-folds: the committed fence covers all input, every
    # stamped (shard, batch, seq) identity is durable exactly once
    after = SpeedCheckpoint(os.path.join(ckpt_dir, "shard-0-of-1"))
    assert after.pending is None
    assert after.input == {0: broker.latest_offset("ItInput")}
    ups = _up_records(broker)
    stamped = [(km.headers[H_SPEED_SHARD], km.headers[H_SPEED_BATCH],
                km.headers[H_SPEED_SEQ]) for km in ups
               if km.headers and H_SPEED_SHARD in km.headers]
    assert len(stamped) == len(set(stamped)), \
        "a staged record was republished over its durable copy"

    # zero lost, byte-identically: the update topic equals the
    # uncrashed control's, record for record
    ctl_ups = _up_records(ctl_broker)
    assert [km.message for km in ups] == [km.message for km in ctl_ups]

    # and the folded factors converge byte-identically on full replay
    got = _replay_manager(from_dict(_overlay(crash_dir, tmp_path)),
                          broker).model
    ref = _replay_manager(from_dict(_overlay(ctl_dir, tmp_path)),
                          ctl_broker).model
    assert sorted(got.X.all_ids()) == sorted(ref.X.all_ids())
    assert sorted(got.Y.all_ids()) == sorted(ref.Y.all_ids())
    for uid in ref.X.all_ids():
        assert np.array_equal(got.get_user_vector(uid),
                              ref.get_user_vector(uid))
    for iid in ref.Y.all_ids():
        assert np.array_equal(got.get_item_vector(iid),
                              ref.get_item_vector(iid))
