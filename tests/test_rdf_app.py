"""RDF app tests: batch update, speed manager, serving manager, and the
classreg REST endpoints (reference: RDFUpdateIT, RDFSpeedIT,
RDFServingModelManagerIT, PredictTest, ClassificationDistributionTest,
FeatureImportanceTest, TrainTest)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from oryx_tpu.app.rdf import pmml as rdf_pmml
from oryx_tpu.app.rdf.serving import RDFServingModelManager
from oryx_tpu.app.rdf.speed import RDFSpeedModelManager
from oryx_tpu.app.rdf.update import RDFUpdate
from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_MODEL, KEY_UP, KeyMessage


def _schema_entries():
    return {
        "oryx.input-schema.feature-names": ["a", "color", "label"],
        "oryx.input-schema.categorical-features": ["color", "label"],
        "oryx.input-schema.target-feature": "label",
    }


def _batch_config():
    return from_dict({
        "oryx.ml.eval.test-fraction": 0.2,
        "oryx.ml.eval.candidates": 1,
        "oryx.ml.eval.parallelism": 1,
        "oryx.ml.eval.threshold": None,
        "oryx.update-topic.message.max-size": 1 << 24,
        "oryx.rdf.num-trees": 3,
        "oryx.rdf.hyperparams.max-split-candidates": 16,
        "oryx.rdf.hyperparams.max-depth": 4,
        "oryx.rdf.hyperparams.impurity": "gini",
        **_schema_entries(),
    })


def _lines(n=400, seed=11):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        a = rng.uniform(-1, 1)
        color = rng.choice(["red", "green", "blue"])
        label = "yes" if (a >= 0.1 or color == "blue") else "no"
        lines.append(f"{a:.4f},{color},{label}")
    return lines


def test_rdf_update_builds_and_evaluates(tmp_path):
    data = [KeyMessage(None, ln) for ln in _lines()]
    update = RDFUpdate(_batch_config())
    doc = update.build_model(data, [16, 4, "gini"], str(tmp_path))
    assert doc is not None
    forest, encodings = rdf_pmml.read_forest(doc)
    assert len(forest.trees) == 3
    accuracy = update.evaluate(doc, str(tmp_path), data[:80], data[80:])
    assert accuracy > 0.9
    # importances present in PMML mining schema
    assert "importance" in pmml_io.to_string(doc)


def test_rdf_update_regression(tmp_path):
    cfg = from_dict({
        "oryx.ml.eval.test-fraction": 0.2,
        "oryx.ml.eval.candidates": 1,
        "oryx.ml.eval.parallelism": 1,
        "oryx.ml.eval.threshold": None,
        "oryx.update-topic.message.max-size": 1 << 24,
        "oryx.rdf.num-trees": 3,
        "oryx.rdf.hyperparams.max-split-candidates": 64,
        "oryx.rdf.hyperparams.max-depth": 3,
        "oryx.rdf.hyperparams.impurity": "variance",
        "oryx.input-schema.feature-names": ["a", "y"],
        "oryx.input-schema.numeric-features": ["a", "y"],
        "oryx.input-schema.target-feature": "y",
    })
    rng = np.random.default_rng(2)
    data = []
    for _ in range(300):
        a = rng.uniform(0, 4)
        y = 1.0 if a < 2 else 5.0
        data.append(KeyMessage(None, f"{a:.4f},{y}"))
    update = RDFUpdate(cfg)
    doc = update.build_model(data, [64, 3, "variance"], "unused")
    ev = update.evaluate(doc, "unused", data[:50], data[50:])
    assert ev > -0.5  # -RMSE


def _model_message():
    data = [KeyMessage(None, ln) for ln in _lines()]
    update = RDFUpdate(_batch_config())
    doc = update.build_model(data, [16, 4, "gini"], "unused")
    return pmml_io.to_string(doc)


@pytest.fixture(scope="module")
def model_message():
    return _model_message()


def test_speed_manager_routes_and_emits(model_message):
    cfg = from_dict(_schema_entries())
    mgr = RDFSpeedModelManager(cfg)
    mgr.consume_key_message(KEY_MODEL, model_message)
    assert mgr.model is not None
    data = [KeyMessage(None, "0.9,red,yes"), KeyMessage(None, "0.8,red,yes"),
            KeyMessage(None, "-0.9,green,no"),
            KeyMessage(None, "0.5,blue,")]  # no target -> skipped
    ups = list(mgr.build_updates(data))
    assert ups
    parsed = [json.loads(u) for u in ups]
    # one update per (tree, terminal node) with 3 routed examples
    for p in parsed:
        assert isinstance(p[0], int) and isinstance(p[1], str)
        assert p[1].startswith("r")
        assert isinstance(p[2], dict)
    total = sum(sum(p[2].values()) for p in parsed)
    assert total == 3 * 3  # 3 examples x 3 trees
    mgr.consume_key_message(KEY_UP, ups[0])  # ignored


def test_serving_manager_predict_and_up(model_message):
    cfg = from_dict({**_schema_entries(),
                     "oryx.serving.api.read-only": False})
    mgr = RDFServingModelManager(cfg)
    mgr.consume_key_message(KEY_UP, '[0,"r",{"0":1}]')  # no model yet: skip
    assert mgr.get_model() is None
    mgr.consume_key_message(KEY_MODEL, model_message)
    model = mgr.get_model()
    assert model.predict(["0.9", "red", ""]) == "yes"
    assert model.predict(["-0.9", "green", ""]) == "no"
    bulk = model.predict_bulk([["0.9", "red", ""], ["-0.9", "green", ""]])
    assert bulk == ["yes", "no"]
    # distribution sums to 1
    pred = model.make_prediction(["0.9", "blue", ""])
    assert pred.category_probabilities.sum() == pytest.approx(1.0)
    # leaf update shifts the prediction stats of a terminal node
    leaf = model.forest.trees[0].find_terminal(
        model._example(["0.9", "red", ""]))
    enc_no = model.encodings.encode(2, "no")
    before = leaf.prediction.category_counts[enc_no]
    mgr.consume_key_message(
        KEY_UP, json.dumps([0, leaf.id, {str(enc_no): 50}]))
    assert leaf.prediction.category_counts[enc_no] == before + 50
    with pytest.raises(ValueError):
        model.predict(["0.9", "red"])  # wrong feature count


# -- REST endpoints over live HTTP -------------------------------------------

class MockRDFManager(RDFServingModelManager):
    pass


@pytest.fixture(scope="module")
def rdf_server(model_message):
    from oryx_tpu.lambda_rt.serving import ServingLayer
    from oryx_tpu.kafka.inproc import get_broker
    cfg = from_dict({
        "oryx.serving.model-manager-class":
            "tests.test_rdf_app.MockRDFManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.classreg",
        "oryx.input-topic.broker": "memory://rdf-test",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "RInput",
        "oryx.update-topic.broker": "memory://rdf-test",
        "oryx.update-topic.message.topic": "RUpdate",
        **_schema_entries(),
    })
    broker = get_broker("rdf-test")
    broker.send("RUpdate", KEY_MODEL, model_message)
    layer = ServingLayer(cfg, port=0)
    layer.start()
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{layer.port}/ready", timeout=2)
            break
        except Exception:
            time.sleep(0.1)
    yield layer, broker
    layer.close()


def _get(layer, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{layer.port}{path}", timeout=10)


def test_predict_endpoint(rdf_server):
    layer, _ = rdf_server
    assert json.loads(_get(layer, "/predict/0.9,red,").read()) == "yes"


def test_predict_post_bulk(rdf_server):
    layer, _ = rdf_server
    req = urllib.request.Request(
        f"http://127.0.0.1:{layer.port}/predict",
        data=b"0.9,red,\n-0.9,green,\n", method="POST")
    assert json.loads(urllib.request.urlopen(req, timeout=10).read()) == \
        ["yes", "no"]


def test_classification_distribution(rdf_server):
    layer, _ = rdf_server
    out = json.loads(_get(layer, "/classificationDistribution/0.9,red,")
                     .read())
    labels = {o["id"] for o in out}
    assert labels == {"yes", "no"}
    assert sum(o["value"] for o in out) == pytest.approx(1.0)


def test_feature_importance(rdf_server):
    layer, _ = rdf_server
    imps = json.loads(_get(layer, "/feature/importance").read())
    # predictor-indexed (reference: importances sized by numPredictors)
    assert len(imps) == 2
    assert sum(imps) == pytest.approx(1.0)
    one = json.loads(_get(layer, "/feature/importance/0").read())
    assert one == pytest.approx(imps[0])


def test_update_skips_unlabeled_and_unseen_values(tmp_path):
    data = [KeyMessage(None, ln) for ln in _lines(200)]
    data.append(KeyMessage(None, "0.5,red,"))        # unlabeled
    update = RDFUpdate(_batch_config())
    doc = update.build_model(data, [16, 4, "gini"], str(tmp_path))
    _, encodings = rdf_pmml.read_forest(doc)
    # '' must not become a phantom class
    assert "" not in encodings.get_value_encoding_map(2)
    # unseen categorical value in test data is treated as missing,
    # unseen target value is skipped -- neither crashes evaluate
    test = [KeyMessage(None, "0.9,purple,yes"),
            KeyMessage(None, "0.9,red,maybe")] + data[:40]
    accuracy = update.evaluate(doc, str(tmp_path), test, data)
    assert 0.0 <= accuracy <= 1.0


def test_train_endpoint_works_without_model(model_message):
    """Training data must flow before the first model exists."""
    from oryx_tpu.lambda_rt.serving import ServingLayer
    from oryx_tpu.kafka.inproc import get_broker
    cfg = from_dict({
        "oryx.serving.model-manager-class":
            "tests.test_rdf_app.MockRDFManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.classreg",
        "oryx.input-topic.broker": "memory://rdf-nomodel",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "RInput",
        "oryx.update-topic.broker": "memory://rdf-nomodel",
        "oryx.update-topic.message.topic": "RUpdate",
        **_schema_entries(),
    })
    broker = get_broker("rdf-nomodel")
    layer = ServingLayer(cfg, port=0)
    layer.start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{layer.port}/train/0.5,red,yes",
                    data=b"", method="POST")
                urllib.request.urlopen(req, timeout=2)
                break
            except urllib.error.URLError:
                time.sleep(0.1)
        assert broker.latest_offset("RInput") >= 1
    finally:
        layer.close()


def test_train_endpoint_writes_input(rdf_server):
    layer, broker = rdf_server
    before = broker.latest_offset("RInput")
    req = urllib.request.Request(
        f"http://127.0.0.1:{layer.port}/train/0.5,red,yes", data=b"",
        method="POST")
    urllib.request.urlopen(req, timeout=10)
    assert broker.latest_offset("RInput") == before + 1
