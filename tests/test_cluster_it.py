"""Sharded serving cluster integration tests (ISSUE 4 acceptance):
a 2-replica cluster over the in-proc broker proves

1. router top-N ≡ single-node exact top-N (ids and order, values to
   float tolerance) across the public endpoint surface;
2. kill one replica → partial answer (``X-Oryx-Partial: shards=1/2``,
   HTTP 200, within deadline) → rejoin → exact again, all WITHOUT a
   router restart;
3. the chaos fault points: ``router-shard-timeout`` (a stalled shard
   degrades to a partial answer inside the request deadline) and
   ``replica-heartbeat-drop`` (a silent replica ages out of routing,
   returns when heartbeats resume);
4. hedged failover: with two replicas of the same shard, a dead-but-
   not-yet-aged-out replica's failure fails over inside one request.

Marker: chaos (in the tier-1 budget).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.cluster.router import RouterLayer
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.batch import BatchLayer
from oryx_tpu.lambda_rt.serving import ServingLayer
from oryx_tpu.resilience import faults
from oryx_tpu.resilience.policy import Deadline

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _config(tmp_path, broker_name, **extra):
    overlay = {
        "oryx.id": "cluster-it",
        "oryx.input-topic.broker": f"memory://{broker_name}",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "CIn",
        "oryx.update-topic.broker": f"memory://{broker_name}",
        "oryx.update-topic.message.topic": "CUp",
        "oryx.batch.update-class": "oryx_tpu.app.als.update.ALSUpdate",
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.als.iterations": 2,
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": 3,
        "oryx.ml.eval.test-fraction": 0.0,
        # fast cluster timings so membership transitions stay inside
        # the tier-1 budget
        "oryx.cluster.heartbeat-interval-ms": 60,
        "oryx.cluster.heartbeat-ttl-ms": 400,
        "oryx.cluster.hedge-after-ms": 50,
        "oryx.cluster.shard-timeout-ms": 5000,
        "oryx.resilience.retry.max-attempts": 2,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
        "oryx.resilience.breaker.reset-timeout-ms": 50,
    }
    overlay.update(extra)
    return from_dict(overlay)


def _produce_ratings(broker, topic, nu=20, ni=14, seed=9):
    rng = np.random.default_rng(seed)
    t = 1_700_000_000_000
    for u in range(nu):
        for i in range(ni):
            if rng.random() < 0.45:
                broker.send(topic, None,
                            f"u{u},i{i},{rng.exponential(1):.2f},{t}")
                t += 1000
    # one id that is BOTH a user and an item: X and Y are independent
    # stores single-node, so "dual" must resolve per-store everywhere
    for line in ("dual,i0,1.5", "dual,i3,0.7", "u0,dual,2.0",
                 "u3,dual,0.9", "dual,dual,1.0"):
        broker.send(topic, None, f"{line},{t}")
        t += 1000


def _get(port, path, headers=None, timeout=15):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), json.loads(r.read() or b"null")


def _await(predicate, what, timeout=25.0):
    deadline = Deadline.after(timeout)
    while not deadline.expired:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _router_ready(router):
    try:
        return _get(router.port, "/ready")[0] in (200, 204)
    except (urllib.error.HTTPError, urllib.error.URLError, OSError):
        return False


def _start_replica(cfg_fn, shard, of, replica_id=None, extra=None):
    overlay = {"oryx.cluster.enabled": True,
               "oryx.cluster.shard": f"{shard}/{of}"}
    overlay.update(extra or {})
    if replica_id:
        overlay["oryx.cluster.replica-id"] = replica_id
    layer = ServingLayer(cfg_fn(overlay), port=0)
    layer.start()
    return layer


def _ids(payload):
    return [d["id"] for d in payload]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """One shared 2-shard cluster + single-node reference + router."""
    tmp_path = tmp_path_factory.mktemp("cluster-it")
    broker = get_broker("cluster-it")
    _produce_ratings(broker, "CIn")

    def cfg_fn(extra=None):
        return _config(tmp_path, "cluster-it", **(extra or {}))

    BatchLayer(cfg_fn()).run_one_generation()
    replicas = [_start_replica(cfg_fn, s, 2) for s in range(2)]
    single = ServingLayer(cfg_fn(), port=0)
    single.start()
    router = RouterLayer(cfg_fn(), port=0)
    router.start()
    _await(lambda: _router_ready(router), "router readiness")
    _await(lambda: (m := single.model_manager.get_model()) is not None
           and m.get_fraction_loaded() >= 0.8, "single-node model")
    yield {"cfg_fn": cfg_fn, "replicas": replicas, "single": single,
           "router": router, "broker": broker}
    for layer in replicas + [single, router]:
        try:
            layer.close()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass


def test_router_top_n_equals_single_node_exact(cluster):
    single, router = cluster["single"], cluster["router"]
    model = single.model_manager.get_model()
    users = sorted(model.all_user_ids())
    assert users
    for uid in users:
        for hm in (3, 10):
            _, h1, r1 = _get(router.port, f"/recommend/{uid}?howMany={hm}")
            _, _, r2 = _get(single.port, f"/recommend/{uid}?howMany={hm}")
            assert h1.get("X-Oryx-Partial") is None
            assert _ids(r1) == _ids(r2), uid
            for a, b in zip(r1, r2):
                # scores are the same f32 dot up to kernel-shape
                # rounding: tolerance must be absolute near zero
                assert a["value"] == pytest.approx(b["value"], rel=1e-5,
                                                   abs=1e-6)


def test_router_wider_endpoint_surface_matches_single_node(cluster):
    single, router = cluster["single"], cluster["router"]
    model = single.model_manager.get_model()
    uid = sorted(model.all_user_ids())[0]
    i1, i2 = sorted(model.all_item_ids())[:2]
    # identical payloads end-to-end
    for path in (f"/similarity/{i1}/{i2}",
                 f"/similarityToItem/{i1}/{i2}",
                 f"/estimate/{uid}/{i1}/{i2}",
                 f"/because/{uid}/{i1}",
                 f"/mostSurprising/{uid}",
                 "/mostPopularItems", "/mostActiveUsers",
                 "/allUserIDs", f"/knownItems/{uid}",
                 "/popularRepresentativeItems"):
        _, _, r1 = _get(router.port, path)
        _, _, r2 = _get(single.port, path)
        assert r1 == r2, path
    # recommendToMany: exact ids/order; scores may differ in the last
    # ulp (the fetch-window shape rounds the same dot differently)
    _, _, r1 = _get(router.port, f"/recommendToMany/{uid}")
    _, _, r2 = _get(single.port, f"/recommendToMany/{uid}")
    assert _ids(r1) == _ids(r2)
    for a, b in zip(r1, r2):
        assert a["value"] == pytest.approx(b["value"], rel=1e-5, abs=1e-6)
    # catalog enumeration: same set (order is shard-interleaved)
    _, _, r1 = _get(router.port, "/allItemIDs")
    _, _, r2 = _get(single.port, "/allItemIDs")
    assert sorted(r1) == sorted(r2)
    # fold-in endpoints: the router solves against the SUMMED shard
    # Gramians — same ids, values to solver tolerance
    for path in (f"/recommendToAnonymous/{i1}=2.0/{i2}",
                 f"/recommendWithContext/{uid}/{i1}=1.5"):
        _, _, r1 = _get(router.port, path)
        _, _, r2 = _get(single.port, path)
        assert _ids(r1) == _ids(r2), path
        for a, b in zip(r1, r2):
            assert a["value"] == pytest.approx(b["value"], rel=1e-4)
    _, _, v1 = _get(router.port, f"/estimateForAnonymous/{i1}/{i2}")
    _, _, v2 = _get(single.port, f"/estimateForAnonymous/{i1}/{i2}")
    assert v1 == pytest.approx(v2, rel=1e-4)
    # 404 parity
    for path in ("/recommend/nosuchuser", f"/estimate/nosuchuser/{i1}",
                 f"/similarity/nosuchitem/{i1}"):
        with pytest.raises(urllib.error.HTTPError) as e1:
            _get(router.port, path)
        with pytest.raises(urllib.error.HTTPError) as e2:
            _get(single.port, path)
        assert e1.value.code == e2.value.code == 404, path


def test_estimate_with_user_item_id_collision(cluster):
    """'dual' names both a user and an item: the router must pair the
    USER vector with the ITEM vector, not whichever one happened to
    land last in a shared id map (xu·xu instead of xu·y)."""
    single, router = cluster["single"], cluster["router"]
    for path in ("/estimate/dual/dual", "/estimate/dual/dual/i0",
                 "/recommend/dual?howMany=5"):
        _, _, r1 = _get(router.port, path)
        _, _, r2 = _get(single.port, path)
        if isinstance(r1, list) and r1 and isinstance(r1[0], dict):
            assert _ids(r1) == _ids(r2), path
            for a, b in zip(r1, r2):
                assert a["value"] == pytest.approx(b["value"], rel=1e-5,
                                                   abs=1e-6)
        else:
            assert r1 == pytest.approx(r2, rel=1e-5, abs=1e-6), path


def _publish_synthetic_model(broker, topic, n_users=4, n_items=10,
                             features=3, seed=3):
    """MODEL + UP straight onto the update topic: replicas load through
    their normal replay path, no batch run needed."""
    from oryx_tpu.common import pmml as pmml_io
    from oryx_tpu.kafka.api import KEY_MODEL, KEY_UP

    # "sp ace" exercises percent-encoded ids across the internal hop
    users = [f"au{j}" for j in range(n_users)] + ["sp ace"]
    items = [f"ai{j}" for j in range(n_items)]
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", features)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", users)
    pmml_io.add_extension_content(doc, "YIDs", items)
    broker.send(topic, KEY_MODEL, pmml_io.to_string(doc))
    rng = np.random.default_rng(seed)
    for iid in items:
        broker.send(topic, KEY_UP, json.dumps(
            ["Y", iid, [float(x) for x in rng.standard_normal(features)]]))
    for uid in users:
        broker.send(topic, KEY_UP, json.dumps(
            ["X", uid, [float(x) for x in rng.standard_normal(features)],
             []]))


def test_digest_auth_secures_public_and_scatter_hops(tmp_path):
    """DIGEST credentials in one shared conf: the router challenges the
    public client AND answers the replicas' challenge on the internal
    scatter hop with the same credentials — a 200 with rows through the
    router proves both hops."""
    broker = get_broker("cluster-auth")
    _publish_synthetic_model(broker, "CUp")

    auth = {"oryx.serving.api.user-name": "oryx-admin",
            "oryx.serving.api.password": "s3cret"}

    def cfg_fn(extra=None):
        return _config(tmp_path, "cluster-auth", **{**auth, **(extra or {})})

    replica = _start_replica(cfg_fn, 0, 1)
    router = RouterLayer(cfg_fn(), port=0)
    router.start()
    try:
        pm = urllib.request.HTTPPasswordMgrWithDefaultRealm()
        for port in (router.port, replica.port):
            pm.add_password(None, f"http://127.0.0.1:{port}/",
                            "oryx-admin", "s3cret")
        opener = urllib.request.build_opener(
            urllib.request.HTTPDigestAuthHandler(pm))

        def dget(port, path):
            with opener.open(f"http://127.0.0.1:{port}{path}",
                             timeout=15) as r:
                return r.status, dict(r.headers), json.loads(
                    r.read() or b"null")

        _await(lambda: dget(replica.port, "/shard/meta")[2]["ready"],
               "auth replica model load")
        _await(lambda: _safe(lambda: dget(
            router.port, "/ready")[0] in (200, 204)),
            "auth router readiness")
        # unauthenticated: challenged at the public door
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(router.port, "/recommend/au0")
        assert e.value.code == 401
        # authenticated: full scatter-gather through the DIGEST-
        # enforcing replica
        status, headers, rows = dget(router.port,
                                     "/recommend/au0?howMany=5")
        assert status == 200 and headers.get("X-Oryx-Partial") is None
        assert len(rows) == 5
        # byte-identical to the replica's own (authenticated) answer
        _, _, local = dget(replica.port,
                           "/shard/recommend/au0?howMany=5")
        assert _ids(rows) == [r[0] for r in local["rows"][:5]]
        # percent-encoded id through the proxied user-store hop: the
        # router must RE-quote the decoded path on the internal wire
        status, _, known = dget(router.port, "/knownItems/sp%20ace")
        assert status == 200 and known == []
    finally:
        for layer in (router, replica):
            try:
                layer.close()
            except Exception:  # noqa: BLE001 — teardown best effort
                pass


def _safe(fn):
    try:
        return fn()
    except (urllib.error.HTTPError, urllib.error.URLError, OSError):
        return False


def test_stale_keepalive_socket_retries_on_fresh_connection(tmp_path):
    """A pooled keep-alive socket whose replica restarted (supervised
    restart is a designed event) must retry once on a fresh connection
    — a dead socket is a property of the pool, not a shard failure."""
    import http.server
    import threading

    from oryx_tpu.cluster.membership import Heartbeat, MembershipRegistry
    from oryx_tpu.cluster.scatter import ScatterGather

    class H(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            body = b'{"rows": []}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    def start(port=0):
        srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    srv = start()
    port = srv.server_address[1]
    reg = MembershipRegistry(ttl_sec=60.0)
    reg.note(Heartbeat(replica="r", shard=0, of=1,
                       url=f"http://127.0.0.1:{port}", generation=1,
                       ready=True))
    sg = ScatterGather(reg, _config(tmp_path, "stale-conn"))
    try:
        assert sg.query_shard(0, "GET", "/x").ok  # pools the socket
        srv.shutdown()
        srv.server_close()
        srv2 = start(port)  # replica back on the same URL
        assert sg.query_shard(0, "GET", "/x").ok  # stale → fresh retry
        assert sg.shard_failures == 0
        srv2.shutdown()
        srv2.server_close()
    finally:
        sg.close()


def test_tls_replicas_behind_plain_router(tmp_path):
    """Replicas serving HTTPS (self-signed, the cluster-internal trust
    model): their heartbeats advertise https:// URLs and the router's
    scatter transport must speak TLS to them."""
    from tests.test_serving import _self_signed_pem  # skips w/o package
    pem = _self_signed_pem(tmp_path)
    broker = get_broker("cluster-tls")
    _publish_synthetic_model(broker, "CUp")

    def cfg_fn(extra=None):
        return _config(tmp_path, "cluster-tls", **(extra or {}))

    replica = _start_replica(
        cfg_fn, 0, 1, extra={"oryx.serving.api.keystore-file": pem})
    assert replica.scheme == "https"
    router = RouterLayer(cfg_fn(), port=0)  # plain-HTTP public door
    router.start()
    try:
        import ssl
        ctx = ssl._create_unverified_context()

        def sget(path):
            req = urllib.request.Request(
                f"https://127.0.0.1:{replica.port}{path}")
            with urllib.request.urlopen(req, timeout=15,
                                        context=ctx) as r:
                return json.loads(r.read() or b"null")

        _await(lambda: sget("/shard/meta")["ready"],
               "tls replica model load")
        _await(lambda: _router_ready(router), "tls router readiness")
        status, headers, rows = _get(router.port,
                                     "/recommend/au0?howMany=5")
        assert status == 200 and headers.get("X-Oryx-Partial") is None
        assert len(rows) == 5
        local = sget("/shard/recommend/au0?howMany=5")
        assert _ids(rows) == [r[0] for r in local["rows"][:5]]
    finally:
        for layer in (router, replica):
            try:
                layer.close()
            except Exception:  # noqa: BLE001 — teardown best effort
                pass


def test_kill_replica_partial_then_rejoin_exact(cluster):
    """The headline acceptance scenario, all through ONE router with no
    restart: kill → 200 + X-Oryx-Partial within deadline → rejoin →
    exact."""
    single, router = cluster["single"], cluster["router"]
    cfg_fn = cluster["cfg_fn"]
    from oryx_tpu.cluster.sharding import shard_of
    model = single.model_manager.get_model()
    uid = sorted(model.all_user_ids())[0]
    _, _, full = _get(single.port, f"/recommend/{uid}?howMany=6")
    full_ids = _ids(full)
    victim = cluster["replicas"][1]
    victim.close()
    try:
        # after the TTL the shard is uncovered: partial answers, never
        # errors or hangs
        def partial_seen():
            _, h, _ = _get(router.port, f"/recommend/{uid}?howMany=6",
                           headers={"X-Deadline-Ms": "10000"})
            return h.get("X-Oryx-Partial") == "shards=1/2"
        _await(partial_seen, "partial answer after replica kill")

        t0 = time.monotonic()
        status, headers, partial = _get(
            router.port, f"/recommend/{uid}?howMany=6",
            headers={"X-Deadline-Ms": "10000"})
        elapsed = time.monotonic() - t0
        assert status == 200
        assert headers.get("X-Oryx-Partial") == "shards=1/2"
        assert elapsed < 10.0  # answered within the propagated deadline
        # the partial answer is EXACT over the surviving catalog: the
        # single-node global ranking restricted to shard-0 items
        _, _, full_deep = _get(single.port,
                               f"/recommend/{uid}?howMany=100")
        survivors = [i for i in _ids(full_deep) if shard_of(i, 2) == 0]
        assert _ids(partial) == survivors[:len(_ids(partial))]
        # readiness reflects the uncovered shard
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(router.port, "/ready")
        assert exc.value.code == 503
        # counted on /metrics
        _, _, m = _get(router.port, "/metrics")
        assert m["counters"]["partial_answers"] >= 1
        assert m["cluster"]["covered_shards"] == [0]
    finally:
        # rejoin: a fresh replica of the killed shard, same topic
        # replay (in finally, so a failing assertion above cannot
        # leave the shared cluster half-dead for later tests)
        cluster["replicas"][1] = _start_replica(cfg_fn, 1, 2)
    _await(lambda: _router_ready(router), "rejoin readiness")

    def exact_again():
        _, h, r1 = _get(router.port, f"/recommend/{uid}?howMany=6")
        return h.get("X-Oryx-Partial") is None and _ids(r1) == full_ids
    _await(exact_again, "exact answers after rejoin")


def test_router_shard_timeout_fault_degrades_to_partial(cluster):
    """Chaos point ``router-shard-timeout``: one shard query stalls
    past the request deadline — the router answers from the survivors
    within the deadline instead of hanging."""
    single, router = cluster["single"], cluster["router"]
    model = single.model_manager.get_model()
    uid = sorted(model.all_user_ids())[0]
    _, _, before = _get(router.port, "/metrics")
    faults.inject("router-shard-timeout", mode="delay", times=1,
                  delay_sec=2.0)
    t0 = time.monotonic()
    status, headers, _ = _get(router.port, f"/recommend/{uid}?howMany=6",
                              headers={"X-Deadline-Ms": "900"})
    elapsed = time.monotonic() - t0
    assert status == 200
    assert headers.get("X-Oryx-Partial") == "shards=1/2"
    assert elapsed < 2.0  # did not wait out the stall
    assert faults.fired("router-shard-timeout") == 1
    _, _, after = _get(router.port, "/metrics")
    assert after["counters"]["partial_answers"] > \
        before["counters"].get("partial_answers", 0)


def test_heartbeat_drop_ages_replica_out_and_back(cluster):
    """Chaos point ``replica-heartbeat-drop``: a replica that stays up
    but stops heartbeating (partitioned from the broker) must age out
    of routing — partial answers — and return once heartbeats resume,
    with no restarts anywhere."""
    single, router = cluster["single"], cluster["router"]
    model = single.model_manager.get_model()
    uid = sorted(model.all_user_ids())[0]
    faults.inject("replica-heartbeat-drop", mode="drop", times=None)
    # BOTH replicas go silent -> no live replica -> 503 (not a hang)
    def all_aged_out():
        try:
            _get(router.port, f"/recommend/{uid}?howMany=4",
                 headers={"X-Deadline-Ms": "3000"})
            return False
        except urllib.error.HTTPError as e:
            return e.code == 503
    _await(all_aged_out, "silent replicas aged out")
    assert faults.fired("replica-heartbeat-drop") > 0
    faults.clear("replica-heartbeat-drop")

    def recovered():
        try:
            _, h, _ = _get(router.port, f"/recommend/{uid}?howMany=4")
            return h.get("X-Oryx-Partial") is None
        except urllib.error.HTTPError:
            return False
    _await(recovered, "heartbeats resumed")


def test_hedged_failover_within_replica_ttl(cluster):
    """Two replicas of shard 0: kill one WITHOUT waiting for its TTL —
    the very next request fails over (connection refused -> hedge to
    the sibling) and still answers exactly."""
    single, router = cluster["single"], cluster["router"]
    cfg_fn = cluster["cfg_fn"]
    model = single.model_manager.get_model()
    uid = sorted(model.all_user_ids())[0]
    extra = _start_replica(cfg_fn, 0, 2, replica_id="shard0-sibling")
    try:
        _await(lambda: len(_get(router.port, "/metrics")[2]["cluster"]
                           ["membership"]["replicas"]) >= 3,
               "sibling registered")
        extra.close()  # dead but still inside its TTL window
        _, _, expected = _get(single.port, f"/recommend/{uid}?howMany=5")
        # several requests in a row: whichever candidate order the
        # rotation picks, failover must hide the dead sibling
        for _ in range(6):
            status, h, got = _get(router.port,
                                  f"/recommend/{uid}?howMany=5",
                                  headers={"X-Deadline-Ms": "8000"})
            assert status == 200
            assert h.get("X-Oryx-Partial") is None
            assert _ids(got) == _ids(expected)
    finally:
        try:
            extra.close()
        except Exception:  # noqa: BLE001
            pass


def test_write_path_flows_through_router_to_input_topic(cluster):
    router, broker = cluster["router"], cluster["broker"]
    end_before = broker.latest_offset("CIn")
    req = urllib.request.Request(
        f"http://127.0.0.1:{router.port}/pref/u0/i1", data=b"2.5",
        method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status in (200, 204)
    req = urllib.request.Request(
        f"http://127.0.0.1:{router.port}/ingest",
        data=b"u1,i2,1.0\nu2,i3,0.5\n", method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 200
    assert broker.latest_offset("CIn") == end_before + 3


def test_router_metrics_surface(cluster):
    router = cluster["router"]
    _, _, m = _get(router.port, "/metrics")
    assert m["cluster"]["membership"]["shards"] == 2
    assert any(r["shard"] == 0 and r["live"] for r in
               m["cluster"]["membership"]["replicas"].values())
    assert "GET /recommend/{userID}" in m["routes"]
    assert "scatter" in m["cluster"]
    # per-replica breakers are registered under the resilience surface
    assert any(k.startswith("router-replica[") for k in m["resilience"])
