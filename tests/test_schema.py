"""InputSchema + CategoricalValueEncodings tests (reference:
InputSchemaTest.java:28, CategoricalValueEncodingsTest)."""

import pytest

from oryx_tpu.app.schema import CategoricalValueEncodings, InputSchema
from oryx_tpu.common.config import from_dict


def test_generated_feature_names():
    s = InputSchema(from_dict({"oryx.input-schema.num-features": 3,
                               "oryx.input-schema.numeric-features":
                                   ["0", "1", "2"]}))
    assert s.feature_names == ["0", "1", "2"]
    assert s.num_predictors == 3
    assert not s.has_target()


def test_id_ignored_target_and_predictor_map():
    s = InputSchema(from_dict({
        "oryx.input-schema.feature-names": ["id", "a", "b", "c", "junk"],
        "oryx.input-schema.id-features": ["id"],
        "oryx.input-schema.ignored-features": ["junk"],
        "oryx.input-schema.categorical-features": ["b"],
        "oryx.input-schema.target-feature": "c"}))
    assert s.is_id("id") and s.is_id(0)
    assert not s.is_active(0) and s.is_active("a")
    assert s.is_numeric("a") and s.is_numeric("c")
    assert s.is_categorical("b") and s.is_categorical(2)
    assert s.is_target(3) and s.has_target()
    assert s.target_feature_index == 3
    # predictors are a and b only (c is target, id/junk inactive)
    assert s.num_predictors == 2
    assert s.feature_to_predictor_index(1) == 0
    assert s.feature_to_predictor_index(2) == 1
    assert s.predictor_to_feature_index(1) == 2


def test_numeric_features_variant():
    s = InputSchema(from_dict({
        "oryx.input-schema.feature-names": ["a", "b"],
        "oryx.input-schema.numeric-features": ["a"]}))
    assert s.is_categorical("b")


def test_schema_validation_errors():
    with pytest.raises(ValueError):
        InputSchema(from_dict({"oryx.input-schema.num-features": 0}))
    with pytest.raises(ValueError):
        InputSchema(from_dict({
            "oryx.input-schema.feature-names": ["a", "a"],
            "oryx.input-schema.numeric-features": ["a"]}))
    with pytest.raises(ValueError):
        InputSchema(from_dict({
            "oryx.input-schema.feature-names": ["a"],
            "oryx.input-schema.id-features": ["nope"],
            "oryx.input-schema.numeric-features": ["a"]}))
    with pytest.raises(ValueError):
        InputSchema(from_dict({
            "oryx.input-schema.feature-names": ["a", "b"],
            "oryx.input-schema.numeric-features": ["a", "b"],
            "oryx.input-schema.target-feature": "zz"}))


def test_categorical_value_encodings():
    enc = CategoricalValueEncodings({0: ["x", "y", "x", "z"], 2: ["p"]})
    assert enc.get_value_count(0) == 3
    assert enc.encode(0, "y") == 1
    assert enc.decode(0, 2) == "z"
    assert enc.get_category_counts() == {0: 3, 2: 1}
    assert enc.get_value_encoding_map(0) == {"x": 0, "y": 1, "z": 2}
    assert enc.get_encoding_value_map(2) == {0: "p"}


def test_encodings_from_data():
    s = InputSchema(from_dict({
        "oryx.input-schema.feature-names": ["a", "b"],
        "oryx.input-schema.categorical-features": ["b"]}))
    rows = [["1", "red"], ["2", "blue"], ["3", "red"]]
    enc = CategoricalValueEncodings.from_data(rows, s)
    assert enc.get_value_count(1) == 2
    assert enc.encode(1, "red") == 0
    assert enc.encode(1, "blue") == 1
