"""Tier-1 coverage for the ISSUE 3 bench tooling: the grid-regression
CI guard (bench/check_regression.py), a small-shape roofline-probe
invocation, and the AOT warmup's shape planning — all CPU-cheap."""

from __future__ import annotations

import json

import numpy as np
import pytest

from oryx_tpu.bench import check_regression as cr


def _grid_doc(cells, backend="tpu"):
    return {"metric": "als_recommend_http_grid", "backend": backend,
            "rows": [{"features": f, "items": i, "lsh": lsh,
                      "open_loop_sustained_qps": qps, "qps": qps * 1.2,
                      "device_exec_ms": 10.0}
                     for (f, i, lsh, qps) in cells]}


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_check_regression_passes_within_threshold(tmp_path, capsys):
    prev = _grid_doc([(50, 10**6, False, 100.0), (50, 10**6, True, 200.0)])
    cur = _grid_doc([(50, 10**6, False, 95.0), (50, 10**6, True, 260.0)])
    rc = cr.main(["--previous", _write(tmp_path, "BENCH_GRID_r05.json", prev),
                  "--current", _write(tmp_path, "BENCH_GRID_r06.json", cur)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert not report["regressions"]
    assert len(report["improved"]) == 1


def test_check_regression_fails_on_over_10pct_drop(tmp_path, capsys):
    prev = _grid_doc([(50, 10**6, False, 100.0), (250, 10**6, False, 50.0)])
    cur = _grid_doc([(50, 10**6, False, 89.0), (250, 10**6, False, 50.0)])
    rc = cr.main(["--previous", _write(tmp_path, "BENCH_GRID_r05.json", prev),
                  "--current", _write(tmp_path, "BENCH_GRID_r06.json", cur)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert len(report["regressions"]) == 1
    assert report["regressions"][0]["cell"] == "50f/1M"


def test_check_regression_skips_cross_backend(tmp_path, capsys):
    prev = _grid_doc([(50, 10**6, False, 100.0)], backend="tpu")
    cur = _grid_doc([(50, 10**6, False, 1.0)], backend="cpu")
    rc = cr.main(["--previous", _write(tmp_path, "BENCH_GRID_r05.json", prev),
                  "--current", _write(tmp_path, "BENCH_GRID_r06.json", cur)])
    assert rc == 0
    assert "backend mismatch" in json.loads(capsys.readouterr().out)["skipped"]


def test_check_regression_discovers_newest_rounds(tmp_path, capsys):
    _write(tmp_path, "BENCH_GRID_r04.json",
           _grid_doc([(50, 10**6, False, 500.0)]))
    _write(tmp_path, "BENCH_GRID_r05.json",
           _grid_doc([(50, 10**6, False, 100.0)]))
    _write(tmp_path, "BENCH_GRID_r06.json",
           _grid_doc([(50, 10**6, False, 50.0)]))
    # newest (r06) vs prior (r05): the r04 value must NOT be the base
    rc = cr.main(["--dir", str(tmp_path)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert report["previous"] == "BENCH_GRID_r05.json"
    assert report["current"] == "BENCH_GRID_r06.json"
    # zero-sustained previous cells never divide by zero
    _write(tmp_path, "BENCH_GRID_r07.json",
           _grid_doc([(50, 10**6, False, 0.0)]))
    _write(tmp_path, "BENCH_GRID_r08.json",
           _grid_doc([(50, 10**6, False, 10.0)]))
    assert cr.main(["--dir", str(tmp_path)]) == 0


def test_check_regression_walks_back_to_same_backend_round(tmp_path,
                                                           capsys):
    """A CPU smoke round committed between two TPU rounds must not
    un-gate the TPU sequence: r07 (tpu) compares against r05 (tpu),
    skipping the cpu r06 — and a >10% drop across that gap still
    fails."""
    _write(tmp_path, "BENCH_GRID_r05.json",
           _grid_doc([(50, 10**6, False, 100.0)], backend="tpu"))
    _write(tmp_path, "BENCH_GRID_r06.json",
           _grid_doc([(50, 10**6, False, 1.0)], backend="cpu"))
    _write(tmp_path, "BENCH_GRID_r07.json",
           _grid_doc([(50, 10**6, False, 80.0)], backend="tpu"))
    rc = cr.main(["--dir", str(tmp_path)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert report["previous"] == "BENCH_GRID_r05.json"
    assert report["skipped_rounds"] == ["BENCH_GRID_r06.json"]
    assert len(report["regressions"]) == 1
    # no same-backend prior round at all -> skip, exit 0
    _write(tmp_path, "BENCH_GRID_r08.json",
           _grid_doc([(50, 10**6, False, 5.0)], backend="gpu"))
    assert cr.main(["--dir", str(tmp_path)]) == 0
    assert "no prior grid round" in \
        json.loads(capsys.readouterr().out)["skipped"]


def test_check_regression_single_round_is_ok(tmp_path, capsys):
    _write(tmp_path, "BENCH_GRID_r06.json", _grid_doc([]))
    assert cr.main(["--dir", str(tmp_path)]) == 0
    assert "skipped" in json.loads(capsys.readouterr().out)


def test_kernel_probe_small_shape_roofline():
    """Small-shape probe invocation: the roofline decomposition fields
    the grid publishes must be present and self-consistent on a CPU
    streaming shape (the tier-1-safe stand-in for the 20M cells)."""
    from oryx_tpu.app.als import serving_model as sm
    from oryx_tpu.app.als.serving_model import ALSServingModel
    from oryx_tpu.bench.kernel_probe import measure_peaks, probe_model

    rng = np.random.default_rng(3)
    model = ALSServingModel(features=50, implicit=True)
    n = 8192
    model.Y.bulk_load([f"i{j}" for j in range(n)],
                      rng.standard_normal((n, 50)).astype(np.float32))
    old = (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS, sm._PA_TILE)
    sm._FLAT_SCORES_LIMIT = 1
    sm._MAX_CHUNK_ROWS = 2048
    sm._PA_TILE = 2048
    try:
        peaks = measure_peaks(m=3)
        assert peaks["hbm_gb_per_s"] is None \
            or peaks["hbm_gb_per_s"] > 0
        out = probe_model(model, batch=32, m=3, peaks=peaks)
    finally:
        (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS, sm._PA_TILE) = old
    assert out["streaming"]
    tw = out["twophase"]
    roof = tw.get("roofline")
    if tw.get("unmeasurable") or roof is None:
        pytest.skip("timer noise swallowed the m-queue delta")
    # analytic bytes: the scan build streams the lane-padded store plus
    # the (B, N) score spill, write+read
    assert roof["phase_a_bytes"] >= n * 128 * 4
    assert roof["phase_a_flops"] == 2 * 32 * n * 128
    if "phase_b_ms" in roof:
        assert roof["phase_a_ms"] + roof["phase_b_ms"] == pytest.approx(
            tw["exec_ms"], rel=1e-6)


def test_warmup_planned_capacity_matches_bulk_load():
    """The AOT warmup's shape planning must predict the EXACT padded
    capacity a real bulk_load produces — a one-row drift would compile
    a ladder no model load ever hits."""
    from oryx_tpu.app.als.feature_vectors import (FeatureVectorStore,
                                                  planned_capacity)

    for n in (1, 16, 17, 40, 1000, 131072, 131073, 400000):
        store = FeatureVectorStore(8)
        store.bulk_load([f"i{j}" for j in range(n)],
                        np.zeros((n, 8), np.float32))
        assert len(store.row_ids()) == planned_capacity(n), n
    # ... and for the REAL serving load path: set_expected_ids
    # pre-sizes via reserve(), so a per-UP-message replay fills the
    # planned (warmed) capacity in place instead of pow2-regrowing
    # through shapes the warmup never compiled
    n = 3000
    store = FeatureVectorStore(8)
    store.reserve(n)
    assert len(store.row_ids()) == planned_capacity(n)
    for j in range(n):
        store.set_vector(f"i{j}", np.ones(8, np.float32))
    assert len(store.row_ids()) == planned_capacity(n)  # no regrow


def test_warmup_cli_reports_compiles(tmp_path):
    """The warmup subcommand compiles a tiny ladder into a fresh cache
    dir and reports per-kernel outcomes (pallas failures on CPU are
    recorded, never fatal)."""
    import os
    import subprocess
    import sys

    conf = tmp_path / "w.conf"
    conf.write_text(
        'oryx { compile-cache-dir = "%s" }\n' % (tmp_path / "cache"))
    out = subprocess.run(
        [sys.executable, "-m", "oryx_tpu", "warmup", "--conf",
         str(conf), "--items", "0.002", "--features", "8",
         "--dtypes", "float32"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stderr
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["metric"] == "aot_warmup"
    assert report["compiled_count"] > 0
    assert report["cache_dir"] == str(tmp_path / "cache")


# -- gateway scaling regression gate (ISSUE 4 satellite) ---------------------

def _gateway_doc(cells, backend="cpu"):
    """Cells are (features, items, replicas, qps) or, since the r09
    replica-group dimension, (features, items, replicas, R, qps)."""
    rows = []
    for cell in cells:
        f, i, n, *rest = cell
        rps, qps = (rest[0], rest[1]) if len(rest) == 2 \
            else (None, rest[0])
        row = {"features": f, "items": i, "replicas": n,
               "open_loop_sustained_qps": qps,
               "merge_spotcheck_ok": True}
        if rps is not None:
            row["replicas_per_shard"] = rps
        rows.append(row)
    return {"metric": "gateway_recommend_scaling", "backend": backend,
            "rows": rows}


def test_check_regression_gateway_passes_and_reports_cells(tmp_path,
                                                           capsys):
    prev = _gateway_doc([(50, 65536, 1, 100.0), (50, 65536, 2, 170.0)])
    cur = _gateway_doc([(50, 65536, 1, 98.0), (50, 65536, 2, 200.0)])
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r07.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r08.json", cur)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert not report["regressions"]
    assert {c["cell"] for c in report["ok"] + report["improved"]} == \
        {"50f/0.065536M/1rep", "50f/0.065536M/2rep"}


def test_check_regression_gateway_fails_on_per_replica_cell_drop(
        tmp_path, capsys):
    """The 2-replica cell dropping >10% fails even when the 1-replica
    cell held — scaling regressions gate per replica count."""
    prev = _gateway_doc([(50, 65536, 1, 100.0), (50, 65536, 2, 170.0)])
    cur = _gateway_doc([(50, 65536, 1, 101.0), (50, 65536, 2, 140.0)])
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r07.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r08.json", cur)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert [c["cell"] for c in report["regressions"]] == \
        ["50f/0.065536M/2rep"]


def test_check_regression_gateway_replica_group_cells_gate_independently(
        tmp_path, capsys):
    """An R=2 replica-group cell regressing fails the gate even when
    its R=1 sibling at the same shard count improved — and rows
    without the field (pre-r09 artifacts) join the R=1 key."""
    prev = _gateway_doc([(50, 65536, 2, 170.0),          # implicit R=1
                         (50, 65536, 2, 2, 160.0)])
    cur = _gateway_doc([(50, 65536, 2, 1, 190.0),        # explicit R=1
                        (50, 65536, 2, 2, 120.0)])
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r08.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r09.json", cur)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert [c["cell"] for c in report["regressions"]] == \
        ["50f/0.065536M/2repx2"]
    assert [c["cell"] for c in report["improved"]] == \
        ["50f/0.065536M/2rep"]


def test_check_regression_gateway_new_replica_group_cell_not_gated(
        tmp_path, capsys):
    """A first-ever R-cell has no baseline: reported as new, exit 0."""
    prev = _gateway_doc([(50, 65536, 2, 170.0)])
    cur = _gateway_doc([(50, 65536, 2, 1, 168.0),
                        (50, 65536, 2, 2, 150.0)])
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r08.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r09.json", cur)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["new_cells"] == ["(50, 65536, 2, 2)"]
    assert not report["missing_cells"]


def test_check_regression_gateway_zipf_cells_gate_independently(
        tmp_path, capsys):
    """The r11 hot-user Zipf rung gates as its own pseudo-cell: a
    result-cache regression (zipf qps collapsing back toward the cold
    ceiling) fails the gate even when the cold cell held."""
    prev = _gateway_doc([(50, 65536, 1, 100.0)])
    prev["rows"][0]["zipf"] = {"a": 1.2,
                               "open_loop_sustained_qps": 900.0}
    cur = _gateway_doc([(50, 65536, 1, 101.0)])
    cur["rows"][0]["zipf"] = {"a": 1.2,
                              "open_loop_sustained_qps": 300.0}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r09.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r11.json", cur)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert [c["cell"] for c in report["regressions"]] == \
        ["50f/0.065536M/1rep/zipf"]


def test_check_regression_gateway_zipf_cell_back_compat(tmp_path,
                                                        capsys):
    """Pre-cache artifacts carry no zipf rung: the new pseudo-cell is
    reported as new and never gated against the cold baseline."""
    prev = _gateway_doc([(50, 65536, 1, 100.0)])           # r09 shape
    cur = _gateway_doc([(50, 65536, 1, 99.0)])
    cur["rows"][0]["zipf"] = {"a": 1.2,
                              "open_loop_sustained_qps": 800.0}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r09.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r11.json", cur)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["new_cells"] == ["(50, 65536, 1, 1, 'zipf')"]
    assert not report["regressions"]


def test_check_regression_gateway_load_cell_gates_on_load_speed(
        tmp_path, capsys):
    """The r12 model-load telemetry gates as its own pseudo-cell on
    1/model_load_s: a slice-load regression (load time blowing back up
    toward the full-replay cost) fails the gate even when the cold qps
    cell held."""
    prev = _gateway_doc([(50, 65536, 2, 100.0)])
    prev["rows"][0]["model_load"] = {"mode": "slices",
                                     "max_replica_load_s": 5.0}
    cur = _gateway_doc([(50, 65536, 2, 101.0)])
    cur["rows"][0]["model_load"] = {"mode": "slices",
                                    "max_replica_load_s": 20.0}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r11.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r12.json", cur)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert [c["cell"] for c in report["regressions"]] == \
        ["50f/0.065536M/2rep/load"]
    # and a faster load gates green (reported improved, never failed)
    cur["rows"][0]["model_load"]["max_replica_load_s"] = 2.0
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r11.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r12.json", cur)])
    assert rc == 0


def test_check_regression_gateway_load_cell_back_compat(tmp_path,
                                                        capsys):
    """r07/r09/r11 artifacts carry no model_load block: the load
    pseudo-cell is reported as new, never gated against them."""
    prev = _gateway_doc([(50, 65536, 2, 100.0)])           # r11 shape
    cur = _gateway_doc([(50, 65536, 2, 99.0)])
    cur["rows"][0]["model_load"] = {"mode": "slices",
                                    "max_replica_load_s": 4.2}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r11.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r12.json", cur)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["new_cells"] == ["(50, 65536, 2, 1, 'load')"]
    assert not report["regressions"]


def test_check_regression_gateway_mirror_cell_gates_on_catchup_speed(
        tmp_path, capsys):
    """The r13 two-region mirror probe (ISSUE 11) gates as its own
    pseudo-cell on healed-partition catch-up records/s: a mirror
    replay-throughput regression fails the gate even when the qps cell
    held, and steady staleness rides along for diagnosis."""
    prev = _gateway_doc([(50, 65536, 1, 100.0)])
    prev["rows"][0]["mirror"] = {"catch_up_records_per_s": 900.0,
                                 "catch_up_s": 2.2,
                                 "steady_staleness_ms": 90.0}
    cur = _gateway_doc([(50, 65536, 1, 101.0)])
    cur["rows"][0]["mirror"] = {"catch_up_records_per_s": 500.0,
                                "catch_up_s": 4.0,
                                "steady_staleness_ms": 95.0}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r12.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r13.json", cur)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert [c["cell"] for c in report["regressions"]] == \
        ["50f/0.065536M/1rep/mirror"]
    # a faster catch-up gates green
    cur["rows"][0]["mirror"]["catch_up_records_per_s"] = 1800.0
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r12.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r13.json", cur)])
    assert rc == 0


def test_check_regression_gateway_mirror_cell_back_compat(tmp_path,
                                                          capsys):
    """Pre-region artifacts carry no mirror block: the pseudo-cell is
    reported new, never gated against them."""
    prev = _gateway_doc([(50, 65536, 1, 100.0)])           # r12 shape
    cur = _gateway_doc([(50, 65536, 1, 99.0)])
    cur["rows"][0]["mirror"] = {"catch_up_records_per_s": 900.0,
                                "catch_up_s": 2.2,
                                "steady_staleness_ms": 90.0}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r12.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r13.json", cur)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["new_cells"] == ["(50, 65536, 1, 1, 'mirror')"]
    assert not report["regressions"]


def test_check_regression_gateway_conns_cell_gates_on_sustained_qps(
        tmp_path, capsys):
    """The r14 connection-count rung (C10K front end, ISSUE 12) gates
    as its own pseudo-cell: the async front end losing throughput at
    high connection counts fails the gate even when the low-
    concurrency cold cell held; socket/thread telemetry rides along."""
    prev = _gateway_doc([(50, 65536, 1, 100.0)])
    prev["rows"][0]["conns"] = {
        "connections": 4096, "open_loop_sustained_qps": 900.0,
        "router_threads_at_load": 44, "hit_p50_ms": 0.8}
    cur = _gateway_doc([(50, 65536, 1, 101.0)])
    cur["rows"][0]["conns"] = {
        "connections": 4096, "open_loop_sustained_qps": 400.0,
        "router_threads_at_load": 45, "hit_p50_ms": 2.2}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r13.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r14.json", cur)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert [c["cell"] for c in report["regressions"]] == \
        ["50f/0.065536M/1rep/conns"]
    # errors during the rung zero the gated number: also a failure
    cur["rows"][0]["conns"]["open_loop_sustained_qps"] = 0.0
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r13.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r14.json", cur)])
    assert rc == 1
    # and a healthy rung gates green
    cur["rows"][0]["conns"]["open_loop_sustained_qps"] = 950.0
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r13.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r14.json", cur)])
    assert rc == 0


def test_check_regression_gateway_conns_cell_back_compat(tmp_path,
                                                         capsys):
    """r13-and-earlier artifacts carry no conns rung: the pseudo-cell
    is reported as new, never gated against them — and an old round
    being compared AGAINST a conns round reports it missing without
    failing."""
    prev = _gateway_doc([(50, 65536, 1, 100.0)])           # r13 shape
    cur = _gateway_doc([(50, 65536, 1, 99.0)])
    cur["rows"][0]["conns"] = {
        "connections": 4096, "open_loop_sustained_qps": 900.0}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r13.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r14.json", cur)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["new_cells"] == ["(50, 65536, 1, 1, 'conns')"]
    assert not report["regressions"]


def test_check_regression_gateway_writes_cell_gates_independently(
        tmp_path, capsys):
    """The r15 write-heavy rung (durable-ack ingest, ISSUE 17) gates
    as its own pseudo-cell on sustained ACKED writes/s: a write-path
    regression — gate, pipelined produce, broker append — fails the
    gate even when the read cell held; the acked==durable ledger and
    fold-in freshness ride along for diagnosis."""
    prev = _gateway_doc([(50, 65536, 1, 100.0)])
    prev["rows"][0]["writes"] = {
        "open_loop_sustained_qps": 1200.0,
        "acked_equals_durable": True,
        "ingest_to_servable_ms": 700.0,
        "overload": {"p50_shed_ms": 1.5}}
    cur = _gateway_doc([(50, 65536, 1, 101.0)])
    cur["rows"][0]["writes"] = {
        "open_loop_sustained_qps": 500.0,
        "acked_equals_durable": True,
        "ingest_to_servable_ms": 2400.0,
        "overload": {"p50_shed_ms": 1.4}}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r14.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r15.json", cur)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert [c["cell"] for c in report["regressions"]] == \
        ["50f/0.065536M/1rep/writes"]
    # no rung sustained (errors or sheds on every rung) zeroes the
    # gated number: also a failure
    cur["rows"][0]["writes"]["open_loop_sustained_qps"] = 0.0
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r14.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r15.json", cur)])
    assert rc == 1
    # and a healthy rung gates green
    cur["rows"][0]["writes"]["open_loop_sustained_qps"] = 1180.0
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r14.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r15.json", cur)])
    assert rc == 0


def test_check_regression_gateway_writes_cell_back_compat(tmp_path,
                                                          capsys):
    """r14-and-earlier artifacts carry no write rung: the pseudo-cell
    is reported as new, never gated against them."""
    prev = _gateway_doc([(50, 65536, 1, 100.0)])           # r14 shape
    cur = _gateway_doc([(50, 65536, 1, 99.0)])
    cur["rows"][0]["writes"] = {
        "open_loop_sustained_qps": 1200.0,
        "acked_equals_durable": True}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r14.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r15.json", cur)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["new_cells"] == ["(50, 65536, 1, 1, 'writes')"]
    assert not report["regressions"]


def test_check_regression_gateway_ann_cell_gates_independently(
        tmp_path, capsys):
    """The r15 IVF-ANN rung (ISSUE 18, ``--ann``) gates as its own
    pseudo-cell on the ANN door's sustained qps: an index-build or
    routing regression — ANN silently failing closed serves correct
    answers at exact-kernel speed, collapsing the number — fails the
    gate even when the exact cells held; the recall certificate and
    speedup ride along for diagnosis."""
    prev = _gateway_doc([(50, 65536, 1, 100.0)])
    prev["rows"][0]["ann"] = {
        "open_loop_sustained_qps": 950.0,
        "speedup_vs_exact": 8.3,
        "certificate": {"recall": 0.988, "min_recall": 0.95},
        "sustained_p99_ms": 41.0}
    cur = _gateway_doc([(50, 65536, 1, 101.0)])
    cur["rows"][0]["ann"] = {
        "open_loop_sustained_qps": 120.0,   # fell back to exact speed
        "speedup_vs_exact": 1.05,
        "certificate": {"recall": 0.988, "min_recall": 0.95},
        "sustained_p99_ms": 600.0}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r14.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r15.json", cur)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert [c["cell"] for c in report["regressions"]] == \
        ["50f/0.065536M/1rep/ann"]
    # the rung never sustaining (door down, every rung shed) zeroes
    # the gated number: also a failure
    cur["rows"][0]["ann"]["open_loop_sustained_qps"] = 0.0
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r14.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r15.json", cur)])
    assert rc == 1
    # and a healthy rung gates green
    cur["rows"][0]["ann"]["open_loop_sustained_qps"] = 940.0
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r14.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r15.json", cur)])
    assert rc == 0


def test_check_regression_gateway_ann_cell_back_compat(tmp_path,
                                                       capsys):
    """r14-and-earlier artifacts carry no ANN rung — the pseudo-cell
    is new, never gated; and a probe that WITHHELD its headline (ivf
    never routed under emulation: the qps would be fantasy) drops the
    cell entirely rather than gating a number no device produced."""
    prev = _gateway_doc([(50, 65536, 1, 100.0)])           # r14 shape
    cur = _gateway_doc([(50, 65536, 1, 99.0)])
    cur["rows"][0]["ann"] = {
        "open_loop_sustained_qps": 950.0,
        "speedup_vs_exact": 8.3,
        "certificate": {"recall": 0.988}}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r14.json", prev),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r15.json", cur)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["new_cells"] == ["(50, 65536, 1, 1, 'ann')"]
    assert not report["regressions"]
    # headline withheld (None): the probe refused to certify a number
    # (ivf never routed under emulation) — the cell drops out and is
    # surfaced as MISSING, the same non-gating visibility every
    # skipped rung gets, rather than gating a fantasy qps
    prev2 = _gateway_doc([(50, 65536, 1, 100.0)])
    prev2["rows"][0]["ann"] = dict(cur["rows"][0]["ann"])
    cur2 = _gateway_doc([(50, 65536, 1, 99.0)])
    cur2["rows"][0]["ann"] = {
        "open_loop_sustained_qps": None,
        "ann_door_qps_raw": 950.0, "ivf_routed": False}
    rc = cr.main(["--kind", "gateway",
                  "--previous", _write(tmp_path,
                                       "BENCH_GATEWAY_r15a.json", prev2),
                  "--current", _write(tmp_path,
                                      "BENCH_GATEWAY_r15b.json", cur2)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert "(50, 65536, 1, 1, 'ann')" in report["missing_cells"]
    assert not report["regressions"]


def test_check_regression_gateway_discovers_rounds_and_skips_cross_backend(
        tmp_path, capsys):
    _write(tmp_path, "BENCH_GATEWAY_r07.json",
           _gateway_doc([(50, 65536, 2, 170.0)], backend="cpu"))
    _write(tmp_path, "BENCH_GATEWAY_r08.json",
           _gateway_doc([(50, 65536, 2, 100.0)], backend="cpu"))
    # grid artifacts in the same dir must not be picked up
    _write(tmp_path, "BENCH_GRID_r09.json", _grid_doc([]))
    rc = cr.main(["--kind", "gateway", "--dir", str(tmp_path)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert report["previous"] == "BENCH_GATEWAY_r07.json"
    assert report["current"] == "BENCH_GATEWAY_r08.json"
    # cross-backend rounds never compare
    _write(tmp_path, "BENCH_GATEWAY_r09.json",
           _gateway_doc([(50, 65536, 2, 1.0)], backend="tpu"))
    assert cr.main(["--kind", "gateway", "--dir", str(tmp_path)]) == 0


# -- --kind obs: the observability overhead gate (ISSUE 7) --------------------

def _obs_doc(unsampled_ns, full_ns=None, armed_ns=None,
             backend="cpu"):
    micro = {"unsampled_begin_branch_current": unsampled_ns,
             "sampled_begin_record_end": unsampled_ns * 6}
    if full_ns is not None:
        micro["unsampled_full_pipeline"] = full_ns
    if armed_ns is not None:
        micro["unsampled_recorder_armed"] = armed_ns
    return {"metric": "obs_tracing_overhead", "backend": backend,
            "microbench_ns_per_request": micro}


def test_check_regression_obs_passes_within_budget(tmp_path, capsys):
    rc = cr.main(["--kind", "obs",
                  "--previous", _write(tmp_path,
                                       "BENCH_OBS_OVERHEAD_r08.json",
                                       _obs_doc(2738)),
                  "--current", _write(tmp_path,
                                      "BENCH_OBS_OVERHEAD_r10.json",
                                      _obs_doc(2900, full_ns=3500))])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert not report["regressions"]
    assert report["budget_ns"] == 10_000


def test_check_regression_obs_hard_budget_gates(tmp_path, capsys):
    # even a round that "improved" relative to a terrible previous
    # round fails when the absolute single-digit-us budget is broken
    rc = cr.main(["--kind", "obs",
                  "--previous", _write(tmp_path,
                                       "BENCH_OBS_OVERHEAD_r09.json",
                                       _obs_doc(50_000, full_ns=60_000)),
                  "--current", _write(tmp_path,
                                      "BENCH_OBS_OVERHEAD_r10.json",
                                      _obs_doc(9_000, full_ns=12_000))])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert any(c.get("over_budget_ns") == 10_000
               for c in report["regressions"])


def test_check_regression_obs_relative_creep_gates(tmp_path, capsys):
    rc = cr.main(["--kind", "obs",
                  "--previous", _write(tmp_path,
                                       "BENCH_OBS_OVERHEAD_r08.json",
                                       _obs_doc(2000)),
                  "--current", _write(tmp_path,
                                      "BENCH_OBS_OVERHEAD_r10.json",
                                      _obs_doc(4000, full_ns=5000))])
    assert rc == 1   # 2x creep > the 50% obs threshold
    report = json.loads(capsys.readouterr().out)
    assert report["threshold"] == 0.5
    assert any(c["cell"] == "unsampled_begin_branch_current"
               for c in report["regressions"])


def test_check_regression_obs_discovers_rounds(tmp_path, capsys):
    _write(tmp_path, "BENCH_OBS_OVERHEAD_r08.json", _obs_doc(2738))
    _write(tmp_path, "BENCH_OBS_OVERHEAD_r10.json",
           _obs_doc(2800, full_ns=3100))
    # sibling families in the same dir must not be picked up
    _write(tmp_path, "BENCH_GRID_r09.json", _grid_doc([]))
    rc = cr.main(["--kind", "obs", "--dir", str(tmp_path)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["previous"] == "BENCH_OBS_OVERHEAD_r08.json"
    assert report["current"] == "BENCH_OBS_OVERHEAD_r10.json"


def test_check_regression_obs_recorder_armed_cell_gates_budget(
        tmp_path, capsys):
    # r16 (ISSUE 20): the recorder-armed cell is the WORST unsampled
    # cell, so the hard budget gates on it — a healthy full_pipeline
    # number cannot hide an over-budget armed recorder
    rc = cr.main(["--kind", "obs",
                  "--previous", _write(tmp_path,
                                       "BENCH_OBS_OVERHEAD_r10.json",
                                       _obs_doc(2000, full_ns=3000)),
                  "--current", _write(tmp_path,
                                      "BENCH_OBS_OVERHEAD_r16.json",
                                      _obs_doc(2100, full_ns=3100,
                                               armed_ns=12_000))])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert any(c.get("over_budget_ns") == 10_000
               and c.get("ns_cur") == 12_000
               for c in report["regressions"])


def test_check_regression_obs_recorder_armed_pre_r16_back_compat(
        tmp_path, capsys):
    # a pre-r16 previous round simply lacks the recorder-armed cell:
    # the relative gate skips it (never a phantom regression), the
    # budget still gates the current round's armed number
    rc = cr.main(["--kind", "obs",
                  "--previous", _write(tmp_path,
                                       "BENCH_OBS_OVERHEAD_r10.json",
                                       _obs_doc(2000, full_ns=3000)),
                  "--current", _write(tmp_path,
                                      "BENCH_OBS_OVERHEAD_r16.json",
                                      _obs_doc(2100, full_ns=3100,
                                               armed_ns=6_000))])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert not report["regressions"]
    compared = {c["cell"] for c in report["ok"]}
    assert "unsampled_recorder_armed" not in compared
    # ... and two armed rounds DO compare: 2x creep on the armed cell
    # alone gates even inside budget
    rc = cr.main(["--kind", "obs",
                  "--previous", _write(tmp_path,
                                       "BENCH_OBS_OVERHEAD_r16.json",
                                       _obs_doc(2000, full_ns=3000,
                                                armed_ns=4_000)),
                  "--current", _write(tmp_path,
                                      "BENCH_OBS_OVERHEAD_r17.json",
                                      _obs_doc(2100, full_ns=3100,
                                               armed_ns=9_000))])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert any(c["cell"] == "unsampled_recorder_armed"
               for c in report["regressions"])


def test_check_regression_obs_budget_gates_even_without_prior_round(
        tmp_path, capsys):
    # first-ever round (or first on a new backend): no relative
    # comparison exists, but the absolute budget must still gate
    _write(tmp_path, "BENCH_OBS_OVERHEAD_r10.json",
           _obs_doc(9_000, full_ns=12_000))
    rc = cr.main(["--kind", "obs", "--dir", str(tmp_path)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert "absolute budget only" in report["skipped"]
    assert any(c.get("over_budget_ns") == 10_000
               for c in report["regressions"])
    # ... and a within-budget first round passes
    _write(tmp_path, "BENCH_OBS_OVERHEAD_r10.json",
           _obs_doc(2_000, full_ns=3_000))
    assert cr.main(["--kind", "obs", "--dir", str(tmp_path)]) == 0
