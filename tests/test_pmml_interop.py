"""Interop against the reference's ACTUAL wire format: hand-constructed
JPMML-4.3 documents exactly as the reference writers marshal them
(namespace http://www.dmg.org/PMML-4_3, Extensions placed last in
document order per the JAXB propOrder, JPMML attribute spellings).

Fixture provenance (structure, not bytes):
 - ALS:      ALSUpdate.mfModelToPMML (ALSUpdate.java:430-473) —
             X/Y path, features/lambda/implicit/alpha/logStrength/
             epsilon value-Extensions, XIDs/YIDs content-Extensions
             with PMML space-delimited quoting.
 - RDF:      RDFUpdate.rdfModelToPMML/toTreeModel (RDFUpdate.java:
             368-521) — MiningModel+Segmentation for forests, bare
             TreeModel for one tree, r/+/- node ids, greaterThan
             predicates, isNotIn SimpleSetPredicate, defaultChild,
             ScoreDistribution with confidence, MiningField importance.
 - k-means:  KMeansUpdate.kMeansModelToPMML (KMeansUpdate.java:
             184-230) — centerBased ClusteringModel, squaredEuclidean
             ComparisonMeasure, isCenterField ClusteringFields,
             Cluster size + real Array with n.
"""

import math
import os

import pytest

from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.common.config import from_dict

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _fixture(name):
    return pmml_io.read(os.path.join(FIXTURES, name))


# -- ALS ---------------------------------------------------------------------

def test_reads_jpmml_als_extensions():
    doc = _fixture("jpmml_als.pmml.xml")
    assert pmml_io.get_extension_value(doc, "X") == "X/"
    assert pmml_io.get_extension_value(doc, "Y") == "Y/"
    assert int(pmml_io.get_extension_value(doc, "features")) == 3
    assert float(pmml_io.get_extension_value(doc, "lambda")) == 0.001
    assert pmml_io.get_extension_value(doc, "implicit") == "true"
    assert float(pmml_io.get_extension_value(doc, "alpha")) == 1.0
    assert pmml_io.get_extension_value(doc, "logStrength") == "true"
    assert float(pmml_io.get_extension_value(doc, "epsilon")) == 0.01
    # quoted IDs use the PMML space-delimited convention
    # (TextUtils.joinPMMLDelimited)
    assert pmml_io.get_extension_content(doc, "XIDs") == \
        ["u0", "u1", "user two", "u3"]
    assert pmml_io.get_extension_content(doc, "YIDs") == \
        ["i0", "item one", "i2"]


def test_own_als_writer_round_trips_jpmml_structure():
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "X", "X/")
    pmml_io.add_extension(doc, "features", 3)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", ["u0", "user two"])
    reparsed = pmml_io.from_string(pmml_io.to_string(doc))
    assert pmml_io.get_extension_value(reparsed, "features") == "3"
    assert pmml_io.get_extension_value(reparsed, "implicit") == "true"
    assert pmml_io.get_extension_content(reparsed, "XIDs") == \
        ["u0", "user two"]


# -- RDF ---------------------------------------------------------------------

def _rdf_schema(feature_names, numeric, categorical, target):
    return __import__(
        "oryx_tpu.app.schema", fromlist=["InputSchema"]).InputSchema(
        from_dict({"oryx.input-schema": {
            "feature-names": feature_names,
            "numeric-features": numeric,
            "categorical-features": categorical,
            "target-feature": target,
        }}))


def test_reads_jpmml_rdf_forest():
    from oryx_tpu.app.classreg import Example
    from oryx_tpu.app.rdf.pmml import read_forest, validate_pmml_vs_schema

    doc = _fixture("jpmml_rdf_classification.pmml.xml")
    schema = _rdf_schema(["age", "fruit", "color"], ["age"],
                         ["fruit", "color"], "color")
    validate_pmml_vs_schema(doc, schema)
    forest, encodings = read_forest(doc)

    assert len(forest.trees) == 2
    assert list(forest.weights) == [1.0, 1.0]
    # importances ride MiningField order
    assert list(forest.feature_importances[:2]) == [0.75, 0.25]
    # DataDictionary Value order defines the encodings
    assert encodings.get_value_encoding_map(2) == {"red": 0, "green": 1}
    assert encodings.get_value_encoding_map(1) == \
        {"apple": 0, "banana": 1, "cherry": 2}

    # tree 1: age > 30.5 routes right to the red-heavy leaf
    t1 = forest.trees[0]
    old = t1.find_terminal(Example(None, [45.0, 0, None]))
    assert old.id == "r+"
    assert list(old.prediction.category_counts) == [36.0, 4.0]
    young = t1.find_terminal(Example(None, [20.0, 0, None]))
    assert young.id == "r-"

    # tree 2: isNotIn {banana, cherry} selects apples rightward, then
    # age > 10 picks the deeper leaf
    t2 = forest.trees[1]
    apple_old = t2.find_terminal(Example(None, [12.0, 0, None]))
    assert apple_old.id == "r++"
    banana = t2.find_terminal(Example(None, [12.0, 1, None]))
    assert banana.id == "r-"

    # defaultChild drives the missing-value route (tree 1: r- default)
    missing = t1.find_terminal(Example(None, [None, 0, None]))
    assert missing.id == "r-"


def test_reads_jpmml_rdf_regression_tree():
    from oryx_tpu.app.classreg import Example
    from oryx_tpu.app.rdf.pmml import read_forest, validate_pmml_vs_schema

    doc = _fixture("jpmml_rdf_regression.pmml.xml")
    schema = _rdf_schema(["sqft", "rooms", "price"], ["sqft", "rooms",
                         "price"], None, "price")
    validate_pmml_vs_schema(doc, schema)
    forest, _ = read_forest(doc)
    assert len(forest.trees) == 1
    big = forest.trees[0].find_terminal(Example(None, [2000.0, 3.0, None]))
    assert big.prediction.prediction == 400000.0
    small = forest.trees[0].find_terminal(Example(None, [900.0, 2.0, None]))
    assert small.prediction.prediction == 250000.0
    # greaterThan boundary: exactly 1500.0 is NOT greater -> left child
    edge = forest.trees[0].find_terminal(Example(None, [1500.0, 2.0, None]))
    assert edge.prediction.prediction == 250000.0


def test_own_rdf_writer_round_trips_jpmml_structure():
    from oryx_tpu.app.classreg import Example
    from oryx_tpu.app.rdf.pmml import forest_to_pmml, read_forest, \
        validate_pmml_vs_schema

    doc = _fixture("jpmml_rdf_classification.pmml.xml")
    schema = _rdf_schema(["age", "fruit", "color"], ["age"],
                         ["fruit", "color"], "color")
    forest, encodings = read_forest(doc)
    rewritten = pmml_io.from_string(pmml_io.to_string(
        forest_to_pmml(forest, schema, encodings, max_depth=8,
                       max_split_candidates=100, impurity="entropy")))
    validate_pmml_vs_schema(rewritten, schema)
    forest2, _ = read_forest(rewritten)
    assert pmml_io.get_extension_value(rewritten, "impurity") == "entropy"
    for age, fruit in [(45.0, 0), (20.0, 0), (12.0, 1), (5.0, 2)]:
        ex = Example(None, [age, fruit, None])
        for t1, t2 in zip(forest.trees, forest2.trees):
            assert t1.find_terminal(ex).id == \
                t2.find_terminal(ex).id


# -- k-means -----------------------------------------------------------------

def test_reads_jpmml_kmeans_clusters():
    from oryx_tpu.app.kmeans.pmml import read_clusters, \
        validate_pmml_vs_schema

    doc = _fixture("jpmml_kmeans.pmml.xml")
    schema = _rdf_schema(["x0", "x1", "x2"], ["x0", "x1", "x2"], None,
                         None)
    validate_pmml_vs_schema(doc, schema)
    clusters = read_clusters(doc)
    assert [c.id for c in clusters] == [0, 1, 2]
    assert [c.count for c in clusters] == [1200, 800, 2000]
    assert list(clusters[0].center) == [-1.5, 0.25, 3.0]
    assert list(clusters[2].center) == [0.0, 4.5, -2.25]


def test_own_kmeans_writer_round_trips_jpmml_structure():
    from oryx_tpu.app.kmeans.common import ClusterInfo
    from oryx_tpu.app.kmeans.pmml import clusters_to_pmml, read_clusters, \
        validate_pmml_vs_schema

    schema = _rdf_schema(["x0", "x1", "x2"], ["x0", "x1", "x2"], None,
                         None)
    clusters = [ClusterInfo(0, [1.0, -2.0, 0.5], 10),
                ClusterInfo(1, [0.0, 3.25, -1.0], 20)]
    doc = pmml_io.from_string(pmml_io.to_string(
        clusters_to_pmml(clusters, schema)))
    validate_pmml_vs_schema(doc, schema)
    back = read_clusters(doc)
    assert [(c.id, list(c.center), c.count) for c in back] == \
        [(0, [1.0, -2.0, 0.5], 10), (1, [0.0, 3.25, -1.0], 20)]


def test_reads_single_node_tree():
    """A root that never split is a bare TreeModel whose only Node is a
    leaf (RDFUpdate.rdfModelToPMML:381-383 skips the MiningModel
    wrapper for one tree; toTreeModel leaf branch :463-479)."""
    from oryx_tpu.app.rdf.pmml import read_forest, validate_pmml_vs_schema

    doc = _fixture("jpmml_rdf_single_node.pmml.xml")
    schema = _rdf_schema(["age", "color"], ["age"], ["color"], "color")
    validate_pmml_vs_schema(doc, schema)
    forest, encodings = read_forest(doc)
    assert len(forest.trees) == 1
    root = forest.trees[0].root
    assert root.is_terminal
    probs = root.prediction.category_probabilities
    assert probs[encodings.get_value_encoding_map(1)["red"]] == \
        pytest.approx(0.8)
    assert list(forest.feature_importances) == [1.0, 0.0]


def test_model_ref_sized_als_doc_resolves_and_parses():
    """The MODEL-REF size class: a document bigger than the tier-3
    max-message-size (AbstractLambdaIT.java:104 uses 1<<12) travels as
    a path under key MODEL-REF (MLUpdate.java:224-237) and the consumer
    opens it (AppPMMLUtils.readPMMLFromUpdateKeyMessage:259-277).
    XIDs/YIDs exercise every joinPMMLDelimited quoting rule."""
    from oryx_tpu.app.pmml_utils import read_pmml_from_update_key_message

    path = os.path.join(FIXTURES, "jpmml_als_modelref.pmml.xml")
    assert os.path.getsize(path) > (1 << 12)  # the MODEL-REF size class
    doc = read_pmml_from_update_key_message("MODEL-REF", f"file://{path}")
    assert doc is not None
    assert pmml_io.get_extension_value(doc, "features") == "25"
    xids = pmml_io.get_extension_content(doc, "XIDs")
    yids = pmml_io.get_extension_content(doc, "YIDs")
    assert len(xids) == 400 and len(yids) == 300
    assert xids[7] == "user 7"        # space-quoted value
    assert xids[100] == 'u"100'       # embedded-quote escape
    assert yids[0] == "item 0" and yids[1] == "i1"
