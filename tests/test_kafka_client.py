"""Wire-protocol Kafka binding tests: codec units + the production
client (kafka/wire.py + kafka/client.py) against the in-process
MiniKafkaBroker over real sockets.

Reference analog: the kafka-util tests run against
LocalKafkaBroker.java:35 — a real broker in-process — so the binding's
protocol bytes, offset semantics, and drain logic execute for real
rather than against a mocked library.
"""

import threading

import pytest

from oryx_tpu.kafka.client import KafkaBroker
from oryx_tpu.kafka.mini_broker import MiniKafkaBroker
from oryx_tpu.kafka.wire import (KafkaProtocolError, WireKafkaClient,
                                 crc32c, decode_record_batches,
                                 encode_record_batch, read_varint,
                                 write_varint)


# -- codec units -------------------------------------------------------------

def test_crc32c_known_vectors():
    # RFC 3720 B.4 test vectors
    assert crc32c(b"") == 0x00000000
    assert crc32c(bytes(32)) == 0x8A9136AA
    assert crc32c(bytes([0xFF] * 32)) == 0x62A8AB43
    assert crc32c(bytes(range(32))) == 0x46DD794E


def test_varint_round_trip():
    buf = bytearray()
    values = [0, 1, -1, 63, -64, 64, 300, -300, 2 ** 31, -2 ** 31,
              2 ** 40]
    for v in values:
        write_varint(buf, v)
    o, out = 0, []
    for _ in values:
        v, o = read_varint(bytes(buf), o)
        out.append(v)
    assert out == values and o == len(buf)


def test_record_batch_round_trip():
    records = [(b"k0", b"v0"), (None, b"v1"), (b"k2", None)]
    batch = encode_record_batch(42, records)
    got = decode_record_batches(batch)
    assert got == [(42, b"k0", b"v0"), (43, None, b"v1"),
                   (44, b"k2", None)]
    # concatenated batches parse as one stream; a truncated tail is
    # tolerated (brokers cut at max_bytes)
    two = batch + encode_record_batch(45, [(b"k", b"v")])
    assert len(decode_record_batches(two)) == 4
    assert decode_record_batches(two[:-5]) == got


def test_record_batch_crc_covers_payload():
    import struct
    batch = bytearray(encode_record_batch(0, [(b"a", b"b")]))
    crc = struct.unpack_from("!I", batch, 17)[0]
    assert crc == crc32c(bytes(batch[21:]))
    batch[-1] ^= 0xFF  # corrupt the value
    assert crc != crc32c(bytes(batch[21:]))


# -- client <-> mini broker over real sockets --------------------------------

@pytest.fixture(scope="module")
def mini():
    b = MiniKafkaBroker()
    yield b
    b.close()


@pytest.fixture
def wire(mini):
    c = WireKafkaClient(mini.bootstrap)
    yield c
    c.close()


def test_api_versions_handshake(wire):
    versions = wire.api_versions()
    assert versions[0][1] >= 3 and versions[1][1] >= 4  # produce, fetch


def test_admin_produce_fetch_offsets(wire):
    assert wire.partitions_for("wt1") is None
    assert wire.create_topic("wt1", partitions=2) == 0
    assert wire.create_topic("wt1") == 36  # already exists
    assert wire.partitions_for("wt1") == [0, 1]

    off = wire.produce("wt1", 0, [(b"k", b"hello"), (None, b"world")])
    assert off == 0
    assert wire.produce("wt1", 0, [(b"x", b"!")]) == 2
    assert wire.list_offset("wt1", 0, -1) == 3   # latest
    assert wire.list_offset("wt1", 0, -2) == 0   # earliest
    assert wire.list_offset("wt1", 1, -1) == 0

    got = wire.fetch("wt1", 0, 1, max_wait_ms=10)
    assert [(o, v) for o, _, v in got] == [(1, b"world"), (2, b"!")]

    wire.offset_commit("g1", "wt1", {0: 2})
    assert wire.offset_fetch("g1", "wt1", [0, 1]) == {0: 2, 1: None}

    assert wire.delete_topic("wt1") == 0
    assert wire.partitions_for("wt1") is None


def test_fetch_long_poll_wakes_on_produce(mini):
    import time
    c = WireKafkaClient(mini.bootstrap)
    c.create_topic("wt-poll")
    c2 = WireKafkaClient(mini.bootstrap)
    got = []

    def tail():
        got.extend(c2.fetch("wt-poll", 0, 0, max_wait_ms=5000))

    t = threading.Thread(target=tail)
    t.start()
    time.sleep(0.2)
    c.produce("wt-poll", 0, [(None, b"wake")])
    t.join(timeout=5)
    assert not t.is_alive() and [v for _, _, v in got] == [b"wake"]
    c.close()
    c2.close()


def test_fetch_out_of_range(wire):
    wire.create_topic("wt-range")
    wire.produce("wt-range", 0, [(None, b"a")])
    with pytest.raises(KafkaProtocolError):
        wire.fetch("wt-range", 0, 99, max_wait_ms=10)


def test_broker_binding_keyed_sends_and_drain(mini):
    b = KafkaBroker(mini.bootstrap)
    b.create_topic("kb1", partitions=4)
    for i in range(12):
        b.send("kb1", f"key{i}", f"m{i}")
    assert sum(b.latest_offsets("kb1")) == 12
    # identical keys land in the same partition
    b.send("kb1", "stable", "s1")
    b.send("kb1", "stable", "s2")
    ends = b.latest_offsets("kb1")
    msgs = [km.message for km in b.read_ranges("kb1", [0] * 4, ends)]
    assert sorted(msgs) == sorted([f"m{i}" for i in range(12)]
                                  + ["s1", "s2"])
    b.close()


def test_broker_binding_accepts_record_headers(mini):
    """The widened TopicProducer protocol passes record headers; the
    wire binding accepts them for API parity (in-proc propagates them,
    the wire codec documents them as absent-by-default) — a real-broker
    producer must not TypeError on a headered send (send_input always
    attaches a `ts` header)."""
    from oryx_tpu.kafka.client import KafkaTopicProducer
    b = KafkaBroker(mini.bootstrap)
    b.create_topic("kbh1", partitions=1)
    b.send("kbh1", "k", "direct", headers={"ts": "1"})
    p = KafkaTopicProducer(mini.bootstrap, "kbh1")
    p.send("k", "via-producer", headers={"ts": "2",
                                         "traceparent": "00-x"})
    p.close()
    msgs = [km.message for km in b.read_range("kbh1", 0, 2)]
    assert msgs == ["direct", "via-producer"]
    b.close()
