"""Unit tests for the real-Kafka binding's client logic, with a stub
``kafka`` package injected so no broker (or kafka-python) is needed.

The live-broker behavior is covered by the contract suite in
test_kafka.py (skipped when unreachable); these pin the pure logic —
keyed commit-per-record, position-based gap-safe drains, consumer
caching — that would otherwise only run in production.
"""

import sys
import types

import pytest


class _FakeRecord:
    def __init__(self, topic, partition, offset, key, value):
        self.topic = topic
        self.partition = partition
        self.offset = offset
        self.key = key
        self.value = value


class _FakeLog:
    """Shared per-test broker state: topic -> partition -> records
    (offsets may have gaps, like a compacted topic)."""

    def __init__(self):
        self.topics: dict[str, dict[int, list[_FakeRecord]]] = {}
        self.committed: dict[tuple[str, str, int], int] = {}
        self.consumers_created = 0

    def add(self, topic, partition, offset, key, value):
        self.topics.setdefault(topic, {}).setdefault(partition, []).append(
            _FakeRecord(topic, partition, offset,
                        key.encode() if key else None, value.encode()))


class _FakeConsumer:
    def __init__(self, log: _FakeLog, group):
        self._log = log
        self._group = group
        self._assigned: list = []
        self._pos: dict = {}
        log.consumers_created += 1

    # metadata
    def partitions_for_topic(self, topic):
        parts = self._log.topics.get(topic)
        return set(parts) if parts else None

    def end_offsets(self, tps):
        out = {}
        for tp in tps:
            recs = self._log.topics.get(tp.topic, {}).get(tp.partition, [])
            out[tp] = (recs[-1].offset + 1) if recs else 0
        return out

    # assignment / seeking
    def assign(self, tps):
        self._assigned = list(tps)

    def unsubscribe(self):
        self._assigned = []

    def subscribe(self, topics):
        self._assigned = []
        for t in topics:
            for p in sorted(self._log.topics.get(t, {0: []})):
                self._assigned.append(_tp(t, p))

    def seek(self, tp, offset):
        self._pos[tp] = offset

    def position(self, tp):
        return self._pos.get(tp, 0)

    def poll(self, timeout_ms=0):
        out = {}
        for tp in self._assigned:
            recs = [r for r in self._log.topics
                    .get(tp.topic, {}).get(tp.partition, [])
                    if r.offset >= self._pos.get(tp, 0)]
            if recs:
                out[tp] = recs
                self._pos[tp] = recs[-1].offset + 1
        return out

    # offsets
    def committed(self, tp):
        return self._log.committed.get((self._group, tp.topic, tp.partition))

    def commit(self, offsets):
        for tp, om in offsets.items():
            self._log.committed[(self._group, tp.topic, tp.partition)] = \
                om.offset

    def close(self):
        pass


def _tp(topic, partition):
    mod = sys.modules["kafka"]
    return mod.TopicPartition(topic, partition)


@pytest.fixture
def fake_kafka(monkeypatch):
    """Install a stub kafka package and return its shared log."""
    log = _FakeLog()

    import collections
    TopicPartition = collections.namedtuple("TopicPartition",
                                            ["topic", "partition"])
    OffsetAndMetadata = collections.namedtuple("OffsetAndMetadata",
                                               ["offset", "metadata"])

    kafka_mod = types.ModuleType("kafka")
    kafka_mod.TopicPartition = TopicPartition
    kafka_mod.KafkaConsumer = lambda bootstrap_servers=None, group_id=None, \
        enable_auto_commit=None, **kw: _FakeConsumer(log, group_id)
    structs_mod = types.ModuleType("kafka.structs")
    structs_mod.OffsetAndMetadata = OffsetAndMetadata
    kafka_mod.structs = structs_mod
    monkeypatch.setitem(sys.modules, "kafka", kafka_mod)
    monkeypatch.setitem(sys.modules, "kafka.structs", structs_mod)

    # fresh broker object per test (module-level registry is keyed)
    from oryx_tpu.kafka.client import KafkaBroker
    return KafkaBroker("fake:9092"), log


def test_latest_and_num_partitions(fake_kafka):
    broker, log = fake_kafka
    log.add("t", 0, 0, None, "a")
    log.add("t", 0, 1, None, "b")
    log.add("t", 1, 0, None, "c")
    assert broker.num_partitions("t") == 2
    assert broker.latest_offsets("t") == [2, 1]


def test_read_ranges_tolerates_offset_gaps(fake_kafka):
    """Completion is judged by consumer POSITION: a range whose tail
    offsets are compacted away must still drain without timing out."""
    broker, log = fake_kafka
    # offsets 0, 2, 4 exist; 1, 3 compacted away
    for off in (0, 2, 4):
        log.add("t", 0, off, "k", f"m{off}")
    got = broker.read_ranges("t", [0], [5])
    assert [km.message for km in got] == ["m0", "m2", "m4"]


def test_offsets_roundtrip_and_fill_in_latest(fake_kafka):
    broker, log = fake_kafka
    log.add("t", 0, 0, None, "a")
    log.add("t", 1, 0, None, "b")
    log.add("t", 1, 1, None, "c")
    assert broker.get_offsets("g", "t") == [None, None]
    broker.set_offsets("g", "t", [1, 2])
    assert broker.get_offsets("g", "t") == [1, 2]
    broker.set_offset("g2", "t", 1, partition=1)
    assert broker.get_offset("g2", "t", 1) == 1
    broker.fill_in_latest_offsets("g3", ["t"])
    assert broker.get_offsets("g3", "t") == [1, 2]


def test_consume_commits_only_processed_record(fake_kafka):
    """A poll batch of 3 with a consumer that stops after 1 must commit
    only past the first record (at-least-once for the rest)."""
    broker, log = fake_kafka
    for off in range(3):
        log.add("t", 0, off, None, f"m{off}")
    it = broker.consume("t", group="g", from_beginning=True,
                        max_idle_sec=0.2)
    assert next(it).message == "m0"
    # the commit for m0 lands when the consumer comes back for more —
    # a crash mid-processing must leave the in-flight record uncommitted
    assert ("g", "t", 0) not in log.committed
    assert next(it).message == "m1"
    it.close()
    assert log.committed[("g", "t", 0)] == 1  # m1, m2 uncommitted


def test_shared_consumer_is_cached(fake_kafka):
    broker, log = fake_kafka
    log.add("t", 0, 0, None, "a")
    broker.latest_offsets("t")
    broker.latest_offsets("t")
    broker.num_partitions("t")
    created_metadata = log.consumers_created
    assert created_metadata == 1  # one shared group=None consumer
    broker.get_offsets("g", "t")
    broker.get_offsets("g", "t")
    assert log.consumers_created == 2  # plus one for group g


def test_read_ranges_validates_range_count(fake_kafka):
    """ADVICE r2 (medium): zip() must not silently truncate — the batch
    layer would commit ends for partitions that were never drained."""
    broker, log = fake_kafka
    log.add("t", 0, 0, None, "a")
    log.add("t", 1, 0, None, "b")
    with pytest.raises(ValueError):
        broker.read_ranges("t", [0], [1])          # 2 partitions, 1 range
    with pytest.raises(ValueError):
        broker.read_ranges("t", [0, 0], [1])       # starts/ends mismatch
    with pytest.raises(ValueError):
        broker.read_ranges("missing", [0], [1])    # no partition metadata


def test_read_ranges_uses_dedicated_consumer(fake_kafka):
    """Range drains can block up to 30 s per partition; they must not
    borrow (and hold the lock of) the shared metadata consumer."""
    broker, log = fake_kafka
    log.add("t", 0, 0, None, "a")
    broker.latest_offsets("t")            # creates the shared consumer
    base = log.consumers_created
    broker.read_ranges("t", [0], [1])
    broker.read_ranges("t", [0], [1])
    assert log.consumers_created == base + 2  # one fresh consumer each


def test_consume_commits_on_poll_batch_boundaries(fake_kafka):
    """ADVICE r2: one synchronous commit per record throttles the
    update-topic tail; commits must batch per poll while staying
    at-least-once (only fully-processed records committed)."""
    broker, log = fake_kafka
    commits = []
    orig_commit = _FakeConsumer.commit

    def counting_commit(self, offsets):
        commits.append({tp: om.offset for tp, om in offsets.items()})
        orig_commit(self, offsets)

    _FakeConsumer.commit = counting_commit
    try:
        for off in range(4):
            log.add("t", 0, off, None, f"m{off}")
        msgs = [km.message for km in broker.consume(
            "t", group="g", from_beginning=True, max_idle_sec=0.2)]
    finally:
        _FakeConsumer.commit = orig_commit
    assert msgs == ["m0", "m1", "m2", "m3"]
    # all four drained in one poll -> at most a couple of batched
    # commits (boundary + final), never one per record
    assert len(commits) <= 2
    assert log.committed[("g", "t", 0)] == 4
