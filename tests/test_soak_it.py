"""Concurrent three-layer soak: batch, speed and serving live at once
over one broker while traffic flows and the model hot-swaps.

The sequential ITs (test_lambda_it.py, test_lambda_apps_it.py) exercise
each layer's correctness in isolation; this one exercises what only
concurrency can — the serving model's read/write locking under load,
MODEL replay racing UP deltas, and the retain-on-swap grace logic —
the behaviors reference §5.2 guards with AutoReadWriteLock and
versioned snapshots.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.batch import BatchLayer
from oryx_tpu.lambda_rt.serving import ServingLayer
from oryx_tpu.lambda_rt.speed import SpeedLayer


def test_three_layers_concurrent_soak(tmp_path):
    cfg = from_dict({
        "oryx.id": "soak",
        "oryx.input-topic.broker": "memory://soak",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "SoakIn",
        "oryx.update-topic.broker": "memory://soak",
        "oryx.update-topic.message.topic": "SoakUp",
        "oryx.batch.update-class": "oryx_tpu.app.als.update.ALSUpdate",
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.als.speed.ALSSpeedModelManager",
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.als.iterations": 2,
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": 3,
        "oryx.ml.eval.test-fraction": 0.0,
    })
    broker = get_broker("soak")
    rng = np.random.default_rng(31)
    ts = 1_700_000_000_000
    for u in range(20):
        for i in range(12):
            if rng.random() < 0.5:
                broker.send("SoakIn", None,
                            f"u{u},i{i},{rng.exponential(1):.2f},{ts}")
                ts += 1000

    batch = BatchLayer(cfg)
    batch.run_one_generation()  # first model exists before layers start

    speed = SpeedLayer(cfg)
    serving = ServingLayer(cfg, port=0)
    speed.start()
    serving.start()
    errors: list[str] = []
    stop = threading.Event()
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            m = serving.model_manager.get_model()
            if m is not None and m.get_fraction_loaded() >= 0.8:
                break
            time.sleep(0.05)
        base = f"http://127.0.0.1:{serving.port}"

        def reader(worker: int):
            rng_l = np.random.default_rng(worker)
            while not stop.is_set():
                uid = f"u{rng_l.integers(0, 20)}"
                try:
                    with urllib.request.urlopen(
                            f"{base}/recommend/{uid}?howMany=3",
                            timeout=10) as r:
                        json.loads(r.read())
                except urllib.error.HTTPError as e:
                    if e.code not in (404, 503):  # new users may 404
                        errors.append(f"recommend {uid}: HTTP {e.code}")
                except Exception as e:  # noqa: BLE001
                    errors.append(f"recommend {uid}: {e}")

        def writer():
            n = 0
            while not stop.is_set():
                try:
                    req = urllib.request.Request(
                        f"{base}/pref/u{n % 25}/i{n % 12}", method="POST",
                        data=b"1.0")
                    urllib.request.urlopen(req, timeout=10).read()
                except Exception as e:  # noqa: BLE001
                    errors.append(f"pref: {e}")
                n += 1
                time.sleep(0.01)

        threads = [threading.Thread(target=reader, args=(w,), daemon=True)
                   for w in range(4)] + [
            threading.Thread(target=writer, daemon=True)]
        for t in threads:
            t.start()

        # under live traffic: a speed micro-batch emits UP deltas and a
        # fresh batch generation hot-swaps the MODEL.  Wait on the
        # OBSERVABLE condition (u20's pref visible on the input topic),
        # not a fixed sleep — under a loaded CI box the writer thread
        # may need longer than any constant to reach u20 (n=20 at one
        # pref per 10 ms is >= 200 ms of fair scheduling)
        deadline = time.time() + 30
        while time.time() < deadline:
            end = broker.latest_offset("SoakIn")
            if any("u20," in km.message
                   for km in broker.read_range("SoakIn", 0, end)):
                break
            time.sleep(0.05)
        speed.run_one_micro_batch()
        batch.run_one_generation()
        # bounded wait for the serving consumer to replay the new MODEL
        deadline = time.time() + 15
        while time.time() < deadline:
            m = serving.model_manager.get_model()
            if (m is not None and m.get_fraction_loaded() >= 0.8
                    and "u20" in m.all_user_ids()):
                break
            time.sleep(0.05)

        stop.set()
        for t in threads:
            t.join(10.0)
        assert not errors, errors[:5]

        # the swapped model still serves, including the new user the
        # writer introduced (u20+ arrived via /pref -> input topic ->
        # second generation)
        with urllib.request.urlopen(f"{base}/ready", timeout=10) as r:
            assert r.status in (200, 204)
        model = serving.model_manager.get_model()
        assert model.get_fraction_loaded() >= 0.8
        assert "u20" in model.all_user_ids()  # writer-introduced user
    finally:
        stop.set()
        serving.close()
        speed.close()
