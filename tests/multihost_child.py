"""Child process for the two-process multi-host join IT.

Each child is one "host": it joins the jax.distributed cluster through
the SAME config-driven path production uses
(oryx_tpu.parallel.mesh.initialize_multihost), builds the global mesh
spanning both processes' virtual CPU devices, and runs one distributed
ALS training step over it.  Prints MULTIHOST_OK on success,
DISTRIBUTED_UNSUPPORTED when the platform cannot initialize a
multi-process CPU cluster (the parent skips), anything else = failure.

Reference analog: every Spark IT implicitly proves driver/executor
cluster join; SURVEY §5.8's DCN story needs the same.
"""

from __future__ import annotations

import json
import sys


def main() -> None:
    coord, pid, n_dev = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    sys.path.insert(0, sys.argv[4])  # repo root
    from oryx_tpu.common.config import from_dict
    from oryx_tpu.parallel.mesh import build_mesh, initialize_multihost

    cfg = from_dict({
        "oryx.distributed.coordinator-address": coord,
        "oryx.distributed.num-processes": 2,
        "oryx.distributed.process-id": pid,
    })
    try:
        joined = initialize_multihost(cfg)
    except Exception as e:  # noqa: BLE001 — env capability, not a bug
        print("DISTRIBUTED_UNSUPPORTED", repr(e))
        return
    assert joined, "configured join returned False"
    assert jax.process_count() == 2, jax.process_count()
    n_total = len(jax.devices())
    assert n_total == 2 * n_dev, (n_total, n_dev)

    from jax.sharding import NamedSharding, PartitionSpec as P

    from oryx_tpu.app.als.common import ParsedRatings
    from oryx_tpu.parallel import block_ratings, make_train_step

    mesh = build_mesh(None)

    # identical synthetic ratings in both processes (same seed); each
    # process materializes only its addressable shards
    rng = np.random.default_rng(11)
    n_users, n_items, k = 4 * n_total, 3 * n_total, 8
    pairs = sorted({(int(rng.integers(n_users)), int(rng.integers(n_items)))
                    for _ in range(8 * n_users)})
    users, items = np.array(pairs, dtype=np.int32).T
    vals = rng.uniform(0.5, 3.0, size=len(users)).astype(np.float32)
    ratings = ParsedRatings([f"u{i}" for i in range(n_users)],
                            [f"i{i}" for i in range(n_items)],
                            users, items, vals)
    blocks = block_ratings(ratings, n_total)

    sh = NamedSharding(mesh, P("d"))

    def mk(arr):
        return jax.make_array_from_callback(
            arr.shape, sh, lambda idx: arr[idx])

    X = mk(np.zeros((blocks.u_cols.shape[0], k), np.float32))
    Y0 = rng.standard_normal((blocks.i_cols.shape[0], k)).astype(np.float32)
    Y0[blocks.n_items:] = 0.0
    Y = mk(Y0)
    args = [mk(a) for a in (blocks.u_cols, blocks.u_vals, blocks.u_mask,
                            blocks.i_cols, blocks.i_vals, blocks.i_mask)]

    step = make_train_step(mesh, lam=0.01, alpha=1.0, implicit=True)
    X2, Y2 = step(X, Y, *args)
    jax.block_until_ready((X2, Y2))
    for shard in X2.addressable_shards:
        assert np.isfinite(np.asarray(shard.data)).all()
    for shard in Y2.addressable_shards:
        assert np.isfinite(np.asarray(shard.data)).all()
    # a deterministic cross-process fingerprint: both processes print
    # the same global checksum iff the collective actually synchronized
    checksum = float(jax.device_get(
        jax.jit(lambda a: a.sum())(X2)))
    print("MULTIHOST_OK", json.dumps({
        "process": pid,
        "devices": n_total,
        "checksum": round(checksum, 4),
    }))


if __name__ == "__main__":
    main()
