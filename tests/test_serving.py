"""Serving layer + ALS endpoint tests over live HTTP (reference analogs:
AbstractALSServingTest/RecommendTest/SimilarityTest/IngestTest/
ReadOnlyTest/CompressedResponseTest via the Grizzly test container;
here the real ServingLayer serves on a loopback port)."""

import gzip
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.api.serving import AbstractServingModelManager
from oryx_tpu.app.als.serving_model import ALSServingModel
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.serving import ServingLayer

FEATURES = 4


def _build_test_model() -> ALSServingModel:
    """Deterministic model from fixed matrices
    (reference: TestALSModelFactory.java:23)."""
    rng = np.random.default_rng(123)
    model = ALSServingModel(FEATURES, implicit=True)
    X = rng.standard_normal((8, FEATURES)).astype(np.float32) * 0.5
    Y = rng.standard_normal((12, FEATURES)).astype(np.float32) * 0.5
    for i in range(8):
        model.set_user_vector(f"U{i}", X[i])
    for j in range(12):
        model.set_item_vector(f"I{j}", Y[j])
    model.add_known_items("U0", ["I0", "I1"])
    model.add_known_items("U1", ["I1"])
    return model


class MockALSManager(AbstractServingModelManager):
    model = None

    def get_model(self):
        return MockALSManager.model

    def consume_key_message(self, key, message):
        pass


@pytest.fixture(scope="module")
def server():
    MockALSManager.model = _build_test_model()
    cfg = from_dict({
        "oryx.serving.model-manager-class": "tests.test_serving.MockALSManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.input-topic.broker": "memory://serving-test",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "TestInput",
        "oryx.update-topic.broker": None,
        "oryx.update-topic.message.topic": None,
    })
    layer = ServingLayer(cfg, port=0)
    layer.start()
    yield layer
    layer.close()


def _get(server, path, accept="application/json", raw=False):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}", headers={"Accept": accept})
    with urllib.request.urlopen(req, timeout=10) as resp:
        body = resp.read()
        if raw:
            return resp, body
        return json.loads(body) if "json" in accept else body.decode()


def _status_of(server, path, method="GET", data=None, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}", method=method, data=data,
        headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        return e.code


def test_ready(server):
    assert _status_of(server, "/ready") in (200, 204)
    assert _status_of(server, "/ready", method="HEAD") in (200, 204)


def test_recommend(server):
    recs = _get(server, "/recommend/U2?howMany=4")
    assert len(recs) == 4
    assert all(set(r) == {"id", "value"} for r in recs)
    scores = [r["value"] for r in recs]
    assert scores == sorted(scores, reverse=True)


def test_recommend_excludes_known_items(server):
    recs = _get(server, "/recommend/U0?howMany=12")
    ids = {r["id"] for r in recs}
    assert "I0" not in ids and "I1" not in ids
    recs2 = _get(server, "/recommend/U0?howMany=12&considerKnownItems=true")
    assert len(recs2) == 12


def test_recommend_offset_pagination(server):
    all_recs = _get(server, "/recommend/U2?howMany=6")
    page2 = _get(server, "/recommend/U2?howMany=3&offset=3")
    assert [r["id"] for r in page2] == [r["id"] for r in all_recs[3:]]


def test_recommend_unknown_user_404(server):
    assert _status_of(server, "/recommend/nobody") == 404


def test_recommend_bad_params_400(server):
    assert _status_of(server, "/recommend/U0?howMany=-1") == 400


def test_recommend_csv(server):
    text = _get(server, "/recommend/U2?howMany=3", accept="text/csv")
    lines = [l for l in text.splitlines() if l]
    assert len(lines) == 3
    assert all(len(l.split(",")) == 2 for l in lines)


def test_recommend_to_many(server):
    recs = _get(server, "/recommendToMany/U2/U3?howMany=5")
    assert len(recs) == 5


def test_recommend_to_anonymous(server):
    recs = _get(server, "/recommendToAnonymous/I2=2.0/I5?howMany=5")
    assert len(recs) == 5
    assert "I2" not in {r["id"] for r in recs}  # context items excluded


def test_recommend_with_context(server):
    recs = _get(server, "/recommendWithContext/U2/I3=1.5?howMany=5")
    assert len(recs) == 5
    assert "I3" not in {r["id"] for r in recs}


def test_similarity(server):
    sims = _get(server, "/similarity/I0/I1?howMany=5")
    assert len(sims) == 5
    assert {"I0", "I1"}.isdisjoint({s["id"] for s in sims})


def test_similarity_to_item(server):
    sims = _get(server, "/similarityToItem/I0/I1/I2")
    assert [s["id"] for s in sims] == ["I1", "I2"]
    # self-similarity is exactly 1
    self_sim = _get(server, "/similarityToItem/I0/I0")
    assert self_sim[0]["value"] == pytest.approx(1.0, abs=1e-5)


def test_estimate(server):
    model = MockALSManager.model
    ests = _get(server, "/estimate/U1/I2/I3")
    want2 = float(model.get_user_vector("U1") @ model.get_item_vector("I2"))
    assert ests[0]["value"] == pytest.approx(want2, rel=1e-5)
    # unknown item estimates 0 (reference behavior)
    est0 = _get(server, "/estimate/U1/nosuch")
    assert est0[0]["value"] == 0.0


def test_estimate_for_anonymous(server):
    v = _get(server, "/estimateForAnonymous/I0/I1=2.0/I2")
    assert isinstance(v, float)


def test_because(server):
    vals = _get(server, "/because/U0/I5")
    ids = {v["id"] for v in vals}
    assert ids <= {"I0", "I1"}  # only known items explain


def test_most_surprising(server):
    vals = _get(server, "/mostSurprising/U0")
    assert len(vals) == 2
    assert vals[0]["value"] <= vals[1]["value"]  # ascending dot


def test_known_items(server):
    assert _get(server, "/knownItems/U0") == ["I0", "I1"]


def test_most_active_users_and_popular_items(server):
    active = _get(server, "/mostActiveUsers")
    assert active[0] == {"id": "U0", "count": 2}
    popular = _get(server, "/mostPopularItems")
    assert popular[0] == {"id": "I1", "count": 2}


def test_popular_representative_items(server):
    items = _get(server, "/popularRepresentativeItems")
    assert len(items) == FEATURES


def test_all_ids(server):
    assert sorted(_get(server, "/allUserIDs")) == [f"U{i}" for i in range(8)]
    assert len(_get(server, "/allItemIDs")) == 12
    # reference-exact paths (AllUserIDs.java:33-37: /user/allIDs)
    assert sorted(_get(server, "/user/allIDs")) == \
        sorted(_get(server, "/allUserIDs"))
    assert _get(server, "/item/allIDs") == _get(server, "/allItemIDs")


def test_pref_post_and_delete_write_input(server):
    broker = get_broker("serving-test")
    start = broker.latest_offset("TestInput")
    assert _status_of(server, "/pref/U0/I7", method="POST",
                      data=b"3.5") in (200, 204)
    assert _status_of(server, "/pref/U0/I7", method="DELETE") in (200, 204)
    end = broker.latest_offset("TestInput")
    new = [km.message for km in broker.read_range("TestInput", start, end)]
    assert new == ["U0,I7,3.5", "U0,I7,"]


def test_ingest_plain_and_gzip(server):
    broker = get_broker("serving-test")
    start = broker.latest_offset("TestInput")
    body = b"U1,I2,1\nU1,I3,2.0\n"
    st = _status_of(server, "/ingest", method="POST", data=body)
    assert st == 200
    gz = gzip.compress(b"U4,I5,1\n")
    st2 = _status_of(server, "/ingest", method="POST", data=gz,
                     headers={"Content-Type": "application/gzip"})
    assert st2 == 200
    end = broker.latest_offset("TestInput")
    assert [km.message
            for km in broker.read_range("TestInput", start, end)] == \
        ["U1,I2,1", "U1,I3,2.0", "U4,I5,1"]


def test_gzip_response(server):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/allItemIDs",
        headers={"Accept": "application/json", "Accept-Encoding": "gzip"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        body = resp.read()
        if resp.headers.get("Content-Encoding") == "gzip":
            body = gzip.decompress(body)
    assert len(json.loads(body)) == 12


def test_404_unknown_path(server):
    assert _status_of(server, "/nosuchendpoint") == 404


def test_503_when_model_not_loaded():
    MockALSManager.model = None
    cfg = from_dict({
        "oryx.serving.model-manager-class": "tests.test_serving.MockALSManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.input-topic.broker": "memory://serving-test-2",
        "oryx.update-topic.broker": None,
        "oryx.update-topic.message.topic": None,
    })
    layer = ServingLayer(cfg, port=0)
    layer.start()
    try:
        assert _status_of(layer, "/ready") == 503
        assert _status_of(layer, "/recommend/U0") == 503
    finally:
        layer.close()
        MockALSManager.model = _build_test_model()


def test_read_only_forbids_mutations():
    MockALSManager.model = _build_test_model()
    cfg = from_dict({
        "oryx.serving.model-manager-class": "tests.test_serving.MockALSManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.serving.api.read-only": True,
        "oryx.update-topic.broker": None,
        "oryx.update-topic.message.topic": None,
    })
    layer = ServingLayer(cfg, port=0)
    layer.start()
    try:
        assert _status_of(layer, "/pref/U0/I1", method="POST", data=b"1") == 403
        assert _status_of(layer, "/recommend/U0") == 200  # reads still fine
    finally:
        layer.close()


def test_digest_auth():
    MockALSManager.model = _build_test_model()
    cfg = from_dict({
        "oryx.serving.model-manager-class": "tests.test_serving.MockALSManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.serving.api.user-name": "oryx",
        "oryx.serving.api.password": "pass",
        "oryx.input-topic.broker": "memory://serving-test-auth",
        "oryx.update-topic.broker": None,
        "oryx.update-topic.message.topic": None,
    })
    layer = ServingLayer(cfg, port=0)
    layer.start()
    try:
        # unauthenticated -> 401 challenge
        assert _status_of(layer, "/allUserIDs") == 401
        # authenticated via urllib's digest handler -> 200
        mgr = urllib.request.HTTPPasswordMgrWithDefaultRealm()
        mgr.add_password(None, f"http://127.0.0.1:{layer.port}/",
                         "oryx", "pass")
        opener = urllib.request.build_opener(
            urllib.request.HTTPDigestAuthHandler(mgr))
        with opener.open(f"http://127.0.0.1:{layer.port}/allUserIDs",
                         timeout=10) as resp:
            assert resp.status == 200
    finally:
        layer.close()


# -- consoles + HTTPS ---------------------------------------------------------

def test_console_page(server):
    """Each app serves an HTML console at the context root (reference:
    AbstractConsoleResource per-app index.html)."""
    body = _get(server, "/", accept="text/html")
    assert "<!DOCTYPE html>" in body
    assert "Alternating Least Squares" in body
    assert "/recommend" in body
    resp, raw = _get(server, "/", accept="text/html", raw=True)
    assert resp.headers["Content-Type"].startswith("text/html")


def _self_signed_pem(tmp_path):
    """PEM cert+key via the cryptography package (test fixture only;
    skip cleanly where the package is absent, as test_http2's TLS
    fixture already does)."""
    import datetime
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID
    except ImportError:
        pytest.skip("cryptography unavailable")

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=1))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName("localhost")]), critical=False)
            .sign(key, hashes.SHA256()))
    pem = tmp_path / "server.pem"
    pem.write_bytes(
        key.private_bytes(serialization.Encoding.PEM,
                          serialization.PrivateFormat.TraditionalOpenSSL,
                          serialization.NoEncryption())
        + cert.public_bytes(serialization.Encoding.PEM))
    return str(pem)


def test_https_with_digest_auth(tmp_path):
    """HTTPS + DIGEST together (reference: SecureAPIConfigIT.java:44;
    connector spec ServingLayer.java:202-255)."""
    import ssl
    MockALSManager.model = _build_test_model()
    pem = _self_signed_pem(tmp_path)
    cfg = from_dict({
        "oryx.serving.model-manager-class": "tests.test_serving.MockALSManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.serving.api.user-name": "oryx",
        "oryx.serving.api.password": "pass",
        "oryx.serving.api.keystore-file": pem,
        "oryx.input-topic.broker": "memory://serving-test-tls",
        "oryx.update-topic.broker": None,
        "oryx.update-topic.message.topic": None,
    })
    layer = ServingLayer(cfg, port=0)
    layer.start()
    try:
        assert layer.scheme == "https"
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        base = f"https://127.0.0.1:{layer.port}"
        # plain HTTP against the TLS port fails at the transport level
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://127.0.0.1:{layer.port}/ready",
                                   timeout=5)
        # unauthenticated over TLS -> 401 challenge
        try:
            urllib.request.urlopen(
                urllib.request.Request(base + "/allUserIDs"),
                timeout=10, context=ctx)
            status = 200
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 401
        # digest-authenticated over TLS -> 200 with data
        mgr = urllib.request.HTTPPasswordMgrWithDefaultRealm()
        mgr.add_password(None, base + "/", "oryx", "pass")
        opener = urllib.request.build_opener(
            urllib.request.HTTPSHandler(context=ctx),
            urllib.request.HTTPDigestAuthHandler(mgr))
        with opener.open(base + "/allUserIDs", timeout=10) as resp:
            assert resp.status == 200
            assert len(json.loads(resp.read())) == 8
    finally:
        layer.close()


def test_https_secure_port_default(tmp_path):
    """With a keystore configured and no port override, the layer binds
    secure-port (reference: connector.setPort(securePort))."""
    MockALSManager.model = _build_test_model()
    pem = _self_signed_pem(tmp_path)
    cfg = from_dict({
        "oryx.serving.model-manager-class": "tests.test_serving.MockALSManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.serving.api.keystore-file": pem,
        "oryx.serving.api.secure-port": 0,
        "oryx.input-topic.broker": "memory://serving-test-tls2",
        "oryx.update-topic.broker": None,
        "oryx.update-topic.message.topic": None,
    })
    layer = ServingLayer(cfg)
    assert layer.keystore_file == pem
    layer.start()
    try:
        assert layer.scheme == "https"
        assert layer.port > 0
    finally:
        layer.close()


def test_ingest_multipart(server):
    """multipart/form-data ingest with a plain part and a gzipped part
    (reference: Ingest.java:61 accepts multipart file uploads)."""
    broker = get_broker("serving-test")
    start = broker.latest_offset("TestInput")
    boundary = "testboundary42"
    part1 = b"U6,I1,1\nU6,I2,2.0\n"
    part2 = gzip.compress(b"U7,I3,1\n")
    body = (
        f"--{boundary}\r\n"
        f'Content-Disposition: form-data; name="a"; filename="a.csv"\r\n'
        f"Content-Type: text/csv\r\n\r\n").encode() + part1 + (
        f"\r\n--{boundary}\r\n"
        f'Content-Disposition: form-data; name="b"; filename="b.csv.gz"\r\n'
        f"Content-Type: application/octet-stream\r\n"
        f"Content-Transfer-Encoding: binary\r\n\r\n").encode() + part2 + (
        f"\r\n--{boundary}--\r\n").encode()
    st = _status_of(server, "/ingest", method="POST", data=body, headers={
        "Content-Type": f"multipart/form-data; boundary={boundary}"})
    assert st == 200
    end = broker.latest_offset("TestInput")
    got = sorted(km.message
                 for km in broker.read_range("TestInput", start, end))
    assert got == ["U6,I1,1", "U6,I2,2.0", "U7,I3,1"]


def test_ingest_multipart_no_parts_400(server):
    boundary = "emptyb"
    body = f"--{boundary}--\r\n".encode()
    st = _status_of(server, "/ingest", method="POST", data=body, headers={
        "Content-Type": f"multipart/form-data; boundary={boundary}"})
    assert st == 400


def test_metrics_endpoint(server):
    """/metrics exposes per-route counts and latency percentiles
    (ops parity for the reference's Spark-UI observability)."""
    for _ in range(3):
        _get(server, "/recommend/U2?howMany=2")
    _status_of(server, "/recommend/nobody")  # 404 counted as error
    m = _get(server, "/metrics")
    # device-time accounting is always on: the batcher's execute
    # brackets feed device_time_us counters + the busy-fraction gauge
    assert set(m) == {"routes", "model_fraction_loaded",
                      "scoring_batcher", "model_metrics", "resilience",
                      "counters", "freshness", "device_time"}
    assert m["device_time"]["busy_s"] >= 0.0
    assert m["freshness"]["device_busy_fraction"] >= 0.0
    # every resilience entry is a named retry/breaker counter dict
    for stats in m["resilience"].values():
        assert stats["kind"] in ("retry", "breaker")
    rec = m["routes"]["GET /recommend/{userID}"]
    assert rec["count"] >= 4
    assert rec["errors"] >= 1
    assert rec["p50_ms"] > 0
    assert rec["p95_ms"] >= rec["p50_ms"]
    assert m["model_fraction_loaded"] == 1.0


def test_https_silent_client_does_not_block_others(tmp_path):
    """A client that connects to the TLS port and never speaks must not
    stall the accept loop: the handshake is deferred to the connection's
    worker thread, so other clients keep being served."""
    import socket
    import ssl
    MockALSManager.model = _build_test_model()
    pem = _self_signed_pem(tmp_path)
    cfg = from_dict({
        "oryx.serving.model-manager-class": "tests.test_serving.MockALSManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.serving.api.keystore-file": pem,
        "oryx.input-topic.broker": "memory://serving-test-tls3",
        "oryx.update-topic.broker": None,
        "oryx.update-topic.message.topic": None,
    })
    layer = ServingLayer(cfg, port=0)
    layer.start()
    try:
        # open raw TCP connections that never start a TLS handshake
        silent = [socket.create_connection(("127.0.0.1", layer.port), 5)
                  for _ in range(3)]
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        # real clients still get served promptly
        for _ in range(3):
            with urllib.request.urlopen(
                    f"https://127.0.0.1:{layer.port}/ready",
                    timeout=5, context=ctx) as r:
                assert r.status in (200, 204)
        for s in silent:
            s.close()
    finally:
        layer.close()


def test_oversized_header_line_rejected(server):
    import socket
    with socket.create_connection(("127.0.0.1", server.port), timeout=10) as s:
        s.sendall(b"GET /ready HTTP/1.1\r\nHost: a\r\n"
                  b"X-Big: " + b"a" * 70000 + b"\r\n\r\n")
        resp = s.makefile("rb").readline()
    assert b"400" in resp


def test_too_many_headers_rejected(server):
    import socket
    with socket.create_connection(("127.0.0.1", server.port), timeout=10) as s:
        s.sendall(b"GET /ready HTTP/1.1\r\nHost: a\r\n"
                  + b"".join(b"X-H%d: v\r\n" % i for i in range(200))
                  + b"\r\n")
        resp = s.makefile("rb").readline()
    assert b"400" in resp


def test_expect_100_continue_interim_response(server):
    import socket
    body = b"U9,I9,1.0"
    with socket.create_connection(("127.0.0.1", server.port), timeout=10) as s:
        s.sendall(b"POST /ingest HTTP/1.1\r\nHost: a\r\n"
                  b"Expect: 100-continue\r\n"
                  b"Content-Length: %d\r\n\r\n" % len(body))
        r = s.makefile("rb")
        interim = r.readline()
        assert interim.startswith(b"HTTP/1.1 100"), interim
        assert r.readline() in (b"\r\n", b"\n")
        s.sendall(body)
        final = r.readline()
    assert b"200" in final or b"204" in final, final


def test_keep_alive_multiple_requests_one_connection(server):
    import socket
    with socket.create_connection(("127.0.0.1", server.port), timeout=10) as s:
        r = s.makefile("rb")
        for _ in range(3):
            s.sendall(b"GET /ready HTTP/1.1\r\nHost: a\r\n\r\n")
            status = r.readline()
            assert b"204" in status  # /ready responds No Content
            clen = 0
            while True:
                h = r.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                if h.lower().startswith(b"content-length:"):
                    clen = int(h.split(b":")[1])
            if clen:
                r.read(clen)


def test_keep_alive_survives_post_to_404_with_body(server):
    """Error paths that return before the body is read must drain it;
    otherwise the leftover bytes parse as the next request line."""
    import socket
    body = b"x" * 300
    with socket.create_connection(("127.0.0.1", server.port), timeout=10) as s:
        r = s.makefile("rb")
        s.sendall(b"POST /no/such/path HTTP/1.1\r\nHost: a\r\n"
                  b"Content-Length: %d\r\n\r\n" % len(body) + body)
        status = r.readline()
        assert b"404" in status, status
        clen = 0
        while True:
            h = r.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            if h.lower().startswith(b"content-length:"):
                clen = int(h.split(b":")[1])
        r.read(clen)
        # the connection must still speak clean HTTP
        s.sendall(b"GET /ready HTTP/1.1\r\nHost: a\r\n\r\n")
        assert b"204" in r.readline()


def test_header_line_without_colon_rejected(server):
    import socket
    with socket.create_connection(("127.0.0.1", server.port), timeout=10) as s:
        s.sendall(b"GET /ready HTTP/1.1\r\nHost: a\r\n"
                  b"not-a-header-line\r\n\r\n")
        assert b"400" in s.makefile("rb").readline()


def test_obs_fold_continuation_rejected(server):
    import socket
    with socket.create_connection(("127.0.0.1", server.port), timeout=10) as s:
        s.sendall(b"GET /ready HTTP/1.1\r\nHost: a\r\n"
                  b"X-A: one\r\n two\r\n\r\n")
        assert b"400" in s.makefile("rb").readline()


def test_error_resource(server):
    """The /error resource is the addressable form of the uniform error
    page (reference: ErrorResource.java:36): it renders status/uri/
    message from the query string, HTML for browsers and plain text
    otherwise, and returns the carried status code."""
    # plain text form, carrying a status
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/error?code=404&uri=/nope"
        "&message=gone", headers={"Accept": "text/plain"})
    try:
        urllib.request.urlopen(req, timeout=10)
        raise AssertionError("expected HTTP 404")
    except urllib.error.HTTPError as e:
        body = e.read().decode()
        assert e.code == 404
        assert "HTTP 404" in body and "/nope" in body and "gone" in body
    # HTML form for browsers
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/error?message=<boom>",
        headers={"Accept": "text/html"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        body = resp.read().decode()
        assert resp.headers["Content-Type"].startswith("text/html")
        assert "&lt;boom&gt;" in body  # script-safe escaping
        assert "Error" in body


def test_inline_errors_negotiate_html(server):
    """An in-flight error (404 route miss) renders the same page: plain
    text by default, the HTML document when the client is a browser
    (reference: ServingLayer.java:305-311 forwards every error status
    to ErrorResource)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/no-such-endpoint",
        headers={"Accept": "text/html"})
    try:
        urllib.request.urlopen(req, timeout=10)
        raise AssertionError("expected HTTP 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
        body = e.read().decode()
        assert e.headers["Content-Type"].startswith("text/html")
        assert "<strong>Error 404</strong>" in body


def test_head_error_keeps_keepalive_framing(server):
    """A HEAD request that errors must send headers only: writing the
    error body would desynchronize keep-alive framing for the next
    response on the connection (RFC 9110 §9.3.2)."""
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
    try:
        conn.request("HEAD", "/no-such-endpoint")
        resp = conn.getresponse()
        assert resp.status == 404
        assert resp.read() == b""  # http.client enforces no-body for HEAD
        # the connection is still usable and correctly framed
        conn.request("GET", "/ready")
        resp2 = conn.getresponse()
        assert resp2.status in (200, 204)
        resp2.read()
    finally:
        conn.close()
