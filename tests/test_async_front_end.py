"""C10K async front end integration tests (ISSUE 12 acceptance): the
event-loop router against a real 2-shard cluster, proving

1. byte-identity: the async front end serves the full cacheable
   surface byte-identical to the THREADED cached router and to an
   uncached cold router — misses (bridged), hits (on-loop), CSV and
   gzip variants, negative 404s (the test_cache_it oracle pattern);
2. connection scale: >= 1k concurrent keep-alive connections all
   answer 200 on the cache-hit workload while the PROCESS THREAD
   COUNT stays flat (the concurrency ceiling is sockets, not
   threads);
3. graceful behavior at the connection cap: one fast 503 and a
   close, never a hang;
4. the ``async-loop-block`` chaos point: a handler that blocks the
   loop is seen by the watchdog (counter + slow-loop log);
5. coalescing on-loop: a burst of identical requests collapses onto
   one scatter with every response byte-identical.

Marker: chaos (in the tier-1 budget).
"""

from __future__ import annotations

import gzip
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.cluster.router import RouterLayer
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.serving import ServingLayer
from oryx_tpu.resilience import faults
from oryx_tpu.resilience.policy import Deadline

pytestmark = pytest.mark.chaos

BROKER = "async-it"
UPDATE_TOPIC = "AUp"
FEATURES = 3


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _config(**extra):
    overlay = {
        "oryx.id": "async-it",
        "oryx.input-topic.broker": f"memory://{BROKER}",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "AIn",
        "oryx.update-topic.broker": f"memory://{BROKER}",
        "oryx.update-topic.message.topic": UPDATE_TOPIC,
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": FEATURES,
        "oryx.cluster.heartbeat-interval-ms": 60,
        "oryx.cluster.heartbeat-ttl-ms": 400,
        "oryx.cluster.hedge-after-ms": 50,
        "oryx.cluster.shard-timeout-ms": 5000,
        "oryx.resilience.retry.max-attempts": 2,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
    }
    overlay.update(extra)
    return from_dict(overlay)


def _cached_overlay(**extra):
    overlay = {"oryx.cluster.cache.enabled": True,
               "oryx.cluster.coalesce.enabled": True}
    overlay.update(extra)
    return overlay


def _publish_model(broker, n_users=6, n_items=14, seed=29):
    from oryx_tpu.common import pmml as pmml_io
    from oryx_tpu.kafka.api import KEY_MODEL, KEY_UP
    users = [f"au{j}" for j in range(n_users)]
    items = [f"ai{j}" for j in range(n_items)]
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", FEATURES)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", users)
    pmml_io.add_extension_content(doc, "YIDs", items)
    broker.send(UPDATE_TOPIC, KEY_MODEL, pmml_io.to_string(doc))
    rng = np.random.default_rng(seed)
    for iid in items:
        broker.send(UPDATE_TOPIC, KEY_UP, json.dumps(
            ["Y", iid, [float(x) for x in rng.standard_normal(FEATURES)]]))
    for uid in users:
        broker.send(UPDATE_TOPIC, KEY_UP, json.dumps(
            ["X", uid, [float(x) for x in rng.standard_normal(FEATURES)],
             []]))
    return users, items


def _raw_get(port, path, headers=None, timeout=20):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), r.read()


def _raw_get_any(port, path, headers=None, timeout=20):
    try:
        return _raw_get(port, path, headers=headers, timeout=timeout)
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _await(predicate, what, timeout=30.0):
    deadline = Deadline.after(timeout)
    while not deadline.expired:
        try:
            if predicate():
                return
        except (urllib.error.URLError, OSError, KeyError):
            pass
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _flush(port):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/admin/cache/flush", data=b"",
        method="POST")
    with urllib.request.urlopen(req, timeout=15) as r:
        return json.loads(r.read())


def _verdict(headers):
    return headers.get("X-Oryx-Cache")


@pytest.fixture(scope="module")
def cluster():
    """2 shards + async cached router + threaded cached router +
    uncached cold router."""
    broker = get_broker(BROKER)
    users, items = _publish_model(broker)
    replicas = []
    for s in range(2):
        layer = ServingLayer(_config(**{
            "oryx.cluster.enabled": True,
            "oryx.cluster.shard": f"{s}/2"}), port=0)
        layer.start()
        replicas.append(layer)
    a_sync = RouterLayer(_config(**_cached_overlay(**{
        "oryx.cluster.async.enabled": True})), port=0)
    a_sync.start()
    threaded = RouterLayer(_config(**_cached_overlay()), port=0)
    threaded.start()
    cold = RouterLayer(_config(), port=0)
    cold.start()

    def ready(router):
        return _raw_get(router.port, "/ready")[0] in (200, 204)

    def fully_loaded(layer):
        meta = json.loads(_raw_get(layer.port, "/shard/meta")[2])
        return meta.get("users", 0) >= len(users)

    for r in (a_sync, threaded, cold):
        _await(lambda rr=r: ready(rr), "router readiness")
    _await(lambda: all(fully_loaded(r) for r in replicas),
           "full replica replay")
    yield {"replicas": replicas, "async": a_sync,
           "threaded": threaded, "cold": cold, "broker": broker,
           "users": users, "items": items}
    for layer in replicas + [a_sync, threaded, cold]:
        try:
            layer.close()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass


# -- 1. byte identity ---------------------------------------------------------

def test_async_miss_and_hit_byte_identical_to_threaded_and_cold(cluster):
    a, t, c = cluster["async"], cluster["threaded"], cluster["cold"]
    _flush(a.port)
    _flush(t.port)
    for uid in cluster["users"][:3]:
        for qs in ("?howMany=5", "?howMany=10&offset=3",
                   "?howMany=4&considerKnownItems=true"):
            path = f"/recommend/{uid}{qs}"
            _, _, cold_body = _raw_get(c.port, path)
            s1, h1, miss_body = _raw_get(a.port, path)
            s2, h2, hit_body = _raw_get(a.port, path)
            assert (s1, s2) == (200, 200)
            assert _verdict(h1) == "miss" and _verdict(h2) == "hit"
            assert miss_body == cold_body == hit_body, path
            # ... and identical to the THREADED cached router's bytes
            _, ht, tb = _raw_get(t.port, path)
            assert tb == cold_body
            assert _verdict(ht) in ("miss", "hit")


def test_async_wider_cacheable_surface_byte_identical(cluster):
    a, c = cluster["async"], cluster["cold"]
    uid, items = cluster["users"][0], cluster["items"]
    i1, i2 = items[0], items[1]
    for path in (f"/similarity/{i1}/{i2}?howMany=5",
                 f"/similarityToItem/{i1}/{i2}/{items[2]}",
                 f"/estimate/{uid}/{i1}/{i2}",
                 f"/because/{uid}/{i1}?howMany=4",
                 f"/mostSurprising/{uid}",
                 f"/knownItems/{uid}",
                 f"/recommendToMany/{uid}/{cluster['users'][1]}",
                 f"/recommendToAnonymous/{i1}=2.0/{i2}",
                 f"/recommendWithContext/{uid}/{i1}=1.5",
                 f"/estimateForAnonymous/{i1}/{i2}=0.5"):
        _, _, cold_body = _raw_get(c.port, path)
        _, h1, b1 = _raw_get(a.port, path)
        _, h2, b2 = _raw_get(a.port, path)
        assert b1 == cold_body == b2, path
        assert _verdict(h2) == "hit", path


def test_async_csv_and_gzip_variants_byte_identical(cluster):
    a, c = cluster["async"], cluster["cold"]
    uid = cluster["users"][1]
    path = f"/recommend/{uid}?howMany=14&considerKnownItems=true"
    _raw_get(a.port, path)  # prime via the JSON form
    # CSV from the ON-LOOP hit path == cold render
    hdr = {"Accept": "text/csv"}
    _, _, cold_csv = _raw_get(c.port, path, headers=hdr)
    _, h, csv1 = _raw_get(a.port, path, headers=hdr)
    assert _verdict(h) == "hit" and csv1 == cold_csv
    # gzip variant round-trips and reuses the stored bytes
    gz_hdr = {"Accept-Encoding": "gzip"}
    _, _, cold_gz = _raw_get(c.port, path, headers=gz_hdr)
    _, h, gz1 = _raw_get(a.port, path, headers=gz_hdr)
    assert _verdict(h) == "hit"
    assert h.get("Content-Encoding") == "gzip"
    assert gzip.decompress(gz1) == gzip.decompress(cold_gz)
    _, _, gz2 = _raw_get(a.port, path, headers=gz_hdr)
    assert gz2 == gz1


def test_async_negative_404_served_on_loop(cluster):
    a, c = cluster["async"], cluster["cold"]
    path = "/recommend/no-such-user-async?howMany=5"
    sc, _, cold_body = _raw_get_any(c.port, path)
    s1, h1, b1 = _raw_get_any(a.port, path)
    s2, h2, b2 = _raw_get_any(a.port, path)
    assert sc == s1 == s2 == 404
    assert _verdict(h1) == "miss" and _verdict(h2) == "hit"
    assert b1 == b2 == cold_body


def test_async_coalesced_burst_collapses_to_one_scatter(cluster):
    a, c = cluster["async"], cluster["cold"]
    _flush(a.port)
    uid = cluster["users"][3]
    path = f"/recommend/{uid}?howMany=7"
    _, _, cold_body = _raw_get(c.port, path)
    before = a.result_cache.stats()["coalesced_requests"]
    results = []
    barrier = threading.Barrier(8)

    def one():
        barrier.wait()
        s, h, b = _raw_get(a.port, path, timeout=30)
        results.append((s, _verdict(h), b))

    threads = [threading.Thread(target=one) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert len(results) == 8
    assert all(s == 200 and b == cold_body for s, _, b in results)
    assert {v for _, v, _ in results} <= {"miss", "coalesced", "hit"}
    after = a.result_cache.stats()
    assert after["coalesced_requests"] + after["hits"] > before


# -- 2. connection scale ------------------------------------------------------

def _open_keepalive(port):
    s = socket.create_connection(("127.0.0.1", port), timeout=20)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def _request_on(sock, path):
    sock.sendall(f"GET {path} HTTP/1.1\r\nHost: a\r\n\r\n"
                 .encode("latin-1"))


def _read_response(rfile):
    status_line = rfile.readline(65537)
    if not status_line:
        raise ConnectionError("closed")
    status = int(status_line.split(b" ", 2)[1])
    clen = 0
    while True:
        h = rfile.readline(65537)
        if h in (b"\r\n", b"\n", b""):
            break
        if h[:15].lower() == b"content-length:":
            clen = int(h[15:])
    body = b""
    while len(body) < clen:
        got = rfile.read(clen - len(body))
        if not got:
            raise ConnectionError("short body")
        body += got
    return status, body


def test_1k_concurrent_keepalive_connections_flat_thread_count(cluster):
    """The acceptance IT: >= 1k concurrent keep-alive sockets against
    the async front end on the cache-hit workload — every response a
    full 200, and the process thread count FLAT while the socket
    count grew 16x (connections cost fds, not stacks)."""
    a = cluster["async"]
    uid = cluster["users"][0]
    path = f"/recommend/{uid}?howMany=10"
    _raw_get(a.port, path)  # prime the entry
    n = 1024
    socks = []
    try:
        for _ in range(64):
            socks.append(_open_keepalive(a.port))
        # one request per socket at 64 connections: the thread
        # baseline AFTER the loop and bridge are warm
        rfiles = [s.makefile("rb") for s in socks]
        for s in socks:
            _request_on(s, path)
        for rf in rfiles:
            status, body = _read_response(rf)
            assert status == 200
        threads_at_64 = threading.active_count()
        while len(socks) < n:
            s = _open_keepalive(a.port)
            socks.append(s)
            rfiles.append(s.makefile("rb"))
        _await(lambda: cluster["async"]._frontend.open_connections
               >= n, "server sees all connections", timeout=30.0)
        # every connection answers — all 1024 in flight as far as the
        # server is concerned (requests written before any read)
        expected = None
        for s in socks:
            _request_on(s, path)
        ok = 0
        for rf in rfiles:
            status, body = _read_response(rf)
            assert status == 200
            expected = expected or body
            assert body == expected
            ok += 1
        assert ok == n
        threads_at_n = threading.active_count()
        # 16x the sockets, ~0x the threads: the bounded bridge pool
        # (and nothing per-connection) is the only thread source
        assert threads_at_n - threads_at_64 <= 8, \
            (threads_at_64, threads_at_n)
        fe = a._frontend
        assert fe.fast_hits >= n  # the hits never left the loop
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass


def test_connection_cap_sheds_fast_503_never_hangs(cluster):
    """A dedicated async router with a tiny cap: connections up to the
    cap serve; the next one gets a FAST 503 and a close."""
    router = RouterLayer(_config(**_cached_overlay(**{
        "oryx.cluster.async.enabled": True,
        "oryx.cluster.async.max-connections": 8})), port=0)
    router.start()
    try:
        _await(lambda: _raw_get(router.port, "/ready")[0]
               in (200, 204), "cap router readiness")
        uid = cluster["users"][0]
        path = f"/recommend/{uid}?howMany=5"
        held = []
        try:
            for _ in range(8):
                s = _open_keepalive(router.port)
                held.append((s, s.makefile("rb")))
            _await(lambda: router._frontend.open_connections >= 8,
                   "cap reached")
            # held connections still serve
            _request_on(held[0][0], path)
            status, _ = _read_response(held[0][1])
            assert status == 200
            # the 9th: fast 503, closed, bounded time — never a hang
            t0 = time.monotonic()
            s9 = _open_keepalive(router.port)
            rf9 = s9.makefile("rb")
            status, _ = _read_response(rf9)
            assert status == 503
            assert rf9.readline() == b""  # server closed it
            assert time.monotonic() - t0 < 5.0
            assert router._frontend.rejected_connections >= 1
            s9.close()
        finally:
            for s, _ in held:
                try:
                    s.close()
                except OSError:
                    pass
    finally:
        router.close()


# -- 3. chaos: a handler blocks the loop --------------------------------------

def test_async_loop_block_chaos_watchdog_counts(cluster):
    """``async-loop-block``: a handler does synchronous work ON the
    loop — the watchdog measures the stall, counts it, and the router
    keeps serving afterwards."""
    router = RouterLayer(_config(**_cached_overlay(**{
        "oryx.cluster.async.enabled": True,
        "oryx.cluster.async.watchdog-interval-ms": 40,
        "oryx.cluster.async.watchdog-stall-ms": 100})), port=0)
    router.start()
    try:
        _await(lambda: _raw_get(router.port, "/ready")[0]
               in (200, 204), "watchdog router readiness")
        uid = cluster["users"][0]
        path = f"/recommend/{uid}?howMany=5"
        _raw_get(router.port, path)
        faults.inject("async-loop-block", mode="delay", times=1,
                      delay_sec=0.6)
        _raw_get(router.port, path)  # this one blocks the loop
        assert faults.fired("async-loop-block") == 1
        _await(lambda: router._frontend.loop_stalls >= 1,
               "watchdog counted the stall")
        # the counter is on the metrics surface too
        _, _, m = _raw_get(router.port, "/metrics")
        assert json.loads(m)["counters"].get("async_loop_stalls",
                                             0) >= 1
        # and the loop recovered: requests keep flowing
        assert _raw_get(router.port, path)[0] == 200
    finally:
        router.close()


def test_async_front_end_serves_admin_and_writes_through_bridge(cluster):
    """Non-cacheable surface rides the bridge pool: admin endpoints,
    metrics, and the write path behave exactly as on the threaded
    server."""
    a = cluster["async"]
    _, _, m = _raw_get(a.port, "/metrics")
    m = json.loads(m)
    assert "cluster" in m and "cache" in m["cluster"]
    assert m["freshness"]["async_open_connections"] >= 0
    st = json.loads(_raw_get(a.port, "/admin/cache")[2])
    assert st["enabled"]
    # write path: /pref flows to the input topic
    broker = cluster["broker"]
    end_before = broker.latest_offset("AIn")
    req = urllib.request.Request(
        f"http://127.0.0.1:{a.port}/pref/au0/ai1", data=b"2.5",
        method="POST")
    with urllib.request.urlopen(req, timeout=15) as r:
        assert r.status in (200, 204)
    assert broker.latest_offset("AIn") == end_before + 1
    # 405 parity for unknown methods on a known path
    req = urllib.request.Request(
        f"http://127.0.0.1:{a.port}/recommend/au0", method="PUT")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=15)
    assert e.value.code == 405
