"""Unit tests for the router's exact result cache + single-flight
coalescer (cluster/result_cache.py, ISSUE 8): key/tag extraction,
byte-identical render variants, LRU budgets, precise fold-in
invalidation with store fencing, epoch flushes, coalescing leader/
follower protocol, and the two chaos points
(``router-cache-stale-feed``, ``router-coalesce-leader-death``).

Marker: chaos only where a fault is armed; everything is in-process
and deterministic.
"""

from __future__ import annotations

import gzip
import json
import threading

import pytest

from oryx_tpu.cluster.result_cache import ResultCache, route_tags
from oryx_tpu.common.config import from_dict
from oryx_tpu.lambda_rt.http import json_or_csv
from oryx_tpu.lambda_rt.metrics import MetricsRegistry
from oryx_tpu.resilience import faults
from oryx_tpu.serving.als import IDValue


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


class _Reg:
    """MembershipRegistry stand-in: just the cache's epoch surface."""

    def __init__(self):
        self.epoch = (2, (5, 5), False)

    def generation_topology(self):
        return self.epoch


def _render(value, kind):
    return json_or_csv(value,
                       "text/csv" if kind == "csv"
                       else "application/json")


class _Clock:
    """Injectable monotonic clock (the invalidation quarantine is
    time-based; tests advance it explicitly via ``rc._clock.t``)."""

    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _build(store=True, coalesce=True, **kv):
    overlay = {"oryx.cluster.cache.enabled": store,
               "oryx.cluster.coalesce.enabled": coalesce}
    overlay.update(kv)
    reg = _Reg()
    metrics = MetricsRegistry()
    rc = ResultCache(from_dict(overlay), metrics, reg, clock=_Clock())
    return rc, reg, metrics


def _probe(rc, uid="u1", how_many="10", pattern="/recommend/{userID}"):
    return rc.probe(pattern, f"/recommend/{uid}",
                    {"howMany": [how_many]}, {"userID": uid})


def _rows(*pairs):
    return [IDValue(i, v) for i, v in pairs]


# -- key/tag extraction -------------------------------------------------------

def test_route_tags_cover_the_cacheable_surface():
    assert route_tags("/recommend/{userID}", {"userID": "u"}) \
        == (("u",), ())
    assert route_tags("/recommendToMany/{userIDs:+}",
                      {"userIDs": "a/b"}) == (("a", "b"), ())
    assert route_tags("/recommendToAnonymous/{itemIDs:+}",
                      {"itemIDs": "i1=2.5/i2"}) == ((), ("i1", "i2"))
    assert route_tags("/recommendWithContext/{userID}/{itemIDs:+}",
                      {"userID": "u", "itemIDs": "i1=1.5"}) \
        == (("u",), ("i1",))
    assert route_tags("/similarity/{itemIDs:+}",
                      {"itemIDs": "i1/i2"}) == ((), ("i1", "i2"))
    assert route_tags("/similarityToItem/{toItemID}/{itemIDs:+}",
                      {"toItemID": "t", "itemIDs": "i1/i2"}) \
        == ((), ("t", "i1", "i2"))
    assert route_tags("/estimate/{userID}/{itemIDs:+}",
                      {"userID": "u", "itemIDs": "i1/i2"}) \
        == (("u",), ("i1", "i2"))
    assert route_tags("/estimateForAnonymous/{toItemID}/{itemIDs:+}",
                      {"toItemID": "t", "itemIDs": "i=0.5"}) \
        == ((), ("t", "i"))
    assert route_tags("/because/{userID}/{itemID}",
                      {"userID": "u", "itemID": "i"}) == (("u",), ("i",))
    assert route_tags("/mostSurprising/{userID}", {"userID": "u"}) \
        == (("u",), ())
    assert route_tags("/knownItems/{userID}", {"userID": "u"}) \
        == (("u",), ())
    # global aggregates have no precise invalidation key
    assert route_tags("/mostPopularItems", {}) is None
    assert route_tags("/allItemIDs", {}) is None


def test_probe_rejects_rescorer_params_and_unkeyed_routes():
    rc, _, _ = _build()
    assert rc.probe("/recommend/{userID}", "/recommend/u",
                    {"rescorerParams": ["x"]}, {"userID": "u"}) is None
    assert rc.probe("/mostPopularItems", "/mostPopularItems",
                    {}, {}) is None
    p = _probe(rc)
    assert p is not None
    assert ("u", "u1") in p.tags


def test_probe_key_distinguishes_args_and_epoch():
    rc, reg, _ = _build()
    a = _probe(rc, how_many="10")
    b = _probe(rc, how_many="20")
    assert a.key != b.key
    reg.epoch = (2, (6, 5), False)  # one shard's generation moved
    c = _probe(rc, how_many="10")
    assert c.key != a.key


def test_mixed_generation_group_is_uncacheable():
    """While a replica group spans generations mid-rollout, a hedge
    may fall back to an older-generation sibling and win — a complete
    answer is not provably of the newest generation, so the cache
    stands down until the group converges."""
    rc, reg, _ = _build()
    reg.epoch = (2, (6, 6), True)
    assert _probe(rc) is None
    reg.epoch = (2, (6, 6), False)
    assert _probe(rc) is not None


def test_membership_generation_topology_flags_mixed_groups():
    from oryx_tpu.cluster.membership import Heartbeat, MembershipRegistry
    reg = MembershipRegistry(ttl_sec=60.0)

    def beat(rid, shard, gen, of=2):
        reg.note(Heartbeat(replica=rid, shard=shard, of=of,
                           url=f"http://h/{rid}", generation=gen,
                           ready=True))

    beat("a", 0, 3)
    beat("b", 1, 3)
    assert reg.generation_topology() == (2, (3, 3), False)
    beat("a2", 0, 4)  # rollout: shard 0's group now spans 3 and 4
    of, gens, mixed = reg.generation_topology()
    assert (of, gens, mixed) == (2, (4, 3), True)
    beat("a", 0, 4)   # group converges
    assert reg.generation_topology() == (2, (4, 3), False)


# -- store / hit / variants ---------------------------------------------------

def test_store_then_hit_is_byte_identical_to_cold_render():
    rc, _, metrics = _build()
    p = _probe(rc)
    value = _rows(("i1", 2.5), ("i2", 1.0))
    assert rc.lookup(p) is None
    entry = rc.store(p, 200, value, {}, _render)
    assert entry is not None
    hit = rc.lookup(_probe(rc))
    assert hit is entry
    cold_json = json_or_csv(value, "application/json")
    cold_csv = json_or_csv(value, "text/csv")
    assert rc.render(entry, False, False, _render)[:2] == \
        (cold_json[0], cold_json[1])
    assert rc.render(entry, True, False, _render)[:2] == \
        (cold_csv[0], cold_csv[1])
    assert metrics.counters_snapshot()["cache_hits"] == 1
    assert metrics.counters_snapshot()["cache_misses"] == 1


def test_uncacheable_results_are_never_stored():
    rc, _, _ = _build()
    # partial answers (extra headers), errors, empty values
    assert rc.store(_probe(rc), 200, _rows(("i", 1.0)),
                    {"X-Oryx-Partial": "shards=1/2"}, _render) is None
    assert rc.store(_probe(rc), 404, _rows(("i", 1.0)), {},
                    _render) is None
    assert rc.store(_probe(rc), 200, None, {}, _render) is None
    assert rc.stats()["entries"] == 0


def test_gzip_variant_renders_once_and_is_reused():
    rc, _, _ = _build()
    value = _rows(*[(f"item-{j}", float(j)) for j in range(50)])
    entry = rc.store(_probe(rc), 200, value, {}, _render)
    payload, ctype, gzipped = rc.render(entry, False, True, _render)
    assert gzipped and ctype == "application/json"
    raw = json_or_csv(value, "application/json")[0]
    assert gzip.decompress(payload) == raw
    again = rc.render(entry, False, True, _render)[0]
    assert again is payload  # memoized bytes, no recompression
    # the variants charge the byte budget
    assert entry.bytes >= len(raw) + len(payload)
    assert rc.stats()["bytes"] == entry.bytes


def test_value_footprint_charged_then_dropped_after_csv_render():
    """The retained Python value is charged to the byte budget (a
    multiple of its JSON bytes) and dropped — charge released — once
    both plain variant kinds exist; gzip derives from the bytes."""
    rc, _, _ = _build()
    value = _rows(*[(f"item-{j}", float(j)) for j in range(30)])
    entry = rc.store(_probe(rc), 200, value, {}, _render)
    raw = json_or_csv(value, "application/json")[0]
    assert entry.value_charge > 0
    assert entry.bytes == len(raw) + entry.value_charge
    before = entry.bytes
    csv_payload = rc.render(entry, True, False, _render)[0]
    assert entry.value is None and entry.value_charge == 0
    assert entry.bytes == before - 3 * len(raw) + len(csv_payload)
    assert rc.stats()["bytes"] == entry.bytes
    # a later gzip render still works, from the rendered bytes
    gz = rc.render(entry, False, True, _render)[0]
    assert gzip.decompress(gz) == raw


def test_small_bodies_skip_gzip_like_cold_sends():
    rc, _, _ = _build()
    entry = rc.store(_probe(rc), 200, _rows(("i", 1.0)), {}, _render)
    payload, _, gzipped = rc.render(entry, False, True, _render)
    assert not gzipped
    assert payload == json_or_csv(_rows(("i", 1.0)),
                                  "application/json")[0]


def test_lru_evicts_by_entry_and_byte_budget():
    rc, _, metrics = _build(**{"oryx.cluster.cache.max-entries": 3})
    for j in range(5):
        rc.store(_probe(rc, uid=f"u{j}"), 200, _rows((f"i{j}", 1.0)),
                 {}, _render)
    st = rc.stats()
    assert st["entries"] == 3 and st["evictions"] == 2
    assert metrics.counters_snapshot()["cache_evictions"] == 2
    # oldest evicted: u0/u1 gone, u4 present
    assert rc.lookup(_probe(rc, uid="u0")) is None
    assert rc.lookup(_probe(rc, uid="u4")) is not None

    rc2, _, _ = _build(**{"oryx.cluster.cache.max-bytes": 200})
    big = _rows(*[(f"item-{j}", float(j)) for j in range(20)])
    rc2.store(_probe(rc2, uid="a"), 200, big, {}, _render)
    rc2.store(_probe(rc2, uid="b"), 200, big, {}, _render)
    assert rc2.stats()["bytes"] <= 200 or rc2.stats()["entries"] <= 1


# -- precise invalidation -----------------------------------------------------

def test_x_record_evicts_exactly_the_touched_user():
    rc, _, metrics = _build()
    for uid in ("u1", "u2"):
        rc.store(_probe(rc, uid=uid), 200, _rows((f"i-{uid}", 1.0)),
                 {}, _render)
    rc.note_up(json.dumps(["X", "u1", [0.1, 0.2], ["i9"]]))
    assert rc.lookup(_probe(rc, uid="u1")) is None   # touched: evicted
    assert rc.lookup(_probe(rc, uid="u2")) is not None  # survives
    assert rc.stats()["invalidations"] == 1
    assert metrics.counters_snapshot()["cache_invalidations"] == 1


def test_y_record_evicts_item_keys_and_the_named_user():
    rc, _, _ = _build()
    sim = rc.probe("/similarity/{itemIDs:+}", "/similarity/i1/i2",
                   {}, {"itemIDs": "i1/i2"})
    rc.store(sim, 200, _rows(("i3", 0.9)), {}, _render)
    rc.store(_probe(rc, uid="u1"), 200, _rows(("i1", 1.0)), {},
             _render)
    rc.store(_probe(rc, uid="u2"), 200, _rows(("i9", 1.0)), {},
             _render)
    rc.note_up(json.dumps(["Y", "i1", [0.1, 0.2], ["u1"]]))
    assert rc.lookup(rc.probe("/similarity/{itemIDs:+}",
                              "/similarity/i1/i2", {},
                              {"itemIDs": "i1/i2"})) is None
    assert rc.lookup(_probe(rc, uid="u1")) is None
    assert rc.lookup(_probe(rc, uid="u2")) is not None


def test_malformed_up_records_are_ignored():
    rc, _, _ = _build()
    rc.store(_probe(rc), 200, _rows(("i", 1.0)), {}, _render)
    rc.note_up("not json")
    rc.note_up(json.dumps({"kind": "X"}))
    assert rc.lookup(_probe(rc)) is not None


def test_generation_publish_flushes_the_epoch():
    rc, _, _ = _build()
    rc.store(_probe(rc, uid="u1"), 200, _rows(("i", 1.0)), {}, _render)
    rc.store(_probe(rc, uid="u2"), 200, _rows(("i", 1.0)), {}, _render)
    rc.note_generation_publish()
    st = rc.stats()
    assert st["entries"] == 0 and st["epoch_flushes"] == 1


def test_store_is_fenced_by_invalidation_during_flight():
    """A scatter that read pre-fold-in replica state must not insert
    over a newer invalidation: the zero-stale race guard."""
    rc, _, _ = _build()
    p = _probe(rc, uid="u1")           # probe minted BEFORE the UP
    rc.note_up(json.dumps(["X", "u1", [0.1], []]))
    # fenced: neither retained nor handed to coalesced followers — a
    # follower may have arrived AFTER the tap applied the eviction,
    # and sharing these bytes would serve pre-fold-in rows past it
    assert rc.store(p, 200, _rows(("stale", 1.0)), {}, _render) is None
    assert rc.lookup(_probe(rc, uid="u1")) is None
    assert rc.stats()["store_rejects"] == 1
    # a probe minted AFTER the invalidation but within the quarantine
    # window is refused too: the router's tap can run a beat ahead of
    # a replica's replay of the same topic, so a just-evicted tag
    # stays store-quarantined until the replicas have caught up
    assert rc.store(_probe(rc, uid="u1"), 200, _rows(("racy", 1.0)),
                    {}, _render) is None
    assert rc.lookup(_probe(rc, uid="u1")) is None
    assert rc.stats()["store_rejects"] == 2
    # past the quarantine, a fresh probe stores fine
    rc._clock.t += rc.quarantine_sec + 0.01
    assert rc.store(_probe(rc, uid="u1"), 200, _rows(("fresh", 1.0)),
                    {}, _render) is not None
    assert rc.lookup(_probe(rc, uid="u1")) is not None


def test_store_is_fenced_by_epoch_move():
    rc, reg, _ = _build()
    p = _probe(rc)
    reg.epoch = (2, (6, 6), False)  # rollout finished mid-request
    assert rc.store(p, 200, _rows(("i", 1.0)), {}, _render) is None
    assert rc.stats()["entries"] == 0


def test_flush_is_a_store_fence_too():
    rc, _, _ = _build()
    p = _probe(rc)
    rc.flush("admin")
    rc.store(p, 200, _rows(("i", 1.0)), {}, _render)
    assert rc.lookup(_probe(rc)) is None


# -- single-flight coalescing -------------------------------------------------

def test_followers_reuse_the_leaders_rendered_result():
    rc, _, metrics = _build()
    p = _probe(rc)
    kind, flight = rc.begin_flight(p, None)
    assert kind == "lead"
    results = []
    ready = []

    def follower():
        fp = _probe(rc)
        ready.append(1)
        results.append(rc.begin_flight(fp, None))

    threads = [threading.Thread(target=follower) for _ in range(3)]
    for t in threads:
        t.start()
    # wait until every follower is at (or inside) its latch before the
    # leader publishes — a follower arriving after the finish would
    # correctly lead its own flight, which is not this test
    while len(ready) < 3:
        threading.Event().wait(0.01)
    threading.Event().wait(0.3)
    entry = rc.store(p, 200, _rows(("i1", 2.0)), {}, _render)
    rc.finish_flight(flight, entry)
    for t in threads:
        t.join(5.0)
    assert len(results) == 3
    assert all(k == "coalesced" and e is entry for k, e in results)
    assert metrics.counters_snapshot()["coalesced_requests"] == 3
    assert rc.stats()["in_flight"] == 0


def test_leader_death_wakes_followers_to_their_own_scatter():
    rc, _, _ = _build()
    p = _probe(rc)
    kind, flight = rc.begin_flight(p, None)
    assert kind == "lead"
    out = []

    def follower():
        out.append(rc.begin_flight(_probe(rc), None))

    t = threading.Thread(target=follower)
    t.start()
    rc.finish_flight(flight, None)  # leader died / result uncacheable
    t.join(5.0)
    assert out and out[0] == ("solo", None)
    assert rc.stats()["coalesce_fallthroughs"] == 1
    # the NEXT request can lead again
    assert rc.begin_flight(_probe(rc), None)[0] == "lead"


def test_finish_flight_is_idempotent():
    rc, _, _ = _build()
    _, flight = rc.begin_flight(_probe(rc), None)
    entry = rc.store(_probe(rc), 200, _rows(("i", 1.0)), {}, _render)
    rc.finish_flight(flight, entry)
    rc.finish_flight(flight, None)  # late duplicate must not clobber
    assert flight.entry is entry


def test_coalesce_disabled_means_solo():
    rc, _, _ = _build(coalesce=False)
    assert rc.begin_flight(_probe(rc), None) == ("solo", None)


@pytest.mark.chaos
def test_coalesce_leader_death_chaos_point():
    """``router-coalesce-leader-death``: the would-be leader dies at
    the latch — followers are woken empty-handed and fall through; the
    next request leads normally (no permanently poisoned key)."""
    rc, _, _ = _build()
    faults.inject("router-coalesce-leader-death", mode="error", times=1)
    with pytest.raises(faults.InjectedFault):
        rc.begin_flight(_probe(rc), None)
    assert faults.fired("router-coalesce-leader-death") == 1
    kind, _ = rc.begin_flight(_probe(rc), None)
    assert kind == "lead"  # flight cleaned up, no hang


@pytest.mark.chaos
def test_stale_feed_chaos_counts_and_generation_flush_rescues():
    """``router-cache-stale-feed``: a stalled invalidation tap leaves
    the touched user's entry in place (counted), and the epoch flush
    on the next generation publish is the safety valve."""
    rc, _, metrics = _build()
    rc.store(_probe(rc, uid="u1"), 200, _rows(("pre", 1.0)), {},
             _render)
    faults.inject("router-cache-stale-feed", mode="drop", times=None)
    rc.note_up(json.dumps(["X", "u1", [0.1], []]))
    assert rc.lookup(_probe(rc, uid="u1")) is not None  # stale served
    assert rc.stats()["stale_feed_stalls"] == 1
    assert metrics.counters_snapshot()["cache_stale_feed_stalls"] == 1
    rc.note_generation_publish()  # the safety valve
    assert rc.lookup(_probe(rc, uid="u1")) is None


# -- config gates -------------------------------------------------------------

def test_from_config_is_none_unless_a_gate_is_armed():
    reg, metrics = _Reg(), MetricsRegistry()
    assert ResultCache.from_config(from_dict({}), metrics, reg) is None
    rc = ResultCache.from_config(
        from_dict({"oryx.cluster.cache.enabled": True}), metrics, reg)
    assert rc is not None and rc.store_enabled and not rc.coalesce
    rc = ResultCache.from_config(
        from_dict({"oryx.cluster.coalesce.enabled": True}), metrics,
        reg)
    assert rc is not None and rc.coalesce and not rc.store_enabled


def test_coalesce_only_mode_shares_without_retaining():
    rc, _, _ = _build(store=False, coalesce=True)
    p = _probe(rc)
    entry = rc.store(p, 200, _rows(("i", 1.0)), {}, _render)
    assert entry is not None          # shareable with followers
    assert rc.lookup(_probe(rc)) is None  # never retained
    assert rc.stats()["entries"] == 0


# -- negative caching (hot 404s) ----------------------------------------------

def test_negative_store_and_hit_under_the_same_epoch():
    rc, _, metrics = _build()
    p = _probe(rc, uid="ghost")
    assert rc.lookup(p) is None
    entry = rc.store_negative(p, 404, "ghost")
    assert entry is not None and entry.status == 404
    got = rc.lookup(_probe(rc, uid="ghost"))
    assert got is entry
    assert rc.negative_hits == 1
    assert metrics.counters_snapshot().get("cache_negative_hits") == 1
    # a DIFFERENT missing id is its own key
    assert rc.lookup(_probe(rc, uid="ghost2")) is None


def test_negative_entry_evicted_by_the_creating_up_record():
    """The whole point: the fold-in that CREATES the user evicts its
    404 — a freshly folded-in user is never served 'unknown' from the
    cache."""
    rc, _, _ = _build()
    rc.store_negative(_probe(rc, uid="newbie"), 404, "newbie")
    assert rc.lookup(_probe(rc, uid="newbie")) is not None
    rc.note_up(json.dumps(["X", "newbie", [0.1, 0.2], ["i1"]]))
    assert rc.lookup(_probe(rc, uid="newbie")) is None
    # item-side creation evicts item-tagged 404s too
    sim = rc.probe("/similarity/{itemIDs:+}", "/similarity/newitem",
                   {}, {"itemIDs": "newitem"})
    rc.store_negative(sim, 404, "newitem")
    rc.note_up(json.dumps(["Y", "newitem", [0.1, 0.2]]))
    assert rc.lookup(sim) is None


def test_negative_store_respects_fencing_and_epoch():
    rc, reg, _ = _build()
    p = _probe(rc, uid="gone")
    # invalidation AFTER the probe fences the store
    rc.note_up(json.dumps(["X", "gone", [0.1], []]))
    rc._clock.t += rc.quarantine_sec + 1.0
    assert rc.store_negative(p, 404, "gone") is None
    assert rc.store_rejects == 1
    # epoch moved mid-flight: refused
    p2 = _probe(rc, uid="gone2")
    reg.epoch = (2, (6, 6), False)
    assert rc.store_negative(p2, 404, "gone2") is None


def test_negative_caching_gate_and_non_404s():
    rc, _, _ = _build(**{"oryx.cluster.cache.negative-enabled": False})
    assert rc.store_negative(_probe(rc), 404, "x") is None
    rc2, _, _ = _build()
    # only 404s are negative-cacheable (503s are transient state)
    assert rc2.store_negative(_probe(rc2), 503, "overloaded") is None


def test_negative_entries_flush_with_the_generation():
    rc, _, _ = _build()
    rc.store_negative(_probe(rc, uid="ghost"), 404, "ghost")
    rc.note_generation_publish()
    assert rc.lookup(_probe(rc, uid="ghost")) is None


def test_negative_coalesce_only_shares_without_retaining():
    rc, _, _ = _build(store=False, coalesce=True)
    p = _probe(rc, uid="ghost")
    entry = rc.store_negative(p, 404, "ghost")
    assert entry is not None and entry.status == 404  # shareable
    assert rc.lookup(p) is None  # never retained
