"""Scheme-routed storage plane (common/store.py): the shared-filesystem
role HDFS plays in the reference (SaveToHDFSFunction.java:35-86,
MLUpdate.java:233-237, AppPMMLUtils.readPMMLFromUpdateKeyMessage :259).

``memory://`` (fsspec's in-process filesystem) stands in for a remote
object store; ``file://`` is exercised across *processes with different
cwds* to prove a MODEL-REF published by a trainer resolves from a
separately-launched serving process.
"""

import gzip
import os
import subprocess
import sys
import time

import numpy as np
import pytest

pytest.importorskip("fsspec")

from oryx_tpu.common import store
from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.kafka.api import KEY_MODEL_REF, KeyMessage
from oryx_tpu.lambda_rt import data_store


def _clear_memory_fs():
    import fsspec
    fs = fsspec.filesystem("memory")
    for p in list(fs.store):
        fs.store.pop(p, None)
    fs.pseudo_dirs[:] = [""]


@pytest.fixture(autouse=True)
def memory_fs():
    _clear_memory_fs()
    yield
    _clear_memory_fs()


def test_store_primitives_memory_scheme():
    base = "memory://bucket/dir"
    p = store.join(base, "sub", "file.txt")
    assert p == "memory://bucket/dir/sub/file.txt"
    assert not store.exists(p)
    with store.open_write(p) as f:
        f.write(b"hello")
    assert store.exists(p) and store.getsize(p) == 5
    with store.open_read(p) as f:
        assert f.read() == b"hello"
    assert store.glob(store.join(base, "sub"), "*.txt") == [p]
    store.rename(p, store.join(base, "sub", "renamed.txt"))
    assert not store.exists(p)
    assert store.exists(store.join(base, "sub", "renamed.txt"))
    store.delete_recursively(store.join(base, "sub"))
    assert not store.exists(store.join(base, "sub", "renamed.txt"))


def test_store_primitives_local(tmp_path):
    base = f"file://{tmp_path}"
    p = store.join(base, "a", "b.bin")
    with store.open_write(p) as f:
        f.write(b"x" * 10)
    assert (tmp_path / "a" / "b.bin").read_bytes() == b"x" * 10
    assert store.getsize(p) == 10
    assert store.is_local(p) and not store.is_local("memory://x/y")


def test_generations_on_memory_store():
    data_dir = "memory://lake/data"
    data = [KeyMessage("k", "1,2,3"), KeyMessage(None, "4,5,6")]
    path = data_store.save_generation(data_dir, 1000, data)
    assert path.startswith("memory://")
    data_store.save_generation(data_dir, 2000, [KeyMessage("z", "7,8,9")])
    got = data_store.read_all_data(data_dir)
    assert [km.message for km in got] == ["1,2,3", "4,5,6", "7,8,9"]
    # TTL deletion routes through the same store
    assert data_store.delete_old_data(data_dir, 0) == 2
    assert data_store.read_all_data(data_dir) == []


def test_mlupdate_publishes_model_ref_through_memory_store():
    """The full batch loop on a remote-scheme model-dir: candidates are
    built, the winner is rename-published, and (with a tiny
    max-message-size, the reference's tier-3 trick —
    AbstractLambdaIT.java:104) the model goes out as a MODEL-REF whose
    URI resolves through the store from a consumer that shares no cwd
    with the trainer."""
    from oryx_tpu.app.als.update import ALSUpdate
    from oryx_tpu.app.pmml_utils import read_pmml_from_update_key_message
    from oryx_tpu.common.config import from_dict

    cfg = from_dict({
        "oryx.update-topic.message.max-size": 1 << 7,  # force MODEL-REF
        "oryx.ml.eval.test-fraction": 0.0,
        "oryx.ml.eval.candidates": 1,
        "oryx.als.hyperparams.features": 4,
        "oryx.als.hyperparams.lambda": 0.001,
        "oryx.als.implicit": True,
    })
    rng = np.random.default_rng(7)
    data = [KeyMessage(None, f"u{rng.integers(20)},i{rng.integers(30)},1")
            for _ in range(300)]

    sent = []

    class Capture:
        def send(self, key, message):
            sent.append((key, message))

    ALSUpdate(cfg).run_update(0, data, [], "memory://lake/model", Capture())
    keys = [k for k, _ in sent]
    assert KEY_MODEL_REF in keys, keys
    ref = dict(sent)[KEY_MODEL_REF]
    # since the sharded-distribution PR the MODEL-REF payload is a
    # manifest-carrying envelope; the path inside keeps the full
    # memory:// scheme end-to-end
    from oryx_tpu.app.als.slices import parse_model_ref
    path, env_dir, manifest = parse_model_ref(ref)
    assert path.startswith("memory://lake/model/")
    assert manifest is not None and manifest["ring"] >= 1
    # the .temporary staging dir is cleaned after the atomic publish
    assert store.glob("memory://lake/model", ".temporary/*") == []
    # a consumer resolves the REF through the store alone
    doc = read_pmml_from_update_key_message(KEY_MODEL_REF, ref)
    assert doc is not None
    assert pmml_io.get_extension_value(doc, "features") == "4"
    # and the X/Y artifacts load from the same store
    from oryx_tpu.app.als.update import load_features
    model_dir = path.rsplit("/", 1)[0]
    y_ids, Y = load_features(store.join(model_dir, "Y"))
    assert len(y_ids) == Y.shape[0] > 0 and Y.shape[1] == 4
    # ...as do the SLICES (a remote-scheme store can serve a sharded
    # load end-to-end): a 0/1 manager bulk-loads the whole catalog
    from oryx_tpu.app.als.serving_manager import ALSServingModelManager
    mgr = ALSServingModelManager(from_dict(
        {"oryx.serving.model-manager-class": "unused"}))
    mgr.consume_key_message(KEY_MODEL_REF, ref)
    assert mgr.slice_load_fallbacks == 0 and mgr.slice_loads > 0
    assert sorted(mgr.model.Y.all_ids()) == sorted(y_ids)


def test_model_ref_resolves_from_other_process_and_cwd(tmp_path):
    """file:// MODEL-REF published by this process resolves from a
    different process running in a different cwd — the trainer-here /
    serving-there contract (reference: BatchUpdateFunction.java:103-130
    reads the shared filesystem from whichever host runs the layer)."""
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", 3)
    model_uri = f"file://{tmp_path}/models/123/model.pmml.xml"
    pmml_io.write(doc, model_uri)

    other_cwd = tmp_path / "elsewhere"
    other_cwd.mkdir()
    code = (
        "from oryx_tpu.app.pmml_utils import read_pmml_from_update_key_message\n"
        "from oryx_tpu.common import pmml as pmml_io\n"
        f"doc = read_pmml_from_update_key_message('MODEL-REF', {str(model_uri)!r})\n"
        "assert doc is not None\n"
        "print(pmml_io.get_extension_value(doc, 'features'))\n")
    out = subprocess.run(
        [sys.executable, "-c", code], cwd=other_cwd, capture_output=True,
        text=True, env={**os.environ, "PYTHONPATH": os.getcwd()})
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "3"


def test_missing_model_ref_is_tolerated():
    from oryx_tpu.app.pmml_utils import read_pmml_from_update_key_message
    assert read_pmml_from_update_key_message(
        "MODEL-REF", "memory://lake/model/nope.pmml.xml") is None


def test_corrupt_model_ref_is_tolerated(tmp_path):
    """A truncated artifact behind a MODEL-REF returns None with a
    warning, like a missing file — never a raised parse error (the
    consumers replay-from-0 on failure, so a poison ref would loop)."""
    from oryx_tpu.app.pmml_utils import read_pmml_from_update_key_message
    bad = tmp_path / "model.pmml.xml"
    bad.write_text("<PMML version='4.4'><Header/><Extensio")  # truncated
    assert read_pmml_from_update_key_message("MODEL-REF", str(bad)) is None
    # inline corrupt MODEL payloads are tolerated the same way
    assert read_pmml_from_update_key_message("MODEL", "<PMML><unclosed") \
        is None


def test_rename_rejects_cross_scheme_uris(tmp_path):
    """rename() resolves ONE filesystem and reuses it for both ends; a
    cross-scheme move would run against the wrong store (VERDICT Weak
    #7), so it must refuse loudly."""
    src = "memory://bucket/a.txt"
    with store.open_write(src) as f:
        f.write(b"x")
    with pytest.raises(ValueError, match="matching URI schemes"):
        store.rename(src, f"file://{tmp_path}/a.txt")
    with pytest.raises(ValueError, match="matching URI schemes"):
        store.rename(f"file://{tmp_path}/a.txt", src)
    # the refused rename moved nothing
    assert store.exists(src)
