"""Request micro-batcher tests (SURVEY §2.14 P6: concurrent requests
coalesce into one device dispatch; reference contrast:
ServingLayer.java:235 thread-pool fan-out)."""

import threading
import time
import urllib.request

import numpy as np
import pytest

from oryx_tpu.app.als.serving_model import ALSServingModel
from oryx_tpu.bench.load import StaticModelManager
from oryx_tpu.common.config import from_dict
from oryx_tpu.lambda_rt.serving import ServingLayer
from oryx_tpu.serving.batcher import TopNBatcher


def _small_model(users=6, items=40, features=8, seed=5):
    rng = np.random.default_rng(seed)
    model = ALSServingModel(features=features, implicit=True)
    for u in range(users):
        model.set_user_vector(f"u{u}",
                              rng.standard_normal(features).astype(np.float32))
    for i in range(items):
        model.set_item_vector(f"i{i}",
                              rng.standard_normal(features).astype(np.float32))
    return model


def test_batcher_matches_single_request_path():
    model = _small_model()
    batcher = TopNBatcher()
    try:
        for u in range(6):
            vec = model.get_user_vector(f"u{u}")
            got = batcher.top_n(model, 5, vec, exclude={"i0", "i3"})
            want = model.top_n(5, user_vector=vec, exclude={"i0", "i3"})
            assert [i for i, _ in got] == [i for i, _ in want]
            assert np.allclose([v for _, v in got], [v for _, v in want])
    finally:
        batcher.close()


def test_batcher_concurrent_correctness_and_coalescing():
    model = _small_model()

    in_dispatch = threading.Event()
    release = threading.Event()

    class GatedModel:
        """Delegate that stalls the first dispatch so later submissions
        provably pile up into one drain."""

        def __init__(self, inner):
            self._inner = inner
            self._first = True

        def top_n_batch(self, how_many, vectors, exclude):
            if self._first:
                self._first = False
                in_dispatch.set()
                release.wait(5.0)
            return self._inner.top_n_batch(how_many, vectors, exclude)

    gated = GatedModel(model)
    batcher = TopNBatcher(pipeline=1)  # single drain: coalescing is provable
    results: dict[int, list] = {}

    def submit(idx, uid, how_many):
        results[idx] = batcher.top_n(gated, how_many,
                                     model.get_user_vector(uid))

    try:
        first = threading.Thread(target=submit, args=(0, "u0", 3))
        first.start()
        assert in_dispatch.wait(5.0)
        rest = [threading.Thread(target=submit, args=(i, f"u{i % 6}", 2 + i))
                for i in range(1, 9)]
        for t in rest:
            t.start()
        # the 8 jobs must all be pending before the gate opens
        deadline = time.time() + 5.0
        while len(batcher._pending) < 8 and time.time() < deadline:
            time.sleep(0.005)
        release.set()
        first.join(5.0)
        for t in rest:
            t.join(5.0)
    finally:
        release.set()
        batcher.close()

    assert len(results) == 9
    for i in range(1, 9):
        uid, how_many = f"u{i % 6}", 2 + i
        want = model.top_n(how_many,
                           user_vector=model.get_user_vector(uid))
        assert [x for x, _ in results[i]] == [x for x, _ in want]
        assert np.allclose([v for _, v in results[i]],
                           [v for _, v in want], rtol=1e-4)
    # everything after the gate went through as one coalesced drain
    assert max(batcher.batch_sizes) == 8


def test_batcher_propagates_errors():
    class Boom:
        def top_n_batch(self, *a, **k):
            raise ValueError("boom")

    batcher = TopNBatcher()
    try:
        with pytest.raises(ValueError, match="boom"):
            batcher.top_n(Boom(), 3, np.zeros(4, np.float32))
    finally:
        batcher.close()


def test_top_n_batch_empty_batch():
    model = _small_model()
    assert model.top_n_batch(5, np.zeros((0, 8), np.float32)) == []


def test_batcher_degrades_gracefully_after_close():
    batcher = TopNBatcher()
    batcher.close()
    model = _small_model()
    vec = model.get_user_vector("u0")
    got = batcher.top_n(model, 3, vec)
    want = model.top_n(3, user_vector=vec)
    assert [i for i, _ in got] == [i for i, _ in want]


class BatcherMockManager(StaticModelManager):
    model = None


def test_http_recommend_goes_through_batcher():
    BatcherMockManager.model = _small_model(users=20, items=100)
    cfg = from_dict({
        "oryx.serving.model-manager-class":
            "tests.test_batcher.BatcherMockManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.input-topic.broker": None,
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": None,
        "oryx.update-topic.broker": None,
        "oryx.update-topic.message.topic": None,
    })
    layer = ServingLayer(cfg, port=0)
    layer.start()
    try:
        base = f"http://127.0.0.1:{layer.port}"
        errs = []

        def hit(u):
            try:
                with urllib.request.urlopen(
                        f"{base}/recommend/u{u}?howMany=4", timeout=10) as r:
                    assert r.status == 200
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=hit, args=(u % 20,))
                   for u in range(40)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(15.0)
        assert not errs
        # the shared batcher saw the traffic
        assert sum(layer.top_n_batcher.batch_sizes) == 40
    finally:
        layer.close()


def test_pacing_coalesces_under_slow_device():
    """When each dispatch is slow (big model), free dispatcher threads
    must NOT shred the queue into minimal batches: pacing at the
    measured service rate makes concurrent requests coalesce."""
    import time as _time

    class SlowModel:
        def __init__(self, model):
            self.model = model

        def top_n_batch(self, how_many, vectors, exclude=None):
            _time.sleep(0.05)  # 50 ms per dispatch, like a 5M-item scan
            return self.model.top_n_batch(how_many, vectors, exclude)

    model = _small_model(items=50, features=4)
    slow = SlowModel(model)
    batcher = TopNBatcher(pipeline=32)
    try:
        results = [None] * 80
        def call(i):
            results[i] = batcher.top_n(
                slow, 3, np.asarray([1, 0, 0, 0], np.float32) * (i + 1))
        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(80)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(r is not None and len(r) == 3 for r in results)
        # without pacing, 32 idle dispatchers produce ~80 batches of ~1;
        # with pacing the tail coalesces into service-interval drains
        sizes = batcher.batch_sizes
        assert sum(sizes) == 80
        assert max(sizes) >= 4, sizes
        assert len(sizes) <= 40, sizes
    finally:
        batcher.close()


def test_pacing_relearns_after_hot_swap():
    """The service-rate estimate must relearn DOWNWARD when a big model
    is hot-swapped for a small one — otherwise pacing stays locked at
    the old model's interval and serializes dispatches forever."""
    import time as _time

    class SerialDevice:
        """Device-like: executions serialize behind one lock."""

        def __init__(self, model):
            self.model = model
            self.exec_s = 0.06
            self.lock = threading.Lock()

        def top_n_batch(self, hm, v, e=None):
            with self.lock:
                _time.sleep(self.exec_s)
            return self.model.top_n_batch(hm, v, e)

    model = _small_model(items=50, features=4)
    mm = SerialDevice(model)
    batcher = TopNBatcher(pipeline=8)
    try:
        def load(seconds, workers=12):
            stop = time.monotonic() + seconds
            def w():
                while time.monotonic() < stop:
                    batcher.top_n(mm, 3, np.zeros(4, np.float32))
            ts = [threading.Thread(target=w) for _ in range(workers)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()

        load(2.0)
        ewma_slow = batcher._exec_ewma
        assert ewma_slow > 0.02, ewma_slow  # learned the service time
        mm.exec_s = 0.001
        load(1.2)
        assert batcher._exec_ewma < ewma_slow / 3, \
            (ewma_slow, batcher._exec_ewma)
    finally:
        batcher.close()


def test_metrics_surface_exposes_batcher_and_fallback_state():
    """/metrics reports the pacing/batching internals and the streaming
    top-k certificate-fallback counter."""
    import json as _json
    import urllib.request

    BatcherMockManager.model = _small_model(users=4, items=30)
    cfg = from_dict({
        "oryx.serving.model-manager-class":
            "tests.test_batcher.BatcherMockManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.input-topic.broker": None,
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": None,
        "oryx.update-topic.broker": None,
        "oryx.update-topic.message.topic": None,
    })
    layer = ServingLayer(cfg, port=0)
    layer.start()
    try:
        base = f"http://127.0.0.1:{layer.port}"
        for u in range(4):
            with urllib.request.urlopen(f"{base}/recommend/u{u}",
                                        timeout=10) as r:
                assert r.status == 200
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            m = _json.loads(r.read())
        sb = m["scoring_batcher"]
        assert sb["dispatches"] >= 4 and sb["mean_recent_batch"] >= 1
        assert sb["service_time_ms"] >= 0
        assert sb["in_flight_target"] >= 1
        assert m["model_metrics"]["twophase_fallbacks"] == 0
        assert m["model_metrics"]["items"] == 30
    finally:
        layer.close()


def test_close_submit_race_degrades_to_unbatched():
    """Shutdown race (batcher.top_n's stopped branch): keep-alive
    handler threads outliving close() must get a correct unbatched
    answer, never a 500."""
    model = _small_model()
    batcher = TopNBatcher(pipeline=2)
    batcher.close()
    vec = model.get_user_vector("u0")
    got = batcher.top_n(model, 4, vec, exclude={"i1"})
    want = model.top_n(4, user_vector=vec, exclude={"i1"})
    assert [i for i, _ in got] == [i for i, _ in want]


def test_concurrent_close_and_submit_never_errors():
    """Hammer submits from many threads while close() lands mid-stream:
    every request must complete correctly through either the batched or
    the degraded path."""
    model = _small_model()
    batcher = TopNBatcher(pipeline=4)
    errors: list[BaseException] = []
    results: list[int] = []
    start = threading.Event()

    def worker(uid):
        vec = model.get_user_vector(uid)
        start.wait(5.0)
        for _ in range(20):
            try:
                got = batcher.top_n(model, 3, vec)
                assert len(got) == 3
                results.append(1)
            except BaseException as e:  # noqa: BLE001 — recorded
                errors.append(e)

    threads = [threading.Thread(target=worker, args=(f"u{i % 6}",))
               for i in range(8)]
    for t in threads:
        t.start()
    start.set()
    # close lands while workers are mid-flight
    batcher.close()
    for t in threads:
        t.join(10.0)
    assert not errors
    assert len(results) == 8 * 20


def test_deadline_expired_at_submit_is_rejected():
    from oryx_tpu.resilience.policy import Deadline, DeadlineExceeded

    model = _small_model()
    batcher = TopNBatcher()
    try:
        with pytest.raises(DeadlineExceeded):
            batcher.top_n(model, 3, model.get_user_vector("u0"),
                          deadline=Deadline.after(0.0))
        assert batcher.stats()["deadline_rejects"] == 1
        # an ample deadline is untouched
        got = batcher.top_n(model, 3, model.get_user_vector("u0"),
                            deadline=Deadline.after(30.0))
        assert len(got) == 3
    finally:
        batcher.close()


def test_deadline_expiring_while_queued_is_shed_at_dispatch():
    """A job whose budget runs out while it waits behind a stalled
    dispatch is shed (DeadlineExceeded) instead of being scored."""
    from oryx_tpu.resilience.policy import Deadline, DeadlineExceeded

    model = _small_model()
    in_dispatch = threading.Event()
    release = threading.Event()

    class GatedModel:
        def __init__(self, inner):
            self._inner = inner
            self._first = True

        def top_n_batch(self, how_many, vectors, exclude):
            if self._first:
                self._first = False
                in_dispatch.set()
                release.wait(10.0)
            return self._inner.top_n_batch(how_many, vectors, exclude)

    gated = GatedModel(model)
    batcher = TopNBatcher(pipeline=1)
    outcome: dict = {}

    def stalled_submit():
        outcome["first"] = batcher.top_n(
            gated, 3, model.get_user_vector("u0"))

    def doomed_submit():
        deadline = Deadline.after(0.05)
        try:
            batcher.top_n(gated, 3, model.get_user_vector("u1"),
                          deadline=deadline)
            outcome["second"] = "scored"
        except DeadlineExceeded:
            outcome["second"] = "shed"

    try:
        first = threading.Thread(target=stalled_submit)
        first.start()
        assert in_dispatch.wait(5.0)
        # valid at submit, expired by the time the drain dispatches
        second = threading.Thread(target=doomed_submit)
        second.start()
        deadline = time.monotonic() + 5.0
        while not batcher._pending and time.monotonic() < deadline:
            time.sleep(0.002)
        # hold the gate until the queued job's budget is provably gone
        expiry = time.monotonic() + 0.06
        while time.monotonic() < expiry:
            time.sleep(0.005)
        release.set()
        first.join(5.0)
        second.join(5.0)
    finally:
        release.set()
        batcher.close()

    assert len(outcome["first"]) == 3
    assert outcome["second"] == "shed"
    assert batcher.deadline_rejects >= 1
