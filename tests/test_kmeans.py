"""k-means app tests (reference analogs: KMeansUpdateIT,
KMeansSpeedIT, KMeansServingModelManagerIT, ClusterInfo/KMeansUtils/
KMeansPMMLUtils unit tests, the four eval-index tests)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from oryx_tpu.app.kmeans import evaluation
from oryx_tpu.app.kmeans import pmml as kmeans_pmml
from oryx_tpu.app.kmeans.common import (ClusterInfo, assign_points,
                                        closest_cluster,
                                        features_from_tokens)
from oryx_tpu.app.kmeans.serving import (KMeansServingModel,
                                         KMeansServingModelManager)
from oryx_tpu.app.kmeans.speed import KMeansSpeedModelManager
from oryx_tpu.app.kmeans.trainer import train_kmeans
from oryx_tpu.app.kmeans.update import KMeansUpdate
from oryx_tpu.app.schema import InputSchema
from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_MODEL, KEY_UP, KeyMessage


def _schema(n=2):
    return InputSchema(from_dict({"oryx.input-schema.num-features": n,
                                  "oryx.input-schema.numeric-features":
                                      [str(i) for i in range(n)]}))


def _blobs(n_per=50, seed=0):
    rng = np.random.default_rng(seed)
    cs = np.array([[0.0, 0.0], [10.0, 0.0], [0.0, 10.0]])
    pts = np.concatenate([c + rng.standard_normal((n_per, 2)) * 0.5
                          for c in cs]).astype(np.float32)
    return pts, cs


# -- ClusterInfo / assignment ------------------------------------------------

def test_cluster_info_moving_average_update():
    c = ClusterInfo(0, [1.0, 1.0], 2)
    c.update([4.0, 4.0], 1)
    # c' = c + (1/3)(p - c) = 2.0
    np.testing.assert_allclose(c.center, [2.0, 2.0])
    assert c.count == 3


def test_closest_cluster_and_batch_assign_agree():
    pts, cs = _blobs()
    clusters = [ClusterInfo(i, cs[i], 1) for i in range(3)]
    idx, dist = assign_points(pts, cs.astype(np.float32))
    for p, i, d in zip(pts[::17], idx[::17], dist[::17]):
        ci, cd = closest_cluster(clusters, p)
        assert ci.id == i
        np.testing.assert_allclose(cd, d, rtol=1e-4)


def test_features_from_tokens_skips_inactive():
    schema = InputSchema(from_dict({
        "oryx.input-schema.feature-names": ["id", "a", "b"],
        "oryx.input-schema.id-features": ["id"],
        "oryx.input-schema.numeric-features": ["a", "b"]}))
    vec = features_from_tokens(["x1", "2.0", "3.0"], schema)
    np.testing.assert_allclose(vec, [2.0, 3.0])


# -- trainer -----------------------------------------------------------------

@pytest.mark.parametrize("init", ["k-means||", "random"])
def test_train_kmeans_recovers_blobs(init):
    pts, cs = _blobs()
    clusters = train_kmeans(pts, k=3, iterations=20, runs=2,
                            initialization=init, seed=42)
    # each true center must have exactly one found center nearby
    matched = set()
    for want in cs:
        dists = [float(np.linalg.norm(c.center - want)) for c in clusters]
        j = int(np.argmin(dists))
        assert dists[j] < 0.5 and j not in matched
        matched.add(j)
    assert sum(c.count for c in clusters) == len(pts)


# -- evals -------------------------------------------------------------------

def test_eval_indices_prefer_true_clustering():
    pts, cs = _blobs()
    good = [ClusterInfo(i, cs[i], 1) for i in range(3)]
    bad_cs = np.array([[5.0, 5.0], [5.2, 5.0], [4.8, 5.2]])
    bad = [ClusterInfo(i, bad_cs[i], 1) for i in range(3)]
    for strategy in evaluation.EVAL_STRATEGIES:
        g = evaluation.evaluate(strategy, good, pts)
        b = evaluation.evaluate(strategy, bad, pts)
        assert g > b, strategy


def test_silhouette_bounds_and_singletons():
    pts, cs = _blobs(n_per=20)
    clusters = [ClusterInfo(i, cs[i], 1) for i in range(3)]
    s = evaluation.silhouette_coefficient(clusters, pts)
    assert -1.0 <= s <= 1.0
    assert s > 0.5  # well-separated blobs


# -- PMML --------------------------------------------------------------------

def test_clustering_pmml_roundtrip():
    schema = _schema()
    clusters = [ClusterInfo(0, [1.0, 2.0], 10), ClusterInfo(1, [3.5, -1.25], 4)]
    doc = kmeans_pmml.clusters_to_pmml(clusters, schema)
    s = pmml_io.to_string(doc)
    back = kmeans_pmml.read_clusters(pmml_io.from_string(s))
    assert [c.id for c in back] == [0, 1]
    assert [c.count for c in back] == [10, 4]
    np.testing.assert_allclose(back[1].center, [3.5, -1.25])
    kmeans_pmml.validate_pmml_vs_schema(doc, schema)
    with pytest.raises(ValueError):
        kmeans_pmml.validate_pmml_vs_schema(doc, _schema(3))


# -- batch update through the ML loop ---------------------------------------

def _batch_config(tmp_path, k=3):
    return from_dict({
        "oryx.ml.eval.test-fraction": 0.2,
        "oryx.ml.eval.candidates": 1,
        "oryx.ml.eval.parallelism": 1,
        "oryx.ml.eval.threshold": None,
        "oryx.update-topic.message.max-size": 1 << 24,
        "oryx.kmeans.iterations": 15,
        "oryx.kmeans.initialization-strategy": "k-means||",
        "oryx.kmeans.evaluation-strategy": "SILHOUETTE",
        "oryx.kmeans.runs": 1,
        "oryx.kmeans.hyperparams.k": k,
        "oryx.input-schema.num-features": 2,
        "oryx.input-schema.numeric-features": ["0", "1"],
    })


def test_kmeans_update_builds_and_evaluates(tmp_path):
    pts, _ = _blobs()
    data = [KeyMessage(None, f"{p[0]},{p[1]}") for p in pts]
    update = KMeansUpdate(_batch_config(tmp_path))
    doc = update.build_model(data, [3], str(tmp_path))
    assert doc is not None
    clusters = kmeans_pmml.read_clusters(doc)
    assert len(clusters) == 3
    ev = update.evaluate(doc, str(tmp_path), data[:30], data[30:])
    assert ev > 0.5  # silhouette of well-separated blobs


def test_kmeans_update_rejects_categorical():
    cfg = from_dict({
        "oryx.ml.eval.test-fraction": 0.2,
        "oryx.ml.eval.candidates": 1,
        "oryx.ml.eval.parallelism": 1,
        "oryx.ml.eval.threshold": None,
        "oryx.update-topic.message.max-size": 1 << 24,
        "oryx.kmeans.iterations": 5,
        "oryx.kmeans.initialization-strategy": "k-means||",
        "oryx.kmeans.evaluation-strategy": "SSE",
        "oryx.kmeans.runs": 1,
        "oryx.kmeans.hyperparams.k": 2,
        "oryx.input-schema.num-features": 2,
        "oryx.input-schema.categorical-features": ["1"],
    })
    with pytest.raises(ValueError):
        KMeansUpdate(cfg)


# -- speed -------------------------------------------------------------------

def _kmeans_model_message():
    schema = _schema()
    clusters = [ClusterInfo(0, [0.0, 0.0], 10),
                ClusterInfo(1, [10.0, 10.0], 10)]
    return pmml_io.to_string(kmeans_pmml.clusters_to_pmml(clusters, schema))


def test_speed_manager_emits_center_updates():
    cfg = from_dict({"oryx.input-schema.num-features": 2,
                     "oryx.input-schema.numeric-features": ["0", "1"]})
    mgr = KMeansSpeedModelManager(cfg)
    mgr.consume_key_message(KEY_MODEL, _kmeans_model_message())
    assert mgr.model is not None
    data = [KeyMessage(None, "0.5,0.5"), KeyMessage(None, "-0.5,-0.5"),
            KeyMessage(None, "10.5,10.5")]
    ups = list(mgr.build_updates(data))
    assert len(ups) == 2
    parsed = [json.loads(u) for u in ups]
    by_id = {p[0]: p for p in parsed}
    assert by_id[0][2] == 12  # 10 + 2 points
    assert by_id[1][2] == 11
    # cluster 0: mean of (.5,.5),(-.5,-.5)=(0,0), center stays ~0
    np.testing.assert_allclose(by_id[0][1], [0.0, 0.0], atol=1e-6)
    # UP messages are ignored when consumed back
    mgr.consume_key_message(KEY_UP, ups[0])


# -- serving -----------------------------------------------------------------

def test_serving_manager_model_and_up():
    cfg = from_dict({"oryx.input-schema.num-features": 2,
                     "oryx.input-schema.numeric-features": ["0", "1"],
                     "oryx.serving.api.read-only": False})
    mgr = KMeansServingModelManager(cfg)
    mgr.consume_key_message(KEY_UP, "[0,[1.0,1.0],5]")  # ignored, no model
    assert mgr.get_model() is None
    mgr.consume_key_message(KEY_MODEL, _kmeans_model_message())
    model = mgr.get_model()
    assert model.nearest_cluster_id(["1.0", "0.5"]) == 0
    assert model.nearest_cluster_id(["9.0", "9.5"]) == 1
    mgr.consume_key_message(KEY_UP, "[1,[20.0,20.0],42]")
    assert model.get_cluster(1).count == 42
    np.testing.assert_allclose(model.get_cluster(1).center, [20.0, 20.0])
    assert model.nearest_cluster_ids([["1.0", "0.5"], ["19.0", "19.5"]]) \
        == [0, 1]


# -- REST endpoints over live HTTP ------------------------------------------

class MockKMeansManager(KMeansServingModelManager):
    pass


@pytest.fixture(scope="module")
def kmeans_server():
    from oryx_tpu.lambda_rt.serving import ServingLayer
    from oryx_tpu.kafka.inproc import get_broker
    cfg = from_dict({
        "oryx.serving.model-manager-class":
            "tests.test_kmeans.MockKMeansManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.clustering",
        "oryx.input-topic.broker": "memory://kmeans-test",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "KInput",
        "oryx.update-topic.broker": "memory://kmeans-test",
        "oryx.update-topic.message.topic": "KUpdate",
        "oryx.input-schema.num-features": 2,
        "oryx.input-schema.numeric-features": ["0", "1"],
    })
    broker = get_broker("kmeans-test")
    broker.send("KUpdate", KEY_MODEL, _kmeans_model_message())
    layer = ServingLayer(cfg, port=0)
    layer.start()
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{layer.port}/ready", timeout=2)
            break
        except Exception:
            time.sleep(0.1)
    yield layer, broker
    layer.close()


def _get(layer, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{layer.port}{path}", timeout=10)


def test_assign_endpoint(kmeans_server):
    layer, _ = kmeans_server
    assert _get(layer, "/assign/0.4,0.6").read().decode().strip('"') == "0"
    assert _get(layer, "/assign/9.5,10.2").read().decode().strip('"') == "1"


def test_assign_post_bulk(kmeans_server):
    layer, _ = kmeans_server
    req = urllib.request.Request(
        f"http://127.0.0.1:{layer.port}/assign",
        data=b"0.4,0.6\n9.5,10.2\n", method="POST")
    out = json.loads(urllib.request.urlopen(req, timeout=10).read())
    assert out == ["0", "1"]


def test_distance_to_nearest_endpoint(kmeans_server):
    layer, _ = kmeans_server
    d = float(json.loads(_get(layer, "/distanceToNearest/0,1").read()))
    np.testing.assert_allclose(d, 1.0, atol=1e-5)


def test_add_endpoint_writes_input(kmeans_server):
    layer, broker = kmeans_server
    before = broker.latest_offset("KInput")
    _get(layer, "/add/1.0,2.0")
    assert broker.latest_offset("KInput") == before + 1


def test_kmeans_parallel_init_large_magnitude_features():
    """k-means|| init must survive un-normalized data (e.g. an
    epoch-timestamp-scale feature): the padded assignment kernel
    duplicates a real candidate instead of using a sentinel whose dot
    products would overflow float32."""
    rng = np.random.default_rng(1)
    pts = rng.standard_normal((500, 3)).astype(np.float32)
    pts[:, 0] += 1.7e9
    clusters = train_kmeans(pts, k=3, iterations=3, seed=4)
    assert len(clusters) == 3
    assert all(np.isfinite(c.center).all() for c in clusters)
