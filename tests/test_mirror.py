"""Cross-region mirror unit tests (cluster/mirror.py, ISSUE 11): the
exactly-once-effective replay contract — origin headers, loop
prevention, the checkpoint + dedup fence across a crash — plus the
measured-staleness gauges, the kind="gauge" SLO objective, the
region-pinned membership rejection, and the headless /metrics
resilience block.  All in-process over memory:// brokers; the
real-process two-region chaos IT is tests/test_region_it.py."""

from __future__ import annotations

import json
import time
import urllib.request
import uuid

import pytest

from oryx_tpu.cluster import mirror as mirror_mod
from oryx_tpu.cluster.membership import Heartbeat, MembershipRegistry
from oryx_tpu.cluster.mirror import (H_ORIGIN_OFFSET, H_ORIGIN_PARTITION,
                                     H_ORIGIN_REGION, MirrorCheckpoint,
                                     MirrorLayer)
from oryx_tpu.common.clock import ManualClock
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_MODEL, KEY_UP
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.metrics import MetricsRegistry
from oryx_tpu.obs.slo import SloEngine, SloObjective
from oryx_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _mirror_config(tmp_path, src_name, dst_name,
                   src_region="west", dst_region="east", **extra):
    overlay = {
        "oryx.cluster.region.name": dst_region,
        "oryx.cluster.region.mirror.source-broker":
            f"memory://{src_name}",
        "oryx.cluster.region.mirror.source-region": src_region,
        "oryx.cluster.region.mirror.checkpoint-dir":
            str(tmp_path / f"ckpt-{dst_name}"),
        "oryx.update-topic.broker": f"memory://{dst_name}",
        "oryx.resilience.retry.max-attempts": 2,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
    }
    overlay.update(extra)
    return from_dict(overlay)


def _names():
    """Unique broker names per test (the in-process registry is
    process-global)."""
    tag = uuid.uuid4().hex[:8]
    return f"mw-{tag}", f"me-{tag}"


def _records(broker, topic="OryxUpdate"):
    end = broker.latest_offset(topic)
    return broker.read_range(topic, 0, end)


UP1 = '["X","u1",[1.0,2.0]]'
UP2 = '["Y","i1",[3.0,4.0],["u1"]]'


def test_replay_stamps_origin_headers_and_preserves_existing(tmp_path):
    src_name, dst_name = _names()
    src, dst = get_broker(src_name), get_broker(dst_name)
    m = MirrorLayer(_mirror_config(tmp_path, src_name, dst_name))
    try:
        src.send("OryxUpdate", KEY_UP, UP1, headers={"ts": "1700"})
        # an already-mirrored record (multi-hop): its birth coordinates
        # must be preserved untouched, not re-stamped at this hop
        src.send("OryxUpdate", KEY_UP, UP2, headers={
            H_ORIGIN_REGION: "south", H_ORIGIN_PARTITION: "0",
            H_ORIGIN_OFFSET: "99"})
        assert m.poll_once() == 2
        got = _records(dst)
        assert [km.key for km in got] == [KEY_UP, KEY_UP]
        assert got[0].headers == {"ts": "1700",
                                  H_ORIGIN_REGION: "west",
                                  H_ORIGIN_PARTITION: "0",
                                  H_ORIGIN_OFFSET: "0"}
        assert got[1].headers[H_ORIGIN_REGION] == "south"
        assert got[1].headers[H_ORIGIN_OFFSET] == "99"
        # a second poll replays nothing — the position advanced
        assert m.poll_once() == 0
        assert len(_records(dst)) == 2
    finally:
        m.close()


def test_heartbeats_and_looped_records_are_dropped(tmp_path):
    src_name, dst_name = _names()
    src, dst = get_broker(src_name), get_broker(dst_name)
    m = MirrorLayer(_mirror_config(tmp_path, src_name, dst_name))
    try:
        src.send("OryxUpdate", "HB", '{"replica":"r1"}')
        # born in the DESTINATION region, bounced back through the
        # opposite mirror: must never re-enter (A⇄B no ping-pong)
        src.send("OryxUpdate", KEY_UP, UP1, headers={
            H_ORIGIN_REGION: "east", H_ORIGIN_PARTITION: "0",
            H_ORIGIN_OFFSET: "5"})
        src.send("OryxUpdate", KEY_UP, UP2)
        assert m.poll_once() == 1
        got = _records(dst)
        assert len(got) == 1 and got[0].message == UP2
        counters = m.metrics.counters_snapshot()
        assert counters["mirror_heartbeat_drops"] == 1
        assert counters["mirror_loop_drops"] == 1
        assert counters["mirror_records_replayed"] == 1
    finally:
        m.close()


def test_checkpoint_round_trips_through_the_store(tmp_path):
    ck = MirrorCheckpoint(str(tmp_path / "ck"))
    ck.source[0] = 17
    ck.advance_fence("west", 0, 41)
    ck.dest_scanned[0] = 9
    ck.save()
    ck2 = MirrorCheckpoint(str(tmp_path / "ck"))
    assert ck2.source == {0: 17}
    assert ck2.watermarks == {("west", 0): 41}
    assert ck2.dest_scanned == {0: 9}
    assert ck2.behind_fence("west", 0, 41)
    assert ck2.behind_fence("west", 0, 40)
    assert not ck2.behind_fence("west", 0, 42)
    assert not ck2.behind_fence("north", 0, 1)
    # the fence never rewinds
    ck2.advance_fence("west", 0, 3)
    assert ck2.watermarks[("west", 0)] == 41


def test_crash_between_replay_and_checkpoint_does_not_duplicate(tmp_path):
    """The headline fence: kill the mirror AFTER a batch's sends but
    BEFORE its checkpoint write — the restarted mirror re-reads the
    batch and must skip every record (counted), leaving exactly one
    copy of each fold-in in the destination log."""
    src_name, dst_name = _names()
    src, dst = get_broker(src_name), get_broker(dst_name)
    cfg = _mirror_config(tmp_path, src_name, dst_name)
    src.send("OryxUpdate", KEY_MODEL, "<PMML/>")
    src.send("OryxUpdate", KEY_UP, UP1)
    src.send("OryxUpdate", KEY_UP, UP2)

    m1 = MirrorLayer(cfg)
    m1.recover()
    faults.inject("mirror-crash-mid-replay", mode="crash", times=1)
    with pytest.raises(faults.InjectedCrash):
        m1.poll_once()
    assert faults.fired("mirror-crash-mid-replay") == 1
    # the dangerous intermediate state: all three records SENT, source
    # position and fence NOT durably advanced
    assert len(_records(dst)) == 3
    assert MirrorCheckpoint(str(tmp_path / f"ckpt-{dst_name}")
                            ).source == {}

    # "restart": recovery scans the destination log and re-derives the
    # fence; the re-read batch dedups instead of re-sending
    m2 = MirrorLayer(cfg)
    try:
        assert m2.recover() == 3
        assert m2.poll_once() == 0
        counters = m2.metrics.counters_snapshot()
        assert counters["mirror_dedup_skips"] == 3
        got = _records(dst)
        assert len(got) == 3  # no duplicated fold-in effects
        assert [km.message for km in got] == ["<PMML/>", UP1, UP2]
        # and the fence is durable now: a third incarnation re-reads
        # nothing at all
        assert m2.poll_once() == 0
    finally:
        m2.close()
        m1.close()


def test_two_mirrors_a_b_never_ping_pong(tmp_path):
    """A⇄B loop test: N records born in A replay into B exactly once;
    B's mirror sees its copies, drops every one by origin, and the
    total record count across both regions is bounded forever."""
    a_name, b_name = _names()
    a, b = get_broker(a_name), get_broker(b_name)
    ab = MirrorLayer(_mirror_config(tmp_path, a_name, b_name,
                                    src_region="west",
                                    dst_region="east"))
    ba = MirrorLayer(_mirror_config(tmp_path, b_name, a_name,
                                    src_region="east",
                                    dst_region="west"))
    try:
        n = 5
        for i in range(n):
            a.send("OryxUpdate", KEY_UP, f'["X","u{i}",[1.0]]')
        b.send("OryxUpdate", KEY_UP, '["X","bu",[2.0]]')  # born in B
        for _ in range(4):  # several full rounds: a loop would grow
            ab.poll_once()
            ba.poll_once()
        a_recs, b_recs = _records(a), _records(b)
        # A: its n originals + B's one mirrored record.  B: its one
        # original + A's n mirrored records.  Nothing ping-ponged.
        assert len(a_recs) == n + 1
        assert len(b_recs) == n + 1
        assert ba.metrics.counters_snapshot()["mirror_loop_drops"] == n
        assert ab.metrics.counters_snapshot()["mirror_loop_drops"] == 1
        # every mirrored record names its true birth region
        assert {km.headers[H_ORIGIN_REGION] for km in b_recs
                if km.headers and H_ORIGIN_REGION in km.headers} \
            == {"west"}
        assert {km.headers[H_ORIGIN_REGION] for km in a_recs
                if km.headers and H_ORIGIN_REGION in km.headers} \
            == {"east"}
    finally:
        ab.close()
        ba.close()


def test_staleness_gauges_climb_through_a_partitioned_link(tmp_path):
    # virtual clock: the climb windows are advanced by hand, so the
    # "staleness grew" assertions can never flake under scheduler
    # load — and the climb is exact, not merely monotone.  Pinned
    # start values: with a real-time start, (t + 0.04) - t can floor
    # to 39 ms for unlucky t, and the gauge is int-truncated
    clock = ManualClock(start_monotonic=0.0,
                        start_time=1_700_000_000.0)
    src_name, dst_name = _names()
    src = get_broker(src_name)
    m = MirrorLayer(_mirror_config(tmp_path, src_name, dst_name),
                    clock=clock)
    try:
        src.send("OryxUpdate", KEY_UP, UP1,
                 headers={"ts": str(int(clock.time() * 1000) - 250)})
        assert m.poll_once() == 1
        # the drained batch carried a ts stamp: staleness is MEASURED
        assert m._last_batch_staleness_ms >= 250
        assert m.poll_once() == 0  # caught up: confirmation stamped
        s0 = m.metrics.gauges_snapshot()["cross_region_staleness_ms"]
        # partition the link: polls fail, lag holds, staleness climbs
        faults.inject("mirror-link-partition", mode="error", times=None)
        src.send("OryxUpdate", KEY_UP, UP2)
        for _ in range(2):
            with pytest.raises(ConnectionError):
                m.poll_once()
        clock.advance(0.04)
        g1 = m.metrics.gauges_snapshot()
        assert g1["cross_region_staleness_ms"] >= s0 + 30
        assert g1["mirror_lag_records"] == 1
        clock.advance(0.04)
        g2 = m.metrics.gauges_snapshot()
        assert g2["cross_region_staleness_ms"] \
            >= g1["cross_region_staleness_ms"] + 30
        # heal: one poll drains the backlog and the gauges collapse
        faults.clear("mirror-link-partition")
        assert m.poll_once() == 1
        assert m.poll_once() == 0
        g3 = m.metrics.gauges_snapshot()
        assert g3["mirror_lag_records"] == 0
        assert g3["cross_region_staleness_ms"] \
            < g2["cross_region_staleness_ms"]
    finally:
        m.close()


def test_link_failure_holds_position_and_counts(tmp_path):
    src_name, dst_name = _names()
    src, dst = get_broker(src_name), get_broker(dst_name)
    m = MirrorLayer(_mirror_config(tmp_path, src_name, dst_name))
    try:
        src.send("OryxUpdate", KEY_UP, UP1)
        faults.inject("mirror-link-partition", mode="error", times=3)
        for _ in range(3):
            with pytest.raises(ConnectionError):
                m.poll_once()
        # the fault exhausted: the very next poll replays the backlog —
        # nothing was lost or skipped while the link was down
        assert m.poll_once() == 1
        assert len(_records(dst)) == 1
    finally:
        m.close()


def test_mirror_config_validation(tmp_path):
    with pytest.raises(ValueError, match="region.name"):
        MirrorLayer(from_dict({
            "oryx.cluster.region.mirror.source-broker": "memory://x"}))
    with pytest.raises(ValueError, match="source-broker"):
        MirrorLayer(from_dict({"oryx.cluster.region.name": "east"}))
    with pytest.raises(ValueError, match="checkpoint-dir"):
        MirrorLayer(from_dict({
            "oryx.cluster.region.name": "east",
            "oryx.cluster.region.mirror.source-broker": "memory://x",
            "oryx.update-topic.broker": "memory://y"}))
    with pytest.raises(ValueError, match="self-mirror"):
        MirrorLayer(from_dict({
            "oryx.cluster.region.name": "east",
            "oryx.cluster.region.mirror.source-broker": "memory://y",
            "oryx.cluster.region.mirror.checkpoint-dir":
                str(tmp_path / "ck"),
            "oryx.update-topic.broker": "memory://y"}))


def test_malformed_origin_headers_treated_as_source_born(tmp_path):
    src_name, dst_name = _names()
    src, dst = get_broker(src_name), get_broker(dst_name)
    m = MirrorLayer(_mirror_config(tmp_path, src_name, dst_name))
    try:
        src.send("OryxUpdate", KEY_UP, UP1, headers={
            H_ORIGIN_REGION: "south", H_ORIGIN_OFFSET: "not-a-number"})
        assert m.poll_once() == 1
        got = _records(dst)[0]
        # re-stamped at this hop: identity must stay machine-usable
        assert got.headers[H_ORIGIN_REGION] == "west"
        assert got.headers[H_ORIGIN_OFFSET] == "0"
    finally:
        m.close()


# -- region-pinned membership (multi-region defense in depth) ----------------


def _hb(region=None, replica="r1"):
    return Heartbeat(replica=replica, shard=0, of=1, url="http://x:1",
                     generation=1, ready=True, fraction=1.0,
                     region=region)


def test_registry_rejects_foreign_region_heartbeats():
    reg = MembershipRegistry(ttl_sec=60.0, region="east")
    assert reg.note(_hb(region="east", replica="local"))
    assert not reg.note(_hb(region="west", replica="foreign"))
    assert reg.stale_topology_heartbeats == 1
    # unstamped beats (single-region deployments, older replicas)
    # always merge — back-compat
    assert reg.note(_hb(region=None, replica="legacy"))
    assert sorted(reg.snapshot()["replicas"]) == ["legacy", "local"]


def test_regionless_registry_accepts_any_stamp():
    reg = MembershipRegistry(ttl_sec=60.0)
    assert reg.note(_hb(region="west", replica="w"))
    assert reg.note(_hb(region=None, replica="n"))
    assert reg.stale_topology_heartbeats == 0


def test_heartbeat_json_region_round_trip_and_back_compat():
    hb = _hb(region="east")
    parsed = Heartbeat.from_json(hb.to_json())
    assert parsed.region == "east"
    # a region-less beat serializes WITHOUT the field (wire-compatible
    # with pre-region consumers) and parses back as None
    legacy = _hb(region=None).to_json()
    assert "region" not in json.loads(legacy)
    assert Heartbeat.from_json(legacy).region is None


# -- kind="gauge" SLO objective (the staleness bound as a burn alert) --------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_gauge_slo_objective_pages_on_sustained_breach():
    registry = MetricsRegistry()
    registry.set_gauge("cross_region_staleness_ms", 10.0)
    obj = SloObjective("staleness", kind="gauge", target=0.99,
                       gauge="cross_region_staleness_ms",
                       max_value=5000.0)
    clock = _Clock()
    engine = SloEngine([obj], registry, resolution_sec=15.0,
                       clock=clock)
    engine.evaluate()
    st = engine.status()["objectives"]["staleness"]
    assert st["state"] == "ok" and st["gauge"] \
        == "cross_region_staleness_ms"
    # the region falls behind: sustained ticks over the bound burn the
    # 1%-stale budget orders of magnitude too fast -> page
    registry.set_gauge("cross_region_staleness_ms", 60000.0)
    for _ in range(4):
        clock.t += 16.0
        engine.evaluate()
    assert engine.status()["objectives"]["staleness"]["state"] == "page"
    assert engine.burn_gauge() >= 14.4
    # healed: good ticks past the fast windows clear the page
    registry.set_gauge("cross_region_staleness_ms", 100.0)
    for _ in range(300):
        clock.t += 16.0
        engine.evaluate()
    assert engine.status()["objectives"]["staleness"]["state"] == "ok"


def test_gauge_slo_objective_absent_gauge_casts_no_vote():
    registry = MetricsRegistry()
    obj = SloObjective("staleness", kind="gauge", target=0.99,
                       gauge="never_registered", max_value=100.0)
    clock = _Clock()
    engine = SloEngine([obj], registry, resolution_sec=15.0,
                       clock=clock)
    for _ in range(5):
        clock.t += 16.0
        engine.evaluate()
    st = engine.status()["objectives"]["staleness"]
    assert st["state"] == "ok"
    assert st["windows"]["5m"]["total"] == 0


def test_gauge_slo_objective_requires_gauge_name():
    with pytest.raises(ValueError, match="kind=gauge"):
        SloObjective("bad", kind="gauge", max_value=5.0)


def test_gauge_slo_objective_rejects_watching_the_engines_own_exports():
    # slo_* gauge fns call evaluate() — watching one would deadlock
    # evaluation on its own (non-reentrant) lock
    with pytest.raises(ValueError, match="slo_burn_rate"):
        SloObjective("bad", kind="gauge", gauge="slo_burn_rate",
                     max_value=5.0)


def test_gauge_slo_objective_requires_positive_bound():
    # an implicit max-value of 0 would count every positive reading
    # bad: a page that never clears
    with pytest.raises(ValueError, match="max-value"):
        SloObjective("bad", kind="gauge",
                     gauge="cross_region_staleness_ms")


def test_lag_gauge_is_unknown_until_the_source_is_first_observed(
        tmp_path):
    """A mirror restarted INTO a partition must report lag as None
    (unknown), never a seeded 0 that the failover runbook would read
    as 'caught up'; once the source HAS been observed, a later outage
    holds the last real value."""
    src_name, dst_name = _names()
    get_broker(src_name).send("OryxUpdate", KEY_UP, UP1)
    m = MirrorLayer(_mirror_config(tmp_path, src_name, dst_name))
    real_resolve = mirror_mod.resolve_broker

    def dead_link(uri):
        raise ConnectionError("link down")

    try:
        # dead link from birth: the source has never been reachable
        mirror_mod.resolve_broker = dead_link
        assert m._lag_gauge() is None
        assert m.metrics.gauges_snapshot()["mirror_lag_records"] is None
        # link up: lag becomes a real observation...
        mirror_mod.resolve_broker = real_resolve
        assert m._lag_gauge() == 1
        # ...and a later outage HOLDS it instead of forgetting it
        mirror_mod.resolve_broker = dead_link
        assert m._lag_gauge() == 1
    finally:
        mirror_mod.resolve_broker = real_resolve
        m.close()


def test_engine_from_config_parses_gauge_objective():
    from oryx_tpu.obs.slo import engine_from_config
    cfg = from_dict({
        "oryx.obs.slo.enabled": True,
        "oryx.obs.slo.objectives.staleness.kind": "gauge",
        "oryx.obs.slo.objectives.staleness.gauge":
            "cross_region_staleness_ms",
        "oryx.obs.slo.objectives.staleness.max-value": 5000,
        "oryx.obs.slo.objectives.staleness.target": 0.99,
    })
    engine = engine_from_config(cfg, MetricsRegistry())
    (obj,) = engine.objectives
    assert obj.kind == "gauge"
    assert obj.gauge == "cross_region_staleness_ms"
    assert obj.max_value == 5000.0


# -- headless /metrics surface (ISSUE 11 satellite) --------------------------


def test_obs_server_metrics_exposes_resilience_block(tmp_path):
    """The headless tiers (speed, batch, mirror) run producers behind
    retries/breakers but had no way to SEE them: the side-door
    /metrics must carry the same resilience block the serving tier
    and router expose — and the mirror's /admin/slo must serve its
    staleness objective's alert state on the same port."""
    src_name, dst_name = _names()
    cfg = _mirror_config(
        tmp_path, src_name, dst_name,
        **{"oryx.obs.metrics-port": 0,
           "oryx.obs.slo.enabled": True,
           "oryx.obs.slo.objectives.staleness.kind": "gauge",
           "oryx.obs.slo.objectives.staleness.gauge":
               "cross_region_staleness_ms",
           "oryx.obs.slo.objectives.staleness.max-value": 5000,
           "oryx.obs.slo.objectives.staleness.target": 0.99})
    m = MirrorLayer(cfg)
    try:
        m.obs_server.start()
        port = m.obs_server.port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            snap = json.loads(r.read())
        # the mirror's own named policies are visible where its gauges
        # already were
        assert snap["resilience"]["mirror-replay"]["kind"] == "retry"
        assert snap["resilience"]["mirror-replay-dest"]["state"] \
            == "closed"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/admin/region",
                timeout=10) as r:
            region = json.loads(r.read())
        assert region["region"] == "east"
        assert region["role"] == "mirror"
        assert region["source_region"] == "west"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/admin/slo", timeout=10) as r:
            slo = json.loads(r.read())
        assert slo["objectives"]["staleness"]["kind"] == "gauge"
        assert snap["freshness"]["slo_burn_rate"] is not None
    finally:
        m.close()
