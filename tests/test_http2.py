"""HTTP/2 connector tests: HPACK against the RFC's own vectors, and the
full h2 stack against curl's nghttp2 — a real, independent client
(reference connector parity: ServingLayer.java:202-255)."""

import json
import shutil
import socket
import subprocess
import threading

import numpy as np
import pytest

from oryx_tpu.lambda_rt.hpack import (HpackDecoder, HpackEncoder,
                                      huffman_decode, huffman_encode)

# -- HPACK: RFC 7541 Appendix C ground truth ---------------------------------

RFC_HUFFMAN_VECTORS = [
    ("f1e3c2e5f23a6ba0ab90f4ff", b"www.example.com"),
    ("a8eb10649cbf", b"no-cache"),
    ("25a849e95ba97d7f", b"custom-key"),
    ("25a849e95bb8e8b4bf", b"custom-value"),
    ("6402", b"302"),
    ("aec3771a4b", b"private"),
    ("d07abe941054d444a8200595040b8166e082a62d1bff",
     b"Mon, 21 Oct 2013 20:13:21 GMT"),
    ("9d29ad171863c78f0b97c8e9ae82ae43d3", b"https://www.example.com"),
]


def test_huffman_rfc_vectors_decode_and_encode():
    for hx, want in RFC_HUFFMAN_VECTORS:
        assert huffman_decode(bytes.fromhex(hx)) == want
        assert huffman_encode(want).hex() == hx


def test_huffman_round_trip_fuzz():
    rng = np.random.default_rng(2)
    for _ in range(200):
        raw = bytes(rng.integers(0, 256, rng.integers(0, 60),
                                 dtype=np.uint8))
        assert huffman_decode(huffman_encode(raw)) == raw


def test_hpack_rfc_c3_request_sequence_without_huffman():
    """RFC 7541 C.3: three requests on one connection, dynamic table
    evolving across them."""
    d = HpackDecoder()
    first = bytes.fromhex("828684410f7777772e6578616d706c652e636f6d")
    assert d.decode(first) == [(":method", "GET"), (":scheme", "http"),
                               (":path", "/"),
                               (":authority", "www.example.com")]
    second = bytes.fromhex("828684be58086e6f2d6361636865")
    assert d.decode(second) == [(":method", "GET"), (":scheme", "http"),
                                (":path", "/"),
                                (":authority", "www.example.com"),
                                ("cache-control", "no-cache")]
    third = bytes.fromhex(
        "828785bf400a637573746f6d2d6b65790c637573746f6d2d76616c7565")
    assert d.decode(third) == [(":method", "GET"), (":scheme", "https"),
                               (":path", "/index.html"),
                               (":authority", "www.example.com"),
                               ("custom-key", "custom-value")]


def test_hpack_rfc_c4_request_sequence_with_huffman():
    d = HpackDecoder()
    first = bytes.fromhex("828684418cf1e3c2e5f23a6ba0ab90f4ff")
    assert d.decode(first)[-1] == (":authority", "www.example.com")
    second = bytes.fromhex("828684be5886a8eb10649cbf")
    assert d.decode(second)[-1] == ("cache-control", "no-cache")


def test_hpack_encoder_is_decodable_and_uses_static_indexing():
    enc, dec = HpackEncoder(), HpackDecoder()
    headers = [(":status", "200"), ("content-type", "application/json"),
               ("content-length", "42"), ("x-custom", "v1")]
    block = enc.encode(headers)
    assert dec.decode(block) == headers
    # ":status 200" must be the single static-index byte 0x88
    assert block[0] == 0x88


# -- live h2 against curl/nghttp2 --------------------------------------------

def _serving_app(**app_kwargs):
    from oryx_tpu.app.als.serving_model import ALSServingModel
    from oryx_tpu.bench.load import StaticModelManager
    from oryx_tpu.lambda_rt.http import HttpApp, make_server
    from oryx_tpu.serving import als as als_resources
    from oryx_tpu.serving import framework as framework_resources
    from oryx_tpu.serving.batcher import TopNBatcher

    rng = np.random.default_rng(0)
    model = ALSServingModel(features=6, implicit=True)
    model.Y.bulk_load([f"i{j}" for j in range(80)],
                      rng.standard_normal((80, 6)).astype(np.float32))
    model.X.bulk_load([f"u{j}" for j in range(10)],
                      rng.standard_normal((10, 6)).astype(np.float32))
    import time as _time

    from oryx_tpu.kafka.inproc import InProcTopicProducer

    StaticModelManager.model = model
    batcher = TopNBatcher(pipeline=2)
    producer = InProcTopicProducer(
        f"memory://h2test-{_time.monotonic_ns()}", "In")
    app_kwargs.setdefault("read_only", False)
    app = HttpApp(
        framework_resources.ROUTES + als_resources.ROUTES,
        context={"model_manager": StaticModelManager(),
                 "input_producer": producer, "config": None,
                 "min_model_load_fraction": 0.0,
                 "top_n_batcher": batcher},
        **app_kwargs)
    return app, batcher, make_server


@pytest.fixture
def h2_server():
    app, batcher, make_server = _serving_app()
    server = make_server(app, 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield port
    server.shutdown()
    batcher.close()


def _curl(args: list[str], timeout=20) -> subprocess.CompletedProcess:
    if shutil.which("curl") is None:
        pytest.skip("curl not available")
    return subprocess.run(["curl", "-sS", *args], capture_output=True,
                          text=True, timeout=timeout)


def test_curl_h2c_prior_knowledge_get(h2_server):
    r = _curl(["--http2-prior-knowledge", "-w", "\n%{http_version}",
               f"http://127.0.0.1:{h2_server}/recommend/u0?howMany=3"])
    assert r.returncode == 0, r.stderr
    body, version = r.stdout.rsplit("\n", 1)
    assert version == "2"
    recs = json.loads(body)
    assert len(recs) == 3 and all("id" in x for x in recs)


def test_curl_h2c_matches_h1_response(h2_server):
    h2 = _curl(["--http2-prior-knowledge",
                f"http://127.0.0.1:{h2_server}/recommend/u1?howMany=5"])
    h1 = _curl(["--http1.1",
                f"http://127.0.0.1:{h2_server}/recommend/u1?howMany=5"])
    assert h2.returncode == 0 and h1.returncode == 0
    assert json.loads(h2.stdout) == json.loads(h1.stdout)


def test_curl_h2c_post_body_and_multiple_requests(h2_server):
    # POST /pref with a body (DATA frames), then a GET on a second
    # connection-reused stream; -d forces content-length handling
    r = _curl(["--http2-prior-knowledge", "-X", "POST",
               "-d", "2.5",
               "-o", "/dev/null", "-w", "%{http_code}",
               f"http://127.0.0.1:{h2_server}/pref/u0/i3"])
    # /pref returns 204 No Content on success (reference Preference.java)
    assert r.returncode == 0 and r.stdout == "204", (r.stdout, r.stderr)


def test_multiple_streams_on_one_connection(h2_server):
    """Two sequential streams multiplex over one h2c connection.  Driven
    with a raw-socket client built on our HpackEncoder because curl
    7.88's h2c connection REUSE is broken client-side (its h2 filter
    rewrite; fixed in curl 8.x — reuse over TLS works, see the ALPN
    test); the frames this asserts on were independently validated
    against curl for single transfers."""
    import struct

    from oryx_tpu.lambda_rt import http2 as h2mod

    enc = HpackEncoder()
    with socket.create_connection(("127.0.0.1", h2_server),
                                  timeout=10) as s:
        s.sendall(h2mod.PREFACE)
        s.sendall(b"\x00\x00\x00\x04\x00\x00\x00\x00\x00")  # SETTINGS
        for sid, path in ((1, "/ready"), (3, "/allItemIDs")):
            block = enc.encode([(":method", "GET"), (":path", path),
                                (":scheme", "http"), (":authority", "a")])
            s.sendall(len(block).to_bytes(3, "big") + bytes([1, 0x5])
                      + sid.to_bytes(4, "big") + block)
        got: dict[int, dict] = {}
        body = bytearray()
        r = s.makefile("rb")
        while not (got.get(1, {}).get("done")
                   and got.get(3, {}).get("done")):
            head = r.read(9)
            length = int.from_bytes(head[:3], "big")
            ftype, flags = head[3], head[4]
            sid = int.from_bytes(head[5:9], "big") & 0x7FFFFFFF
            payload = r.read(length)
            if ftype == 1:  # HEADERS
                got.setdefault(sid, {})["status"] = payload[0]
                if flags & 0x1:
                    got[sid]["done"] = True
            elif ftype == 0:  # DATA
                body += payload
                if flags & 0x1:
                    got[sid]["done"] = True
            elif ftype == 4 and not flags & 0x1:
                s.sendall(b"\x00\x00\x00\x04\x01\x00\x00\x00\x00")  # ack
        assert got[1]["status"] == 0x89  # :status 204 (static index 9)
        assert json.loads(bytes(body))  # allItemIDs payload on stream 3


def _tls_server_context(tmp_path):
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID
    except ImportError:
        pytest.skip("cryptography unavailable")
    import datetime
    import ssl

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder().subject_name(name).issuer_name(name)
            .public_key(key.public_key()).serial_number(1)
            .not_valid_before(now - datetime.timedelta(days=1))
            .not_valid_after(now + datetime.timedelta(days=1))
            .sign(key, hashes.SHA256()))
    pem = tmp_path / "s.pem"
    pem.write_bytes(
        cert.public_bytes(serialization.Encoding.PEM)
        + key.private_bytes(serialization.Encoding.PEM,
                            serialization.PrivateFormat.TraditionalOpenSSL,
                            serialization.NoEncryption()))
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(str(pem))
    return ctx


def test_curl_h2_over_tls_alpn(tmp_path):
    """Full ALPN negotiation: curl --http2 over TLS must land on h2."""
    ctx = _tls_server_context(tmp_path)
    app, batcher, make_server = _serving_app()
    server = make_server(app, 0, ssl_context=ctx)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        r = _curl(["--http2", "-k", "-w", "\n%{http_version}",
                   f"https://127.0.0.1:{port}/recommend/u2?howMany=2"])
        assert r.returncode == 0, r.stderr
        body, version = r.stdout.rsplit("\n", 1)
        assert version == "2"
        assert len(json.loads(body)) == 2
        # connection REUSE with a real client: two URLs share one h2
        # session over TLS (exercises a second stream's HPACK state)
        r = _curl(["--http2", "-k",
                   f"https://127.0.0.1:{port}/allItemIDs",
                   f"https://127.0.0.1:{port}/allUserIDs"])
        assert r.returncode == 0, r.stderr
        assert r.stdout.count("[") == 2  # both JSON arrays arrived
    finally:
        server.shutdown()
        batcher.close()


def test_h2c_sniff_rejects_garbage_preface(h2_server):
    with socket.create_connection(("127.0.0.1", h2_server),
                                  timeout=10) as s:
        s.sendall(b"PRI * HTTP/2.0\r\nXXGARBAGE")
        assert s.makefile("rb").read() == b""  # clean close, no crash


def test_huffman_rejects_invalid_padding():
    # '0' is the 5-bit code 00000; three trailing 0-bits are NOT the
    # EOS prefix and must be rejected (RFC 7541 §5.2)
    from oryx_tpu.lambda_rt.hpack import HpackError
    assert huffman_decode(b"\x07") == b"0"  # correct all-ones padding
    with pytest.raises(HpackError):
        huffman_decode(b"\x00")


def test_h2_request_trailers_are_tolerated(h2_server):
    """HEADERS + DATA + trailing HEADERS(END_STREAM) is a legal request
    shape (RFC 9113 §8.1); trailers must not clobber :method/:path."""
    from oryx_tpu.lambda_rt import http2 as h2mod

    enc = HpackEncoder()
    with socket.create_connection(("127.0.0.1", h2_server),
                                  timeout=10) as s:
        s.sendall(h2mod.PREFACE)
        s.sendall(b"\x00\x00\x00\x04\x00\x00\x00\x00\x00")
        block = enc.encode([(":method", "POST"), (":path", "/pref/u0/i5"),
                            (":scheme", "http"), (":authority", "a")])
        s.sendall(len(block).to_bytes(3, "big") + bytes([1, 0x4])
                  + (1).to_bytes(4, "big") + block)          # no END_STREAM
        s.sendall((3).to_bytes(3, "big") + bytes([0, 0x0])
                  + (1).to_bytes(4, "big") + b"4.5")         # DATA
        trailer = enc.encode([("x-checksum", "abc")])
        s.sendall(len(trailer).to_bytes(3, "big") + bytes([1, 0x5])
                  + (1).to_bytes(4, "big") + trailer)        # trailers+ES
        r = s.makefile("rb")
        saw_status = None
        while saw_status is None:
            head = r.read(9)
            if len(head) < 9:
                break
            length = int.from_bytes(head[:3], "big")
            ftype, flags = head[3], head[4]
            payload = r.read(length)
            if ftype == 1:
                saw_status = payload[0]
        assert saw_status == 0x89  # 204: the pref was ingested


def test_h2_flow_control_small_window(h2_server):
    """A client advertising a tiny INITIAL_WINDOW_SIZE must receive the
    response in window-sized DATA chunks, the server pausing until
    WINDOW_UPDATEs open credit (the blocked-send branch of
    _send_response)."""
    from oryx_tpu.lambda_rt import http2 as h2mod

    enc = HpackEncoder()
    window = 256
    with socket.create_connection(("127.0.0.1", h2_server),
                                  timeout=10) as s:
        s.sendall(h2mod.PREFACE)
        # SETTINGS: INITIAL_WINDOW_SIZE=256 (id 0x4)
        payload = (4).to_bytes(2, "big") + window.to_bytes(4, "big")
        s.sendall(len(payload).to_bytes(3, "big") + bytes([4, 0])
                  + (0).to_bytes(4, "big") + payload)
        block = enc.encode([(":method", "GET"), (":path", "/allItemIDs"),
                            (":scheme", "http"), (":authority", "a")])
        s.sendall(len(block).to_bytes(3, "big") + bytes([1, 0x5])
                  + (1).to_bytes(4, "big") + block)
        r = s.makefile("rb")
        body = bytearray()
        done = False
        while not done:
            head = r.read(9)
            assert len(head) == 9, "connection closed mid-response"
            length = int.from_bytes(head[:3], "big")
            ftype, flags = head[3], head[4]
            payload = r.read(length)
            if ftype == 0:  # DATA
                assert length <= window  # never exceeds our credit
                body += payload
                done = bool(flags & 0x1)
                # grant credit back on stream AND connection
                inc = length.to_bytes(4, "big")
                for sid in (0, 1):
                    s.sendall(b"\x00\x00\x04\x08\x00"
                              + sid.to_bytes(4, "big") + inc)
            elif ftype == 4 and not flags & 0x1:
                s.sendall(b"\x00\x00\x00\x04\x01\x00\x00\x00\x00")
        items = json.loads(bytes(body))
        assert len(items) == 80  # the full response arrived, chunked


def test_curl_h2_digest_auth_and_errors(tmp_path):
    """DIGEST auth and the plain-text error pages work unchanged over
    h2.  Runs over TLS because the challenge/response dance is two
    requests on one connection — the path curl 7.88's h2c reuse bug
    breaks (see test_multiple_streams_on_one_connection)."""
    ctx = _tls_server_context(tmp_path)  # skippable step FIRST
    app, batcher, make_server = _serving_app(read_only=True,
                                             user_name="oryx",
                                             password="pw")
    server = make_server(app, 0, ssl_context=ctx)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"https://127.0.0.1:{port}"
    try:
        # no credentials -> 401 over h2
        r = _curl(["--http2", "-k", "-o", "/dev/null",
                   "-w", "%{http_code}\n%{http_version}",
                   f"{base}/allItemIDs"])
        code, ver = r.stdout.split("\n")
        assert r.returncode == 0 and code == "401" and ver == "2", r.stdout
        # digest credentials -> 200 over h2
        r = _curl(["--http2", "-k", "--digest", "-u", "oryx:pw",
                   "-o", "/dev/null", "-w", "%{http_code}",
                   f"{base}/allItemIDs"])
        assert r.returncode == 0 and r.stdout == "200", (r.stdout, r.stderr)
        # 404 error page over h2 keeps the plain-text error body
        r = _curl(["--http2", "-k", "--digest", "-u", "oryx:pw",
                   "-w", "\n%{http_code}", f"{base}/nope"])
        body, code = r.stdout.rsplit("\n", 1)
        assert code == "404" and "HTTP 404" in body
    finally:
        server.shutdown()
        batcher.close()


def test_h2_flow_control_small_window(h2_server):
    """A client advertising a tiny INITIAL_WINDOW_SIZE must receive the
    response in window-sized DATA chunks, the server pausing until
    WINDOW_UPDATEs open credit (the blocked-send branch of
    _send_response)."""
    from oryx_tpu.lambda_rt import http2 as h2mod

    enc = HpackEncoder()
    window = 256
    with socket.create_connection(("127.0.0.1", h2_server),
                                  timeout=10) as s:
        s.sendall(h2mod.PREFACE)
        # SETTINGS: INITIAL_WINDOW_SIZE=256 (id 0x4)
        payload = (4).to_bytes(2, "big") + window.to_bytes(4, "big")
        s.sendall(len(payload).to_bytes(3, "big") + bytes([4, 0])
                  + (0).to_bytes(4, "big") + payload)
        block = enc.encode([(":method", "GET"), (":path", "/allItemIDs"),
                            (":scheme", "http"), (":authority", "a")])
        s.sendall(len(block).to_bytes(3, "big") + bytes([1, 0x5])
                  + (1).to_bytes(4, "big") + block)
        r = s.makefile("rb")
        body = bytearray()
        done = False
        while not done:
            head = r.read(9)
            assert len(head) == 9, "connection closed mid-response"
            length = int.from_bytes(head[:3], "big")
            ftype, flags = head[3], head[4]
            payload = r.read(length)
            if ftype == 0:  # DATA
                assert length <= window  # never exceeds our credit
                body += payload
                done = bool(flags & 0x1)
                # grant credit back on stream AND connection
                inc = length.to_bytes(4, "big")
                for sid in (0, 1):
                    s.sendall(b"\x00\x00\x04\x08\x00"
                              + sid.to_bytes(4, "big") + inc)
            elif ftype == 4 and not flags & 0x1:
                s.sendall(b"\x00\x00\x00\x04\x01\x00\x00\x00\x00")
        items = json.loads(bytes(body))
        assert len(items) == 80  # the full response arrived, chunked


def test_curl_h2_digest_auth_and_errors(tmp_path):
    """DIGEST auth and the plain-text error pages work unchanged over
    h2.  Runs over TLS because the challenge/response dance is two
    requests on one connection — the path curl 7.88's h2c reuse bug
    breaks (see test_multiple_streams_on_one_connection)."""
    from oryx_tpu.lambda_rt.http import HttpApp, make_server
    from oryx_tpu.serving import als as als_resources
    from oryx_tpu.serving import framework as framework_resources
    from oryx_tpu.bench.load import StaticModelManager
    from oryx_tpu.app.als.serving_model import ALSServingModel
    from oryx_tpu.serving.batcher import TopNBatcher

    rng = np.random.default_rng(1)
    model = ALSServingModel(features=4, implicit=True)
    model.Y.bulk_load([f"i{j}" for j in range(20)],
                      rng.standard_normal((20, 4)).astype(np.float32))
    model.X.bulk_load(["u0"], rng.standard_normal((1, 4)).astype(np.float32))
    StaticModelManager.model = model
    batcher = TopNBatcher(pipeline=2)
    app = HttpApp(
        framework_resources.ROUTES + als_resources.ROUTES,
        context={"model_manager": StaticModelManager(),
                 "input_producer": None, "config": None,
                 "min_model_load_fraction": 0.0,
                 "top_n_batcher": batcher},
        read_only=True, user_name="oryx", password="pw")
    server = make_server(app, 0, ssl_context=_tls_server_context(tmp_path))
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"https://127.0.0.1:{port}"
    try:
        # no credentials -> 401 over h2
        r = _curl(["--http2", "-k", "-o", "/dev/null",
                   "-w", "%{http_code}\n%{http_version}",
                   f"{base}/allItemIDs"])
        code, ver = r.stdout.split("\n")
        assert r.returncode == 0 and code == "401" and ver == "2", r.stdout
        # digest credentials -> 200 over h2
        r = _curl(["--http2", "-k", "--digest", "-u", "oryx:pw",
                   "-o", "/dev/null", "-w", "%{http_code}",
                   f"{base}/allItemIDs"])
        assert r.returncode == 0 and r.stdout == "200", (r.stdout, r.stderr)
        # 404 error page over h2 keeps the plain-text error body
        r = _curl(["--http2", "-k", "--digest", "-u", "oryx:pw",
                   "-w", "\n%{http_code}", f"{base}/nope"])
        body, code = r.stdout.rsplit("\n", 1)
        assert code == "404" and "HTTP 404" in body
    finally:
        server.shutdown()
        batcher.close()


def test_streams_past_advertised_cap_are_refused(h2_server):
    """The server advertises SETTINGS_MAX_CONCURRENT_STREAMS=128 and
    must enforce it: the 129th concurrently open stream is refused with
    RST_STREAM(REFUSED_STREAM), while HPACK state stays consistent so
    already-open streams still complete."""
    from oryx_tpu.lambda_rt import http2 as h2mod

    enc = HpackEncoder()

    def headers_frame(sid, end_stream=False):
        block = enc.encode([(":method", "GET"), (":path", "/ready"),
                            (":scheme", "http"), (":authority", "a")])
        flags = 0x4 | (0x1 if end_stream else 0)
        return (len(block).to_bytes(3, "big") + bytes([1, flags])
                + sid.to_bytes(4, "big") + block)

    with socket.create_connection(("127.0.0.1", h2_server),
                                  timeout=10) as s:
        s.sendall(h2mod.PREFACE)
        s.sendall(b"\x00\x00\x00\x04\x00\x00\x00\x00\x00")  # SETTINGS
        # 128 open streams (no END_STREAM), then one more
        for i in range(129):
            s.sendall(headers_frame(2 * i + 1))
        r = s.makefile("rb")
        rst = None
        while rst is None:
            head = r.read(9)
            length = int.from_bytes(head[:3], "big")
            ftype, flags = head[3], head[4]
            sid = int.from_bytes(head[5:9], "big") & 0x7FFFFFFF
            payload = r.read(length)
            if ftype == 4 and not flags & 0x1:
                s.sendall(b"\x00\x00\x00\x04\x01\x00\x00\x00\x00")
            elif ftype == 3:  # RST_STREAM
                rst = (sid, int.from_bytes(payload, "big"))
        assert rst == (257, 0x7), rst  # REFUSED_STREAM on the 129th
        # stream 1 (admitted) still completes: empty DATA + END_STREAM
        s.sendall(b"\x00\x00\x00\x00\x01" + (1).to_bytes(4, "big"))
        status = None
        while status is None:
            head = r.read(9)
            length = int.from_bytes(head[:3], "big")
            ftype, _, sid = head[3], head[4], \
                int.from_bytes(head[5:9], "big") & 0x7FFFFFFF
            payload = r.read(length)
            if ftype == 1 and sid == 1:
                status = payload[0]
        assert status == 0x89  # :status 204, HPACK static index 9


def test_late_frames_on_closed_streams_do_not_kill_connection(h2_server):
    """DATA or trailer HEADERS racing a completed/refused stream must be
    dropped as frames on a *closed* stream (any unknown id at or below
    the connection's high-water mark), not treated as idle-stream
    protocol errors that tear down every healthy stream on the
    connection (RFC 9113 §5.1 closed-state tolerance)."""
    from oryx_tpu.lambda_rt import http2 as h2mod

    enc = HpackEncoder()

    def headers_frame(sid, end_stream=True):
        block = enc.encode([(":method", "GET"), (":path", "/ready"),
                            (":scheme", "http"), (":authority", "a")])
        flags = 0x4 | (0x1 if end_stream else 0)
        return (len(block).to_bytes(3, "big") + bytes([1, flags])
                + sid.to_bytes(4, "big") + block)

    def read_response(r, want_sid):
        while True:
            head = r.read(9)
            assert head, "connection closed unexpectedly"
            length = int.from_bytes(head[:3], "big")
            ftype, flags = head[3], head[4]
            sid = int.from_bytes(head[5:9], "big") & 0x7FFFFFFF
            payload = r.read(length)
            if ftype == 7:  # GOAWAY
                raise AssertionError(f"GOAWAY: {payload!r}")
            if ftype == 1 and sid == want_sid:
                return payload[0]

    with socket.create_connection(("127.0.0.1", h2_server),
                                  timeout=10) as s:
        s.sendall(h2mod.PREFACE)
        s.sendall(b"\x00\x00\x00\x04\x00\x00\x00\x00\x00")  # SETTINGS
        r = s.makefile("rb")
        # complete stream 1, then throw late frames at its closed id
        s.sendall(headers_frame(1))
        assert read_response(r, 1) == 0x89  # :status 204
        # late DATA for the closed stream (5 bytes, END_STREAM)
        s.sendall(b"\x00\x00\x05\x00\x01" + (1).to_bytes(4, "big")
                  + b"hello")
        # late trailers for the closed stream must not resurrect it
        trailer_block = enc.encode([("x-late", "1")])
        s.sendall(len(trailer_block).to_bytes(3, "big") + bytes([1, 0x5])
                  + (1).to_bytes(4, "big") + trailer_block)
        # the connection is still healthy: stream 3 completes normally
        s.sendall(headers_frame(3))
        assert read_response(r, 3) == 0x89
