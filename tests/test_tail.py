"""Unit tests for the tail-anatomy + SLO + wide-event layer (ISSUE 7):
obs/anatomy.py's stage decomposition (pure over span dicts, sums
exactly to the root duration), obs/slo.py's burn-rate math / alert
state machine / config parsing / chaos freeze, and obs/events.py's
emit gates, span-field derivation, rotation, and disk-full chaos."""

from __future__ import annotations

import json
import os

import pytest

from oryx_tpu.common.config import from_dict
from oryx_tpu.lambda_rt.metrics import MetricsRegistry
from oryx_tpu.obs import anatomy, events, slo
from oryx_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# -- anatomy ------------------------------------------------------------------

def _router_trace(total=100.0, shard_ms=(80.0, 40.0), serving_ms=70.0,
                  qw=10.0, de=50.0, merge=5.0, lead=5.0):
    spans = [
        {"name": "router.request", "trace_id": "t", "span_id": "r",
         "parent_id": None, "start_ms": 0.0, "duration_ms": total,
         "attrs": {"route": "GET /r", "http.status": 200},
         "status": "ok"},
        {"name": "router.merge", "span_id": "m", "parent_id": "r",
         "start_ms": total - merge, "duration_ms": merge, "attrs": {}},
    ]
    for i, d in enumerate(shard_ms):
        spans.append({"name": "router.shard_call", "span_id": f"c{i}",
                      "parent_id": "r", "start_ms": lead,
                      "duration_ms": d, "attrs": {"shard": i},
                      "status": "ok"})
    # the slowest shard's replica-side tree (what ?join=1 contributes)
    spans += [
        {"name": "serving.request", "span_id": "s0", "parent_id": "c0",
         "start_ms": lead + 2.0, "duration_ms": serving_ms,
         "attrs": {}, "status": "ok"},
        {"name": "serving.queue_wait", "span_id": "q0",
         "parent_id": "s0", "duration_ms": qw},
        {"name": "serving.device_execute", "span_id": "d0",
         "parent_id": "s0", "duration_ms": de,
         "attrs": {"batch_size": 3, "kernel_route": "int8_fold"}},
    ]
    return spans


def test_analyze_router_trace_sums_exactly_to_total():
    b = anatomy.analyze_trace(_router_trace())
    assert b["trace_id"] == "t" and b["route"] == "GET /r"
    s = b["stages"]
    assert set(s) == set(anatomy.STAGES)
    assert sum(s.values()) == pytest.approx(b["total_ms"], abs=1e-6)
    # the slowest shard (80 ms) attributes, not the fast one
    assert s["serving.device_execute"] == pytest.approx(50.0)
    assert s["serving.queue_wait"] == pytest.approx(10.0)
    assert s["serving.request"] == pytest.approx(10.0)  # 70 - 10 - 50
    assert s["scatter.wait"] == pytest.approx(10.0)     # 80 - 70
    assert s["router.merge"] == pytest.approx(5.0)
    assert s["router.dispatch"] == pytest.approx(5.0)   # timeline lead
    assert s["untraced"] == pytest.approx(10.0)  # 100-80-5-5


def test_analyze_clamps_overlong_children():
    # a retroactive child longer than its parent must not push the
    # breakdown past the total
    spans = _router_trace(total=50.0, shard_ms=(200.0,),
                          serving_ms=500.0, qw=400.0, de=400.0)
    b = anatomy.analyze_trace(spans)
    assert sum(b["stages"].values()) == pytest.approx(50.0, abs=1e-6)
    assert all(v >= 0.0 for v in b["stages"].values())


def test_analyze_single_node_trace():
    spans = [
        {"name": "serving.request", "trace_id": "t", "span_id": "s",
         "parent_id": None, "start_ms": 0.0, "duration_ms": 40.0,
         "attrs": {"route": "GET /recommend/{userID}"}, "status": "ok"},
        {"name": "serving.queue_wait", "span_id": "q",
         "parent_id": "s", "duration_ms": 5.0},
        {"name": "serving.device_execute", "span_id": "d",
         "parent_id": "s", "duration_ms": 30.0, "attrs": {}},
    ]
    b = anatomy.analyze_trace(spans)
    s = b["stages"]
    assert s["serving.queue_wait"] == pytest.approx(5.0)
    assert s["serving.device_execute"] == pytest.approx(30.0)
    assert s["serving.request"] == pytest.approx(5.0)
    assert sum(s.values()) == pytest.approx(40.0, abs=1e-6)


def test_analyze_rootless_fragment_is_none():
    assert anatomy.analyze_trace(
        [{"name": "serving.queue_wait", "span_id": "q",
          "parent_id": "s", "duration_ms": 5.0}]) is None


def test_analyze_orphan_root_replica_local_ring():
    """A replica analyzing its OWN ring sees serving.request spans
    parented under the router's shard_call — which lives in another
    process's ring.  Such an orphan .request is still a perfectly
    analyzable local root (the replica-local /admin/tail view)."""
    spans = [
        {"name": "serving.request", "trace_id": "t", "span_id": "s",
         "parent_id": "router-side-id", "start_ms": 0.0,
         "duration_ms": 40.0,
         "attrs": {"route": "GET /shard/recommend/{userID}"},
         "status": "ok"},
        {"name": "serving.queue_wait", "span_id": "q",
         "parent_id": "s", "duration_ms": 5.0},
        {"name": "serving.device_execute", "span_id": "d",
         "parent_id": "s", "duration_ms": 30.0, "attrs": {}},
    ]
    b = anatomy.analyze_trace(spans)
    assert b is not None and b["total_ms"] == pytest.approx(40.0)
    assert b["stages"]["serving.device_execute"] == pytest.approx(30.0)
    # but when the router's root IS in the (joined) span set, it wins
    joined = spans + [
        {"name": "router.request", "trace_id": "t", "span_id":
         "router-root", "parent_id": None, "start_ms": 0.0,
         "duration_ms": 60.0, "attrs": {"route": "GET /r"},
         "status": "ok"},
        {"name": "router.shard_call", "span_id": "router-side-id",
         "parent_id": "router-root", "start_ms": 2.0,
         "duration_ms": 45.0, "attrs": {"shard": 0}, "status": "ok"},
    ]
    b2 = anatomy.analyze_trace(joined)
    assert b2["total_ms"] == pytest.approx(60.0)
    assert b2["route"] == "GET /r"


def test_tail_report_shares_and_topk():
    traces = {}
    # 30 fast traces + 2 slow ones dominated by device time
    for i in range(30):
        traces[f"f{i}"] = _router_trace(total=20.0, shard_ms=(15.0,),
                                        serving_ms=14.0, qw=1.0,
                                        de=12.0, merge=1.0, lead=1.0)
    for i in range(2):
        traces[f"s{i}"] = _router_trace(total=500.0, shard_ms=(480.0,),
                                        serving_ms=470.0, qw=10.0,
                                        de=450.0, merge=5.0, lead=5.0)
    rep = anatomy.tail_report(traces, top_k=3)
    assert rep["analyzed"] == 32 and rep["skipped"] == 0
    share = rep["tail"]["stage_share"]
    assert sum(share.values()) == pytest.approx(1.0, abs=0.01)
    assert share["serving.device_execute"] > 0.8
    assert [t["total_ms"] for t in rep["top"]] == \
        sorted((t["total_ms"] for t in rep["top"]), reverse=True)
    assert rep["top"][0]["total_ms"] == pytest.approx(500.0)
    # per-stage histograms cover every analyzed trace
    assert sum(rep["stages"]["serving.device_execute"]["buckets"]) == 32


def test_tail_report_route_prefix_filter():
    traces = {"a": _router_trace()}
    spans_other = _router_trace()
    spans_other[0] = dict(spans_other[0],
                          attrs={"route": "GET /admin/profile"})
    traces["b"] = spans_other
    rep = anatomy.tail_report(traces, route_prefix="/r")
    assert rep["analyzed"] == 1 and rep["skipped"] == 1
    assert rep["top"][0]["route"] == "GET /r"


def test_tail_report_empty_ring():
    rep = anatomy.tail_report({})
    assert rep["analyzed"] == 0 and rep["p99_ms"] is None
    assert rep["top"] == []


# -- SLO engine ---------------------------------------------------------------

def _fill(reg, route, n, ms, status=200):
    for _ in range(n):
        reg.record(route, status, ms / 1000.0)


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _engine(reg, objectives, **kw):
    clock = _Clock()
    eng = slo.SloEngine(objectives, reg, resolution_sec=1.0,
                        clock=clock, **kw)
    return eng, clock


def test_latency_objective_burn_and_page_state():
    reg = MetricsRegistry()
    eng, clock = _engine(
        reg, [slo.SloObjective("lat", "latency", target=0.99,
                               threshold_ms=200.0)])
    _fill(reg, "GET /r", 98, 50.0)
    _fill(reg, "GET /r", 2, 500.0)        # 2% over threshold
    st = eng.evaluate()["objectives"]["lat"]
    # err 0.02 / budget 0.01 -> burn 2.0 on every window: no page
    assert st["windows"]["5m"]["burn"] == pytest.approx(2.0)
    assert st["state"] == "ok"
    clock.t += 10.0
    _fill(reg, "GET /r", 50, 500.0)       # a real incident
    st = eng.evaluate()["objectives"]["lat"]
    assert st["windows"]["5m"]["burn"] >= 14.4
    assert st["state"] == "page"
    assert st["transitions"] == 1
    assert eng.burn_gauge() >= 14.4
    # budget consumed = burn(6h) x 6h/30d: a finite bite out of the
    # period's budget, never "all gone" from one window's burn
    burn6 = st["windows"]["6h"]["burn"]
    want = max(0.0, 1.0 - burn6 * (21600.0 / (30 * 24 * 3600.0)))
    assert eng.budget_gauge() == pytest.approx(want, abs=1e-3)
    assert 0.0 < eng.budget_gauge() < 1.0


def test_availability_objective_counts_server_errors():
    reg = MetricsRegistry()
    eng, _ = _engine(
        reg, [slo.SloObjective("avail", "availability", target=0.999)])
    _fill(reg, "GET /r", 99, 10.0)
    _fill(reg, "GET /r", 1, 10.0, status=503)
    _fill(reg, "GET /r", 5, 10.0, status=404)   # 4xx never count bad
    st = eng.evaluate()["objectives"]["avail"]
    w = st["windows"]["5m"]
    assert w["total"] == 105 and w["total"] - w["good"] == 1
    assert w["burn"] == pytest.approx((1 / 105) / 0.001, rel=1e-3)


def test_window_baseline_uses_ring_history():
    reg = MetricsRegistry()
    eng, clock = _engine(
        reg, [slo.SloObjective("lat", "latency", target=0.99,
                               threshold_ms=200.0)])
    _fill(reg, "GET /r", 1000, 500.0)     # ancient all-bad history
    eng.evaluate()
    # an hour later the incident is long over: fresh traffic is clean
    clock.t += 4000.0
    _fill(reg, "GET /r", 100, 10.0)
    st = eng.evaluate()["objectives"]["lat"]
    # the 5m window baseline is the old snapshot just before the
    # window start -> only the 100 new good requests are inside
    assert st["windows"]["5m"]["total"] == 100
    assert st["windows"]["5m"]["burn"] == 0.0
    # the 6h window still sees the whole incident
    assert st["windows"]["6h"]["total"] == 1100
    assert st["state"] != "page"


def test_control_plane_routes_never_vote():
    reg = MetricsRegistry()
    eng, _ = _engine(
        reg, [slo.SloObjective("avail", "availability", target=0.99)])
    _fill(reg, "GET /metrics", 50, 10.0, status=503)
    _fill(reg, "GET /admin/traces", 50, 10.0, status=503)
    _fill(reg, "GET /shard/recommend/{userID}", 5, 10.0, status=503)
    st = eng.evaluate()["objectives"]["avail"]
    assert st["windows"]["5m"]["total"] == 0
    assert st["state"] == "ok"


def test_route_prefix_objective():
    reg = MetricsRegistry()
    eng, _ = _engine(
        reg, [slo.SloObjective("rec", "latency", target=0.99,
                               threshold_ms=200.0,
                               route_prefix="/recommend")])
    _fill(reg, "GET /recommend/{userID}", 10, 500.0)
    _fill(reg, "GET /similarity/{itemIDs:+}", 10, 500.0)
    st = eng.evaluate()["objectives"]["rec"]
    assert st["windows"]["5m"]["total"] == 10   # only /recommend votes


def test_eval_error_chaos_freezes_state_and_counts():
    reg = MetricsRegistry()
    eng, clock = _engine(
        reg, [slo.SloObjective("lat", "latency", target=0.99,
                               threshold_ms=200.0)])
    _fill(reg, "GET /r", 100, 500.0)      # everything bad -> page
    before = eng.evaluate()["objectives"]["lat"]["state"]
    assert before == "page"
    clock.t += 10.0
    # recovery traffic deep enough to dilute even the 6h window's
    # burn below the ticket line...
    _fill(reg, "GET /r", 20000, 10.0)
    faults.inject("obs-slo-eval-error", mode="error", times=1)
    st = eng.evaluate()                   # ...which the evaluator
    assert st["objectives"]["lat"]["state"] == "page"  # never sees
    assert eng.eval_failures == 1
    assert reg.counters_snapshot()["slo_eval_failures"] == 1
    # next (clean) evaluation thaws and recovers
    clock.t += 10.0
    assert eng.evaluate()["objectives"]["lat"]["state"] == "ok"


def test_engine_from_config_parses_objectives_and_gates():
    reg = MetricsRegistry()
    assert slo.engine_from_config(from_dict({}), reg) is None
    cfg = from_dict({
        "oryx.obs.slo.enabled": True,
        "oryx.obs.slo.objectives.availability.kind": "availability",
        "oryx.obs.slo.objectives.availability.target": 0.999,
        "oryx.obs.slo.objectives.lat.kind": "latency",
        "oryx.obs.slo.objectives.lat.target": 0.99,
        "oryx.obs.slo.objectives.lat.threshold-ms": 200,
        "oryx.obs.slo.objectives.lat.route-prefix": "/recommend",
    })
    eng = slo.engine_from_config(cfg, reg)
    by = {o.name: o for o in eng.objectives}
    assert by["availability"].kind == "availability"
    assert by["lat"].threshold_ms == 200.0
    assert by["lat"].route_prefix == "/recommend"
    assert eng.fast_burn == 14.4 and eng.slow_burn == 6.0


def test_latency_threshold_must_sit_on_a_bucket_bound():
    with pytest.raises(ValueError, match="bucket"):
        slo.SloObjective("x", "latency", target=0.99, threshold_ms=123.0)
    with pytest.raises(ValueError, match="kind"):
        slo.SloObjective("x", "weird")


# -- wide-event log -----------------------------------------------------------

def _read_events(log):
    with open(log.path, encoding="utf-8") as f:
        return [json.loads(line) for line in f]


def test_emit_gates_sampled_error_and_slow(tmp_path):
    log = events.WideEventLog(str(tmp_path), "t", always_slow_ms=1000)
    assert log.should_emit(200, 5.0, sampled=True)
    assert not log.should_emit(200, 5.0, sampled=False)
    assert log.should_emit(503, 5.0, sampled=False)   # server error
    assert log.should_emit(0, 5.0, sampled=False)     # conn died
    assert not log.should_emit(404, 5.0, sampled=False)
    assert log.should_emit(200, 1500.0, sampled=False)  # slow
    # with no slow threshold, slow-but-ok unsampled stays silent
    log2 = events.WideEventLog(str(tmp_path), "t2")
    assert not log2.should_emit(200, 99999.0, sampled=False)


def test_emit_derives_span_fields(tmp_path):
    log = events.WideEventLog(str(tmp_path), "router")
    spans = [
        {"name": "router.shard_call", "status": "ok", "attrs": {}},
        {"name": "router.shard_call", "status": "error", "attrs": {}},
        {"name": "router.merge", "attrs": {"shards_merged": 1}},
        {"name": "serving.queue_wait", "duration_ms": 7.25},
        {"name": "serving.device_execute", "duration_ms": 30.0,
         "attrs": {"batch_size": 4, "kernel_route": "int8_fold"}},
    ]
    log.emit("GET /recommend/{userID}", 200, 55.5, "ab" * 16, spans)
    (ev,) = _read_events(log)
    assert ev["route"] == "GET /recommend/{userID}"
    assert ev["trace_id"] == "ab" * 16 and ev["sampled"] is True
    assert ev["latency_ms"] == 55.5
    assert ev["shards_called"] == 2 and ev["shard_errors"] == 1
    assert ev["shards_merged"] == 1
    assert ev["queue_wait_ms"] == 7.25
    assert ev["batch_size"] == 4
    assert ev["kernel_route"] == "int8_fold"
    # every emitted key is in the documented schema
    assert set(ev) <= set(events.FIELDS)
    # unsampled error line: minimal fields, no trace id
    log.emit("GET /r", 503, 9.9, None, None)
    ev2 = _read_events(log)[1]
    assert "trace_id" not in ev2 and ev2["sampled"] is False


def test_rotation_keeps_max_files(tmp_path):
    log = events.WideEventLog(str(tmp_path), "t", max_bytes=400,
                              max_files=3)
    for i in range(50):
        log.emit(f"GET /r{i}", 200, 1.0, "ab" * 16, None)
    files = sorted(os.listdir(tmp_path))
    base = os.path.basename(log.path)
    assert base in files
    assert f"{base}.1" in files and f"{base}.2" in files
    assert f"{base}.3" not in files
    assert os.path.getsize(log.path) <= 400
    # the newest line is in the live file
    assert _read_events(log)[-1]["route"] == "GET /r49"


def test_disk_full_chaos_drops_and_counts(tmp_path):
    reg = MetricsRegistry()
    log = events.WideEventLog(str(tmp_path), "t", registry=reg)
    faults.inject("obs-event-disk-full", mode="error", times=2)
    log.emit("GET /r", 200, 1.0, "ab" * 16, None)  # must NOT raise
    log.emit("GET /r", 200, 1.0, "cd" * 16, None)
    log.emit("GET /r", 200, 1.0, "ef" * 16, None)  # fault disarmed
    assert log.dropped == 2 and log.emitted == 1
    assert reg.counters_snapshot()["event_write_failures"] == 2
    assert len(_read_events(log)) == 1


def test_events_from_config_gates_on_dir(tmp_path):
    reg = MetricsRegistry()
    assert events.events_from_config(from_dict({}), "t", reg) is None
    cfg = from_dict({"oryx.obs.events.dir": str(tmp_path),
                     "oryx.obs.events.always-slow-ms": 250})
    log = events.events_from_config(cfg, "serving", reg)
    assert log is not None
    assert log.always_slow_ms == 250
    assert log.max_bytes == 16777216 and log.max_files == 4
    assert "events-serving-" in log.path
    log.close()


def test_emit_after_close_drops_instead_of_resurrecting(tmp_path):
    log = events.WideEventLog(str(tmp_path), "t")
    log.emit("GET /r", 200, 1.0, "ab" * 16, None)
    log.close()
    # a handler thread outliving close() must not reopen the file
    log.emit("GET /r", 200, 1.0, "cd" * 16, None)
    assert log.dropped == 1 and log.emitted == 1
    assert log._f is None
    assert len(_read_events(log)) == 1
