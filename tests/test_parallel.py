"""Multi-device distributed ALS tests over the 8-way virtual CPU mesh
(conftest sets xla_force_host_platform_device_count=8).

Mirrors the role of the reference's batch ALS ITs
(app/oryx-app-mllib/src/test/java/.../als/ALSUpdateIT.java:48) for the
scale-out path: the distributed trainer must agree with the single-chip
trainer and actually reconstruct the interaction structure.
"""

import jax
import numpy as np
import pytest

from oryx_tpu.app.als.common import ParsedRatings
from oryx_tpu.app.als.trainer import train_als
from oryx_tpu.parallel import (
    block_ratings,
    build_mesh,
    make_train_step,
    train_als_distributed,
)


def _synthetic(n_users=40, n_items=30, nnz=400, implicit=True, seed=7):
    rng = np.random.default_rng(seed)
    pairs = set()
    while len(pairs) < nnz:
        pairs.add((int(rng.integers(n_users)), int(rng.integers(n_items))))
    users, items = np.array(sorted(pairs), dtype=np.int32).T
    if implicit:
        vals = rng.uniform(0.5, 3.0, size=len(users)).astype(np.float32)
    else:
        vals = rng.uniform(1.0, 5.0, size=len(users)).astype(np.float32)
    return ParsedRatings(
        [f"u{i}" for i in range(n_users)],
        [f"i{i}" for i in range(n_items)],
        users, items, vals)


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = build_mesh(8)
    assert mesh.devices.size == 8


@pytest.mark.parametrize("implicit", [True, False])
def test_distributed_matches_single_device(implicit):
    ratings = _synthetic(implicit=implicit)
    mesh = build_mesh(8)
    kwargs = dict(features=6, lam=0.01, alpha=1.0,
                  implicit=implicit, iterations=4, seed=123)
    single = train_als(ratings, **kwargs)
    dist = train_als_distributed(ratings, mesh=mesh, **kwargs)
    assert dist.X.shape == single.X.shape
    assert dist.Y.shape == single.Y.shape
    # same math, same init (first n_items rows of the padded init are the
    # same draws) — allow small numeric drift from reduction ordering
    np.testing.assert_allclose(dist.X, single.X, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(dist.Y, single.Y, rtol=2e-3, atol=2e-3)


def test_distributed_reconstructs_implicit_preferences():
    ratings = _synthetic(implicit=True)
    mesh = build_mesh(8)
    model = train_als_distributed(
        ratings, features=10, lam=0.005, alpha=10.0,
        implicit=True, iterations=8, mesh=mesh, seed=5)
    scores = model.X @ model.Y.T
    observed = scores[ratings.users, ratings.items]
    mask = np.ones_like(scores, dtype=bool)
    mask[ratings.users, ratings.items] = False
    assert observed.mean() > scores[mask].mean() + 0.2


@pytest.mark.parametrize("implicit", [True, False])
def test_ring_mode_matches_gather_and_single_device(implicit):
    """The multi-host ring half-sweep (ppermute rotation, Gramian
    folded into the hops, never a materialized full opposite factor)
    is the same math as the all-gather step in a different reduction
    order — both must land on the single-chip trainer within f32
    reassociation drift."""
    ratings = _synthetic(implicit=implicit)
    mesh = build_mesh(8)
    kwargs = dict(features=6, lam=0.01, alpha=1.0,
                  implicit=implicit, iterations=4, seed=123)
    single = train_als(ratings, **kwargs)
    ring = train_als_distributed(ratings, mesh=mesh, mode="ring",
                                 **kwargs)
    gather = train_als_distributed(ratings, mesh=mesh, mode="gather",
                                   **kwargs)
    np.testing.assert_allclose(ring.X, single.X, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(ring.Y, single.Y, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(ring.X, gather.X, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(ring.Y, gather.Y, rtol=2e-3, atol=2e-3)


def test_ring_mode_with_donated_buffers():
    """donate_argnums on the factor buffers (in-place HBM update
    across iterations) must not change results — donation is a memory
    contract, not a math one."""
    ratings = _synthetic(nnz=200)
    mesh = build_mesh(8)
    kwargs = dict(features=5, lam=0.02, alpha=1.0, implicit=True,
                  iterations=3, seed=11)
    plain = train_als_distributed(ratings, mesh=mesh, mode="ring",
                                  donate=False, **kwargs)
    donated = train_als_distributed(ratings, mesh=mesh, mode="ring",
                                    donate=True, **kwargs)
    np.testing.assert_array_equal(plain.X, donated.X)
    np.testing.assert_array_equal(plain.Y, donated.Y)


def test_ring_blocked_layout_partitions_by_owner_block():
    """Every interaction lands in exactly one (row, owner-block) slab
    with a LOCAL index inside the block — the property that keeps the
    ring schedule's total einsum slots at ~P instead of n_dev x P."""
    from oryx_tpu.parallel import block_ratings_ring

    ratings = _synthetic(n_users=13, n_items=21, nnz=90)
    n_dev = 8
    blocks = block_ratings_ring(ratings, n_dev)
    assert blocks.u_cols.shape[1] == n_dev
    assert blocks.i_cols.shape[1] == n_dev
    # real slot count == nnz on both sides (no duplication, no loss)
    assert int(blocks.u_mask.sum()) == len(ratings.users)
    assert int(blocks.i_mask.sum()) == len(ratings.users)
    # reconstruct the COO pairs from the user-side layout
    rb = blocks.i_cols.shape[0] and (
        # item rows padded to a multiple of n_dev, block = pad // n_dev
        max(n_dev, -(-len(ratings.item_ids) // n_dev) * n_dev) // n_dev)
    got = set()
    rows, owners, slots = np.nonzero(blocks.u_mask)
    for r, b, s in zip(rows, owners, slots):
        got.add((int(r), int(blocks.u_cols[r, b, s] + b * rb)))
    want = set(zip(ratings.users.tolist(), ratings.items.tolist()))
    assert got == want


def test_blocked_layout_row_padding():
    ratings = _synthetic(n_users=13, n_items=5, nnz=30)
    blocks = block_ratings(ratings, 8)
    assert blocks.u_cols.shape[0] % 8 == 0
    assert blocks.i_cols.shape[0] % 8 == 0
    assert blocks.n_users == 13 and blocks.n_items == 5
    # every real interaction appears exactly once in each layout
    assert int(blocks.u_mask.sum()) == len(ratings.users)
    assert int(blocks.i_mask.sum()) == len(ratings.users)


def _rdf_schema(classification=True):
    from oryx_tpu.app.schema import InputSchema
    from oryx_tpu.common.config import from_dict
    if classification:
        cfg = from_dict({
            "oryx.input-schema.feature-names": ["a", "b", "label"],
            "oryx.input-schema.numeric-features": ["a", "b"],
            "oryx.input-schema.target-feature": "label",
        })
    else:
        cfg = from_dict({
            "oryx.input-schema.feature-names": ["a", "b", "y"],
            "oryx.input-schema.numeric-features": ["a", "b", "y"],
            "oryx.input-schema.target-feature": "y",
        })
    return InputSchema(cfg)


def test_distributed_forest_matches_single_device():
    """Classification histograms are integer-valued, so the psum over
    device shards is exact — the distributed forest must equal the
    single-device forest split for split (reference capability:
    distributed RandomForest at RDFUpdate.java:141-163)."""
    from oryx_tpu.app.rdf.trainer import train_forest

    rng = np.random.default_rng(3)
    n = 500
    x = rng.uniform(-1, 1, (n, 2)).astype(np.float32)
    y = ((x[:, 0] + 0.3 * x[:, 1]) > 0.1).astype(np.int32)
    schema = _rdf_schema(classification=True)
    kwargs = dict(category_counts={}, num_trees=3, max_depth=4,
                  max_split_candidates=16, impurity="gini", seed=99,
                  num_classes=2)
    single = train_forest(x, y, schema, **kwargs)
    mesh = build_mesh(8)
    dist = train_forest(x, y, schema, mesh=mesh, **kwargs)

    np.testing.assert_allclose(dist.feature_importances,
                               single.feature_importances)
    from oryx_tpu.app.classreg import Example
    probes = [Example(None, [float(rng.uniform(-1, 1)),
                             float(rng.uniform(-1, 1)), None])
              for _ in range(200)]
    for tree_s, tree_d in zip(single.trees, dist.trees):
        for ex in probes:
            assert tree_s.find_terminal(ex).id == tree_d.find_terminal(ex).id


def test_distributed_forest_regression_quality():
    """Regression sums reassociate across shards (float drift can flip
    near-tie splits), so the distributed check is a quality gate, not
    bit equality."""
    from oryx_tpu.app.rdf.trainer import train_forest

    rng = np.random.default_rng(4)
    n = 600
    x = rng.uniform(0, 4, (n, 2)).astype(np.float32)
    y = np.where(x[:, 0] < 2, 1.0, 5.0).astype(np.float32)
    schema = _rdf_schema(classification=False)
    mesh = build_mesh(8)
    forest = train_forest(x, y, schema, category_counts={}, num_trees=3,
                          max_depth=3, max_split_candidates=32,
                          impurity="variance", seed=7, mesh=mesh)
    from oryx_tpu.app.classreg import Example
    preds = np.array([
        np.mean([t.find_terminal(
            Example(None, [float(a), float(b), None])).prediction.prediction
            for t in forest.trees])
        for a, b in x])
    assert np.sqrt(np.mean((preds - y) ** 2)) < 0.5


def test_train_step_is_jittable_and_finite():
    ratings = _synthetic(n_users=16, n_items=16, nnz=80)
    mesh = build_mesh(8)
    blocks = block_ratings(ratings, 8)
    step = make_train_step(mesh, lam=0.01, alpha=1.0, implicit=True)
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, P("d"))
    X = jax.device_put(np.zeros((blocks.u_cols.shape[0], 4), np.float32), sh)
    Y = jax.device_put(
        np.full((blocks.i_cols.shape[0], 4), 0.1, np.float32), sh)
    args = [jax.device_put(a, sh) for a in
            (blocks.u_cols, blocks.u_vals, blocks.u_mask,
             blocks.i_cols, blocks.i_vals, blocks.i_mask)]
    X2, Y2 = step(X, Y, *args)
    assert np.isfinite(np.asarray(X2)).all()
    assert np.isfinite(np.asarray(Y2)).all()


def test_initialize_multihost_noop_without_config():
    """Unconfigured multi-host init is a no-op (single-host default);
    the config keys exist in reference.conf as nulls."""
    from oryx_tpu.common.config import from_dict, get_default
    from oryx_tpu.parallel.mesh import initialize_multihost

    assert initialize_multihost(None) is False
    assert initialize_multihost(from_dict({})) is False
    cfg = get_default()
    assert cfg.get_optional_string(
        "oryx.distributed.coordinator-address") is None
    assert not cfg.has_path("oryx.distributed.num-processes")


@pytest.mark.slow
def test_distributed_kmeans_moderate_scale_agreement():
    """Distributed k-means at 120k points on the 8-way mesh (three
    orders of magnitude above the dryrun smoke test): the per-device
    partial-sum + psum aggregation must land on the same planted
    centers a single-device train finds."""
    from oryx_tpu.app.kmeans.trainer import train_kmeans
    from oryx_tpu.parallel.kmeans_dist import train_kmeans_distributed

    rng = np.random.default_rng(21)
    k, d = 12, 8
    true_c = rng.standard_normal((k, d)).astype(np.float32) * 9
    pts = (true_c[rng.integers(0, k, 120_000)]
           + rng.standard_normal((120_000, d)).astype(np.float32))
    mesh = build_mesh(8)
    dist = train_kmeans_distributed(pts, k=k, iterations=12, mesh=mesh,
                                    seed=6)
    dist_centers = np.stack([c.center for c in dist])
    # the distributed psum aggregation must land on the SAME model the
    # single-device trainer finds from the same seed (k-means|| may
    # legitimately merge planted clusters; agreement is the property)
    single = train_kmeans(pts, k=k, iterations=12, seed=6)
    single_centers = np.stack([c.center for c in single])
    ds = np.linalg.norm(single_centers[:, None, :]
                        - dist_centers[None, :, :], axis=2)
    assert ds.min(axis=1).max() < 0.05, ds.min(axis=1)
    assert ds.min(axis=0).max() < 0.05, ds.min(axis=0)
    # and most planted centers are recovered (quality sanity)
    dd = np.linalg.norm(true_c[:, None, :] - dist_centers[None, :, :],
                        axis=2).min(axis=1)
    assert (dd < 0.6).sum() >= k - 3, dd
    assert sum(c.count for c in dist) == 120_000


@pytest.mark.slow
def test_distributed_forest_moderate_scale_quality():
    """Distributed forest at 40k examples x 8 predictors, depth 8 (the
    dryrun exercises depth 2 on a few dozen rows): per-level histogram
    psums must still produce a forest that generalizes on held-out
    rows."""
    from oryx_tpu.app.classreg import Example
    from oryx_tpu.app.rdf.trainer import train_forest
    from oryx_tpu.app.schema import InputSchema
    from oryx_tpu.common.config import from_dict

    rng = np.random.default_rng(22)
    n, p = 40_000, 8
    x = rng.uniform(-1, 1, (n, p)).astype(np.float32)
    y = ((x[:, 0] + 0.5 * x[:, 1] - 0.25 * x[:, 2]
          + 0.1 * x[:, 3]) > 0).astype(np.int32)
    names = [f"f{i}" for i in range(p)] + ["label"]
    schema = InputSchema(from_dict({
        "oryx.input-schema.feature-names": names,
        "oryx.input-schema.numeric-features": names[:-1],
        "oryx.input-schema.target-feature": "label",
    }))
    mesh = build_mesh(8)
    n_test = 4000
    forest = train_forest(x[n_test:], y[n_test:], schema,
                          category_counts={}, num_trees=5, max_depth=8,
                          max_split_candidates=16, impurity="gini",
                          seed=23, num_classes=2, mesh=mesh)
    correct = 0
    probe = rng.choice(n_test, 800, replace=False)
    for i in probe:
        votes = [t.find_terminal(
            Example(None, [float(v) for v in x[i]] + [None])
        ).prediction.max_category for t in forest.trees]
        pred = max(set(votes), key=votes.count)
        correct += int(pred == y[i])
    assert correct / len(probe) >= 0.9, correct / len(probe)


def test_sharded_scorer_matches_single_device_serving():
    """The mesh-sharded serving scan (per-shard top-k + all_gather
    merge) must return exactly what the single-device serving model's
    exact scan returns (SURVEY P4/P5 beyond one chip)."""
    from oryx_tpu.app.als.serving_model import ALSServingModel
    from oryx_tpu.parallel.serving_dist import ShardedItemScorer

    rng = np.random.default_rng(31)
    ni, f = 4003, 12  # deliberately NOT a multiple of the mesh size
    ids = [f"i{j}" for j in range(ni)]
    Y = rng.standard_normal((ni, f)).astype(np.float32)
    mesh = build_mesh(8)
    scorer = ShardedItemScorer(mesh, ids, Y, dtype="float32")
    model = ALSServingModel(f, implicit=True)
    model.Y.bulk_load(ids, Y)
    Q = rng.standard_normal((5, f)).astype(np.float32)
    sharded = scorer.top_n_batch(7, Q)
    single = model.top_n_batch(7, Q)
    for a, b in zip(sharded, single):
        assert [i for i, _ in a] == [i for i, _ in b]
        np.testing.assert_allclose([s for _, s in a], [s for _, s in b],
                                   rtol=1e-5)
    # per-device memory accounting: each shard holds ~1/8 of the rows
    assert scorer.memory_bytes_per_device() <= (ni // 8 + 8) * f * 4 + 640


def test_sharded_scorer_bf16_quality():
    from oryx_tpu.parallel.serving_dist import ShardedItemScorer

    rng = np.random.default_rng(32)
    ni, f = 1024, 16
    Y = rng.standard_normal((ni, f)).astype(np.float32)
    mesh = build_mesh(8)
    scorer = ShardedItemScorer(mesh, [str(j) for j in range(ni)], Y)
    q = rng.standard_normal((1, f)).astype(np.float32)
    got = scorer.top_n_batch(5, q)[0]
    want = np.argsort(-(Y @ q[0]))[:5]
    # bf16 rounding may swap near-ties; the top hit must agree
    assert got[0][0] == str(int(want[0]))
    assert len(got) == 5


def test_sharded_scorer_how_many_exceeds_rows_per_shard():
    """how_many larger than one shard's row count must still return a
    full, exactly-ordered list (each shard ships its whole top and the
    merge width clamps to the global row count)."""
    from oryx_tpu.parallel.serving_dist import ShardedItemScorer

    rng = np.random.default_rng(33)
    ni, f = 40, 4  # 5 rows per shard on the 8-way mesh
    ids = [str(j) for j in range(ni)]
    Y = rng.standard_normal((ni, f)).astype(np.float32)
    mesh = build_mesh(8)
    scorer = ShardedItemScorer(mesh, ids, Y, dtype="float32")
    q = rng.standard_normal((1, f)).astype(np.float32)
    got = scorer.top_n_batch(10, q)[0]
    assert len(got) == 10
    want = np.argsort(-(Y @ q[0]))[:10]
    assert [g[0] for g in got] == [str(int(w)) for w in want]
