"""Active-active multi-region chaos IT (ISSUE 11 acceptance): REAL OS
processes — per region a `serving --shard 0/1` replica, a `router`, a
`speed` layer, and a `mirror` tailing the OTHER region's update topic —
over two durable ``file://`` brokers, proving:

1. steady state: a fold-in written to region A's router becomes
   servable in region B (and vice versa) through the mirror, and both
   regions answer byte-identically;
2. a partitioned mirror link (fault point ``mirror-link-partition``,
   conf-armed in the mirror processes so it fires there and only
   there): BOTH regions keep serving complete 200s — zero 5xx, zero
   partials — from their local fleets while the staleness gauges
   climb on both mirrors and writes land locally on each side;
3. heal (fresh mirror processes resume from the durable checkpoints):
   both regions converge to byte-identical answers for every user and
   item touched on either side during the partition — with the
   routers' exact result cache ARMED, so the mirrored-UP invalidation
   path is part of what byte-identity proves;
4. the A⇄B pair never ping-pongs: after convergence both topics stop
   growing (loop-prevention headers asserted on the mirrored records).

The mirror kill-mid-replay dedup fence is proven in-process in
tests/test_mirror.py (deterministic crash seam); this module is the
end-to-end topology.  Marker: chaos (tier-1).
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.bench.gateway import (_await, _free_port, _get_json,
                                    _get_json_retry_cold, _spawn,
                                    _write_conf)
from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.kafka.api import KEY_MODEL, KEY_UP
from oryx_tpu.kafka.inproc import resolve_broker

pytestmark = [pytest.mark.chaos, pytest.mark.slow]
# slow: this module is the retained real-process smoke for scenarios
# whose tier-1 coverage moved to the deterministic simulation
# (tests/test_sim_sweep.py) — hundreds of seeded interleavings per
# run instead of one wall-clock interleaving per CI run.

_USERS = [f"u{j}" for j in range(6)]
_ITEMS = [f"i{j}" for j in range(24)]
_FEATURES = 3
_FAST = {
    "oryx.cluster.heartbeat-interval-ms": 150,
    "oryx.cluster.heartbeat-ttl-ms": 900,
    "oryx.serving.min-model-load-fraction": 1.0,
    "oryx.speed.streaming.generation-interval-sec": 1,
}
# per-region touches stay on DISJOINT users and items: fold-in UP
# records are idempotent SETs, so disjoint ids make the cross-region
# interleaving commute — the convergence argument this IT proves
_TOUCH = {"a": ("u0", ["i1", "i2"]), "b": ("u5", ["i20", "i21"])}


def _publish_model(broker_dir: str) -> None:
    """Inline MODEL + per-row UP flood into region A's topic ONLY: the
    mirror carries the generation to region B — model distribution IS
    mirrored replay, same as every other update."""
    rng = np.random.default_rng(23)
    os.makedirs(broker_dir, exist_ok=True)
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", _FEATURES)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", _USERS)
    pmml_io.add_extension_content(doc, "YIDs", _ITEMS)
    # small-magnitude factors: every (user, item) estimate starts well
    # below 1, so implicit fold-ins always have headroom to publish
    # (compute_target_qui is a designed no-op at estimates >= 1 —
    # see tests/test_cache_it.py's /estimate-picked pairs)
    y = np.round(rng.standard_normal((len(_ITEMS), _FEATURES)) * 0.05, 4)
    x = np.round(rng.standard_normal((len(_USERS), _FEATURES)) * 0.05, 4)
    with open(os.path.join(broker_dir, "GwUp.topic.jsonl"), "a",
              encoding="utf-8") as f:
        f.write(json.dumps([KEY_MODEL, pmml_io.to_string(doc)]) + "\n")
        for iid, row in zip(_ITEMS, y.tolist()):
            f.write(json.dumps(
                [KEY_UP, json.dumps(["Y", iid, row])]) + "\n")
        for uid, row in zip(_USERS, x.tolist()):
            f.write(json.dumps(
                [KEY_UP, json.dumps(["X", uid, row, []])]) + "\n")


def _get_raw(port, path, timeout=15):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), r.read()


def _post(port, path, body="", timeout=15):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=body.encode(), method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status


class _Region:
    """One region's process set + addresses."""

    def __init__(self, name: str, work_dir: str):
        self.name = name
        self.work_dir = work_dir
        self.broker_dir = os.path.join(work_dir, f"broker-{name}")
        os.makedirs(self.broker_dir, exist_ok=True)
        self.procs: dict[str, object] = {}
        self.router_port: int | None = None
        self.mirror_obs_port: int | None = None
        self.mirror_ckpt = os.path.join(work_dir, f"mirror-ckpt-{name}")

    def _conf(self, tag: str, port: int, extra: dict) -> str:
        path = os.path.join(self.work_dir, f"{self.name}-{tag}.conf")
        overlay = {"oryx.cluster.region.name": self.name,
                   "oryx.id": f"region-{self.name}", **_FAST, **extra}
        _write_conf(path, self.broker_dir, port, overlay)
        return path

    def _log(self, tag: str) -> str:
        return os.path.join(self.work_dir, f"{self.name}-{tag}.log")

    def spawn_replica(self) -> None:
        port = _free_port()
        conf = self._conf("replica", port, {
            "oryx.cluster.enabled": True,
            "oryx.cluster.shard": "0/1",
            "oryx.cluster.replica-id": f"{self.name}-r0"})
        self.procs["replica"] = (_spawn(["serving", "--shard", "0/1"],
                                        conf, None,
                                        self._log("replica")), port)

    def spawn_router(self) -> None:
        port = _free_port()
        conf = self._conf("router", port, {
            # the exact result cache rides along: mirrored UP records
            # must evict through the router's tap like local ones, so
            # post-heal byte-identity also proves invalidation
            "oryx.cluster.cache.enabled": True,
            "oryx.cluster.coalesce.enabled": True})
        self.procs["router"] = (_spawn(["router"], conf, None,
                                       self._log("router")), port)
        self.router_port = port

    def spawn_speed(self) -> None:
        conf = self._conf("speed", _free_port(), {
            "oryx.speed.model-manager-class":
                "oryx_tpu.app.als.speed.ALSSpeedModelManager"})
        self.procs["speed"] = (_spawn(["speed"], conf, None,
                                      self._log("speed")), None)

    def spawn_mirror(self, source: "_Region",
                     partitioned: bool = False) -> None:
        """The inbound mirror: tails ``source``'s topic into ours.
        ``partitioned`` conf-arms ``mirror-link-partition`` unlimited
        in THAT process — every poll fails, the production shape of a
        dead inter-region link."""
        self.mirror_obs_port = _free_port()
        extra = {
            "oryx.cluster.region.mirror.source-broker":
                f"file://{source.broker_dir}",
            "oryx.cluster.region.mirror.source-region": source.name,
            "oryx.cluster.region.mirror.checkpoint-dir":
                self.mirror_ckpt,
            "oryx.cluster.region.mirror.poll-interval-ms": 150,
            "oryx.obs.metrics-port": self.mirror_obs_port,
            "oryx.resilience.supervisor.enabled": False,
        }
        if partitioned:
            extra.update({
                "oryx.resilience.faults.mirror-link-partition.mode":
                    "error",
                "oryx.resilience.faults.mirror-link-partition.times":
                    -1})
        conf = self._conf("mirror", _free_port(), extra)
        self.procs["mirror"] = (_spawn(["mirror"], conf, None,
                                       self._log("mirror")),
                                self.mirror_obs_port)

    def kill(self, tag: str) -> None:
        proc, _ = self.procs.pop(tag)
        proc.kill()
        proc.wait(timeout=15)

    def mirror_gauges(self) -> dict:
        return _get_json(self.mirror_obs_port, "/metrics").get(
            "freshness", {})

    def data_records(self) -> list:
        """The topic's non-heartbeat records (HB is periodic control
        plane — it grows forever and never mirrors)."""
        broker = resolve_broker(f"file://{self.broker_dir}")
        return [km for km in broker.read_range(
                    "GwUp", 0, broker.latest_offset("GwUp"))
                if km.key != "HB"]

    def close(self) -> None:
        for tag in list(self.procs):
            try:
                self.kill(tag)
            except Exception:  # noqa: BLE001 — teardown best effort
                pass


@pytest.fixture(scope="module")
def regions(tmp_path_factory):
    work = str(tmp_path_factory.mktemp("region-it"))
    a, b = _Region("alpha", work), _Region("beta", work)
    _publish_model(a.broker_dir)  # region A is where the model is born
    try:
        for r in (a, b):
            r.spawn_replica()
            r.spawn_router()
            r.spawn_speed()
        b.spawn_mirror(source=a)
        a.spawn_mirror(source=b)
        # region B's whole model arrives THROUGH the mirror; both
        # replicas must reach full load and both routers coverage
        for r in (a, b):
            _await(lambda r=r: _get_json(
                r.procs["replica"][1], "/shard/meta").get("ready")
                and _get_json(r.procs["replica"][1],
                              "/shard/meta").get("users", 0)
                >= len(_USERS),
                f"{r.name} replica load", timeout=240.0)
            _await(lambda r=r: _get_json(
                r.router_port, "/metrics")["cluster"]["covered_shards"]
                == [0], f"{r.name} router coverage", timeout=60.0)
        # warm the cold scoring path on both routers
        for r in (a, b):
            _get_json_retry_cold(r.router_port,
                                 f"/recommend/{_USERS[0]}?howMany=8")
        yield a, b
    finally:
        a.close()
        b.close()


def _answers(region: _Region, users, items) -> dict[str, bytes]:
    """Raw response bytes for every touched surface — byte-identity is
    the convergence claim, so compare bytes, not parsed floats."""
    out = {}
    for uid in users:
        status, headers, body = _get_raw(
            region.router_port, f"/recommend/{uid}?howMany=8")
        assert status == 200 and not headers.get("X-Oryx-Partial")
        out[f"recommend/{uid}"] = body
        status, _, body = _get_raw(region.router_port,
                                   f"/knownItems/{uid}")
        assert status == 200
        out[f"known/{uid}"] = body
    for i in range(0, len(items) - 1, 2):
        status, headers, body = _get_raw(
            region.router_port,
            f"/similarity/{items[i]}/{items[i + 1]}?howMany=6")
        assert status == 200 and not headers.get("X-Oryx-Partial")
        out[f"similarity/{items[i]}/{items[i + 1]}"] = body
    return out


def _await_gone_from_cache_and_folded(region: _Region, uid: str,
                                      item: str, timeout=90.0) -> None:
    """Wait until the region serves ``uid`` with ``item`` among its
    known items — the fold-in is servable locally."""
    def _has():
        _, _, body = _get_raw(region.router_port, f"/knownItems/{uid}")
        return item.encode() in body
    _await(_has, f"{region.name} serves fold-in {uid}/{item}",
           timeout=timeout)


def test_01_steady_state_fold_in_crosses_regions(regions):
    a, b = regions
    # identity probe — the failover runbook's first question
    assert _get_json(a.router_port, "/admin/region")["region"] == "alpha"
    assert _get_json(b.router_port, "/admin/region")["region"] == "beta"
    assert _get_json(b.mirror_obs_port,
                     "/admin/region")["source_region"] == "alpha"
    # a write in region A...
    assert _post(a.router_port, "/pref/u1/i5", "2.0") in (200, 204)
    # ...folds locally (speed A) and crosses the mirror into B
    _await_gone_from_cache_and_folded(a, "u1", "i5")
    _await_gone_from_cache_and_folded(b, "u1", "i5")
    # replayed mirrored records are visible on the mirror's counters
    m = _get_json(b.mirror_obs_port, "/metrics")
    assert m["counters"]["mirror_records_replayed"] >= 1
    # the headless mirror exposes breaker state (ISSUE 11 satellite)
    assert m["resilience"]["mirror-replay-dest"]["state"] == "closed"
    # both regions answer byte-identically once drained
    _await(lambda: _answers(a, ["u1"], []) == _answers(b, ["u1"], []),
           "steady-state byte identity", timeout=60.0)


def test_02_partition_serve_local_climb_then_converge(regions):
    # retained as the real-process smoke for this scenario; the
    # tier-1 coverage moved to the deterministic sim, which sweeps
    # hundreds of partition/heal interleavings per run at ~0.1 s each
    # (tests/test_sim_sweep.py, scenario "mirror-partition")
    a, b = regions
    # === partition the link: replace both healthy mirrors with ones
    # whose every poll fails at the mirror-link-partition seam ===
    a.kill("mirror")
    b.kill("mirror")
    b.spawn_mirror(source=a, partitioned=True)
    a.spawn_mirror(source=b, partitioned=True)
    _await(lambda: _get_json(a.mirror_obs_port, "/metrics")
           ["counters"].get("mirror_link_failures", 0) > 0
           and _get_json(b.mirror_obs_port, "/metrics")
           ["counters"].get("mirror_link_failures", 0) > 0,
           "both links down", timeout=60.0)

    # === divergent writes on both sides (disjoint users AND items) ===
    (ua, items_a), (ub, items_b) = _TOUCH["a"], _TOUCH["b"]
    for item in items_a:
        assert _post(a.router_port, f"/pref/{ua}/{item}", "3.0") in (200, 204)
    for item in items_b:
        assert _post(b.router_port, f"/pref/{ub}/{item}", "3.0") in (200, 204)
    # each side serves its OWN writes from its local fleet...
    _await_gone_from_cache_and_folded(a, ua, items_a[0])
    _await_gone_from_cache_and_folded(b, ub, items_b[0])

    # === both regions keep serving COMPLETE answers: zero 5xx, zero
    # partials, across the whole user population ===
    failures, partials = [], 0
    for round_ in range(3):
        for r in (a, b):
            for uid in _USERS:
                try:
                    status, headers, _ = _get_raw(
                        r.router_port, f"/recommend/{uid}?howMany=8")
                    if status != 200:
                        failures.append((r.name, uid, status))
                    elif headers.get("X-Oryx-Partial"):
                        partials += 1
                except Exception as e:  # noqa: BLE001 — any counts
                    failures.append((r.name, uid, str(e)))
    assert failures == []
    assert partials == 0

    # === the divergence is real (B hasn't seen A's write)... ===
    _, _, known_b = _get_raw(b.router_port, f"/knownItems/{ua}")
    assert items_a[0].encode() not in known_b
    # === ...and MEASURED: staleness gauges climb on both mirrors ===
    g1 = {r.name: r.mirror_gauges() for r in (a, b)}
    time.sleep(1.0)
    g2 = {r.name: r.mirror_gauges() for r in (a, b)}
    for name in ("alpha", "beta"):
        assert g2[name]["cross_region_staleness_ms"] \
            > g1[name]["cross_region_staleness_ms"], name
    # lag counts the unreplayed records stuck behind the partition
    assert g2["alpha"]["mirror_lag_records"] > 0
    assert g2["beta"]["mirror_lag_records"] > 0

    # === heal: fresh mirrors resume from the durable checkpoints ===
    a.kill("mirror")
    b.kill("mirror")
    b.spawn_mirror(source=a)
    a.spawn_mirror(source=b)
    _await(lambda: a.mirror_gauges().get("mirror_lag_records") == 0
           and b.mirror_gauges().get("mirror_lag_records") == 0,
           "mirrors drained after heal", timeout=120.0)
    # both speed layers + replicas must absorb the mirrored tail
    _await_gone_from_cache_and_folded(b, ua, items_a[0])
    _await_gone_from_cache_and_folded(a, ub, items_b[0])

    # === the mirrored UP records drove PRECISE evictions through each
    # router's tap (the invalidation path works cross-region exactly
    # like locally)... ===
    for r in (a, b):
        assert _get_json(r.router_port,
                         "/admin/cache")["invalidations"] > 0, r.name
    # ...but per-tag precision leaves PR 8's documented residual: an
    # entry for an UNtouched key whose rows reference a re-folded
    # item's vector persists until touch/eviction/generation — in
    # production bounded by live traffic and generation publishes, in
    # this frozen post-heal world by the runbook's one flush (the same
    # docs/SCALING.md "Result cache" argument, now cross-region)
    for r in (a, b):
        req = urllib.request.Request(
            f"http://127.0.0.1:{r.router_port}/admin/cache/flush",
            data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=15) as resp:
            resp.read()

    # === convergence: byte-identical answers for EVERY user and item
    # touched on either side during the partition (result cache armed:
    # repeated reads below also pin hit==miss byte identity) ===
    touched_users = [ua, ub]
    touched_items = items_a + items_b

    def _converged():
        return _answers(a, touched_users, touched_items) \
            == _answers(b, touched_users, touched_items)

    try:
        _await(_converged, "post-heal byte identity", timeout=120.0)
    except RuntimeError:
        ans_a = _answers(a, touched_users, touched_items)
        ans_b = _answers(b, touched_users, touched_items)
        diff = {k: (ans_a.get(k), ans_b.get(k))
                for k in set(ans_a) | set(ans_b)
                if ans_a.get(k) != ans_b.get(k)}
        raise AssertionError(f"byte identity diff: {diff}")
    ans_a = _answers(a, touched_users, touched_items)
    ans_b = _answers(b, touched_users, touched_items)
    assert ans_a == ans_b
    # the divergent folds actually reached the answers (not a trivial
    # identity of untouched state)
    assert _TOUCH["a"][1][0].encode() in ans_a[f"known/{ua}"]
    assert _TOUCH["b"][1][0].encode() in ans_a[f"known/{ub}"]


def test_03_no_ping_pong_after_convergence(regions):
    """Loop prevention end to end: once both regions are drained, the
    A⇄B pair must reach a FIXED POINT — neither topic grows while no
    new writes arrive (a ping-pong would grow both forever)."""
    a, b = regions
    _await(lambda: a.mirror_gauges().get("mirror_lag_records") == 0
           and b.mirror_gauges().get("mirror_lag_records") == 0,
           "drained", timeout=60.0)
    counts1 = (len(a.data_records()), len(b.data_records()))
    time.sleep(2.0)  # many mirror poll intervals
    counts2 = (len(a.data_records()), len(b.data_records()))
    assert counts1 == counts2, \
        "data records grew with no writes: ping-pong"
    # loop-prevention headers did the work, countably
    la = _get_json(a.mirror_obs_port, "/metrics")["counters"]
    lb = _get_json(b.mirror_obs_port, "/metrics")["counters"]
    assert la.get("mirror_loop_drops", 0) > 0 \
        or lb.get("mirror_loop_drops", 0) > 0
    # and every mirrored record in each topic names the OTHER region
    for region, foreign in ((a, "beta"), (b, "alpha")):
        origins = {(km.headers or {}).get("origin-region")
                   for km in region.data_records()}
        origins.discard(None)
        assert origins == {foreign}, (region.name, origins)
