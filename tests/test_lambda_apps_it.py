"""Full lambda-loop integration for the k-means and RDF app families,
plus a real hyperparameter-tuning run through ALSUpdate.

Reference analogs: KMeansUpdateIT / RDFUpdateIT (full batch build over
a local cluster, assert published model + update-topic traffic) and
ALSHyperParamTuningIT.java:36 (grid of candidates, best model wins).
The ALS full loop lives in test_lambda_it.py; these cover the other
two app families end-to-end over the in-proc broker: input topic ->
BatchLayer generation -> MODEL on the update topic -> ServingLayer
replay -> live REST answers.
"""

import json
import time
import urllib.request

import numpy as np

from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_MODEL
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.batch import BatchLayer
from oryx_tpu.lambda_rt.serving import ServingLayer


def _await_model(serving, min_fraction=0.8, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        model = serving.model_manager.get_model()
        if model is not None and model.get_fraction_loaded() >= min_fraction:
            return model
        time.sleep(0.05)
    raise AssertionError("serving model never loaded")


def _get(serving, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{serving.port}{path}", timeout=10) as r:
        return json.loads(r.read())


def test_kmeans_full_loop(tmp_path):
    cfg = from_dict({
        "oryx.id": "kmit",
        "oryx.input-topic.broker": "memory://kmit",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "KmIn",
        "oryx.update-topic.broker": "memory://kmit",
        "oryx.update-topic.message.topic": "KmUp",
        "oryx.batch.update-class": "oryx_tpu.app.kmeans.update.KMeansUpdate",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.kmeans.serving.KMeansServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.clustering",
        "oryx.kmeans.hyperparams.k": 3,
        "oryx.input-schema.num-features": 2,
        "oryx.input-schema.numeric-features": ["0", "1"],
        "oryx.ml.eval.test-fraction": 0.2,
    })
    broker = get_broker("kmit")
    rng = np.random.default_rng(11)
    centers = np.array([[0.0, 0.0], [8.0, 8.0], [-8.0, 8.0]])
    for i in range(300):
        c = centers[i % 3] + rng.standard_normal(2) * 0.4
        broker.send("KmIn", None, f"{c[0]:.3f},{c[1]:.3f}")

    BatchLayer(cfg).run_one_generation()
    msgs = list(broker.consume("KmUp", from_beginning=True, max_idle_sec=0.2))
    assert msgs and msgs[0].key == KEY_MODEL
    assert "ClusteringModel" in msgs[0].message

    serving = ServingLayer(cfg, port=0)
    serving.start()
    try:
        _await_model(serving)
        # points near each true center land in three distinct clusters
        assigns = {int(_get(serving, f"/assign/{x},{y}"))
                   for x, y in [(0, 0), (8, 8), (-8, 8)]}
        assert len(assigns) == 3
        d = float(_get(serving, "/distanceToNearest/0.1,0.1"))
        assert d < 2.0
    finally:
        serving.close()


def test_rdf_full_loop(tmp_path):
    cfg = from_dict({
        "oryx.id": "rdfit",
        "oryx.input-topic.broker": "memory://rdfit",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "RdfIn",
        "oryx.update-topic.broker": "memory://rdfit",
        "oryx.update-topic.message.topic": "RdfUp",
        "oryx.batch.update-class": "oryx_tpu.app.rdf.update.RDFUpdate",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.rdf.serving.RDFServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.classreg",
        "oryx.rdf.num-trees": 5,
        "oryx.input-schema.feature-names": ["a", "b", "label"],
        "oryx.input-schema.numeric-features": ["a", "b"],
        "oryx.input-schema.target-feature": "label",
        "oryx.ml.eval.test-fraction": 0.2,
    })
    broker = get_broker("rdfit")
    rng = np.random.default_rng(13)
    for _ in range(400):
        a, b = rng.uniform(-1, 1, 2)
        label = "pos" if a + 0.5 * b > 0 else "neg"
        broker.send("RdfIn", None, f"{a:.3f},{b:.3f},{label}")

    BatchLayer(cfg).run_one_generation()
    msgs = list(broker.consume("RdfUp", from_beginning=True, max_idle_sec=0.2))
    assert msgs and msgs[0].key == KEY_MODEL
    assert "MiningModel" in msgs[0].message or "TreeModel" in msgs[0].message

    serving = ServingLayer(cfg, port=0)
    serving.start()
    try:
        _await_model(serving)
        # trailing comma = empty target slot (reference datum format)
        assert _get(serving, "/predict/0.9,0.4,") == "pos"
        assert _get(serving, "/predict/-0.9,-0.4,") == "neg"
        dist = _get(serving, "/classificationDistribution/0.9,0.4,")
        probs = {d["id"]: d["value"] for d in dist}
        assert probs["pos"] > probs["neg"]
        importances = _get(serving, "/feature/importance")
        assert len(importances) == 2  # two predictors
    finally:
        serving.close()


def test_als_hyperparam_tuning_picks_best(tmp_path):
    """Real grid search through ALSUpdate: two candidate feature counts,
    best held-out eval wins and its PMML records the winning value
    (reference: ALSHyperParamTuningIT.java:36)."""
    cfg = from_dict({
        "oryx.id": "alsht",
        "oryx.input-topic.broker": "memory://alsht",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "HtIn",
        "oryx.update-topic.broker": "memory://alsht",
        "oryx.update-topic.message.topic": "HtUp",
        "oryx.batch.update-class": "oryx_tpu.app.als.update.ALSUpdate",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.als.iterations": 3,
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": [2, 4],
        "oryx.ml.eval.test-fraction": 0.25,
        "oryx.ml.eval.candidates": 2,
        "oryx.ml.eval.parallelism": 2,
    })
    broker = get_broker("alsht")
    rng = np.random.default_rng(17)
    t = 1_700_000_000_000
    for u in range(24):
        for i in range(16):
            if rng.random() < 0.5:
                broker.send("HtIn", None,
                            f"u{u},i{i},{rng.exponential(1):.2f},{t}")
                t += 1000
    BatchLayer(cfg).run_one_generation()
    msgs = list(broker.consume("HtUp", from_beginning=True, max_idle_sec=0.2))
    assert msgs and msgs[0].key == KEY_MODEL
    # the published PMML's features extension holds one of the candidates
    import re
    m = re.search(r'name="features"\s+value="(\d+)"', msgs[0].message)
    assert m and int(m.group(1)) in (2, 4)


def _await_speed_model(speed, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        m = speed.model_manager.model
        if m is not None:
            return m
        time.sleep(0.05)
    raise AssertionError("speed model never loaded")


def test_kmeans_speed_full_loop(tmp_path):
    """SpeedLayer consumes the published k-means MODEL, then turns new
    input into center-update UP deltas (reference: KMeansSpeedIT)."""
    from oryx_tpu.lambda_rt.speed import SpeedLayer

    cfg = from_dict({
        "oryx.id": "kmsp",
        "oryx.input-topic.broker": "memory://kmsp",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "KmIn",
        "oryx.update-topic.broker": "memory://kmsp",
        "oryx.update-topic.message.topic": "KmUp",
        "oryx.batch.update-class": "oryx_tpu.app.kmeans.update.KMeansUpdate",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.kmeans.speed.KMeansSpeedModelManager",
        "oryx.kmeans.hyperparams.k": 2,
        "oryx.input-schema.num-features": 2,
        "oryx.input-schema.numeric-features": ["0", "1"],
        "oryx.ml.eval.test-fraction": 0.2,
    })
    broker = get_broker("kmsp")
    rng = np.random.default_rng(21)
    for i in range(200):
        c = (0.0, 0.0) if i % 2 else (9.0, 9.0)
        broker.send("KmIn", None,
                    f"{c[0] + rng.standard_normal() * 0.3:.3f},"
                    f"{c[1] + rng.standard_normal() * 0.3:.3f}")
    BatchLayer(cfg).run_one_generation()

    speed = SpeedLayer(cfg)
    speed.start()
    try:
        _await_speed_model(speed)
        before = broker.latest_offset("KmUp")
        for _ in range(10):
            broker.send("KmIn", None, "8.9,9.1")
        speed.run_one_micro_batch()
        end = broker.latest_offset("KmUp")
        ups = [json.loads(km.message)
               for km in broker.read_range("KmUp", before, end)
               if km.key == "UP"]
        assert ups, "no k-means UP deltas"
        # [clusterId, center, count]: the cluster absorbing the fed
        # points grew and its center stays near them
        grown = [u for u in ups if u[2] >= 10
                 and abs(u[1][0] - 9.0) < 1.5 and abs(u[1][1] - 9.0) < 1.5]
        assert grown, ups
    finally:
        speed.close()


def test_rdf_speed_full_loop(tmp_path):
    """SpeedLayer consumes the published forest MODEL, then routes new
    labeled examples to terminal nodes and emits leaf-update deltas
    (reference: RDFSpeedIT)."""
    from oryx_tpu.lambda_rt.speed import SpeedLayer

    cfg = from_dict({
        "oryx.id": "rdfsp",
        "oryx.input-topic.broker": "memory://rdfsp",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "RdfIn",
        "oryx.update-topic.broker": "memory://rdfsp",
        "oryx.update-topic.message.topic": "RdfUp",
        "oryx.batch.update-class": "oryx_tpu.app.rdf.update.RDFUpdate",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.rdf.speed.RDFSpeedModelManager",
        "oryx.rdf.num-trees": 3,
        "oryx.input-schema.feature-names": ["a", "b", "label"],
        "oryx.input-schema.numeric-features": ["a", "b"],
        "oryx.input-schema.target-feature": "label",
        "oryx.ml.eval.test-fraction": 0.2,
    })
    broker = get_broker("rdfsp")
    rng = np.random.default_rng(22)
    for _ in range(300):
        a, b = rng.uniform(-1, 1, 2)
        label = "pos" if a > 0 else "neg"
        broker.send("RdfIn", None, f"{a:.3f},{b:.3f},{label}")
    BatchLayer(cfg).run_one_generation()

    speed = SpeedLayer(cfg)
    speed.start()
    try:
        _await_speed_model(speed)
        before = broker.latest_offset("RdfUp")
        for _ in range(8):
            broker.send("RdfIn", None, "0.9,0.0,pos")
        speed.run_one_micro_batch()
        end = broker.latest_offset("RdfUp")
        ups = [json.loads(km.message)
               for km in broker.read_range("RdfUp", before, end)
               if km.key == "UP"]
        assert ups, "no RDF UP deltas"
        # [treeID, nodeID, counts] — per-tree terminal updates
        # (reference wire format: RDFSpeedModelManager joinJSON :127)
        assert all(isinstance(u[0], int) and isinstance(u[1], str)
                   and isinstance(u[2], dict) for u in ups)
        assert {u[0] for u in ups} <= {0, 1, 2}  # three trees
    finally:
        speed.close()
