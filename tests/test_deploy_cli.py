"""Deploy CLI + example conf tests (reference: deploy/bin/oryx-run.sh
subcommands, deploy Main classes, app/conf/*.conf)."""

import glob
import os

import pytest

from oryx_tpu.common.config import from_file
from oryx_tpu.deploy.main import main
from oryx_tpu.kafka import inproc


def test_shipped_conf_files_parse():
    confs = glob.glob(os.path.join(os.path.dirname(__file__), "..",
                                   "conf", "*.conf"))
    assert len(confs) >= 4
    for path in confs:
        cfg = from_file(path)
        # substitutions resolved and defaults overlaid
        assert cfg.get_string("oryx.input-topic.broker") == \
            cfg.get_string("oryx.update-topic.broker")
        assert cfg.get_string("oryx.serving.model-manager-class")
        assert cfg.get_int("oryx.serving.api.port") == 8080


def _write_conf(tmp_path, broker_uri):
    conf = tmp_path / "app.conf"
    conf.write_text(f"""
oryx {{
  input-topic.broker = "{broker_uri}"
  update-topic.broker = "{broker_uri}"
  input-topic.message.topic = "CliIn"
  update-topic.message.topic = "CliUp"
}}
""")
    return str(conf)


def test_cli_kafka_commands(tmp_path, capsys):
    broker_uri = f"file://{tmp_path}/broker"
    conf = _write_conf(tmp_path, broker_uri)

    assert main(["kafka-setup", "--conf", conf]) == 0
    out = capsys.readouterr().out
    assert "CliIn" in out and "exists" in out

    data = tmp_path / "lines.csv"
    data.write_text("u1,i1,1.0\nu2,i2,2.0\n\n")
    assert main(["kafka-input", "--conf", conf,
                 "--file", str(data)]) == 0

    assert main(["kafka-tail", "--once", "--conf", conf]) == 0
    out = capsys.readouterr().out
    assert "u1,i1,1.0" in out and "u2,i2,2.0" in out


def test_file_broker_survives_process_restart(tmp_path):
    broker_uri = f"file://{tmp_path}/durable"
    broker = inproc.resolve_broker(broker_uri)
    broker.send("T", "K", "hello")
    broker.set_offset("g", "T", 1)
    broker.flush()
    name = broker.name
    # simulate a new process: drop the in-memory registry entry
    with inproc._REGISTRY_LOCK:
        inproc._REGISTRY.pop(name).close()
    reloaded = inproc.resolve_broker(broker_uri)
    msgs = reloaded.read_range("T", 0, reloaded.latest_offset("T"))
    assert [(m.key, m.message) for m in msgs] == [("K", "hello")]
    assert reloaded.get_offset("g", "T") == 1


def test_cli_rejects_unknown_command():
    with pytest.raises(SystemExit):
        main(["no-such-command"])


def test_file_broker_live_between_processes(tmp_path):
    """A consumer in THIS process must see records another live process
    appends to the shared file:// broker (tailing, not just reload)."""
    import subprocess
    import sys

    broker_uri = f"file://{tmp_path}/live"
    conf = _write_conf(tmp_path, broker_uri)
    broker = inproc.resolve_broker(broker_uri)
    assert broker.latest_offset("CliIn") == 0

    data = tmp_path / "lines.csv"
    data.write_text("x1,y1,1.0\nx2,y2,2.0\n")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.path.dirname(os.path.dirname(__file__))}
    subprocess.run(
        [sys.executable, "-m", "oryx_tpu", "kafka-input",
         "--conf", conf, "--file", str(data)],
        check=True, env=env, timeout=120)

    # same broker object, no restart: tail picks the records up
    msgs = list(broker.consume("CliIn", from_beginning=True,
                               max_idle_sec=1.0))
    assert [m.message for m in msgs] == ["x1,y1,1.0", "x2,y2,2.0"]
    # and offsets committed by this process merge with the file
    broker.set_offset("g2", "CliIn", 2)
    broker.flush()


def test_cli_config_to_properties(tmp_path, capsys):
    """config-to-properties prints the resolved oryx.* tree as sorted
    key=value lines for shell consumption (reference:
    ConfigToProperties.java:29-58, used by oryx-run.sh:87)."""
    from oryx_tpu.deploy.main import main

    conf = tmp_path / "t.conf"
    conf.write_text('oryx.id = "props-test"\n')
    assert main(["config-to-properties", "--conf", str(conf)]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    # sorted by KEY (the reference's TreeMap order): a key that is a
    # strict prefix of another sorts first even when the '=' separator
    # would collate after the longer key's next char ('-' < '=')
    keys = [line.split("=", 1)[0] for line in out]
    assert keys == sorted(keys)
    assert all("=" in line and line.startswith("oryx") for line in out)
    kv = dict(line.split("=", 1) for line in out)
    assert kv["oryx.id"] == "props-test"
    # nulls are omitted like the reference's NULL case
    assert "oryx.serving.api.user-name" not in kv
    # defaults from reference.conf are resolved in
    assert kv["oryx.serving.api.read-only"] == "false"
