"""Sharded serving as a CONFIGURED mode: `oryx.serving.api.item-shards`
row-shards the item matrix over the (virtual 8-device) mesh and the
live serving layer answers the ALS endpoint surface through the SPMD
merge kernel.

Reference parity: the reference's production serving path IS its
partitioned scan — PartitionedFeatureVectors.mapPartitionsParallel
(PartitionedFeatureVectors.java:84-148) wired into ALSServingModel.topN
(ALSServingModel.java:265-280).  Round-3 shipped the kernel as a
library class only; these tests pin the full wiring: config key ->
manager -> model -> batcher -> HTTP.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from oryx_tpu.app.als.serving_model import ALSServingModel
from oryx_tpu.common.config import from_dict


def _loaded_model(item_shards, features=6, items=200, users=12,
                  seed=0, dtype="float32"):
    rng = np.random.default_rng(seed)
    m = ALSServingModel(features=features, implicit=True,
                        item_shards=item_shards, dtype=dtype)
    m.Y.bulk_load([f"i{j}" for j in range(items)],
                  rng.standard_normal((items, features)).astype(np.float32))
    m.X.bulk_load([f"u{j}" for j in range(users)],
                  rng.standard_normal((users, features)).astype(np.float32))
    return m


def test_sharded_agrees_with_single_chip_exactly():
    single = _loaded_model(1)
    sharded = _loaded_model(8)
    rng = np.random.default_rng(3)
    Q = rng.standard_normal((5, 6)).astype(np.float32)
    a = single.top_n_batch(10, Q, use_lsh=False)
    b = sharded.top_n_batch(10, Q)
    for ra, rb in zip(a, b):
        assert [i for i, _ in ra] == [i for i, _ in rb]
        np.testing.assert_allclose([s for _, s in ra],
                                   [s for _, s in rb], rtol=1e-5)


def test_sharded_exclusions_and_per_request_howmany():
    sharded = _loaded_model(8)
    rng = np.random.default_rng(4)
    Q = rng.standard_normal((3, 6)).astype(np.float32)
    plain = sharded.top_n_batch([4, 2, 6], Q)
    excl = [{plain[0][0][0], plain[0][1][0]}, set(), {plain[2][0][0]}]
    got = sharded.top_n_batch([4, 2, 6], Q, exclude=excl)
    assert [len(r) for r in got] == [4, 2, 6]
    for r, e in zip(got, excl):
        assert not ({i for i, _ in r} & e)


def test_sharded_update_then_query_sees_new_item():
    sharded = _loaded_model(8, items=64)
    # a dominant new item via the UP-style single-vector write path
    sharded.set_item_vector("hot", np.full(6, 10.0, np.float32))
    got = sharded.top_n_batch(3, np.ones((1, 6), np.float32))[0]
    assert got[0][0] == "hot"


def test_sharded_model_ignores_lsh():
    m = _loaded_model(8)
    from oryx_tpu.app.als.lsh import LocalitySensitiveHash

    m.lsh = LocalitySensitiveHash(0.3, 6)
    assert not m._lsh_active()
    # and the scan still answers
    assert m.top_n_batch(5, np.ones((1, 6), np.float32))[0]


def test_manager_builds_sharded_model_from_config():
    from oryx_tpu.app.als.serving_manager import ALSServingModelManager
    from oryx_tpu.common import pmml as pmml_io

    cfg = from_dict({"oryx.serving.api.item-shards": 8})
    mgr = ALSServingModelManager(cfg)
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", 6)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", ["u0"])
    pmml_io.add_extension_content(doc, "YIDs", ["i0", "i1"])
    mgr.consume_key_message("MODEL", pmml_io.to_string(doc))
    assert mgr.get_model()._item_shards == 8
    mgr.consume_key_message("UP", json.dumps(["Y", "i0", [1, 0, 0, 0, 0, 0]]))
    mgr.consume_key_message("UP", json.dumps(["Y", "i1", [0, 1, 0, 0, 0, 0]]))
    mgr.consume_key_message("UP", json.dumps(["X", "u0", [1, 1, 0, 0, 0, 0]]))
    got = mgr.get_model().top_n_batch(2, np.asarray([[1, 0, 0, 0, 0, 0]],
                                                    np.float32))[0]
    assert got[0][0] == "i0"


def test_manager_rejects_non_pow2_shards():
    from oryx_tpu.app.als.serving_manager import ALSServingModelManager

    with pytest.raises(ValueError):
        ALSServingModelManager(from_dict(
            {"oryx.serving.api.item-shards": 3}))


@pytest.fixture(scope="module")
def sharded_server():
    from oryx_tpu.bench.load import StaticModelManager
    from oryx_tpu.lambda_rt.http import HttpApp, make_server
    from oryx_tpu.serving import als as als_resources
    from oryx_tpu.serving import framework as framework_resources
    from oryx_tpu.serving.batcher import TopNBatcher

    model = _loaded_model(8, items=500, users=20)
    model.add_known_items("u0", ["i1", "i2"])
    StaticModelManager.model = model
    batcher = TopNBatcher(pipeline=2)
    app = HttpApp(
        framework_resources.ROUTES + als_resources.ROUTES,
        context={"model_manager": StaticModelManager(),
                 "input_producer": None, "config": None,
                 "min_model_load_fraction": 0.0,
                 "top_n_batcher": batcher},
        read_only=True)
    server = make_server(app, 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield port, model
    server.shutdown()
    batcher.close()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return json.loads(r.read())


def test_http_recommend_over_sharded_model(sharded_server):
    port, model = sharded_server
    recs = _get(port, "/recommend/u0?howMany=5")
    assert len(recs) == 5
    # known items are excluded, per the endpoint contract
    assert not ({r["id"] for r in recs} & {"i1", "i2"})
    # concurrent requests batch through the SPMD kernel
    results = []

    def hit(u):
        results.append(_get(port, f"/recommend/u{u}?howMany=3"))

    threads = [threading.Thread(target=hit, args=(u,)) for u in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 8 and all(len(r) == 3 for r in results)


def test_http_similarity_and_estimate_over_sharded_model(sharded_server):
    port, _ = sharded_server
    sims = _get(port, "/similarity/i3?howMany=4")
    assert len(sims) == 4
    est = _get(port, "/estimate/u1/i5")
    assert est and est[0]["id"] == "i5" \
        and isinstance(est[0]["value"], float)


def test_sharded_survives_exact_fit_odd_capacity():
    """bulk_load's exact-fit growth must round capacity to a multiple
    of the mesh size or the shard_map kernel rejects the leading dim."""
    m = _loaded_model(8, items=3001)
    assert int(m.Y.device_arrays()[0].shape[0]) % 8 == 0
    got = m.top_n_batch(5, np.ones((2, 6), np.float32))
    assert all(len(r) == 5 for r in got)
