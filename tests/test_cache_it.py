"""Result-cache integration tests (ISSUE 8 acceptance): a 2-shard
cluster, a cache+coalesce-armed router, an UNcached reference router
scattering the SAME replicas, and a speed layer driving real fold-ins
through the real update topic.  Proves

1. exactness: cached (hit) and coalesced responses are BYTE-IDENTICAL
   — JSON and CSV, gzip round-trip, tie and offset edges, randomized
   args — to a cold scatter;
2. the zero-stale guarantee: a ``/pref`` fold-in for user U followed
   by ``/recommend/U`` never serves the pre-fold-in cached rows once
   the invalidation tap has the UP record, while user V's entry
   SURVIVES (precise, not epoch, invalidation);
3. hits bypass the admission gate (overload degrades to "cached
   answers + fast 503s");
4. the chaos points: ``router-cache-stale-feed`` (stalled tap → stale
   hits, counted, rescued by the generation-publish epoch flush) and
   ``router-coalesce-leader-death`` (dead leader → followers re-issue,
   no hang); partial answers are never cached.

Marker: chaos (in the tier-1 budget).
"""

from __future__ import annotations

import gzip
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.cluster.router import RouterLayer
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.inproc import get_broker
from oryx_tpu.lambda_rt.serving import ServingLayer
from oryx_tpu.lambda_rt.speed import SpeedLayer
from oryx_tpu.resilience import faults
from oryx_tpu.resilience.policy import Deadline

pytestmark = pytest.mark.chaos

BROKER = "cache-it"
UPDATE_TOPIC = "KUp"
INPUT_TOPIC = "KIn"
FEATURES = 3


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _config(tmp_path, **extra):
    overlay = {
        "oryx.id": "cache-it",
        "oryx.input-topic.broker": f"memory://{BROKER}",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": INPUT_TOPIC,
        "oryx.update-topic.broker": f"memory://{BROKER}",
        "oryx.update-topic.message.topic": UPDATE_TOPIC,
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.als.speed.ALSSpeedModelManager",
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.als.implicit": True,
        "oryx.als.hyperparams.features": FEATURES,
        # only the explicit run_one_micro_batch() hook folds input —
        # the IT controls exactly when the fold-in happens
        "oryx.speed.streaming.generation-interval-sec": 100000,
        "oryx.cluster.heartbeat-interval-ms": 60,
        "oryx.cluster.heartbeat-ttl-ms": 400,
        "oryx.cluster.hedge-after-ms": 50,
        "oryx.cluster.shard-timeout-ms": 5000,
        "oryx.resilience.retry.max-attempts": 2,
        "oryx.resilience.retry.initial-backoff-ms": 1,
        "oryx.resilience.retry.max-backoff-ms": 2,
    }
    overlay.update(extra)
    return from_dict(overlay)


def _model_doc():
    from oryx_tpu.common import pmml as pmml_io
    users = [f"cu{j}" for j in range(6)]
    items = [f"ci{j}" for j in range(14)]
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", FEATURES)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", users)
    pmml_io.add_extension_content(doc, "YIDs", items)
    return users, items, pmml_io.to_string(doc)


def _publish_model(broker):
    """Synthetic MODEL + UP replay with EXACT ties: ci10/ci11/ci12
    share one vector, so any top-N window straddling them exercises
    the ordinal tie-break in both the cold and cached renders."""
    from oryx_tpu.kafka.api import KEY_MODEL, KEY_UP
    users, items, doc = _model_doc()
    broker.send(UPDATE_TOPIC, KEY_MODEL, doc)
    rng = np.random.default_rng(17)
    tied = [float(x) for x in rng.standard_normal(FEATURES)]
    for iid in items:
        vec = tied if iid in ("ci10", "ci11", "ci12") \
            else [float(x) for x in rng.standard_normal(FEATURES)]
        broker.send(UPDATE_TOPIC, KEY_UP, json.dumps(["Y", iid, vec]))
    for uid in users:
        broker.send(UPDATE_TOPIC, KEY_UP, json.dumps(
            ["X", uid, [float(x) for x in rng.standard_normal(FEATURES)],
             []]))
    return users, items


def _raw_get(port, path, headers=None, timeout=15):
    """(status, headers, raw body bytes) — byte-identity assertions
    must see the wire bytes, not a parsed view."""
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), r.read()


def _await(predicate, what, timeout=30.0):
    deadline = Deadline.after(timeout)
    while not deadline.expired:
        try:
            if predicate():
                return
        except (urllib.error.HTTPError, urllib.error.URLError, OSError):
            pass
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _cache_stats(router):
    return json.loads(_raw_get(router.port, "/admin/cache")[2])


def _flush(router):
    req = urllib.request.Request(
        f"http://127.0.0.1:{router.port}/admin/cache/flush",
        data=b"", method="POST")
    with urllib.request.urlopen(req, timeout=15) as r:
        return json.loads(r.read())


def _foldable_item(cluster, uid):
    """An item whose current estimated strength leaves the implicit
    fold-in room to move: computeTargetQui returns NaN ("no change")
    for a positive event when the estimate is already >= 1, so a test
    that needs the fold-in to CHANGE the user's vector must pick a
    pair below that ceiling."""
    cold = cluster["cold"]
    path = f"/estimate/{uid}/" + "/".join(cluster["items"])
    vals = json.loads(_raw_get(cold.port, path)[2])
    for d in sorted(vals, key=lambda d: d["value"]):
        if 0.0 <= d["value"] < 0.8:
            return d["id"]
    return min(vals, key=lambda d: abs(d["value"]))["id"]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """2 shards + cache-armed router + UNcached reference router over
    the same replicas + a speed layer for real fold-ins."""
    tmp_path = tmp_path_factory.mktemp("cache-it")
    broker = get_broker(BROKER)
    users, items = _publish_model(broker)

    def cfg_fn(extra=None):
        return _config(tmp_path, **(extra or {}))

    replicas = []
    for s in range(2):
        layer = ServingLayer(cfg_fn({
            "oryx.cluster.enabled": True,
            "oryx.cluster.shard": f"{s}/2"}), port=0)
        layer.start()
        replicas.append(layer)
    cached = RouterLayer(cfg_fn({
        "oryx.cluster.cache.enabled": True,
        "oryx.cluster.coalesce.enabled": True}), port=0)
    cached.start()
    cold = RouterLayer(cfg_fn(), port=0)
    cold.start()
    speed = SpeedLayer(cfg_fn())
    speed.start()

    def ready(router):
        return _raw_get(router.port, "/ready")[0] in (200, 204)

    def fully_loaded(layer):
        # /ready fires at the 0.8 load gate with the user store still
        # filling (items stream first); the IT drives the LAST users
        # in the replay, so wait for the complete model
        meta = json.loads(_raw_get(layer.port, "/shard/meta")[2])
        return meta.get("users", 0) >= len(users)

    _await(lambda: ready(cached), "cached router readiness")
    _await(lambda: ready(cold), "cold router readiness")
    _await(lambda: all(fully_loaded(r) for r in replicas),
           "full replica replay")
    _await(lambda: (m := speed.model_manager.model) is not None
           and m.get_fraction_loaded() >= 0.8, "speed model")
    yield {"cfg_fn": cfg_fn, "replicas": replicas, "cached": cached,
           "cold": cold, "speed": speed, "broker": broker,
           "users": users, "items": items}
    for layer in replicas + [cached, cold, speed]:
        try:
            layer.close()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass


# -- 1. exactness -------------------------------------------------------------

def _verdict(headers):
    return headers.get("X-Oryx-Cache")


def test_hit_and_miss_are_byte_identical_to_a_cold_scatter(cluster):
    cached, cold = cluster["cached"], cluster["cold"]
    _flush(cached)
    for uid in cluster["users"][:3]:
        for qs in ("?howMany=5", "?howMany=10&offset=3",
                   "?howMany=4&considerKnownItems=true"):
            path = f"/recommend/{uid}{qs}"
            _, ch, cold_body = _raw_get(cold.port, path)
            assert _verdict(ch) is None  # uncached router: no stamp
            s1, h1, miss_body = _raw_get(cached.port, path)
            s2, h2, hit_body = _raw_get(cached.port, path)
            assert (s1, s2) == (200, 200)
            assert _verdict(h1) == "miss" and _verdict(h2) == "hit"
            assert miss_body == cold_body == hit_body, path


def test_csv_variant_is_byte_identical_and_rendered_once(cluster):
    cached, cold = cluster["cached"], cluster["cold"]
    uid = cluster["users"][0]
    path = f"/recommend/{uid}?howMany=6"
    hdr = {"Accept": "text/csv"}
    _, _, cold_csv = _raw_get(cold.port, path, headers=hdr)
    _raw_get(cached.port, path)  # prime via the JSON form
    _, h, csv1 = _raw_get(cached.port, path, headers=hdr)
    assert _verdict(h) == "hit"
    assert csv1 == cold_csv
    # JSON and CSV verdicts come from ONE entry (same key, two
    # variants) — the second CSV read reuses the rendered bytes
    _, h2, csv2 = _raw_get(cached.port, path, headers=hdr)
    assert _verdict(h2) == "hit" and csv2 == csv1


def test_gzip_hit_skips_recompression_and_round_trips(cluster):
    cached, cold = cluster["cached"], cluster["cold"]
    uid = cluster["users"][1]
    # a body comfortably past the 256-byte gzip threshold
    path = f"/recommend/{uid}?howMany=14&considerKnownItems=true"
    hdr = {"Accept-Encoding": "gzip"}
    _, _, cold_gz = _raw_get(cold.port, path, headers=hdr)
    _raw_get(cached.port, path)
    _, h, gz1 = _raw_get(cached.port, path, headers=hdr)
    assert _verdict(h) == "hit"
    assert h.get("Content-Encoding") == "gzip"
    assert gzip.decompress(gz1) == gzip.decompress(cold_gz)
    # cached gzip bytes are deterministic (mtime pinned): stored once,
    # re-served verbatim
    _, _, gz2 = _raw_get(cached.port, path, headers=hdr)
    assert gz2 == gz1


def test_exactness_property_random_args_and_tie_offsets(cluster):
    """Randomized (user, howMany, offset) sweep, biased toward windows
    straddling the ci10/ci11/ci12 exact-tie group: every cached answer
    byte-identical to the cold scatter, JSON and CSV."""
    cached, cold = cluster["cached"], cluster["cold"]
    _flush(cached)
    rng = np.random.default_rng(23)
    users = cluster["users"]
    for _ in range(25):
        uid = users[int(rng.integers(0, len(users)))]
        how_many = int(rng.integers(1, 16))
        offset = int(rng.integers(0, 12))
        path = (f"/recommend/{uid}?howMany={how_many}"
                f"&offset={offset}&considerKnownItems=true")
        accept = {"Accept": "text/csv"} if rng.random() < 0.4 else None
        _, _, cold_body = _raw_get(cold.port, path, headers=accept)
        _, h1, b1 = _raw_get(cached.port, path, headers=accept)
        _, h2, b2 = _raw_get(cached.port, path, headers=accept)
        assert b1 == cold_body == b2, path
        assert _verdict(h2) == "hit", path


def test_wider_cacheable_surface_is_byte_identical(cluster):
    cached, cold = cluster["cached"], cluster["cold"]
    uid, items = cluster["users"][0], cluster["items"]
    i1, i2 = items[0], items[1]
    for path in (f"/similarity/{i1}/{i2}?howMany=5",
                 f"/similarityToItem/{i1}/{i2}/{items[2]}",
                 f"/estimate/{uid}/{i1}/{i2}",
                 f"/because/{uid}/{i1}?howMany=4",
                 f"/mostSurprising/{uid}",
                 f"/knownItems/{uid}",
                 f"/recommendToMany/{uid}/{cluster['users'][1]}",
                 f"/recommendToAnonymous/{i1}=2.0/{i2}",
                 f"/recommendWithContext/{uid}/{i1}=1.5",
                 f"/estimateForAnonymous/{i1}/{i2}=0.5"):
        _, _, cold_body = _raw_get(cold.port, path)
        _, h1, b1 = _raw_get(cached.port, path)
        _, h2, b2 = _raw_get(cached.port, path)
        assert b1 == cold_body == b2, path
        assert _verdict(h1) in ("miss", "hit")
        assert _verdict(h2) == "hit", path


def test_rescorer_params_are_never_cached(cluster):
    cached = cluster["cached"]
    uid = cluster["users"][2]
    path = f"/recommend/{uid}?howMany=3&rescorerParams=x"
    for _ in range(2):
        _, h, _ = _raw_get(cached.port, path)
        assert _verdict(h) is None  # not even stamped: uncacheable


def test_coalesced_burst_collapses_to_one_scatter(cluster):
    cached, cold = cluster["cached"], cluster["cold"]
    _flush(cached)
    uid = cluster["users"][3]
    path = f"/recommend/{uid}?howMany=7"
    _, _, cold_body = _raw_get(cold.port, path)
    before = _cache_stats(cached)["coalesced_requests"]
    results = []
    barrier = threading.Barrier(8)

    def one():
        barrier.wait()
        s, h, b = _raw_get(cached.port, path, timeout=30)
        results.append((s, _verdict(h), b))

    threads = [threading.Thread(target=one) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert len(results) == 8
    assert all(s == 200 and b == cold_body for s, _, b in results)
    verdicts = {v for _, v, _ in results}
    assert verdicts <= {"miss", "coalesced", "hit"}
    # at least one follower latched onto the leader's scatter (the
    # rest may have arrived after completion and hit the stored entry)
    after = _cache_stats(cached)
    assert after["coalesced_requests"] + after["hits"] > before


# -- 2. the zero-stale guarantee ----------------------------------------------

def test_fold_in_evicts_touched_user_and_spares_the_rest(cluster):
    cached, cold, speed = (cluster["cached"], cluster["cold"],
                           cluster["speed"])
    u, v = cluster["users"][4], cluster["users"][5]
    item = _foldable_item(cluster, u)
    _flush(cached)
    pu = f"/recommend/{u}?howMany=8"
    pv = f"/recommend/{v}?howMany=8"
    _, _, u_before = _raw_get(cached.port, pu)   # prime U
    _, _, v_before = _raw_get(cached.port, pv)   # prime V
    assert _verdict(_raw_get(cached.port, pu)[1]) == "hit"
    inval_before = _cache_stats(cached)["invalidations"]

    # the real write path: /pref through the router -> input topic ->
    # speed micro-batch -> UP fold-in on the update topic
    req = urllib.request.Request(
        f"http://127.0.0.1:{cached.port}/pref/{u}/{item}", data=b"5.0",
        method="POST")
    with urllib.request.urlopen(req, timeout=15) as r:
        assert r.status in (200, 204)
    speed.run_one_micro_batch()

    # wait until BOTH consumers of the totally ordered topic are
    # there: the replicas (the cold answer moves) and the router's
    # invalidation tap (the counter moves)
    _await(lambda: _raw_get(cold.port, pu)[2] != u_before,
           "replicas absorbed the fold-in")
    _await(lambda: _cache_stats(cached)["invalidations"] > inval_before,
           "invalidation tap caught up")

    # U: the pre-fold-in rows are GONE — a fresh miss, byte-identical
    # to the cold scatter of the post-fold-in state
    s, h, u_after = _raw_get(cached.port, pu)
    assert s == 200 and _verdict(h) == "miss"
    assert u_after != u_before
    assert u_after == _raw_get(cold.port, pu)[2]
    # V: untouched by the fold-in — the entry SURVIVED (precise
    # invalidation, not an epoch flush) and still serves its bytes
    s, h, v_after = _raw_get(cached.port, pv)
    assert s == 200 and _verdict(h) == "hit"
    assert v_after == v_before


# -- 3. admission bypass ------------------------------------------------------

def test_cache_hits_bypass_admission_shedding(cluster, tmp_path):
    """With the admission gate slammed shut (max-inflight far below
    the probe's concurrency is the production shape; here: a gate of 1
    and an occupied slot), cached answers still flow while cold keys
    shed — overload degrades to 'cached answers + fast 503s'."""
    cfg_fn = cluster["cfg_fn"]
    router = RouterLayer(cfg_fn({
        "oryx.cluster.cache.enabled": True,
        "oryx.cluster.admission.max-inflight": 1}), port=0)
    router.start()
    try:
        _await(lambda: _raw_get(router.port, "/ready")[0] in (200, 204),
               "admission router readiness")
        uid = cluster["users"][0]
        path = f"/recommend/{uid}?howMany=5"
        _, h, body = _raw_get(router.port, path)
        assert _verdict(h) == "miss"
        # hold the single admission slot hostage
        assert router.admission.try_acquire()[0]
        try:
            s, h, b = _raw_get(router.port, path)
            assert s == 200 and _verdict(h) == "hit" and b == body
            with pytest.raises(urllib.error.HTTPError) as e:
                _raw_get(router.port,
                         f"/recommend/{cluster['users'][1]}?howMany=5")
            assert e.value.code == 503  # cold key: shed at the door
            assert e.value.headers.get("Retry-After")
        finally:
            router.admission.release()
    finally:
        router.close()


# -- 4. chaos -----------------------------------------------------------------

def test_stale_feed_stall_counts_and_generation_flush_rescues(cluster):
    """``router-cache-stale-feed``: the invalidation tap stalls, the
    touched user's cached rows keep serving (counted evidence), and
    the epoch flush on the next generation publish is the safety
    valve."""
    from oryx_tpu.kafka.api import KEY_MODEL
    cached, cold, speed = (cluster["cached"], cluster["cold"],
                           cluster["speed"])
    broker = cluster["broker"]
    w = cluster["users"][0]
    item = _foldable_item(cluster, w)
    _flush(cached)
    pw = f"/recommend/{w}?howMany=8"
    _, _, w_before = _raw_get(cached.port, pw)
    faults.inject("router-cache-stale-feed", mode="drop", times=None)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{cached.port}/pref/{w}/{item}",
            data=b"4.0", method="POST")
        with urllib.request.urlopen(req, timeout=15) as r:
            assert r.status in (200, 204)
        speed.run_one_micro_batch()
        _await(lambda: _raw_get(cold.port, pw)[2] != w_before,
               "replicas absorbed the fold-in")
        _await(lambda: _cache_stats(cached)["stale_feed_stalls"] > 0,
               "stall evidence counted")
        # the stalled tap leaves the PRE-fold-in rows serving: the
        # documented failure mode, visible and bounded
        s, h, still = _raw_get(cached.port, pw)
        assert s == 200 and _verdict(h) == "hit" and still == w_before
    finally:
        faults.clear("router-cache-stale-feed")
    # safety valve: a generation publish flushes the epoch even though
    # the per-user feed was stalled while armed
    _, _, doc = _model_doc()
    flushes_before = _cache_stats(cached)["epoch_flushes"]
    broker.send(UPDATE_TOPIC, KEY_MODEL, doc)
    _await(lambda: _cache_stats(cached)["epoch_flushes"] > flushes_before,
           "generation publish flushed the epoch")

    def fresh():
        s, h, now = _raw_get(cached.port, pw)
        return s == 200 and now == _raw_get(cold.port, pw)[2]
    _await(fresh, "post-flush answers fresh")


def test_coalesce_leader_death_followers_reissue(cluster):
    """``router-coalesce-leader-death``: the latch leader dies — every
    follower re-issues its own scatter; nobody hangs, nobody serves a
    broken entry."""
    cached, cold = cluster["cached"], cluster["cold"]
    _flush(cached)
    uid = cluster["users"][2]
    path = f"/recommend/{uid}?howMany=9"
    _, _, cold_body = _raw_get(cold.port, path)
    faults.inject("router-coalesce-leader-death", mode="error", times=1)
    results = []
    barrier = threading.Barrier(6)

    def one():
        barrier.wait()
        try:
            s, h, b = _raw_get(cached.port, path, timeout=30)
            results.append((s, b))
        except urllib.error.HTTPError as e:
            e.read()
            results.append((e.code, None))

    threads = [threading.Thread(target=one) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert len(results) == 6  # nobody hung
    assert faults.fired("router-coalesce-leader-death") == 1
    oks = [b for s, b in results if s == 200]
    assert len(oks) >= 5  # only the injected leader may have died
    assert all(b == cold_body for b in oks)


def test_partial_answers_are_never_cached(cluster):
    """A shard stalled past the deadline degrades to a partial answer
    — stamped miss, never stored: the next full answer is a miss too,
    and only IT becomes the cached entry."""
    cached = cluster["cached"]
    _flush(cached)
    uid = cluster["users"][1]
    path = f"/recommend/{uid}?howMany=6"
    faults.inject("router-shard-timeout", mode="delay", times=1,
                  delay_sec=2.0)
    s, h, _ = _raw_get(cached.port, path,
                       headers={"X-Deadline-Ms": "800"}, timeout=15)
    assert s == 200
    assert h.get("X-Oryx-Partial") == "shards=1/2"
    assert _verdict(h) == "miss"
    # the partial was NOT stored: the next request misses again ...
    s, h, full = _raw_get(cached.port, path)
    assert s == 200 and h.get("X-Oryx-Partial") is None
    assert _verdict(h) == "miss"
    # ... and the full answer is what hits from now on
    s, h, again = _raw_get(cached.port, path)
    assert _verdict(h) == "hit" and again == full


def test_admin_cache_stats_and_flush_surface(cluster):
    cached = cluster["cached"]
    uid = cluster["users"][0]
    _raw_get(cached.port, f"/recommend/{uid}?howMany=3")
    st = _cache_stats(cached)
    assert st["enabled"] and st["coalesce"]
    assert st["entries"] >= 1 and st["bytes"] > 0
    assert {"hits", "misses", "evictions", "invalidations",
            "coalesced_requests", "stale_feed_stalls",
            "epoch_flushes"} <= set(st)
    out = _flush(cached)
    assert out["flushed"] >= 1 and out["stats"]["entries"] == 0
    # the metrics surface carries the same stats block + counters
    _, _, m = _raw_get(cached.port, "/metrics")
    m = json.loads(m)
    assert "cache" in m["cluster"]
    assert "cache_hits" in m["counters"]


def test_cold_router_404s_admin_cache(cluster):
    with pytest.raises(urllib.error.HTTPError) as e:
        _raw_get(cluster["cold"].port, "/admin/cache")
    assert e.value.code == 404


def _raw_get_any(port, path, headers=None, timeout=15):
    """_raw_get that returns error responses instead of raising — the
    negative-caching assertions inspect 404 headers/bodies."""
    try:
        return _raw_get(port, path, headers=headers, timeout=timeout)
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_hot_404_is_negative_cached_until_the_user_is_created(cluster):
    """Negative caching (ISSUE 10 satellite): an unknown user's 404 is
    cached — the second probe never scatters — and the fold-in that
    CREATES the user evicts it, after which the user serves real
    rows."""
    cached, cold, speed = (cluster["cached"], cluster["cold"],
                           cluster["speed"])
    _flush(cached)
    ghost = "ghost-user-404"
    path = f"/recommend/{ghost}?howMany=5"
    s, h, body = _raw_get_any(cached.port, path)
    assert s == 404 and _verdict(h) == "miss"
    stats0 = _cache_stats(cached)
    s2, h2, body2 = _raw_get_any(cached.port, path)
    assert s2 == 404 and _verdict(h2) == "hit"
    assert body2 == body  # the error page re-renders byte-identically
    stats1 = _cache_stats(cached)
    assert stats1["negative_hits"] == stats0["negative_hits"] + 1
    # misses did NOT move on the hit: no scatter happened
    assert stats1["misses"] == stats0["misses"]

    # now CREATE the user through the real write path: /pref -> input
    # topic -> speed micro-batch -> UP X record for the new user
    item = cluster["items"][0]
    req = urllib.request.Request(
        f"http://127.0.0.1:{cached.port}/pref/{ghost}/{item}",
        data=b"5.0", method="POST")
    with urllib.request.urlopen(req, timeout=15) as r:
        assert r.status in (200, 204)
    speed.run_one_micro_batch()
    # replicas absorb the new user; the tap's UP eviction kills the 404
    _await(lambda: _raw_get_any(cold.port, path)[0] == 200,
           "replicas absorbed the new user")
    _await(lambda: _raw_get_any(cached.port, path)[0] == 200,
           "negative entry evicted by the creating UP record")
    s, h, rows = _raw_get_any(cached.port, path)
    assert s == 200 and _verdict(h) in ("miss", "hit")
    assert json.loads(rows)  # real recommendations now
