"""Child process for the two-process FULL-LAMBDA multi-host IT.

Each child is one "host" of a 2-process jax.distributed cluster.  BOTH
run the real ``ALSUpdate.run_update`` over the global mesh (the
training collectives are SPMD — every process must execute them), on
identical seeded input.  Process 0 additionally:

  - publishes the winning model to a shared ``file://`` broker's update
    topic (the cross-process transport tested in test_deploy_cli),
  - boots a ``ServingLayer`` that replays that topic and answers a live
    HTTP ``/recommend`` from the process-spanning-trained model, and
  - boots a ``SpeedLayer`` that consumes the same published model and
    folds a micro-batch of NEW input (an unseen user) into UP deltas,
    which the still-running serving layer absorbs — proving the full
    batch -> speed -> serving lambda triangle inside the multi-host
    cluster (VERDICT r5 Missing #3 / ISSUE 3 satellite).

Prints LAMBDA_OK + a JSON payload on success; DISTRIBUTED_UNSUPPORTED
when the platform cannot initialize a multi-process CPU cluster (the
parent skips).  Reference analog: the batch layer training on a YARN
cluster while the serving layer answers from the published model
(SURVEY §2.14 P1/P3).
"""

from __future__ import annotations

import json
import sys


def main() -> None:
    coord, pid, n_dev, repo, work = (sys.argv[1], int(sys.argv[2]),
                                     int(sys.argv[3]), sys.argv[4],
                                     sys.argv[5])
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    sys.path.insert(0, repo)
    from oryx_tpu.common.config import from_dict
    from oryx_tpu.parallel.mesh import initialize_multihost

    cfg = from_dict({
        "oryx.id": "mhlambda",
        "oryx.distributed.coordinator-address": coord,
        "oryx.distributed.num-processes": 2,
        "oryx.distributed.process-id": pid,
        # force the mesh over the virtual CPU devices (both processes)
        "oryx.batch.streaming.master": "mesh",
        "oryx.input-topic.broker": f"file://{work}/broker",
        "oryx.input-topic.partitions": 1,
        "oryx.input-topic.message.topic": "MhIn",
        "oryx.update-topic.broker": f"file://{work}/broker",
        "oryx.update-topic.message.topic": "MhUp",
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.als.speed.ALSSpeedModelManager",
        "oryx.speed.min-model-load-fraction": 0.0,
        "oryx.als.hyperparams.features": 4,
        "oryx.als.implicit": True,
        "oryx.als.iterations": 3,
        "oryx.ml.eval.test-fraction": 0.0,
        "oryx.ml.eval.candidates": 1,
        # force the MODEL-REF path (the PMML exceeds this) so the run
        # proves SHARDED publish end-to-end: the batch layer writes
        # murmur2 slices + a manifest-carrying envelope, skips the
        # per-row UP flood, and serving/speed bulk-load their slices
        "oryx.update-topic.message.max-size": 512,
    })
    try:
        joined = initialize_multihost(cfg)
    except Exception as e:  # noqa: BLE001 — env capability, not a bug
        print("DISTRIBUTED_UNSUPPORTED", repr(e))
        return
    assert joined, "configured join returned False"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2 * n_dev, (len(jax.devices()), n_dev)

    from oryx_tpu.app.als.update import ALSUpdate
    from oryx_tpu.kafka.api import KEY_MODEL, KEY_MODEL_REF, KeyMessage
    from oryx_tpu.kafka.inproc import InProcTopicProducer, resolve_broker

    # identical seeded input in both processes: the training collectives
    # are one SPMD program, so both hosts must run the same step stream
    rng = np.random.default_rng(23)
    data = [KeyMessage(None,
                       f"u{rng.integers(40)},i{rng.integers(60)},1,{t}")
            for t in range(600)]

    update = ALSUpdate(cfg)
    assert update.mesh is not None and update.mesh.devices.size == 2 * n_dev
    if pid == 0:
        broker = resolve_broker(f"file://{work}/broker")
        producer = InProcTopicProducer(f"file://{work}/broker", "MhUp")
        update.run_update(0, data, [], f"{work}/model0", producer)
    else:
        # same collectives, no publishing duties (the reference's
        # executors train; only the driver writes the model)
        update.run_update(0, data, [], f"{work}/model1", None)

    payload = {"process": pid, "devices": len(jax.devices())}
    if pid == 0:
        msgs = list(broker.consume("MhUp", from_beginning=True,
                                   max_idle_sec=0.2))
        keys = [m.key for m in msgs]
        assert KEY_MODEL_REF in keys, keys[:3]
        # sharded publish: the MODEL-REF record carries the manifest
        # and NO per-row UP flood follows it (slices replace it)
        ref = next(m.message for m in msgs if m.key == KEY_MODEL_REF)
        from oryx_tpu.app.als.slices import parse_model_ref
        _, _, manifest = parse_model_ref(ref)
        assert manifest is not None and manifest["ring"] >= 1, ref[:80]
        assert not any(m.key == "UP" for m in msgs), \
            "sharded publish must skip the Y/X UP stream"
        payload["manifest_ring"] = manifest["ring"]

        import time
        import urllib.request

        from oryx_tpu.lambda_rt.serving import ServingLayer

        serving = ServingLayer(cfg, port=0)
        serving.start()
        try:
            deadline = time.time() + 60
            while time.time() < deadline:
                m = serving.model_manager.get_model()
                if m is not None and m.get_fraction_loaded() >= 0.8:
                    break
                time.sleep(0.1)
            else:
                raise AssertionError("serving model never loaded")
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{serving.port}/recommend/u1"
                    f"?howMany=3", timeout=30) as r:
                recs = json.loads(r.read())
            assert len(recs) == 3 and all("id" in x for x in recs), recs
            payload["recommend_ids"] = [x["id"] for x in recs]
            # the serving model came from SLICE bulk loads, not replay
            mgr = serving.model_manager
            assert mgr.slice_loads > 0, "expected a slice load"
            assert mgr.slice_load_fallbacks == 0
            payload["slice_loads"] = mgr.slice_loads
            payload["model_load_s"] = mgr.model_load_s

            # -- speed fold-in leg: SpeedLayer loads the SAME published
            # model, folds a micro-batch for a user the batch layer
            # never saw, and the live serving layer absorbs the UP
            # deltas — closed lambda triangle in the multi-host cluster
            from oryx_tpu.kafka.api import KEY_UP
            from oryx_tpu.lambda_rt.speed import SpeedLayer

            speed = SpeedLayer(cfg)
            speed.start()
            try:
                deadline = time.time() + 60
                while time.time() < deadline:
                    smodel = speed.model_manager.model
                    if smodel is not None and len(smodel.Y) > 0:
                        break
                    time.sleep(0.1)
                else:
                    raise AssertionError("speed model never loaded")
                before = broker.latest_offset("MhUp")
                # fold against items the published model actually has
                for item in sorted(smodel.Y.all_ids())[:3]:
                    broker.send("MhIn", None, f"unew,{item},1,999")
                speed.run_one_micro_batch()
                ups = [m.message for m in broker.read_range(
                           "MhUp", before, broker.latest_offset("MhUp"))
                       if m.key == KEY_UP]
                fold = [json.loads(u) for u in ups
                        if json.loads(u)[:2] == ["X", "unew"]]
                assert fold, f"no fold-in UP for unew in {ups[:4]}"
                assert len(fold[0][2]) == 4  # a 4-feature folded vector
                payload["fold_in_ups"] = len(ups)
                # the serving layer consumes the same topic: the folded
                # user must become servable WITHOUT any republish
                deadline = time.time() + 60
                while time.time() < deadline:
                    sm = serving.model_manager.get_model()
                    if sm is not None \
                            and sm.get_user_vector("unew") is not None:
                        break
                    time.sleep(0.1)
                else:
                    raise AssertionError(
                        "serving never absorbed the fold-in UP")
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{serving.port}"
                        f"/recommend/unew?howMany=2", timeout=30) as r:
                    new_recs = json.loads(r.read())
                assert len(new_recs) == 2, new_recs
                payload["fold_in_recommend_ids"] = \
                    [x["id"] for x in new_recs]
            finally:
                speed.close()
        finally:
            serving.close()
    print("LAMBDA_OK", json.dumps(payload))


if __name__ == "__main__":
    main()
