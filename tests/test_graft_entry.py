"""Driver contract checks: entry() compiles single-chip; dryrun_multichip
runs the sharded training step over the 8-device virtual mesh."""

import jax


def test_entry_compiles_and_runs():
    from __graft_entry__ import entry
    fn, args = entry()
    scores, idx = jax.jit(fn)(*args)
    assert scores.shape == idx.shape == (16,)


def test_dryrun_multichip_8():
    from __graft_entry__ import dryrun_multichip
    dryrun_multichip(8)


def test_dryrun_multichip_4():
    from __graft_entry__ import dryrun_multichip
    dryrun_multichip(4)
