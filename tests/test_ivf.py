"""ISSUE 18 tier-1 coverage: the IVF-ANN serving path.

Partition property tests on the test_cluster_merge-style exact-grid
harness (every item in exactly one cell, union == catalog), recall
monotone in ``nprobe``, the ``nprobe == cells`` byte-identity claim,
index determinism (PR 8/PR 11 result-cache byte-identity rides on it),
the ``mirror_shapes`` <-> warmup lock-step, the per-generation recall
certificate on quality-oracle-trained factors (PR 2 harness), the
certificate GATE (the router provably never serves ANN below
``oryx.als.ann.min-recall``), the ``ann-index-corrupt`` chaos point's
fail-closed fallback, and the per-slice index artifact round-trip.

All CPU-runnable: the IVF phase-A kernel is plain jit (no pallas), and
the streaming dispatch is forced with the test_int8_route knob idiom.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from oryx_tpu.app.als import ivf
from oryx_tpu.app.als import serving_model as sm
from oryx_tpu.app.als.serving_manager import ALSServingModelManager
from oryx_tpu.app.als.serving_model import ALSServingModel
from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.common.config import from_dict
from oryx_tpu.kafka.api import KEY_MODEL, KEY_MODEL_REF, KEY_UP
from oryx_tpu.ops import ann as ops_ann
from oryx_tpu.resilience import faults

BS = sm._BLOCK_ROWS


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _cfg(cells, nprobe, **kw):
    kw.setdefault("enabled", True)
    kw.setdefault("min_recall", 0.95)
    kw.setdefault("recall_at", 50)
    kw.setdefault("recall_queries", 64)
    kw.setdefault("train_sample", max(cells, 1024))
    kw.setdefault("train_iterations", 8)
    return ivf.AnnConfig(cells=cells, nprobe=nprobe, **kw)


def _mixture(rng, n, features, ncomp, spread=0.25):
    """Clustered item factors (what trained ALS factors look like):
    a gaussian mixture, lane-padded to the 128-lane device width."""
    comp = rng.standard_normal((ncomp, features))
    pick = rng.integers(0, ncomp, size=n)
    y = (comp[pick] + spread * rng.standard_normal((n, features))
         ).astype(np.float32)
    yp = np.zeros((n, 128), np.float32)
    yp[:, :features] = y
    return y, yp


def _recall_vs_exact(an_i, ex_i, k):
    hits = total = 0
    for b in range(len(ex_i)):
        hits += len(set(map(int, an_i[b])) & set(map(int, ex_i[b])))
        total += k
    return hits / total


# -- partition properties -----------------------------------------------------

@pytest.mark.parametrize("cells", [4, 8])
def test_partition_every_row_in_exactly_one_cell(cells):
    """The cell-contiguous mirror is a PARTITION: walking every cell's
    block table visits each catalog row exactly once (union == catalog,
    pairwise disjoint by construction), every visited row's nearest
    centroid is the cell that holds it, and the sentinel block is
    empty."""
    import jax.numpy as jnp

    rng = np.random.default_rng(200 + cells)
    n = 1024
    _, yp = _mixture(rng, n, 16, cells * 2)
    cfg = _cfg(cells, nprobe=1, train_iterations=4)
    cents = ivf.train_generation_centroids(yp[:, :16], cfg)
    state = ivf.AnnState(cfg, cents)
    mirror = ivf.build_mirror(jnp.asarray(yp), jnp.ones(n, bool),
                              state, BS)
    shapes = ivf.mirror_shapes(n, cells, BS)
    assert int(mirror.y8p.shape[0]) == shapes["rows"]
    perm = np.asarray(mirror.perm)
    # all-active store: activep IS the valid-slot mask
    valid = np.asarray(mirror.activep)
    cell_blocks = np.asarray(mirror.cell_blocks)
    sentinel = shapes["blocks"] - 1
    assign = ops_ann.assign_cells(yp, np.asarray(mirror.cents))
    seen: list[int] = []
    for c in range(cells):
        for blk in cell_blocks[c]:
            if blk == sentinel:
                continue  # pow2 padding of the probe table
            slots = np.arange(blk * BS, (blk + 1) * BS)
            rows = perm[slots][valid[slots]]
            assert (assign[rows] == c).all()
            seen.extend(rows.tolist())
    # exactly once each, union == catalog
    assert sorted(seen) == list(range(n))
    # the sentinel block the padding points at holds nothing
    assert not valid[sentinel * BS:(sentinel + 1) * BS].any()


def test_recall_monotone_nondecreasing_in_nprobe():
    """Probe sets nest (top-1 cell is in every top-n probe), so the
    candidate universe only grows with ``nprobe`` — recall against the
    exact kernel must be monotone non-decreasing, reaching 1.0 at
    ``nprobe == cells``."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    n, features, cells, k = 2048, 16, 8, 50
    _, yp = _mixture(rng, n, features, cells // 2)
    cfg = _cfg(cells, nprobe=1)
    cents = ivf.train_generation_centroids(yp[:, :features], cfg)
    state = ivf.AnnState(cfg, cents)
    vecs = jnp.asarray(yp)
    active = jnp.ones(n, bool)
    mirror = ivf.build_mirror(vecs, active, state, BS)
    Q = np.zeros((16, 128), np.float32)
    Q[:, :features] = rng.standard_normal((16, features))
    Qd = jnp.asarray(Q)
    ex_s, ex_i = jax.device_get(sm._batch_top_n_kernel(vecs, Qd,
                                                       active, k))
    recalls = []
    for nprobe in (1, 2, 4, 8):
        # ksel wide open: this test isolates the PROBE approximation
        _, an_i, _ = jax.device_get(ivf.batch_top_n_ivf(
            mirror, vecs, Qd, k, BS, 10_000, nprobe))
        recalls.append(_recall_vs_exact(an_i, ex_i, k))
    assert recalls == sorted(recalls), recalls
    assert recalls[-1] == 1.0


def test_nprobe_equals_cells_byte_identical_to_exact():
    """With every cell probed the candidate universe is the whole
    catalog, and on a catalog whose scores are all exactly
    representable and pairwise distinct (the grid-vector trick plus a
    dominant distinct leading coordinate) the IVF kernel's output is
    byte-identical to the exact kernel's — scores AND indices."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(31)
    n, cells, k = 512, 4, 10
    yp = np.zeros((n, 128), np.float32)
    # coord 0: distinct integers (exact in f32); coords 1-3: grid
    # multiples of 1/4 — every dot is exact, and q0=64 makes adjacent
    # items' scores differ by 64 >> the |rest| <= 12 grid part, so all
    # scores are pairwise distinct: byte-identity is well-defined
    yp[:, 0] = np.arange(n) - n // 2
    yp[:, 1:4] = rng.integers(-8, 9, (n, 3)) / 4.0
    active = np.ones(n, bool)
    active[5::37] = False  # retired rows ride along
    cfg = _cfg(cells, nprobe=cells, train_iterations=4)
    cents = ivf.train_generation_centroids(yp[:, :4], cfg)
    state = ivf.AnnState(cfg, cents)
    vecs = jnp.asarray(yp)
    act = jnp.asarray(active)
    mirror = ivf.build_mirror(vecs, act, state, BS)
    Q = np.zeros((8, 128), np.float32)
    Q[:, 0] = 64.0
    Q[:, 1:4] = rng.integers(-8, 9, (8, 3)) / 4.0
    Qd = jnp.asarray(Q)
    an_s, an_i, cert = jax.device_get(ivf.batch_top_n_ivf(
        mirror, vecs, Qd, k, BS, 10_000, cells))
    ex_s, ex_i = jax.device_get(sm._batch_top_n_kernel(vecs, Qd,
                                                       act, k))
    assert bool(cert.all())
    np.testing.assert_array_equal(an_s, ex_s)
    np.testing.assert_array_equal(an_i, ex_i)


def test_index_build_and_kernel_are_deterministic():
    """Same generation -> same index -> same bytes (the PR 8/PR 11
    result-cache byte-identity contract): training, mirror layout, and
    kernel output must be reproducible from scratch."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(41)
    n, features, cells = 1024, 16, 8
    _, yp = _mixture(rng, n, features, cells)
    Q = np.zeros((8, 128), np.float32)
    Q[:, :features] = rng.standard_normal((8, features))

    def build():
        cfg = _cfg(cells, nprobe=2)
        cents = ivf.train_generation_centroids(yp[:, :features], cfg)
        state = ivf.AnnState(cfg, cents)
        vecs = jnp.asarray(yp)
        mirror = ivf.build_mirror(vecs, jnp.ones(n, bool), state, BS)
        out = jax.device_get(ivf.batch_top_n_ivf(
            mirror, vecs, jnp.asarray(Q), 10, BS, 8, 2))
        return cents, mirror, out

    c1, m1, o1 = build()
    c2, m2, o2 = build()
    assert np.array_equal(c1, c2)
    assert np.array_equal(np.asarray(m1.y8p), np.asarray(m2.y8p))
    assert np.array_equal(np.asarray(m1.perm), np.asarray(m2.perm))
    assert np.array_equal(np.asarray(m1.cell_blocks),
                          np.asarray(m2.cell_blocks))
    for a, b in zip(o1, o2):
        np.testing.assert_array_equal(a, b)


# -- warmup lock-step (satellite 3) -------------------------------------------

def test_mirror_shapes_lockstep_with_build_and_warmup_ladder():
    """``mirror_shapes`` is THE shared derivation: the built mirror's
    padded layout must equal it exactly, and on a balanced catalog the
    probe-table width lands on the warmup ladder's expected rung
    (``e = pow2ceil(capacity / (cells * bs))``)."""
    import jax.numpy as jnp

    n, cells = 1024, 8
    cents = np.zeros((cells, 16), np.float32)
    for c in range(cells):
        cents[c, c % 16] = 10.0 * (1 + c)
    yp = np.zeros((n, 128), np.float32)
    yp[:, :16] = np.repeat(cents, n // cells, axis=0)  # balanced cells
    state = ivf.AnnState(_cfg(cells, nprobe=2), cents)
    mirror = ivf.build_mirror(jnp.asarray(yp), jnp.ones(n, bool),
                              state, BS)
    shapes = ivf.mirror_shapes(n, cells, BS)
    assert int(mirror.y8p.shape[0]) == shapes["rows"]
    assert int(mirror.sy_b.shape[0]) == shapes["blocks"]
    e = max(1, -(-n // (cells * BS)))
    e = 1 << (e - 1).bit_length()
    assert int(mirror.cell_blocks.shape[1]) in (e, 2 * e)


def test_warmup_compiles_ivf_ladder_from_avals():
    """``python -m oryx_tpu warmup`` must pre-compile the IVF phase-A
    ladder from avals alone, at BOTH probe-table widths (e, 2e), with
    zero failures — keyed on the same planned capacity + ANN config a
    later bulk_load produces (satellite 3)."""
    from oryx_tpu.deploy import warmup

    old = (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS, sm._BLOCK_KSEL,
           sm._PA_TILE)
    old_state = dict(sm._PALLAS_STATE)
    sm._PALLAS_STATE.clear()
    sm._FLAT_SCORES_LIMIT = 1
    sm._MAX_CHUNK_ROWS = 1024
    sm._BLOCK_KSEL = 4
    sm._PA_TILE = 1024
    report: dict = {"compiled": [], "failed": []}
    try:
        warmup.warm_serving_shapes(6, 4096, "float32", 1.0, report,
                                   ann=_cfg(8, nprobe=4))
    finally:
        sm._PALLAS_STATE.clear()
        sm._PALLAS_STATE.update(old_state)
        (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS, sm._BLOCK_KSEL,
         sm._PA_TILE) = old
    names = [c["kernel"] for c in report["compiled"]]
    # e = pow2ceil(4096 / (8 * 128)) = 4; ladder covers {e, 2e}
    assert any("ivf bpc=4" in nm for nm in names), names
    assert any("ivf bpc=8" in nm for nm in names), names
    assert not [f for f in report["failed"] if "ivf" in f["kernel"]], \
        report["failed"]


# -- certificate gate (tentpole b: router can never serve below it) ----------

def _streaming_knobs():
    return (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS, sm._BLOCK_KSEL,
            sm._PA_TILE)


def test_certificate_flip_gates_routing_and_answers_stay_exact():
    """The router provably never serves ANN below min-recall: with a
    passing certificate "ivf" heads the phase-A chain, is MEASURED by
    the router, and (at nprobe == cells) serves the exact answers;
    flipping the certificate below min-recall invalidates the cached
    route (the ann half of the re-measure key) and removes "ivf" from
    the chain entirely — below the gate there is no ANN kind to route."""
    rng = np.random.default_rng(50)
    n, features, cells = 4096, 6, 8
    model = ALSServingModel(features=features, implicit=True)
    model.Y.bulk_load([f"i{j}" for j in range(n)],
                      rng.standard_normal((n, features)).astype(
                          np.float32))
    model.X.bulk_load(["u0"], rng.standard_normal(
        (1, features)).astype(np.float32))
    cfg = _cfg(cells, nprobe=cells)  # exact by construction
    yv, ya, _ = model.Y.host_arrays()
    cents = ivf.train_generation_centroids(
        yv[ya][:, :features], cfg)
    state = ivf.AnnState(cfg, cents)
    state.recall = 1.0  # certificate measured elsewhere; pin it
    old = _streaming_knobs()
    old_state = dict(sm._PALLAS_STATE)
    sm._PALLAS_STATE.clear()
    sm._FLAT_SCORES_LIMIT = 1
    sm._MAX_CHUNK_ROWS = 1024
    sm._BLOCK_KSEL = 4
    sm._PA_TILE = 1024
    try:
        model.attach_ann(state)
        n_rows = len(model.Y.row_ids())
        assert model._ann_routable(n_rows)
        kinds, _ = model._phase_a_kinds(n_rows, 128, BS)
        assert kinds[0] == "ivf"
        # static chain (no route yet): the drain dispatches ivf — and
        # at nprobe == cells it returns the exact answer set (scores
        # may differ in the last ulp between accumulation orders, so
        # compare the returned ids, which are ulp-stable here: random
        # gaussian scores have O(0.1) gaps at the top)
        q = rng.standard_normal((16, features)).astype(np.float32)
        got = [[i for i, _ in r] for r in model.top_n_batch(5, q)]
        assert model._ivf_mirror is not None  # ivf really dispatched
        model.attach_ann(None)
        want = [[i for i, _ in r] for r in model.top_n_batch(5, q)]
        assert got == want
        model.attach_ann(state)
        # the router measures the ivf kind alongside the others
        route = model.refresh_route(force=True)
        assert route["ann_key"] == cfg.route_key() + (True,)
        assert route["costs_exact_ms"].get("ivf") is not None
        # certificate flips below min-recall: the cached route is
        # stale (ann_key changed) and the re-measured chain has no
        # "ivf" kind at all
        state.recall = 0.20
        assert model._route_current(n_rows) is None
        route2 = model.refresh_route()
        assert route2 is not route
        assert route2["ann_key"] == cfg.route_key() + (False,)
        assert not model._ann_routable(n_rows)
        kinds2, _ = model._phase_a_kinds(n_rows, 128, BS)
        assert "ivf" not in kinds2
        assert [[i for i, _ in r]
                for r in model.top_n_batch(5, q)] == want
    finally:
        sm._PALLAS_STATE.clear()
        sm._PALLAS_STATE.update(old_state)
        (sm._FLAT_SCORES_LIMIT, sm._MAX_CHUNK_ROWS, sm._BLOCK_KSEL,
         sm._PA_TILE) = old


# -- quality-oracle recall certificate (tentpole b, tier-1 acceptance) --------

def _oracle_catalog(seed=17, n_users=192, n_items=1024, groups=8,
                    features=16):
    """Community-structured implicit ratings -> ALS factors via the
    PR 2 quality oracle: users mostly rate items of their own group,
    so the trained item factors carry the cluster structure real
    catalogs have."""
    from oryx_tpu.ml.oracle import train_als_oracle

    rng = np.random.default_rng(seed)
    users, items, vals = [], [], []
    for u in range(n_users):
        own = np.arange(u % groups, n_items, groups)
        for i in list(rng.choice(own, size=24, replace=False)) + \
                list(rng.choice(n_items, size=3, replace=False)):
            users.append(u)
            items.append(int(i))
            vals.append(1.0)
    X, Y = train_als_oracle(np.array(users), np.array(items),
                            np.array(vals), n_users, n_items, features,
                            0.01, 1.0, True, 8, seed=0)
    return X.astype(np.float32), Y.astype(np.float32)


def _replay(mgr, X, Y, features, known=None):
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", features)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(
        doc, "XIDs", [f"u{j}" for j in range(len(X))])
    pmml_io.add_extension_content(
        doc, "YIDs", [f"i{j}" for j in range(len(Y))])
    mgr.consume_key_message(KEY_MODEL, pmml_io.to_string(doc))
    for j, row in enumerate(Y):
        mgr.consume_key_message(KEY_UP, json.dumps(
            ["Y", f"i{j}", [float(v) for v in row]]))
    for j, row in enumerate(X):
        mgr.consume_key_message(KEY_UP, json.dumps(
            ["X", f"u{j}", [float(v) for v in row],
             (known or {}).get(j, [])]))


def _ann_manager(extra=None, spec=None):
    conf = {
        "oryx.serving.model-manager-class": "unused",
        "oryx.input-topic.broker": None,
        "oryx.update-topic.broker": None,
        # nprobe 6/8: the oracle catalog's 8 communities merge in the
        # row-sample-init k-means (measured recall@50 by nprobe:
        # 4 -> 0.9009, 5 -> 0.9437, 6 -> 0.9725); everything on the
        # measurement path is seeded, so the certificate is exact
        "oryx.als.ann.enabled": True,
        "oryx.als.ann.cells": 8,
        "oryx.als.ann.nprobe": 6,
        "oryx.als.ann.train-sample": 1024,
    }
    if spec is not None:
        conf["oryx.cluster.enabled"] = True
        conf["oryx.cluster.shard"] = spec
    conf.update(extra or {})
    return ALSServingModelManager(from_dict(conf))


@pytest.mark.numerics
def test_recall_certificate_on_oracle_factors_meets_bar():
    """recall@50 >= 0.95 on quality-oracle-trained factors — the
    ISSUE 18 acceptance bar, measured by the REAL load path: the
    manager trains the quantizer, builds the index inside
    ``model_load_s``, measures the certificate against the exact
    kernel on the generation's own user factors, and publishes it on
    /metrics with the routable verdict."""
    X, Y = _oracle_catalog()
    mgr = _ann_manager()
    _replay(mgr, X, Y, 16)
    model = mgr.model
    a = model._ann
    assert a is not None and a.recall is not None
    assert a.recall >= 0.95, a.recall
    assert mgr.ann_index_fallbacks == 0
    assert mgr.ann_index_bytes > 0
    assert mgr.model_load_s > 0.0  # index build is inside the clock
    n_rows = len(model.Y.row_ids())
    assert model._ann_routable(n_rows)
    kinds, _ = model._phase_a_kinds(n_rows, 128, BS)
    assert kinds[0] == "ivf"
    ann_m = model.metrics()["kernel_route"]["ann"]
    assert ann_m["recall"] == a.recall
    assert ann_m["routable"] is True
    assert ann_m["min_recall"] == 0.95
    assert ann_m["index_bytes"] == mgr.ann_index_bytes


# -- per-slice artifacts + chaos fail-closed (satellite 2) --------------------

def _publish_sliced_ann(tmp_path, Y, X, features, ring=24, ann=True):
    from oryx_tpu.app.als import slices

    y_ids = [f"i{j}" for j in range(len(Y))]
    x_ids = [f"u{j}" for j in range(len(X))]
    model_dir = str(tmp_path / "model")
    os.makedirs(model_dir, exist_ok=True)
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", features)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", x_ids)
    pmml_io.add_extension_content(doc, "YIDs", y_ids)
    pmml_path = model_dir + "/model.pmml.xml"
    pmml_io.write(doc, pmml_path)
    pub_ann = None
    cells = None
    if ann:
        cfg = _cfg(8, nprobe=4)
        cents = ivf.train_generation_centroids(Y, cfg)
        cells = ops_ann.assign_cells(Y, cents)
        pub_ann = (cents, cells)
    slim = slices.publish_sliced(model_dir, y_ids, Y, x_ids, X, None,
                                 ring, ann=pub_ann)
    cents = pub_ann[0] if pub_ann else None
    return (model_dir, slim, cents, cells,
            slices.model_ref_message(pmml_path, model_dir, slim))


def test_ann_artifact_round_trip(tmp_path):
    """publish_sliced(ann=...) ships centroids once per generation and
    cell assignments per slice; reading them back must reproduce the
    trainer's partition exactly (crc-checked, manifest-aligned)."""
    X, Y = _oracle_catalog(n_users=32, n_items=512)
    model_dir, slim, cents, cells, _msg = _publish_sliced_ann(
        tmp_path, Y, X, 16)
    cents_rt = ivf.read_centroids(model_dir, slim["ann"])
    assert cents_rt.shape == (8, 16)
    np.testing.assert_allclose(cents_rt, cents, atol=1e-6)
    got: list[int] = []
    for entry in slim["slices"]:
        aent = entry.get("ann")
        assert aent is not None
        sc = ivf.read_slice_cells(model_dir, aent)
        assert len(sc) == int(aent["rows"])
        got.extend(sc)
    assert sorted(got) == sorted(int(c) for c in cells)


def test_manager_builds_ann_from_published_artifacts(tmp_path):
    """The sliced load path consumes the trainer-published index: the
    model certifies and routes without local k-means over rows the
    replica never trains on, and the load-time gauges are live."""
    X, Y = _oracle_catalog()
    _model_dir, _slim, _cents, _cells, msg = _publish_sliced_ann(
        tmp_path, Y, X, 16)
    mgr = _ann_manager(spec="0/1")
    mgr.consume_key_message(KEY_MODEL_REF, msg)
    model = mgr.model
    a = model._ann
    assert a is not None and a.recall is not None
    assert mgr.ann_index_fallbacks == 0
    assert a.recall >= 0.95, a.recall
    assert mgr.ann_index_bytes > 0
    assert model._ann_routable(len(model.Y.row_ids()))


def test_ann_index_corrupt_chaos_fails_closed_to_exact(tmp_path):
    """Chaos point ``ann-index-corrupt``: a corrupt/missing per-slice
    index artifact must NOT fail the model load — the replica serves
    on the exact kernel (fail CLOSED), counts ``ann_index_fallbacks``,
    and reports zero index bytes (docs/RESILIENCE.md row)."""
    X, Y = _oracle_catalog(n_users=32, n_items=512)
    _model_dir, _slim, _cents, _cells, msg = _publish_sliced_ann(
        tmp_path, Y, X, 16)
    faults.inject("ann-index-corrupt", mode="error", times=1)
    mgr = _ann_manager(spec="0/1")
    mgr.consume_key_message(KEY_MODEL_REF, msg)
    assert faults.fired("ann-index-corrupt") == 1
    model = mgr.model
    assert model is not None  # the load itself must survive
    assert mgr.ann_index_fallbacks == 1
    assert mgr.ann_index_bytes == 0
    assert model._ann is None
    kinds, _ = model._phase_a_kinds(len(model.Y.row_ids()), 128, BS)
    assert "ivf" not in kinds
    # and the replica actually serves
    assert model.top_n(5, user_vector=X[0])


def test_ann_centroid_artifact_bitrot_fails_closed(tmp_path):
    """Real on-disk corruption (not just the injected fault): a
    truncated centroid artifact fails the checksum and the load falls
    closed to the exact kernel the same way."""
    X, Y = _oracle_catalog(n_users=32, n_items=512)
    model_dir, _slim, _cents, _cells, msg = _publish_sliced_ann(
        tmp_path, Y, X, 16)
    path = os.path.join(model_dir, ivf.CENTROIDS_FILE)
    payload = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(payload[:len(payload) // 2])
    mgr = _ann_manager(spec="0/1")
    mgr.consume_key_message(KEY_MODEL_REF, msg)
    model = mgr.model
    assert model is not None
    assert mgr.ann_index_fallbacks == 1
    assert model._ann is None
    assert model.top_n(5, user_vector=X[0])
