"""Headline benchmark: ALS /recommend throughput over LIVE HTTP at
reference scale.

Serves a 1M-item x 50-feature ALS model (the reference's published
exact-scan configuration) through the real serving stack — stdlib HTTP
server, route dispatch, model gating, the request micro-batcher, and
the fused matmul+mask+top_k device kernel — and drives it with
concurrent HTTP clients.  Every request scores ALL 1M items exactly
(no LSH pruning).

Reference baselines (docs/docs/performance.html; BASELINE.md), 32-core
Haswell Xeon at saturating concurrency:
  exact scan (no LSH):  70 qps / 28 ms
  LSH 0.3 (approx):    437 qps /  7 ms
This measures the EXACT scan end-to-end over HTTP and should beat both.

vs_baseline = our_http_qps / 70  (>1 means more throughput than the
reference's same-config exact number).

Prints ONE JSON line; extra fields carry latency percentiles and the
in-process kernel ceiling.
"""

from __future__ import annotations

import json
import time

import numpy as np

N_ITEMS = 1_000_000
N_USERS = 10_000
FEATURES = 50
TOP_N = 10
HTTP_WORKERS = 512
HTTP_WARMUP = 1024
HTTP_REQUESTS = 16384
KERNEL_BATCH = 512
KERNEL_BATCHES = 8
BASELINE_QPS = 70.0  # Oryx 2, 50 features / 1M items, exact scan


def main() -> None:
    from oryx_tpu.app.als.serving_model import ALSServingModel
    from oryx_tpu.bench.load import (StaticModelManager,
                                     run_recommend_load,
                                     run_recommend_open_loop)
    from oryx_tpu.lambda_rt.http import HttpApp, make_server
    from oryx_tpu.serving import als as als_resources
    from oryx_tpu.serving import framework as framework_resources
    from oryx_tpu.serving.batcher import TopNBatcher

    rng = np.random.default_rng(0)
    model = ALSServingModel(features=FEATURES, implicit=True)
    item_ids = [str(i) for i in range(N_ITEMS)]
    Y = rng.standard_normal((N_ITEMS, FEATURES)).astype(np.float32)
    model.Y.bulk_load(item_ids, Y)
    model.Y.device_arrays()  # upload once, before the timed region
    user_ids = [f"u{u}" for u in range(N_USERS)]
    X = rng.standard_normal((N_USERS, FEATURES)).astype(np.float32)
    model.X.bulk_load(user_ids, X)
    model.warm_serving_kernels(TOP_N)  # all compiles before timed work

    # in-process kernel ceiling (what the batched device dispatch alone
    # sustains, no HTTP): context for how much the serving stack costs
    queries = rng.standard_normal(
        ((2 + KERNEL_BATCHES) * KERNEL_BATCH, FEATURES)).astype(np.float32)
    for b in range(2):
        model.top_n_batch(TOP_N,
                          queries[b * KERNEL_BATCH:(b + 1) * KERNEL_BATCH])
    t0 = time.perf_counter()
    for b in range(2, 2 + KERNEL_BATCHES):
        out = model.top_n_batch(
            TOP_N, queries[b * KERNEL_BATCH:(b + 1) * KERNEL_BATCH])
        assert len(out) == KERNEL_BATCH and len(out[0]) == TOP_N
    kernel_qps = KERNEL_BATCHES * KERNEL_BATCH / (time.perf_counter() - t0)

    # live HTTP through the real serving stack, at the serving layer's
    # default batcher configuration
    StaticModelManager.model = model
    batcher = TopNBatcher()
    app = HttpApp(
        framework_resources.ROUTES + als_resources.ROUTES,
        context={
            "model_manager": StaticModelManager(),
            "input_producer": None,
            "config": None,
            "min_model_load_fraction": 0.0,
            "top_n_batcher": batcher,
        },
        read_only=True)
    server = make_server(app, 0)
    port = server.server_address[1]
    import threading
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    try:
        run_recommend_load(base, user_ids, requests=HTTP_WARMUP,
                           workers=HTTP_WORKERS, how_many=TOP_N)
        warm_drains = len(batcher.batch_sizes)
        stats = run_recommend_load(base, user_ids, requests=HTTP_REQUESTS,
                                   workers=HTTP_WORKERS, how_many=TOP_N)
        measured_drains = len(batcher.batch_sizes)
        # open-loop ladder above the closed-loop rate: the closed-loop
        # number is bounded by workers/RTT through the device tunnel;
        # sustaining a higher offered arrival rate (TrafficUtil-style,
        # exponential inter-arrival) demonstrates the server was not
        # the closed-loop binding constraint.  If even 1.0x fails
        # (tunnel-RTT overshoot), descend so the artifact reports a
        # measured rate, not 0.0.
        from oryx_tpu.bench.grid import descend_until_sustained
        ladder: list = []
        for mult in (1.0, 1.5, 2.0, 3.0):
            o = run_recommend_open_loop(
                base, user_ids, rate_qps=stats.qps * mult,
                duration_sec=6.0, workers=HTTP_WORKERS, how_many=TOP_N)
            ladder.append(o)
            if not o["sustained"]:
                break
        if not any(o["sustained"] for o in ladder):
            # same 25 qps floor as grid.bench_config's ladder: below it
            # a 6 s window has too few arrivals for the kept-up gate;
            # dedupe so a low closed-loop qps doesn't re-bench the
            # floored rate three times
            descend_until_sustained(
                base, user_ids,
                list(dict.fromkeys(
                    max(25.0, stats.qps * m) for m in (0.7, 0.5, 0.35))),
                ladder,
                duration_sec=6.0, workers=HTTP_WORKERS, how_many=TOP_N)
        open_loop_sustained = max(
            (o["offered_qps"] for o in ladder if o["sustained"]),
            default=0.0)
    finally:
        server.shutdown()
        batcher.close()

    assert stats.errors == 0, f"{stats.errors} HTTP errors during bench"
    qps = stats.qps
    # closed-loop measured run only: the open-loop ladder's drains at
    # other offered rates would otherwise dominate the mean
    sizes = batcher.batch_sizes[warm_drains:measured_drains]
    # HEADLINE = open-loop SUSTAINED qps (VERDICT r5 Next #8): the
    # highest offered arrival rate (TrafficUtil-style exponential
    # inter-arrival) the server held without backlog divergence.  The
    # closed-loop number stays as a secondary column — it is bounded by
    # workers/RTT through the device tunnel and can overstate what the
    # server holds under arrival-driven load.
    headline = open_loop_sustained if open_loop_sustained > 0.0 else qps
    print(json.dumps({
        "metric": "als_recommend_http_sustained_qps_50f_1M_exact",
        "value": round(headline, 1),
        "unit": "qps",
        "vs_baseline": round(headline / BASELINE_QPS, 2),
        "open_loop_sustained_qps": open_loop_sustained,
        "closed_loop_qps": round(qps, 1),
        "vs_baseline_closed_loop": round(qps / BASELINE_QPS, 2),
        "headline_is_closed_loop_fallback": open_loop_sustained <= 0.0,
        "p50_ms": round(stats.percentile_ms(50), 2),
        "p95_ms": round(stats.percentile_ms(95), 2),
        "p99_ms": round(stats.percentile_ms(99), 2),
        "mean_device_batch": round(float(np.mean(sizes)), 1) if sizes else 0,
        "kernel_qps": round(kernel_qps, 1),
    }))


if __name__ == "__main__":
    main()
