"""Headline benchmark: ALS /recommend throughput at reference scale.

Drives the serving model's batched exact top-N — every request scores
ALL 1M items at 50 features (the reference's published exact-scan
configuration) as one fused matmul+mask+top_k per request batch — and
reports sustained queries/second, results landed on host.

Reference baseline for the same exact (no-LSH) scan: 70 qps (28 ms) on
a 32-core Haswell Xeon at saturating concurrency
(docs/docs/performance.html, "Without LSH" table; BASELINE.md).  The
reference's best approximate number (LSH 0.3) is 437 qps; this measures
the EXACT scan and should beat both.

vs_baseline = our_qps / 70  (>1 means more throughput than reference).

Prints ONE JSON line.
"""

from __future__ import annotations

import json
import time

import numpy as np

N_ITEMS = 1_000_000
FEATURES = 50
TOP_N = 10
BATCH = 512
WARMUP_BATCHES = 3
BATCHES = 10
BASELINE_QPS = 70.0  # Oryx 2, 50 features / 1M items, exact scan


def main() -> None:
    from oryx_tpu.app.als.serving_model import ALSServingModel

    rng = np.random.default_rng(0)
    model = ALSServingModel(features=FEATURES, implicit=True)
    ids = [str(i) for i in range(N_ITEMS)]
    Y = rng.standard_normal((N_ITEMS, FEATURES)).astype(np.float32)
    model.Y.bulk_load(ids, Y)
    model.Y.device_arrays()  # upload once, outside the timed region

    queries = rng.standard_normal(
        ((WARMUP_BATCHES + BATCHES) * BATCH, FEATURES)).astype(np.float32)

    for b in range(WARMUP_BATCHES):
        model.top_n_batch(TOP_N, queries[b * BATCH:(b + 1) * BATCH])

    t0 = time.perf_counter()
    n = 0
    for b in range(WARMUP_BATCHES, WARMUP_BATCHES + BATCHES):
        out = model.top_n_batch(TOP_N, queries[b * BATCH:(b + 1) * BATCH])
        assert len(out) == BATCH and len(out[0]) == TOP_N
        n += BATCH
    dt = time.perf_counter() - t0

    qps = n / dt
    print(json.dumps({
        "metric": "als_recommend_qps_50f_1M_exact",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(qps / BASELINE_QPS, 2),
    }))


if __name__ == "__main__":
    main()
