"""Prometheus text exposition over mergeable fixed-bucket histograms.

The serving tier's original latency surface is a percentile reservoir
(lambda_rt/metrics.py): exact for one process, but percentiles cannot
be combined across replicas — the router fronting N shard replicas had
no honest cluster-wide latency view.  Borgmon/Prometheus solved this
with fixed-bucket histograms: bucket counts are plain counters, so the
router can sum each bucket across replicas and the merged histogram is
EXACTLY the histogram a single process observing all requests would
have recorded.  This module owns the bucket layout, the merge, and the
text exposition (`/metrics?format=prometheus`); the JSON reservoir
percentiles stay the per-process default.

All metric names are catalogued in docs/OBSERVABILITY.md and linted by
tests/test_obs_catalog.py.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Iterable, Mapping
from ..common import clock as clockmod

__all__ = ["LATENCY_BUCKETS_MS", "Histogram", "bucket_quantile",
           "merge_histograms", "merge_snapshots", "render_prometheus",
           "render_prometheus_blocks", "render_openmetrics",
           "render_openmetrics_blocks"]

# Fixed latency bucket upper bounds (milliseconds).  Fixed — never
# per-process adaptive — because exact cross-replica merging requires
# every process to bucket identically; the range spans a local cache
# hit (~1 ms) to the 10 s shard-timeout ceiling.
LATENCY_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                      500.0, 1000.0, 2000.0, 5000.0, 10000.0)


class Histogram:
    """Fixed-bucket latency histogram.  Not thread-safe by itself — the
    owning MetricsRegistry serializes observes under its lock.

    A bucket increment may optionally carry an *exemplar*: the sampled
    request's trace id (plus the observed value and a wall-clock
    stamp), so any bucket of the cluster-wide p99 resolves to one
    concrete trace on ``/admin/traces``.  One exemplar per bucket,
    newest wins — the OpenMetrics contract — and the unsampled hot
    path (``trace_id=None``, the overwhelmingly common case) pays one
    branch and no clock read."""

    __slots__ = ("counts", "sum_ms", "exemplars")

    def __init__(self):
        # one count per bucket plus the +Inf overflow bucket; counts are
        # PER-bucket here and cumulated only at exposition time
        self.counts = [0] * (len(LATENCY_BUCKETS_MS) + 1)
        self.sum_ms = 0.0
        # bucket index -> (trace_id, observed_ms, unix_ts); lazily
        # allocated so exemplar-free histograms cost nothing extra
        self.exemplars: dict[int, tuple[str, float, float]] | None = None

    def observe(self, ms: float, trace_id: str | None = None) -> None:
        i = bisect_left(LATENCY_BUCKETS_MS, ms)
        self.counts[i] += 1
        self.sum_ms += ms
        if trace_id is not None:
            if self.exemplars is None:
                self.exemplars = {}
            self.exemplars[i] = (trace_id, ms, clockmod.now())

    def snapshot(self) -> dict:
        out = {"buckets": list(self.counts),
               "sum_ms": round(self.sum_ms, 3)}
        if self.exemplars:
            # JSON-friendly: string bucket keys, list triples — the
            # shape that rides ?format=prometheus-json to the router
            out["exemplars"] = {
                str(i): [t, round(v, 3), round(ts, 3)]
                for i, (t, v, ts) in sorted(self.exemplars.items())}
        return out


def bucket_quantile(buckets: "Iterable[int]", q: float,
                    bounds: "tuple[float, ...]" = LATENCY_BUCKETS_MS
                    ) -> float | None:
    """Estimate the q-quantile (0 < q < 1) from PER-bucket counts —
    the standard Prometheus histogram_quantile: linear interpolation
    inside the bucket the target rank falls in, with the +Inf overflow
    bucket reporting its lower bound (there is nothing to interpolate
    toward).  None on an empty histogram.  This is how the autoscaler
    turns the cluster's exactly-merged latency buckets into the p99 it
    compares against its thresholds — mergeable where reservoir
    percentiles never were."""
    counts = [int(c) for c in buckets]
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        prev = cum
        cum += c
        if cum >= rank:
            if i >= len(bounds):
                return float(bounds[-1])  # +Inf bucket: lower bound
            lo = 0.0 if i == 0 else float(bounds[i - 1])
            hi = float(bounds[i])
            if c <= 0:
                return hi
            return lo + (hi - lo) * (rank - prev) / c
    return float(bounds[-1])


def merge_histograms(snaps: Iterable[Mapping]) -> dict:
    """Sum histogram snapshots bucket-wise — the exact merge reservoir
    percentiles cannot provide.  Exemplars survive the merge exactly:
    per bucket, the exemplar with the newest wall-clock stamp wins
    across all inputs, so the cluster-wide exposition still names a
    live trace for every populated bucket."""
    counts = [0] * (len(LATENCY_BUCKETS_MS) + 1)
    total = 0.0
    exemplars: dict[int, list] = {}
    for s in snaps:
        for i, c in enumerate(s.get("buckets") or ()):
            counts[i] += int(c)
        total += float(s.get("sum_ms") or 0.0)
        for k, ex in (s.get("exemplars") or {}).items():
            i = int(k)
            cur = exemplars.get(i)
            if cur is None or float(ex[2]) > float(cur[2]):
                exemplars[i] = list(ex)
    out = {"buckets": counts, "sum_ms": round(total, 3)}
    if exemplars:
        out["exemplars"] = {str(i): exemplars[i]
                            for i in sorted(exemplars)}
    return out


def merge_snapshots(snaps: Iterable[Mapping]) -> dict:
    """Merge per-process ``MetricsRegistry.prometheus_snapshot()`` dicts
    (route counts, error counts, latency buckets, named counters) into
    one cluster-wide snapshot.  Gauges do not merge (they are
    per-process instantaneous values) and are dropped."""
    routes: dict[str, dict] = {}
    counters: dict[str, int] = {}
    for snap in snaps:
        for route, r in (snap.get("routes") or {}).items():
            agg = routes.get(route)
            if agg is None:
                agg = routes[route] = {
                    "count": 0, "client_errors": 0, "server_errors": 0,
                    "latency_ms": {"buckets": [0] * (
                        len(LATENCY_BUCKETS_MS) + 1), "sum_ms": 0.0}}
            agg["count"] += int(r.get("count") or 0)
            agg["client_errors"] += int(r.get("client_errors") or 0)
            agg["server_errors"] += int(r.get("server_errors") or 0)
            agg["latency_ms"] = merge_histograms(
                [agg["latency_ms"], r.get("latency_ms") or {}])
        for name, v in (snap.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + int(v)
    return {"routes": dict(sorted(routes.items())),
            "counters": dict(sorted(counters.items()))}


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(pairs: dict[str, str]) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs.items())
    return "{" + inner + "}"


def _num(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus(snap: Mapping,
                      labels: dict[str, str] | None = None) -> str:
    """Render one snapshot (a process's own, or a merged cluster view)
    in the Prometheus text exposition format (0.0.4)."""
    return render_prometheus_blocks([(snap, labels or {})])


def render_prometheus_blocks(
        blocks: list[tuple[Mapping, dict[str, str]]]) -> str:
    """Render several ``(snapshot, base_labels)`` blocks as ONE
    exposition — the router scrape carries its own samples
    (``tier="router"``) and the merged replica view
    (``tier="replica"``) together.  The text format allows exactly one
    ``# TYPE`` line per metric name and requires all of a metric's
    samples to form one contiguous group, so each family is emitted
    once across all blocks, never per block."""
    return _render_blocks(blocks, om=False)


# -- OpenMetrics --------------------------------------------------------------

def _om_num(v) -> str:
    """Canonical OpenMetrics float rendering (``1.0``, not ``1``)."""
    return repr(float(v))


def _om_exemplar(ex) -> str:
    """`` # {trace_id="..."} value timestamp`` — the OpenMetrics
    exemplar clause carried on a ``_bucket`` sample line."""
    return (f' # {{trace_id="{_escape(ex[0])}"}} '
            f"{_om_num(ex[1])} {_om_num(ex[2])}")


def render_openmetrics(snap: Mapping,
                       labels: dict[str, str] | None = None) -> str:
    return render_openmetrics_blocks([(snap, labels or {})])


def render_openmetrics_blocks(
        blocks: list[tuple[Mapping, dict[str, str]]]) -> str:
    """The OpenMetrics 1.0 form of the exposition
    (``/metrics?format=openmetrics``): same sample values as the
    Prometheus 0.0.4 text, plus what 0.0.4 cannot say — histogram
    bucket exemplars (``# {trace_id="..."} value timestamp``) naming
    the sampled trace that landed in each bucket, and the mandatory
    ``# EOF`` terminator.  Family naming follows the spec: a counter's
    ``# TYPE`` line names the family WITHOUT the ``_total`` suffix its
    samples carry.  Like the 0.0.4 renderer, several ``(snapshot,
    base_labels)`` blocks emit each family exactly once."""
    return _render_blocks(blocks, om=True)


def _render_blocks(blocks: list[tuple[Mapping, dict[str, str]]],
                   om: bool) -> str:
    """The one block walker both text formats render through, so they
    can never disagree on what a snapshot contains.  ``om`` switches
    the dialect: counter ``# TYPE`` lines without the ``_total``
    suffix, canonical-float ``le`` labels, bucket exemplars, and the
    ``# EOF`` terminator."""
    num = _om_num if om else _num
    out: list[str] = []

    def counter_type(family: str) -> str:
        return f"# TYPE {family} counter" if om \
            else f"# TYPE {family}_total counter"

    with_routes = [(snap.get("routes") or {}, dict(base))
                   for snap, base in blocks if snap.get("routes")]
    if with_routes:
        out.append(counter_type("oryx_requests"))
        for routes, base in with_routes:
            for route, r in routes.items():
                out.append("oryx_requests_total"
                           + _labels({**base, "route": route})
                           + f" {int(r.get('count') or 0)}")
        out.append(counter_type("oryx_request_errors"))
        for routes, base in with_routes:
            for route, r in routes.items():
                for cls, key in (("client", "client_errors"),
                                 ("server", "server_errors")):
                    out.append("oryx_request_errors_total"
                               + _labels({**base, "route": route,
                                          "class": cls})
                               + f" {int(r.get(key) or 0)}")
        out.append("# TYPE oryx_request_latency_ms histogram")
        for routes, base in with_routes:
            for route, r in routes.items():
                hist = r.get("latency_ms") or {}
                counts = hist.get("buckets") or []
                exemplars = hist.get("exemplars") or {} if om else {}
                cum = 0
                for i in range(len(LATENCY_BUCKETS_MS) + 1):
                    le = "+Inf" if i >= len(LATENCY_BUCKETS_MS) \
                        else num(LATENCY_BUCKETS_MS[i])
                    cum += int(counts[i]) if i < len(counts) else 0
                    line = ("oryx_request_latency_ms_bucket"
                            + _labels({**base, "route": route,
                                       "le": le}) + f" {cum}")
                    ex = exemplars.get(str(i))
                    if ex:
                        line += _om_exemplar(ex)
                    out.append(line)
                out.append("oryx_request_latency_ms_sum"
                           + _labels({**base, "route": route})
                           + f" {num(hist.get('sum_ms') or 0.0)}")
                out.append("oryx_request_latency_ms_count"
                           + _labels({**base, "route": route})
                           + f" {cum}")
    for kind, suffix in (("counters", "_total"), ("gauges", "")):
        names: list[str] = []
        for snap, _ in blocks:
            for n in (snap.get(kind) or {}):
                if n not in names:
                    names.append(n)
        for name in sorted(names):
            samples = []
            for snap, base in blocks:
                v = (snap.get(kind) or {}).get(name)
                if v is None:
                    continue
                v = int(v) if kind == "counters" else num(v)
                samples.append(f"oryx_{name}{suffix}"
                               f"{_labels(dict(base))} {v}")
            if samples:
                out.append(counter_type(f"oryx_{name}")
                           if kind == "counters"
                           else f"# TYPE oryx_{name} gauge")
                out.extend(samples)
    if om:
        out.append("# EOF")
        return "\n".join(out) + "\n"
    return "\n".join(out) + "\n" if out else ""
