"""Prometheus text exposition over mergeable fixed-bucket histograms.

The serving tier's original latency surface is a percentile reservoir
(lambda_rt/metrics.py): exact for one process, but percentiles cannot
be combined across replicas — the router fronting N shard replicas had
no honest cluster-wide latency view.  Borgmon/Prometheus solved this
with fixed-bucket histograms: bucket counts are plain counters, so the
router can sum each bucket across replicas and the merged histogram is
EXACTLY the histogram a single process observing all requests would
have recorded.  This module owns the bucket layout, the merge, and the
text exposition (`/metrics?format=prometheus`); the JSON reservoir
percentiles stay the per-process default.

All metric names are catalogued in docs/OBSERVABILITY.md and linted by
tests/test_obs_catalog.py.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Iterable, Mapping

__all__ = ["LATENCY_BUCKETS_MS", "Histogram", "bucket_quantile",
           "merge_histograms", "merge_snapshots", "render_prometheus",
           "render_prometheus_blocks"]

# Fixed latency bucket upper bounds (milliseconds).  Fixed — never
# per-process adaptive — because exact cross-replica merging requires
# every process to bucket identically; the range spans a local cache
# hit (~1 ms) to the 10 s shard-timeout ceiling.
LATENCY_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                      500.0, 1000.0, 2000.0, 5000.0, 10000.0)


class Histogram:
    """Fixed-bucket latency histogram.  Not thread-safe by itself — the
    owning MetricsRegistry serializes observes under its lock."""

    __slots__ = ("counts", "sum_ms")

    def __init__(self):
        # one count per bucket plus the +Inf overflow bucket; counts are
        # PER-bucket here and cumulated only at exposition time
        self.counts = [0] * (len(LATENCY_BUCKETS_MS) + 1)
        self.sum_ms = 0.0

    def observe(self, ms: float) -> None:
        self.counts[bisect_left(LATENCY_BUCKETS_MS, ms)] += 1
        self.sum_ms += ms

    def snapshot(self) -> dict:
        return {"buckets": list(self.counts),
                "sum_ms": round(self.sum_ms, 3)}


def bucket_quantile(buckets: "Iterable[int]", q: float,
                    bounds: "tuple[float, ...]" = LATENCY_BUCKETS_MS
                    ) -> float | None:
    """Estimate the q-quantile (0 < q < 1) from PER-bucket counts —
    the standard Prometheus histogram_quantile: linear interpolation
    inside the bucket the target rank falls in, with the +Inf overflow
    bucket reporting its lower bound (there is nothing to interpolate
    toward).  None on an empty histogram.  This is how the autoscaler
    turns the cluster's exactly-merged latency buckets into the p99 it
    compares against its thresholds — mergeable where reservoir
    percentiles never were."""
    counts = [int(c) for c in buckets]
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        prev = cum
        cum += c
        if cum >= rank:
            if i >= len(bounds):
                return float(bounds[-1])  # +Inf bucket: lower bound
            lo = 0.0 if i == 0 else float(bounds[i - 1])
            hi = float(bounds[i])
            if c <= 0:
                return hi
            return lo + (hi - lo) * (rank - prev) / c
    return float(bounds[-1])


def merge_histograms(snaps: Iterable[Mapping]) -> dict:
    """Sum histogram snapshots bucket-wise — the exact merge reservoir
    percentiles cannot provide."""
    counts = [0] * (len(LATENCY_BUCKETS_MS) + 1)
    total = 0.0
    for s in snaps:
        for i, c in enumerate(s.get("buckets") or ()):
            counts[i] += int(c)
        total += float(s.get("sum_ms") or 0.0)
    return {"buckets": counts, "sum_ms": round(total, 3)}


def merge_snapshots(snaps: Iterable[Mapping]) -> dict:
    """Merge per-process ``MetricsRegistry.prometheus_snapshot()`` dicts
    (route counts, error counts, latency buckets, named counters) into
    one cluster-wide snapshot.  Gauges do not merge (they are
    per-process instantaneous values) and are dropped."""
    routes: dict[str, dict] = {}
    counters: dict[str, int] = {}
    for snap in snaps:
        for route, r in (snap.get("routes") or {}).items():
            agg = routes.get(route)
            if agg is None:
                agg = routes[route] = {
                    "count": 0, "client_errors": 0, "server_errors": 0,
                    "latency_ms": {"buckets": [0] * (
                        len(LATENCY_BUCKETS_MS) + 1), "sum_ms": 0.0}}
            agg["count"] += int(r.get("count") or 0)
            agg["client_errors"] += int(r.get("client_errors") or 0)
            agg["server_errors"] += int(r.get("server_errors") or 0)
            agg["latency_ms"] = merge_histograms(
                [agg["latency_ms"], r.get("latency_ms") or {}])
        for name, v in (snap.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + int(v)
    return {"routes": dict(sorted(routes.items())),
            "counters": dict(sorted(counters.items()))}


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(pairs: dict[str, str]) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs.items())
    return "{" + inner + "}"


def _num(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus(snap: Mapping,
                      labels: dict[str, str] | None = None) -> str:
    """Render one snapshot (a process's own, or a merged cluster view)
    in the Prometheus text exposition format (0.0.4)."""
    return render_prometheus_blocks([(snap, labels or {})])


def render_prometheus_blocks(
        blocks: list[tuple[Mapping, dict[str, str]]]) -> str:
    """Render several ``(snapshot, base_labels)`` blocks as ONE
    exposition — the router scrape carries its own samples
    (``tier="router"``) and the merged replica view
    (``tier="replica"``) together.  The text format allows exactly one
    ``# TYPE`` line per metric name and requires all of a metric's
    samples to form one contiguous group, so each family is emitted
    once across all blocks, never per block."""
    out: list[str] = []
    with_routes = [(snap.get("routes") or {}, dict(base))
                   for snap, base in blocks if snap.get("routes")]
    if with_routes:
        out.append("# TYPE oryx_requests_total counter")
        for routes, base in with_routes:
            for route, r in routes.items():
                out.append("oryx_requests_total"
                           + _labels({**base, "route": route})
                           + f" {int(r.get('count') or 0)}")
        out.append("# TYPE oryx_request_errors_total counter")
        for routes, base in with_routes:
            for route, r in routes.items():
                for cls, key in (("client", "client_errors"),
                                 ("server", "server_errors")):
                    out.append("oryx_request_errors_total"
                               + _labels({**base, "route": route,
                                          "class": cls})
                               + f" {int(r.get(key) or 0)}")
        out.append("# TYPE oryx_request_latency_ms histogram")
        for routes, base in with_routes:
            for route, r in routes.items():
                hist = r.get("latency_ms") or {}
                counts = hist.get("buckets") or []
                cum = 0
                for bound, c in zip(LATENCY_BUCKETS_MS, counts):
                    cum += int(c)
                    out.append("oryx_request_latency_ms_bucket"
                               + _labels({**base, "route": route,
                                          "le": _num(bound)})
                               + f" {cum}")
                cum += int(counts[-1]) if counts else 0
                out.append("oryx_request_latency_ms_bucket"
                           + _labels({**base, "route": route,
                                      "le": "+Inf"}) + f" {cum}")
                out.append("oryx_request_latency_ms_sum"
                           + _labels({**base, "route": route})
                           + f" {_num(hist.get('sum_ms') or 0.0)}")
                out.append("oryx_request_latency_ms_count"
                           + _labels({**base, "route": route})
                           + f" {cum}")
    for kind, suffix in (("counters", "_total"), ("gauges", "")):
        names: list[str] = []
        for snap, _ in blocks:
            for n in (snap.get(kind) or {}):
                if n not in names:
                    names.append(n)
        for name in sorted(names):
            samples = []
            for snap, base in blocks:
                v = (snap.get(kind) or {}).get(name)
                if v is None:
                    continue
                v = int(v) if kind == "counters" else _num(v)
                samples.append(f"oryx_{name}{suffix}"
                               f"{_labels(dict(base))} {v}")
            if samples:
                out.append(f"# TYPE oryx_{name}{suffix} "
                           + ("counter" if kind == "counters"
                              else "gauge"))
                out.extend(samples)
    return "\n".join(out) + "\n" if out else ""
